# Dev harness — the justfile equivalent (reference justfile:10-78).
PYTHON ?= python
PORT ?= 7475

.PHONY: test lint native bench ci fleet-dryrun warp-dryrun warp2-dryrun warp3-dryrun scan-dryrun conc-dryrun rng-dryrun telemetry-dryrun phasegraph-dryrun serve-dryrun serve-chaos-dryrun serve-obs-dryrun costscope-dryrun fedserve-dryrun sparse-dryrun demo2 probe sim clean

test:
	$(PYTHON) -m pytest tests/ -q

# Core lane for the edit-test loop: everything not marked `slow` (the heavy
# end-to-end/parity-at-scale lanes). CI always runs the full `test` lane.
test-quick:
	$(PYTHON) -m pytest tests/ -q -m "not slow"

# The four-lane static gate in ONE invocation (`--all`): graftlint
# (KB1xx-3xx, dependency-free AST — the clippy `-D warnings` analogue,
# reference main.yml:48-52), graftconc (KB5xx, the serve-plane
# concurrency auditor, .graftconc_baseline.json), graftscan (KB4xx over
# the TRACED kernel entry-point registry + the compile-surface budget in
# .graftscan_surface.json — the compile-heavy step, ~1 min on CPU), and
# keyscope (KB6xx key provenance over the same traced registry, with the
# committed-leap-report freshness gate, .keyscope_baseline.json). Lanes
# run cheap-to-expensive against their own debt files; one combined rc
# with a per-lane summary line. `--no-baseline-growth` makes ALL
# checked-in baselines monotonically shrinking debt. The env wrapper is
# for the two traced lanes (CPU pin + wedge timeout); the AST lanes
# don't import jax either way. See kaboodle_tpu/analysis/
# (scripts/lint.py is a shim).
lint:
	timeout 600 env JAX_PLATFORMS=cpu \
	  $(PYTHON) -m kaboodle_tpu.analysis --all --no-baseline-growth
	$(PYTHON) scripts/license_check.py

native:
	$(MAKE) -C native

bench:
	$(PYTHON) bench.py

# run2x2 analogue: four real instances on this host for DURATION seconds,
# output interleaved (the reference used zellij panes; justfile:10-12).
demo2: native
	@for i in 1 2 3 4; do \
	  $(PYTHON) -m kaboodle_tpu --identity pane-$$i --interface v4 \
	    --port $(PORT) --period-ms 250 --duration 8 & \
	done; wait

probe: native
	$(PYTHON) -m kaboodle_tpu --probe --interface v4 --port $(PORT)

sim:
	$(PYTHON) -m kaboodle_tpu --sim 4096 --ticks 32

# ci = test + compile-check of the driver entry points (justfile:30-34).
# Each driver step runs in its own process under `timeout` so a wedged
# accelerator backend fails fast instead of eating the whole CI job; the
# entry compile-check is pinned to CPU for the same reason (the driver runs
# it on real hardware separately). The fleet sweep dryrun exercises the
# whole ensemble stack (vmapped kernel -> masked converge loop -> on-device
# stats -> table/JSON output) end-to-end at toy scale.
ci: lint native test
	$(MAKE) conc-dryrun
	$(MAKE) rng-dryrun
	timeout 420 $(PYTHON) __graft_entry__.py
	timeout 300 $(PYTHON) -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun_multichip(8): ok')"
	$(MAKE) fleet-dryrun
	$(MAKE) warp-dryrun
	$(MAKE) warp2-dryrun
	$(MAKE) warp3-dryrun
	$(MAKE) telemetry-dryrun
	$(MAKE) phasegraph-dryrun
	$(MAKE) serve-dryrun
	$(MAKE) serve-chaos-dryrun
	$(MAKE) serve-obs-dryrun
	$(MAKE) costscope-dryrun
	$(MAKE) fedserve-dryrun
	$(MAKE) sparse-dryrun

# The fleet sweep dryrun (the `make ci` tail step; the workflow runs this
# same target — ONE copy of the invocation).
fleet-dryrun:
	timeout 300 $(PYTHON) -m kaboodle_tpu fleet --platform cpu \
	  --sweep drop_rate=0:0.2:4 --ensemble 16 --n 32 --max-ticks 32

# Warp A/B dryrun (the event-horizon fast-forward engine, kaboodle_tpu/warp)
# at toy scale: dense-vs-leap on the sparse-fault steady-state scenario,
# bit-exactness verified in-process, usual JSON tail. The measured >= 2x
# acceptance run is the full-size `python bench.py --warp --platform cpu`
# (PERF.md "Warp"); CI only proves the lane runs end-to-end.
warp-dryrun:
	timeout 300 $(PYTHON) bench.py --warp --platform cpu --n 256 --ticks 64

# Warp 2.0 dryrun (signature-classed fast-forward, ISSUE 8) at toy scale:
# the churn-recovery lane end-to-end — hybrid + strict spans leap, final
# states bit-diffed vs dense (bench exits nonzero on any mismatch), the
# bounded program cache asserted from the inside (ProgramCache stats) —
# plus the calm-window A/B on the mid-drain state shape. The measured
# >= 10x acceptance run is the full-size
# `python bench.py --warp --scenario churn-recovery --platform cpu`
# (PERF.md "Warp 2.0"); CI only proves the lane + its invariants.
warp2-dryrun:
	timeout 420 $(PYTHON) bench.py --warp --scenario churn-recovery \
	  --platform cpu --n 128 --ticks 1536

# Warp 3.0 dryrun (counter-keyed RNG + signature-keyed span memoization,
# ISSUE 20) at toy scale: the churn-recovery lane runs the memo A/B
# in-process — banking pass, then a timed all-hit replay pass that the
# bench asserts is dispatch-free (every ledger row +memo), within the
# SpanMemo byte/entry bounds, eviction-free, with hits > 0 and ZERO fresh
# compiles (KB405 counter; bench exits 4 on a minted program and 3 on any
# memo-on/off or dense/warp bit mismatch). The CLI step then drives the
# two Warp 3.0 knobs end-to-end: an explicit distributional run with the
# memo off (the one non-bit-exact tier, pinned by its own fuzz arm in
# tests/test_warp_memo.py). The measured >= 5x acceptance run is the
# full-size `python bench.py --warp --scenario churn-recovery --platform
# cpu --out BENCH_warp3.json` (PERF.md "Warp 3.0"); CI only proves the
# lane + its invariants.
warp3-dryrun:
	timeout 420 $(PYTHON) bench.py --warp --scenario churn-recovery \
	  --platform cpu --n 96 --ticks 1024
	timeout 300 env JAX_PLATFORMS=cpu $(PYTHON) -m kaboodle_tpu \
	  --sim 64 --ticks 96 --warp --warp-mode distributional --no-warp-memo

# Telemetry dryrun (kaboodle_tpu/telemetry) at toy scale: a dense run and a
# warped run each write a JSONL manifest (counters + flight-recorder dump),
# then the summarizer schema-gates BOTH files (--check fails on any invalid
# record or an empty manifest) and exports a Chrome-trace/Perfetto JSON.
# Proves the whole export plane — telemetry kernel build -> manifest ->
# summarizer -> trace — end to end in one target.
telemetry-dryrun:
	timeout 300 env JAX_PLATFORMS=cpu $(PYTHON) -m kaboodle_tpu \
	  --sim 32 --ticks 16 --telemetry /tmp/kaboodle-telemetry-dryrun.jsonl
	timeout 300 env JAX_PLATFORMS=cpu $(PYTHON) -m kaboodle_tpu \
	  --sim 32 --ticks 48 --warp --telemetry /tmp/kaboodle-telemetry-dryrun-warp.jsonl
	$(PYTHON) -m kaboodle_tpu telemetry --check \
	  --trace /tmp/kaboodle-telemetry-dryrun.trace.json \
	  /tmp/kaboodle-telemetry-dryrun.jsonl /tmp/kaboodle-telemetry-dryrun-warp.jsonl

# Phase-graph dryrun (kaboodle_tpu/phasegraph, ISSUE 7): build every
# engine the planner derives from the one op graph — dense, standalone
# fused, chunked, sharded, fleet, warp leap — at toy N, run real ticks,
# and diff each bit-for-bit against the dense derivation (exit nonzero on
# any mismatch). Logs the planned pass/prune tables too, so a CI run shows
# the program shapes it just proved equal. The at-scale exactness pins
# live in the parity suites; the measured fast-path numbers in
# `python bench.py --fastpath-ab` (PERF.md "Phase graph").
phasegraph-dryrun:
	timeout 300 env JAX_PLATFORMS=cpu $(PYTHON) -m kaboodle_tpu phasegraph

# Serve dryrun (gossip-as-a-service, ISSUE 10) at toy scale: the full
# stack in one process — engine over a 4-lane resident pool, asyncio TCP
# server on an ephemeral port, client + live stream connection — driving
# 8 mixed requests plus the park/spill/restore/resume/cancel lifecycle,
# asserting the three service contracts from the inside: zero fresh
# compiles after warmup (KB405 counter), harvest bit-exact with a
# standalone run_until_converged, streamed records == written manifest
# (schema-gated). The measured throughput/latency acceptance run is
# `python bench.py --serve` / `python -m kaboodle_tpu serve-load`
# (PERF.md "Serving", BENCH_serve.json); CI only proves the contracts.
serve-dryrun:
	timeout 300 env JAX_PLATFORMS=cpu $(PYTHON) -m kaboodle_tpu serve --dryrun

# Chaos harness (servefort, ISSUE 12): six deterministic fault-injection
# scenarios against the hardened serving stack — async-spill round-latency
# A/B vs the sync baseline, kill-mid-round journal recovery (bit-exact
# continuations, no duplicate completions, zero fresh compiles), injected
# spill-write failure with loud degrade + retry, corrupt spill file ->
# structured restore error with the service intact, a stalled stream
# consumer bounded by counted stream_gap drops, and a 10x pipelined submit
# flood against admission control (queue_full + retry-after, priority
# shedding, quota throttling, goodput under SLO). Each scenario asserts
# its invariant from the inside; the overload CURVES are banked separately
# by `python -m kaboodle_tpu serve-load --overload`
# (PERF.md "Serving under overload", BENCH_serve_overload.json).
serve-chaos-dryrun:
	timeout 540 env JAX_PLATFORMS=cpu $(PYTHON) -m kaboodle_tpu serve --chaos-dryrun

# Servescope dryrun (observability plane, ISSUE 14): the traced-lifecycle
# CI lane — obs-enabled engine + server with manifest and Prometheus
# endpoint, 8 mixed requests through admit/leap/park/spill/restore/resume/
# cancel, asserting from the inside: zero fresh compiles with the plane
# attached (KB405 counter AND the plane's own compiles_steady gauge), the
# metrics RPC + HTTP scrape serve the expected families, the manifest
# passes --check / --serve-report / the Perfetto export (journal track
# included), and an obs-on vs obs-off A/B over the identical scripted
# workload ends bit-exact with <= 5% median busy-round overhead. The
# banked SLO-attribution curves come from
# `python -m kaboodle_tpu serve-load --slo` (PERF.md, BENCH_serve_slo.json).
serve-obs-dryrun:
	timeout 540 env JAX_PLATFORMS=cpu $(PYTHON) -m kaboodle_tpu serve --obs-dryrun

# Costscope dryrun (compiler/hardware observatory, ISSUE 15): the static
# cost plane end-to-end on CPU — AOT-compile every registry entry, extract
# cost_analysis()/memory_analysis() + the collective-bytes audit, gate the
# numbers against the committed .costscope_baseline.json (shrink-only debt,
# graftlint-style), render the roofline report from the committed baseline
# + banked BENCH_*.json wall-times (no compiles, no hardware), and run the
# ICI microbench correctness sweep over 8 virtual CPU devices. On real
# multichip hardware the same `--icibench` (without --dryrun) banks
# MULTICHIP_ici.json; CI only proves extraction, the gate, and the
# collective kernels.
costscope-dryrun:
	timeout 540 env JAX_PLATFORMS=cpu $(PYTHON) -m kaboodle_tpu costscope \
	  --no-baseline-growth
	timeout 120 $(PYTHON) -m kaboodle_tpu costscope --report
	timeout 300 env JAX_PLATFORMS=cpu $(PYTHON) -m kaboodle_tpu costscope \
	  --icibench --dryrun

# Fedserve dryrun (federation tier, ISSUE 17): two ServeEngines — one with
# a ShardedLanePool on a 2x2 virtual-device mesh (hence the forced 8-device
# CPU host platform, same as tests/conftest.py) — behind a FedRouter doing
# consistent-hash + N-class-aware placement over shared per-engine-id
# spill/journal roots. The dryrun drives a mixed open-loop wave through the
# router, then KILLS one engine mid-flight with a kept request spilled on
# it, and asserts from the inside: zero requests lost (every submitted rid
# resolves exactly once), exactly one failover, the dead engine's WAL
# replayed and its spilled request ADOPTED + restored + resumed on the
# survivor, and zero fresh compiles across the steady window (KB405
# counter). The measured ≥10x SLO curves are banked separately by
# `python -m kaboodle_tpu fed-load` (PERF.md "Federated serving",
# BENCH_fedserve.json); CI only proves the failover contracts.
fedserve-dryrun:
	timeout 540 env JAX_PLATFORMS=cpu \
	  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	  $(PYTHON) -m kaboodle_tpu fed-load --dryrun

# Sparseplane dryrun (ISSUE 18): the blocked_topk [N, K] engine — toy-N
# stat check against the dense oracle (matched-seed convergence band,
# steady counter means, zero steady recompiles), then a capped
# million-peer smoke (boot 2^20 peers, a few real ticks, per-peer cost
# logged). The banked numbers (24-tick curve, sub-quadratic bytes)
# live in BENCH_sparse.json via `bench.py --sparse`.
sparse-dryrun:
	timeout 540 env JAX_PLATFORMS=cpu $(PYTHON) -m kaboodle_tpu sparse --dryrun

# graftscan standalone (mirrors warp-dryrun): the full IR gate — trace the
# entry-point registry, run KB401-405, compare the compile surface against
# the committed budget — in its own timed process. The same invocation
# `make lint` runs; this target exists for iterating on the scan itself.
scan-dryrun:
	timeout 300 env JAX_PLATFORMS=cpu \
	  $(PYTHON) -m kaboodle_tpu.analysis --ir --no-baseline-growth

# graftconc standalone (ISSUE 16): the concurrency gate — KB501-KB506 over
# the serve scope against .graftconc_baseline.json, shrink-only debt. The
# same invocation `make lint` line 3 runs; this target exists for
# iterating on the conc lane itself (and is what `make ci` names). The
# RUNTIME half (lock-order sanitizer + event-loop watchdog) rides inside
# serve-chaos-dryrun and the serve/obsplane test suites.
conc-dryrun:
	$(PYTHON) -m kaboodle_tpu.analysis --conc --no-baseline-growth

# keyscope standalone (ISSUE 19): the key-provenance gate — trace the
# entry-point registry, build per-entry provenance graphs, run KB601-604
# against .keyscope_baseline.json (shrink-only debt), and fail if the
# committed KEYSCOPE_LEAP.json no longer matches what the code traces to
# (--check-leap: the KB605 classification is a banked artifact, its
# regeneration is deterministic, so CI diffs it instead of trusting it).
# Regenerate after an intentional RNG change with
# `python -m kaboodle_tpu.analysis --rng --write-leap` and commit.
rng-dryrun:
	timeout 300 env JAX_PLATFORMS=cpu \
	  $(PYTHON) -m kaboodle_tpu.analysis --rng --check-leap --no-baseline-growth

# Sharded scale proof (behavioral): epidemic-boot to asserted convergence,
# then the every-fault-path scan, N=8192 over 8 virtual CPU devices,
# wall-clock + peak RSS logged. Not part of `ci` by default — ~minutes.
scale-proof:
	$(PYTHON) scripts/sharded_scale_proof.py --n 8192 --devices 8 --ticks 8 --boot epidemic

# North-star scale (BASELINE config 4): REAL full-protocol faulty ticks at
# N=65,536 via the chunked (row-blocked) kernel — converged-init asserted
# through the standalone fingerprint check, then kills + partition + manual
# pings, with suspicion/escalation/indirect pings firing from tick 2 on.
# The whole-tensor kernel cannot execute ANY tick at this N on the
# emulating host (8 OOM-killed attempts, SCALE_PROOF.md); the chunked
# kernel bounds transients to O(block*N) and lands it in ~4 GiB + state.
scale-proof-65k:
	$(PYTHON) scripts/chunked_scale_proof.py --n 65536 --block 2048 --ticks 4

# The pre-round-5 sharded converged-init assertion (GSPMD all-reduce check,
# no protocol tick) — kept as the mesh-path complement of scale-proof-65k.
scale-proof-65k-sharded-assert:
	XLA_FLAGS="--xla_cpu_collective_call_terminate_timeout_seconds=21600 \
	  --xla_cpu_collective_timeout_seconds=21600 $$XLA_FLAGS" \
	$(PYTHON) scripts/sharded_scale_proof.py --n 65536 --devices 8 --ticks 0 \
	  --boot converged

# Broadcast-boot to asserted convergence + the FULL fault schedule (revive
# included) at the largest N whose join tick fits the emulating host.
scale-proof-32k:
	XLA_FLAGS="--xla_cpu_collective_call_terminate_timeout_seconds=21600 \
	  --xla_cpu_collective_timeout_seconds=21600 $$XLA_FLAGS" \
	$(PYTHON) scripts/sharded_scale_proof.py --n 32768 --devices 8 --ticks 2 \
	  --boot broadcast --boot-max-ticks 8 --drop-rate 0 --faulty-runs 1 \
	  --stepwise

# Two-machine real-network demo (reference justfile:57-78 analogue); see
# scripts/cross_host.sh for the interface-selection rules.
cross-host:
	./scripts/cross_host.sh

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
