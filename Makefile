# Dev harness — the justfile equivalent (reference justfile:10-78).
PYTHON ?= python
PORT ?= 7475

.PHONY: test lint native bench ci demo2 probe sim clean

test:
	$(PYTHON) -m pytest tests/ -q

# Dependency-free AST lint (undefined names, unused imports) — the clippy
# `-D warnings` analogue (reference main.yml:48-52); see scripts/lint.py.
lint:
	$(PYTHON) scripts/lint.py

native:
	$(MAKE) -C native

bench:
	$(PYTHON) bench.py

# run2x2 analogue: four real instances on this host for DURATION seconds,
# output interleaved (the reference used zellij panes; justfile:10-12).
demo2: native
	@for i in 1 2 3 4; do \
	  $(PYTHON) -m kaboodle_tpu --identity pane-$$i --interface v4 \
	    --port $(PORT) --period-ms 250 --duration 8 & \
	done; wait

probe: native
	$(PYTHON) -m kaboodle_tpu --probe --interface v4 --port $(PORT)

sim:
	$(PYTHON) -m kaboodle_tpu --sim 4096 --ticks 32

# ci = test + compile-check of the driver entry points (justfile:30-34).
# Each driver step runs in its own process under `timeout` so a wedged
# accelerator backend fails fast instead of eating the whole CI job; the
# entry compile-check is pinned to CPU for the same reason (the driver runs
# it on real hardware separately).
ci: lint native test
	timeout 420 $(PYTHON) __graft_entry__.py
	timeout 300 $(PYTHON) -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun_multichip(8): ok')"

# Sharded scale proof: N=8192 over 8 virtual CPU devices, wall-clock and
# peak-RSS logged (VERDICT r2 item 6). Not part of `ci` by default — ~minutes.
scale-proof:
	$(PYTHON) scripts/sharded_scale_proof.py --n 8192 --devices 8 --ticks 8

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
