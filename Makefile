# Dev harness — the justfile equivalent (reference justfile:10-78).
PYTHON ?= python
PORT ?= 7475

.PHONY: test native bench ci demo2 probe sim clean

test:
	$(PYTHON) -m pytest tests/ -q

native:
	$(MAKE) -C native

bench:
	$(PYTHON) bench.py

# run2x2 analogue: four real instances on this host for DURATION seconds,
# output interleaved (the reference used zellij panes; justfile:10-12).
demo2: native
	@for i in 1 2 3 4; do \
	  $(PYTHON) -m kaboodle_tpu --identity pane-$$i --interface v4 \
	    --port $(PORT) --period-ms 250 --duration 8 & \
	done; wait

probe: native
	$(PYTHON) -m kaboodle_tpu --probe --interface v4 --port $(PORT)

sim:
	$(PYTHON) -m kaboodle_tpu --sim 4096 --ticks 32

# ci = test + compile-check of the driver entry points (justfile:30-34).
ci: native test
	$(PYTHON) -c "import __graft_entry__ as g; g.entry(); g.dryrun_multichip(8)"

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
