# Dev harness — the justfile equivalent (reference justfile:10-78).
PYTHON ?= python
PORT ?= 7475

.PHONY: test lint native bench ci demo2 probe sim clean

test:
	$(PYTHON) -m pytest tests/ -q

# Core lane for the edit-test loop: everything not marked `slow` (the heavy
# end-to-end/parity-at-scale lanes). CI always runs the full `test` lane.
test-quick:
	$(PYTHON) -m pytest tests/ -q -m "not slow"

# Dependency-free AST lint (undefined names, unused imports) — the clippy
# `-D warnings` analogue (reference main.yml:48-52); see scripts/lint.py.
lint:
	$(PYTHON) scripts/lint.py
	$(PYTHON) scripts/license_check.py

native:
	$(MAKE) -C native

bench:
	$(PYTHON) bench.py

# run2x2 analogue: four real instances on this host for DURATION seconds,
# output interleaved (the reference used zellij panes; justfile:10-12).
demo2: native
	@for i in 1 2 3 4; do \
	  $(PYTHON) -m kaboodle_tpu --identity pane-$$i --interface v4 \
	    --port $(PORT) --period-ms 250 --duration 8 & \
	done; wait

probe: native
	$(PYTHON) -m kaboodle_tpu --probe --interface v4 --port $(PORT)

sim:
	$(PYTHON) -m kaboodle_tpu --sim 4096 --ticks 32

# ci = test + compile-check of the driver entry points (justfile:30-34).
# Each driver step runs in its own process under `timeout` so a wedged
# accelerator backend fails fast instead of eating the whole CI job; the
# entry compile-check is pinned to CPU for the same reason (the driver runs
# it on real hardware separately).
ci: lint native test
	timeout 420 $(PYTHON) __graft_entry__.py
	timeout 300 $(PYTHON) -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun_multichip(8): ok')"

# Sharded scale proof (behavioral): epidemic-boot to asserted convergence,
# then the every-fault-path scan, N=8192 over 8 virtual CPU devices,
# wall-clock + peak RSS logged. Not part of `ci` by default — ~minutes.
scale-proof:
	$(PYTHON) scripts/sharded_scale_proof.py --n 8192 --devices 8 --ticks 8 --boot epidemic

# North-star scale (BASELINE configs 4-5): N=65,536 lean+int16 sharded.
# Converged-init (ring_contacts=n-1) asserted by the standalone sharded
# all-reduce fingerprint check (one masked state read — any FULL tick's
# XLA:CPU working set exceeds this emulating host at this N: ~131 GiB
# single-path, ~174 GiB with the split tick; OOM-killed four times, see
# SCALE_PROOF.md attempts 1-3/5-6). This target always completes; the
# best-effort single faulty tick lives in scale-proof-65k-faulty.
# Drop stays off: the [N, N] uniform draw alone is 16 GiB at this N.
# XLA's CPU in-process collectives abort if a rendezvous waits > 40 s — at
# this size each single-core shard computes for minutes between
# collectives, so the target raises both timeout flags itself.
scale-proof-65k:
	XLA_FLAGS="--xla_cpu_collective_call_terminate_timeout_seconds=21600 \
	  --xla_cpu_collective_timeout_seconds=21600 $$XLA_FLAGS" \
	$(PYTHON) scripts/sharded_scale_proof.py --n 65536 --devices 8 --ticks 0 \
	  --boot converged

# One steady-state faulty tick at the north-star N — best-effort on the
# emulating host (the tick's working set needs the swapfiles and may still
# be OOM-killed; the boot assertion from scale-proof-65k stands either
# way, and the full fault schedule is proven at N=32,768 by
# scale-proof-32k).
scale-proof-65k-faulty:
	XLA_FLAGS="--xla_cpu_collective_call_terminate_timeout_seconds=21600 \
	  --xla_cpu_collective_timeout_seconds=21600 $$XLA_FLAGS" \
	$(PYTHON) scripts/sharded_scale_proof.py --n 65536 --devices 8 --ticks 1 \
	  --boot converged --drop-rate 0 --faulty-runs 1 --stepwise --no-revive

# Broadcast-boot to asserted convergence + the FULL fault schedule (revive
# included) at the largest N whose join tick fits the emulating host.
scale-proof-32k:
	XLA_FLAGS="--xla_cpu_collective_call_terminate_timeout_seconds=21600 \
	  --xla_cpu_collective_timeout_seconds=21600 $$XLA_FLAGS" \
	$(PYTHON) scripts/sharded_scale_proof.py --n 32768 --devices 8 --ticks 2 \
	  --boot broadcast --boot-max-ticks 8 --drop-rate 0 --faulty-runs 1 \
	  --stepwise

# Two-machine real-network demo (reference justfile:57-78 analogue); see
# scripts/cross_host.sh for the interface-selection rules.
cross-host:
	./scripts/cross_host.sh

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
