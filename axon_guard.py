"""Strip the PYTHONPATH-injected accelerator plugin before importing jax.

The tunneled TPU plugin (``/root/.axon_site`` on this rig) dials its device
at *import* time; while the tunnel is wedged that hangs ``import jax``
forever — before ``JAX_PLATFORMS=cpu`` or any config update can matter. Every
CPU-only entry point (tests/conftest.py, the multichip dry run, the CPU
smoke paths) calls :func:`strip_axon_plugin` before its first jax import.

Must stay import-free of jax (and of kaboodle_tpu, whose package init
imports jax).
"""

from __future__ import annotations

import os
import sys

_MARKER = ".axon_site"


def strip_axon_plugin() -> None:
    """Remove the plugin's path entries from ``sys.path`` and ``PYTHONPATH``.

    Harmless if jax is already imported (the plugin import either happened or
    it didn't) and idempotent."""
    sys.path[:] = [p for p in sys.path if _MARKER not in p]
    os.environ["PYTHONPATH"] = os.pathsep.join(
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if _MARKER not in p
    )
