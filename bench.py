"""Benchmark driver: simulated-peers·ticks/sec/chip + ticks-to-convergence.

Ends with ONE compact JSON line that always fits a stdout-tail capture:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
The full result document (per-config sections, banked captures) precedes it
as a ``BENCHDOC``-tagged line and is mirrored to ``BENCH_last_full.json``
(round 4's full-document-as-last-line overflowed the driver's tail buffer —
BENCH_r04.json ``parsed: null``).

Baseline: the reference has no published numbers (SURVEY.md §6); its
demonstrated scale is the 2x2 zellij demo — 4 real peers at 1 tick/second
(justfile:10-15, kaboodle.rs:38), i.e. an effective 4 simulated-peers·ticks/sec
on a whole laptop. ``vs_baseline`` is the speedup over that demonstrated rate.

Method: boot N peers knowing only themselves, measure (a) ticks to fingerprint
convergence and its wall-clock (the north-star quantity, BASELINE.json), then
(b) steady-state throughput of the fault-free tick kernel under lax.scan,
compile excluded. Timing forces execution by fetching a scalar to the host:
on the tunneled TPU backend ``block_until_ready`` does not synchronize, so
every measurement ends in a device->host fetch and the measured null-fetch
round-trip is subtracted. Sizes auto-step down if the chip runs out of memory.
"""

from __future__ import annotations

import argparse
import json
import re
import resource
import sys
import time


def _leaf_equal(a, b) -> bool:
    """The canonical A/B bit-exactness predicate, imported lazily (bench
    must not import the package — and with it jax — before the platform is
    pinned). Any state difference between arms voids a lane's measurement
    before its number is reported."""
    from kaboodle_tpu.profiling import leaf_equal

    return leaf_equal(a, b)


def _null_rtt() -> float:
    """Round-trip of a trivial jitted fetch (tunnel + dispatch overhead)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    x = jnp.float32(0)
    float(f(x))  # compile

    def once() -> float:
        t0 = time.perf_counter()
        float(f(x))
        return time.perf_counter() - t0

    return min(once() for _ in range(3))


# N at or above which the memory-lean state (no latency EWMA, instant
# identity) is selected automatically — see MEMORY_PLAN.md for the budget.
LEAN_STATE_MIN_N = 4096

# Adaptive timing-floor growth policy (see the loop in _bench): the scan
# grows by x_FLOOR_GROWTH per step while staying within ticks*_FLOOR_CEILING.
# The int16-timer eligibility check derives from the same constants.
_FLOOR_GROWTH = 8
_FLOOR_CEILING = 1024


def _is_oom(e: Exception) -> bool:
    """Memory exhaustion, as XLA/backends spell it (the step-down trigger)."""
    msg = str(e)
    return ("RESOURCE_EXHAUSTED" in msg
            or "out of memory" in msg.lower()
            or "failed to allocate" in msg.lower())


# The tunnel's remote-compile helper surfaces its failure as an HTTP 500:
# match only status-shaped 500s ("HTTP 500", "status: 500", "500 Internal
# Server Error"), never a bare digit-run — a real compile bug whose message
# happens to contain 500 (a shape dim, a line number) must surface as a
# traceback, not be swallowed by the step-down loop (ADVICE r5).
_REMOTE_COMPILE_500 = re.compile(
    r"(?i)(?:http[ /]?|status(?:\s+code)?\s*[:=]?\s*|error\s+)500\b"
    r"|\b500\s+internal\s+server\s+error")


def _is_size_ceiling(e: Exception) -> bool:
    """Size-induced failures that warrant stepping down to a smaller N:
    memory exhaustion, or the tunnel's remote-compile-helper failure — every
    N=32,768 whole-tick compile 500s through it (PERF.md "Ceilings"), and
    that exception is not OOM-shaped, so without this the headline ladder's
    first rung would kill the whole bench in a live window."""
    msg = str(e)
    return (_is_oom(e)
            or "tpu_compile_helper" in msg
            or ("compile" in msg.lower()
                and _REMOTE_COMPILE_500.search(msg) is not None))


def _newest_watch_entry(kind: str, valid=None):
    """Newest TPU_WATCH.log JSON line of the given kind (passing ``valid``
    if given), or None.

    The watcher and one-shot probes bank on-chip measurements there
    (append-only JSON lines; readers take the newest of a kind). ``valid``
    filters out partial/failed/smoke-test bankings so they can never
    shadow a good capture in a published artifact."""
    import os

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "TPU_WATCH.log")
    best = None
    try:
        with open(path) as f:
            for ln in f:
                try:
                    obj = json.loads(ln)
                except ValueError:
                    continue
                if (isinstance(obj, dict) and obj.get("kind") == kind
                        and (valid is None or valid(obj))):
                    best = obj
    except OSError:
        pass
    return best


def _bench(n: int, ticks: int, warmup: int = 1, sharded: bool = False,
           profile_dir: str | None = None):
    import jax
    import jax.numpy as jnp

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.runner import run_until_converged, simulate
    from kaboodle_tpu.sim.state import idle_inputs, init_state

    # Per-stage Pallas kernels are OFF by default since round 5: the
    # round-4c scan-amortized audit measured the jnp formulations winning
    # (oldest-5: 1.52 ms jnp vs 12.3 ms fused_oldest_k; fingerprint: 1.95
    # vs 2.15 ms at N=16,384 — PERF.md "Pallas policy"), and the fast-path
    # tick composes the jnp forms anyway. The kernels stay in-tree and
    # tested; the watcher's A/B variants keep measuring both so a future
    # window can re-open the question with data.
    cfg = SwimConfig()
    lean = n >= LEAN_STATE_MIN_N
    # int16 timers are only valid below ~32k ticks (init_state contract).
    # The decision uses the BASE scan length; the adaptive timing floor
    # below caps its growth to the chosen dtype's headroom (_floor_cap) —
    # budgeting for worst-case growth here instead would flip every default
    # run to int32 (2.8 -> 4.8 ms/sweep, PERF.md round-4c) for a floor that
    # only ever engages at small N.
    # (<= 32000, the same ceiling _floor_cap enforces: a base scan in
    # 32001..32766 would erase the headroom margin the cap promises.)
    narrow = lean and ticks <= 32000
    st = init_state(n, seed=0, track_latency=not lean, instant_identity=lean,
                    timer_dtype=jnp.int16 if narrow else jnp.int32)
    rtt = _null_rtt()

    if sharded:
        from kaboodle_tpu.parallel import (
            make_mesh,
            run_until_converged_sharded,
            shard_inputs,
            shard_state,
            simulate_sharded,
        )

        mesh = make_mesh()
        st = shard_state(st, mesh)

        def _converge(s):
            return run_until_converged_sharded(s, cfg, mesh, max_ticks=32)

        def _scan(s, i):
            return simulate_sharded(s, i, cfg, mesh, faulty=False)

        def _place_inputs(i):
            return shard_inputs(i, mesh, stacked=True)
    else:

        def _converge(s):
            return run_until_converged(s, cfg, max_ticks=32)

        def _scan(s, i):
            return simulate(s, i, cfg, faulty=False)

        def _place_inputs(i):
            return i

    # (a) convergence: compile first (cached), then time a fresh run. The
    # int() fetches force real execution through the tunnel.
    _, conv_ticks, conv = _converge(st)
    int(conv_ticks)
    t0 = time.perf_counter()
    _, conv_ticks, conv = _converge(st)
    conv_ticks_v = int(conv_ticks)
    conv_wall = max(time.perf_counter() - t0 - rtt, 0.0)

    # (b) steady-state throughput of the scanned tick kernel. The jitted fn
    # returns a scalar that depends on the final state, so the whole scan
    # must execute before the fetch completes.
    inp = _place_inputs(idle_inputs(n, ticks=ticks))

    def _run_body(s, i):
        out, _ = _scan(s, i)
        return out.timer.sum() + out.tick

    # AOT lower+compile instead of plain jax.jit: the compiled executable
    # exposes memory_analysis(), so the capture can carry a static peak
    # even when the runtime memory_stats() comes back empty (the tunnel
    # case — every banked TPU capture so far has peak_hbm_mib null).
    run = jax.jit(_run_body).lower(st, inp).compile()

    for _ in range(max(warmup, 1)):
        int(run(st, inp))
    t0 = time.perf_counter()
    int(run(st, inp))
    elapsed = max(time.perf_counter() - t0 - rtt, 1e-9)
    # Timing floor: when the whole scan finishes inside a few tunnel RTTs the
    # subtraction is all noise (seen at small N on the real chip) — grow the
    # scan until the measurement dominates the round-trip.
    eff_ticks = ticks
    _floor_cap = ticks * _FLOOR_CEILING
    if narrow:
        # Grown scans must stay inside the int16 timer headroom (with margin
        # for the convergence phase's tick offset).
        _floor_cap = min(_floor_cap, 32000)
    while elapsed < 5 * rtt and eff_ticks * _FLOOR_GROWTH <= _floor_cap:
        eff_ticks *= _FLOOR_GROWTH
        inp = _place_inputs(idle_inputs(n, ticks=eff_ticks))
        run = jax.jit(_run_body).lower(st, inp).compile()  # new scan length
        int(run(st, inp))  # warm at the new length
        t0 = time.perf_counter()
        int(run(st, inp))
        elapsed = max(time.perf_counter() - t0 - rtt, 1e-9)
    ticks = eff_ticks
    if profile_dir:
        # One profiled execution after the timed one, so the capture overhead
        # cannot pollute the reported numbers (SURVEY §5 tracing slot).
        from kaboodle_tpu.profiling import trace

        with trace(profile_dir):
            int(run(st, inp))
    return {
        "converged": bool(conv),
        "ticks_to_convergence": conv_ticks_v,
        "convergence_wall_s": conv_wall,
        "scan_ticks": ticks,
        "scan_wall_s": elapsed,
        "peers_ticks_per_sec": n * ticks / elapsed,
        "null_rtt_s": rtt,
        "state_variant": ("lean+int16" if narrow else "lean") if lean else "full",
        "pallas_fp": False,  # per-stage Pallas kernels demoted (see cfg note)
        "peak_hbm_mib": _peak_device_memory_mib(),
        "peak_hbm_mib_static": _static_peak_mib(run),
    }


def _bench_warp(n: int, ticks: int):
    """A/B: warp fast-forward vs dense ticking on the sparse-fault baseline.

    The scenario is converged steady state with sparse scheduled events (two
    manual pings) over >= ``ticks`` ticks — the regime the event-horizon
    engine exists for: the warp arm runs the two event ticks dense and leaps
    the three quiescent spans, the dense arm dispatches every tick. Both
    arms run the SAME faulty-build program contract (simulate vs
    simulate_warped), and the final states are compared bit-for-bit on the
    host before any number is reported — a speedup from a wrong state would
    be worthless.
    """
    import jax
    import jax.numpy as jnp

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.runner import simulate
    from kaboodle_tpu.sim.scenario import Scenario
    from kaboodle_tpu.sim.state import init_state
    from kaboodle_tpu.warp.runner import simulate_warped

    cfg = SwimConfig()
    lean = n >= LEAN_STATE_MIN_N
    narrow = lean and ticks <= 32000
    st = init_state(n, seed=0, ring_contacts=n - 1, announced=True,
                    track_latency=not lean, instant_identity=lean,
                    timer_dtype=jnp.int16 if narrow else jnp.int32)
    sc = Scenario(n, ticks, seed=0)
    sc.manual_ping_at(ticks // 3, 0, 1)
    sc.manual_ping_at((2 * ticks) // 3, 1, 2)
    inputs = sc.build()
    rtt = _null_rtt()

    # Dense arm: AOT-compile, then time ONE execution. The faulty-build
    # 256-tick scan at N=4,096 costs many minutes on the CPU lane, so the
    # usual warm-run-then-timed-run pattern would double a cost that is the
    # very thing being measured; compile is excluded either way.
    dense = jax.jit(
        lambda s, i: simulate(s, i, cfg, faulty=True)[0]
    ).lower(st, inputs).compile()
    t0 = time.perf_counter()
    out_d = dense(st, inputs)
    jax.block_until_ready(out_d)
    dense_wall = max(time.perf_counter() - t0 - rtt, 1e-9)

    # Warp arm: first run compiles the per-span leap programs (cached), the
    # second is the timed one — cheap enough to afford the warm run.
    out_w, dense_ticks, _ = simulate_warped(st, inputs, cfg, faulty=True)
    jax.block_until_ready(out_w)
    t0 = time.perf_counter()
    out_w, dense_ticks, _ = simulate_warped(st, inputs, cfg, faulty=True)
    jax.block_until_ready(out_w)
    warp_wall = max(time.perf_counter() - t0 - rtt, 1e-9)

    bit_exact = all(
        _leaf_equal(a, b)
        for a, b in zip(jax.tree.leaves(out_d), jax.tree.leaves(out_w))
    )
    return {
        "n": n,
        "ticks": ticks,
        "dense_wall_s": round(dense_wall, 4),
        "warp_wall_s": round(warp_wall, 4),
        "speedup": round(dense_wall / warp_wall, 2),
        "dense_ticks_executed": int(dense_ticks.size),
        "leaped_ticks": int(ticks - dense_ticks.size),
        "bit_exact": bit_exact,
        "state_variant": ("lean+int16" if narrow else "lean") if lean else "full",
    }


def _bench_warp_drain_window(n: int, k: int):
    """Calm-window ratio at representative scale: dense vs the hybrid leap
    over one mid-drain waiting window, bit-exact.

    The end-to-end churn-recovery A/B is wall-clock-bounded to small N on
    the CPU lane (dead-peer discovery and the expiry seasons are ~N/2
    ticks wide, so a season-dominated run at N=4,096 would scan for
    hours), but the CLAIM-bearing quantity — how much faster the hybrid
    program replays a calm drain tick than the dense kernel — is a
    per-window ratio measurable at any N. This builds the mid-drain state
    shape the calm phase is made of (every survivor holds armed waiting
    cells on the dead peers; membership split over one victim so
    fingerprints disagree and the sterile anti-entropy path is LIVE every
    tick), asserts it classes as ``hybrid``, then times k dense ticks
    (AOT scan) against the k-tick hybrid leap and bit-compares the final
    states before reporting.
    """
    import dataclasses as dc

    import jax
    import jax.numpy as jnp
    import numpy as np

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.kernel import make_tick_fn
    from kaboodle_tpu.sim.runner import simulate
    from kaboodle_tpu.sim.scenario import Scenario
    from kaboodle_tpu.sim.state import idle_inputs, init_state
    from kaboodle_tpu.spec import WAITING_FOR_PING
    from kaboodle_tpu.warp.horizon import decode_signature, make_signature_fn
    from kaboodle_tpu.warp.runner import (
        SpanMemo,
        _digest_leaves,
        _get_leap,
        _host_leaves,
        _memo_replay,
        _memo_store,
        _span_chunks,
    )

    cfg = SwimConfig(ping_timeout_ticks=4 * k)
    lean = n >= LEAN_STATE_MIN_N
    st = init_state(n, seed=1, ring_contacts=n - 1, announced=True,
                    track_latency=not lean, instant_identity=lean,
                    timer_dtype=jnp.int16 if lean else jnp.int32)
    victims = [(i * n) // 9 + 1 for i in range(8)]
    kill1 = jax.tree.map(
        lambda x: x[0], Scenario(n, 1, seed=0).kill_at(0, victims).build()
    )
    st, _ = jax.jit(make_tick_fn(cfg, faulty=True))(st, kill1)
    # Mid-drain shape: every survivor's cell for a dead peer is an armed
    # WaitingForPing stamped now (expiry 4k ticks out); half the rows have
    # already removed victim 0, so fingerprints disagree and anti-entropy
    # candidates fire (sterile: shares carry only live recently-heard
    # peers, which every row already holds).
    t_now = int(st.tick)
    S = np.asarray(st.state).copy()
    T = np.asarray(st.timer).copy()
    alive = np.asarray(st.alive)
    for v in victims:
        S[alive, v] = WAITING_FOR_PING
        T[alive, v] = t_now
    removed_rows = np.arange(n) >= n // 2
    S[alive & removed_rows, victims[0]] = 0
    S[~alive] = np.asarray(st.state)[~alive]  # dead rows untouched
    st = dc.replace(st, state=jnp.asarray(S), timer=jnp.asarray(T))
    cls = decode_signature(make_signature_fn(cfg)(st))
    assert cls.mode == "hybrid", cls.describe()

    rtt = _null_rtt()
    idle = idle_inputs(n, ticks=k)
    dense = jax.jit(
        lambda s, i: simulate(s, i, cfg, faulty=False)[0]
    ).lower(st, idle).compile()
    t0 = time.perf_counter()
    out_d = dense(st, idle)
    jax.block_until_ready(out_d)
    dense_wall = max(time.perf_counter() - t0 - rtt, 1e-9)

    chunks, rem = _span_chunks(k)
    assert rem == 0, k  # pick k a power of two

    def leap_all(s):
        for c in chunks:
            s = _get_leap(cfg, c, None, hybrid=True)(s)
        return s

    out_w = leap_all(st)  # compile
    jax.block_until_ready(out_w)
    t0 = time.perf_counter()
    out_w = leap_all(st)
    jax.block_until_ready(out_w)
    warp_wall = max(time.perf_counter() - t0 - rtt, 1e-9)

    # Memo leg (Warp 3.0): bank the window's delta once, then time a FULL
    # replay — host leaf fetch + entry digest + LRU lookup + XOR apply —
    # exactly the work a recurring serve-lane window costs with the memo
    # on. Timed with the same honest accounting as the dispatch legs.
    memo = SpanMemo()
    entry = _host_leaves(st)
    _memo_store(memo, ("window", cls.key, k, _digest_leaves(entry)), entry,
                out_w)
    t0 = time.perf_counter()
    entry2 = _host_leaves(st)
    deltas = memo.get(("window", cls.key, k, _digest_leaves(entry2)),
                      kind="window")
    assert deltas is not None
    out_m = _memo_replay(st, entry2, deltas[0])
    jax.block_until_ready(out_m)
    memo_wall = max(time.perf_counter() - t0 - rtt, 1e-9)

    bit_exact = all(
        _leaf_equal(a, b)
        for a, b in zip(jax.tree.leaves(out_d), jax.tree.leaves(out_w))
    )
    memo_bit_exact = all(
        _leaf_equal(a, b)
        for a, b in zip(jax.tree.leaves(out_d), jax.tree.leaves(out_m))
    )
    return {
        "window_n": n,
        "window_ticks": k,
        "window_class": cls.describe(),
        "window_dense_wall_s": round(dense_wall, 4),
        "window_warp_wall_s": round(warp_wall, 4),
        "window_speedup": round(dense_wall / warp_wall, 2),
        "window_memo_wall_s": round(memo_wall, 4),
        "window_memo_speedup": round(dense_wall / memo_wall, 2),
        "window_bit_exact": bit_exact,
        "window_memo_bit_exact": memo_bit_exact,
    }


def _bench_warp_churn_recovery(n: int, ticks: int):
    """Warp 2.0/3.0 A/B: signature-classed fast-forward on the churn-recovery
    drain (ISSUE 8 acceptance: >= 10x over dense on the calm phase;
    ISSUE 20 acceptance: memo-on e2e >= 5x over dense at N >= 1,024).

    Config-3-shaped schedule: staggered kills (plus one revive) through the
    first half, calm drain through the second — the regime where Warp 1.x
    never fired (armed suspicion timers keep the mesh non-quiescent for the
    whole drain) and the hybrid near-quiescent program leaps the waiting
    windows between timer-expiry bursts. The drain config uses a
    drain-shaped suspicion timeout (realistic multi-second timeouts vs
    sub-second ticks; the default 2-tick timeout leaves no waiting window
    to model). Both arms run the SAME faulty-build program contract over
    the SAME post-churn entry state — the churn half is executed once,
    densely, and shared — and the calm-phase final states are compared
    bit-for-bit before any number is reported. The compiled-program cache
    bound is asserted from the inside (ProgramCache stats) on top of the
    KB405 gate.

    Warp 3.0 adds the memo A/B on top: a banking pass fills a run-sized
    :class:`SpanMemo` (every span's state delta, leaped AND dense), then a
    timed replay pass re-runs the same calm drain entirely from the cache
    — zero span dispatches (every ledger row ``+memo``), zero fresh
    compiles (asserted via the KB405 counter), bit-identical final state
    (asserted vs the dense arm). The memo-on wall is what the
    counter-keyed RNG bought: with every draw a pure function of
    (state, tick, stream), a recurring span IS its banked delta, so the
    dense seasons the why-dense histogram attributes to the drain
    collapse to host XOR replays.
    """
    import jax
    import jax.numpy as jnp

    from kaboodle_tpu.analysis.ir.surface import compile_counter
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.runner import simulate
    from kaboodle_tpu.sim.scenario import Scenario
    from kaboodle_tpu.sim.state import init_state
    from kaboodle_tpu.warp.runner import (
        CHUNK_BUCKETS,
        SpanMemo,
        WarpLedger,
        leap_cache,
        simulate_warped,
    )

    churn_end = ticks // 2
    # Drain-shaped suspicion timeout: a third of the calm phase, so the
    # whole removal pipeline — dead-peer discovery (~N/2 ticks: a freshly
    # dead cell must age past the staleness population before the oldest-5
    # draw finds it), the WFP waiting window, the escalation season, the
    # WFIP waiting window, the removal season and the converged tail — fits
    # inside the measured calm half. The repo default of 2 ticks models the
    # reference's sub-tick timeouts and leaves no waiting window at all.
    cfg = SwimConfig(ping_timeout_ticks=max(8, (ticks - churn_end) // 3))
    lean = n >= LEAN_STATE_MIN_N
    narrow = lean and ticks <= 32000
    st = init_state(n, seed=0, ring_contacts=n - 1, announced=True,
                    track_latency=not lean, instant_identity=lean,
                    timer_dtype=jnp.int16 if narrow else jnp.int32)
    # A handful of suspect rows at a time draining the removal pipeline:
    # 8 kills clustered at the tail of the churn window (the whole drain
    # lands in the calm phase) plus one early kill+revive cycle (join
    # recovery resolves in-churn — the config-3 join+leave shape).
    sc = Scenario(n, ticks, seed=0)
    victims = [(i * n) // 9 + 1 for i in range(8)]
    for i, p in enumerate(victims):
        sc.kill_at(max(1, churn_end - 16 + 2 * i), [p])
    extra = victims[0] + 1 if victims[0] + 1 not in victims else victims[0] + 2
    sc.kill_at(1, [extra]).revive_at(min(6, churn_end - 1), [extra])
    inputs = sc.build()
    churn_inputs = jax.tree.map(lambda x: x[:churn_end], inputs)
    calm_inputs = jax.tree.map(lambda x: x[churn_end:], inputs)
    rtt = _null_rtt()

    # Shared churn phase: executed once, densely (AOT), both arms start
    # from the identical post-churn state.
    churn_sim = jax.jit(
        lambda s, i: simulate(s, i, cfg, faulty=True)[0]
    ).lower(st, churn_inputs).compile()
    st_c = churn_sim(st, churn_inputs)
    jax.block_until_ready(st_c)

    # Dense arm over the calm drain: AOT-compile, time one execution.
    dense_calm = jax.jit(
        lambda s, i: simulate(s, i, cfg, faulty=True)[0]
    ).lower(st_c, calm_inputs).compile()
    t0 = time.perf_counter()
    out_d = dense_calm(st_c, calm_inputs)
    jax.block_until_ready(out_d)
    dense_wall = max(time.perf_counter() - t0 - rtt, 1e-9)

    # Warp arm: first run compiles the span programs (bounded cache), the
    # second is the timed one. The warm run carries no ledger, so its final
    # state doubles as the obs-off reference for the ledger run.
    out_w0, dense_ticks, _ = simulate_warped(st_c, calm_inputs, cfg, faulty=True)
    jax.block_until_ready(out_w0)
    ledger = WarpLedger()
    t0 = time.perf_counter()
    out_w, dense_ticks, _ = simulate_warped(
        st_c, calm_inputs, cfg, faulty=True, ledger=ledger
    )
    jax.block_until_ready(out_w)
    warp_wall = max(time.perf_counter() - t0 - rtt, 1e-9)

    # Memo arms (Warp 3.0). The memo is sized for the run: every span's
    # delta is full-state-sized, so the default 256 MiB cap would evict
    # the head of a long drain before the replay pass reaches it — the
    # bench wants the all-hit regime (the serve-lane steady state), and
    # reports the actual resident bytes so the sizing is auditable.
    memo = SpanMemo(max_bytes=16 << 30, max_entries=65536)
    out_b, _, _ = simulate_warped(
        st_c, calm_inputs, cfg, faulty=True, memo=memo
    )  # banking pass: dispatches once, banks every span delta
    jax.block_until_ready(out_b)
    memo_ledger = WarpLedger()
    with compile_counter() as box:
        t0 = time.perf_counter()
        out_m, dense_ticks_m, _ = simulate_warped(
            st_c, calm_inputs, cfg, faulty=True, ledger=memo_ledger,
            memo=memo,
        )
        jax.block_until_ready(out_m)
        memo_wall = max(time.perf_counter() - t0 - rtt, 1e-9)
    memo_stats = memo.stats()
    # The replay pass must be all-hit and dispatch-free: every ledger row
    # a +memo replay, zero fresh compiles — the invariants the warp3
    # dryrun gates in CI.
    assert all(r["engine"].endswith("+memo") for r in memo_ledger.spans), (
        memo_ledger.blocked_histogram()
    )
    assert all(r["dispatches"] == 0 for r in memo_ledger.spans)
    assert memo_stats["hits"] > 0 and memo_stats["evictions"] == 0, memo_stats
    assert memo_stats["bytes"] <= memo.max_bytes
    assert memo_stats["entries"] <= memo.max_entries
    compiles_steady = box.count

    bit_exact = all(
        _leaf_equal(a, b)
        for a, b in zip(jax.tree.leaves(out_d), jax.tree.leaves(out_w))
    )
    memo_bit_exact = (
        all(
            _leaf_equal(a, b)
            for a, b in zip(jax.tree.leaves(out_d), jax.tree.leaves(out_m))
        )
        and list(dense_ticks) == list(dense_ticks_m)
    )
    obs_bit_exact = all(
        _leaf_equal(a, b)
        for a, b in zip(jax.tree.leaves(out_w0), jax.tree.leaves(out_w))
    )
    cache = leap_cache.stats()
    assert cache["max_family_programs"] <= len(CHUNK_BUCKETS), cache
    per_class = ledger.per_class()
    hybrid_ticks = sum(
        v["ticks"] for v in per_class.values() if v["engine"] == "hybrid"
    )
    strict_ticks = sum(
        v["ticks"] for v in per_class.values() if v["engine"] == "leap"
    )
    return {
        "n": n,
        "ticks": ticks,
        "calm_ticks": ticks - churn_end,
        "ping_timeout_ticks": cfg.ping_timeout_ticks,
        "dense_wall_s": round(dense_wall, 4),
        "warp_wall_s": round(warp_wall, 4),
        "speedup": round(dense_wall / warp_wall, 2),
        "memo_wall_s": round(memo_wall, 4),
        "memo_speedup": round(dense_wall / memo_wall, 2),
        "dense_ticks_executed": int(dense_ticks.size),
        "leaped_ticks": int(ticks - churn_end - dense_ticks.size),
        "hybrid_leaped_ticks": int(hybrid_ticks),
        "strict_leaped_ticks": int(strict_ticks),
        "signature_classes": len(per_class),
        "leap_cache": cache,
        "why_dense": ledger.blocked_histogram(),
        "why_dense_memo": memo_ledger.blocked_histogram(),
        "memo": {
            k: memo_stats[k]
            for k in ("entries", "bytes", "hits", "misses", "evictions",
                      "hit_rate", "per_kind")
        },
        "compiles_steady": compiles_steady,
        "bit_exact": bit_exact,
        "memo_bit_exact": memo_bit_exact,
        "obs_bit_exact": obs_bit_exact,
        "state_variant": ("lean+int16" if narrow else "lean") if lean else "full",
    }


def _bench_telemetry_ab(n: int, ticks: int):
    """A/B: the telemetry-plane tick vs the plain tick on the steady lane.

    Same faulty-build scan, same converged steady-state scenario as the
    warp A/B's dense arm (two sparse manual pings over ``ticks`` ticks) —
    arm A runs today's ``simulate``, arm B ``simulate_with_telemetry``
    (per-tick ProtocolCounters + a 32-slot flight recorder carried through
    the scan). Both are AOT-compiled, warmed once, and timed as the best
    of three executions per arm (compile excluded; a single-digit-percent
    delta is the size of single-run scheduler noise on the CPU lane), and
    the final states are compared bit-for-bit first: telemetry is a
    pure-added-outputs contract, so any state difference voids the
    measurement. The acceptance bar is overhead
    <= 5% on the bench lane (ISSUE 6 / PERF.md "Telemetry").
    """
    import jax
    import jax.numpy as jnp

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.runner import simulate, simulate_with_telemetry
    from kaboodle_tpu.sim.scenario import Scenario
    from kaboodle_tpu.sim.state import init_state

    cfg = SwimConfig()
    lean = n >= LEAN_STATE_MIN_N
    narrow = lean and ticks <= 32000
    st = init_state(n, seed=0, ring_contacts=n - 1, announced=True,
                    track_latency=not lean, instant_identity=lean,
                    timer_dtype=jnp.int16 if narrow else jnp.int32)
    sc = Scenario(n, ticks, seed=0)
    sc.manual_ping_at(ticks // 3, 0, 1)
    sc.manual_ping_at((2 * ticks) // 3, 1, 2)
    inputs = sc.build()
    rtt = _null_rtt()

    plain = jax.jit(
        lambda s, i: simulate(s, i, cfg, faulty=True)
    ).lower(st, inputs).compile()
    telem = jax.jit(
        lambda s, i: simulate_with_telemetry(s, i, cfg, faulty=True,
                                             recorder_len=32)
    ).lower(st, inputs).compile()

    # Warm both arms once (first execution of an AOT program still pays
    # one-time buffer/donation setup on some backends), then take the best
    # of three timed executions per arm — a single-digit-percent delta is
    # exactly the size of single-run scheduler noise on the CPU lane.
    out_a = plain(st, inputs)
    jax.block_until_ready(out_a)
    out_b = telem(st, inputs)
    jax.block_until_ready(out_b)

    def best_of(fn, reps=3):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(st, inputs))
            best = min(best, time.perf_counter() - t0 - rtt)
        return max(best, 1e-9)

    off_wall = best_of(plain)
    on_wall = best_of(telem)

    bit_exact = all(
        _leaf_equal(a, b)
        for a, b in zip(jax.tree.leaves(out_a[0]), jax.tree.leaves(out_b[0]))
    )
    return {
        "n": n,
        "ticks": ticks,
        "telemetry_off_wall_s": round(off_wall, 4),
        "telemetry_on_wall_s": round(on_wall, 4),
        "overhead_pct": round(100.0 * (on_wall / off_wall - 1.0), 2),
        "recorder_len": 32,
        "bit_exact": bit_exact,
        "state_variant": ("lean+int16" if narrow else "lean") if lean else "full",
    }


def _bench_fastpath_ab(n: int, ticks: int):
    """A/B: the phase-graph fused fast path vs the pre-refactor full tick.

    Three arms over the same converged steady-state scenario (two sparse
    manual pings — the telemetry A/B's lane), all faulty builds:

    - **full** — ``program="full"``: one pass per cond-gated phase, the
      multi-pass shape every faulty tick ran before the phase graph (the
      42-45 ms/tick @ N=16,384 bench-lane baseline in PERF.md round-4c).
    - **dispatched** — the production build: per-tick ``lax.cond`` between
      the full and fused programs on the planner-derived predicate.
    - **fused** — the standalone 2-pass program (draw + folded update),
      legal here because the steady lane keeps the dispatch predicate
      false every tick.

    All three final states AND per-tick metrics are compared bit-for-bit
    BEFORE any number is reported (like ``--warp`` / ``--telemetry-ab``):
    the dispatch contract says program choice never changes values, so any
    difference voids the measurement. The headline is
    ``speedup = full / dispatched`` — what production steady ticks gained —
    with the standalone fused arm as the no-dispatch floor. The pass
    structure behind the numbers rides along from the planner
    (``passes_full`` / ``passes_fused`` / ``pruned``), so the JSON tail
    documents WHY the fused arm is faster, not just that it is.
    """
    import jax
    import jax.numpy as jnp

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.phasegraph.derive import make_dense_tick, make_fused_tick
    from kaboodle_tpu.phasegraph.exec import make_tick_fn
    from kaboodle_tpu.sim.scenario import Scenario
    from kaboodle_tpu.sim.state import init_state

    cfg = SwimConfig()
    lean = n >= LEAN_STATE_MIN_N
    narrow = lean and ticks <= 32000
    st = init_state(n, seed=0, ring_contacts=n - 1, announced=True,
                    track_latency=not lean, instant_identity=lean,
                    timer_dtype=jnp.int16 if narrow else jnp.int32)
    sc = Scenario(n, ticks, seed=0)
    sc.manual_ping_at(ticks // 3, 0, 1)
    sc.manual_ping_at((2 * ticks) // 3, 1, 2)
    inputs = sc.build()
    rtt = _null_rtt()

    full_tick = make_tick_fn(cfg, faulty=True, program="full")
    disp_tick = make_dense_tick(cfg, faulty=True)
    fused_tick = make_fused_tick(cfg, faulty=True)

    def compile_scan(tick):
        return jax.jit(
            lambda s, i: jax.lax.scan(tick, s, i)
        ).lower(st, inputs).compile()

    arms = {
        "full": compile_scan(full_tick),
        "dispatched": compile_scan(disp_tick),
        "fused": compile_scan(fused_tick),
    }

    # Warm each arm once (doubles as the bit-exactness evidence), then best
    # of three timed executions per arm, same discipline as --telemetry-ab.
    outs = {}
    for name, fn in arms.items():
        outs[name] = fn(st, inputs)
        jax.block_until_ready(outs[name])
    ref = jax.tree.leaves(outs["full"])
    bit_exact = all(
        _leaf_equal(a, b)
        for other in ("dispatched", "fused")
        for a, b in zip(ref, jax.tree.leaves(outs[other]))
    )

    def best_of(fn, reps=3):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(st, inputs))
            best = min(best, time.perf_counter() - t0 - rtt)
        return max(best, 1e-9)

    walls = {name: best_of(fn) for name, fn in arms.items()}
    progs = disp_tick.programs
    return {
        "n": n,
        "ticks": ticks,
        "full_wall_s": round(walls["full"], 4),
        "dispatched_wall_s": round(walls["dispatched"], 4),
        "fused_wall_s": round(walls["fused"], 4),
        "full_ms_per_tick": round(1e3 * walls["full"] / ticks, 3),
        "dispatched_ms_per_tick": round(1e3 * walls["dispatched"] / ticks, 3),
        "fused_ms_per_tick": round(1e3 * walls["fused"] / ticks, 3),
        "speedup": round(walls["full"] / walls["dispatched"], 2),
        "fused_speedup": round(walls["full"] / walls["fused"], 2),
        "passes_full": len(progs["full"].passes),
        "passes_fused": len(progs["fused"].passes),
        "pruned": [name for name, _ in progs["fused"].pruned],
        "bit_exact": bit_exact,
        "state_variant": ("lean+int16" if narrow else "lean") if lean else "full",
    }


def _peak_device_memory_mib():
    """Peak device-memory use of the default device, if the backend reports
    it (TPU does; the CPU backend returns nothing)."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        return None
    peak = stats.get("peak_bytes_in_use") or stats.get("bytes_in_use")
    return round(peak / 2**20, 1) if peak else None


def _static_peak_mib(compiled):
    """Compiled-program static peak (costscope derivation) in MiB.

    The tunnel fallback: ``memory_stats()`` is empty through the TPU
    tunnel, so every banked capture's ``peak_hbm_mib`` has been null —
    ``memory_analysis()`` on the AOT-compiled executable always answers
    (argument + output + temp bytes, aliased buffers counted once)."""
    try:
        from kaboodle_tpu.costscope.extract import static_peak_bytes

        return round(static_peak_bytes(compiled.memory_analysis()) / 2**20, 1)
    except Exception:
        return None


def _bench_gossip_boot(sizes, max_ticks: int, ring_contacts: int = 2,
                       backdate: bool = True):
    """Ticks-to-convergence with NO broadcast medium (the gossip boot):
    join_broadcast_enabled=False + ring seed contacts, so membership spreads
    only via pings + anti-entropy pulls (kaboodle.rs:707-740). Unlike the
    broadcast boot — where the first tick's Join broadcast makes everyone
    know everyone (W3) — this measures real epidemic convergence.

    ``backdate=True`` is the reference-faithful mode (Q6 anti-echo; spread is
    ~O(N) ticks); ``backdate=False`` is the epidemic-boot extension
    (~O(log N) ticks — see SwimConfig.backdate_gossip_inserts)."""
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.runner import run_until_converged
    from kaboodle_tpu.sim.state import init_state

    import jax.numpy as jnp

    cfg = SwimConfig(join_broadcast_enabled=False,
                     backdate_gossip_inserts=backdate)
    out = []
    for n in sizes:
        lean = n >= LEAN_STATE_MIN_N
        narrow = lean and max_ticks < jnp.iinfo(jnp.int16).max
        st = init_state(
            n, seed=0, ring_contacts=ring_contacts,
            track_latency=not lean, instant_identity=lean,
            timer_dtype=jnp.int16 if narrow else jnp.int32,
        )
        t0 = time.perf_counter()
        _, ticks, conv = run_until_converged(st, cfg, max_ticks=max_ticks)
        ticks_v, conv_v = int(ticks), bool(conv)
        out.append({
            "n": n,
            "ticks_to_convergence": ticks_v if conv_v else None,
            "converged": conv_v,
            "wall_s": round(time.perf_counter() - t0, 3),
        })
    return out


def _scenario_state_and_inputs(config: int, n: int, ticks: int,
                               calm_budget: int = 0):
    """Baseline-config state + stacked inputs, with dtype headroom for a
    recovery phase of up to ``calm_budget`` further ticks (int16 timers only
    when the whole run stays below the dtype max — init_state contract).
    The single owner of the lean/int16 selection policy for the scenario
    sections (same rule as _bench's headline path)."""
    import jax.numpy as jnp

    from kaboodle_tpu.sim.scenario import baseline_scenario
    from kaboodle_tpu.sim.state import init_state

    lean = n >= LEAN_STATE_MIN_N
    narrow = lean and (ticks + calm_budget) < jnp.iinfo(jnp.int16).max
    st = init_state(n, seed=0, track_latency=not lean, instant_identity=lean,
                    timer_dtype=jnp.int16 if narrow else jnp.int32)
    return st, baseline_scenario(config, n=n, ticks=ticks).build()


def _bench_churn(n: int, ticks: int = 64):
    """BASELINE config 3, throughput half: 5%/tick join+leave churn for the
    first half of the run, then calm — the suspicion / indirect-ping /
    removal path under load. Reports faulty-path throughput; the full
    re-convergence dynamics (which need ~2N calm ticks, far beyond this
    timing window) are measured by :func:`_bench_churn_recovery`."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.runner import simulate

    cfg = SwimConfig()
    st, inp = _scenario_state_and_inputs(3, n, ticks)
    rtt = _null_rtt()

    @jax.jit
    def run(s, i):
        out, m = simulate(s, i, cfg, faulty=True)
        return m.converged, m.agree_fraction

    conv, _ = run(st, inp)  # compile + warm
    int(jnp.sum(conv))
    t0 = time.perf_counter()
    conv, agree = run(st, inp)
    conv_v, agree_v = np.asarray(conv), np.asarray(agree)
    elapsed = max(time.perf_counter() - t0 - rtt, 1e-9)

    stop = ticks // 2
    reconv = None
    if conv_v[-1]:
        later_false = np.where(~conv_v[stop:])[0]
        reconv = int(later_false[-1] + 1) if later_false.size else 0
    return {
        "n": n,
        "ticks": ticks,
        "churn_rate": 0.05,
        "peers_ticks_per_sec": round(n * ticks / elapsed, 2),
        "reconverged_in_window": bool(conv_v[-1]),
        "reconverge_ticks_after_churn": reconv,
        "final_agree_fraction": round(float(agree_v[-1]), 4),
        "wall_s": round(elapsed, 3),
    }


def _recovery_budget(n: int) -> int:
    """Calm ticks to allow for full post-fault re-convergence: the removal
    pipeline completes in ~2N ticks (per-survivor timeout through the
    oldest-5 rotation — the reference's completeness bound, SURVEY §6);
    budget 2.5N for the suspicion-timeout tail."""
    return int(2.5 * n)


def _bench_churn_recovery(n: int, ticks: int = 64):
    """BASELINE config 3, recovery half: after the churn window closes, give
    the mesh up to ~2.5N calm ticks and report how many it actually needed
    to re-converge (every survivor agreeing on the fingerprint again —
    kaboodle.rs:558-653 is the suspicion/removal path this exercises).

    Runs at a deliberately smaller N than the throughput half when needed:
    the recovery loop is O(N) ticks of an O(N^2) kernel, so its cost grows
    as N^3 and would eat a whole live-TPU window at N=8,192."""
    import jax
    import numpy as np

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.runner import run_until_converged, simulate

    cfg = SwimConfig()
    budget = _recovery_budget(n)
    st, inp = _scenario_state_and_inputs(3, n, ticks, calm_budget=budget)

    @jax.jit
    def run(s, i):
        out, m = simulate(s, i, cfg, faulty=True)
        return out, m.converged

    t0 = time.perf_counter()
    out, conv = run(st, inp)
    conv_v = np.asarray(conv)
    stop = ticks // 2
    in_window = ticks - stop  # calm ticks already spent inside the scan
    if conv_v[-1]:
        later_false = np.where(~conv_v[stop:])[0]
        reconv = int(later_false[-1] + 1) if later_false.size else 0
        reconverged = True
    else:
        _, extra, ok = run_until_converged(out, cfg, max_ticks=budget)
        reconverged = bool(ok)
        reconv = in_window + int(extra) if reconverged else None
    alive = np.asarray(out.alive)
    return {
        "n": n,
        "churn_ticks": stop,
        "churn_rate": 0.05,
        "calm_budget": in_window + budget,
        "reconverged": reconverged,
        "reconverge_ticks_after_churn": reconv,
        "survivors": int(alive.sum()),
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def _bench_partition_heal(n: int, ticks: int = 48):
    """BASELINE config 5 scaled: 10% uniform message drop over the first two
    thirds, a 2-way partition over the middle third, both healed at the
    final third — then count the calm ticks until every peer agrees again.

    The partition window must stay well under the peers-behind-the-cut purge
    bound (see sim.scenario.baseline_scenario's config-5 notes); ``ticks=48``
    keeps a 16-tick window against N/2 >= 128 peers behind the cut."""
    import jax
    import numpy as np

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.runner import run_until_converged, simulate

    cfg = SwimConfig()
    budget = _recovery_budget(n)
    st, inp5 = _scenario_state_and_inputs(5, n, ticks, calm_budget=budget)

    @jax.jit
    def run(s, i):
        out, m = simulate(s, i, cfg, faulty=True)
        return out, m.converged

    t0 = time.perf_counter()
    out, conv = run(st, inp5)
    conv_v = np.asarray(conv)
    heal = 2 * (ticks // 3)
    in_window = ticks - heal
    if conv_v[-1]:
        later_false = np.where(~conv_v[heal:])[0]
        reheal = int(later_false[-1] + 1) if later_false.size else 0
        reconverged = True
    else:
        _, extra, ok = run_until_converged(out, cfg, max_ticks=budget)
        reconverged = bool(ok)
        reheal = in_window + int(extra) if reconverged else None
    return {
        "n": n,
        "ticks": ticks,
        "drop_rate": 0.10,
        "partition_ticks": heal - ticks // 3,
        "calm_budget": in_window + budget,
        "reconverged": reconverged,
        "reconverge_ticks_after_heal": reheal,
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def _bench_detection(n: int = 64):
    """Failure-detection parity numbers (BASELINE: ~2-4 s latency, <= ~2N-tick
    completeness): kill one peer in a converged idle mesh and report when the
    survivors' fingerprints first diverge (first removal) and when they
    re-agree (every survivor has dropped it)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.runner import run_until_converged, simulate
    from kaboodle_tpu.sim.scenario import Scenario
    from kaboodle_tpu.sim.state import init_state

    cfg = SwimConfig()
    ticks = 3 * n  # the reference's completeness bound is ~2N (SURVEY §6)
    st, _, _ = run_until_converged(init_state(n, seed=0), cfg, max_ticks=8)
    inp = Scenario(n, ticks, seed=0).kill_at(0, [n // 2]).build()

    @jax.jit
    def run(s, i):
        _, m = simulate(s, i, cfg, faulty=True)
        return m.converged

    conv = np.asarray(run(st, inp))
    diverge = np.where(~conv)[0]
    first = int(diverge[0]) if diverge.size else None
    complete = int(diverge[-1] + 1) if (diverge.size and conv[-1]) else None
    return {
        "n": n,
        "first_removal_tick": first,
        "detection_complete_tick": complete,
        "completeness_bound_2n": 2 * n,
        "within_bound": complete is not None and complete <= 2 * n,
    }


def _probe_once(probe_timeout_s: int) -> bool:
    """One accelerator probe in a subprocess with a hard timeout.

    The tunneled TPU backend can wedge indefinitely (observed: device init
    hangs); a hung benchmark is worse than a degraded one. The probe runs in
    its own session with output discarded so a wedged child (or a tunnel
    helper it spawned) can neither block the timeout on pipe EOF nor survive
    the kill.
    """
    import os
    import signal
    import subprocess

    code = "import jax; jax.devices()"
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    try:
        return proc.wait(timeout=probe_timeout_s) == 0
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        try:
            proc.wait(timeout=5)  # reap; no zombie for the rest of the bench
        except subprocess.TimeoutExpired:
            pass
        return False


def _accelerator_responsive(
    probe_timeout_s: int = 150, attempts: int = 3, backoff_s: float = 20.0
) -> bool:
    """Probe with retries + backoff: a transiently wedged tunnel is the
    difference between a real TPU number and a round of CPU fallback, so one
    failed probe is not a verdict."""
    for i in range(attempts):
        if _probe_once(probe_timeout_s):
            return True
        if i + 1 < attempts:
            print(
                f"bench: accelerator probe {i + 1}/{attempts} failed; "
                f"retrying in {backoff_s:.0f}s",
                file=sys.stderr,
            )
            time.sleep(backoff_s)
    return False


def _emit_benchdoc(line: dict, manifest: str | None = None) -> None:
    """The full-document half of the output contract (VERDICT r4 item 5):
    one ``BENCHDOC``-tagged line + a repo-side mirror file. Every lane ends
    with this followed by its own compact single-line JSON summary, so a
    stdout-tail capture always parses the last line.

    ``manifest`` additionally appends the document as a schema-tagged
    ``run`` record to a JSONL run manifest (kaboodle_tpu.telemetry.manifest)
    — the shared machine-output schema of bench.py, the fleet sweep CLI,
    and the sim CLI, so one summarizer reads any lane's output."""
    import os

    doc = json.dumps(line)
    print("BENCHDOC " + doc)
    root = os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(root, "BENCH_last_full.json"), "w") as f:
            f.write(doc + "\n")
    except OSError as e:
        print(f"bench: could not write BENCH_last_full.json: {e}", file=sys.stderr)
    if manifest:
        from kaboodle_tpu.telemetry import ManifestWriter

        try:
            with ManifestWriter(manifest, append=True) as w:
                w.write("run", **line)
            print(f"bench: manifest record appended to {manifest}",
                  file=sys.stderr)
        except OSError as e:
            print(f"bench: could not write manifest {manifest}: {e}",
                  file=sys.stderr)


def _pin_cpu() -> None:
    """Pin JAX to the CPU backend. Env alone is not enough: the interpreter's
    sitecustomize may have imported jax already with a pinned platform —
    update the live config too (backends are created lazily; same pattern as
    tests/conftest.py). Strip the tunnel plugin first: a wedged tunnel can
    hang `import jax` itself (axon_guard docstring), which is the very
    outcome this fallback exists to avoid."""
    import os

    from axon_guard import strip_axon_plugin

    strip_axon_plugin()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def main() -> None:
    import os

    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=0, help="peer count (0 = auto by platform)")
    # Default scan length is lane-specific (None = pick per lane below):
    # 128 for the headline lane — the axon tunnel costs ~200 ms per
    # dispatched execute (TPU_WATCH.log dispatch-floor probes), so short
    # scans overstate the per-tick cost (32 ticks adds ~6 ms/tick of tunnel
    # overhead, 128 amortizes it under 2 ms) — and 256 for the warp lane
    # (the ISSUE 3 acceptance shape). An explicit value is always honored.
    p.add_argument("--ticks", type=int, default=None)
    p.add_argument("--no-probe", action="store_true",
                   help="skip the accelerator-responsiveness probe")
    p.add_argument("--no-gossip", action="store_true",
                   help="skip the gossip-boot convergence sweep")
    p.add_argument("--no-scenarios", action="store_true",
                   help="skip the churn (config 3) and detection-latency sections")
    p.add_argument("--gossip-sizes", type=int, nargs="*", default=None,
                   help="peer counts for the gossip-boot sweep (default: by platform)")
    p.add_argument("--platform", choices=["cpu"], default=None,
                   help="pin the JAX platform (skips the probe; 'cpu' avoids "
                        "touching a possibly-wedged accelerator plugin)")
    p.add_argument("--profile", metavar="DIR", default=None,
                   help="capture a JAX profiler trace of the throughput scan "
                        "into DIR (open with TensorBoard / xprof)")
    p.add_argument("--warp", action="store_true",
                   help="run the warp-vs-dense A/B (event-horizon fast-forward "
                        "on the sparse-fault steady-state scenario) instead of "
                        "the standard sections; same JSON tail contract")
    p.add_argument("--scenario", choices=["sparse-fault", "churn-recovery"],
                   default="sparse-fault",
                   help="--warp scenario: 'sparse-fault' (the ISSUE 3 "
                        "strict-quiescence A/B) or 'churn-recovery' (the "
                        "Warp 2.0 near-quiescent drain: staggered kills "
                        "first half, calm drain second half, hybrid "
                        "signature-classed fast-forward measured on the "
                        "calm phase)")
    p.add_argument("--telemetry-ab", action="store_true",
                   help="run the telemetry-on-vs-off A/B (the kaboodle_tpu."
                        "telemetry counter+recorder plane on the steady-state "
                        "scan) instead of the standard sections; same JSON "
                        "tail contract")
    p.add_argument("--fastpath-ab", action="store_true",
                   help="run the phase-graph fast-path A/B (full multi-pass "
                        "program vs the dispatched full+fused build vs the "
                        "standalone 2-pass fused program on the steady-state "
                        "scan, bit-exactness checked first) instead of the "
                        "standard sections; same JSON tail contract")
    p.add_argument("--serve", action="store_true",
                   help="run the serving load benchmark (closed + open loop "
                        "against an in-process gossip-as-a-service server, "
                        "throughput + p50/p99 + the zero-recompile pin) "
                        "instead of the standard sections; delegates to "
                        "`python -m kaboodle_tpu serve-load` and writes "
                        "BENCH_serve.json")
    p.add_argument("--sparse", action="store_true",
                   help="run the million-peer blocked_topk bench (boot "
                        "N>=2^20 peers in [N, K] neighbor blocks, per-peer "
                        "tick cost + convergence curves + the zero-recompile "
                        "pin + sub-quadratic bytes evidence) instead of the "
                        "standard sections; writes BENCH_sparse.json")
    p.add_argument("--out", metavar="PATH", default=None,
                   help="(--warp --scenario churn-recovery) also write the "
                        "full JSON line to PATH — the acceptance run banks "
                        "BENCH_warp3.json this way; dryruns omit it so toy "
                        "numbers never overwrite the banked capture")
    p.add_argument("--manifest", metavar="PATH", default=None,
                   help="append the BENCHDOC line as a 'run' record to a "
                        "JSONL telemetry manifest (kaboodle_tpu.telemetry."
                        "manifest schema; summarize with `python -m "
                        "kaboodle_tpu telemetry PATH`)")
    args = p.parse_args()

    if args.platform == "cpu":
        _pin_cpu()
        args.no_probe = True

    # The probe costs one extra backend init, so skip it when the platform is
    # already pinned to CPU (nothing to hang) or explicitly disabled.
    probe_needed = not args.no_probe and os.environ.get("JAX_PLATFORMS") != "cpu"
    fallback = probe_needed and not _accelerator_responsive()
    if fallback:
        print("bench: accelerator unresponsive; falling back to CPU backend",
              file=sys.stderr)
        _pin_cpu()
    import jax

    backend = jax.default_backend()
    n_chips = jax.device_count()
    on_tpu = backend not in ("cpu",)

    if args.serve:
        # Thin delegation: the serving load benchmark owns its own server
        # lifecycle, warmup accounting and JSON output (BENCH_serve.json),
        # so bench.py just routes to it with the shared --n knob.
        from kaboodle_tpu.serve.loadgen import main as serve_load_main

        argv = ["--n", str(args.n)] if args.n else []
        raise SystemExit(serve_load_main(argv))

    if args.sparse:
        # Thin delegation: the sparse bench owns its own chunked run,
        # steady-window compile accounting and JSON output
        # (BENCH_sparse.json); bench.py routes the shared --n knob through.
        from kaboodle_tpu.sparseplane.bench import main as sparse_bench_main

        argv = ["--n", str(args.n)] if args.n else []
        raise SystemExit(sparse_bench_main(argv))

    if args.warp:
        # Focused warp A/B lanes. 'sparse-fault': ISSUE 3 acceptance (>= 2x
        # over dense on the strict-quiescence scenario). 'churn-recovery':
        # ISSUE 8 acceptance (>= 10x over dense on the calm drain phase,
        # hybrid signature-classed fast-forward). Both end with the same
        # BENCHDOC + compact-tail contract as the standard run so the
        # driver's tail capture always parses.
        wn = args.n or (4096 if not on_tpu else 16384)
        if args.scenario == "churn-recovery":
            # Two measurements (PERF.md "Warp 2.0" / "Warp 3.0"): the
            # end-to-end orchestrated A/B at a wall-clock-feasible N
            # (discovery and expiry seasons are ~N/2 ticks wide, so the
            # full drain at N=4,096 would scan for hours on the CPU
            # lane), memo off AND on, and the claim-bearing calm-WINDOW
            # ratio at representative N — dense vs the hybrid leap vs the
            # memo replay over one mid-drain waiting window, the state
            # shape the calm phase is made of. The Warp 3.0 acceptance
            # run is N=1,024 e2e + N=4,096 window (BENCH_warp3.json).
            wn = args.n or (1024 if not on_tpu else 8192)
            if args.ticks is not None:
                wt = args.ticks
            else:
                # Season widths scale with N (~N/2 discovery) but the
                # dense arm's wall scales with N^2 * ticks: at N >= 1,024
                # halve the schedule so the CPU-lane dense arm stays in
                # minutes (the drain still fits: timeout = calm/3).
                wt = 8192 if wn >= 1024 else 16384
            warp = _bench_warp_churn_recovery(wn, wt)
            wn2 = args.n or (4096 if not on_tpu else 16384)
            window = _bench_warp_drain_window(wn2, 256 if wn2 >= 1024 else 64)
            line = {
                "metric": "warp3_churn_recovery_memo_e2e_speedup_vs_dense",
                "value": warp["memo_speedup"],
                "unit": "x",
                "n_peers": warp["n"],
                "ticks": warp["ticks"],
                "backend": backend + (" (fallback: accelerator unresponsive)"
                                      if fallback else ""),
                "e2e_speedup": warp["speedup"],
                "memo_e2e_speedup": warp["memo_speedup"],
                **{k: warp[k] for k in (
                    "calm_ticks", "ping_timeout_ticks", "dense_wall_s",
                    "warp_wall_s", "memo_wall_s", "dense_ticks_executed",
                    "leaped_ticks", "hybrid_leaped_ticks",
                    "strict_leaped_ticks", "signature_classes", "leap_cache",
                    "why_dense", "why_dense_memo", "memo", "compiles_steady",
                    "bit_exact", "memo_bit_exact", "state_variant")},
                **window,
                "peak_rss_mib": round(
                    resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024,
                    1),
            }
            _emit_benchdoc(line, manifest=args.manifest)
            print(json.dumps(line))
            if args.out:
                with open(args.out, "w") as f:
                    json.dump(line, f, indent=4)
                    f.write("\n")
            if not (warp["bit_exact"] and warp["memo_bit_exact"]
                    and window["window_bit_exact"]
                    and window["window_memo_bit_exact"]):
                sys.exit(3)  # a speedup from a wrong state is worthless
            if warp["compiles_steady"] != 0:
                sys.exit(4)  # the memo replay minted a program
            return
        wt = 256 if args.ticks is None else args.ticks  # acceptance shape default
        warp = _bench_warp(wn, wt)
        line = {
            "metric": "warp_speedup_vs_dense",
            "value": warp["speedup"],
            "unit": "x",
            "n_peers": warp["n"],
            "ticks": warp["ticks"],
            "backend": backend + (" (fallback: accelerator unresponsive)"
                                  if fallback else ""),
            **{k: warp[k] for k in (
                "dense_wall_s", "warp_wall_s", "dense_ticks_executed",
                "leaped_ticks", "bit_exact", "state_variant")},
            "peak_rss_mib": round(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1),
        }
        _emit_benchdoc(line, manifest=args.manifest)
        print(json.dumps(line))  # compact == full for this single-section lane
        return
    if args.fastpath_ab:
        # Focused phase-graph fast-path A/B lane (ISSUE 7 acceptance:
        # steady tick measurably under the full-program baseline, bit-exact
        # across all three arms — PERF.md "Phase graph"). Same output
        # contract as the warp/telemetry lanes.
        fn = args.n or (4096 if not on_tpu else 16384)
        ft = 64 if args.ticks is None else args.ticks
        ab = _bench_fastpath_ab(fn, ft)
        line = {
            "metric": "fastpath_speedup_vs_full",
            "value": ab["speedup"],
            "unit": "x",
            "n_peers": ab["n"],
            "ticks": ab["ticks"],
            "backend": backend + (" (fallback: accelerator unresponsive)"
                                  if fallback else ""),
            **{k: ab[k] for k in (
                "full_wall_s", "dispatched_wall_s", "fused_wall_s",
                "full_ms_per_tick", "dispatched_ms_per_tick",
                "fused_ms_per_tick", "fused_speedup", "passes_full",
                "passes_fused", "pruned", "bit_exact", "state_variant")},
            "peak_rss_mib": round(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1),
        }
        _emit_benchdoc(line, manifest=args.manifest)
        print(json.dumps(line))  # compact == full for this single-section lane
        return
    if args.telemetry_ab:
        # Focused telemetry A/B lane (ISSUE 6 acceptance: telemetry-on
        # steady tick <= 5% slower than telemetry-off, PERF.md "Telemetry").
        # Same output contract as the warp lane.
        tn = args.n or (1024 if not on_tpu else 16384)
        tt = 64 if args.ticks is None else args.ticks
        ab = _bench_telemetry_ab(tn, tt)
        line = {
            "metric": "telemetry_overhead_pct",
            "value": ab["overhead_pct"],
            "unit": "%",
            "n_peers": ab["n"],
            "ticks": ab["ticks"],
            "backend": backend + (" (fallback: accelerator unresponsive)"
                                  if fallback else ""),
            **{k: ab[k] for k in (
                "telemetry_off_wall_s", "telemetry_on_wall_s",
                "recorder_len", "bit_exact", "state_variant")},
            "peak_rss_mib": round(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1),
        }
        _emit_benchdoc(line, manifest=args.manifest)
        print(json.dumps(line))  # compact == full for this single-section lane
        return
    # Single-chip ceiling: N=32,768 lean+int16 is 1 GiB state + 2 GiB timers
    # persistent, well inside 16 GiB HBM with the scan transients
    # (MEMORY_PLAN.md); the OOM handler below steps down if a backend proves
    # otherwise. N=65,536 persistent alone is 12 GiB — sharded-only.
    sizes = [args.n] if args.n else ([32768, 16384, 8192] if on_tpu else [512])

    # Engage every chip when there are several (the sharded GSPMD path);
    # single-chip runs use the plain kernel.
    sharded = n_chips > 1
    if sharded:
        adjusted = [max(n_chips, n - n % n_chips) for n in sizes]
        if args.n and adjusted[0] != args.n:
            print(f"bench: --n {args.n} adjusted to {adjusted[0]} "
                  f"(multiple of {n_chips} chips)", file=sys.stderr)
        sizes = adjusted

    result = None
    used_n = None
    for n in sizes:
        try:
            result = _bench(n, 128 if args.ticks is None else args.ticks,
                            sharded=sharded,
                            profile_dir=args.profile)
            used_n = n
            break
        except Exception as e:
            # Step down only on size-induced ceilings (OOM / the tunnel's
            # 32k compile-helper failure); anything else is a real bug and
            # must surface as a traceback, not "all sizes failed".
            if not _is_size_ceiling(e) or n == sizes[-1]:
                raise
            print(f"bench: N={n} size ceiling ({type(e).__name__}); "
                  "stepping down", file=sys.stderr)

    # Gossip-boot convergence (the meaningful ticks-to-convergence metric:
    # the broadcast boot converges in 1 tick by construction, see W3). Sweep
    # sizes double so the growth with N is visible in one line. Reported in
    # both modes: reference-faithful (~O(N) spread) and the epidemic-boot
    # extension (~O(log N)).
    gossip = epidemic = None
    if not args.no_gossip:
        gsizes = args.gossip_sizes
        if gsizes is None:
            gsizes = [256, 512, 1024] if on_tpu else [64, 128]
        gossip = _bench_gossip_boot(gsizes, max_ticks=4096)
        # Auto-picked TPU sizes stretch the epidemic sweep 16x (it converges
        # in O(log N), so N up to 16,384 stays cheap); explicit
        # --gossip-sizes are honored as-is so both modes report the same N
        # values and are directly comparable.
        esizes = [n * 16 for n in gsizes] if (on_tpu and args.gossip_sizes is None) else gsizes
        epidemic = _bench_gossip_boot(esizes, max_ticks=512, backdate=False)

    # Scenario sections must never cost the headline line: step down on OOM
    # (the faulty-path transients exceed the fault-free scan that already
    # succeeded), record the error on anything persistent.
    churn = recovery = heal = detection = None
    if not args.no_scenarios:
        for cn in ([8192, 2048] if on_tpu else [256]):
            try:
                churn = _bench_churn(cn)
                break
            except Exception as e:
                print(f"bench: churn N={cn} failed ({type(e).__name__})",
                      file=sys.stderr)
                churn = {"n": cn, "error": type(e).__name__}
        # Recovery / heal run at a bounded N: the calm phase is ~2.5N ticks
        # of the O(N^2) kernel (N^3 total), so config-3/5 scale for these
        # proofs lives in the virtual-mesh scale proof, not the timing bench.
        for rn in ([2048, 1024] if on_tpu else [1024, 512]):
            try:
                recovery = _bench_churn_recovery(rn)
                break
            except Exception as e:
                print(f"bench: churn recovery N={rn} failed ({type(e).__name__})",
                      file=sys.stderr)
                recovery = {"n": rn, "error": type(e).__name__}
        for pn in ([2048, 1024] if on_tpu else [512, 256]):
            try:
                heal = _bench_partition_heal(pn)
                break
            except Exception as e:
                print(f"bench: partition heal N={pn} failed ({type(e).__name__})",
                      file=sys.stderr)
                heal = {"n": pn, "error": type(e).__name__}
        try:
            detection = _bench_detection(64)
        except Exception as e:
            print(f"bench: detection failed ({type(e).__name__})", file=sys.stderr)
            detection = {"error": type(e).__name__}

    # Fold the measured recovery numbers into the config-3 section (VERDICT
    # r4 item 5): the 64-tick throughput window cannot contain the ~1.3N-tick
    # recovery, so a driver reading churn_config3 alone used to see nulls.
    # The authoritative reconvergence measurement is _bench_churn_recovery's;
    # it runs at its own (smaller) N, recorded alongside.
    # The two sections fold independently so one erroring never drops the
    # other's verdict from the compact line.
    recovery_ok = isinstance(recovery, dict) and "error" not in recovery
    if isinstance(churn, dict) and "error" not in churn:
        churn = {
            **churn,
            "reconverged": bool(churn.get("reconverged_in_window"))
            or (recovery_ok and bool(recovery.get("reconverged"))),
        }
        if recovery_ok:
            churn["measured_recovery"] = recovery
            if not churn.get("reconverged_in_window") and recovery.get("reconverged"):
                churn["reconverge_ticks_after_churn"] = recovery.get(
                    "reconverge_ticks_after_churn")
                churn["reconverge_measured_at_n"] = recovery.get("n")

    value = result["peers_ticks_per_sec"] / n_chips
    # Reference demonstrated rate: 4 peers x 1 tick/s on one whole machine.
    baseline = 4.0
    line = {
        "metric": "simulated_peers_ticks_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "peers*ticks/s/chip",
        "vs_baseline": round(value / baseline, 2),
        "n_peers": used_n,
        "n_chips": n_chips,
        "sharded": sharded,
        "backend": backend + (" (fallback: accelerator unresponsive)" if fallback else ""),
        "state_variant": result["state_variant"],
        "pallas_fp": result["pallas_fp"],
        "converged": result["converged"],
        "ticks_to_convergence_broadcast_boot": result["ticks_to_convergence"],
        "convergence_wall_s": round(result["convergence_wall_s"], 4),
        "scan_wall_s": round(result["scan_wall_s"], 4),
        "null_rtt_s": round(result["null_rtt_s"], 4),
        "peak_hbm_mib": result["peak_hbm_mib"],
        "peak_hbm_mib_static": result.get("peak_hbm_mib_static"),
        # Host-side peak RSS is the memory telemetry fallback when the
        # backend reports no device stats (CPU); on TPU it still bounds the
        # host footprint. Non-null by construction (VERDICT r3 item 6).
        "peak_rss_mib": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1),
        "gossip_boot": gossip,
        "epidemic_boot": epidemic,
        "churn_config3": churn,
        "churn_recovery": recovery,
        "partition_heal": heal,
        "detection_latency": detection,
    }
    # BASELINE-scale recovery proofs (config 3 churn + config 5 partition
    # heal at N=8,192) are measured on-chip by scripts/tpu_recovery_probe.py
    # and banked in TPU_WATCH.log — the in-run sections above use smaller N
    # because the O(N)-tick recovery loop over an O(N^2) kernel would eat a
    # whole live window (or a CPU-fallback run) at 8,192. Attach the newest
    # banked proof so the round artifact carries the at-scale numbers.
    def _recovery_proof_ok(e):
        # Only a complete, successful, at-scale capture qualifies: the probe
        # banks partial lines (incl. *_error sections) and takes N from argv,
        # so a smoke run must never shadow the real proof.
        if any(k.endswith("_error") for k in e):
            return False
        secs = [e.get("churn_recovery"), e.get("partition_heal")]
        return all(isinstance(s, dict) and s.get("reconverged")
                   and s.get("n", 0) >= 8192 for s in secs)

    banked_recovery = _newest_watch_entry("recovery8192_chunked",
                                          _recovery_proof_ok)
    if banked_recovery is not None:
        line["recovery_at_baseline_scale"] = {
            "source": "TPU_WATCH.log", **banked_recovery,
        }
    if fallback:
        # The chip wedges for hours at a time (TPU_BENCH_NOTES.md); when this
        # run could not reach it, attach the newest banked on-TPU capture
        # (clearly labeled as such) so the round artifact still carries the
        # hardware data point.
        import glob

        root = os.path.dirname(os.path.abspath(__file__))
        candidates = []
        for path in glob.glob(os.path.join(root, "BENCH_r*_local.json")):
            try:
                with open(path) as f:
                    data = json.load(f)
                if str(data.get("backend", "")).startswith("tpu"):
                    candidates.append((os.path.getmtime(path), path, data))
            except (OSError, ValueError) as e:
                print(f"bench: unreadable banked capture {path}: {e}",
                      file=sys.stderr)
        if candidates:
            _, path, data = max(candidates)
            line["banked_tpu_capture"] = {"source": os.path.basename(path), **data}
        else:
            print("bench: no banked on-TPU capture to attach", file=sys.stderr)

    # Output contract (VERDICT r4 item 5): the full document overflowed the
    # driver's stdout-tail buffer in round 4 (BENCH_r04.json parsed: null),
    # so it now rides a tagged BENCHDOC line (+ a repo-side file) and the
    # process ENDS with one compact single-line JSON summary that always
    # parses from a tail capture. Readers that want detail follow the tag or
    # the file; machine consumers take the last line.
    _emit_benchdoc(line, manifest=args.manifest)

    def _sec(d, *keys):
        """Terse verdict from a section dict: just the named keys."""
        if not isinstance(d, dict):
            return None
        if "error" in d:
            return {"error": d["error"], "n": d.get("n")}
        return {k: d[k] for k in keys if k in d}

    compact = {
        "metric": line["metric"],
        "value": line["value"],
        "unit": line["unit"],
        "vs_baseline": line["vs_baseline"],
        "n_peers": line["n_peers"],
        "n_chips": n_chips,
        "sharded": sharded,
        "backend": line["backend"],
        "state_variant": line["state_variant"],
        "converged": line["converged"],
        "boot_ticks": line["ticks_to_convergence_broadcast_boot"],
        "peak_rss_mib": line["peak_rss_mib"],
        "gossip_boot_ok": (None if gossip is None
                           else all(g["converged"] for g in gossip)),
        "epidemic_boot_ok": (None if epidemic is None
                             else all(g["converged"] for g in epidemic)),
        "churn_config3": _sec(churn, "n", "reconverged",
                              "reconverge_ticks_after_churn",
                              "reconverge_measured_at_n", "peers_ticks_per_sec"),
        "partition_heal": _sec(heal, "n", "reconverged",
                               "reconverge_ticks_after_heal"),
        "detection_within_bound": (detection or {}).get("within_bound"),
        "recovery_at_scale_ok": banked_recovery is not None,
        "full_doc": "BENCH_last_full.json",
    }
    if "banked_tpu_capture" in line:
        cap = line["banked_tpu_capture"]
        compact["banked_tpu_capture"] = {
            "source": cap.get("source"), "value": cap.get("value"),
            "n_peers": cap.get("n_peers"), "backend": cap.get("backend"),
        }
    print(json.dumps(compact))


if __name__ == "__main__":
    main()
