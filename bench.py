"""Benchmark driver: simulated-peers·ticks/sec/chip + ticks-to-convergence.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Baseline: the reference has no published numbers (SURVEY.md §6); its
demonstrated scale is the 2x2 zellij demo — 4 real peers at 1 tick/second
(justfile:10-15, kaboodle.rs:38), i.e. an effective 4 simulated-peers·ticks/sec
on a whole laptop. ``vs_baseline`` is the speedup over that demonstrated rate.

Method: boot N peers knowing only themselves, measure (a) ticks to fingerprint
convergence and its wall-clock (the north-star quantity, BASELINE.json), then
(b) steady-state throughput of the fault-free tick kernel under lax.scan,
compile excluded. Timing forces execution by fetching a scalar to the host:
on the tunneled TPU backend ``block_until_ready`` does not synchronize, so
every measurement ends in a device->host fetch and the measured null-fetch
round-trip is subtracted. Sizes auto-step down if the chip runs out of memory.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _null_rtt() -> float:
    """Round-trip of a trivial jitted fetch (tunnel + dispatch overhead)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    x = jnp.float32(0)
    float(f(x))  # compile

    def once() -> float:
        t0 = time.perf_counter()
        float(f(x))
        return time.perf_counter() - t0

    return min(once() for _ in range(3))


def _bench(n: int, ticks: int, warmup: int = 1, sharded: bool = False):
    import jax
    import jax.numpy as jnp

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.runner import run_until_converged, simulate
    from kaboodle_tpu.sim.state import idle_inputs, init_state

    cfg = SwimConfig()
    st = init_state(n, seed=0)
    rtt = _null_rtt()

    if sharded:
        from kaboodle_tpu.parallel import (
            make_mesh,
            run_until_converged_sharded,
            shard_inputs,
            shard_state,
            simulate_sharded,
        )

        mesh = make_mesh()
        st = shard_state(st, mesh)

        def _converge(s):
            return run_until_converged_sharded(s, cfg, mesh, max_ticks=32)

        def _scan(s, i):
            return simulate_sharded(s, i, cfg, mesh, faulty=False)

        def _place_inputs(i):
            return shard_inputs(i, mesh, stacked=True)
    else:

        def _converge(s):
            return run_until_converged(s, cfg, max_ticks=32)

        def _scan(s, i):
            return simulate(s, i, cfg, faulty=False)

        def _place_inputs(i):
            return i

    # (a) convergence: compile first (cached), then time a fresh run. The
    # int() fetches force real execution through the tunnel.
    _, conv_ticks, conv = _converge(st)
    int(conv_ticks)
    t0 = time.perf_counter()
    _, conv_ticks, conv = _converge(st)
    conv_ticks_v = int(conv_ticks)
    conv_wall = max(time.perf_counter() - t0 - rtt, 0.0)

    # (b) steady-state throughput of the scanned tick kernel. The jitted fn
    # returns a scalar that depends on the final state, so the whole scan
    # must execute before the fetch completes.
    inp = _place_inputs(idle_inputs(n, ticks=ticks))

    @jax.jit
    def run(s, i):
        out, _ = _scan(s, i)
        return out.timer.sum() + out.tick

    for _ in range(max(warmup, 1)):
        int(run(st, inp))
    t0 = time.perf_counter()
    int(run(st, inp))
    elapsed = max(time.perf_counter() - t0 - rtt, 1e-9)
    return {
        "converged": bool(conv),
        "ticks_to_convergence": conv_ticks_v,
        "convergence_wall_s": conv_wall,
        "scan_ticks": ticks,
        "scan_wall_s": elapsed,
        "peers_ticks_per_sec": n * ticks / elapsed,
        "null_rtt_s": rtt,
    }


def _accelerator_responsive(probe_timeout_s: int = 150) -> bool:
    """Probe the default backend in a subprocess with a hard timeout.

    The tunneled TPU backend can wedge indefinitely (observed: device init
    hangs); a hung benchmark is worse than a degraded one, so when the probe
    times out the bench falls back to the CPU backend and says so. The probe
    runs in its own session with output discarded so a wedged child (or a
    tunnel helper it spawned) can neither block the timeout on pipe EOF nor
    survive the kill.
    """
    import os
    import signal
    import subprocess

    code = "import jax; jax.devices()"
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    try:
        return proc.wait(timeout=probe_timeout_s) == 0
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        try:
            proc.wait(timeout=5)  # reap; no zombie for the rest of the bench
        except subprocess.TimeoutExpired:
            pass
        return False


def main() -> None:
    import os

    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=0, help="peer count (0 = auto by platform)")
    p.add_argument("--ticks", type=int, default=32)
    p.add_argument("--no-probe", action="store_true",
                   help="skip the accelerator-responsiveness probe")
    args = p.parse_args()

    # The probe costs one extra backend init, so skip it when the platform is
    # already pinned to CPU (nothing to hang) or explicitly disabled.
    probe_needed = not args.no_probe and os.environ.get("JAX_PLATFORMS") != "cpu"
    fallback = probe_needed and not _accelerator_responsive()
    if fallback:
        print("bench: accelerator unresponsive; falling back to CPU backend",
              file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        # The environment alone is not enough here: sitecustomize may already
        # have imported jax and pinned the platform, so update the live config
        # too (backends are created lazily; see tests/conftest.py).
        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    backend = jax.default_backend()
    n_chips = jax.device_count()
    on_tpu = backend not in ("cpu",)
    sizes = [args.n] if args.n else ([16384, 8192, 4096] if on_tpu else [512])

    # Engage every chip when there are several (the sharded GSPMD path);
    # single-chip runs use the plain kernel.
    sharded = n_chips > 1
    if sharded:
        adjusted = [max(n_chips, n - n % n_chips) for n in sizes]
        if args.n and adjusted[0] != args.n:
            print(f"bench: --n {args.n} adjusted to {adjusted[0]} "
                  f"(multiple of {n_chips} chips)", file=sys.stderr)
        sizes = adjusted

    result = None
    used_n = None
    for n in sizes:
        try:
            result = _bench(n, args.ticks, sharded=sharded)
            used_n = n
            break
        except Exception as e:
            # Step down only on memory exhaustion; anything else is a real
            # bug and must surface as a traceback, not "all sizes failed".
            msg = str(e)
            oom = ("RESOURCE_EXHAUSTED" in msg
                   or "out of memory" in msg.lower()
                   or "failed to allocate" in msg.lower())
            if not oom or n == sizes[-1]:
                raise
            print(f"bench: N={n} OOM ({type(e).__name__}); stepping down",
                  file=sys.stderr)

    value = result["peers_ticks_per_sec"] / n_chips
    # Reference demonstrated rate: 4 peers x 1 tick/s on one whole machine.
    baseline = 4.0
    line = {
        "metric": "simulated_peers_ticks_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "peers*ticks/s/chip",
        "vs_baseline": round(value / baseline, 2),
        "n_peers": used_n,
        "n_chips": n_chips,
        "sharded": sharded,
        "backend": backend + (" (fallback: accelerator unresponsive)" if fallback else ""),
        "converged": result["converged"],
        "ticks_to_convergence": result["ticks_to_convergence"],
        "convergence_wall_s": round(result["convergence_wall_s"], 4),
        "scan_wall_s": round(result["scan_wall_s"], 4),
        "null_rtt_s": round(result["null_rtt_s"], 4),
    }
    print(json.dumps(line))


if __name__ == "__main__":
    main()
