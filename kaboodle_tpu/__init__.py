"""kaboodle-tpu: a TPU-native SWIM gossip membership framework.

The reference (serval/kaboodle) is a Rust library where N OS processes gossip over
UDP multicast, one tick per second (reference: src/kaboodle.rs). This framework
re-designs the same protocol TPU-first:

- N *simulated* peers live as rows of dense ``[N, N]`` membership tensors.
- One SWIM tick (reference: kaboodle.rs:746-779) is a pure JAX function
  ``state[t] -> state[t+1]`` driven by ``jax.lax.scan``.
- UDP unicast/broadcast become in-memory message matrices; cross-chip delivery is
  XLA collectives over ICI via ``jax.sharding`` (see ``kaboodle_tpu.parallel``).
- The CRC-32 mesh fingerprint (kaboodle.rs:71-83) becomes a vectorized reduction
  with an all-reduce convergence check.
- A real UDP transport (C++ + ctypes; see ``kaboodle_tpu.transport``) preserves
  the original 4-peer LAN demo and wire-format interop.

Public API mirrors the reference's ``Kaboodle`` facade (lib.rs:78-369): see
:class:`kaboodle_tpu.api.Kaboodle`.
"""

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.errors import KaboodleError

__version__ = "0.1.0"

__all__ = ["SwimConfig", "KaboodleError", "Kaboodle", "SimNetwork", "__version__"]


def __getattr__(name):
    # Lazy: the facade pulls in the sim stack; plain `import kaboodle_tpu`
    # (e.g. for config/spec constants) should not.
    if name in ("Kaboodle", "SimNetwork"):
        from kaboodle_tpu import api

        return getattr(api, name)
    raise AttributeError(name)
