"""``python -m kaboodle_tpu`` — the demo CLI (see kaboodle_tpu.cli)."""

import sys

from kaboodle_tpu.cli import main

sys.exit(main())
