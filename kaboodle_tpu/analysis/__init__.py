"""graftlint — the JAX/TPU-aware static analysis gate for this codebase.

A dependency-free, rule-based AST analyzer that supersedes the old
``scripts/lint.py`` (now a shim over this package) and understands the
repo's jit/shard_map idioms. Rule families:

- **KB1xx generic**: undefined names (KB101), unused imports (KB102),
  mutable default args (KB103), shadowed builtins (KB104).
- **KB2xx jax-tracer** (inside jit-traced code, per ``reach.py``): Python
  branching on traced values (KB201), host coercions of tracers (KB202),
  ``print`` in jit (KB203), PRNG key reuse (KB204), use-after-donation
  (KB205).
- **KB3xx hot-path** (``sim/``, ``ops/``, ``fleet/``, ``warp/``,
  ``oracle/``): host syncs in the tick kernels (KB301), dtype-less ``jnp``
  constructors in the dtype-disciplined files (KB302).
- **KB4xx IR** (graftscan, ``analysis/ir/`` — the ``--ir`` lane): passes
  over the *traced* kernel entry points — dtype widening under x64
  (KB401), host callbacks in jitted programs (KB402), oversized captured
  constants (KB403), GSPMD spec derivation (KB404), and the
  compile-surface budget vs ``.graftscan_surface.json`` (KB405).
- **KB5xx concurrency** (graftconc, ``analysis/conc/`` — the ``--conc``
  lane, serve scope only): blocking calls reachable from the event loop
  (KB501), ``# guarded_by:`` lock discipline (KB502), device values
  crossing thread boundaries unmaterialized (KB503), the
  flush->fsync->replace durable-write protocol (KB504), lock-order cycles
  (KB505), unbounded queues (KB506) — plus the RUNTIME half,
  ``conc/sanitizer.py``: dynamic lock-order graph + event-loop watchdog
  under chaos and the serve test suites.

Suppression: per-line ``# noqa: KBnnn`` (bare ``# noqa`` and foreign-code
lists suppress everything on the line), or a justified entry in the
checked-in baseline — ``.graftlint_baseline.json`` for the AST lane,
``.graftscan_baseline.json`` for IR findings (which have no source line to
noqa), ``.graftconc_baseline.json`` for the conc lane — see ``core.py``.

CLI: ``python -m kaboodle_tpu.analysis [--ir|--conc] [--explain KBnnn]
[paths...]``; ``make lint`` and CI run all three lanes, and CI's
``--no-baseline-growth`` steps guarantee every baseline only shrinks.

The default lane imports no jax: analysis is pure AST, so it and its tests
run at parse speed with no accelerator backend. Only the ``--ir`` lane
(and ``analysis/ir/``'s internals) import jax, CPU-pinned.
"""

from kaboodle_tpu.analysis.core import (
    Finding,
    Module,
    analyze_module,
    analyze_path,
    analyze_source,
)
from kaboodle_tpu.analysis.cli import main

__all__ = [
    "Finding",
    "Module",
    "analyze_module",
    "analyze_path",
    "analyze_source",
    "main",
]
