"""graftlint — the JAX/TPU-aware static analysis gate for this codebase.

A dependency-free, rule-based AST analyzer that supersedes the old
``scripts/lint.py`` (now a shim over this package) and understands the
repo's jit/shard_map idioms. Rule families:

- **KB1xx generic**: undefined names (KB101), unused imports (KB102),
  mutable default args (KB103), shadowed builtins (KB104).
- **KB2xx jax-tracer** (inside jit-traced code, per ``reach.py``): Python
  branching on traced values (KB201), host coercions of tracers (KB202),
  ``print`` in jit (KB203), PRNG key reuse (KB204), use-after-donation
  (KB205).
- **KB3xx hot-path** (``kaboodle_tpu/sim/`` + ``kaboodle_tpu/ops/``):
  host syncs in the tick kernels (KB301), dtype-less ``jnp`` constructors
  in the dtype-disciplined files (KB302).

Suppression: per-line ``# noqa: KBnnn`` (bare ``# noqa`` and foreign-code
lists suppress everything on the line), or a justified entry in the
checked-in baseline ``.graftlint_baseline.json`` — see ``core.py``.

CLI: ``python -m kaboodle_tpu.analysis [--explain KBnnn] [paths...]``;
``make lint`` and CI run it with the default target set, and CI's
``--no-baseline-growth`` step guarantees the baseline only shrinks.

This module imports no jax: analysis is pure AST, so the lint lane and its
tests run at parse speed with no accelerator backend.
"""

from kaboodle_tpu.analysis.core import (
    Finding,
    Module,
    analyze_module,
    analyze_path,
    analyze_source,
)
from kaboodle_tpu.analysis.cli import main

__all__ = [
    "Finding",
    "Module",
    "analyze_module",
    "analyze_path",
    "analyze_source",
    "main",
]
