"""``python -m kaboodle_tpu.analysis`` — the graftlint CLI entry point."""

import sys

from kaboodle_tpu.analysis.cli import main

if __name__ == "__main__":
    try:
        rc = main(sys.argv[1:])
    except BrokenPipeError:  # output piped into head/grep that closed early
        rc = 0
    raise SystemExit(rc)
