"""graftlint CLI: ``python -m kaboodle_tpu.analysis [options] [paths...]``.

Exit codes: 0 clean (baselined findings allowed), 1 findings / baseline
violations, 2 usage or baseline-format error.

Modes:

- default: report every finding whose key is not in the baseline.
- ``--no-baseline-growth``: additionally fail on *stale* baseline entries
  (keys that no longer match any finding). Together with the default mode
  this makes the baseline monotonically shrinking: new findings can't land
  (they fail the lint), and fixed findings force their entry's deletion.
- ``--write-baseline``: regenerate the baseline from current findings,
  preserving existing reasons; new entries get a TODO reason that the
  loader will keep accepting but a reviewer should replace.
- ``--explain KBnnn`` / ``--list-rules``: rule documentation.
"""

from __future__ import annotations

import pathlib
import sys

from kaboodle_tpu.analysis import core

DEFAULT_TARGETS = [
    "kaboodle_tpu", "tests", "scripts", "bench.py", "__graft_entry__.py",
]

USAGE = """\
usage: python -m kaboodle_tpu.analysis [options] [paths...]

options:
  --baseline PATH        baseline file (default: .graftlint_baseline.json)
  --no-baseline          ignore the baseline entirely
  --no-baseline-growth   also fail on stale baseline entries (CI debt gate)
  --write-baseline       regenerate the baseline from current findings
  --explain KBnnn        print one rule's documentation and exit
  --list-rules           print every rule id + title and exit
  -h, --help             this message
"""


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    baseline_path = pathlib.Path(core.DEFAULT_BASELINE)
    use_baseline = True
    no_growth = False
    write = False
    targets: list[str] = []

    core._load_rules()
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print(USAGE, end="")
            return 0
        if a == "--baseline":
            i += 1
            if i >= len(argv):
                print("--baseline needs a path", file=sys.stderr)
                return 2
            baseline_path = pathlib.Path(argv[i])
        elif a == "--no-baseline":
            use_baseline = False
        elif a == "--no-baseline-growth":
            no_growth = True
        elif a == "--write-baseline":
            write = True
        elif a == "--list-rules":
            for rid in sorted(core.REGISTRY):
                print(f"{rid}  {core.REGISTRY[rid].title}")
            return 0
        elif a == "--explain":
            i += 1
            rid = argv[i].upper() if i < len(argv) else ""
            r = core.REGISTRY.get(rid)
            if r is None:
                print(f"unknown rule {rid!r}; try --list-rules", file=sys.stderr)
                return 2
            print(f"{r.id} — {r.title}\n\n{r.explain}")
            return 0
        elif a.startswith("-"):
            print(f"unknown option {a!r}\n{USAGE}", file=sys.stderr, end="")
            return 2
        else:
            targets.append(a)
        i += 1

    files = core.iter_python_files(targets or DEFAULT_TARGETS)
    findings: list[core.Finding] = []
    for f in files:
        findings.extend(core.analyze_path(f))

    try:
        baseline = core.load_baseline(baseline_path) if use_baseline else {}
    except core.BaselineError as e:
        print(str(e), file=sys.stderr)
        return 2

    if write:
        core.write_baseline(baseline_path, findings, baseline)
        print(
            f"graftlint: wrote {baseline_path} with "
            f"{len({x.key for x in findings})} entries",
            file=sys.stderr,
        )
        return 0

    active = [f for f in findings if f.key not in baseline]
    suppressed = len(findings) - len(active)
    for f in active:
        print(f.render())

    rc = 1 if active else 0
    if no_growth:
        live_keys = {f.key for f in findings}
        stale = sorted(k for k in baseline if k not in live_keys)
        for k in stale:
            print(f"stale baseline entry (fixed? delete it): {k}")
        if stale:
            rc = 1

    print(
        f"graftlint: {len(files)} files, {len(active)} findings"
        + (f" ({suppressed} baselined)" if suppressed else ""),
        file=sys.stderr,
    )
    return rc
