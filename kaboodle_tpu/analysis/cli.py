"""graftlint/graftscan/graftconc/keyscope CLI: ``python -m kaboodle_tpu.analysis``.

Exit codes: 0 clean (baselined findings allowed), 1 findings / baseline
violations, 2 usage or baseline-format error.

Four lanes share one UX:

- **AST lane** (default): rules KB1xx-KB3xx over the source tree. Pure
  ``ast`` + stdlib — no jax, parse speed.
- **IR lane** (``--ir``): rules KB401-KB405 over the *traced* kernel entry
  points (kaboodle_tpu/analysis/ir/) plus the compile-surface budget.
  Imports jax (CPU-pinned), so it is its own invocation.
- **conc lane** (``--conc``, or the ``conc`` subcommand): rules
  KB501-KB506 (kaboodle_tpu/analysis/conc/) — the host-concurrency
  auditor for the serve plane. Same dependency-free AST machinery as the
  default lane but a separate gate: its scope, findings, and debt file
  (``.graftconc_baseline.json``) evolve independently of graftlint's.
- **rng lane** (``--rng``, or the ``rng`` subcommand): rules KB601-KB605
  (kaboodle_tpu/analysis/rng/) — keyscope, the key-provenance auditor
  over the same traced registry the IR lane reads. Key reuse, stream-id
  collisions, resume impurity, cross-engine chain divergence, and the
  banked leapability report (``--leap-report`` / ``--write-leap`` /
  ``--check-leap`` against ``KEYSCOPE_LEAP.json``). Debt file:
  ``.keyscope_baseline.json``. ``make rng-dryrun`` runs the CI shape.

``--all`` runs every lane in one invocation (AST, conc, IR, rng — each
against its own baseline) and reports one combined exit code with a
per-lane summary line: ``make lint`` is one process instead of four.

Modes (all lanes):

- default: report every finding whose key is not in the lane's baseline
  (``.graftlint_baseline.json`` / ``.graftscan_baseline.json`` /
  ``.graftconc_baseline.json`` / ``.keyscope_baseline.json``).
- ``--no-baseline-growth``: additionally fail on *stale* baseline entries
  (keys that no longer match any finding) and, in the IR lane, on a
  compile-surface count below its committed budget. Together with the
  default mode this makes every baseline monotonically shrinking.
- ``--write-baseline``: regenerate the lane's baseline, preserving
  reasons; ``--write-surface`` (IR) regenerates the surface budget.
- ``--explain KBnnn`` / ``--list-rules``: rule documentation (all six
  families, any lane — the registry is shared).

IR/rng-lane extras: ``--entries a,b`` scans only the named entry points;
``--no-surface`` (IR) skips the compile-heavy KB405 exercise — for fast
local iteration only, the gate always runs it. The rng leap-report
freshness gate (``--check-leap``) only runs on full-registry scans: a
scoped ``--entries`` run can never prove the committed report current.
"""

from __future__ import annotations

import pathlib
import sys

from kaboodle_tpu.analysis import core

DEFAULT_TARGETS = [
    "kaboodle_tpu", "tests", "scripts", "bench.py", "__graft_entry__.py",
]

DEFAULT_IR_BASELINE = ".graftscan_baseline.json"
DEFAULT_CONC_BASELINE = ".graftconc_baseline.json"
DEFAULT_RNG_BASELINE = ".keyscope_baseline.json"

USAGE = """\
usage: python -m kaboodle_tpu.analysis [options] [paths...]

options:
  --baseline PATH        baseline file (default: .graftlint_baseline.json;
                         .graftscan_baseline.json with --ir;
                         .graftconc_baseline.json with --conc;
                         .keyscope_baseline.json with --rng)
  --no-baseline          ignore the baseline entirely
  --no-baseline-growth   also fail on stale baseline entries (CI debt gate)
  --write-baseline       regenerate the baseline from current findings
  --explain KBnnn        print one rule's documentation and exit
  --list-rules           print every rule id + title and exit
  --ir                   run the IR lane (graftscan, KB4xx) instead of the
                         AST lane; traces the kernel entry-point registry
  --conc                 run the concurrency lane (graftconc, KB5xx) over
                         the serve scope ('conc' as first arg works too)
  --rng                  run the key-provenance lane (keyscope, KB6xx) over
                         the traced registry ('rng' as first arg works too)
  --all                  run every lane (AST, conc, IR, rng) against their
                         own baselines; one combined exit code
  --entries a,b          (--ir/--rng) scan only the named entry points
  --surface PATH         (--ir) surface budget (default: .graftscan_surface.json)
  --write-surface        (--ir) regenerate the surface budget file
  --no-surface           (--ir) skip the compile-surface exercise (KB405)
  --leap PATH            (--rng) leap report file (default: KEYSCOPE_LEAP.json)
  --leap-report          (--rng) print the leapability report (KB605 table)
  --write-leap           (--rng) regenerate + write the leap report file
  --check-leap           (--rng) fail if the committed leap report is stale
  -h, --help             this message
"""


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # `python -m kaboodle_tpu.analysis conc ...` == `... --conc ...` (and
    # `rng` likewise): the subcommand spelling matches the other
    # kaboodle_tpu CLI planes.
    if argv and argv[0] in ("conc", "rng"):
        argv[0] = "--" + argv[0]
    baseline_path: pathlib.Path | None = None
    use_baseline = True
    no_growth = False
    write = False
    ir_mode = False
    conc_mode = False
    rng_mode = False
    all_mode = False
    entries_filter: list[str] | None = None
    surface_path: pathlib.Path | None = None
    write_surface = False
    with_surface = True
    leap_path: pathlib.Path | None = None
    leap_report = False
    write_leap = False
    check_leap = False
    targets: list[str] = []

    core._load_rules()
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print(USAGE, end="")
            return 0
        if a == "--baseline":
            i += 1
            if i >= len(argv):
                print("--baseline needs a path", file=sys.stderr)
                return 2
            baseline_path = pathlib.Path(argv[i])
        elif a == "--no-baseline":
            use_baseline = False
        elif a == "--no-baseline-growth":
            no_growth = True
        elif a == "--write-baseline":
            write = True
        elif a == "--ir":
            ir_mode = True
        elif a == "--conc":
            conc_mode = True
        elif a == "--rng":
            rng_mode = True
        elif a == "--all":
            all_mode = True
        elif a == "--entries":
            i += 1
            if i >= len(argv):
                print("--entries needs a comma-separated list", file=sys.stderr)
                return 2
            entries_filter = [e for e in argv[i].split(",") if e]
        elif a == "--surface":
            i += 1
            if i >= len(argv):
                print("--surface needs a path", file=sys.stderr)
                return 2
            surface_path = pathlib.Path(argv[i])
        elif a == "--write-surface":
            write_surface = True
        elif a == "--no-surface":
            with_surface = False
        elif a == "--leap":
            i += 1
            if i >= len(argv):
                print("--leap needs a path", file=sys.stderr)
                return 2
            leap_path = pathlib.Path(argv[i])
        elif a == "--leap-report":
            leap_report = True
        elif a == "--write-leap":
            write_leap = True
        elif a == "--check-leap":
            check_leap = True
        elif a == "--list-rules":
            for rid in sorted(core.REGISTRY):
                print(f"{rid}  {core.REGISTRY[rid].title}")
            return 0
        elif a == "--explain":
            i += 1
            rid = argv[i].upper() if i < len(argv) else ""
            r = core.REGISTRY.get(rid)
            if r is None:
                print(f"unknown rule {rid!r}; try --list-rules", file=sys.stderr)
                return 2
            print(f"{r.id} — {r.title}\n\n{r.explain}")
            return 0
        elif a.startswith("-"):
            print(f"unknown option {a!r}\n{USAGE}", file=sys.stderr, end="")
            return 2
        else:
            targets.append(a)
        i += 1

    lanes_picked = sum((ir_mode, conc_mode, rng_mode))
    if lanes_picked > 1 or (all_mode and lanes_picked):
        print("--ir/--conc/--rng/--all are exclusive; pick one",
              file=sys.stderr)
        return 2
    if all_mode:
        if write or write_surface or write_leap or targets or entries_filter:
            print(
                "--all is the read-only gate over every lane; regenerate "
                "baselines per-lane (--ir --write-baseline, ...)",
                file=sys.stderr,
            )
            return 2
        return _run_all(use_baseline, no_growth, with_surface)
    if ir_mode:
        if targets:
            print(
                "--ir scans the entry-point registry, not paths; use "
                "--entries name,... to scope it",
                file=sys.stderr,
            )
            return 2
        return _run_ir(
            baseline_path or pathlib.Path(DEFAULT_IR_BASELINE),
            use_baseline,
            no_growth,
            write,
            entries_filter,
            surface_path,
            write_surface,
            with_surface,
        )
    if rng_mode:
        if targets:
            print(
                "--rng scans the entry-point registry, not paths; use "
                "--entries name,... to scope it",
                file=sys.stderr,
            )
            return 2
        return _run_rng(
            baseline_path or pathlib.Path(DEFAULT_RNG_BASELINE),
            use_baseline,
            no_growth,
            write,
            entries_filter,
            leap_path,
            leap_report,
            write_leap,
            check_leap,
        )
    return _run_ast(
        conc_mode, baseline_path, use_baseline, no_growth, write, targets
    )


def _run_ast(
    conc_mode: bool,
    baseline_path: pathlib.Path | None,
    use_baseline: bool,
    no_growth: bool,
    write: bool,
    targets: list[str],
) -> int:
    """The source lanes: graftlint (KB1xx-3xx) or graftconc (KB5xx).

    One registry, two debt files — KB5xx rules are scope-gated to the
    serve plane and run only under --conc; KB4xx/KB6xx registrations are
    documentation-only no-ops either way."""
    lane = "graftconc" if conc_mode else "graftlint"
    rules = [
        core.REGISTRY[rid]
        for rid in sorted(core.REGISTRY)
        if rid.startswith("KB5") == conc_mode
    ]
    files = core.iter_python_files(targets or DEFAULT_TARGETS)
    findings: list[core.Finding] = []
    for f in files:
        findings.extend(core.analyze_path(f, rules=rules))

    if baseline_path is None:
        baseline_path = pathlib.Path(
            DEFAULT_CONC_BASELINE if conc_mode else core.DEFAULT_BASELINE
        )
    try:
        baseline = core.load_baseline(baseline_path) if use_baseline else {}
    except core.BaselineError as e:
        print(str(e), file=sys.stderr)
        return 2

    if write:
        core.write_baseline(baseline_path, findings, baseline)
        print(
            f"{lane}: wrote {baseline_path} with "
            f"{len({x.key for x in findings})} entries",
            file=sys.stderr,
        )
        return 0

    active = [f for f in findings if f.key not in baseline]
    suppressed = len(findings) - len(active)
    for f in active:
        print(f.render())

    rc = 1 if active else 0
    if no_growth:
        live_keys = {f.key for f in findings}
        stale = sorted(k for k in baseline if k not in live_keys)
        for k in stale:
            print(f"stale baseline entry (fixed? delete it): {k}")
        if stale:
            rc = 1

    print(
        f"{lane}: {len(files)} files, {len(active)} findings"
        + (f" ({suppressed} baselined)" if suppressed else ""),
        file=sys.stderr,
    )
    return rc


def _run_ir(
    baseline_path: pathlib.Path,
    use_baseline: bool,
    no_growth: bool,
    write_baseline: bool,
    entries_filter: list[str] | None,
    surface_path: pathlib.Path | None,
    write_surface: bool,
    with_surface: bool,
) -> int:
    """The --ir lane: trace, audit, gate — same baseline semantics as AST."""
    from kaboodle_tpu.analysis.ir import scan as ir_scan
    from kaboodle_tpu.analysis.ir import surface as ir_surface

    if surface_path is None:
        surface_path = pathlib.Path(ir_surface.DEFAULT_SURFACE)

    # Validate both committed files BEFORE the (trace + compile)-heavy scan,
    # so a malformed baseline fails in milliseconds, not after a minute.
    try:
        baseline = core.load_baseline(baseline_path) if use_baseline else {}
        committed = ir_surface.load_surface(surface_path)
    except core.BaselineError as e:
        print(str(e), file=sys.stderr)
        return 2

    # --write-baseline alone never reads the surface measurement; skip the
    # compile-heavy exercise for it (traces are all it needs).
    measure = (with_surface and not write_baseline) or write_surface
    try:
        result = ir_scan.run_scan(
            entry_names=entries_filter,
            with_surface=measure,
            progress=lambda msg: print(msg, file=sys.stderr),
        )
    except KeyError as e:
        print(str(e), file=sys.stderr)
        return 2

    if write_baseline:
        core.write_baseline(baseline_path, result.findings, baseline)
        print(
            f"graftscan: wrote {baseline_path} with "
            f"{len({x.key for x in result.findings})} entries",
            file=sys.stderr,
        )
    if write_surface:
        ir_surface.write_surface(surface_path, result.surface_measured, committed)
        print(f"graftscan: wrote {surface_path}", file=sys.stderr)
    if write_baseline or write_surface:
        return 0

    findings = list(result.findings)
    if with_surface:
        findings.extend(
            ir_surface.surface_findings(
                result.surface_measured, committed, no_growth=no_growth
            )
        )

    # KB405 findings are NOT baselineable: the surface budget file (with its
    # per-entry justifications) is the ONLY accepted record of the compile
    # surface — a baseline entry keyed 'surface:*:growth' would bypass the
    # gate for growth of any magnitude, forever.
    active = [
        f for f in findings if f.rule == "KB405" or f.key not in baseline
    ]
    suppressed = len(findings) - len(active)
    for f in active:
        print(f.render())

    rc = 1 if active else 0
    if no_growth:
        live_keys = {f.key for f in findings if f.rule != "KB405"}
        stale = sorted(k for k in baseline if k not in live_keys)
        for k in stale:
            print(f"stale baseline entry (fixed? delete it): {k}")
        if stale:
            rc = 1

    surf = (
        "; surface " + ", ".join(
            f"{k}={v}" for k, v in sorted(result.surface_measured.items())
        )
        if result.surface_measured
        else ""
    )
    print(
        f"graftscan: {result.entries_scanned} entry points, {len(active)} "
        f"findings" + (f" ({suppressed} baselined)" if suppressed else "") + surf,
        file=sys.stderr,
    )
    return rc


def _run_rng(
    baseline_path: pathlib.Path,
    use_baseline: bool,
    no_growth: bool,
    write_baseline: bool,
    entries_filter: list[str] | None,
    leap_path: pathlib.Path | None,
    leap_report: bool,
    write_leap: bool,
    check_leap: bool,
) -> int:
    """The --rng lane: keyscope over the traced registry.

    Same baseline semantics as the other lanes; the leap report rides the
    same scan (graphs are already built) so ``--check-leap`` costs no
    extra traces. KB605 freshness findings are NOT baselineable — the
    leap report file is the only accepted record of the classification,
    and a 'stale' justification would disable the gate forever."""
    from kaboodle_tpu.analysis.rng import scan as rng_scan

    if leap_path is None:
        leap_path = pathlib.Path(rng_scan.DEFAULT_LEAP_REPORT)
    costscope_path = _default_costscope_path()

    try:
        baseline = core.load_baseline(baseline_path) if use_baseline else {}
    except core.BaselineError as e:
        print(str(e), file=sys.stderr)
        return 2

    try:
        result = rng_scan.run_rng_scan(
            entry_names=entries_filter,
            progress=lambda msg: print(msg, file=sys.stderr),
        )
    except KeyError as e:
        print(str(e), file=sys.stderr)
        return 2

    if write_baseline:
        core.write_baseline(baseline_path, result.findings, baseline)
        print(
            f"keyscope: wrote {baseline_path} with "
            f"{len({x.key for x in result.findings})} entries",
            file=sys.stderr,
        )
    if write_leap:
        if entries_filter:
            print(
                "--write-leap needs the full registry (drop --entries): a "
                "partial report would mark every unscanned entry stale",
                file=sys.stderr,
            )
            return 2
        report = rng_scan.build_leap_report(
            result.graphs, costscope_path=costscope_path
        )
        rng_scan.write_leap_report(report, leap_path)
        print(f"keyscope: wrote {leap_path}", file=sys.stderr)
    if write_baseline or write_leap:
        return 0

    findings = list(result.findings)
    if check_leap:
        if entries_filter:
            print(
                "keyscope: --check-leap skipped (scoped --entries run "
                "cannot validate the full-registry report)",
                file=sys.stderr,
            )
        else:
            findings.extend(
                rng_scan.leap_findings(
                    result.graphs, leap_path, costscope_path=costscope_path
                )
            )

    active = [
        f for f in findings if f.rule == "KB605" or f.key not in baseline
    ]
    suppressed = len(findings) - len(active)
    for f in active:
        print(f.render())

    rc = 1 if active else 0
    if no_growth:
        live_keys = {f.key for f in findings if f.rule != "KB605"}
        stale = sorted(k for k in baseline if k not in live_keys)
        for k in stale:
            print(f"stale baseline entry (fixed? delete it): {k}")
        if stale:
            rc = 1

    if leap_report:
        report = rng_scan.build_leap_report(
            result.graphs, costscope_path=costscope_path
        )
        print(rng_scan.render_leap_report(report))

    sinks = sum(len(g.sinks) for g in result.graphs.values())
    print(
        f"keyscope: {result.entries_scanned} entry points, {sinks} draw "
        f"sinks, {len(active)} findings"
        + (f" ({suppressed} baselined)" if suppressed else ""),
        file=sys.stderr,
    )
    return rc


def _default_costscope_path() -> pathlib.Path | None:
    """The committed costscope baseline, if present (leap-report byte join)."""
    try:
        from kaboodle_tpu.costscope.baseline import DEFAULT_BASELINE

        p = pathlib.Path(DEFAULT_BASELINE)
        return p if p.exists() else None
    except Exception:
        return None


def _run_all(use_baseline: bool, no_growth: bool, with_surface: bool) -> int:
    """``--all``: every lane, own baselines, one combined exit code.

    Lane order is cheap-to-expensive (AST, conc, then the two traced
    lanes) so source-level failures surface in the first seconds. Every
    lane always runs — one combined report, not fail-fast — because the
    lanes gate independent debt files."""
    results: list[tuple[str, int]] = []
    results.append(
        ("graftlint", _run_ast(False, None, use_baseline, no_growth, False, []))
    )
    results.append(
        ("graftconc", _run_ast(True, None, use_baseline, no_growth, False, []))
    )
    results.append(
        (
            "graftscan",
            _run_ir(
                pathlib.Path(DEFAULT_IR_BASELINE),
                use_baseline, no_growth, False, None, None, False, with_surface,
            ),
        )
    )
    results.append(
        (
            "keyscope",
            _run_rng(
                pathlib.Path(DEFAULT_RNG_BASELINE),
                use_baseline, no_growth, False, None, None, False, False, True,
            ),
        )
    )
    rc = max(code for _, code in results)
    summary = " ".join(f"{lane}={code}" for lane, code in results)
    print(f"analysis --all: {summary} -> rc {rc}", file=sys.stderr)
    return rc
