"""graftconc — the concurrency/effect analysis plane (KB5xx + sanitizer).

Third analysis plane, alongside graftlint (KB1-3xx, single-threaded AST
discipline) and graftscan/costscope (KB4xx, the traced device program).
This one audits the HOST orchestration layer of the serve stack — the
asyncio event loop, the spill writer thread, the WAL journal — for the
bug classes neither of the other planes can see:

- **Static half** (``rules.py``, rules KB501-KB506): a dependency-free
  AST pass over the serve/spill/journal/admission/obsplane/server scope.
  Runs behind ``python -m kaboodle_tpu.analysis --conc`` (or the ``conc``
  subcommand) with its own shrink-only baseline
  (``.graftconc_baseline.json``) and the usual ``# noqa: KB5nn`` /
  ``--explain`` plumbing.
- **Runtime half** (``sanitizer.py``): instrumented lock wrappers that
  record the dynamic lock-acquisition-order graph and raise on the first
  cycle-closing edge, plus an event-loop blocking-call detector (slow
  callback threshold). Enabled inside the chaos harness and the serve
  robustness/obsplane test suites, so every CI run doubles as a race
  regression test.

Like the rest of ``analysis/``, nothing here imports jax: the static
rules are pure ``ast`` and the sanitizer is stdlib ``threading``/
``asyncio`` only, so both halves load in any process (including the
serve engine itself, which uses ``sanitizer.make_lock``).
"""
