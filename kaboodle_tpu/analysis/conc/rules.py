"""KB5xx — graftconc static rules, scoped to the serve concurrency surface.

The scope (:data:`CONC_SCOPE`) is the code where three execution contexts
meet: the asyncio event loop (server/engine round dispatch), the spill
writer thread, and the durable-write path (journal/checkpoint files). The
rules encode the contracts those files document in prose:

- KB501: nothing blocking runs on the event loop. ``async def`` bodies are
  the seeds; ``# conc: event-loop`` on a ``def`` line marks functions the
  loop calls from *another* module (the per-module analogue of graftlint's
  ``# graftlint: traced`` pragma). Reachability closes over module-local
  calls — plain names and ``self.method()`` within one class — but NOT
  through executor offloads (``asyncio.to_thread`` / ``run_in_executor`` /
  ``Thread(target=...)`` arguments run off-loop by construction).
- KB502: a field annotated ``# guarded_by: <lock>`` is only touched inside
  ``with self.<lock>:``. Guardedness is inferred interprocedurally: a
  private helper whose every intra-class call site holds the lock is
  lock-held inside too, and ``# guarded_by`` on a ``def`` line asserts the
  lock at entry (and at every call site).
- KB503: device values must be materialized (``np.asarray`` /
  ``jax.device_get`` / ``.item()``) before crossing a thread boundary.
- KB504: durable writes follow tmp-write -> flush -> fsync ->
  ``os.replace``; serve-side ``checkpoint.save`` calls must say
  ``atomic=True``.
- KB505: the static lock-acquisition-order graph has no cycles.
- KB506: no unbounded ``Queue()``/``deque()`` in serve scope.

Approximation stance mirrors ``reach.py``: per-module, no lambda bodies,
false negatives acceptable, false positives engineered against — a
concurrency gate that cries wolf gets noqa'd into uselessness.
"""

from __future__ import annotations

import ast
import re

from kaboodle_tpu.analysis.core import Finding, Module, rule

# Paths the KB5xx rules fire in: the serve plane's three execution contexts
# plus the durable-write helpers they call and the sanitizer itself
# (dogfooding: the lock-order checker's own lock discipline is checked).
CONC_SCOPE = (
    "kaboodle_tpu/serve/",
    "kaboodle_tpu/checkpoint.py",
    "kaboodle_tpu/telemetry/manifest.py",
    "kaboodle_tpu/analysis/conc/sanitizer.py",
)

EVENT_LOOP_PRAGMA = "conc: event-loop"
_GUARDED_BY_RE = re.compile(r"#\s*guarded_by:\s*(?:self\.)?(?P<lock>[A-Za-z_]\w*)")

# Blocking callables by resolved dotted name (KB501). jnp.asarray resolves
# to jax.numpy.asarray and deliberately does NOT match numpy.asarray — a
# device put is async, the device->host fetch is the stall.
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep()",
    "os.fsync": "os.fsync()",
    "jax.device_get": "jax.device_get()",
    "numpy.asarray": "np.asarray()",
    "numpy.array": "np.array()",
    "kaboodle_tpu.checkpoint.save": "checkpoint.save()",
    "kaboodle_tpu.checkpoint.load": "checkpoint.load()",
}
_BLOCKING_ATTRS = {
    "block_until_ready": ".block_until_ready()",
    "acquire": ".acquire()",
}

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition"}

_UNBOUNDED = {
    # dotted ctor -> the keyword that bounds it (None: never boundable)
    "queue.Queue": "maxsize",
    "queue.LifoQueue": "maxsize",
    "queue.PriorityQueue": "maxsize",
    "queue.SimpleQueue": None,
    "asyncio.Queue": "maxsize",
    "collections.deque": "maxlen",
}

# Materializers that cut KB503 device taint: the value that crosses the
# thread boundary afterwards is host memory (or a Python scalar).
_MATERIALIZERS = {
    "numpy.asarray", "numpy.array", "jax.device_get", "float", "int", "bool",
}


def _in_scope(mod: Module) -> bool:
    return any(s in mod.path for s in CONC_SCOPE)


def _def_line(mod: Module, node: ast.AST) -> str:
    return mod.lines[node.lineno - 1] if 0 < node.lineno <= len(mod.lines) else ""


def _classes(mod: Module) -> list[ast.ClassDef]:
    return [n for n in ast.walk(mod.tree) if isinstance(n, ast.ClassDef)]


def _methods(cls: ast.ClassDef) -> dict[str, ast.FunctionDef | ast.AsyncFunctionDef]:
    return {
        n.name: n
        for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _self_attr(e: ast.AST) -> str | None:
    """``self.X`` -> ``"X"`` (the with-lock / guarded-field shape)."""
    if (
        isinstance(e, ast.Attribute)
        and isinstance(e.value, ast.Name)
        and e.value.id == "self"
    ):
        return e.attr
    return None


def _scan_calls(fn: ast.AST):
    """Call nodes in ``fn``'s own body — nested defs/lambdas are skipped
    (they only run when called; local calls to them are resolved at their
    call sites, and offload targets never run on this context at all)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


# ---------------------------------------------------------------------------
# KB501 — blocking calls on the event loop


@rule(
    "KB501",
    "blocking call reachable from the event loop",
    """
A blocking call — `os.fsync`, file `open()`, `time.sleep`, `Lock.acquire`,
`.block_until_ready()`, device->host `np.asarray`/`jax.device_get`, or
`checkpoint.save/load` — inside an `async def` body (or a function marked
`# conc: event-loop`, the cross-module escape hatch: the asyncio server
dispatches `ServeEngine.step`/`submit`/... inline on the loop), reached
through module-local calls. One such call stalls EVERY connection and the
whole round loop: the serve plane's p99 latency is exactly the longest
synchronous segment anything schedules on the loop. Offload it
(`await asyncio.to_thread(...)`, `run_in_executor`, or the SpillManager's
writer thread — offload arguments are exempt by construction), or justify
the stall in `.graftconc_baseline.json` / `# noqa: KB501` with a reason.
""",
)
def check_loop_blocking(mod: Module) -> list[Finding]:
    if not _in_scope(mod):
        return []
    reach = mod.reach

    # class membership, for resolving `self.m()` calls within one class
    method_class: dict[ast.AST, ast.ClassDef] = {}
    for cls in _classes(mod):
        for m in _methods(cls).values():
            method_class[m] = cls

    seeds: list[ast.AST] = []
    for node in reach.by_node:
        if isinstance(node, ast.AsyncFunctionDef) or EVENT_LOOP_PRAGMA in _def_line(
            mod, node
        ):
            seeds.append(node)

    # worklist closure over module-local calls; `via` keeps the seed each
    # function was reached from, for the message
    via: dict[ast.AST, ast.AST] = {s: s for s in seeds}
    work = list(seeds)
    while work:
        fn = work.pop()
        for call in _scan_calls(fn):
            target = None
            if isinstance(call.func, ast.Name):
                cands = reach.by_name.get(call.func.id, [])
                target = cands[0].node if len(cands) == 1 else None
            else:
                attr = _self_attr(call.func)
                cls = method_class.get(fn)
                if attr and cls is not None:
                    target = _methods(cls).get(attr)
            if target is not None and target not in via:
                via[target] = via[fn]
                work.append(target)

    out: list[Finding] = []
    for fn in via:
        info = reach.by_node.get(fn)
        qual = info.qualname if info else getattr(fn, "name", "<fn>")
        seed_info = reach.by_node.get(via[fn])
        seed = seed_info.qualname if seed_info else qual
        for call in _scan_calls(fn):
            d = mod.dotted(call.func)
            hit = _BLOCKING_DOTTED.get(d or "")
            if hit is None and d == "open":
                hit = "open()"
            if hit is None and isinstance(call.func, ast.Attribute):
                hit = _BLOCKING_ATTRS.get(call.func.attr)
            if hit is None:
                continue
            where = f"'{qual}'" + (
                f" (reachable from event-loop '{seed}')" if fn is not via[fn] else ""
            )
            out.append(
                Finding(
                    mod.path, "KB501", call.lineno,
                    f"blocking {hit} on the event loop in {where}",
                    f"{qual}.{hit}",
                )
            )
    return out


# ---------------------------------------------------------------------------
# KB502 — guarded_by lock discipline


@rule(
    "KB502",
    "guarded_by field accessed outside its lock",
    """
A field annotated `# guarded_by: <lock>` (on its assignment in the class
body) was read or written outside a `with self.<lock>:` region. The
annotation is the cross-thread contract — e.g. SpillManager's `_cache` is
touched by both the round loop and the writer thread — and this rule makes
it checkable. Guardedness is interprocedural within the class: a helper
whose EVERY intra-class call site holds the lock counts as lock-held, and
`# guarded_by: <lock>` on a `def` line (methods and properties) asserts
the lock is held at entry — its body passes, and every intra-class call/
access site must hold the lock. `__init__` is exempt: construction is
single-threaded and the lock may not exist yet. Fix by widening the `with`
region, or drop the annotation if the field is genuinely single-threaded.
""",
)
def check_guarded_by(mod: Module) -> list[Finding]:
    if not _in_scope(mod):
        return []
    out: list[Finding] = []
    for cls in _classes(mod):
        methods = _methods(cls)
        guarded_fields: dict[str, str] = {}
        for n in ast.walk(cls):
            if isinstance(n, (ast.Assign, ast.AnnAssign)):
                m = _GUARDED_BY_RE.search(_def_line(mod, n))
                if not m:
                    continue
                targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                for t in targets:
                    field = _self_attr(t)
                    if field:
                        guarded_fields[field] = m.group("lock")
        guarded_defs: dict[str, str] = {}
        for name, fn in methods.items():
            m = _GUARDED_BY_RE.search(_def_line(mod, fn))
            if m:
                guarded_defs[name] = m.group("lock")
        if not guarded_fields and not guarded_defs:
            continue

        # entry_held[m]: locks provably held whenever m runs. Grows
        # monotonically from {} (plus any `# guarded_by` def pragma), so
        # the fixed point terminates.
        entry_held: dict[str, set[str]] = {
            name: ({guarded_defs[name]} if name in guarded_defs else set())
            for name in methods
        }

        def walk(name: str):
            """(field_accesses, method_refs): each is (node, lineno, held)."""
            accesses: list[tuple[str, int, set[str]]] = []
            refs: list[tuple[str, int, set[str]]] = []

            def visit_expr(e: ast.AST, held: frozenset) -> None:
                for n in ast.walk(e):
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                        continue
                    attr = _self_attr(n)
                    if attr is None:
                        continue
                    if attr in guarded_fields:
                        accesses.append((attr, n.lineno, set(held)))
                    if attr in methods:
                        refs.append((attr, n.lineno, set(held)))

            def do(stmts, held: frozenset) -> None:
                for s in stmts:
                    if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                        # closures escape the region: conservatively unheld
                        for sub in (
                            s.body if not isinstance(s, ast.ClassDef) else []
                        ):
                            do([sub], frozenset())
                        continue
                    if isinstance(s, (ast.With, ast.AsyncWith)):
                        inner = set(held)
                        for item in s.items:
                            visit_expr(item.context_expr, held)
                            lock = _self_attr(item.context_expr)
                            if lock is None and isinstance(
                                item.context_expr, ast.Name
                            ):
                                lock = item.context_expr.id
                            if lock:
                                inner.add(lock)
                        do(s.body, frozenset(inner))
                        continue
                    for fname, value in ast.iter_fields(s):
                        if fname in ("body", "orelse", "finalbody", "handlers"):
                            continue
                        if isinstance(value, ast.AST):
                            visit_expr(value, held)
                        elif isinstance(value, list):
                            for v in value:
                                if isinstance(v, ast.expr):
                                    visit_expr(v, held)
                    for fname in ("body", "orelse", "finalbody"):
                        do(getattr(s, fname, []) or [], held)
                    for h in getattr(s, "handlers", []) or []:
                        do(h.body, held)

            do(methods[name].body, frozenset(entry_held[name]))
            return accesses, refs

        # fixed point over intra-class call sites
        for _ in range(len(methods) + 1):
            site_held: dict[str, list[set[str]]] = {}
            for name in methods:
                _, refs = walk(name)
                if name == "__init__":
                    continue  # ctor refs (e.g. Thread(target=self._run)) are pre-sharing
                for ref, _line, held in refs:
                    site_held.setdefault(ref, []).append(held)
            changed = False
            for name in methods:
                sites = site_held.get(name)
                inferred = (
                    set.intersection(*sites) if sites else set()
                ) | ({guarded_defs[name]} if name in guarded_defs else set())
                if inferred - entry_held[name]:
                    entry_held[name] |= inferred
                    changed = True
            if not changed:
                break

        for name in methods:
            if name == "__init__":
                continue  # single-threaded construction; lock may not exist yet
            accesses, refs = walk(name)
            for field, lineno, held in accesses:
                lock = guarded_fields[field]
                if lock not in held:
                    out.append(
                        Finding(
                            mod.path, "KB502", lineno,
                            f"'{cls.name}.{field}' (guarded_by: {lock}) accessed "
                            f"in '{name}' without 'with self.{lock}'",
                            f"{cls.name}.{name}.{field}",
                        )
                    )
            for ref, lineno, held in refs:
                lock = guarded_defs.get(ref)
                if lock and lock not in held and lock not in entry_held[name]:
                    out.append(
                        Finding(
                            mod.path, "KB502", lineno,
                            f"'{cls.name}.{ref}' (guarded_by: {lock}) called "
                            f"from '{name}' without 'with self.{lock}'",
                            f"{cls.name}.{name}.{ref}",
                        )
                    )
    return out


# ---------------------------------------------------------------------------
# KB503 — device values crossing thread boundaries


def _device_tainted(mod: Module, e: ast.AST, tainted: set[str]) -> bool:
    if isinstance(e, ast.Name):
        return e.id in tainted
    if isinstance(e, ast.Call):
        d = mod.dotted(e.func)
        if d in _MATERIALIZERS:
            return False
        if isinstance(e.func, ast.Attribute) and e.func.attr in ("item", "tolist"):
            return False
        if d and (d == "jax" or d.startswith(("jax.", "jax_"))):
            return True
    return any(_device_tainted(mod, c, tainted) for c in ast.iter_child_nodes(e))


@rule(
    "KB503",
    "device value crossing a thread boundary unmaterialized",
    """
A value produced by a `jnp.`/`jax.` call is handed to another thread —
`queue.put(...)`, `Thread(target=..., args=...)`, `run_in_executor`/
`to_thread` arguments — without materialization. A jax.Array is a handle
to (possibly still-executing) device buffers: the consuming thread's first
use forces the transfer at an uncontrolled point, two threads can race the
same donated buffer, and the spill protocol's 'the host tree IS the
request' durability contract silently becomes 'a device pointer is'.
Materialize first (`np.asarray`, `jax.device_get`, `.item()`) or hand over
a zero-arg thunk so the WORKER executes the fetch (the SpillManager
`member_snapshot` pattern).
""",
)
def check_device_cross_thread(mod: Module) -> list[Finding]:
    if not _in_scope(mod):
        return []
    out: list[Finding] = []
    reach = mod.reach

    for node, info in reach.by_node.items():
        tainted: set[str] = set()

        def handoff(args: list[ast.expr], call: ast.Call, what: str, qual: str):
            for a in args:
                if _device_tainted(mod, a, tainted):
                    out.append(
                        Finding(
                            mod.path, "KB503", call.lineno,
                            f"unmaterialized device value into {what} in "
                            f"'{qual}' — np.asarray/device_get it first",
                            f"{qual}.{what}",
                        )
                    )
                    return

        # linear walk in source order: assignments taint, handoffs check
        for n in ast.walk(node):
            if isinstance(n, ast.Assign) and _device_tainted(mod, n.value, tainted):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)
            elif isinstance(n, ast.Call):
                d = mod.dotted(n.func)
                attr = n.func.attr if isinstance(n.func, ast.Attribute) else None
                if attr in ("put", "put_nowait"):
                    handoff(list(n.args), n, f"{attr}()", info.qualname)
                elif d == "threading.Thread":
                    for kw in n.keywords:
                        if kw.arg == "args":
                            handoff([kw.value], n, "Thread(args=...)", info.qualname)
                elif d == "asyncio.to_thread" or attr == "run_in_executor":
                    handoff(list(n.args)[1:], n, f"{attr or 'to_thread'}()",
                            info.qualname)
    return out


# ---------------------------------------------------------------------------
# KB504 — durable-write protocol


@rule(
    "KB504",
    "durable write missing the flush->fsync->replace protocol",
    """
A function calls `os.replace` (the atomic-publish step) without `os.fsync`
+ `.flush()` on the temp file first, or a serve-side `checkpoint.save`
omits `atomic=True`. The protocol — write tmp, flush Python buffers, fsync
to the platter, `os.replace` into place — is what makes a crash leave
either the old complete file or the new complete file. Skip the fsync and
the rename can land BEFORE the data blocks: a power cut then publishes a
hole, and recovery (journal replay, spill restore) trips over a truncated
archive it was promised could not exist. `journal._write_json_atomic` and
`checkpoint._savez_atomic` are the canonical implementations.
""",
)
def check_durable_protocol(mod: Module) -> list[Finding]:
    if not _in_scope(mod):
        return []
    out: list[Finding] = []
    for node, info in mod.reach.by_node.items():
        calls = list(_scan_calls(node))
        replaces = [c for c in calls if mod.dotted(c.func) == "os.replace"]
        if replaces:
            has_fsync = any(mod.dotted(c.func) == "os.fsync" for c in calls)
            has_flush = any(
                isinstance(c.func, ast.Attribute) and c.func.attr == "flush"
                for c in calls
            )
            if not (has_fsync and has_flush):
                missing = "os.fsync" if not has_fsync else ".flush()"
                out.append(
                    Finding(
                        mod.path, "KB504", replaces[0].lineno,
                        f"os.replace in '{info.qualname}' without {missing} "
                        "before it — torn durable write on crash",
                        f"{info.qualname}.os.replace",
                    )
                )
        if "kaboodle_tpu/serve/" in mod.path:
            for c in calls:
                d = mod.dotted(c.func)
                if d != "kaboodle_tpu.checkpoint.save":
                    continue
                atomic = any(
                    kw.arg == "atomic"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                    for kw in c.keywords
                )
                if not atomic:
                    out.append(
                        Finding(
                            mod.path, "KB504", c.lineno,
                            f"checkpoint.save without atomic=True in "
                            f"'{info.qualname}' — a crash mid-spill leaves a "
                            "truncated archive",
                            f"{info.qualname}.checkpoint.save",
                        )
                    )
    return out


# ---------------------------------------------------------------------------
# KB505 — static lock-order graph


@rule(
    "KB505",
    "lock-acquisition-order cycle",
    """
Two code paths acquire the same locks in opposite orders (`with a: with
b:` somewhere, `with b: with a:` somewhere else) — the classic ABBA
deadlock: each thread holds one lock and waits forever on the other, and
it only manifests under exactly the interleaving chaos tests don't hit.
The graph is built per module from `with` nesting, closed over
module-local calls (a helper that takes lock B, called under lock A, adds
the edge A->B). Fix by picking ONE acquisition order and sticking to it;
the runtime sanitizer (analysis/conc/sanitizer.py) asserts the same
invariant on the DYNAMIC graph under chaos.
""",
)
def check_lock_order(mod: Module) -> list[Finding]:
    if not _in_scope(mod):
        return []
    reach = mod.reach
    method_class: dict[ast.AST, ast.ClassDef] = {}
    for cls in _classes(mod):
        for m in _methods(cls).values():
            method_class[m] = cls

    def lock_label(e: ast.AST, fn: ast.AST) -> str | None:
        attr = _self_attr(e)
        if attr is not None:
            cls = method_class.get(fn)
            return f"{cls.name}.{attr}" if cls is not None else f"self.{attr}"
        if isinstance(e, ast.Name):
            return e.id
        return None

    # per function: direct nesting edges, own acquisitions, call sites under
    # held locks (resolved module-locally, same rules as KB501)
    acquires: dict[ast.AST, set[str]] = {}
    callees: dict[ast.AST, set[ast.AST]] = {}
    edges: dict[tuple[str, str], int] = {}
    deferred: list[tuple[set[str], ast.AST]] = []

    def resolve(call: ast.Call, fn: ast.AST) -> ast.AST | None:
        if isinstance(call.func, ast.Name):
            cands = reach.by_name.get(call.func.id, [])
            return cands[0].node if len(cands) == 1 else None
        attr = _self_attr(call.func)
        cls = method_class.get(fn)
        if attr and cls is not None:
            return _methods(cls).get(attr)
        return None

    for fn in reach.by_node:
        acquires[fn] = set()
        callees[fn] = set()

        def do(stmts, held: tuple, fn=fn) -> None:
            for s in stmts:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(s, (ast.With, ast.AsyncWith)):
                    inner = list(held)
                    for item in s.items:
                        lock = lock_label(item.context_expr, fn)
                        if lock:
                            for h in inner:
                                edges[(h, lock)] = min(
                                    edges.get((h, lock), s.lineno), s.lineno
                                )
                            acquires[fn].add(lock)
                            inner.append(lock)
                    do(s.body, tuple(inner))
                    continue
                for e in ast.iter_child_nodes(s):
                    if isinstance(e, (ast.stmt, ast.FunctionDef)):
                        continue
                    for c in ast.walk(e):
                        if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                            continue
                        if isinstance(c, ast.Call):
                            target = resolve(c, fn)
                            if target is not None:
                                callees[fn].add(target)
                                if held:
                                    deferred.append((set(held), target))
                for f in ("body", "orelse", "finalbody"):
                    do(getattr(s, f, []) or [], held)
                for h in getattr(s, "handlers", []) or []:
                    do(h.body, held)

        do(fn.body, ())

    # transitive acquisitions, then the call-under-lock edges
    changed = True
    while changed:
        changed = False
        for fn in acquires:
            for cal in callees[fn]:
                extra = acquires.get(cal, set()) - acquires[fn]
                if extra:
                    acquires[fn] |= extra
                    changed = True
    for held, target in deferred:
        for h in held:
            for lock in acquires.get(target, set()):
                if h != lock:
                    edges.setdefault((h, lock), 0)

    # cycle detection (iterative DFS over the small lock graph)
    graph: dict[str, set[str]] = {}
    for (a, b), _line in edges.items():
        if a == b:
            continue
        graph.setdefault(a, set()).add(b)
    out: list[Finding] = []
    seen_cycles: set[frozenset] = set()
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    key = frozenset(path)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        cyc = "->".join(path + [start])
                        line = min(
                            (edges.get((a, b), 1) or 1)
                            for a, b in zip(path, path[1:] + [start])
                        )
                        out.append(
                            Finding(
                                mod.path, "KB505", max(line, 1),
                                f"lock-order cycle {cyc}: ABBA deadlock "
                                "under the wrong interleaving",
                                f"cycle:{'->'.join(sorted(set(path)))}",
                            )
                        )
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))
    return out


# ---------------------------------------------------------------------------
# KB506 — unbounded queues


@rule(
    "KB506",
    "unbounded Queue/deque in serve scope",
    """
`queue.Queue()` / `asyncio.Queue()` / `collections.deque()` constructed
without `maxsize=`/`maxlen=` in the serve plane. An unbounded queue is
admission control with the sign flipped: under overload it converts
backpressure into unbounded host memory growth and silently unbounded
latency (the PR-12 admission-control design exists precisely to bound
these). `queue.SimpleQueue` cannot be bounded at all — use `queue.Queue`.
Give it a bound, or `# noqa: KB506` with the invariant that bounds it
externally (e.g. a drain-every-round contract against a bounded feeder).
""",
)
def check_unbounded_queue(mod: Module) -> list[Finding]:
    if not _in_scope(mod):
        return []
    out: list[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        d = mod.dotted(node.func)
        if d not in _UNBOUNDED:
            continue
        bound_kw = _UNBOUNDED[d]
        bounded = bound_kw is not None and (
            any(kw.arg == bound_kw for kw in node.keywords)
            or (d == "collections.deque" and len(node.args) >= 2)
            or (d != "collections.deque" and len(node.args) >= 1)
        )
        if not bounded:
            ctor = d.rsplit(".", 1)[-1]
            out.append(
                Finding(
                    mod.path, "KB506", node.lineno,
                    f"unbounded {d}() in serve scope — overload turns into "
                    "host memory growth, not backpressure",
                    f"{ctor}",
                )
            )
    return out
