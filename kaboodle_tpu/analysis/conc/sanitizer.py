"""Runtime concurrency sanitizer: dynamic lock-order graph + loop watchdog.

The static KB5xx rules (rules.py) prove properties of the code the AST can
see; this module asserts the same two invariants on the EXECUTION the
chaos harness and the serve test suites actually drive:

- **Lock order**: :func:`make_lock` hands out :class:`SanitizedLock`
  wrappers (plain ``threading.Lock`` when the sanitizer is disabled —
  zero overhead in production). Every acquisition records edges
  ``held -> acquired`` into one process-wide order graph, and the edge
  that would close a cycle raises :class:`LockOrderError` *immediately*,
  on whichever thread adds it — a deterministic report of the ABBA that
  deadlocks only under the lost interleaving. The pre-acquire check means
  a single thread exercising both orders is enough to trip it: no race
  required, so the chaos scenarios double as deadlock regression tests.
- **Event loop**: while enabled, ``asyncio.events.Handle._run`` is timed.
  A callback's synchronous segment exceeding the slow-callback threshold
  is recorded as a violation (the serve plane's p99 is exactly the longest
  such segment). Known-budgeted stalls — warmup compiles, recovery replay
  — wrap themselves in :func:`budgeted` and are excused, mirroring the
  compiles_steady contract (budgeted at warmup, gated at steady state).

stdlib only (threading/asyncio/contextlib): importable from the serve
engine itself without dragging the analysis plane's rule registry in.

Env hook: ``KABOODLE_CONC_SANITIZE=1`` enables at import (threshold from
``KABOODLE_CONC_LOOP_THRESHOLD_MS``, default 500), so any dryrun can run
sanitized for overhead measurement without code changes (PERF.md banks
the serve-obs number).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

__all__ = [
    "LockOrderError", "SanitizedLock", "make_lock", "enable", "disable",
    "enabled", "is_enabled", "budgeted", "budget_current_callback",
    "lock_graph", "loop_violations",
    "reset", "report", "assert_clean",
]


class LockOrderError(RuntimeError):
    """Acquiring this lock here closes a cycle in the lock-order graph."""


_state = threading.Lock()  # guards _edges/_violations; never wrapped itself
_enabled = False
_edges: dict[str, set[str]] = {}
_violations: list[tuple[str, float]] = []
_threshold_s = 0.5
_tls = threading.local()
_orig_handle_run = None
_budget_flag = False  # set by budgeted(), cleared at each callback entry


def _held() -> list[str]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _path(src: str, dst: str) -> list[str] | None:
    """A path src ~> dst in the recorded graph, else None (caller holds
    ``_state``)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


class SanitizedLock:
    """A ``threading.Lock`` that records acquisition-order edges.

    Non-reentrant, like the lock it wraps; re-acquiring on the same
    thread raises :class:`LockOrderError` instead of deadlocking.
    """

    __slots__ = ("name", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held()
        if self.name in held:
            raise LockOrderError(
                f"re-acquiring non-reentrant lock {self.name!r} on the same "
                f"thread (held: {held})"
            )
        # Record BEFORE blocking: the ordering violation is the bug even
        # when the actual deadlock interleaving doesn't happen this run.
        with _state:
            for h in held:
                back = _path(self.name, h)
                if back is not None:
                    raise LockOrderError(
                        f"lock-order cycle: acquiring {self.name!r} while "
                        f"holding {h!r}, but the recorded order already has "
                        f"{'->'.join(back)}->{h!r} — ABBA deadlock"
                    )
                _edges.setdefault(h, set()).add(self.name)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            held.append(self.name)
        return ok

    def release(self) -> None:
        self._lock.release()
        held = _held()
        if self.name in held:
            # remove the most recent acquisition of this name
            for i in range(len(held) - 1, -1, -1):
                if held[i] == self.name:
                    del held[i]
                    break

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


def make_lock(name: str):
    """A lock for ``name`` (e.g. ``"SpillManager._lock"``): sanitized when
    the sanitizer is enabled, a plain ``threading.Lock`` otherwise."""
    return SanitizedLock(name) if _enabled else threading.Lock()


# ---------------------------------------------------------------------------
# event-loop watchdog


def _install_loop_monitor() -> None:
    global _orig_handle_run
    if _orig_handle_run is not None:
        return
    import asyncio.events

    _orig_handle_run = asyncio.events.Handle._run

    def _timed_run(handle):
        global _budget_flag
        _budget_flag = False
        t0 = time.perf_counter()
        try:
            return _orig_handle_run(handle)
        finally:
            dt = time.perf_counter() - t0
            if dt > _threshold_s and not _budget_flag:
                cb = repr(getattr(handle, "_callback", handle))[:120]
                with _state:
                    _violations.append((cb, dt))

    asyncio.events.Handle._run = _timed_run


def _uninstall_loop_monitor() -> None:
    global _orig_handle_run
    if _orig_handle_run is None:
        return
    import asyncio.events

    asyncio.events.Handle._run = _orig_handle_run
    _orig_handle_run = None


def budget_current_callback() -> None:
    """Excuse the CURRENT event-loop callback from the slow-callback gate
    (warmup compiles, recovery replay: blocking that is part of the
    budgeted startup contract, not a steady-state stall). The flag is
    cleared at the next callback's entry, so the excuse never outlives
    the callback that earned it."""
    global _budget_flag
    _budget_flag = True


@contextlib.contextmanager
def budgeted():
    """Context-manager spelling of :func:`budget_current_callback`."""
    try:
        yield
    finally:
        budget_current_callback()


# ---------------------------------------------------------------------------
# lifecycle


def enable(loop_threshold_s: float = 0.5) -> None:
    global _enabled, _threshold_s
    _threshold_s = float(loop_threshold_s)
    _enabled = True
    _install_loop_monitor()


def disable() -> None:
    global _enabled
    _enabled = False
    _uninstall_loop_monitor()


def is_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def enabled(loop_threshold_s: float = 0.5, fresh: bool = True):
    """Enable for a scope (chaos harness, a test module), disabling on
    exit. ``fresh`` resets the recorded graph/violations on entry so the
    scope asserts ITS execution, not history."""
    if fresh:
        reset()
    enable(loop_threshold_s)
    try:
        yield
    finally:
        disable()


def reset() -> None:
    with _state:
        _edges.clear()
        _violations.clear()


def lock_graph() -> dict[str, list[str]]:
    with _state:
        return {a: sorted(bs) for a, bs in _edges.items()}


def loop_violations() -> list[tuple[str, float]]:
    with _state:
        return list(_violations)


def report() -> dict:
    """JSON-able summary for dryrun reports."""
    g = lock_graph()
    return {
        "locks": sorted({a for a in g} | {b for bs in g.values() for b in bs}),
        "order_edges": sum(len(bs) for bs in g.values()),
        "loop_violations": [
            {"callback": cb, "blocked_s": round(dt, 4)}
            for cb, dt in loop_violations()
        ],
    }


def assert_clean() -> None:
    """Raise if the watched execution blocked the loop. (Lock-order cycles
    raise at the acquisition itself; this is the end-of-run gate for the
    watchdog half.)"""
    v = loop_violations()
    if v:
        lines = ", ".join(f"{cb} blocked {dt * 1e3:.0f}ms" for cb, dt in v[:5])
        raise AssertionError(
            f"event loop blocked past {_threshold_s * 1e3:.0f}ms slow-callback "
            f"threshold {len(v)}x: {lines}"
        )


if os.environ.get("KABOODLE_CONC_SANITIZE") == "1":
    enable(float(os.environ.get("KABOODLE_CONC_LOOP_THRESHOLD_MS", "500")) / 1e3)
