"""graftlint core: findings, the rule registry, noqa + baseline plumbing.

Dependency-free by construction (``ast`` + stdlib only): the build image
ships no ruff/flake8/pyflakes and installs are not allowed, so every check
here is a hand-rolled AST walk. The analyzer never imports jax — rules that
reason about jit/tracing do so purely syntactically (see ``reach.py``), which
keeps ``make lint`` at AST-parse speed and lets the analysis tests run
without touching a backend.

Vocabulary:

- A **rule** has a stable id (``KB1xx`` generic, ``KB2xx`` jax-tracer,
  ``KB3xx`` hot-path, ``KB5xx`` concurrency — the graftconc lane, run via
  ``--conc`` against its own ``.graftconc_baseline.json``), a one-line
  title, and an ``--explain`` text that says what it catches, why it
  matters on this codebase, and how to suppress it.
- A **finding** is one diagnostic. Its ``key`` (path :: rule :: symbol —
  deliberately *no line number*, so baselines survive unrelated edits)
  is what the baseline file matches against.
- ``# noqa`` on the offending line suppresses findings there: bare ``noqa``
  (or a foreign-linter code list like ``# noqa: E731`` — compat with the
  pre-graftlint convention) suppresses everything on the line, while
  ``# noqa: KB203`` suppresses only the named rules.
- The **baseline** (``.graftlint_baseline.json``) holds pre-existing,
  justified findings so CI gates on *new* debt only; every entry must carry
  a non-empty ``reason``. ``--no-baseline-growth`` additionally fails on
  stale entries, so the file can only ever shrink.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
import re
from typing import Callable, Iterable

# ---------------------------------------------------------------------------
# findings + rules


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic. ``symbol`` is a stable context (enclosing function
    qualname plus the offending name), so ``key`` survives line shifts."""

    path: str
    rule: str
    line: int
    message: str
    symbol: str

    @property
    def key(self) -> str:
        return f"{self.path}::{self.rule}::{self.symbol}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str  # noqa: KB104 — dataclass field, not a scope binding that can leak
    title: str
    explain: str
    check: Callable[["Module"], list[Finding]]


REGISTRY: dict[str, Rule] = {}


def rule(rule_id: str, title: str, explain: str):
    """Register ``check(module) -> [Finding]`` under a stable rule id."""

    def deco(fn):
        REGISTRY[rule_id] = Rule(rule_id, title, explain.strip(), fn)
        return fn

    return deco


# ---------------------------------------------------------------------------
# per-file model

_NOQA_RE = re.compile(r"#\s*noqa(?:\s*:\s*(?P<codes>[A-Za-z0-9_,\s]+))?", re.I)
_ALL = frozenset({"*"})


def noqa_codes(line: str) -> frozenset[str]:
    """Rule ids suppressed by a source line; {'*'} means everything.

    Foreign-linter code lists (``# noqa: E402``) suppress everything too:
    the pre-graftlint checker treated any ``noqa`` substring as a blanket
    waiver and the repo's existing annotations rely on that.
    """
    m = _NOQA_RE.search(line)
    if not m:
        return frozenset()
    codes = m.group("codes")
    if not codes:
        return _ALL
    kb = frozenset(c.strip().upper() for c in codes.split(",") if c.strip().upper().startswith("KB"))
    return kb or _ALL


class Module:
    """Parsed source handed to every rule, with shared per-file analyses.

    ``path`` is kept verbatim (repo-relative in normal runs) — rules use it
    for scoping (KB3xx) and findings/baseline keys embed it.
    """

    def __init__(self, path: str, source: str):
        self.path = path.replace("\\", "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, path)
        self.import_aliases = self._collect_import_aliases(self.tree)
        self._reach = None

    # -- imports ------------------------------------------------------------

    @staticmethod
    def _collect_import_aliases(tree: ast.AST) -> dict[str, str]:
        """local name -> dotted module/object path, for every import."""
        aliases: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    if a.name != "*":
                        aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def dotted(self, node: ast.AST) -> str | None:
        """Dotted path of a Name/Attribute chain with the leading alias
        resolved through the imports: ``jnp.full`` -> ``jax.numpy.full``."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.import_aliases.get(node.id, node.id)
        parts.append(base)
        return ".".join(reversed(parts))

    # -- reachability (lazy; see reach.py) ----------------------------------

    @property
    def reach(self):
        if self._reach is None:
            from kaboodle_tpu.analysis import reach

            self._reach = reach.ReachInfo(self)
        return self._reach

    # -- noqa ---------------------------------------------------------------

    def suppressed(self, rule_id: str, lineno: int) -> bool:
        if not (0 < lineno <= len(self.lines)):
            return False
        codes = noqa_codes(self.lines[lineno - 1])
        return "*" in codes or rule_id in codes


# ---------------------------------------------------------------------------
# running


def analyze_module(mod: Module, rules: Iterable[Rule] | None = None) -> list[Finding]:
    """All non-noqa'd findings for one parsed module, sorted by position."""
    out: list[Finding] = []
    for r in rules if rules is not None else REGISTRY.values():
        for f in r.check(mod):
            if not mod.suppressed(f.rule, f.line):
                out.append(f)
    return sorted(out, key=lambda f: (f.line, f.rule, f.symbol))


def analyze_source(source: str, path: str = "module.py") -> list[Finding]:
    """Convenience for tests/fixtures: findings for a source string.

    ``path`` participates in rule scoping (KB3xx) exactly as a real file
    path would, so fixtures can opt snippets into the hot-path rules.
    """
    _load_rules()
    return analyze_module(Module(path, source))


def analyze_path(
    path: pathlib.Path,
    display: str | None = None,
    rules: Iterable[Rule] | None = None,
) -> list[Finding]:
    """Findings for one file; unparseable source yields a single KB100.

    ``rules`` restricts the pass to one lane's rule set (the CLI's AST
    lane excludes KB5xx; the --conc lane runs only KB5xx)."""
    _load_rules()
    name = display if display is not None else str(path)
    try:
        mod = Module(name, path.read_text())
    except SyntaxError as e:
        return [Finding(name, "KB100", e.lineno or 1, f"syntax error: {e.msg}", "<syntax>")]
    return analyze_module(mod, rules)


def iter_python_files(targets: list[str]) -> list[pathlib.Path]:
    files: list[pathlib.Path] = []
    for t in targets:
        p = pathlib.Path(t)
        files.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
    return files


def _load_rules() -> None:
    """Import the rule modules (idempotent) so REGISTRY is populated.

    rules_ir registers only the KB4xx documentation (no-op AST checks);
    the passes themselves live in analysis/ir/ behind the --ir lane.
    conc.rules (KB5xx) register here too — --list-rules/--explain cover
    every family — but the CLI runs them only in the --conc lane.
    rules_rng (KB6xx) is the same shape as rules_ir: documentation only,
    the provenance checks live in analysis/rng/ behind --rng."""
    from kaboodle_tpu.analysis import (  # noqa: F401
        rules_generic,
        rules_hotpath,
        rules_ir,
        rules_jax,
        rules_rng,
    )
    from kaboodle_tpu.analysis.conc import rules as rules_conc  # noqa: F401


# ---------------------------------------------------------------------------
# baseline

DEFAULT_BASELINE = ".graftlint_baseline.json"


class BaselineError(ValueError):
    """Malformed baseline file (bad JSON, missing key/reason)."""


def load_baseline(path: pathlib.Path) -> dict[str, str]:
    """key -> reason. A missing file is an empty baseline; a malformed one
    (or any entry without a non-empty justification) is a hard error —
    the justification is the point of the file."""
    if not path.exists():
        return {}
    try:
        data = json.loads(path.read_text())
    except json.JSONDecodeError as e:
        raise BaselineError(f"{path}: invalid JSON: {e}") from e
    entries = data.get("entries") if isinstance(data, dict) else None
    if not isinstance(entries, list):
        raise BaselineError(f"{path}: expected an object with an 'entries' list")
    out: dict[str, str] = {}
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or not e.get("key") or not str(e.get("reason", "")).strip():
            raise BaselineError(
                f"{path}: entries[{i}] needs a 'key' and a non-empty 'reason' justification"
            )
        out[str(e["key"])] = str(e["reason"])
    return out


def write_baseline(path: pathlib.Path, findings: list[Finding], old: dict[str, str]) -> None:
    """Regenerate the baseline from current findings, keeping old reasons."""
    keys = sorted({f.key for f in findings})
    payload = {
        "comment": (
            "graftlint baseline: pre-existing, justified findings. Entries may "
            "only be removed (fix the finding, delete the entry); CI's "
            "--no-baseline-growth step fails on stale or new entries."
        ),
        "entries": [
            {"key": k, "reason": old.get(k, "TODO: justify this exemption")} for k in keys
        ],
    }
    path.write_text(json.dumps(payload, indent=2) + "\n")
