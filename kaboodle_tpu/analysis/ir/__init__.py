"""graftscan — the jaxpr/IR-level kernel auditor (rules KB401-KB405).

graftlint (the KB1xx-KB3xx AST lane) guards the source text; this package
audits the **traced programs**: every registered kernel entry point
(``registry.py`` — dense/chunked tick, warp leap, fleet tick, fused ops,
sharded twins) is traced to a ``ClosedJaxpr`` and swept by the pass
pipeline (``passes.py``):

- **KB401** dtype widening: f64 anywhere (traced under ``enable_x64`` so
  implicit defaults become visible), int16 lean-state promotions outside
  the age-arithmetic allowlist;
- **KB402** host boundaries (callbacks/infeed) inside jitted kernels;
- **KB403** oversized closure constants baked into programs;
- **KB404** GSPMD sharding constraints not derived from
  ``parallel.state_specs``;
- **KB405** the compile-surface budget (``surface.py``): fresh XLA
  compilations across a scripted dense+warp+fleet exercise vs the
  committed ``.graftscan_surface.json``, shrink-only like the lint
  baseline.

CLI: ``python -m kaboodle_tpu.analysis --ir [--no-baseline-growth]``, with
``--explain KB4nn`` served by the shared rule registry (rules_ir.py). This
package imports jax; the parent package's default AST lane does not.
"""

from kaboodle_tpu.analysis.ir.registry import ENTRY_POINTS, EntryPoint, trace_entry
from kaboodle_tpu.analysis.ir.scan import ScanResult, run_scan, scan_entry

__all__ = [
    "ENTRY_POINTS",
    "EntryPoint",
    "ScanResult",
    "run_scan",
    "scan_entry",
    "trace_entry",
]
