"""KB401-KB404: the per-jaxpr graftscan passes.

Each pass is ``check(entry, closed_jaxpr) -> [Finding]`` over one traced
entry point (see ``registry.py`` for which trace — x32 or x64 — each pass
reads). Findings reuse the AST lane's :class:`~kaboodle_tpu.analysis.core.
Finding` so the baseline/CLI plumbing is shared verbatim:

- ``path`` is the pseudo-path ``ir://<entry name>`` (entry points, not
  files, are the unit of scanning);
- ``line`` is the *source* line of the nearest user frame when attribution
  succeeds (clickable in terminals), 0 otherwise;
- ``symbol`` — the baseline key component — is line-free and built from
  (source file, primitive, dtype/spec), so one justified entry covers a
  finding class and survives unrelated edits, exactly like KB302's
  one-entry-per-constructor design.
"""

from __future__ import annotations

from kaboodle_tpu.analysis.core import Finding
from kaboodle_tpu.analysis.ir.registry import EntryPoint
from kaboodle_tpu.analysis.ir.walk import (
    aval_nbytes,
    eqn_avals,
    iter_eqns,
    iter_jaxprs,
    source_of,
)

# -- KB401 ------------------------------------------------------------------

# 64-bit float dtypes only: the rule is "any f64 anywhere" (ISSUE), traced
# under enable_x64 so implicit defaults become visible. int64 is NOT swept —
# jax.random's key plumbing and iota bookkeeping legitimately widen index
# scalars under x64, and the int16 discipline has its own detector below.
_WIDE_FLOATS = frozenset({"float64", "complex128"})

# The lean int16 allowlisted accumulation set: a widened timer may feed age
# arithmetic (`t - T` computes in int32 by design — kernel.py's documented
# contract) and comparisons, and nothing else. A widened value reaching a
# write (`select_n`), a scatter, a reduction carry, or escaping the scope
# is a resident-doubling leak.
_ALLOWED_WIDEN_CONSUMERS = frozenset(
    {"sub", "add", "lt", "le", "gt", "ge", "eq", "ne"}
)

_INT16_WIDENED = frozenset({"int32", "int64", "float32", "float64"})


def check_kb401_wide_floats(entry: EntryPoint, closed_jaxpr) -> list[Finding]:
    """Non-scalar f64/c128 values in the x64 trace -> findings.

    Scalars are exempt: a weak Python-float constant folding through a
    ``where`` is trace noise, while an f64 tensor is a doubled resident."""
    out: dict[str, Finding] = {}
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        for aval in eqn_avals(eqn):
            dtype = str(getattr(aval, "dtype", ""))
            if dtype not in _WIDE_FLOATS or not getattr(aval, "shape", ()):
                continue
            src = source_of(eqn)
            symbol = f"{src.file}:{eqn.primitive.name}:{dtype}"
            if symbol not in out:
                out[symbol] = Finding(
                    f"ir://{entry.name}",
                    "KB401",
                    src.line,
                    f"{dtype} {list(aval.shape)} reaches '{eqn.primitive.name}' "
                    f"({src.render()}) under x64 — spell the dtype "
                    "(dtype=jnp.float32 / f32-pinned constants)",
                    symbol,
                )
    return list(out.values())


def check_kb401_lean_widening(entry: EntryPoint, closed_jaxpr) -> list[Finding]:
    """int16 -> wider converts in a lean program, outside the allowlist.

    Runs on the x32 trace of entries flagged ``lean=True``. The consumer
    set of each widening convert is resolved through transparent ops and
    pjit bodies (walk.terminal_consumers); any consumer outside the
    age-arithmetic/comparison allowlist — including escaping the enclosing
    scope — fails."""
    if not entry.lean:
        return []
    from kaboodle_tpu.analysis.ir.walk import terminal_consumers

    out: dict[str, Finding] = {}
    for jaxpr in iter_jaxprs(closed_jaxpr.jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name != "convert_element_type":
                continue
            src_aval = getattr(eqn.invars[0], "aval", None)
            dst_aval = getattr(eqn.outvars[0], "aval", None)
            if src_aval is None or dst_aval is None:
                continue
            if str(src_aval.dtype) != "int16":
                continue
            if str(dst_aval.dtype) not in _INT16_WIDENED:
                continue
            consumers = terminal_consumers(jaxpr, eqn.outvars[0])
            bad = consumers - _ALLOWED_WIDEN_CONSUMERS
            if not bad:
                continue
            src = source_of(eqn)
            symbol = f"{src.file}:int16->{dst_aval.dtype}:{'|'.join(sorted(bad))}"
            if symbol not in out:
                out[symbol] = Finding(
                    f"ir://{entry.name}",
                    "KB401",
                    src.line,
                    f"int16 state widened to {dst_aval.dtype} ({src.render()}) "
                    f"flows into {sorted(bad)} — outside the age-arithmetic "
                    "allowlist; the lean-mode timer resident must stay int16",
                    symbol,
                )
    return list(out.values())


# -- KB402 ------------------------------------------------------------------

_HOST_PRIMS = frozenset(
    {
        "io_callback",
        "pure_callback",
        "debug_callback",
        "callback",
        "outside_call",
        "host_callback_call",
        "infeed",
        "outfeed",
    }
)


def check_kb402_host_boundary(entry: EntryPoint, closed_jaxpr) -> list[Finding]:
    """Host-callback-shaped primitives anywhere in the traced program."""
    out: dict[str, Finding] = {}
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        name = eqn.primitive.name
        if name not in _HOST_PRIMS:
            continue
        src = source_of(eqn)
        symbol = f"{src.file}:{name}"
        if symbol not in out:
            out[symbol] = Finding(
                f"ir://{entry.name}",
                "KB402",
                src.line,
                f"host boundary '{name}' ({src.render()}) inside a jitted "
                "kernel program — one device->host round trip per dispatch",
                symbol,
            )
    return list(out.values())


# -- KB403 ------------------------------------------------------------------


def check_kb403_captured_consts(entry: EntryPoint, closed_jaxpr) -> list[Finding]:
    """Closure consts above the entry's byte budget baked into the program."""
    out: list[Finding] = []
    consts = getattr(closed_jaxpr, "consts", ()) or ()
    for i, const in enumerate(consts):
        aval = getattr(const, "aval", None)
        if aval is None:
            try:
                import numpy as np

                arr = np.asarray(const)
                nbytes, shape, dtype = arr.nbytes, arr.shape, arr.dtype
            except Exception:
                continue
        else:
            nbytes, shape, dtype = aval_nbytes(aval), aval.shape, aval.dtype
        if nbytes <= entry.const_budget_bytes:
            continue
        symbol = f"const:{dtype}{list(shape)}"
        out.append(
            Finding(
                f"ir://{entry.name}",
                "KB403",
                0,
                f"captured constant {dtype}{list(shape)} ({nbytes} bytes > "
                f"budget {entry.const_budget_bytes}) baked into the program "
                "— pass it as an argument instead",
                symbol,
            )
        )
    return out


# -- KB404 ------------------------------------------------------------------


def _normalize_spec(spec) -> tuple:
    """PartitionSpec -> trailing-None-stripped tuple (rank-independent)."""
    parts = tuple(spec)
    while parts and parts[-1] is None:
        parts = parts[:-1]
    return parts


def allowed_sharding_specs() -> frozenset:
    """Every spec derivable from ``parallel.state_specs`` (the single
    source of truth): the peer-layer specs, their fleet-stacked 1-D and
    2-D E x peers derivations, and the fleet inputs/knob specs."""
    import jax
    from jax.sharding import PartitionSpec as P

    from kaboodle_tpu.fleet.sharding import fleet_state_specs
    from kaboodle_tpu.parallel.mesh import inputs_specs, state_specs

    is_p = lambda x: isinstance(x, P)  # noqa: E731
    specs = set()
    for tree in (
        state_specs(None),
        inputs_specs(),
        fleet_state_specs(None, peers_sharded=False),
        fleet_state_specs(None, peers_sharded=True),
    ):
        for s in jax.tree.leaves(tree, is_leaf=is_p):
            if is_p(s):
                specs.add(_normalize_spec(s))
    return frozenset(specs)


def check_kb404_sharding_specs(entry: EntryPoint, closed_jaxpr) -> list[Finding]:
    """Sharding constraints in sharded programs must derive from state_specs.

    Flags (a) any ``sharding_constraint`` whose PartitionSpec is not in the
    derivable set (hand-rolled layout), and (b) a sharded entry whose
    program carries NO constraints at all (the carry placement is unpinned
    and drifts wherever XLA's cost model wanders)."""
    if not entry.sharded:
        return []
    allowed = allowed_sharding_specs()
    out: dict[str, Finding] = {}
    n_constraints = 0
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name != "sharding_constraint":
            continue
        n_constraints += 1
        sharding = eqn.params.get("sharding")
        spec = getattr(sharding, "spec", None)
        if spec is None:  # GSPMD/opaque sharding: cannot verify derivation
            norm = ("<opaque>",)
        else:
            norm = _normalize_spec(spec)
        if spec is not None and norm in allowed:
            continue
        src = source_of(eqn)
        symbol = f"{src.file}:spec:{norm}"
        if symbol not in out:
            out[symbol] = Finding(
                f"ir://{entry.name}",
                "KB404",
                src.line,
                f"sharding constraint {spec!r} ({src.render()}) is not "
                "derivable from parallel.state_specs — hand-rolled layouts "
                "force per-tick resharding collectives",
                symbol,
            )
    if n_constraints == 0:
        out["<missing>"] = Finding(
            f"ir://{entry.name}",
            "KB404",
            0,
            "sharded entry point compiles with NO sharding constraints — "
            "the scan-carry placement is unpinned (constrain_state missing?)",
            "missing-constraints",
        )
    return list(out.values())


# -- pass pipeline ----------------------------------------------------------

# (pass fn, which trace it reads). KB401's f64 sweep wants the x64 trace;
# everything else audits the production-mode x32 program.
PASSES_X32 = (
    check_kb401_lean_widening,
    check_kb402_host_boundary,
    check_kb403_captured_consts,
    check_kb404_sharding_specs,
)
PASSES_X64 = (check_kb401_wide_floats,)
