"""The graftscan entry-point registry: every traced kernel the gate audits.

One :class:`EntryPoint` per compiled-program family the simulator actually
dispatches in production. Since the phase-graph refactor (ISSUE 7) every
tick/leap family is a **derivation of the one op graph**
(``phasegraph/derive.py``), and the registry names say which derivation:
``phasegraph.tick.*`` (the dense full+fused dispatch and its fused /
lean-int16 / random / blocked / telemetry / fleet / sharded builds) and
``phasegraph.leap.*`` (the quiescent-span program). The old
``sim.tick.*`` / ``warp.leap`` / ``fleet.tick`` entries — one registry row
per hand-specialized protocol copy — are retired with those copies; the
``ops.*`` primitives and the flight-recorder scan body keep their names
(they are not graph derivations). Each entry knows how to build
``(fn, example_args)``
at **toy trace scale** — tracing is abstract evaluation, so N=32 exercises
the identical program structure the production N=65,536 program has, at
AST-adjacent cost.

``build()`` is called inside the tracing context (per pass: default x32 or
``enable_x64``), so example states are constructed under the flag being
audited; everything dtype-pinned stays pinned, and only implicit defaults
drift — which is exactly what KB401 measures.

Entries are deliberately *data*: tests register synthetic/mutated entries
through the same type to prove the passes catch seeded regressions, and
``--entries a,b`` filters by name for fast local iteration.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

TRACE_N = 32  # toy trace scale; program structure is N-independent
TRACE_E = 4  # fleet ensemble width at trace scale
LEAP_K = 8  # leap span length traced (one representative power of two)


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    """One traced kernel entry point.

    ``build()`` returns ``(fn, example_args)`` for ``jax.make_jaxpr``.
    ``lean`` opts the program into the KB401 int16-widening pass;
    ``sharded`` opts it into KB404. ``const_budget_bytes`` is the KB403
    threshold — sized to catch an [N, N]-at-trace-scale capture (int8
    state at N=32 is 1 KiB; the crc32 table, the one legitimate big-ish
    table, is 1 KiB and rides under the default with headroom to spare
    only via its explicit override)."""

    name: str
    build: Callable[[], tuple[Callable, tuple]]
    lean: bool = False
    sharded: bool = False
    const_budget_bytes: int = 4096


def _cfg(**kw):
    from kaboodle_tpu.config import SwimConfig

    return SwimConfig(deterministic=True, **kw)


def _full_state(n: int = TRACE_N):
    from kaboodle_tpu.sim.state import init_state

    return init_state(n, seed=0)


def _lean_state(n: int = TRACE_N, converged: bool = False):
    import jax.numpy as jnp

    from kaboodle_tpu.sim.state import init_state

    return init_state(
        n,
        seed=0,
        timer_dtype=jnp.int16,
        track_latency=False,
        instant_identity=True,
        ring_contacts=n - 1 if converged else 0,
        announced=converged,
    )


def _converged_state(n: int = TRACE_N):
    from kaboodle_tpu.sim.state import init_state

    return init_state(n, seed=0, ring_contacts=n - 1, announced=True)


def _idle(n: int = TRACE_N):
    from kaboodle_tpu.sim.state import idle_inputs

    return idle_inputs(n)


# -- builders ---------------------------------------------------------------
# Every tick/leap builder goes through kaboodle_tpu.phasegraph.derive — the
# one place compiled-program families are assembled from the op graph. The
# audited program IS the dispatched production program (sim/kernel.py etc.
# are import shims over the same derivations).


def _tick_faulty():
    from kaboodle_tpu.phasegraph.derive import make_dense_tick

    return make_dense_tick(_cfg(), faulty=True), (_full_state(), _idle())


def _tick_faultfree():
    from kaboodle_tpu.phasegraph.derive import make_dense_tick

    return make_dense_tick(_cfg(), faulty=False), (_full_state(), _idle())


def _tick_fused():
    # The standalone 2-pass fused program (no dispatch guard) — the same
    # faulty build bench's --fastpath-ab A/Bs. Auditing it in isolation
    # pins its pass structure: KB401-404 see exactly the (faulty)
    # prologue + draw + update passes, with no full-program branch to
    # hide behind.
    from kaboodle_tpu.phasegraph.derive import make_fused_tick

    return make_fused_tick(_cfg(), faulty=True), (_full_state(), _idle())


def _tick_lean():
    from kaboodle_tpu.phasegraph.derive import make_dense_tick

    return make_dense_tick(_cfg(), faulty=False), (_lean_state(), _idle())


def _tick_random():
    # deterministic=False exercises the real sampling draws (gumbel /
    # bernoulli / uniform) — where dtype-less defaults hide. Since Warp
    # 3.0 this entry IS the counter-keyed variant: every draw derives a
    # per-(row, tick, stream) key via phasegraph.rng.tick_draw_keys, so
    # keyscope's provenance walk over this trace (and tick.sparse's, the
    # (seed, cursor, stream) twin) is what banks the counter_keyed sinks
    # in KEYSCOPE_LEAP.json — no extra registry entry needed, the
    # migration changed the programs these entries already trace.
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.phasegraph.derive import make_dense_tick

    cfg = SwimConfig(deterministic=False)
    return make_dense_tick(cfg, faulty=True), (_full_state(), _idle())


def _tick_blocked():
    from kaboodle_tpu.phasegraph.derive import make_chunked_tick

    fn = make_chunked_tick(_cfg(), faulty=True, block=TRACE_N // 2)
    return fn, (_full_state(), _idle())


def _sparse_build(timer_dtype: str):
    # The blocked_topk steady tick (ISSUE 18): [N, K] neighbor blocks +
    # counter-based draws. deterministic=False exercises the real
    # stream_uniform draws; the cfg must be sparse-legal
    # (join broadcast off, faithful Q3/Q11 on — kernel._validate).
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.phasegraph.derive import make_sparse_tick
    from kaboodle_tpu.sparseplane import (
        SparseSpec,
        init_sparse_state,
        sparse_idle_inputs,
    )

    cfg = SwimConfig(deterministic=False, join_broadcast_enabled=False)
    spec = SparseSpec(k=8, gossip_fanout=2, boot_contacts=2,
                      timer_dtype=timer_dtype)
    fn = make_sparse_tick(cfg, spec, faulty=True)
    return fn, (init_sparse_state(TRACE_N, spec, seed=0),
                sparse_idle_inputs(TRACE_N))


def _tick_sparse():
    return _sparse_build("int32")


def _tick_sparse_lean():
    return _sparse_build("int16")


# -- telemetry-plane builds (ISSUE 6): the telemetry=True twins of the tick
# programs. Same pass pipeline as their plain counterparts — in particular
# KB402 proves the counter/recorder plane adds NO host callback, and the
# lean entry proves the int16 discipline survives the added reductions.


def _tick_telemetry():
    from kaboodle_tpu.phasegraph.derive import make_dense_tick

    return (
        make_dense_tick(_cfg(), faulty=True, telemetry=True),
        (_full_state(), _idle()),
    )


def _tick_telemetry_lean():
    from kaboodle_tpu.phasegraph.derive import make_dense_tick

    return (
        make_dense_tick(_cfg(), faulty=False, telemetry=True),
        (_lean_state(), _idle()),
    )


def _tick_blocked_telemetry():
    from kaboodle_tpu.phasegraph.derive import make_chunked_tick

    fn = make_chunked_tick(
        _cfg(), faulty=True, block=TRACE_N // 2, telemetry=True
    )
    return fn, (_full_state(), _idle())


def _tick_fleet_telemetry():
    from kaboodle_tpu.fleet.core import fleet_idle_inputs, init_fleet
    from kaboodle_tpu.phasegraph.derive import make_fleet_tick

    fleet = init_fleet(TRACE_N // 2, TRACE_E)
    inputs = fleet_idle_inputs(TRACE_N // 2, TRACE_E)
    return (
        make_fleet_tick(_cfg(), faulty=True, telemetry=True),
        (fleet.mesh, inputs),
    )


def _recorder_scan_telemetry():
    # The converged-run shape: telemetry tick + flight-recorder ring in ONE
    # while_loop body — the program run_until_converged_telemetry dispatches.
    from kaboodle_tpu.phasegraph.derive import make_dense_tick
    from kaboodle_tpu.telemetry.recorder import init_recorder, record_tick

    tick = make_dense_tick(_cfg(), faulty=False, telemetry=True)
    rec0 = init_recorder(8, TRACE_N)

    def tick_and_record(st, inp, rec):
        st, out = tick(st, inp)
        return st, record_tick(rec, st.tick - 1, out)

    return tick_and_record, (_full_state(), _idle(), rec0)


def _leap():
    from kaboodle_tpu.phasegraph.derive import make_warp_leap

    return make_warp_leap(_cfg(), LEAP_K), (_converged_state(),)


def _leap_lean():
    from kaboodle_tpu.phasegraph.derive import make_warp_leap

    return make_warp_leap(_cfg(), LEAP_K), (_lean_state(converged=True),)


def _leap_hybrid():
    # Warp 2.0: the near-quiescent span program (strict span + sterile
    # anti-entropy pass + kpr ledger carry). Tracing is abstract, so the
    # converged example state exercises the identical program structure a
    # mid-drain near-quiescent state would.
    from kaboodle_tpu.phasegraph.derive import make_warp_leap

    return make_warp_leap(_cfg(), LEAP_K, hybrid=True), (_converged_state(),)


def _leap_hybrid_lean():
    from kaboodle_tpu.phasegraph.derive import make_warp_leap

    return (
        make_warp_leap(_cfg(), LEAP_K, hybrid=True),
        (_lean_state(converged=True),),
    )


def _leap_fleet_masked():
    # The per-member fleet warp program: the masked hybrid leap (span
    # length a traced per-member k_m) vmapped over the ensemble axis —
    # exactly what run_fleet_warped dispatches per leap round.
    import jax
    import jax.numpy as jnp

    from kaboodle_tpu.fleet.core import init_fleet
    from kaboodle_tpu.phasegraph.derive import make_warp_leap

    n = TRACE_N // 2
    fleet = init_fleet(n, TRACE_E, ring_contacts=n - 1, announced=True)
    leap = jax.vmap(make_warp_leap(_cfg(), LEAP_K, hybrid=True, masked=True))
    k_m = jnp.full((TRACE_E,), LEAP_K // 2, dtype=jnp.int32)
    return leap, (fleet.mesh, k_m)


def _serve_lane_vectors():
    import jax.numpy as jnp

    return (
        jnp.ones((TRACE_E,), bool),  # active
        jnp.ones((TRACE_E,), bool),  # until_conv
        jnp.full((TRACE_E,), 16, jnp.int32),  # remaining
        jnp.zeros((TRACE_E,), jnp.int32),  # ticks_run
        jnp.full((TRACE_E,), -1, jnp.int32),  # conv_tick
    )


def _serve_step():
    # The serve pool's resident chunk program (phasegraph serve engine):
    # masked converge chunks with per-lane modes/budgets all traced — the
    # program the server dispatches every dense round, forever.
    from kaboodle_tpu.fleet.core import init_fleet
    from kaboodle_tpu.phasegraph.derive import make_serve_step

    fleet = init_fleet(TRACE_N // 2, TRACE_E)
    fn = make_serve_step(_cfg(), chunk=4)
    return fn, (fleet.mesh, fleet.drop_rate, *_serve_lane_vectors())


def _serve_step_telemetry():
    from kaboodle_tpu.fleet.core import init_fleet
    from kaboodle_tpu.phasegraph.derive import make_serve_step

    fleet = init_fleet(TRACE_N // 2, TRACE_E)
    fn = make_serve_step(_cfg(), chunk=4, telemetry=True)
    return fn, (fleet.mesh, fleet.drop_rate, *_serve_lane_vectors())


def _serve_reseed():
    # The on-device retire/re-seed scatter: init_state under trace (lane,
    # seed, knob all traced) + generation bump — the mid-flight admission
    # program. KB403 pins that the fresh member state is BUILT on device,
    # not captured as a host constant.
    import jax.numpy as jnp

    from kaboodle_tpu.fleet.core import init_fleet
    from kaboodle_tpu.serve.pool import make_reseed_fn

    n = TRACE_N // 2
    fleet = init_fleet(n, TRACE_E)
    generation = jnp.zeros((TRACE_E,), jnp.int32)
    fn = make_reseed_fn(n)
    return fn, (
        fleet.mesh, generation, fleet.drop_rate,
        jnp.int32(0), jnp.int32(0), jnp.float32(0.0),
    )


def _serve_step_sharded():
    # The ShardedLanePool's chunk program (ISSUE 17): the serve step with
    # its [E] lane carry pinned onto the fleet mesh every while_loop
    # iteration. KB404 audits the derived GSPMD specs — the lane axis must
    # split across the ensemble mesh axis, and the output placement must
    # match the input's (the sharded pool's zero-recompile leg).
    import jax.numpy as jnp

    from kaboodle_tpu.fleet.core import init_fleet
    from kaboodle_tpu.fleet.sharding import make_fleet_mesh
    from kaboodle_tpu.phasegraph.derive import make_sharded_serve_step

    mesh = make_fleet_mesh(len(_devices()))
    e = max(TRACE_E, len(_devices()))  # E must divide across the mesh
    fleet = init_fleet(TRACE_N // 2, e)
    fn = make_sharded_serve_step(_cfg(), 4, mesh)
    lanes = (
        jnp.ones((e,), bool),  # active
        jnp.ones((e,), bool),  # until_conv
        jnp.full((e,), 16, jnp.int32),  # remaining
        jnp.zeros((e,), jnp.int32),  # ticks_run
        jnp.full((e,), -1, jnp.int32),  # conv_tick
    )
    return fn, (fleet.mesh, fleet.drop_rate, *lanes)


def _serve_leap():
    # The serve engine's warped-lane variant: the SAME masked per-member
    # hybrid leap family as phasegraph.leap.fleet, registered under the
    # serve name because the serve round loop dispatches it directly
    # (horizon-mode lanes fast-forward; k_m == 0 lanes freeze).
    import jax
    import jax.numpy as jnp

    from kaboodle_tpu.fleet.core import init_fleet
    from kaboodle_tpu.phasegraph.derive import make_warp_leap

    n = TRACE_N // 2
    fleet = init_fleet(n, TRACE_E, ring_contacts=n - 1, announced=True)
    leap = jax.vmap(make_warp_leap(_cfg(), LEAP_K, hybrid=True, masked=True))
    k_m = jnp.array([LEAP_K, 0, LEAP_K // 2, 0], dtype=jnp.int32)
    return leap, (fleet.mesh, k_m)


def _tick_fleet():
    from kaboodle_tpu.fleet.core import fleet_idle_inputs, init_fleet
    from kaboodle_tpu.phasegraph.derive import make_fleet_tick

    fleet = init_fleet(TRACE_N // 2, TRACE_E)
    inputs = fleet_idle_inputs(TRACE_N // 2, TRACE_E)
    return make_fleet_tick(_cfg(), faulty=True), (fleet.mesh, inputs)


def _tick_sharded():
    from kaboodle_tpu.parallel.mesh import make_mesh
    from kaboodle_tpu.phasegraph.derive import make_sharded_tick

    mesh = make_mesh(len(_devices()))
    return make_sharded_tick(_cfg(), mesh, faulty=False), (_full_state(), _idle())


def _leap_sharded():
    import jax

    from kaboodle_tpu.parallel.mesh import (
        constrain_state,
        make_mesh,
        row_matrix_sharding,
    )
    from kaboodle_tpu.phasegraph.derive import make_warp_leap

    mesh = make_mesh(len(_devices()))
    sharding = row_matrix_sharding(mesh)
    leap = make_warp_leap(
        _cfg(), LEAP_K, constrain=lambda x: jax.lax.with_sharding_constraint(x, sharding)
    )

    def sharded_leap(st):
        return constrain_state(leap(st), mesh)

    return sharded_leap, (_converged_state(),)


def _tick_fleet_sharded():
    from kaboodle_tpu.fleet.core import fleet_idle_inputs, init_fleet
    from kaboodle_tpu.fleet.sharding import make_fleet_mesh, make_sharded_fleet_tick

    mesh = make_fleet_mesh(len(_devices()))
    e = max(TRACE_E, len(_devices()))  # E must divide across the mesh
    fleet = init_fleet(TRACE_N // 2, e)
    inputs = fleet_idle_inputs(TRACE_N // 2, e)
    return make_sharded_fleet_tick(_cfg(), mesh, faulty=True), (fleet.mesh, inputs)


def _ops_fused_fp():
    import jax.numpy as jnp

    from kaboodle_tpu.ops.fused_fp import fused_fp_count

    n = 128  # fused kernels require lane alignment (n % 128 == 0)
    return (
        lambda s, i: fused_fp_count(s, i),
        (jnp.zeros((n, n), jnp.int8), jnp.ones((n,), jnp.uint32)),
    )


def _ops_fused_oldest_k():
    import jax.numpy as jnp

    from kaboodle_tpu.ops.fused_oldest_k import fused_oldest_k

    n = 128
    return (
        lambda s, t, a: fused_oldest_k(s, t, a, 5),
        (
            jnp.zeros((n, n), jnp.int8),
            jnp.zeros((n, n), jnp.int32),
            jnp.ones((n,), bool),
        ),
    )


def _ops_fused_suspicion():
    import jax.numpy as jnp

    from kaboodle_tpu.ops.fused_suspicion import fused_suspicion

    n = 128
    return (
        lambda s, t, a, th: fused_suspicion(s, t, a, th),
        (
            jnp.zeros((n, n), jnp.int8),
            jnp.zeros((n, n), jnp.int32),
            jnp.ones((n,), bool),
            jnp.int32(0),
        ),
    )


def _ops_crc32():
    import jax.numpy as jnp

    from kaboodle_tpu.ops.crc32 import membership_crc32

    n = 16  # the byte scan is O(N) eqns; keep the trace small
    return (
        membership_crc32,
        (jnp.ones((n, n), bool), jnp.ones((n,), jnp.uint32)),
    )


def _devices():
    import jax

    return jax.devices()


ENTRY_POINTS: tuple[EntryPoint, ...] = (
    # phasegraph derivations (the retired sim.tick.* / warp.leap /
    # fleet.tick rows were the per-copy entries of the four deleted
    # hand-specialized kernels; phasegraph.tick.fused is NEW — the
    # standalone 2-pass program bench's --fastpath-ab dispatches).
    EntryPoint("phasegraph.tick.faulty", _tick_faulty),
    EntryPoint("phasegraph.tick.faultfree", _tick_faultfree),
    EntryPoint("phasegraph.tick.fused", _tick_fused),
    EntryPoint("phasegraph.tick.lean", _tick_lean, lean=True),
    EntryPoint("phasegraph.tick.random", _tick_random),
    EntryPoint("phasegraph.tick.blocked", _tick_blocked),
    EntryPoint("phasegraph.tick.sparse", _tick_sparse),
    EntryPoint("phasegraph.tick.sparse.lean", _tick_sparse_lean, lean=True),
    EntryPoint("phasegraph.tick.telemetry", _tick_telemetry),
    EntryPoint("phasegraph.tick.telemetry.lean", _tick_telemetry_lean, lean=True),
    EntryPoint("phasegraph.tick.blocked.telemetry", _tick_blocked_telemetry),
    EntryPoint("phasegraph.tick.fleet.telemetry", _tick_fleet_telemetry),
    EntryPoint("sim.recorder.telemetry", _recorder_scan_telemetry),
    EntryPoint("phasegraph.leap", _leap),
    EntryPoint("phasegraph.leap.lean", _leap_lean, lean=True),
    EntryPoint("phasegraph.leap.hybrid", _leap_hybrid),
    EntryPoint("phasegraph.leap.hybrid.lean", _leap_hybrid_lean, lean=True),
    EntryPoint("phasegraph.leap.fleet", _leap_fleet_masked),
    EntryPoint("phasegraph.tick.fleet", _tick_fleet),
    # serve (ISSUE 10): the resident service program set — the chunk
    # program (dense + telemetry), the admission re-seed scatter, and the
    # warped-lane leap dispatch.
    EntryPoint("phasegraph.serve.step", _serve_step),
    EntryPoint("phasegraph.serve.step.telemetry", _serve_step_telemetry),
    EntryPoint("phasegraph.serve.step.sharded", _serve_step_sharded, sharded=True),
    EntryPoint("serve.reseed", _serve_reseed),
    EntryPoint("serve.leap", _serve_leap),
    EntryPoint("phasegraph.tick.sharded", _tick_sharded, sharded=True),
    EntryPoint("phasegraph.leap.sharded", _leap_sharded, sharded=True),
    EntryPoint("phasegraph.tick.fleet.sharded", _tick_fleet_sharded, sharded=True),
    EntryPoint("ops.fused_fp", _ops_fused_fp),
    EntryPoint("ops.fused_oldest_k", _ops_fused_oldest_k),
    EntryPoint("ops.fused_suspicion", _ops_fused_suspicion),
    EntryPoint("ops.crc32", _ops_crc32, const_budget_bytes=2048),
)


def select_entries(names: Sequence[str] | None) -> tuple[EntryPoint, ...]:
    """The registry, optionally filtered to the named entries (exact match)."""
    if not names:
        return ENTRY_POINTS
    by_name = {e.name: e for e in ENTRY_POINTS}
    missing = [n for n in names if n not in by_name]
    if missing:
        raise KeyError(f"unknown entry point(s): {', '.join(missing)}")
    return tuple(by_name[n] for n in names)


def trace_entry(entry: EntryPoint, x64: bool = False):
    """``ClosedJaxpr`` of one entry, optionally under ``jax_enable_x64``.

    The build runs inside the flag context so implicit-default dtypes
    drift exactly as they would in a production x64 process — the KB401
    detection surface. Pinned dtypes are flag-invariant, so the x32 trace
    (used by KB402-404) and the x64 trace share structure."""
    import contextlib

    import jax
    from jax.experimental import enable_x64

    ctx = enable_x64() if x64 else contextlib.nullcontext()
    with ctx:
        fn, args = entry.build()
        return jax.make_jaxpr(fn)(*args)
