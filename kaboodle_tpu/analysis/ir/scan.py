"""The graftscan driver: trace the registry, run the passes, gate the debt.

``run_scan`` is the one entry the CLI (and the mutation tests) call. It
returns every finding — KB401-404 from the per-entry traces, KB405 from
the compile-surface exercise — *before* baseline filtering, so the CLI
applies the exact same baseline/no-growth plumbing the AST lane uses.

jax is imported here (lazily, CPU-pinned): the default AST lint lane never
reaches this module, so ``make lint``'s first line stays backend-free and
the analysis tests keep their parse-speed fast lane.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Sequence

from kaboodle_tpu.analysis.core import Finding
from kaboodle_tpu.analysis.ir.registry import EntryPoint, select_entries, trace_entry


def _prepare_backend() -> None:
    """CPU-pin jax before first import: the scan is a lint gate, not a
    workload — tracing is backend-independent and must never wedge on (or
    warm up) an accelerator. Explicit JAX_PLATFORMS wins."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        from axon_guard import strip_axon_plugin

        strip_axon_plugin()
    except ImportError:  # installed-package runs outside the repo root
        pass


@dataclasses.dataclass
class ScanResult:
    findings: list[Finding]
    surface_measured: dict[str, int]
    entries_scanned: int


def scan_entry(entry: EntryPoint) -> list[Finding]:
    """KB401-404 findings for one entry point (both traces).

    An x32 trace failure is a broken entry (propagates — the registry must
    always trace in production mode); an x64 trace failure IS a KB401
    finding: the program contains a dtype that only holds together under
    the 32-bit defaults (e.g. an unpinned integer accumulator that int64
    widening snaps against a pinned ref)."""
    from kaboodle_tpu.analysis.ir.passes import PASSES_X32, PASSES_X64

    findings: list[Finding] = []
    x32 = trace_entry(entry, x64=False)
    for p in PASSES_X32:
        findings.extend(p(entry, x32))
    try:
        x64 = trace_entry(entry, x64=True)
    except Exception as e:  # noqa: BLE001 — any trace-time error qualifies
        findings.append(
            Finding(
                f"ir://{entry.name}",
                "KB401",
                0,
                "entry fails to trace under x64 — an implicit dtype widens "
                f"until the program is inconsistent: {type(e).__name__}: "
                f"{str(e).splitlines()[0] if str(e) else ''}",
                "x64-trace-error",
            )
        )
    else:
        for p in PASSES_X64:
            findings.extend(p(entry, x64))
    return findings


def run_scan(
    entry_names: Sequence[str] | None = None,
    entries: Sequence[EntryPoint] | None = None,
    with_surface: bool = True,
    progress=None,
) -> ScanResult:
    """Trace + audit the registry (or injected ``entries``), then measure
    the compile surface. ``progress(msg)`` gets one line per phase."""
    _prepare_backend()
    from kaboodle_tpu.analysis.ir import surface as surface_mod

    chosen = entries if entries is not None else select_entries(entry_names)
    findings: list[Finding] = []
    for entry in chosen:
        if progress:
            progress(f"graftscan: tracing {entry.name}")
        findings.extend(scan_entry(entry))

    measured: dict[str, int] = {}
    if with_surface:
        if progress:
            progress("graftscan: measuring compile surface (dense+warp+fleet+serve)")
        measured = surface_mod.measure_surface()

    findings.sort(key=lambda f: (f.path, f.rule, f.symbol))
    return ScanResult(findings, measured, len(chosen))
