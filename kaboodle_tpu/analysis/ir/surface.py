"""KB405: the compile-surface budget — count programs, gate growth.

Memoization-based simulators get their speed from a small, stable set of
compiled programs; a recompilation storm (a spurious static arg, a shape
that varies per call, a span-chunking policy that degrades to one program
per span length) is invisible to unit tests — everything still passes,
just N times slower. This module:

- provides :func:`compile_counter`, a context manager counting *fresh* XLA
  compilations via jax's monitoring events (cache hits do not fire) —
  shared with the parity fuzz's zero-recompile assertion arm
  (tests/test_fuzz_parity.py);
- defines the scripted dense+warp+fleet+serve+sparse **exercise** — a
  fixed sequence of
  representative dispatches per entry-point family — and measures how many
  compilations each family triggers;
- loads/writes the committed budget ``.graftscan_surface.json`` and turns
  measured-vs-committed deltas into KB405 findings.

The committed counts are only meaningful for a FRESH process running the
whole script in order (eager-op caches warm deterministically along the
way), which is exactly how ``make lint`` / CI invoke the scan. In-process
callers (tests) use the measurement machinery with their own synthetic
exercises and baselines instead of asserting the committed numbers.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import pathlib
from typing import Callable, Iterator, Sequence

from kaboodle_tpu.analysis.core import BaselineError, Finding

DEFAULT_SURFACE = ".graftscan_surface.json"

# The event jax records once per fresh backend compilation (never on a
# jit/pjit cache hit) — see jax._src.compiler.
_COMPILE_EVENT = "/jax/compilation_cache/compile_requests_use_cache"


@dataclasses.dataclass
class CompileCount:
    count: int = 0


_active_counters: list[CompileCount] = []
_listener_registered = False


def _listener(name: str, **kw) -> None:
    if name == _COMPILE_EVENT:
        for box in _active_counters:
            box.count += 1


@contextlib.contextmanager
def compile_counter() -> Iterator[CompileCount]:
    """Count fresh XLA compilations inside the block (cache hits: zero).

    Nestable; the singleton monitoring listener stays registered for the
    life of the process (jax's listener list has no compaction — repeated
    register/unregister cycles would leak)."""
    global _listener_registered
    from jax._src import monitoring

    if not _listener_registered:
        monitoring.register_event_listener(_listener)
        _listener_registered = True
    box = CompileCount()
    _active_counters.append(box)
    try:
        yield box
    finally:
        _active_counters.remove(box)


def assert_counter_live() -> None:
    """Fail loudly if the compile-event stream is dead in this process.

    jax only records the monitored event when its compilation-cache
    machinery is engaged for the backend; with it disabled (e.g.
    ``JAX_ENABLE_COMPILATION_CACHE=0``) every exercise would measure 0 and
    the KB405 gate — and any zero-recompile assertion — would be vacuous
    (or, worse, advise committing zero budgets that then fail elsewhere as
    growth). A sentinel compile (a fresh closure, so never jit-cached)
    must register exactly one event before any measurement is trusted."""
    import jax
    import jax.numpy as jnp

    with compile_counter() as box:
        jax.jit(lambda x: x + 1)(jnp.zeros((3,), jnp.float32))
    if box.count == 0:
        raise RuntimeError(
            "compile-event stream is dead (jax compilation cache disabled?) "
            "— compile-surface counts would be vacuously 0; re-enable the "
            "cache (unset JAX_ENABLE_COMPILATION_CACHE=0) to run this gate"
        )


# ---------------------------------------------------------------------------
# the scripted exercise

_EX_N = 32  # exercise mesh size (CPU-friendly; program count is N-free)
_EX_E = 4  # fleet ensemble width


@dataclasses.dataclass(frozen=True)
class SurfaceExercise:
    """One entry-point family: a scripted run whose compile count is gated.

    ``prep()`` builds states/inputs (its eager-op compilations are NOT
    counted); ``run(ctx)`` dispatches the entry points under the counter.
    Host-driven runners (warp) still compile a few eager helpers inside
    ``run`` — those are part of their real dispatch surface and count."""

    name: str
    prep: Callable[[], object]
    run: Callable[[object], None]


def _cfg():
    from kaboodle_tpu.config import SwimConfig

    return SwimConfig(deterministic=True)


def _prep_dense():
    import dataclasses as dc

    import jax
    import jax.numpy as jnp

    from kaboodle_tpu.phasegraph.derive import make_dense_tick
    from kaboodle_tpu.sim.state import idle_inputs, init_state

    cfg = _cfg()
    n = _EX_N
    idle = idle_inputs(n)
    variants = (
        idle,
        dc.replace(idle, kill=idle.kill.at[1].set(True)),
        dc.replace(idle, revive=idle.revive.at[1].set(True)),
        dc.replace(idle, manual_target=idle.manual_target.at[0].set(3)),
        dc.replace(idle, drop_rate=jnp.float32(0.1)),
    )
    return {
        "tick": jax.jit(make_dense_tick(cfg, faulty=True)),
        "fast": jax.jit(make_dense_tick(cfg, faulty=False)),
        "lean": jax.jit(make_dense_tick(cfg, faulty=False)),
        "st": init_state(n, seed=0),
        "stf": init_state(n, seed=1),
        "stl": init_state(
            n, seed=2, timer_dtype=jnp.int16, track_latency=False,
            instant_identity=True,
        ),
        "idle": idle,
        "variants": variants,
    }


def _run_dense(ctx) -> None:
    """The dense tick across its input envelope: idle / kill / revive /
    manual / nonzero drop all share ONE faulty program; the fault-free and
    lean builds are one program each. Five shapes-identical dispatches per
    jit prove the cache holds: budget = 3."""
    st = ctx["st"]
    for inp in ctx["variants"]:
        st, _ = ctx["tick"](st, inp)
    stf, stl = ctx["stf"], ctx["stl"]
    for _ in range(2):
        stf, _ = ctx["fast"](stf, ctx["idle"])
    for _ in range(2):
        stl, _ = ctx["lean"](stl, ctx["idle"])


def _prep_warp():
    import jax.numpy as jnp
    import numpy as np

    from kaboodle_tpu.sim.state import TickInputs, idle_inputs, init_state
    from kaboodle_tpu.warp import runner

    # The runner memoizes jitted programs process-wide; start this exercise
    # from a cold runner cache so the count is the runner's real surface.
    runner._dense_tick.cache_clear()
    runner._converged.cache_clear()
    runner.leap_cache.clear()

    n = _EX_N
    ticks = 24
    idle = idle_inputs(n, ticks=ticks)
    kill = np.zeros((ticks, n), dtype=bool)
    kill[8, 1] = True  # one mid-run fault: leap -> drain window -> leap
    inputs = TickInputs(
        kill=jnp.asarray(kill),
        revive=idle.revive,
        partition=idle.partition,
        drop_rate=idle.drop_rate,
        manual_target=idle.manual_target,
        drop_ok=None,
    )
    # Warp 2.0 prep: a mid-drain near-quiescent state (two dead peers, every
    # survivor's cell for them armed) under a drain-shaped config, plus a
    # two-member fleet (one converged, one mid-drain) for the per-member
    # warp round. All dense ticking here is eager prep — not counted.
    import jax

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.fleet.core import FleetState
    from kaboodle_tpu.phasegraph.derive import make_dense_tick
    from kaboodle_tpu.sim.scenario import Scenario
    from kaboodle_tpu.warp.horizon import decode_signature, make_signature_fn

    cfg2 = SwimConfig(deterministic=True, ping_timeout_ticks=64)
    st2 = init_state(n, seed=3, ring_contacts=n - 1, announced=True)
    kill1 = jax.tree.map(
        lambda x: x[0], Scenario(n, 1, seed=0).kill_at(0, [2, 9]).build()
    )
    st2, _ = jax.jit(make_dense_tick(cfg2, faulty=True))(st2, kill1)
    tick0 = jax.jit(make_dense_tick(cfg2, faulty=False))
    sig = make_signature_fn(cfg2)
    idle1 = idle_inputs(n)
    for _ in range(40):
        if decode_signature(sig(st2)).mode == "hybrid":
            break
        st2, _ = tick0(st2, idle1)
    members = [init_state(n, seed=0, ring_contacts=n - 1, announced=True), st2]
    fleet = FleetState(
        mesh=jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *members),
        drop_rate=jnp.zeros((2,), jnp.float32),
    )
    return {
        "st": init_state(n, seed=0, ring_contacts=n - 1, announced=True),
        "inputs": inputs,
        "cfg2": cfg2,
        "drain": st2,
        "fleet": fleet,
    }


def _run_warp(ctx) -> None:
    """The warp runner over a converged mesh and a sparse-fault schedule:
    the dense tick + the signature/convergence programs + the bucketed
    power-of-two leap-chunk programs, strict AND hybrid (plus the runner's
    own host-side eager helpers — slicing, metric stacking — which are
    part of its dispatch surface). Two run lengths whose span
    decompositions share chunks (48 = 32+16, 44 = 32+8 and 4 dense
    remainder ticks) prove the bucketing policy bounds the cache; the
    regression this guards is one program per distinct span length. The
    post-kill drain drives the hybrid-class program family too."""
    from kaboodle_tpu.warp.runner import run_fleet_warped, run_warped, simulate_warped

    cfg = _cfg()
    st = ctx["st"]
    run_warped(st, cfg, ticks=48, recheck_every=8)
    run_warped(st, cfg, ticks=44, recheck_every=8)
    simulate_warped(st, ctx["inputs"], cfg, faulty=True, recheck_every=8)
    # Warp 2.0: the hybrid program family over the drain state (chunks
    # shared with a second length), and one per-member fleet warp round
    # (vmapped signature + masked fleet leap + masked dense freeze).
    run_warped(ctx["drain"], ctx["cfg2"], ticks=40, recheck_every=8)
    run_warped(ctx["drain"], ctx["cfg2"], ticks=24, recheck_every=8)
    run_fleet_warped(ctx["fleet"], ctx["cfg2"], ticks=16, recheck_every=8)


def _prep_fleet():
    import jax.numpy as jnp

    from kaboodle_tpu.fleet.core import fleet_idle_inputs, init_fleet

    n, e = _EX_N // 2, _EX_E
    return {
        "fleet": init_fleet(n, e),
        "fleet2": init_fleet(n, e, drop_rates=jnp.linspace(0.0, 0.2, e)),
        "inputs": fleet_idle_inputs(n, e, ticks=4),
    }


def _run_fleet(ctx) -> None:
    """The vmapped ensemble: one scan program advances all members; a
    second dispatch with different per-member knob values must hit the
    cache (knobs are traced, not static): budget = 1."""
    from kaboodle_tpu.fleet.core import simulate_fleet

    cfg = _cfg()
    simulate_fleet(ctx["fleet"], ctx["inputs"], cfg, faulty=True)
    simulate_fleet(ctx["fleet2"], ctx["inputs"], cfg, faulty=True)


def _prep_serve():
    from kaboodle_tpu.serve.engine import ServeEngine, ServeRequest
    from kaboodle_tpu.serve.pool import LanePool
    from kaboodle_tpu.warp import runner

    # The serve engine warms its own leap buckets and signature program;
    # start from cold warp caches so the count is the serve surface, not
    # whatever the warp exercise left behind.
    runner.leap_cache.clear()
    runner._fleet_signature.cache_clear()

    pool = LanePool(_EX_N // 2, _EX_E, cfg=_cfg(), chunk=4)
    engine = ServeEngine([pool], warp=True, max_leap=16)
    return {"engine": engine, "request": ServeRequest}


def _run_serve(ctx) -> None:
    """The serving surface: engine warmup (pool program set + signature +
    leap buckets 8/16), then admit→converge→re-seed cycles and a
    horizon/leap request. Everything compiles in warmup — the cycles
    after it are the zero-recompile-after-warmup contract, so any budget
    growth here is a recompilation regression on the serving path."""
    engine = ctx["engine"]
    request = ctx["request"]
    n = _EX_N // 2
    engine.warmup()
    # Two full admit -> converge -> harvest -> re-seed cycles through the
    # same lane (the second proves the re-seed path hits the cache)...
    for seed in (0, 1):
        engine.submit(request(n=n, seed=seed))
        engine.drain()
    # ...and one horizon-mode steady request that rides the leap path.
    engine.submit(request(n=n, seed=2, mode="ticks", ticks=24,
                          scenario="steady"))
    engine.drain()


def _prep_sparse():
    import dataclasses as dc

    import jax.numpy as jnp

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sparseplane import (
        SparseSpec,
        init_sparse_state,
        sparse_idle_inputs,
    )

    n = _EX_N
    cfg = SwimConfig(deterministic=True, join_broadcast_enabled=False)
    spec = SparseSpec(k=8, gossip_fanout=2, boot_contacts=2)
    lean = SparseSpec(k=8, gossip_fanout=2, boot_contacts=2,
                      timer_dtype="int16")
    idle = sparse_idle_inputs(n, ticks=4)
    variants = (
        idle,
        dc.replace(idle, kill=idle.kill.at[1, 2].set(True)),
        dc.replace(idle, revive=idle.revive.at[2, 2].set(True)),
        dc.replace(idle, drop_rate=jnp.full((4,), 0.1, jnp.float32)),
    )
    return {
        "cfg": cfg,
        "spec": spec,
        "lean": lean,
        "st": init_sparse_state(n, spec, seed=0),
        "st2": init_sparse_state(n, spec, seed=7),
        "stl": init_sparse_state(n, lean, seed=0),
        "variants": variants,
    }


def _run_sparse(ctx) -> None:
    """The blocked_topk engine (ISSUE 18): the scanned sparse tick across
    its input envelope (idle / kill / revive / nonzero drop all share ONE
    program — drop_rate is traced), the lean int16 build, and the
    while_loop converge runner; a second converge dispatch from different
    data must hit the cache. Budget = 3."""
    from kaboodle_tpu.sparseplane import (
        run_sparse_until_converged,
        simulate_sparse,
    )

    cfg, spec = ctx["cfg"], ctx["spec"]
    st = ctx["st"]
    for inp in ctx["variants"]:
        st, _ = simulate_sparse(st, inp, cfg, spec)
    simulate_sparse(ctx["stl"], ctx["variants"][0], cfg, ctx["lean"])
    run_sparse_until_converged(ctx["st"], cfg, spec, max_ticks=64)
    run_sparse_until_converged(ctx["st2"], cfg, spec, max_ticks=64)


EXERCISES: tuple[SurfaceExercise, ...] = (
    SurfaceExercise("dense", _prep_dense, _run_dense),
    SurfaceExercise("warp", _prep_warp, _run_warp),
    SurfaceExercise("fleet", _prep_fleet, _run_fleet),
    SurfaceExercise("serve", _prep_serve, _run_serve),
    SurfaceExercise("sparse", _prep_sparse, _run_sparse),
)


def measure_surface(
    exercises: Sequence[SurfaceExercise] | None = None,
) -> dict[str, int]:
    """Run the scripted exercises in order; fresh compiles per family.

    Prep (state/input construction) runs outside the counter, so the
    committed numbers track entry-point programs, not eager setup noise."""
    assert_counter_live()
    out: dict[str, int] = {}
    for ex in exercises if exercises is not None else EXERCISES:
        ctx = ex.prep()
        with compile_counter() as box:
            ex.run(ctx)
        out[ex.name] = box.count
    return out


# ---------------------------------------------------------------------------
# committed budget file


def load_surface(path: pathlib.Path) -> dict[str, tuple[int, str]]:
    """entry -> (programs, reason). Missing file = empty budget; malformed
    entries (no name/count/justification) are hard errors, like the lint
    baseline — the justification is the point of the file."""
    if not path.exists():
        return {}
    try:
        data = json.loads(path.read_text())
    except json.JSONDecodeError as e:
        raise BaselineError(f"{path}: invalid JSON: {e}") from e
    entries = data.get("entries") if isinstance(data, dict) else None
    if not isinstance(entries, list):
        raise BaselineError(f"{path}: expected an object with an 'entries' list")
    out: dict[str, tuple[int, str]] = {}
    for i, e in enumerate(entries):
        if (
            not isinstance(e, dict)
            or not e.get("entry")
            or not isinstance(e.get("programs"), int)
            or not str(e.get("reason", "")).strip()
        ):
            raise BaselineError(
                f"{path}: entries[{i}] needs 'entry', integer 'programs', and "
                "a non-empty 'reason' justification"
            )
        out[str(e["entry"])] = (int(e["programs"]), str(e["reason"]))
    return out


def write_surface(
    path: pathlib.Path,
    measured: dict[str, int],
    old: dict[str, tuple[int, str]],
) -> None:
    """Regenerate the budget from measured counts, keeping old reasons."""
    payload = {
        "comment": (
            "graftscan compile-surface budget: distinct XLA compilations per "
            "entry-point family across the scripted "
            "dense+warp+fleet+serve+sparse exercise "
            "(fresh process — `python -m kaboodle_tpu.analysis --ir`). CI "
            "fails on growth; raising a count requires editing this file "
            "with a justification. Shrink when the measured count drops."
        ),
        "entries": [
            {
                "entry": name,
                "programs": count,
                "reason": old.get(name, (0, "TODO: justify this budget"))[1],
            }
            for name, count in sorted(measured.items())
        ],
    }
    path.write_text(json.dumps(payload, indent=2) + "\n")


def surface_findings(
    measured: dict[str, int],
    committed: dict[str, tuple[int, str]],
    no_growth: bool = False,
) -> list[Finding]:
    """Measured-vs-committed deltas as KB405 findings.

    Growth (or a family missing from the file) always fails; shrink fails
    only under ``--no-baseline-growth`` — the same monotonic-debt contract
    as the lint baseline, so the committed counts can only ratchet down."""
    out: list[Finding] = []
    for name, count in sorted(measured.items()):
        if name not in committed:
            out.append(
                Finding(
                    f"ir://surface.{name}",
                    "KB405",
                    0,
                    f"no committed budget for '{name}' ({count} programs "
                    "measured) — run --write-surface and justify the entry",
                    f"surface:{name}:missing",
                )
            )
            continue
        budget, _reason = committed[name]
        if count > budget:
            out.append(
                Finding(
                    f"ir://surface.{name}",
                    "KB405",
                    0,
                    f"compile surface grew: {count} programs vs committed "
                    f"{budget} — a recompilation regression, or raise the "
                    "budget in .graftscan_surface.json with a justification",
                    f"surface:{name}:growth",
                )
            )
        elif count < budget and no_growth:
            out.append(
                Finding(
                    f"ir://surface.{name}",
                    "KB405",
                    0,
                    f"compile surface shrank: {count} programs vs committed "
                    f"{budget} — commit the smaller count (--write-surface)",
                    f"surface:{name}:stale",
                )
            )
    if no_growth:
        for name in sorted(set(committed) - set(measured)):
            out.append(
                Finding(
                    f"ir://surface.{name}",
                    "KB405",
                    0,
                    f"stale surface entry '{name}': family no longer measured "
                    "— delete it from .graftscan_surface.json",
                    f"surface:{name}:orphan",
                )
            )
    return out
