"""Jaxpr traversal + source attribution shared by every graftscan pass.

A traced entry point is a ``ClosedJaxpr`` whose equations nest more jaxprs
inside their params (``pjit`` bodies, ``scan``/``while``/``cond`` branches,
custom-call subcomputations). Every pass wants the same two things:

- a flat walk over ALL equations, wherever they nest
  (:func:`iter_eqns`), and
- a stable, human-meaningful location for a finding
  (:func:`source_of`): the nearest *user* frame (repo code, not jax
  internals) of the equation's traceback. Baseline keys embed the file
  basename but not the line, so entries survive unrelated edits — the same
  key discipline as the AST lane's ``Finding.key``.

Consumer analysis (:func:`terminal_consumers`) answers "what ultimately
uses this value" for the KB401 int16-widening allowlist: structural ops
that merely move data (broadcast/reshape/transpose/...) are transparent,
and a ``pjit`` consumer is resolved into its body. Everything here is
read-only over jax's public jaxpr surface (``eqns``/``invars``/``outvars``/
``params``) plus ``source_info``, guarded so a jax upgrade degrades
attribution to "<unknown>" instead of crashing the gate.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator


def _sub_jaxprs(params: dict) -> Iterator:
    """Every (Closed)Jaxpr nested in an equation's params."""
    for p in params.values():
        for sub in p if isinstance(p, (list, tuple)) else [p]:
            inner = getattr(sub, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                yield inner  # ClosedJaxpr -> its Jaxpr
            elif hasattr(sub, "eqns"):
                yield sub  # bare Jaxpr


def iter_eqns(jaxpr) -> Iterator:
    """Depth-first over every equation of ``jaxpr`` and its sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def iter_jaxprs(jaxpr) -> Iterator:
    """``jaxpr`` plus every nested sub-jaxpr (for per-scope analyses)."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_jaxprs(sub)


def eqn_avals(eqn) -> list:
    """Abstract values of an equation's inputs + outputs (literals included)."""
    out = []
    for v in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(v, "aval", None)
        if aval is not None:
            out.append(aval)
    return out


@dataclasses.dataclass(frozen=True)
class Source:
    """Nearest user frame of an equation: repo file + line (best effort)."""

    file: str  # basename, "<unknown>" when attribution failed
    line: int  # 0 when unknown

    def render(self) -> str:
        return self.file if not self.line else f"{self.file}:{self.line}"


_UNKNOWN = Source("<unknown>", 0)


def source_of(eqn) -> Source:
    """The equation's nearest non-jax-internal frame.

    ``source_info_util.user_frame`` already filters jax library frames, so a
    64-bit draw inside ``jax.random`` attributes to the repo call site that
    asked for it — the line the fix belongs on. Private API, so any failure
    degrades to ``<unknown>`` rather than failing the scan."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return _UNKNOWN
        return Source(frame.file_name.replace("\\", "/").rsplit("/", 1)[-1], frame.start_line)
    except Exception:
        return _UNKNOWN


# ---------------------------------------------------------------------------
# consumer analysis (KB401 lean-widening allowlist)

# Ops that move/reshape a value without computing on it: consumers *through*
# these are the ones that matter for the widening allowlist.
TRANSPARENT_PRIMS = frozenset(
    {
        "broadcast_in_dim",
        "reshape",
        "transpose",
        "squeeze",
        "expand_dims",
        "slice",
        "rev",
        "copy",
    }
)


def terminal_consumers(jaxpr, var, _depth: int = 0) -> set[str]:
    """Primitive names that ultimately consume ``var`` within ``jaxpr``.

    Transparent ops are traversed (their outputs' consumers substitute for
    them); a ``pjit`` consumer resolves into its body via the matching
    parameter. A value escaping as a (sub-)jaxpr output reports the
    sentinel ``"<jaxpr-output>"`` — callers treat escape as
    not-allowlisted, because the pass cannot see what the parent does with
    it."""
    out: set[str] = set()
    if _depth > 16:  # defensive: malformed/cyclic structures
        return {"<depth-limit>"}
    for eqn in jaxpr.eqns:
        if not any(v is var for v in eqn.invars):
            continue
        name = eqn.primitive.name
        if name in TRANSPARENT_PRIMS:
            out |= terminal_consumers(jaxpr, eqn.outvars[0], _depth + 1)
        elif name == "pjit":
            inner = eqn.params["jaxpr"].jaxpr
            for pos, v in enumerate(eqn.invars):
                if v is var and pos < len(inner.invars):
                    out |= terminal_consumers(inner, inner.invars[pos], _depth + 1)
        else:
            out.add(name)
    if any(v is var for v in jaxpr.outvars):
        out.add("<jaxpr-output>")
    return out


def aval_nbytes(aval) -> int:
    """Concrete byte size of a shaped aval (0 for abstract/opaque ones)."""
    try:
        import numpy as np

        size = 1
        for d in aval.shape:
            size *= int(d)
        return size * np.dtype(aval.dtype).itemsize
    except Exception:
        return 0
