"""Syntactic jit-reachability + tracer taint for the KB2xx/KB3xx rules.

The JAX rules only make sense inside code that runs under tracing. Without
importing jax, "traced" is decided per module from syntax:

Seeds (a function is traced if any of these hold):

- decorated with a trace wrapper: ``@jax.jit``, ``@jit``, ``@pjit``,
  ``@shard_map``, or ``@(functools.)partial(<wrapper>, ...)``;
- passed by name to a trace-wrapper call (``jax.jit(f)``, ``shard_map(f,
  mesh, ...)``) or to a tracing callback site (``jax.lax.scan/cond/
  while_loop/fori_loop/switch/map/associative_scan``, ``pl.pallas_call``,
  ``jax.vmap/grad/checkpoint``, ``jax.tree.map`` — their callees all
  receive tracers when the surrounding program is traced);
- marked ``# graftlint: traced`` on its ``def`` line — the escape hatch for
  functions that are traced from *another* module (e.g. the tick closures
  returned by ``make_tick_fn`` and scanned by ``runner.simulate``), since
  reachability is per-module by design;
- defined inside, or called by name from, an already-traced function
  (transitive closure over module-local names).

Traced parameters: every parameter of a seed, minus names listed in a
``static_argnames=(...)`` / ``static_argnums=(...)`` literal on the wrapper.
Inside a traced function, taint then propagates forward through assignments,
with two structural exemptions that keep idiomatic JAX clean:

- attribute reads that are static under tracing (``.shape``, ``.dtype``,
  ``.ndim``, ``.size``, ...) cut the taint — ``n = st.state.shape[-1]`` is
  a Python int at trace time;
- ``x is None`` / ``x is not None`` is a static structural test (pytrees
  with optional leaves), not a value branch.

This is deliberately an approximation: no cross-module reachability, no
lambda bodies, loops don't re-run the taint pass. False negatives are
acceptable (the gate tightens over time); false positives are the thing
being engineered against, because a ``-D warnings`` gate that cries wolf
gets noqa'd into uselessness.
"""

from __future__ import annotations

import ast
import dataclasses

from kaboodle_tpu.analysis.core import Module

TRACE_WRAPPERS = {
    "jax.jit",
    "jit",
    "pjit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
    "jax.vmap",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "shard_map",
    "jax.experimental.shard_map.shard_map",
    "jax.sharding.shard_map",
}

CALLBACK_SITES = {
    "jax.lax.scan",
    "jax.lax.cond",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.switch",
    "jax.lax.map",
    "jax.lax.associative_scan",
    "jax.experimental.pallas.pallas_call",
    "jax.tree.map",
    "jax.tree_util.tree_map",
}

PARTIAL = {"functools.partial", "partial"}

# Attribute reads that are static (Python values) on a traced array/pytree.
STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "itemsize", "weak_type", "sharding",
    "aval", "names",
}

TRACED_PRAGMA = "graftlint: traced"


@dataclasses.dataclass
class FuncInfo:
    node: ast.FunctionDef | ast.AsyncFunctionDef
    qualname: str
    traced: bool = False
    # Whether traced_params carries the full parameter list. Seeds and nested
    # defs of traced functions get all params (minus static_arg* names);
    # functions merely *called by name* from traced code run at trace time
    # but receive unknown — often static — arguments, so they are marked
    # traced with NO tainted params: their bodies still see the taint-free
    # checks (print, host sync) but never a spurious traced-branch finding.
    params_full: bool = False
    traced_params: set[str] = dataclasses.field(default_factory=set)


def _param_names(node) -> list[str]:
    a = node.args
    return [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)] + (
        [a.vararg.arg] if a.vararg else []
    ) + ([a.kwarg.arg] if a.kwarg else [])


def _static_names_from_call(mod: Module, call: ast.Call, params: list[str]) -> set[str]:
    """Params named static via static_argnames/static_argnums literals."""
    static: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    static.add(c.value)
        elif kw.arg == "static_argnums":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, int):
                    if 0 <= c.value < len(params):
                        static.add(params[c.value])
    return static


class ReachInfo:
    """Per-module map: function node -> FuncInfo with traced flags."""

    def __init__(self, mod: Module):
        self.mod = mod
        self.by_node: dict[ast.AST, FuncInfo] = {}
        self.by_name: dict[str, list[FuncInfo]] = {}
        self._collect(mod.tree, "")
        self._seed()
        self._closure()

    # -- collection ---------------------------------------------------------

    def _collect(self, node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                info = FuncInfo(child, qual)
                self.by_node[child] = info
                self.by_name.setdefault(child.name, []).append(info)
                self._collect(child, qual + ".")
            elif isinstance(child, ast.ClassDef):
                self._collect(child, f"{prefix}{child.name}.")
            else:
                self._collect(child, prefix)

    # -- seeding ------------------------------------------------------------

    def _wrapper_call(self, call: ast.Call) -> str | None:
        """The trace-wrapper name if ``call`` is jit-like (incl. partial)."""
        d = self.mod.dotted(call.func)
        if d in TRACE_WRAPPERS:
            return d
        if d in PARTIAL and call.args:
            inner = self.mod.dotted(call.args[0])
            if inner in TRACE_WRAPPERS:
                return inner
        return None

    def _mark(
        self, info: FuncInfo, static: set[str] | None = None, full: bool = True
    ) -> None:
        info.traced = True
        if full and not info.params_full:
            info.params_full = True
            info.traced_params = {
                p for p in _param_names(info.node) if p not in (static or set())
            }

    def _seed(self) -> None:
        mod = self.mod
        # decorators + pragma
        for info in self.by_node.values():
            node = info.node
            line = mod.lines[node.lineno - 1] if node.lineno <= len(mod.lines) else ""
            if TRACED_PRAGMA in line:
                self._mark(info)
                continue
            params = _param_names(node)
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    w = self._wrapper_call(dec)
                    if w:
                        self._mark(info, _static_names_from_call(mod, dec, params))
                elif mod.dotted(dec) in TRACE_WRAPPERS:
                    self._mark(info)
        # call sites: jax.jit(f, ...), lax.scan(f, ...), partial(jax.jit,...)(f)
        for call in (n for n in ast.walk(mod.tree) if isinstance(n, ast.Call)):
            d = mod.dotted(call.func)
            names: list[str] = []
            static: set[str] = set()
            if self._wrapper_call(call) and call.args and isinstance(call.args[0], ast.Name):
                names = [call.args[0].id]
                # static names on the wrapper apply to the wrapped function
                for info in self.by_name.get(names[0], []):
                    static = _static_names_from_call(mod, call, _param_names(info.node))
            elif d in CALLBACK_SITES:
                names = [a.id for a in call.args if isinstance(a, ast.Name)]
            for name in names:
                for info in self.by_name.get(name, []):
                    if not info.traced:
                        self._mark(info, static)

    def _closure(self) -> None:
        """Nested defs of traced fns (full params) + local fns *called* from
        traced fns (traced, but no tainted params — see FuncInfo)."""
        changed = True
        while changed:
            changed = False
            for info in list(self.by_node.values()):
                if not info.traced:
                    continue
                for sub in ast.walk(info.node):
                    if sub is info.node:
                        continue
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        target = self.by_node.get(sub)
                        if target is not None and not (
                            target.traced and target.params_full
                        ):
                            self._mark(target, full=True)
                            changed = True
                    elif isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                        cands = self.by_name.get(sub.func.id, [])
                        target = cands[0] if len(cands) == 1 else None
                        if target is not None and not target.traced:
                            self._mark(target, full=False)
                            changed = True

    # -- public -------------------------------------------------------------

    def traced_functions(self) -> list[FuncInfo]:
        return [i for i in self.by_node.values() if i.traced]


# ---------------------------------------------------------------------------
# taint


def expr_tainted(e: ast.AST, tainted: set[str]) -> bool:
    """Does ``e``'s value (under tracing) depend on a tainted name?

    Prunes static-attribute reads and ``is (not) None`` tests — see module
    docstring.
    """
    if isinstance(e, ast.Name):
        return e.id in tainted
    if isinstance(e, ast.Attribute):
        return False if e.attr in STATIC_ATTRS else expr_tainted(e.value, tainted)
    if isinstance(e, ast.Compare):
        if (
            all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops)
            and all(
                isinstance(c, ast.Constant) and c.value is None for c in e.comparators
            )
        ):
            return False
        return expr_tainted(e.left, tainted) or any(
            expr_tainted(c, tainted) for c in e.comparators
        )
    if isinstance(e, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
        return False
    return any(expr_tainted(c, tainted) for c in ast.iter_child_nodes(e))


def _assign_targets(t: ast.AST) -> list[str]:
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out: list[str] = []
        for e in t.elts:
            out.extend(_assign_targets(e))
        return out
    if isinstance(t, ast.Starred):
        return _assign_targets(t.value)
    return []


def shallow_exprs(stmt: ast.stmt) -> list[ast.expr]:
    """The expressions belonging directly to ``stmt`` — not the ones inside
    its nested statement blocks (those get their own visit)."""
    out: list[ast.expr] = []
    for name, value in ast.iter_fields(stmt):
        if name in ("body", "orelse", "finalbody", "handlers", "decorator_list"):
            continue
        if isinstance(value, ast.expr):
            out.append(value)
        elif isinstance(value, list):
            out.extend(v for v in value if isinstance(v, ast.expr))
        elif isinstance(value, ast.withitem):
            out.append(value.context_expr)
    # `with a as b, c as d:` — items is a list of withitems
    for item in getattr(stmt, "items", []) or []:
        if isinstance(item, ast.withitem):
            out.append(item.context_expr)
    return out


def walk_with_taint(info: FuncInfo, visit) -> None:
    """Forward walk of a traced function's own body (nested defs skipped —
    they are separate traced entries) maintaining the tainted-name set.
    ``visit(stmt, tainted)`` is called per statement, pre-propagation."""
    tainted = set(info.traced_params)

    def do(stmts) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            visit(s, tainted)
            if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)) and getattr(
                s, "value", None
            ) is not None:
                if expr_tainted(s.value, tainted):
                    targets = s.targets if isinstance(s, ast.Assign) else [s.target]
                    for t in targets:
                        tainted.update(_assign_targets(t))
            elif isinstance(s, ast.For):
                if expr_tainted(s.iter, tainted):
                    tainted.update(_assign_targets(s.target))
            # walk nested blocks in order
            for field in ("body", "orelse", "finalbody"):
                do(getattr(s, field, []) or [])
            for h in getattr(s, "handlers", []) or []:
                do(h.body)

    do(info.node.body)
