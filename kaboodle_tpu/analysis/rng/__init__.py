"""keyscope — the PRNG key-provenance auditor (KB6xx, the rng lane).

The repo runs two RNG disciplines side by side: the dense engines carry a
threefry key through the state and ``split(key, 5)`` it every tick
(phasegraph/ops.py ``KEY_LAYOUT``), while sparseplane derives every draw
on the fly from checkpointable ``(seed, cursor)`` counters folded per
``STREAM_*`` id (sparseplane/rng.py). Nothing but convention keeps the two
collision-free, resume-pure, and bit-exact across the five engines derived
from one op graph — so keyscope walks each traced graftscan registry
entry's jaxpr, rebuilds the **key-provenance graph** (roots: carried state
key / counter seed; edges: split-row, fold_in; sinks: the shaped
``random_bits`` draws) and checks it mechanically:

- **KB601** key reuse — two draw sinks on the same unforked key,
- **KB602** stream-id collision / registry drift,
- **KB603** resume-impurity — a draw not rooted in checkpointable state,
- **KB604** cross-engine chain divergence against the declared fates,
- **KB605** leapability — every sink classed ``counter_keyed`` (leapable)
  or ``chain_coupled`` (dense-blocking), banked as the leap report that
  names ROADMAP item 2's migration worklist.

Run via ``python -m kaboodle_tpu.analysis --rng`` (the CLI's fourth lane,
``.keyscope_baseline.json`` debt file) or ``make rng-dryrun``.
"""

from kaboodle_tpu.analysis.rng.provenance import (
    ProvenanceGraph,
    Sink,
    build_provenance,
)
from kaboodle_tpu.analysis.rng.rules import (
    CHAIN_GROUPS,
    KEYSCOPE_STREAMS,
)
from kaboodle_tpu.analysis.rng.scan import (
    DEFAULT_LEAP_REPORT,
    build_leap_report,
    leap_findings,
    run_rng_scan,
)

__all__ = [
    "ProvenanceGraph",
    "Sink",
    "build_provenance",
    "CHAIN_GROUPS",
    "KEYSCOPE_STREAMS",
    "DEFAULT_LEAP_REPORT",
    "build_leap_report",
    "leap_findings",
    "run_rng_scan",
]
