"""Key-provenance graphs over traced jaxprs (the keyscope engine).

Under jax 0.4.37 the threefry lifecycle is fully visible in the jaxpr:
raw ``uint32[2]`` keys are ``random_wrap``-ed into typed key arrays at
every ``jax.random`` call, forked via ``random_split`` /
``random_fold_in``, and consumed by ``random_bits`` — the one primitive
every ``uniform``/``bernoulli``/``randint`` bottoms out in. The split-row
idiom the dense engines compile to is::

    random_split -> random_unwrap (u32[m,2]) -> slice[(r,0):(r+1,2)]
                 -> squeeze -> random_wrap          # "row r of the split"

:func:`build_provenance` walks one traced entry's ClosedJaxpr and
rebuilds the key dataflow as a DAG of :class:`Node`:

- **roots** — where key material enters: ``carried_key`` (a
  ``random_wrap`` of argument-derived ``u32[2]``, i.e. the checkpointed
  state key), ``counter_seed`` (``random_seed`` on an argument-derived
  scalar, i.e. sparseplane's ``(seed, cursor)`` discipline), and their
  resume-impure twins ``const_key`` / ``const_seed`` (key material baked
  into the program — KB603's target);
- **edges** — ``split`` / ``row`` / ``fold`` (fold constants are the
  ``STREAM_*`` ids when the chain roots in a counter seed — KB602's
  target), plus ``carry`` (a fresh per-iteration key entering a
  scan/while body), ``stack`` (scan-stacked per-iteration keys) and
  ``merge`` (cond branches disagreeing on a key output);
- **sinks** — every shaped ``random_bits`` draw, annotated with its
  branch path (so two draws on the same key in *mutually exclusive*
  ``cond`` branches are not reuse — the dispatched dense build puts its
  full and fused programs under one ``lax.cond``) and a ``looped`` flag
  (a loop-invariant key drawn inside a scan body is reuse even with a
  single textual sink).

Two dataflow tracks run side by side: a per-var **taint** (which
top-level arguments feed a value — empty taint = constant material) for
every value, and the key-node env for key-typed values and their raw
``u32`` shadows. Canonicalisation is structural: re-extracting row ``r``
of the same split, or re-folding the same constant onto the same parent,
lands on the same :class:`Node`, because ``jax.make_jaxpr`` does not CSE
and textual duplicates would otherwise never collide. Rules (KB601-605)
live in :mod:`kaboodle_tpu.analysis.rng.rules`; this module only builds
the graph.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator

from kaboodle_tpu.analysis.ir.walk import Source, aval_nbytes, source_of

ROOT_KINDS = frozenset({"carried_key", "counter_seed", "const_key", "const_seed"})

# Ops that move raw key bytes without transforming them: the unwrapped
# u32 shadow of a key survives these.
_RAW_TRANSPARENT = frozenset({"squeeze", "reshape", "copy", "expand_dims", "transpose"})

# Call-like primitives walked transparently (positional invar mapping),
# name -> params key holding the (Closed)Jaxpr.
_CALL_PRIMS = {
    "pjit": "jaxpr",
    "closed_call": "call_jaxpr",
    "core_call": "call_jaxpr",
    "remat2": "jaxpr",
    "remat": "jaxpr",
    "checkpoint": "jaxpr",
    "custom_jvp_call": "call_jaxpr",
    "custom_vjp_call": "call_jaxpr",
    "custom_vjp_call_jaxpr": "fun_jaxpr",
    "sharding_constraint": None,  # identity on its operand
    "custom_partitioning": "call_jaxpr",
}


def _literal_value(v) -> Any:
    """Python scalar of a jaxpr Literal, else None (not a literal)."""
    val = getattr(v, "val", None)
    if val is None or hasattr(v, "count"):  # Vars have .count, Literals don't
        return None
    try:
        return val.item() if hasattr(val, "item") and getattr(val, "ndim", 1) == 0 else val
    except Exception:
        return val


def _is_key_aval(aval) -> bool:
    return str(getattr(aval, "dtype", "")).startswith("key<")


@dataclasses.dataclass(eq=False)
class Node:
    """One key value in the provenance DAG (identity-hashed).

    ``descr()`` is the canonical, engine-portable name of the derivation
    chain — ``carried_key/split5[1]`` is "row ping of the tick split"
    regardless of which engine or nesting depth produced it. KB602
    groups folds and KB604 compares engines on descriptors, never on
    node identity.
    """

    kind: str  # ROOT_KINDS | "split" | "row" | "fold" | "carry" | "stack" | "merge"
    parents: tuple = ()
    taint: frozenset = frozenset()
    looped: bool = False
    m: int | None = None  # split width
    row: int | None = None  # row index within the parent split
    const: Any = None  # fold constant (None = traced fold operand)
    src: Source | None = None
    _descr: str | None = dataclasses.field(default=None, repr=False)

    def descr(self) -> str:
        if self._descr is None:
            self._descr = self._render()
        return self._descr

    def _render(self) -> str:
        if self.kind in ROOT_KINDS:
            return self.kind
        p = self.parents[0].descr() if self.parents else "?"
        if self.kind == "split":
            return f"{p}/split{self.m}"
        if self.kind == "row":
            return f"{p}[{self.row}]"
        if self.kind == "fold":
            c = "?" if self.const is None else self.const
            return f"{p}/fold[{c}]"
        if self.kind == "carry":
            return f"{p}/carry"
        if self.kind == "stack":
            return f"{p}/stack"
        if self.kind == "merge":
            inner = ",".join(sorted({q.descr() for q in self.parents}))
            return f"merge({inner})"
        return f"{p}/{self.kind}"

    def roots(self) -> frozenset:
        """Root *kinds* reachable upward from this node."""
        out, stack, seen = set(), [self], set()
        while stack:
            n = stack.pop()
            if id(n) in seen:
                continue
            seen.add(id(n))
            if n.kind in ROOT_KINDS:
                out.add(n.kind)
            stack.extend(n.parents)
        return frozenset(out)

    def layout_row(self) -> int | None:
        """Row index of the nearest split-row ancestor (self included).

        This is the KEY_LAYOUT coordinate of a dense-chain key: the leap
        report names sinks by it (``phasegraph.ops.KEY_LAYOUT[row]``)."""
        n = self
        seen: set[int] = set()
        while n is not None and id(n) not in seen:
            seen.add(id(n))
            if n.kind == "row":
                return n.row
            n = n.parents[0] if n.parents else None
        return None


@dataclasses.dataclass(eq=False)
class Sink:
    """One shaped ``random_bits`` draw and the key node feeding it."""

    node: Node
    shape: tuple
    bit_width: int
    nbytes: int
    source: Source
    path: tuple  # ((cond_site_id, branch_ix), ...) outermost-first
    looped: bool  # key is loop-invariant across a scan/while body

    def descr(self) -> str:
        return self.node.descr()


@dataclasses.dataclass(eq=False)
class ProvenanceGraph:
    """Everything keyscope's rules need about one traced entry."""

    entry: str
    sinks: list = dataclasses.field(default_factory=list)
    folds: list = dataclasses.field(default_factory=list)  # every fold Node
    roots: list = dataclasses.field(default_factory=list)  # every root Node

    def sink_descrs(self) -> tuple:
        """Sorted multiset of sink descriptors — the KB604 fingerprint."""
        return tuple(sorted(s.descr() for s in self.sinks))


# -- raw-key shadows ---------------------------------------------------------


@dataclasses.dataclass(eq=False)
class _Raw:
    """Unwrapped u32 bytes of ``node`` (possibly the whole split block)."""

    node: Node


@dataclasses.dataclass(eq=False)
class _RowRaw:
    """Raw bytes of row ``row`` sliced out of an unwrapped split."""

    split: Node
    row: int


# -- the walker --------------------------------------------------------------


class _Walker:
    def __init__(self, entry_name: str):
        self.graph = ProvenanceGraph(entry_name)
        self._splits: dict[tuple, Node] = {}  # (id(parent), m) -> Node
        self._rows: dict[tuple, Node] = {}  # (id(split), row) -> Node
        self._folds: dict[tuple, Node] = {}  # (id(parent), const) -> Node
        self._cond_sites = 0

    # -- node constructors (canonicalising) --

    def _root(self, kind: str, taint: frozenset, src: Source) -> Node:
        n = Node(kind, (), taint, src=src)
        self.graph.roots.append(n)
        return n

    def _split(self, parent: Node, m: int, src: Source) -> Node:
        key = (id(parent), m)
        if key not in self._splits:
            self._splits[key] = Node(
                "split", (parent,), parent.taint, looped=parent.looped, m=m, src=src
            )
        return self._splits[key]

    def _row_node(self, split: Node, row: int) -> Node:
        key = (id(split), row)
        if key not in self._rows:
            self._rows[key] = Node(
                "row", (split,), split.taint, looped=split.looped, row=row
            )
        return self._rows[key]

    def _fold(self, parent: Node, const, taint: frozenset, fresh: bool, src: Source) -> Node:
        if const is not None:
            key = (id(parent), const)
            if key not in self._folds:
                n = Node(
                    "fold", (parent,), parent.taint, looped=parent.looped,
                    const=const, src=src,
                )
                self._folds[key] = n
                self.graph.folds.append(n)
            return self._folds[key]
        # Traced fold operand: never canonicalised (values unknowable), and
        # folding in per-iteration data (carry/xs-derived) breaks loop
        # invariance.
        n = Node(
            "fold", (parent,), parent.taint | taint,
            looped=parent.looped and not fresh, src=src,
        )
        self.graph.folds.append(n)
        return n

    # -- env helpers --

    @staticmethod
    def _read(env: dict, v):
        return None if _literal_value(v) is not None else env["nodes"].get(v)

    @staticmethod
    def _taint_of(env: dict, v) -> frozenset:
        if _literal_value(v) is not None:
            return frozenset()
        return env["taint"].get(v, frozenset())

    @staticmethod
    def _fresh_of(env: dict, v) -> bool:
        if _literal_value(v) is not None:
            return False
        return v in env["fresh"]

    def _rootify(self, env: dict, v, src: Source) -> Node:
        """A key value with no tracked lineage: it *enters* here.

        A var that reached us as a loop-body const is raw key bytes frozen
        across iterations — root it looped (KB601's single-sink case)."""
        taint = self._taint_of(env, v)
        kind = "carried_key" if taint else "const_key"
        n = self._root(kind, taint, src)
        if v in env["loop_consts"]:
            n.looped = True
        env["nodes"][v] = n
        return n

    def _node_of(self, env: dict, v, src: Source) -> Node:
        tracked = self._read(env, v)
        if isinstance(tracked, Node):
            return tracked
        if isinstance(tracked, _Raw):
            return tracked.node
        if isinstance(tracked, _RowRaw):
            return self._row_node(tracked.split, tracked.row)
        return self._rootify(env, v, src)

    # -- main walk --

    def walk(self, jaxpr, env: dict, path: tuple) -> None:
        for eqn in jaxpr.eqns:
            self._eqn(jaxpr, eqn, env, path)

    def _eqn(self, jaxpr, eqn, env: dict, path: tuple) -> None:
        name = eqn.primitive.name
        taint = frozenset().union(*(self._taint_of(env, v) for v in eqn.invars)) if eqn.invars else frozenset()
        fresh = any(self._fresh_of(env, v) for v in eqn.invars)
        for ov in eqn.outvars:
            env["taint"][ov] = taint
            if fresh:
                env["fresh"].add(ov)

        if name == "random_seed":
            op = eqn.invars[0]
            op_taint = self._taint_of(env, op)
            kind = "counter_seed" if op_taint else "const_seed"
            env["nodes"][eqn.outvars[0]] = self._root(kind, op_taint, source_of(eqn))
            return
        if name == "random_wrap":
            tracked = self._read(env, eqn.invars[0])
            if isinstance(tracked, _Raw):
                env["nodes"][eqn.outvars[0]] = tracked.node
            elif isinstance(tracked, _RowRaw):
                env["nodes"][eqn.outvars[0]] = self._row_node(tracked.split, tracked.row)
            elif isinstance(tracked, Node):
                env["nodes"][eqn.outvars[0]] = tracked
            elif _literal_value(eqn.invars[0]) is None:
                # Root the *invar*, not the outvar: re-wrapping the same raw
                # u32 var (every jax.random call wraps afresh) must alias to
                # one node or identity-based KB601 goes blind.
                env["nodes"][eqn.outvars[0]] = self._rootify(
                    env, eqn.invars[0], source_of(eqn)
                )
            else:
                self._rootify(env, eqn.outvars[0], source_of(eqn))
            return
        if name == "random_unwrap":
            env["nodes"][eqn.outvars[0]] = _Raw(self._node_of(env, eqn.invars[0], source_of(eqn)))
            return
        if name == "random_split":
            parent = self._node_of(env, eqn.invars[0], source_of(eqn))
            shape = eqn.params.get("shape") or getattr(eqn.outvars[0].aval, "shape", (2,))
            m = int(shape[0]) if shape else 2
            env["nodes"][eqn.outvars[0]] = self._split(parent, m, source_of(eqn))
            return
        if name == "random_fold_in":
            parent = self._node_of(env, eqn.invars[0], source_of(eqn))
            data = eqn.invars[1]
            const = _literal_value(data)
            if const is None:
                const = env["constval"].get(data)
            if const is not None:
                try:
                    const = int(const)
                except Exception:
                    pass
            env["nodes"][eqn.outvars[0]] = self._fold(
                parent, const, self._taint_of(env, data), self._fresh_of(env, data),
                source_of(eqn),
            )
            return
        if name == "random_bits":
            node = self._node_of(env, eqn.invars[0], source_of(eqn))
            aval = getattr(eqn.outvars[0], "aval", None)
            self.graph.sinks.append(
                Sink(
                    node,
                    tuple(getattr(aval, "shape", ())),
                    int(eqn.params.get("bit_width", 32)),
                    aval_nbytes(aval) if aval is not None else 0,
                    source_of(eqn),
                    path,
                    node.looped,
                )
            )
            return

        if name == "select_n":
            # Batched ``lax.cond`` (vmapped engines: the fleet/serve tick)
            # lowers key selection to ``select_n`` over the unwrapped u32
            # bytes. Operand 0 is the predicate; merge the data operands so
            # the counter chains survive vmap instead of re-rooting as a
            # fresh carried_key at the next wrap.
            cands = [self._read(env, v) for v in eqn.invars[1:]]
            resolved, raw = [], False
            for c in cands:
                if isinstance(c, Node):
                    resolved.append(c)
                elif isinstance(c, _Raw):
                    resolved.append(c.node)
                    raw = True
                elif isinstance(c, _RowRaw):
                    resolved.append(self._row_node(c.split, c.row))
                    raw = True
            if resolved:
                first = resolved[0]
                if all(r is first for r in resolved) and len(resolved) == len(cands):
                    out = first
                else:
                    out = Node(
                        "merge", tuple(resolved),
                        frozenset().union(*(r.taint for r in resolved)),
                        looped=all(r.looped for r in resolved),
                    )
                env["nodes"][eqn.outvars[0]] = _Raw(out) if raw else out
            return

        # Raw-byte plumbing: keep the u32 shadow alive through moves.
        if name == "slice":
            tracked = self._read(env, eqn.invars[0])
            if isinstance(tracked, _Raw) and tracked.node.kind == "split":
                start = eqn.params.get("start_indices", ())
                limit = eqn.params.get("limit_indices", ())
                if len(start) >= 1 and limit and int(limit[0]) == int(start[0]) + 1:
                    env["nodes"][eqn.outvars[0]] = _RowRaw(tracked.node, int(start[0]))
                    return
            if tracked is not None:
                env["nodes"][eqn.outvars[0]] = tracked
            return
        if name in _RAW_TRANSPARENT:
            tracked = self._read(env, eqn.invars[0])
            if tracked is not None:
                env["nodes"][eqn.outvars[0]] = tracked
            return
        if name == "convert_element_type":
            lit = _literal_value(eqn.invars[0])
            if lit is None:
                lit = env["constval"].get(eqn.invars[0])
            if lit is not None:
                env["constval"][eqn.outvars[0]] = lit
            tracked = self._read(env, eqn.invars[0])
            if tracked is not None:
                env["nodes"][eqn.outvars[0]] = tracked
            return

        if name in _CALL_PRIMS:
            self._call(eqn, env, path, name)
            return
        if name == "cond":
            self._cond(eqn, env, path)
            return
        if name == "scan":
            self._scan(eqn, env, path)
            return
        if name == "while":
            self._while(eqn, env, path)
            return

    # -- higher-order primitives --

    @staticmethod
    def _inner(params_val):
        return getattr(params_val, "jaxpr", params_val)  # ClosedJaxpr -> Jaxpr

    def _seed_consts(self, inner, env: dict) -> None:
        for cv in getattr(inner, "constvars", ()):
            env["taint"].setdefault(cv, frozenset())

    def _call(self, eqn, env: dict, path: tuple, name: str) -> None:
        key = _CALL_PRIMS[name]
        if key is None:  # identity-style (sharding_constraint)
            tracked = self._read(env, eqn.invars[0])
            if tracked is not None:
                env["nodes"][eqn.outvars[0]] = tracked
            return
        sub = eqn.params.get(key)
        if sub is None:
            return
        inner = self._inner(sub)
        if not hasattr(inner, "eqns"):
            return
        for pos, v in enumerate(inner.invars):
            if pos >= len(eqn.invars):
                break
            ov = eqn.invars[pos]
            env["taint"][v] = self._taint_of(env, ov)
            if self._fresh_of(env, ov):
                env["fresh"].add(v)
            tracked = self._read(env, ov)
            if tracked is not None:
                env["nodes"][v] = tracked
            elif _is_key_aval(getattr(v, "aval", None)):
                env["nodes"][v] = self._node_of(env, ov, source_of(eqn))
        self._seed_consts(inner, env)
        self.walk(inner, env, path)
        for pos, ov in enumerate(eqn.outvars):
            if pos >= len(inner.outvars):
                break
            iv = inner.outvars[pos]
            env["taint"][ov] = self._taint_of(env, iv)
            if self._fresh_of(env, iv):
                env["fresh"].add(ov)
            tracked = self._read(env, iv)
            if tracked is not None:
                env["nodes"][ov] = tracked

    def _cond(self, eqn, env: dict, path: tuple) -> None:
        branches = eqn.params.get("branches") or ()
        site = self._cond_sites = self._cond_sites + 1
        operands = eqn.invars[1:]  # invars = [index, *operands]
        per_branch_outs: list[list] = []
        for bi, br in enumerate(branches):
            inner = self._inner(br)
            if not hasattr(inner, "eqns"):
                per_branch_outs.append([None] * len(eqn.outvars))
                continue
            for pos, v in enumerate(inner.invars):
                if pos >= len(operands):
                    break
                ov = operands[pos]
                env["taint"][v] = self._taint_of(env, ov)
                if self._fresh_of(env, ov):
                    env["fresh"].add(v)
                tracked = self._read(env, ov)
                if tracked is not None:
                    env["nodes"][v] = tracked
                elif _is_key_aval(getattr(v, "aval", None)):
                    env["nodes"][v] = self._node_of(env, ov, source_of(eqn))
            self._seed_consts(inner, env)
            self.walk(inner, env, path + ((site, bi),))
            outs = []
            for iv in inner.outvars:
                outs.append(self._read(env, iv))
            per_branch_outs.append(outs)
        for pos, ov in enumerate(eqn.outvars):
            cands = [outs[pos] for outs in per_branch_outs if pos < len(outs)]
            nodes = [c for c in cands if c is not None]
            if not nodes:
                continue
            resolved = []
            for c in nodes:
                if isinstance(c, Node):
                    resolved.append(c)
                elif isinstance(c, _Raw):
                    resolved.append(c.node)
                elif isinstance(c, _RowRaw):
                    resolved.append(self._row_node(c.split, c.row))
            if not resolved:
                continue
            first = resolved[0]
            if all(r is first for r in resolved) and len(resolved) == len(cands):
                out_obj = nodes[0]
            else:
                out_obj = Node(
                    "merge", tuple(resolved),
                    frozenset().union(*(r.taint for r in resolved)),
                    looped=all(r.looped for r in resolved),
                )
            env["nodes"][ov] = out_obj

    def _loop_body(self, inner, eqn, env, path, n_consts: int, n_carry: int) -> list:
        """Map a scan/while body's invars, walk it, return its out-tracks.

        Consts keep their outer node (loop-invariant: ``looped`` flips on
        any key node entering this way); carries become fresh per-iteration
        ``carry`` nodes; xs slices are per-iteration fresh values."""
        invars = list(inner.invars)
        for pos, v in enumerate(invars):
            if pos >= len(eqn.invars):
                break
            ov = eqn.invars[pos]
            env["taint"][v] = self._taint_of(env, ov)
            tracked = self._read(env, ov)
            if pos < n_consts:
                if tracked is None and _is_key_aval(getattr(v, "aval", None)):
                    tracked = self._node_of(env, ov, source_of(eqn))
                if tracked is not None:
                    env["nodes"][v] = self._mark_looped(tracked)
                else:
                    # Possibly raw key bytes (untrackable until wrapped in
                    # the body): remember the const-ness for _rootify.
                    env["loop_consts"].add(v)
                if self._fresh_of(env, ov):
                    env["fresh"].add(v)
            elif pos < n_consts + n_carry:
                env["fresh"].add(v)
                base = None
                if tracked is None and _is_key_aval(getattr(v, "aval", None)):
                    base = self._node_of(env, ov, source_of(eqn))
                elif isinstance(tracked, Node):
                    base = tracked
                elif isinstance(tracked, _Raw):
                    base = tracked.node
                elif isinstance(tracked, _RowRaw):
                    base = self._row_node(tracked.split, tracked.row)
                if base is not None:
                    carry = Node("carry", (base,), base.taint, src=source_of(eqn))
                    env["nodes"][v] = _Raw(carry) if isinstance(tracked, (_Raw, _RowRaw)) else carry
            else:
                env["fresh"].add(v)
        self._seed_consts(inner, env)
        self.walk(inner, env, path)
        return [self._read(env, iv) for iv in inner.outvars]

    def _mark_looped(self, tracked):
        def loop_node(n: Node) -> Node:
            if n.looped:
                return n
            return Node(n.kind, n.parents, n.taint, looped=True, m=n.m, row=n.row, const=n.const, src=n.src)

        if isinstance(tracked, Node):
            return loop_node(tracked)
        if isinstance(tracked, _Raw):
            return _Raw(loop_node(tracked.node))
        if isinstance(tracked, _RowRaw):
            return tracked  # row canonicalisation would lose the flag; rare
        return tracked

    def _scan(self, eqn, env: dict, path: tuple) -> None:
        inner = self._inner(eqn.params.get("jaxpr"))
        if not hasattr(inner, "eqns"):
            return
        n_consts = int(eqn.params.get("num_consts", 0))
        n_carry = int(eqn.params.get("num_carry", 0))
        outs = self._loop_body(inner, eqn, env, path, n_consts, n_carry)
        # eqn outvars = [final_carry x n_carry, stacked ys]; body outvars
        # = [carry x n_carry, ys]. Final carry keeps the body's node (one
        # more iteration of the same chain); ys get stacked.
        for pos, ov in enumerate(eqn.outvars):
            if pos >= len(outs):
                break
            tracked = outs[pos]
            if tracked is None:
                continue
            if pos < n_carry:
                env["nodes"][ov] = tracked
            else:
                if isinstance(tracked, _Raw):
                    base = tracked.node
                elif isinstance(tracked, _RowRaw):
                    base = self._row_node(tracked.split, tracked.row)
                else:
                    base = tracked
                stack = Node("stack", (base,), base.taint)
                env["nodes"][ov] = _Raw(stack) if isinstance(tracked, (_Raw, _RowRaw)) else stack

    def _while(self, eqn, env: dict, path: tuple) -> None:
        cond_j = self._inner(eqn.params.get("cond_jaxpr"))
        body_j = self._inner(eqn.params.get("body_jaxpr"))
        cn = int(eqn.params.get("cond_nconsts", 0))
        bn = int(eqn.params.get("body_nconsts", 0))
        n_carry = len(eqn.invars) - cn - bn
        if hasattr(cond_j, "eqns"):
            # cond sees [cond_consts..., carry...]: remap a pseudo-eqn view.
            class _V:  # noqa: N801 - tiny positional shim
                invars = list(eqn.invars[:cn]) + list(eqn.invars[cn + bn:])
            self._loop_body(cond_j, _V, env, path, cn, n_carry)
        if hasattr(body_j, "eqns"):
            class _W:  # noqa: N801
                invars = list(eqn.invars[cn:cn + bn]) + list(eqn.invars[cn + bn:])
            outs = self._loop_body(body_j, _W, env, path, bn, n_carry)
            for pos, ov in enumerate(eqn.outvars):
                if pos < len(outs) and outs[pos] is not None:
                    env["nodes"][ov] = outs[pos]


def build_provenance(entry_name: str, closed_jaxpr) -> ProvenanceGraph:
    """Walk one traced entry and return its key-provenance graph."""
    walker = _Walker(entry_name)
    jaxpr = closed_jaxpr.jaxpr
    env: dict = {
        "taint": {}, "nodes": {}, "constval": {}, "fresh": set(),
        "loop_consts": set(),
    }
    for i, v in enumerate(jaxpr.invars):
        env["taint"][v] = frozenset({i})
        if _is_key_aval(getattr(v, "aval", None)):
            env["nodes"][v] = walker._root("carried_key", frozenset({i}), Source("<arg>", 0))
    for cv in getattr(jaxpr, "constvars", ()):
        env["taint"][cv] = frozenset()
    walker.walk(jaxpr, env, ())
    return walker.graph


def iter_sinks(graphs) -> Iterator[Sink]:
    for g in graphs:
        yield from g.sinks
