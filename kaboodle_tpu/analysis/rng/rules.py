"""KB601-605: the keyscope checks over per-entry provenance graphs.

Per-graph rules follow the graftscan pass shape — ``check(graph) ->
[Finding]`` with ``ir://<entry>`` pseudo-paths and line-free symbols (one
justified baseline entry covers a finding class and survives unrelated
edits). KB602 additionally has a trace-free registry half (the pinned
``KEYSCOPE_STREAMS`` table vs the live ``phasegraph.rng`` constants —
double-entry bookkeeping, so a renumbering or swap trips the lane even
when the swapped streams still trace to a collision-free set), and KB604
is a cross-entry rule over the whole scanned set.
"""

from __future__ import annotations

from kaboodle_tpu.analysis.core import Finding
from kaboodle_tpu.analysis.rng.provenance import ProvenanceGraph, Sink

# -- the pinned stream table (KB602's second ledger) ------------------------

# keyscope's own copy of phasegraph/rng.py's STREAM_* registry (the
# canonical counter-RNG module; sparseplane/rng.py re-exports it). The two
# are compared verbatim on every rng run: ids must be dense from 0, in
# append-only order, and value-identical. A new randomized phase appends
# to BOTH (this tuple and rng.py) — that dual edit is the mechanical form
# of rng.py's "new phases append, renumbering changes every banked run".
# Ids 0-5 are the sparse tick's (seed, cursor) streams; 6-9 are the dense
# tick's (key, tick) streams (the Warp 3.0 counter-keyed migration of the
# legacy KEY_LAYOUT split rows).
KEYSCOPE_STREAMS = (
    ("STREAM_PROXY", 0),
    ("STREAM_CHAIN", 1),
    ("STREAM_DRAW", 2),
    ("STREAM_PING", 3),
    ("STREAM_ACK", 4),
    ("STREAM_GOSSIP", 5),
    ("STREAM_TICK_PROXY", 6),
    ("STREAM_TICK_PING", 7),
    ("STREAM_TICK_BERN", 8),
    ("STREAM_TICK_DROP", 9),
)

_STREAMS_PATH = "rng://sparseplane.streams"

# Dense-tick stream id -> the legacy KEY_LAYOUT row name the stream
# replaced. The leap report uses this to keep naming the migrated
# (now counter-keyed) sinks by their warp rows, so WARP_TERMS joins
# survive the re-keying.
TICK_STREAM_ROWS = {6: "proxy", 7: "ping", 8: "bern", 9: "drop"}

# -- KB604: declared cross-engine fates -------------------------------------

# Entries derived from the same op graph whose key-provenance fingerprints
# (sorted sink-descriptor multisets) must be identical: bit-exactness
# between these engines is pinned by the phasegraph dryrun, and a
# provenance divergence is the compile-time shadow of a numeric diff.
# Entries NOT grouped here have *declared* divergent fates (tick.random
# alone runs the fully randomized phase config; blocked folds per-block
# data onto the row keys — its own group against its telemetry twin) —
# the groups are the pinned subset, and growing one is a deliberate edit.
CHAIN_GROUPS: tuple[tuple[str, tuple[str, ...]], ...] = (
    # The dense drop-matrix engines: one draw, the KEY_LAYOUT `drop` row.
    (
        "dense-drop",
        (
            "phasegraph.tick.faulty",
            "phasegraph.tick.fused",
            "phasegraph.tick.telemetry",
        ),
    ),
    # Engines whose traced config exercises no randomized phase at all:
    # a draw appearing in ONE of these is a provenance event, not a tweak.
    (
        "dense-drawfree",
        (
            "phasegraph.tick.faultfree",
            "phasegraph.tick.lean",
            "phasegraph.tick.sharded",
            "phasegraph.tick.telemetry.lean",
        ),
    ),
    (
        "blocked",
        ("phasegraph.tick.blocked", "phasegraph.tick.blocked.telemetry"),
    ),
    (
        "fleet",
        (
            "phasegraph.tick.fleet",
            "phasegraph.tick.fleet.sharded",
            "phasegraph.tick.fleet.telemetry",
        ),
    ),
    # The sparse pair must present the full six-stream counter discipline.
    (
        "sparse",
        ("phasegraph.tick.sparse", "phasegraph.tick.sparse.lean"),
    ),
    # Leap/span programs are draw-free by construction (det pick chains);
    # a sink here means the span program started consuming randomness the
    # warp classes cannot model.
    (
        "leap",
        (
            "phasegraph.leap",
            "phasegraph.leap.lean",
            "phasegraph.leap.hybrid",
            "phasegraph.leap.hybrid.lean",
            "phasegraph.leap.fleet",
            "phasegraph.leap.sharded",
        ),
    ),
    (
        "serve-step",
        (
            "phasegraph.serve.step",
            "phasegraph.serve.step.telemetry",
            "phasegraph.serve.step.sharded",
        ),
    ),
)

# -- KB605: leapability vocabulary ------------------------------------------

# KEY_LAYOUT row -> the warp signature terms (warp/horizon.py decode
# vocabulary, plus the runner's pseudo-terms) whose activity the draw
# serves. The leap report joins chain-coupled sinks to these terms so the
# why-dense histogram (warp runner ledger) and the which-draw-blocks
# worklist (this lane) speak the same names.
WARP_TERMS = {
    "proxy": ("any_a2",),  # escalation fan-out: suspicion matured
    "ping": ("probe_draw",),  # every dense tick's probe target pick
    "bern": ("any_join", "missing_alive"),  # delivery/gossip bernoulli field
    "drop": ("delivery_gate",),  # the [N, N] drop matrix (faulty builds)
    "next": (),  # the chain carry itself — never drawn
}

CLASS_COUNTER = "counter_keyed"
CLASS_CHAIN = "chain_coupled"
CLASS_IMPURE = "impure"


def classify(sink: Sink) -> str:
    """KB605 class of one draw sink.

    ``counter_keyed`` — provenance bottoms out ONLY in ``random_seed`` on
    argument-derived counters: the draw is a pure function of
    checkpointable ``(seed, cursor)`` state and leaps for free
    (memoization/fast-forwarding, PAPERS.md 2602.10615).
    ``chain_coupled`` — a carried split-chain key feeds it: reproducing
    tick T's draw requires advancing the chain T times, which is exactly
    what keeps the drain seasons dense (ROADMAP item 2).
    ``impure`` — const-rooted key material (KB603 fires separately)."""
    roots = sink.node.roots()
    if roots & {"const_key", "const_seed"}:
        return CLASS_IMPURE
    if "carried_key" in roots or not roots:
        return CLASS_CHAIN
    return CLASS_COUNTER


# -- KB601 ------------------------------------------------------------------


def _paths_exclusive(a: tuple, b: tuple) -> bool:
    """True when two sinks sit in different branches of a shared cond —
    mutually exclusive at runtime, so not reuse (the dispatched dense
    build keeps its full and fused programs under one ``lax.cond``, both
    drawing the same layout rows)."""
    branch_a = dict(a)
    for site, bi in b:
        if site in branch_a and branch_a[site] != bi:
            return True
    return False


def check_kb601_key_reuse(graph: ProvenanceGraph) -> list[Finding]:
    """Two reachable draws on one unforked key (or one draw looped)."""
    out: dict[str, Finding] = {}
    by_node: dict[int, list[Sink]] = {}
    for s in graph.sinks:
        by_node.setdefault(id(s.node), []).append(s)
    for sinks in by_node.values():
        clash = None
        for i, a in enumerate(sinks):
            for b in sinks[i + 1 :]:
                if not _paths_exclusive(a.path, b.path):
                    clash = (a, b)
                    break
            if clash:
                break
        if clash is None:
            continue
        a, b = clash
        symbol = f"reuse:{a.descr()}"
        sites = " + ".join(sorted({a.source.render(), b.source.render()}))
        out[symbol] = Finding(
            f"ir://{graph.entry}",
            "KB601",
            a.source.line,
            f"key {a.descr()} feeds two reachable draws ({sites}) without "
            "an intervening split/fold_in — identical threefry streams; "
            "fork the key once per consumer",
            symbol,
        )
    for s in graph.sinks:
        if not s.looped:
            continue
        symbol = f"looped:{s.descr()}"
        if symbol in out:
            continue
        out[symbol] = Finding(
            f"ir://{graph.entry}",
            "KB601",
            s.source.line,
            f"loop-invariant key {s.descr()} drawn inside a scan/while body "
            f"({s.source.render()}) — every iteration redraws the same "
            "stream; fold the iteration counter in first",
            symbol,
        )
    return list(out.values())


# -- KB602 ------------------------------------------------------------------


def check_kb602_stream_collision(graph: ProvenanceGraph) -> list[Finding]:
    """Two folds of one constant onto the same canonical parent.

    Grouping is by parent *descriptor*, not node identity: make_jaxpr does
    not CSE, so the sparse kernel's six ``stream_key`` chains are six
    textually separate seed->fold chains that only a canonical-name
    grouping can see side by side."""
    out: dict[str, Finding] = {}
    seen: dict[tuple, list] = {}
    for f in graph.folds:
        if f.const is None or not f.parents:
            continue
        seen.setdefault((f.parents[0].descr(), f.const), []).append(f)
    for (parent_descr, const), folds in seen.items():
        if len(folds) < 2:
            continue
        sites = " + ".join(
            sorted({f.src.render() for f in folds if f.src is not None})
        )
        symbol = f"collide:{parent_descr}:{const}"
        out[symbol] = Finding(
            f"ir://{graph.entry}",
            "KB602",
            folds[0].src.line if folds[0].src else 0,
            f"fold_in constant {const} applied to {parent_descr} at "
            f"{len(folds)} distinct sites ({sites}) — colliding streams "
            "draw identical randomness; give each phase its own STREAM_* id",
            symbol,
        )
    # Stream-position folds (literal fold onto a counter-seed chain) must
    # use registered ids: an unregistered constant is a phase drawing off
    # the books.
    registered = {v for _, v in KEYSCOPE_STREAMS}
    for f in graph.folds:
        if f.const is None or not f.parents:
            continue
        parent = f.parents[0]
        if parent.kind != "fold" or "counter_seed" not in parent.roots():
            continue
        if f.const in registered:
            continue
        symbol = f"unregistered:{f.const}"
        if symbol in out:
            continue
        out[symbol] = Finding(
            f"ir://{graph.entry}",
            "KB602",
            f.src.line if f.src else 0,
            f"counter-chain fold_in constant {f.const} "
            f"({f.src.render() if f.src else '<unknown>'}) is not a "
            "registered STREAM_* id — append it to phasegraph/rng.py AND "
            "keyscope's KEYSCOPE_STREAMS table",
            symbol,
        )
    return list(out.values())


def check_kb602_stream_registry() -> list[Finding]:
    """The pinned table vs the live phasegraph.rng constants (trace-free).

    Ids must be dense from 0 in append-only order and value-identical to
    ``KEYSCOPE_STREAMS`` — a swap keeps the traced fold constants
    collision-free and set-equal, so only this double-entry comparison
    catches it before a banked run diverges."""
    from kaboodle_tpu.phasegraph.rng import stream_table

    out: list[Finding] = []
    live = stream_table()
    pinned = dict(KEYSCOPE_STREAMS)
    for name, want in KEYSCOPE_STREAMS:
        got = live.get(name)
        if got != want:
            out.append(
                Finding(
                    _STREAMS_PATH,
                    "KB602",
                    0,
                    f"{name} is {got!r} live but pinned {want} in "
                    "KEYSCOPE_STREAMS — stream ids renumber banked draws; "
                    "ids append, they never move",
                    f"drift:{name}",
                )
            )
    for name in sorted(set(live) - set(pinned)):
        out.append(
            Finding(
                _STREAMS_PATH,
                "KB602",
                0,
                f"live stream {name}={live[name]} is not in keyscope's "
                "KEYSCOPE_STREAMS table — append it (new phases append to "
                "both ledgers)",
                f"unpinned:{name}",
            )
        )
    ids = sorted(live.values())
    if ids != list(range(len(ids))):
        out.append(
            Finding(
                _STREAMS_PATH,
                "KB602",
                0,
                f"live STREAM_* ids {ids} are not dense from 0 — gaps or "
                "duplicates mean a collision or a renumbering hazard",
                "not-dense",
            )
        )
    return out


# -- KB603 ------------------------------------------------------------------


def check_kb603_resume_impurity(graph: ProvenanceGraph) -> list[Finding]:
    """Draws whose provenance does not bottom out in entry arguments.

    A ``const_seed`` (``PRNGKey(0)`` baked into the trace) or ``const_key``
    root means the draw replays identically on every resume regardless of
    the checkpoint — the exact property sparseplane's counter discipline
    exists to prevent, and a hard gate for item 2's per-row re-keying."""
    out: dict[str, Finding] = {}
    for s in graph.sinks:
        bad = s.node.roots() & {"const_key", "const_seed"}
        if not bad:
            continue
        symbol = f"impure:{s.descr()}"
        if symbol in out:
            continue
        out[symbol] = Finding(
            f"ir://{graph.entry}",
            "KB603",
            s.source.line,
            f"draw {s.descr()} ({s.source.render()}) roots in "
            f"{sorted(bad)} — key material baked into the program, not the "
            "checkpointable state planes; thread it through the entry "
            "arguments (state key or (seed, cursor) counters)",
            symbol,
        )
    return list(out.values())


# -- KB604 ------------------------------------------------------------------


def check_kb604_chain_divergence(graphs: dict[str, ProvenanceGraph]) -> list[Finding]:
    """Pinned-isomorphic engine groups must fingerprint identically.

    Runs over whatever subset of the registry was scanned; a group with
    fewer than two present members is skipped (scoped ``--entries`` runs
    stay meaningful)."""
    out: list[Finding] = []
    for group_name, members in CHAIN_GROUPS:
        present = [m for m in members if m in graphs]
        if len(present) < 2:
            continue
        ref_name = present[0]
        ref = graphs[ref_name].sink_descrs()
        for other in present[1:]:
            got = graphs[other].sink_descrs()
            if got == ref:
                continue
            missing = sorted(set(ref) - set(got))
            extra = sorted(set(got) - set(ref))
            delta = []
            if missing:
                delta.append(f"missing {missing}")
            if extra:
                delta.append(f"extra {extra}")
            out.append(
                Finding(
                    f"ir://{other}",
                    "KB604",
                    0,
                    f"provenance diverges from '{ref_name}' within pinned "
                    f"group '{group_name}': {'; '.join(delta) or 'multiset mismatch'} "
                    "— engines derived from one op graph must fork keys "
                    "identically wherever bit-exactness is pinned",
                    f"diverge:{group_name}",
                )
            )
    return out


PER_GRAPH_CHECKS = (
    check_kb601_key_reuse,
    check_kb602_stream_collision,
    check_kb603_resume_impurity,
)
