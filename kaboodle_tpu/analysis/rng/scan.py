"""The keyscope driver: trace, build provenance, rule, bank the leap report.

``run_rng_scan`` mirrors graftscan's ``run_scan``: trace every selected
registry entry (x32 — the production program), build its key-provenance
graph, run the per-graph KB601-603 checks, the trace-free KB602 registry
comparison and the cross-entry KB604 fingerprint check, and return ALL
findings pre-baseline so the CLI applies the shared shrink-only plumbing.

The leap report (KB605's artifact) is a deterministic JSON classifying
every draw sink in every engine — site, shape, leapability class,
KEY_LAYOUT row, warp signature terms, draw bytes — joined with the
costscope per-entry ``bytes_accessed`` so the chain-coupled sites of the
dense drain seasons arrive byte-attributed: ROADMAP item 2's migration
worklist, measured instead of guessed. ``leap_findings`` gates freshness
against the committed copy (``make rng-dryrun`` / CI), same ratchet shape
as the costscope baseline.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Sequence

from kaboodle_tpu.analysis.core import Finding
from kaboodle_tpu.analysis.ir.registry import EntryPoint, select_entries, trace_entry
from kaboodle_tpu.analysis.ir.scan import _prepare_backend
from kaboodle_tpu.analysis.rng import rules
from kaboodle_tpu.analysis.rng.provenance import ProvenanceGraph, build_provenance

DEFAULT_LEAP_REPORT = "KEYSCOPE_LEAP.json"
LEAP_SCHEMA = "kaboodle-keyscope-leap/1"
_LEAP_PATH = "rng://leap-report"


@dataclasses.dataclass
class RngScanResult:
    findings: list[Finding]
    graphs: dict[str, ProvenanceGraph]
    entries_scanned: int


def run_rng_scan(
    entry_names: Sequence[str] | None = None,
    entries: Sequence[EntryPoint] | None = None,
    progress=None,
) -> RngScanResult:
    """Audit the registry's key provenance (or an injected subset)."""
    _prepare_backend()
    chosen = entries if entries is not None else select_entries(entry_names)
    findings: list[Finding] = list(rules.check_kb602_stream_registry())
    graphs: dict[str, ProvenanceGraph] = {}
    for entry in chosen:
        if progress:
            progress(f"keyscope: tracing {entry.name}")
        graph = build_provenance(entry.name, trace_entry(entry, x64=False))
        graphs[entry.name] = graph
        for check in rules.PER_GRAPH_CHECKS:
            findings.extend(check(graph))
    findings.extend(rules.check_kb604_chain_divergence(graphs))
    findings.sort(key=lambda f: (f.path, f.rule, f.symbol))
    return RngScanResult(findings, graphs, len(chosen))


# -- the leap report ---------------------------------------------------------


def _layout_name(sink) -> str | None:
    """Draw-row name of a sink (None when it has no row identity).

    Dense-chain sinks are named by their split-row coordinate
    (``KEY_LAYOUT[row]``); counter-keyed tick draws (Warp 3.0) by the
    ``STREAM_TICK_*`` fold constant nearest the sink — the same four row
    names, so per-row warp_terms attribution survives the migration off
    the split chain. Sparse (seed, cursor, stream) sinks keep a null row:
    their streams are per-op lanes, not tick-split rows."""
    from kaboodle_tpu.phasegraph.ops import KEY_LAYOUT

    roots = sink.node.roots()
    if "carried_key" in roots:
        row = sink.node.layout_row()
        if row is None or row >= len(KEY_LAYOUT):
            return None
        return KEY_LAYOUT[row]
    if "counter_seed" in roots:
        n, seen = sink.node, set()
        while n is not None and id(n) not in seen:
            seen.add(id(n))
            if n.kind == "fold" and isinstance(n.const, int):
                if n.const in rules.TICK_STREAM_ROWS:
                    return rules.TICK_STREAM_ROWS[n.const]
            n = n.parents[0] if n.parents else None
    return None


def build_leap_report(
    graphs: dict[str, ProvenanceGraph],
    costscope_path: str | Path | None = None,
) -> dict:
    """Deterministic KB605 classification of every sink in ``graphs``.

    No timestamps, no ids — two runs over the same code produce the same
    bytes, which is what lets CI diff the committed copy."""
    cost: dict[str, int] = {}
    if costscope_path is not None:
        try:
            from kaboodle_tpu.costscope.baseline import load_baseline

            data = load_baseline(costscope_path)
            if data is not None:
                cost = {
                    name: int(rec.get("bytes_accessed", 0))
                    for name, rec in data["entries"].items()
                }
        except Exception:
            cost = {}

    entries: dict[str, dict] = {}
    totals = {
        rules.CLASS_CHAIN: 0,
        rules.CLASS_COUNTER: 0,
        rules.CLASS_IMPURE: 0,
        "chain_coupled_draw_bytes": 0,
    }
    for name in sorted(graphs):
        graph = graphs[name]
        sinks = []
        counts = {rules.CLASS_CHAIN: 0, rules.CLASS_COUNTER: 0, rules.CLASS_IMPURE: 0}
        for s in sorted(
            graph.sinks, key=lambda s: (s.source.file, s.source.line, s.descr())
        ):
            cls = rules.classify(s)
            counts[cls] += 1
            totals[cls] += 1
            if cls == rules.CLASS_CHAIN:
                totals["chain_coupled_draw_bytes"] += s.nbytes
            layout = _layout_name(s)
            sinks.append(
                {
                    "site": s.source.render(),
                    "key": s.descr(),
                    "shape": list(s.shape),
                    "bit_width": s.bit_width,
                    "draw_bytes": s.nbytes,
                    "class": cls,
                    "roots": sorted(s.node.roots()),
                    "layout_row": layout,
                    "warp_terms": list(rules.WARP_TERMS.get(layout, ()))
                    if layout
                    else [],
                }
            )
        entries[name] = {
            "sinks": sinks,
            "chain_coupled": counts[rules.CLASS_CHAIN],
            "counter_keyed": counts[rules.CLASS_COUNTER],
            "impure": counts[rules.CLASS_IMPURE],
            "entry_bytes_accessed": cost.get(name, 0),
        }
    return {
        "schema": LEAP_SCHEMA,
        "streams": {name: sid for name, sid in rules.KEYSCOPE_STREAMS},
        "entries": entries,
        "totals": totals,
    }


def write_leap_report(report: dict, path: str | Path = DEFAULT_LEAP_REPORT) -> None:
    Path(path).write_text(json.dumps(report, indent=1, sort_keys=True) + "\n")


def load_leap_report(path: str | Path = DEFAULT_LEAP_REPORT) -> dict | None:
    p = Path(path)
    if not p.exists():
        return None
    data = json.loads(p.read_text())
    if not isinstance(data, dict) or data.get("schema") != LEAP_SCHEMA:
        raise ValueError(f"{p}: not a {LEAP_SCHEMA} report")
    return data


def leap_findings(
    graphs: dict[str, ProvenanceGraph],
    path: str | Path = DEFAULT_LEAP_REPORT,
    costscope_path: str | Path | None = None,
) -> list[Finding]:
    """KB605 freshness gate: regenerate and diff against the committed copy.

    Only meaningful on a full-registry run — the caller (CLI) skips it for
    scoped ``--entries`` invocations. NOT baselineable: a stale report is
    fixed by regenerating it, never by justifying the staleness."""
    committed = load_leap_report(path)
    if committed is None:
        return [
            Finding(
                _LEAP_PATH,
                "KB605",
                0,
                f"no committed leap report at {path} — run the rng lane "
                "with --write-leap and commit it",
                "missing",
            )
        ]
    live = build_leap_report(graphs, costscope_path=costscope_path)
    findings: list[Finding] = []
    # Warp 3.0 shrink gate: the chain-coupled total is a ratchet. New
    # split-chain draw sites regress the counter-key migration (they
    # re-create exactly the dense seasons item 2 retired), so growth is a
    # hard finding even on an otherwise regenerated report — fixed by
    # deriving the new draw from phasegraph.rng counter keys, never by
    # committing the bigger number.
    c_chain = int(committed.get("totals", {}).get(rules.CLASS_CHAIN, 0))
    l_chain = int(live["totals"][rules.CLASS_CHAIN])
    if l_chain > c_chain:
        findings.append(
            Finding(
                _LEAP_PATH,
                "KB605",
                0,
                f"chain-coupled sink total grew {c_chain} -> {l_chain} — "
                "new draw sites fork the carried key chain; re-key them "
                "through phasegraph.rng counter streams instead of "
                "re-banking the report",
                "growth",
            )
        )
    if committed == live:
        return findings
    stale = []
    c_entries, l_entries = committed.get("entries", {}), live["entries"]
    for name in sorted(set(c_entries) | set(l_entries)):
        if c_entries.get(name) != l_entries.get(name):
            stale.append(name)
    detail = f"entries differ: {stale[:6]}" if stale else "header/totals differ"
    findings.append(
        Finding(
            _LEAP_PATH,
            "KB605",
            0,
            f"committed leap report is stale ({detail}) — the draw sites "
            "moved under it; regenerate with --write-leap and commit",
            "stale",
        )
    )
    return findings


def render_leap_report(report: dict) -> str:
    """Human table: per-entry class counts, then every chain-coupled site.

    The second half IS the item-2 worklist: which draws must move to the
    counter discipline before the drain seasons can leap, ordered by the
    bytes their entry touches per dispatch."""
    lines = ["keyscope leap report — draw-sink leapability by entry", ""]
    lines.append(
        f"{'entry':40s} {'chain':>6s} {'counter':>8s} {'impure':>7s} "
        f"{'entry bytes/dispatch':>21s}"
    )
    for name, rec in sorted(report["entries"].items()):
        lines.append(
            f"{name:40s} {rec['chain_coupled']:6d} {rec['counter_keyed']:8d} "
            f"{rec['impure']:7d} {rec['entry_bytes_accessed']:21,d}"
        )
    totals = report["totals"]
    lines.append("")
    lines.append(
        f"totals: {totals[rules.CLASS_CHAIN]} chain-coupled / "
        f"{totals[rules.CLASS_COUNTER]} counter-keyed / "
        f"{totals[rules.CLASS_IMPURE]} impure sinks; "
        f"{totals['chain_coupled_draw_bytes']:,d} chain-coupled draw bytes "
        "per full-registry dispatch"
    )
    chain_sites: dict[tuple, list] = {}
    for name, rec in sorted(report["entries"].items()):
        for s in rec["sinks"]:
            if s["class"] != rules.CLASS_CHAIN:
                continue
            key = (s["site"], s["layout_row"], tuple(s["warp_terms"]))
            chain_sites.setdefault(key, []).append((name, s["draw_bytes"]))
    lines.append("")
    lines.append("chain-coupled sites (ROADMAP item 2's re-keying worklist):")
    for (site, layout, terms), users in sorted(
        chain_sites.items(), key=lambda kv: (kv[0][0], kv[0][1] or "", kv[0][2])
    ):
        row = f"row={layout}" if layout else "row=?"
        term_s = ",".join(terms) or "-"
        total_bytes = sum(b for _, b in users)
        lines.append(
            f"  {site:28s} {row:10s} terms={term_s:22s} "
            f"entries={len(users):2d} draw_bytes={total_bytes:,d}"
        )
    return "\n".join(lines)
