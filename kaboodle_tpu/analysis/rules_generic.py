"""KB1xx — generic Python rules.

KB101/KB102 are the two checks ported verbatim-in-spirit from the original
``scripts/lint.py`` (which is now a shim over this package): the round-2
HEAD-breaking ``NameError`` class, and dead-import drift. KB103/KB104 are
the two classic foot-guns cheap enough to gate on with zero dependencies.
"""

from __future__ import annotations

import ast
import builtins

from kaboodle_tpu.analysis.core import Finding, Module, rule

IMPLICIT = {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__debug__", "__class__", "__annotations__",
}

BUILTINS = frozenset(dir(builtins))

# Builtins whose shadowing has actually caused grief in numeric codebases —
# not every builtin (shadowing `license` or `copyright` harms nobody).
SHADOW_RISK = frozenset({
    "abs", "all", "any", "bin", "bool", "bytes", "chr", "compile", "complex",
    "dict", "dir", "eval", "exec", "filter", "float", "format", "hash", "hex",
    "id", "input", "int", "iter", "len", "list", "map", "max", "min", "next",
    "object", "oct", "open", "ord", "pow", "print", "range", "repr", "round",
    "set", "slice", "sorted", "str", "sum", "tuple", "type", "vars", "zip",
})


def _collect_defined(tree: ast.AST) -> tuple[set, dict]:
    """All names bound anywhere (any scope), plus import bindings -> lineno.

    Scope approximation (inherited from scripts/lint.py): a name defined in
    *any* scope counts as defined everywhere in the module. That misses
    scope-escape bugs but has no false positives on idiomatic code — the
    right trade for a ``-D warnings`` style gate.
    """
    defined = set(BUILTINS) | IMPLICIT
    imports: dict[str, tuple[int, bool]] = {}  # name -> (lineno, is_future)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                defined.add(name)
                imports.setdefault(name, (node.lineno, False))
        elif isinstance(node, ast.ImportFrom):
            future = node.module == "__future__"
            for a in node.names:
                if a.name == "*":
                    continue
                name = a.asname or a.name
                defined.add(name)
                imports.setdefault(name, (node.lineno, future))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            defined.add(node.name)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store, ast.Del)):
            defined.add(node.id)
        elif isinstance(node, ast.arg):
            defined.add(node.arg)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            defined.add(node.name)
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            defined.update(node.names)
        elif isinstance(node, (ast.MatchAs, ast.MatchStar)) and node.name:
            defined.add(node.name)
        elif isinstance(node, ast.MatchMapping) and node.rest:
            defined.add(node.rest)
    return defined, imports


def _collect_used(tree: ast.AST) -> tuple[set, list]:
    """Names loaded anywhere + every (lineno, name) load for KB101."""
    used = set()
    loads = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            used.add(node.id)
            loads.append((node.lineno, node.id))
    # __all__ re-export strings count as uses (package __init__ pattern).
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "__all__" for t in node.targets
            )
            and isinstance(node.value, (ast.List, ast.Tuple))
        ):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    used.add(elt.value)
    return used, loads


@rule(
    "KB101",
    "undefined name",
    """
A loaded name is bound nowhere in the module (any scope) and is not a
builtin. This is the exact failure class that broke HEAD in round 2: a
module-level reference to a deleted/renamed function that no test imported
until CI did. Names bound in *any* scope count as defined everywhere
(no-false-positive approximation); suppress a deliberate late-bound name
with `# noqa: KB101`.
""",
)
def check_undefined_names(mod: Module) -> list[Finding]:
    defined, _ = _collect_defined(mod.tree)
    _, loads = _collect_used(mod.tree)
    return [
        Finding(mod.path, "KB101", lineno, f"undefined name '{name}'", name)
        for lineno, name in loads
        if name not in defined
    ]


@rule(
    "KB102",
    "unused import",
    """
An imported name is never loaded in the module (``__all__`` strings count
as loads; ``from __future__`` and ``_`` are exempt). Dead imports are the
most common dead-code drift and can hide real costs — importing a jax
extension pulls a backend. Suppress an intentional re-export or
side-effect import with `# noqa: KB102`.
""",
)
def check_unused_imports(mod: Module) -> list[Finding]:
    _, imports = _collect_defined(mod.tree)
    used, _ = _collect_used(mod.tree)
    out = []
    for name, (lineno, future) in imports.items():
        if future or name == "_" or name in used:
            continue
        out.append(Finding(mod.path, "KB102", lineno, f"unused import '{name}'", name))
    return out


@rule(
    "KB103",
    "mutable default argument",
    """
A function parameter defaults to a mutable literal (``[]``, ``{}``,
``set()``, ``list()``, ``dict()``). The default is evaluated once at def
time and shared across calls — state leaks between invocations. Use
``None`` + an in-body default. Suppress a deliberate shared-cache default
with `# noqa: KB103`.
""",
)
def check_mutable_defaults(mod: Module) -> list[Finding]:
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        name = getattr(node, "name", "<lambda>")
        for d in [*node.args.defaults, *node.args.kw_defaults]:
            if d is None:
                continue
            mutable = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(d, ast.Call)
                and isinstance(d.func, ast.Name)
                and d.func.id in ("list", "dict", "set")
                and not d.args
                and not d.keywords
            )
            if mutable:
                out.append(
                    Finding(
                        mod.path, "KB103", d.lineno,
                        f"mutable default argument in '{name}'", name,
                    )
                )
    return out


@rule(
    "KB104",
    "shadowed builtin",
    """
A binding (assignment, parameter, def/class name) reuses a builtin name
from the high-risk set (``id``, ``type``, ``sum``, ``bytes``, ``hash``,
``next``, ...). Later code in the same module that means the builtin gets
the shadow instead — in jit-adjacent code this typically surfaces as a
confusing trace-time TypeError far from the binding. Rename, or suppress
a deliberate local with `# noqa: KB104`.
""",
)
def check_shadowed_builtins(mod: Module) -> list[Finding]:
    out = []

    def flag(name: str, lineno: int) -> None:
        if name in SHADOW_RISK:
            out.append(
                Finding(
                    mod.path, "KB104", lineno, f"'{name}' shadows a builtin", name
                )
            )

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            flag(node.name, node.lineno)
        elif isinstance(node, ast.arg):
            flag(node.arg, node.lineno)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            flag(node.id, node.lineno)
    return out
