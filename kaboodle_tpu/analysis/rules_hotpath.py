"""KB3xx — hot-path rules, scoped to the tick-kernel stack.

These rules only fire in ``kaboodle_tpu/sim/``, ``kaboodle_tpu/ops/``,
``kaboodle_tpu/fleet/``, and ``kaboodle_tpu/warp/`` (matched on the module
path): the whole-tensor and chunked tick kernels, their fused Pallas stages,
the sampling/hashing primitives they call, the ensemble layer that
vmaps/scans the tick over the ``[E]`` axis (where a stray host sync stalls E
meshes at once and the on-device-statistics contract forbids per-member
round-trips), and the warp fast-forward stack (whose leap scan replaces
whole dense-tick sequences — a host sync or promotion inside it undoes the
very sweeps it exists to skip). That is the code whose per-tick cost the
north-star budget (ROADMAP.md: 65,536 peers converging in <2s on a v5e-8)
is spent on — a stray host sync or an accidental int64 promotion there
costs more than any micro-optimization wins.
"""

from __future__ import annotations

import ast

from kaboodle_tpu.analysis.core import Finding, Module, rule
from kaboodle_tpu.analysis.reach import shallow_exprs, walk_with_taint

HOT_DIRS = (
    "kaboodle_tpu/sim/",
    "kaboodle_tpu/ops/",
    "kaboodle_tpu/fleet/",
    "kaboodle_tpu/warp/",
    # The oracle is host-side by design (pure-Python lockstep semantics),
    # but its jnp surfaces (fingerprint.py) feed the parity suites that
    # pin kernel bit-exactness: a dtype drift THERE silently re-defines
    # what "exact" means, so the dtype-discipline rules cover it too.
    # KB301 is reachability-scoped, so the oracle's intentional host numpy
    # (untraced code) does not fire.
    "kaboodle_tpu/oracle/",
    # telemetry/: the counter pytree and flight-recorder ring ride INSIDE
    # the jitted tick/scan programs (counters.py, recorder.py); a host sync
    # or dtype drift there taxes every telemetry-enabled run and breaks the
    # cross-engine counter-parity pins. The export half (manifest/trace/
    # summary) is host-side by design — untraced, so KB301 stays quiet.
    "kaboodle_tpu/telemetry/",
    # phasegraph/: where the protocol logic actually lives now — the one
    # op graph every engine (dense/fused/chunked/sharded/fleet/warp) is
    # derived from. sim/kernel.py, sim/chunked.py and warp/leap.py are
    # shims over it, so a host sync or dtype drift HERE is one landing in
    # all five compiled program families at once.
    "kaboodle_tpu/phasegraph/",
    # serve/: the resident service loop (ISSUE 10). pool.py/engine.py sit
    # on the hot dispatch path of a LONG-RUNNING server — a host sync or
    # eager indexing there is paid per round forever, and an untraced
    # admission input mints programs until the zero-recompile contract is
    # gone. The asyncio front end (server/client/loadgen/dryrun) is
    # host-side by design; KB301's reachability scoping keeps it quiet.
    "kaboodle_tpu/serve/",
    # costscope: the observatory is mostly host-side (AOT compile + HLO
    # text walking), but icibench.py builds the shard_map collective
    # kernels whose timings BECOME the banked ICI numbers — a host sync
    # inside those bodies would time the sync, not the collective, and a
    # dtype drift changes the payload bytes the ring formulas attribute.
    "kaboodle_tpu/costscope/",
    # sparseplane/: the blocked_topk [N, K] engine (ISSUE 18) — the only
    # tick family that scales to million-peer worlds, so its per-tick cost
    # IS the per-peer budget BENCH_sparse.json banks. A host sync in the
    # segment-gather kernel or block repair stalls every steady tick, and
    # an implicit promotion doubles the [N, K] residents the sub-quadratic
    # claim is built on.
    "kaboodle_tpu/sparseplane/",
    # analysis/conc/: the graftconc lane (ISSUE 16) is host-side AST + a
    # runtime sanitizer, but the sanitizer's lock wrappers and loop
    # watchdog run INSIDE the serve round loop under chaos/tests — an
    # accidental host sync or dtype drift added there would be charged to
    # the very latency numbers the sanitizer gates. KB301's reachability
    # scoping keeps the untraced analyzer code quiet; this entry makes the
    # dtype-discipline rules cover any traced surface it ever grows.
    "kaboodle_tpu/analysis/conc/",
)

# Files whose tensors carry the int8/int16/int32/uint32 discipline the
# MEMORY_PLAN/SEMANTICS docs commit to: the CRC/mix-hash paths (wrong dtype =
# wrong fingerprint) and the state/timer/sampling paths (implicit defaults
# promote, silently doubling the [N, N] residents or wrapping sentinels).
# The fleet core/stats carry the same discipline stacked E-fold (a promoted
# [E, N, N] resident is E times the waste); the warp stack carries it over
# whole leaped spans (a promoted score carry or a wrapped int16 sentinel
# breaks bit-exactness with the dense kernel, the subsystem's entire
# contract). Names are matched within HOT_DIRS only, so e.g.
# analysis/core.py never collides with fleet/core.py.
DTYPE_DISCIPLINE_FILES = (
    "crc32.py", "hashing.py", "kernel.py", "chunked.py", "state.py", "sampling.py",
    "core.py", "stats.py",
    "horizon.py", "leap.py", "runner.py",
    # oracle/: the reference-semantics twins whose fingerprints the parity
    # suites compare against the kernels' — wrong dtype = wrong oracle.
    "fingerprint.py", "engine.py", "lockstep.py",
    # telemetry/: the on-device halves. ProtocolCounters leaves are pinned
    # int32/uint32 (gossip_bytes REQUIRES modular uint32 wraparound), and
    # the recorder ring's slots must hold the exact dtypes the counters
    # carry or the dump re-defines what the parity fuzz compared.
    "counters.py", "recorder.py",
    # phasegraph/: the derived-engine bodies (exec.py dense full+fused,
    # blocked.py chunked twin, span.py warp leap). These inherited the
    # kernel.py/chunked.py/leap.py discipline wholesale — int8 state,
    # int16/int32 timers with sentinel wraparound, uint32 fingerprints —
    # and every parity pin in the tree compares THEIR outputs now.
    "exec.py", "blocked.py", "span.py",
    # serve/: pool.py's traced lane vectors (int32 budgets/counters, bool
    # masks) ride into the serve step every round — a promoted vector
    # changes the program signature and re-compiles; engine.py's derive
    # body (in phasegraph) and its k_m handoff carry the same discipline.
    # (engine.py the FILENAME is already listed for oracle/; names match
    # within HOT_DIRS, so serve/engine.py is covered by that entry.)
    "pool.py",
    # sparseplane/ + phasegraph/: kernel.py/state.py ride the entries
    # above (names match within HOT_DIRS); repair.py's rank-match scatter
    # and rng.py's counter-draw chains carry the same discipline — int32
    # neighbor indices with -1 sentinels, int16/int32 block timers, uint32
    # (seed, cursor) / (key, tick, stream) folds whose wraparound both the
    # checkpoint resume and the Warp 3.0 counter keys depend on. "rng.py"
    # covers BOTH the canonical kaboodle_tpu/phasegraph/rng.py and the
    # sparseplane shim re-exporting it.
    "repair.py", "rng.py",
    # costscope: the microbench payloads. uint32 fingerprints into pmin/
    # pmax agreement, uint32 all-ones partials into psum_scatter — a
    # promoted payload doubles the bytes the banked GB/s is computed from.
    "icibench.py",
)

_CONSTRUCTORS = {
    # name -> number of positional args at which dtype is already supplied
    "jax.numpy.zeros": 2,
    "jax.numpy.ones": 2,
    "jax.numpy.empty": 2,
    "jax.numpy.full": 3,
    "jax.numpy.arange": 4,
}


def _in_hot_dirs(mod: Module) -> bool:
    return any(d in mod.path for d in HOT_DIRS)


@rule(
    "KB301",
    "host sync in the tick hot path",
    """
Inside jit-traced code under `kaboodle_tpu/sim/` or `kaboodle_tpu/ops/`:
`jax.device_get(...)`, `.block_until_ready()`, or a host `numpy` call.
Each one forces a device->host round trip (or a trace-time concretization)
in the code that must execute as one fused XLA program per tick — at
N=65,536 a single stray sync outweighs every fused-kernel win recorded in
PERF.md. Hoist host work out of the kernel, or keep it in `jnp`.
Trace-time-static numpy (table building at module import) is outside
traced functions and does not fire.
""",
)
def check_hot_host_sync(mod: Module) -> list[Finding]:
    if not _in_hot_dirs(mod):
        return []
    out: list[Finding] = []
    for info in mod.reach.traced_functions():

        def visit(stmt, tainted, info=info):
            for expr in shallow_exprs(stmt):
                for node in ast.walk(expr):
                    if not isinstance(node, ast.Call):
                        continue
                    hit = None
                    d = mod.dotted(node.func)
                    if d == "jax.device_get":
                        hit = "jax.device_get()"
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "block_until_ready"
                    ):
                        hit = ".block_until_ready()"
                    elif d and d.startswith("numpy."):
                        hit = d.replace("numpy.", "np.") + "()"
                    if hit:
                        out.append(
                            Finding(
                                mod.path, "KB301", node.lineno,
                                f"{hit} inside jit-traced '{info.qualname}' "
                                "(tick hot path)",
                                f"{info.qualname}.{hit}",
                            )
                        )

        walk_with_taint(info, visit)
    return out


@rule(
    "KB302",
    "dtype-less jnp constructor in a dtype-disciplined file",
    """
`jnp.zeros/ones/full/empty/arange` without an explicit dtype in one of the
int-discipline files (crc32/hashing/kernel/chunked/state/sampling). The
implicit default there is a trap twice over: `arange`/`zeros` default to
the x64-flag-dependent int/float, and a bare Python scalar promotes the
whole [N, N] expression (the int16-timer carry bug class documented in
kernel.py — "a bare `t` in a where() would promote the whole tensor").
The crc32/mix-hash paths additionally *require* uint32 wraparound; a
defaulted int32 there changes fingerprints. Spell the dtype.
""",
)
def check_dtypeless_constructor(mod: Module) -> list[Finding]:
    if not (
        _in_hot_dirs(mod) and mod.path.rsplit("/", 1)[-1] in DTYPE_DISCIPLINE_FILES
    ):
        return []
    out: list[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        d = mod.dotted(node.func)
        if d not in _CONSTRUCTORS:
            continue
        has_dtype = any(kw.arg == "dtype" for kw in node.keywords) or len(
            node.args
        ) >= _CONSTRUCTORS[d]
        if not has_dtype:
            ctor = d.rsplit(".", 1)[-1]
            # Symbol is the constructor name, not the line: one baseline
            # entry covers every instance of that constructor in the file
            # (and stays valid across unrelated edits).
            out.append(
                Finding(
                    mod.path, "KB302", node.lineno,
                    f"jnp.{ctor}(...) without an explicit dtype in a "
                    "dtype-disciplined file",
                    f"jnp.{ctor}",
                )
            )
    return out
