"""KB4xx — graftscan: jaxpr/IR-level rules over the traced kernel programs.

The KB1xx-KB3xx families read *source*; this family reads the *traced
program*. The properties the north-star budget actually depends on — no
dtype widening in the lean int16 path, no host syncs inside jitted tick
kernels, no silent recompilation storms across warp leap spans — live in
the ``ClosedJaxpr``, not the AST: a ``jax.random.uniform`` without a dtype
parses identically either way, but the traced program shows the f64 [N, N]
draw it becomes once ``jax_enable_x64`` flips.

This module registers only the rule *documentation* (``--explain KB4nn``,
``--list-rules``) with no-op AST checks, so the default AST lane stays
jax-free and parse-fast. The actual passes live in
``kaboodle_tpu/analysis/ir/`` (which imports jax) and run under
``python -m kaboodle_tpu.analysis --ir`` over the entry-point registry
(``ir/registry.py``): the dense/chunked tick kernels, the warp leap scan,
the vmapped fleet tick, the fused ops + crc32, and the GSPMD-sharded twins.

IR findings have no source line to ``# noqa``; suppression is the justified
baseline ``.graftscan_baseline.json`` (same format and shrink-only debt
contract as ``.graftlint_baseline.json``), and the KB405 program counts are
pinned by ``.graftscan_surface.json``.
"""

from __future__ import annotations

from kaboodle_tpu.analysis.core import rule


def _ir_only(mod):
    """KB4xx rules run on traced jaxprs (analysis/ir/), never on ASTs."""
    return []


rule(
    "KB401",
    "dtype widening in a traced kernel program",
    """
Two detectors over each registered entry point's ClosedJaxpr:

1. Any non-scalar float64/complex128 value anywhere in the program, traced
   under `jax_enable_x64`. With x64 off, a dtype-less draw or a bare-float
   promotion silently lands on f32 and the program *looks* fine; flipping
   x64 during the trace makes every implicit default visible. A clean
   program pins every tensor dtype, so it traces 32-bit under either flag;
   an f64 [N, N] resident doubles the HBM bill of exactly the tensors the
   MEMORY_PLAN budget is spent on.
2. In the lean-mode programs (int16 timers — MEMORY_PLAN.md), any
   `convert_element_type` that widens int16 state beyond the allowlisted
   accumulation set: age arithmetic (`t - T` computes in int32 by design)
   and comparisons. A widened value flowing into anything else — a state
   write, a scatter, a carry — silently doubles the timer resident and
   breaks the int16 discipline the lean mode exists for.

Fix by spelling the dtype (`dtype=jnp.float32` on draws, f32-pinned
probability constants); baseline with a justification only for widenings
that are provably trace-local.
""",
)(_ir_only)


rule(
    "KB402",
    "host boundary inside a jitted tick program",
    """
A host-callback-shaped primitive (`io_callback`, `pure_callback`,
`debug_callback`, infeed/outfeed) reachable inside a registered entry
point's traced program. Each one forces a device->host round trip per
dispatch — inside a tick kernel that `lax.scan` rolls thousands of times,
or a warp leap that exists to *avoid* per-tick dispatches, a single
callback stalls the whole pipeline (and under `vmap` it stalls all E fleet
members at once). Debug prints belong outside the kernel or behind a
non-default debug build; data extraction belongs in TickMetrics, which the
scan stacks on-device for free.

The AST-level twin (KB301) catches host syncs it can see in source; this
rule catches what actually reached the program — including callbacks
introduced through helper layers or decorators the per-module
reachability cannot follow.
""",
)(_ir_only)


rule(
    "KB403",
    "oversized constant baked into a traced program",
    """
A closure-captured constant above the per-entry byte threshold embedded in
the compiled program instead of passed as an argument. Baked-in constants
are copied into every compiled executable (once per jit cache entry — a
leap program cached per power-of-two span length would hold one copy
EACH), are re-hashed on every cache lookup, and silently pin stale data if
the captured array should have been an input. Small lookup tables (the
256-word crc32 table) are fine; a whole [N, N] mesh tensor is not — thread
it through the function signature so it lives in one donated buffer.

The registry traces at toy N, so the threshold is sized to catch
state-shaped captures at trace scale, not only production scale.
""",
)(_ir_only)


rule(
    "KB404",
    "hand-rolled or missing GSPMD sharding constraint",
    """
In the sharded twins (parallel/mesh.py, fleet/sharding.py, the sharded
warp leap), every `sharding_constraint` in the traced program must carry a
PartitionSpec derived from `parallel.state_specs` — row axis on 'peers',
ensemble axis on 'ensemble', control scalars replicated. A hand-rolled
spec (e.g. column-sharded state) silently forces GSPMD to insert resharding
collectives on every tick; a sharded entry point with NO constraints at
all has lost its layout pinning entirely, and the scan-carry placement
drifts wherever XLA's cost model wanders (the exact failure
`constrain_state` exists to prevent). `state_specs` is the single source
of truth; derive from it, never restate it.
""",
)(_ir_only)


rule(
    "KB405",
    "compile-surface budget exceeded (recompilation debt gate)",
    """
Memoization-based simulators get their speed from a small, stable set of
compiled programs (PAPERS.md: memoization + fast-forwarding): the dense
tick is ONE program rolled under scan, warp spans leap through
O(log max_span) power-of-two programs, a fleet of thousands shares ONE
vmapped program. This rule runs the scripted dense+warp+fleet exercise
(analysis/ir/surface.py) in a fresh process, counts the XLA compilations
each entry point triggers, and compares against the committed
`.graftscan_surface.json`.

A count above the committed budget is a recompilation regression — a
spurious static argument, a shape that varies per call, a span-chunking
policy that degraded to one program per span length — and fails the gate.
Raising a committed count requires editing the budget file WITH a
justification entry (CI rejects growth without one); under
`--no-baseline-growth` a count *below* the budget also fails until the
smaller number is committed, so the surface file is a shrink-only record
of exactly how many programs the hot paths are allowed to cost.

Unlike every other rule, KB405 findings are NOT baselineable in
`.graftscan_baseline.json`: the justified surface file is the one and
only accepted record, so growth can never be waved through sideways.
""",
)(_ir_only)
