"""KB2xx — jax-tracer rules.

These run only inside functions the per-module reachability pass
(``reach.py``) marks as traced — decorated/wrapped with jit/shard_map,
passed to a ``lax`` control-flow or ``pallas_call`` site, marked
``# graftlint: traced``, or reachable from one of those. KB204/KB205
(key reuse, donation) are call-protocol rules and run in *every* function:
a key reused in host code corrupts statistics just as surely.
"""

from __future__ import annotations

import ast

from kaboodle_tpu.analysis.core import Finding, Module, rule
from kaboodle_tpu.analysis.reach import (
    FuncInfo,
    _assign_targets,
    expr_tainted,
    shallow_exprs,
    walk_with_taint,
)

_COERCERS = {"float", "int", "bool", "complex"}
_ITEM_ATTRS = {"item", "tolist"}

# jax.random functions that *transform* keys rather than consume them.
_KEY_PRODUCERS = {
    "jax.random.key",
    "jax.random.PRNGKey",
    "jax.random.split",
    "jax.random.fold_in",
    "jax.random.clone",
    "jax.random.wrap_key_data",
}
_KEY_NEUTRAL = _KEY_PRODUCERS | {"jax.random.key_data", "jax.random.key_impl"}


def _finding(mod, rule_id, node, msg, symbol):
    return Finding(mod.path, rule_id, node.lineno, msg, symbol)


# ---------------------------------------------------------------------------
# KB201 — Python control flow on traced values


@rule(
    "KB201",
    "Python branch on a traced value",
    """
Inside jit-traced code, a Python `if`/`while`/`assert` whose condition
depends on a traced value. Under tracing this either raises
TracerBoolConversionError or — worse, with weak typing — silently burns
one branch into the compiled program (a host sync + wrong semantics).
Use `jax.lax.cond` / `jnp.where` / checkify instead. Structural tests
(`x is None`, `.shape`/`.dtype`/`.ndim` reads) are static at trace time
and exempt. A deliberate trace-time specialization on an argument that is
static *by contract* belongs in the baseline with that contract as the
reason, or under `# noqa: KB201`.
""",
)
def check_traced_branch(mod: Module) -> list[Finding]:
    out: list[Finding] = []

    def visit_factory(info: FuncInfo):
        def visit(stmt, tainted):
            kind = None
            if isinstance(stmt, (ast.If, ast.While)):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                test = stmt.test
            elif isinstance(stmt, ast.Assert):
                kind, test = "assert", stmt.test
            if kind and expr_tainted(test, tainted):
                # The tainted names participate in the symbol so a baselined
                # `if deterministic:` cannot mask a later `if prob > 0.5:`
                # added to the same function — distinct conditions get
                # distinct baseline keys.
                names = sorted(
                    {
                        n.id
                        for n in ast.walk(test)
                        if isinstance(n, ast.Name) and n.id in tainted
                    }
                )
                out.append(
                    _finding(
                        mod, "KB201", stmt,
                        f"Python `{kind}` on a traced value inside jit-traced "
                        f"'{info.qualname}' — use lax.cond/jnp.where",
                        f"{info.qualname}.{kind}({','.join(names)})",
                    )
                )

        return visit

    for info in mod.reach.traced_functions():
        walk_with_taint(info, visit_factory(info))
    return out


# ---------------------------------------------------------------------------
# KB202 — host coercions on traced values


@rule(
    "KB202",
    "host coercion of a traced value",
    """
Inside jit-traced code, `float()`/`int()`/`bool()`/`complex()`, `.item()`,
`.tolist()`, or a host `numpy` call applied to a traced value. These force
a concrete value at trace time: TracerArrayConversionError at best, a
silent device->host sync baked into every call at worst (the regression
`tests/test_sampling.py::test_choose_one_of_oldest_k_traces_under_jit`
pins one real instance). Keep the computation in `jnp`, or hoist the
coercion out of the traced region. Static reads (`int(x.shape[0])`) are
exempt.
""",
)
def check_tracer_coercion(mod: Module) -> list[Finding]:
    out: list[Finding] = []

    def visit_factory(info: FuncInfo):
        def visit(stmt, tainted):
            for expr in shallow_exprs(stmt):
                for node in ast.walk(expr):
                    if not isinstance(node, ast.Call):
                        continue
                    args = [*node.args, *(kw.value for kw in node.keywords)]
                    hit = None
                    if (
                        isinstance(node.func, ast.Name)
                        and node.func.id in _COERCERS
                        and any(expr_tainted(a, tainted) for a in args)
                    ):
                        hit = f"{node.func.id}()"
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in _ITEM_ATTRS
                        and expr_tainted(node.func.value, tainted)
                    ):
                        hit = f".{node.func.attr}()"
                    else:
                        d = mod.dotted(node.func)
                        if (
                            d
                            and d.startswith("numpy.")
                            and any(expr_tainted(a, tainted) for a in args)
                        ):
                            hit = d.replace("numpy.", "np.") + "()"
                    if hit:
                        out.append(
                            _finding(
                                mod, "KB202", node,
                                f"{hit} on a traced value inside jit-traced "
                                f"'{info.qualname}'",
                                f"{info.qualname}.{hit}",
                            )
                        )

        return visit

    for info in mod.reach.traced_functions():
        walk_with_taint(info, visit_factory(info))
    return out


# ---------------------------------------------------------------------------
# KB203 — print inside traced code


@rule(
    "KB203",
    "print() inside jit-traced code",
    """
A bare `print` inside jit-traced code executes once at trace time with
abstract values — it does not print per step, and what it does print is
`Traced<...>` noise. Use `jax.debug.print` for runtime values, or move
the print outside the traced region. A deliberate trace-time diagnostic
can be suppressed with `# noqa: KB203`.
""",
)
def check_print_in_jit(mod: Module) -> list[Finding]:
    out: list[Finding] = []
    for info in mod.reach.traced_functions():

        def visit(stmt, tainted, info=info):
            for expr in shallow_exprs(stmt):
                for node in ast.walk(expr):
                    if (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "print"
                    ):
                        out.append(
                            _finding(
                                mod, "KB203", node,
                                f"print() inside jit-traced '{info.qualname}' "
                                "— use jax.debug.print",
                                f"{info.qualname}.print",
                            )
                        )

        walk_with_taint(info, visit)
    return out


# ---------------------------------------------------------------------------
# KB204 — PRNG key reuse


class _PathWalker:
    """Statement walk tracking a branch path: two events conflict only when
    one path is a prefix of the other (same execution path) — uses in
    sibling `if`/`else` arms never execute together and don't conflict."""

    def __init__(self):
        self.path: tuple = ()

    def compatible(self, a: tuple, b: tuple) -> bool:
        return a[: len(b)] == b or b[: len(a)] == a

    def walk(self, stmts, on_stmt):
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            on_stmt(s)
            branches = [("body", s.body)] if hasattr(s, "body") else []
            if getattr(s, "orelse", None):
                branches.append(("orelse", s.orelse))
            if getattr(s, "finalbody", None):
                branches.append(("finalbody", s.finalbody))
            for h in getattr(s, "handlers", []) or []:
                # each except arm is its own exclusive path (keyed on the
                # handler node, so sibling handlers never "share" a path)
                branches.append((("handler", id(h)), h.body))
            for label, body in branches:
                if not isinstance(body, list):
                    continue
                base = self.path
                # if/else arms and except arms exclude each other; loop/with/
                # try bodies are on the parent's path.
                excl = isinstance(s, ast.If) or (
                    isinstance(label, tuple) and label[0] == "handler"
                )
                self.path = base + ((id(s), label),) if excl else base
                self.walk(body, on_stmt)
                self.path = base


def _functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


@rule(
    "KB204",
    "PRNG key reused",
    """
The same PRNG key name is passed to two different consuming calls on one
execution path with no `jax.random.split`/rebind in between. The draws are
then perfectly correlated — the classic silent JAX statistics bug. Key
provenance is tracked syntactically: names bound from `jax.random.key/
PRNGKey/split/fold_in` (and those inherited from the enclosing function)
count as keys; passing one as an argument to anything except the key
transformers consumes it. Parity tests that *want* identical draws across
two implementations are the legitimate exception — baseline them with
that justification.
""",
)
def check_key_reuse(mod: Module) -> list[Finding]:
    out: list[Finding] = []

    def scan(func_node, qualname: str, inherited: frozenset[str]) -> None:
        keys: set[str] = set(inherited)
        uses: dict[str, list[tuple]] = {}
        flagged: set[tuple[str, int]] = set()
        pw = _PathWalker()

        def handle_call(node: ast.Call) -> None:
            d = mod.dotted(node.func)
            args = [*node.args, *(kw.value for kw in node.keywords)]
            if d in _KEY_NEUTRAL:
                return
            for a in args:
                if isinstance(a, ast.Name) and a.id in keys:
                    prior = uses.setdefault(a.id, [])
                    if any(pw.compatible(p, pw.path) for p in prior):
                        if (a.id, node.lineno) not in flagged:
                            flagged.add((a.id, node.lineno))
                            out.append(
                                _finding(
                                    mod, "KB204", node,
                                    f"PRNG key '{a.id}' reused without an "
                                    f"intervening split in '{qualname}'",
                                    f"{qualname}.{a.id}",
                                )
                            )
                    prior.append(pw.path)

        def on_stmt(s: ast.stmt) -> None:
            for expr in shallow_exprs(s):
                for node in ast.walk(expr):
                    if isinstance(node, ast.Call):
                        handle_call(node)
            # (re)bindings after the loads of this statement
            if isinstance(s, (ast.Assign, ast.AnnAssign)):
                targets = s.targets if isinstance(s, ast.Assign) else [s.target]
                produced = isinstance(s.value, ast.Call) and (
                    mod.dotted(s.value.func) in _KEY_PRODUCERS
                )
                for t in targets:
                    for name in _target_names(t):
                        uses.pop(name, None)
                        if produced:
                            keys.add(name)
                        else:
                            keys.discard(name)

        pw.walk(func_node.body, on_stmt)
        # nested functions: inherit this scope's key names
        for child in ast.iter_child_nodes(func_node):
            _scan_nested(child, qualname, frozenset(keys))

    def _scan_nested(node, prefix, inherited):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan(node, f"{prefix}.{node.name}", inherited)
            return  # scan() recurses into its own children
        for child in ast.iter_child_nodes(node):
            _scan_nested(child, prefix, inherited)

    # module level + every top-level function (nested handled recursively);
    # the Module node itself plays the role of the outermost "function".
    scan(mod.tree, "<module>", frozenset())
    return out


# assignment-target flattening is shared with the taint pass
_target_names = _assign_targets


# ---------------------------------------------------------------------------
# KB205 — use after donation


def _donated_positions(mod: Module, call: ast.Call) -> list[int] | None:
    """Donated argument positions if ``call`` is a jit-with-donation wrapper
    call. ``donate_argnums`` int literals map directly; ``donate_argnames``
    string literals are resolved to positions through the wrapped function's
    def when it is a module-local name (unresolvable names are skipped
    rather than silently claiming coverage)."""
    d = mod.dotted(call.func)
    if d not in ("jax.jit", "jit", "pjit", "jax.pjit"):
        return None
    nums: list[int] = []
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums.extend(
                c.value
                for c in ast.walk(kw.value)
                if isinstance(c, ast.Constant) and isinstance(c.value, int)
            )
        elif kw.arg == "donate_argnames":
            names = [
                c.value
                for c in ast.walk(kw.value)
                if isinstance(c, ast.Constant) and isinstance(c.value, str)
            ]
            if names and call.args and isinstance(call.args[0], ast.Name):
                wrapped = call.args[0].id
                for fn in ast.walk(mod.tree):
                    if (
                        isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and fn.name == wrapped
                    ):
                        params = [
                            p.arg for p in (*fn.args.posonlyargs, *fn.args.args)
                        ]
                        nums.extend(
                            params.index(n) for n in names if n in params
                        )
                        break
    return sorted(set(nums)) or None


@rule(
    "KB205",
    "donated argument used after donation",
    """
A buffer passed at a donated position of a `jax.jit(...,
donate_argnums=...)` function is read again afterwards. Donation hands the
input buffer to XLA for reuse — on TPU the old array is *deleted* and any
later use raises (or, on backends that ignore donation, silently works in
dev and dies in prod). Rebind the result over the donated name
(`st, m = tick(st, inp)`) or drop the donation. Tracked syntactically per
function: `f = jax.jit(g, donate_argnums=0)` call sites of `f` mark the
positional arg name donated until it is rebound.
""",
)
def check_use_after_donation(mod: Module) -> list[Finding]:
    out: list[Finding] = []

    # names bound to donating jitted callables, module- or function-scope
    donators: dict[str, list[int]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            pos = _donated_positions(mod, node.value)
            if pos:
                for t in node.targets:
                    for name in _target_names(t):
                        donators[name] = pos
    if not donators:
        return out

    def scan(func_body, qualname: str) -> None:
        donated: dict[str, tuple] = {}  # var name -> path at donation
        pw = _PathWalker()

        def on_stmt(s: ast.stmt) -> None:
            exprs = shallow_exprs(s)
            # 1. loads of already-donated names (excluding rebinding targets)
            for expr in exprs:
                for node in ast.walk(expr):
                    if (
                        isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id in donated
                        and pw.compatible(donated[node.id], pw.path)
                    ):
                        # the donating call itself re-donating is also a bug,
                        # but a load inside the original donating statement
                        # was already cleared below before registering.
                        out.append(
                            _finding(
                                mod, "KB205", node,
                                f"'{node.id}' used after being donated to a "
                                f"jit-donated call in '{qualname}'",
                                f"{qualname}.{node.id}",
                            )
                        )
                        del donated[node.id]
            # 2. new donations from calls in this statement
            for expr in exprs:
                for node in ast.walk(expr):
                    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                        pos = donators.get(node.func.id)
                        if not pos:
                            continue
                        for i in pos:
                            if i < len(node.args) and isinstance(
                                node.args[i], ast.Name
                            ):
                                donated[node.args[i].id] = pw.path
            # 3. rebindings clear donation
            if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.For)):
                targets = (
                    s.targets
                    if isinstance(s, ast.Assign)
                    else [s.target]
                )
                for t in targets:
                    for name in _target_names(t):
                        donated.pop(name, None)

        pw.walk(func_body, on_stmt)

    scan(mod.tree.body, "<module>")
    for node in _functions(mod.tree):
        scan(node.body, node.name)
    return out
