"""KB6xx — keyscope: PRNG key-provenance rules over the traced programs.

Like the KB4xx family, this module registers only the rule
*documentation* (``--explain KB6nn``, ``--list-rules``) with no-op AST
checks, so the default AST lane stays jax-free. The provenance engine and
the actual checks live in ``kaboodle_tpu/analysis/rng/`` and run under
``python -m kaboodle_tpu.analysis --rng`` over the same entry-point
registry graftscan traces.

Suppression is the justified ``.keyscope_baseline.json`` (shrink-only,
same contract as the other three debt files). KB605's leap-report
freshness findings are NOT baselineable — a stale report is fixed by
regenerating it (``--write-leap``), never by justifying the staleness.
"""

from __future__ import annotations

from kaboodle_tpu.analysis.core import rule


def _rng_only(mod):
    """KB6xx rules run on key-provenance graphs (analysis/rng/), not ASTs."""
    return []


rule(
    "KB601",
    "key reuse: two reachable draws on one unforked key",
    """
Two `random_bits` sinks in one traced program whose key provenance reaches
the same canonical key node — the same split row, the same fold chain —
without an intervening `split`/`fold_in`. Identical threefry keys produce
identical streams: the two "independent" draws are bit-equal, which skews
every statistic built on them and (for delivery bernoullis) correlates
phases the protocol model assumes independent.

This is the runtime generalization of the AST lane's KB204: the source may
fork keys through helpers the AST cannot follow, but the traced program
cannot hide a shared node. Sinks in *different branches of the same
`lax.cond`* are mutually exclusive at runtime and do NOT count (the
dispatched dense build keeps its full and fused programs under one cond,
both consuming the same layout rows). A loop-invariant key drawn inside a
`scan`/`while` body fires too — every iteration redraws the same stream.

Fix by forking per consumer: another KEY_LAYOUT row (dense chain) or a
fresh STREAM_* id (counter chain). Baseline only draws that are provably
never live together beyond what the cond structure already shows.
""",
)(_rng_only)


rule(
    "KB602",
    "stream-id collision / STREAM_* registry drift",
    """
Two ledgers, compared mechanically on every rng run:

1. *In-trace*: two `fold_in` constants colliding on the same canonical
   parent key (two phases drawing the same stream), or a literal fold on a
   counter-seed chain using an id absent from the registered STREAM_*
   set (a phase drawing off the books).
2. *Registry*: the live `sparseplane/rng.py` STREAM_* constants vs
   keyscope's pinned KEYSCOPE_STREAMS table (analysis/rng/rules.py). Ids
   must be value-identical, dense from 0, and append-only-ordered. A
   *swap* of two ids keeps the traced constants collision-free and
   set-equal — only this double-entry comparison catches it before every
   banked run's draws silently renumber.

"New phases append" is rng.py's stated contract; this rule is its
mechanical form: appending edits both ledgers, renumbering trips the lane.
""",
)(_rng_only)


rule(
    "KB603",
    "resume-impure draw: provenance outside the checkpointable state",
    """
A draw whose key provenance does not bottom out in the entry point's
arguments — a `PRNGKey(0)` (or any constant seed/key) baked into the
traced program. Such a draw replays identically on every resume and every
process, regardless of the checkpoint: restarts stop being bit-exact
continuations, A/B seeds silently share randomness, and the
fast-forwarding lever (PAPERS.md 2602.10615) mis-replays the stream.

This is the exact property sparseplane's `(seed, cursor)` counter
discipline claims by construction — here it is proven for every engine:
dense chains must root in the carried state key (`MeshState.key`, a
checkpointed plane), counter chains in the argument-fed seed/cursor.

Fix by threading the key material through the state planes. A baseline
entry is almost never justified — constant keys belong in tests only, and
test jaxprs are not registry entries.
""",
)(_rng_only)


rule(
    "KB604",
    "cross-engine key-chain divergence",
    """
Engines derived from the same phasegraph op table, with bit-exactness
pinned by the phasegraph dryrun, must fork keys identically: their
provenance fingerprints (the sorted multiset of sink descriptors, e.g.
`carried_key/split5[3]`) must be equal. A divergence — one engine drawing
a row its twin never touches — is the compile-time shadow of a numeric
diff: the bit-compare will catch it at runtime, this rule catches it at
trace time and names the draw.

The pinned groups live in analysis/rng/rules.py CHAIN_GROUPS (dense drop
engines; draw-free dense configs; blocked pair; fleet trio; sparse pair;
the draw-free leap and serve-step families). Entries outside the groups
have *declared* divergent fates (tick.random's fully randomized config,
blocked's per-block folds). Growing or splitting a group is a deliberate
edit to that table, reviewed like a baseline change.
""",
)(_rng_only)


rule(
    "KB605",
    "leapability: chain-coupled vs counter-keyed draw sites",
    """
Not a pass/fail property of the code but of the *artifact*: every draw
sink in every engine is classified

- `counter_keyed` — provenance bottoms out ONLY in `random_seed` on
  argument-derived counters (sparseplane's discipline). The draw is a pure
  function of checkpointable `(seed, cursor)` state: memoization /
  fast-forwarding (PAPERS.md 2602.10615) can leap over it for free.
- `chain_coupled` — a carried split-chain key feeds it: reproducing tick
  T's draw requires advancing the chain T times. These are the draws that
  keep the dense drain seasons dense — ROADMAP item 2's 13.25x-vs-1.61x
  gap lives exactly here.

`keyscope --leap-report` renders the classification with per-site byte
attribution (costscope `bytes_accessed` join) and the warp signature
terms each dense-chain row serves; `--write-leap` banks it as
KEYSCOPE_LEAP.json and `--check-leap` (make rng-dryrun / CI) fails when
the committed copy goes stale. Freshness findings are not baselineable —
regenerate instead.
""",
)(_rng_only)
