"""The consumer-facing ``Kaboodle`` facade over the simulated mesh.

Mirrors the reference's public API (lib.rs:78-369): lifecycle
(``start``/``stop``), queries (``peers``, ``peer_states``, ``fingerprint``),
manual bootstrap (``ping_addrs``), identity management (``set_identity``), and
the three discovery event streams (``discover_peers``,
``discover_departures``, ``discover_fingerprint_changes``,
``discover_next_peer``).

Where the reference binds one instance per OS process to a UDP socket, here
many instances attach to one :class:`SimNetwork` — the in-memory "LAN" whose
medium is the ``[N, N]`` state tensor and whose clock is the tick kernel. An
instance's "address" is its peer index. Instances advance together:
``SimNetwork.tick()`` steps the whole mesh one protocol period (the lockstep
twin of every reference instance's independent 1 Hz tokio loop) and then
delivers events to each attached instance's streams.

Documented deviations from the reference:

- *Identity payload resolution is host-side*: the kernel propagates identity
  words via traffic (``MeshState.id_view``, refreshed at every Q1 mark — the
  envelope semantics of structs.rs:77-83), but consumer-supplied identity
  *bytes* are kept host-side per network and resolved in ``peers()`` from the
  current instances; the on-device word is their CRC-32. Facade-level
  ``fingerprint()`` hashes the caller's membership against current identity
  words (D-API1: a ``set_identity`` is visible to these *queries* immediately,
  while the kernel's internal convergence metric honors per-row views).
- *Restart keeps the address* (D-API2): the reference re-binds an ephemeral
  port on ``start()`` after ``stop()`` (a new address); a simulated instance
  keeps its index, re-entering with a reset row + Join broadcast — the same
  protocol behavior, minus the address churn.
- ``stop()`` is a silent leave (quirk Q8 — no departure announcement), and
  the instance keeps its membership map while stopped (lib.rs:167-170).
"""

from __future__ import annotations

import collections
import dataclasses
import zlib

import jax.numpy as jnp
import numpy as np

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.errors import ConvergenceTimeout, InvalidOperation
from kaboodle_tpu.events import EventTap, FingerprintChanged, PeerDeparted, PeerDiscovered
from kaboodle_tpu.sim.kernel import make_tick_fn
from kaboodle_tpu.sim.state import MeshState, TickInputs, TickMetrics, init_state
from kaboodle_tpu.spec import STATE_NAMES


def _identity_word(identity) -> int:
    """Consumer identity -> on-device uint32 word (CRC-32 of the bytes)."""
    if isinstance(identity, int):
        return identity & 0xFFFFFFFF
    if isinstance(identity, str):
        identity = identity.encode()
    return zlib.crc32(bytes(identity)) & 0xFFFFFFFF


class SimNetwork:
    """The shared medium: N peer slots, the tick clock, and fault controls.

    All peers start dead; :meth:`Kaboodle.start` revives a slot (the kernel
    resets its row and schedules a Join broadcast, kaboodle.rs:144-152,
    228-251). Fault controls (``set_drop_rate``, ``set_partition``) take
    effect on subsequent ticks — the interactive twin of the declarative
    :class:`kaboodle_tpu.sim.Scenario`.
    """

    def __init__(self, capacity: int, cfg: SwimConfig | None = None, seed: int = 0):
        self.cfg = cfg or SwimConfig()
        self.capacity = capacity
        self.state: MeshState = init_state(
            capacity, seed=seed, alive=jnp.zeros((capacity,), dtype=bool)
        )
        self._tick_fn = make_tick_fn(self.cfg, faulty=True)
        self._instances: dict[int, "Kaboodle"] = {}
        self._next_slot = 0
        # pending per-tick control inputs, drained by tick()
        self._kill = np.zeros(capacity, dtype=bool)
        self._revive = np.zeros(capacity, dtype=bool)
        self._manual: dict[int, collections.deque[int]] = collections.defaultdict(
            collections.deque
        )
        self._partition = np.zeros(capacity, dtype=np.int32)
        self._drop_rate = 0.0
        # Host-side RNG derived from the network seed (probe member choice):
        # keeps sim runs reproducible from (cfg, seed) and never touches the
        # global numpy stream.
        self._rng = np.random.default_rng(seed)
        self.metrics: TickMetrics | None = None  # last tick's metrics

    # ---- slots -------------------------------------------------------------

    def _attach(self, inst: "Kaboodle") -> int:
        if self._next_slot >= self.capacity:
            raise InvalidOperation(f"network is full ({self.capacity} slots)")
        slot = self._next_slot
        self._next_slot += 1
        self._instances[slot] = inst
        return slot

    # ---- fault controls ----------------------------------------------------

    def set_drop_rate(self, rate: float) -> None:
        """Random per-edge message drop probability for subsequent ticks."""
        self._drop_rate = float(rate)

    def set_partition(self, groups) -> None:
        """Partition group ids (int [capacity]); equal ids communicate."""
        self._partition = np.asarray(groups, dtype=np.int32).copy()

    def heal_partition(self) -> None:
        self._partition[:] = 0

    # ---- the clock ---------------------------------------------------------

    def tick(self, ticks: int = 1) -> TickMetrics:
        """Advance the whole mesh ``ticks`` protocol periods and deliver events."""
        for _ in range(ticks):
            n = self.capacity
            manual = np.full(n, -1, dtype=np.int32)
            for slot, q in self._manual.items():
                # Queued pings of a stopped instance are held, not discarded:
                # the kernel would mask them by aliveness anyway (D8).
                inst = self._instances.get(slot)
                if q and inst is not None and inst.is_running:
                    manual[slot] = q.popleft()
            # NB: copies are load-bearing — jnp.asarray may alias a NumPy
            # buffer on CPU, and the pending masks are cleared right below.
            inp = TickInputs(
                kill=jnp.asarray(self._kill.copy()),
                revive=jnp.asarray(self._revive.copy()),
                partition=jnp.asarray(self._partition.copy()),
                drop_rate=jnp.float32(self._drop_rate),
                manual_target=jnp.asarray(manual),
                drop_ok=None,
            )
            self._kill[:] = False
            self._revive[:] = False
            self.state, self.metrics = self._tick_fn(self.state, inp)
            self._deliver_events()
        return self.metrics

    def tick_until_converged(self, max_ticks: int = 64) -> int:
        """Tick until the alive peers agree on the fingerprint; returns ticks
        run. Raises InvalidOperation if nothing is running and
        ConvergenceTimeout if agreement is not reached within ``max_ticks``."""
        if not any(i.is_running for i in self._instances.values()):
            raise InvalidOperation("no running instances")
        for t in range(1, max_ticks + 1):
            m = self.tick()
            if bool(m.converged):
                return t
        raise ConvergenceTimeout(f"no fingerprint agreement within {max_ticks} ticks")

    def discover_mesh_member(self) -> tuple[int, object]:
        """Find one mesh member without joining (discovery.rs:30-89,
        lib.rs:359-368): returns ``(address, identity)`` of a running
        instance.

        The reference broadcasts ``Probe`` with backoff until any member
        replies (reply probability max(1, 100-n^2)%, kaboodle.rs:344-353) and
        returns the first reply — an arbitrary member. The sim's broadcast
        domain is the network object itself, so the probe resolves instantly;
        with nobody running it raises :class:`InvalidOperation` instead of
        backing off forever (deviation: the reference loops indefinitely,
        discovery.rs:51-72, which a synchronous facade cannot)."""
        running = [s for s, i in sorted(self._instances.items()) if i.is_running]
        if not running:
            raise InvalidOperation("no running instances to discover")
        slot = running[0] if self.cfg.deterministic else int(self._rng.choice(running))
        return slot, self._instances[slot]._identity

    def _deliver_events(self) -> None:
        from kaboodle_tpu.ops.hashing import membership_fingerprint

        member_dev = self.state.state > 0
        # One vectorized device pass for all rows' fingerprints instead of a
        # per-instance Python hash loop (it matches mix_fingerprint bit-exactly,
        # tests/test_oracle.py).
        fps = np.asarray(membership_fingerprint(member_dev, self.state.identity))
        member = np.asarray(member_dev)
        ids = np.asarray(self.state.identity)
        for slot, inst in self._instances.items():
            if inst.is_running:
                inst._dispatch(inst._tap.feed(member[slot], ids, fingerprint=int(fps[slot])))


class Kaboodle:
    """One mesh instance — the reference's ``Kaboodle`` struct (lib.rs:78-92).

    ``identity`` is an opaque consumer payload (bytes/str/int), as in the
    reference (README.md:15); it travels as a 32-bit word on device (D-API1).
    """

    def __init__(self, network: SimNetwork, identity=b"") -> None:
        self._net = network
        self._slot = network._attach(self)
        self._identity = identity
        self._running = False
        self._tap = EventTap()
        self._discover_subs: list[collections.deque] = []
        self._depart_subs: list[collections.deque] = []
        self._fp_subs: list[collections.deque] = []
        self.set_identity(identity)

    # ---- lifecycle (lib.rs:136-183) ---------------------------------------

    def start(self) -> None:
        """Join the mesh: reset row, revive, Join broadcast on the next tick."""
        if self._running:
            raise InvalidOperation("already running")
        self._net._revive[self._slot] = True
        self._net._kill[self._slot] = False  # cancel a not-yet-applied stop
        self._running = True

    def stop(self) -> None:
        """Silent leave (Q8). The membership map is kept (lib.rs:167-170)."""
        if not self._running:
            raise InvalidOperation("not running")
        self._net._kill[self._slot] = True
        self._net._revive[self._slot] = False  # cancel a not-yet-applied start
        self._running = False

    @property
    def is_running(self) -> bool:
        return self._running

    # ---- addressing --------------------------------------------------------

    def self_addr(self) -> int:
        """The instance's address: its peer index (lib.rs:339-345 analogue)."""
        return self._slot

    def interface(self) -> str:
        """The 'interface' the instance is bound to (lib.rs analogue)."""
        return "sim"

    # ---- queries (lib.rs:301-354) -----------------------------------------

    def _row(self) -> np.ndarray:
        return np.asarray(self._net.state.state[self._slot])

    def peers(self) -> dict[int, object]:
        """Membership map: peer index -> identity payload (lib.rs:339-345).

        Identity payloads are resolved to the attached instance's consumer
        bytes where known; detached slots report their raw identity word."""
        ids = np.asarray(self._net.state.identity)
        out: dict[int, object] = {}
        for j in np.flatnonzero(self._row() > 0):
            inst = self._net._instances.get(int(j))
            out[int(j)] = inst._identity if inst is not None else int(ids[j])
        return out

    def peer_states(self) -> dict[int, tuple[str, int, float | None]]:
        """peer index -> (state name, last-heard/sent-at tick, latency EWMA)
        (lib.rs:348-354).

        Latency is the per-peer EWMA in ticks the kernel tracks
        (kaboodle.rs:789-817, weight 0.8 newest); ``None`` when no sample has
        been taken yet (the reference's ``Option::None``) or when the network
        runs a ``track_latency=False`` lean state."""
        row = self._row()
        timer = np.asarray(self._net.state.timer[self._slot])
        lat_row = self._net.state.latency
        lat = None if lat_row is None else np.asarray(lat_row[self._slot])
        return {
            int(j): (
                STATE_NAMES[int(row[j])],
                int(timer[j]),
                None if lat is None or np.isnan(lat[j]) else float(lat[j]),
            )
            for j in np.flatnonzero(row > 0)
        }

    def fingerprint(self) -> int:
        """Current mesh fingerprint of this instance's view (lib.rs:301-304)."""
        from kaboodle_tpu.oracle.fingerprint import mix_fingerprint

        ids = np.asarray(self._net.state.identity)
        return mix_fingerprint(
            {int(j): int(ids[j]) for j in np.flatnonzero(self._row() > 0)}
        )

    # ---- identity (lib.rs:323-336) ----------------------------------------

    def set_identity(self, identity) -> None:
        self._identity = identity
        self._net.state = dataclasses.replace(
            self._net.state,
            identity=self._net.state.identity.at[self._slot].set(_identity_word(identity)),
        )

    # ---- manual pings (lib.rs:268-297) ------------------------------------

    def ping_addrs(self, addrs) -> None:
        """Queue manual pings, one per subsequent tick (kaboodle.rs:550-556)."""
        if not self._running:
            raise InvalidOperation("not running")
        for a in addrs:
            self._net._manual[self._slot].append(int(a))

    # ---- event streams (lib.rs:186-263) -----------------------------------

    def discover_peers(self):
        """Subscribe to (peer, identity) discoveries; returns a deque that
        fills as the network ticks (the reference's mpsc channel)."""
        q: collections.deque = collections.deque()
        self._discover_subs.append(q)
        return q

    def discover_departures(self):
        q: collections.deque = collections.deque()
        self._depart_subs.append(q)
        return q

    def discover_fingerprint_changes(self):
        q: collections.deque = collections.deque()
        self._fp_subs.append(q)
        return q

    def discover_next_peer(self, max_ticks: int = 64):
        """Tick the network until this instance discovers a peer; returns
        (peer, identity) or None after ``max_ticks`` (lib.rs:246-260)."""
        if not self._running:
            raise InvalidOperation("not running")
        q = self.discover_peers()
        try:
            for _ in range(max_ticks):
                if q:
                    break
                self._net.tick()
            return q.popleft() if q else None
        finally:
            self._discover_subs.remove(q)

    def _dispatch(self, events) -> None:
        for e in events:
            if isinstance(e, PeerDiscovered):
                for q in self._discover_subs:
                    q.append((e.peer, e.identity))
            elif isinstance(e, PeerDeparted):
                for q in self._depart_subs:
                    q.append(e.peer)
            elif isinstance(e, FingerprintChanged):
                for q in self._fp_subs:
                    q.append(e.fingerprint)
