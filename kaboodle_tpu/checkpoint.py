"""Checkpoint / resume for simulator state.

The reference has no checkpointing at all (SURVEY.md §5): membership state
lives only in RAM and is rebuilt from the network after a restart. The
simulator's whole mesh is a pytree of dense arrays (``MeshState``), so a
checkpoint is a bit-exact array dump and resume is a load — determinism tests
(same seed => identical trajectory) extend across a save/load boundary, which
is asserted in tests/test_checkpoint.py.

Format: a single ``.npz`` with one entry per ``MeshState`` field plus a format
version. Every array round-trips exactly (including the PRNG key), so a
resumed run is indistinguishable from an uninterrupted one. ``load`` can place
the restored state directly onto a device mesh for the sharded runners.

Two paths:

- ``save``/``load`` — dependency-free synchronous ``.npz`` (below).
- ``save_async``/``load_orbax`` — orbax-checkpoint: the save runs in a
  background thread (the tick loop keeps running while bytes hit disk) and
  is multi-host coordinated by orbax; the restore can place leaves directly
  into a device-mesh layout with no intermediate full-host copy.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import zipfile

import jax
import jax.numpy as jnp
import numpy as np

from kaboodle_tpu.errors import CheckpointError
from kaboodle_tpu.sim.state import MeshState

_FORMAT_VERSION = 1


def _optional_fields() -> set[str]:
    """MeshState fields that may be ``None`` (default-None dataclass fields)."""
    return {f.name for f in dataclasses.fields(MeshState) if f.default is None}


@contextlib.contextmanager
def _open_npz(path):
    """``np.load`` with the failure modes a long-running service actually
    meets — file missing, truncated mid-write, not a zip at all, a zip with
    a corrupt member — normalized to :class:`CheckpointError` instead of
    the raw ``FileNotFoundError``/``zipfile.BadZipFile``/``EOFError`` zoo,
    so one bad restore degrades one request, not the host loop."""
    try:
        z = np.load(path)
    except FileNotFoundError as e:
        raise CheckpointError(f"checkpoint missing: {path}") from e
    except (zipfile.BadZipFile, OSError, EOFError, ValueError) as e:
        raise CheckpointError(f"checkpoint unreadable ({e}): {path}") from e
    try:
        with z:
            yield z
    except CheckpointError:
        raise
    except (zipfile.BadZipFile, OSError, EOFError, KeyError, ValueError) as e:
        # A member that decompresses short / CRC-fails surfaces HERE, on
        # the array read, not at open time.
        raise CheckpointError(f"checkpoint corrupt ({e}): {path}") from e


def _savez_atomic(path, arrays: dict) -> None:
    """fsync-then-rename npz write: a reader (or a crash-recovery restore)
    sees either the complete previous file or the complete new one, never
    a truncated archive. This is graftconc KB504's canonical shape (with
    journal._write_json_atomic): write tmp -> flush -> fsync -> os.replace,
    every step present in THIS function so the rule can see them."""
    path = os.fspath(path)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def save(
    path, state: MeshState, atomic: bool = False, owner: str | None = None
) -> None:
    """Write ``state`` to ``path`` (.npz), host-fetching device arrays.

    Optional fields that are ``None`` (the memory-lean ``track_latency=False``
    / ``instant_identity=True`` states) are simply absent from the archive —
    never pickled as object arrays, which ``load`` could not read back.
    ``atomic=True`` writes through a same-directory temp file with
    fsync-then-rename (the serve spill path's durability contract).
    ``owner`` stamps a writer identity (a federation engine-id) into the
    archive; ``load(expect_owner=...)`` refuses an alien engine's snapshot
    — two engines sharing one spill root can never cross-restore a lane by
    accident (an intentional failover handover passes the dead engine's id
    as ``expect_owner``)."""
    arrays = {
        f.name: np.asarray(getattr(state, f.name))
        for f in dataclasses.fields(state)
        if getattr(state, f.name) is not None
    }
    arrays["__version__"] = np.int32(_FORMAT_VERSION)
    if owner is not None:
        arrays["__owner__"] = np.frombuffer(
            owner.encode("utf-8"), dtype=np.uint8
        )
    if atomic:
        _savez_atomic(path, arrays)
    else:
        np.savez(path, **arrays)


def checkpoint_owner(path) -> str | None:
    """The engine-id stamped into a checkpoint by ``save(owner=...)``, or
    ``None`` for an unstamped (single-engine era) archive."""
    with _open_npz(path) as z:
        if "__owner__" not in z.files:
            return None
        return bytes(np.asarray(z["__owner__"])).decode("utf-8")


def load(path, mesh=None, expect_owner: str | None = None) -> MeshState:
    """Read a checkpoint; with ``mesh`` set, place rows across its devices
    (the layout kaboodle_tpu.parallel.shard_state would give a fresh state).
    Optional fields absent from the archive restore as ``None``. All failure
    modes — missing / truncated / corrupt file, wrong marker, missing
    entries — raise :class:`CheckpointError`. ``expect_owner`` enforces the
    writer-identity stamp (see :func:`save`): a mismatching OR missing
    stamp raises — an unstamped file in a shared federation spill root is
    as suspect as an alien one."""
    with _open_npz(path) as z:
        if "__version__" not in z.files:
            raise CheckpointError(
                f"not a kaboodle checkpoint (no version entry): {path}"
            )
        version = int(z["__version__"])
        if version != _FORMAT_VERSION:
            raise CheckpointError(f"unsupported checkpoint version {version}")
        if expect_owner is not None:
            if "__owner__" not in z.files:
                raise CheckpointError(
                    f"checkpoint has no owner stamp (expected "
                    f"{expect_owner!r}): {path}"
                )
            got = bytes(np.asarray(z["__owner__"])).decode("utf-8")
            if got != expect_owner:
                raise CheckpointError(
                    f"checkpoint owned by alien engine {got!r} (expected "
                    f"{expect_owner!r}): {path}"
                )
        fields = {f.name for f in dataclasses.fields(MeshState)}
        missing = fields - set(z.files) - _optional_fields()
        if missing:
            raise CheckpointError(
                f"checkpoint missing fields: {sorted(missing)}"
            )
        state = MeshState(
            **{
                name: jnp.asarray(z[name]) if name in z.files else None
                for name in fields
            }
        )
    if mesh is not None:
        from kaboodle_tpu.parallel import shard_state

        state = shard_state(state, mesh)
    return state


def save_fleet(path, fleet, generation=None, atomic: bool = False) -> None:
    """Write a ``FleetState`` (the serve pool resident) to ``path`` (.npz).

    One entry per stacked ``MeshState`` field (``mesh.`` prefixed) plus the
    per-member ``drop_rate`` knob vector and — when given — the serve
    pool's per-lane ``generation`` counters, so a restored pool resumes
    with its (lane, generation) trajectory names intact. Same absent-if-
    None convention as :func:`save`; ``atomic=True`` gets the same
    fsync-then-rename durability."""
    arrays = {
        "mesh." + f.name: np.asarray(getattr(fleet.mesh, f.name))
        for f in dataclasses.fields(fleet.mesh)
        if getattr(fleet.mesh, f.name) is not None
    }
    arrays["drop_rate"] = np.asarray(fleet.drop_rate)
    if generation is not None:
        arrays["generation"] = np.asarray(generation, dtype=np.int32)
    arrays["__version__"] = np.int32(_FORMAT_VERSION)
    arrays["__fleet__"] = np.int32(1)
    if atomic:
        _savez_atomic(path, arrays)
    else:
        np.savez(path, **arrays)


def load_fleet(path):
    """Read a fleet checkpoint back; returns ``(fleet, generation)``.

    ``generation`` is ``None`` when the checkpoint was saved without lane
    counters (a bare ensemble snapshot). Round-trips bit-exactly — the
    serve admission parity contract survives a spill/restore of the whole
    pool (tests/test_checkpoint.py)."""
    from kaboodle_tpu.fleet.core import FleetState

    with _open_npz(path) as z:
        if "__version__" not in z.files:
            raise CheckpointError(
                f"not a kaboodle checkpoint (no version entry): {path}"
            )
        version = int(z["__version__"])
        if version != _FORMAT_VERSION:
            raise CheckpointError(f"unsupported checkpoint version {version}")
        if "__fleet__" not in z.files:
            raise CheckpointError(
                "not a fleet checkpoint (single-mesh? use checkpoint.load)"
            )
        fields = {f.name for f in dataclasses.fields(MeshState)}
        present = {
            name[len("mesh."):] for name in z.files if name.startswith("mesh.")
        }
        missing = fields - present - _optional_fields()
        if missing:
            raise CheckpointError(
                f"checkpoint missing fields: {sorted(missing)}"
            )
        mesh = MeshState(
            **{
                name: jnp.asarray(z["mesh." + name]) if name in present else None
                for name in fields
            }
        )
        if "drop_rate" not in z.files:
            raise CheckpointError("fleet checkpoint missing drop_rate")
        fleet = FleetState(mesh=mesh, drop_rate=jnp.asarray(z["drop_rate"]))
        generation = (
            jnp.asarray(z["generation"]) if "generation" in z.files else None
        )
    return fleet, generation


def save_sparse(path, state, atomic: bool = False) -> None:
    """Write a ``SparseState`` (blocked-sparse mesh) to ``path`` (.npz).

    One entry per plane (``sparse.`` prefixed) — the neighbor-index /
    state / timer blocks AND the counter-RNG ``(seed, cursor)`` pair, so a
    restored mesh reproduces the exact draw sequence an uninterrupted run
    would have made (counter draws are pure functions of the cursor).
    Schema-guarded with a ``__sparse__`` marker like ``__fleet__``, so the
    three checkpoint families can never cross-restore."""
    from kaboodle_tpu.sparseplane.state import SparseState

    arrays = {
        "sparse." + f.name: np.asarray(getattr(state, f.name))
        for f in dataclasses.fields(SparseState)
    }
    arrays["__version__"] = np.int32(_FORMAT_VERSION)
    arrays["__sparse__"] = np.int32(1)
    if atomic:
        _savez_atomic(path, arrays)
    else:
        np.savez(path, **arrays)


def load_sparse(path):
    """Read a sparse checkpoint back; returns a ``SparseState``.

    Round-trips bit-exactly (tests/test_checkpoint.py): identical blocks,
    identical ``(seed, cursor)``, so a resumed sparse run is
    indistinguishable from an uninterrupted one. Same normalized failure
    modes as :func:`load`; a dense or fleet archive raises."""
    from kaboodle_tpu.sparseplane.state import SparseState

    with _open_npz(path) as z:
        if "__version__" not in z.files:
            raise CheckpointError(
                f"not a kaboodle checkpoint (no version entry): {path}"
            )
        version = int(z["__version__"])
        if version != _FORMAT_VERSION:
            raise CheckpointError(f"unsupported checkpoint version {version}")
        if "__sparse__" not in z.files:
            raise CheckpointError(
                "not a sparse checkpoint (dense mesh? use checkpoint.load)"
            )
        fields = {f.name for f in dataclasses.fields(SparseState)}
        present = {
            name[len("sparse."):]
            for name in z.files
            if name.startswith("sparse.")
        }
        missing = fields - present
        if missing:
            raise CheckpointError(
                f"checkpoint missing fields: {sorted(missing)}"
            )
        state = SparseState(
            **{name: jnp.asarray(z["sparse." + name]) for name in fields}
        )
    return state


_ASYNC_CKPTR = None


def _async_checkpointer():
    """One shared AsyncCheckpointer: repeated saves reuse its background
    machinery instead of leaking a thread pool per call (orbax itself waits
    for the previous save before starting the next on the same instance)."""
    global _ASYNC_CKPTR
    import orbax.checkpoint as ocp

    if _ASYNC_CKPTR is None:
        _ASYNC_CKPTR = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return _ASYNC_CKPTR


def save_async(path, state: MeshState):
    """Checkpoint ``state`` via orbax in the background.

    Returns the shared ``AsyncCheckpointer``; call ``wait_until_finished()``
    on it to join the write (and before reading the checkpoint back). The
    tick loop can keep running meanwhile — device buffers are snapshotted up
    front. Multi-host runs are coordinated by orbax across processes."""
    import orbax.checkpoint as ocp

    ckptr = _async_checkpointer()
    ckptr.save(path, args=ocp.args.StandardSave(state))
    return ckptr


def load_orbax(path, template: MeshState, mesh=None) -> MeshState:
    """Restore a checkpoint written by :func:`save_async`.

    ``template`` supplies the tree structure/shapes/dtypes — a fresh
    ``init_state`` with the same options works (only shapes are read, not
    values). With ``mesh`` set, leaves restore *directly* into the
    row-sharded layout (the ``shard_state`` placement) with no intermediate
    single-device copy — the path big resumes should take."""
    import orbax.checkpoint as ocp

    tmpl = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from kaboodle_tpu.parallel import state_specs

        shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            state_specs(template),
            is_leaf=lambda x: isinstance(x, P),
        )
        tmpl = jax.tree.map(
            lambda x, sh: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh),
            tmpl,
            shardings,
        )
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        return ckptr.restore(path, args=ocp.args.StandardRestore(tmpl))
