"""Checkpoint / resume for simulator state.

The reference has no checkpointing at all (SURVEY.md §5): membership state
lives only in RAM and is rebuilt from the network after a restart. The
simulator's whole mesh is a pytree of dense arrays (``MeshState``), so a
checkpoint is a bit-exact array dump and resume is a load — determinism tests
(same seed => identical trajectory) extend across a save/load boundary, which
is asserted in tests/test_checkpoint.py.

Format: a single ``.npz`` with one entry per ``MeshState`` field plus a format
version. Every array round-trips exactly (including the PRNG key), so a
resumed run is indistinguishable from an uninterrupted one. ``load`` can place
the restored state directly onto a device mesh for the sharded runners.

``MeshState`` is an ordinary registered pytree, so orbax-checkpoint works on
it unmodified if async/multi-host checkpointing is ever needed; this module is
the dependency-free synchronous path.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from kaboodle_tpu.errors import KaboodleError
from kaboodle_tpu.sim.state import MeshState

_FORMAT_VERSION = 1


def _optional_fields() -> set[str]:
    """MeshState fields that may be ``None`` (default-None dataclass fields)."""
    return {f.name for f in dataclasses.fields(MeshState) if f.default is None}


def save(path, state: MeshState) -> None:
    """Write ``state`` to ``path`` (.npz), host-fetching device arrays.

    Optional fields that are ``None`` (the memory-lean ``track_latency=False``
    / ``instant_identity=True`` states) are simply absent from the archive —
    never pickled as object arrays, which ``load`` could not read back."""
    arrays = {
        f.name: np.asarray(getattr(state, f.name))
        for f in dataclasses.fields(state)
        if getattr(state, f.name) is not None
    }
    np.savez(path, __version__=np.int32(_FORMAT_VERSION), **arrays)


def load(path, mesh=None) -> MeshState:
    """Read a checkpoint; with ``mesh`` set, place rows across its devices
    (the layout kaboodle_tpu.parallel.shard_state would give a fresh state).
    Optional fields absent from the archive restore as ``None``."""
    with np.load(path) as z:
        if "__version__" not in z.files:
            raise KaboodleError("not a kaboodle checkpoint (no version entry)")
        version = int(z["__version__"])
        if version != _FORMAT_VERSION:
            raise KaboodleError(f"unsupported checkpoint version {version}")
        fields = {f.name for f in dataclasses.fields(MeshState)}
        missing = fields - set(z.files) - _optional_fields()
        if missing:
            raise KaboodleError(f"checkpoint missing fields: {sorted(missing)}")
        state = MeshState(
            **{
                name: jnp.asarray(z[name]) if name in z.files else None
                for name in fields
            }
        )
    if mesh is not None:
        from kaboodle_tpu.parallel import shard_state

        state = shard_state(state, mesh)
    return state
