"""CLI demo: the reference's demo app (main.rs) plus a simulator front-end.

Real-network mode reproduces main.rs's behavior: join the mesh on a chosen
interface/port, print an event-driven status table of peers (state, latency,
identity) and the mesh fingerprint once per second, update the terminal
title, and support probe mode (discover one member and exit, main.rs:70-84)
and manual ping bootstrap (--ping addr, lib.rs:268-297).

Sim mode is the TPU-native addition: run one of the benchmark scenarios (or a
custom size) on the accelerator and stream per-tick convergence metrics. The
``fleet`` subcommand is the ensemble front-end (kaboodle_tpu/fleet/bench.py):
sweep a per-member knob grid over E batched meshes in one dispatch and print
on-device convergence-quantile statistics.

    python -m kaboodle_tpu --identity my-node            # join the LAN mesh
    python -m kaboodle_tpu --probe                       # find a member, exit
    python -m kaboodle_tpu --sim 4096 --ticks 32         # simulate on TPU
    python -m kaboodle_tpu --sim-scenario 3              # BASELINE config 3
    python -m kaboodle_tpu fleet --sweep drop_rate=0:0.3:16 --ensemble 1024
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from kaboodle_tpu.errors import KaboodleError, NoAvailableInterfaces


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kaboodle_tpu", description="TPU-native SWIM mesh: demo CLI"
    )
    # Real-network args (main.rs:38-50).
    p.add_argument("--identity", default=None, help="identity payload for this node")
    p.add_argument(
        "--interface",
        default=None,
        help="interface IP to bind, or 'v4'/'v6' to pick by family (default: "
        "reference policy, IPv6-preferred non-loopback)",
    )
    p.add_argument("--port", type=int, default=7475, help="broadcast/multicast port")
    p.add_argument("--probe", action="store_true", help="discover one member and exit")
    p.add_argument("--probe-timeout", type=float, default=0.0, metavar="S",
                   help="give up probing after S seconds (0 = retry forever "
                        "with the reference's 1s x1.25 backoff)")
    p.add_argument("--ping", action="append", default=[], metavar="ADDR",
                   help="manually ping ADDR after start (repeatable)")
    p.add_argument("--period-ms", type=int, default=1000, help="protocol period")
    p.add_argument("--duration", type=float, default=0.0,
                   help="exit after this many seconds (0 = run until ^C)")
    # Simulator args.
    p.add_argument("--sim", type=int, default=0, metavar="N",
                   help="simulate N peers on the accelerator instead of joining a LAN")
    p.add_argument("--sim-scenario", type=int, default=0, metavar="K",
                   help="run BASELINE config K (1-5) on the accelerator")
    p.add_argument("--ticks", type=int, default=64, help="sim ticks to run")
    p.add_argument("--warp", action="store_true",
                   help="sim mode: fast-forward quiescent tick spans through "
                        "the event-horizon leap engine (kaboodle_tpu.warp) — "
                        "bit-exact with dense ticking, dispatches only the "
                        "eventful/dense ticks")
    p.add_argument("--no-warp-hybrid", action="store_true",
                   help="with --warp: disable the Warp 2.0 hybrid "
                        "(near-quiescent signature class) span programs and "
                        "fast-forward only strictly quiescent spans — the "
                        "Warp 1.x behavior knob; the default leaps armed-"
                        "timer drain windows too (bit-exact either way)")
    p.add_argument("--no-warp-memo", action="store_true",
                   help="with --warp: disable the Warp 3.0 signature-keyed "
                        "span memo — every span (leaped and dense) then "
                        "dispatches instead of replaying banked state "
                        "deltas; results are bit-identical either way")
    p.add_argument("--warp-mode", choices=["exact", "distributional"],
                   default="exact",
                   help="with --warp: 'exact' (default) is bit-exact with "
                        "dense ticking; 'distributional' also leaps drain "
                        "seasons whose per-tick draws only shift event "
                        "ARRIVAL ticks (suspicion countdowns, probe "
                        "waits) — distribution-pinned, not bit-pinned")
    p.add_argument("--telemetry", nargs="?", const="telemetry.jsonl",
                   default=None, metavar="PATH",
                   help="sim mode: run the telemetry-plane kernel build "
                        "(kaboodle_tpu.telemetry — per-tick ProtocolCounters "
                        "+ flight recorder) and write a JSONL run manifest "
                        "(default: telemetry.jsonl); summarize with "
                        "`python -m kaboodle_tpu telemetry PATH`")
    p.add_argument("--metrics-jsonl", default=None, metavar="PATH",
                   help="sim mode: dump per-tick TickMetrics as manifest "
                        "'tick' records to PATH (no telemetry build needed; "
                        "same schema as --telemetry manifests)")
    p.add_argument("--seed", type=int, default=0)
    return p


def resolve_interface(spec: str | None) -> tuple[str, int, str]:
    """Resolve --interface to (ip, ifindex, broadcast_ip).

    ``spec``: None (reference policy: IPv6-preferred), a family keyword
    ('ipv4'/'ipv6', with 'v4'/'v6' accepted as shorthand), or an explicit IP
    or device name — the reference resolves the same three forms, matching
    IP or name uncanonicalized in one pass (main.rs:18-36).
    """
    from kaboodle_tpu.transport.native import list_interfaces

    ifaces = list_interfaces()
    if not ifaces:
        raise NoAvailableInterfaces("no non-loopback interface")

    def bcast(i):
        return i["broadcast"] if i["family"] == 4 else "ff02::1213:1989"

    if spec in ("v4", "v6", "ipv4", "ipv6"):
        fam = 4 if spec in ("v4", "ipv4") else 6
        for i in ifaces:
            if i["family"] == fam:
                return i["ip"], i["ifindex"], bcast(i)
        raise NoAvailableInterfaces(f"no {spec} interface")
    if spec:
        for i in ifaces:
            if i["ip"] == spec or i.get("name") == spec:
                return i["ip"], i["ifindex"], bcast(i)
        raise NoAvailableInterfaces(f"interface {spec!r} not found (by ip or name)")
    for i in ifaces:  # IPv6-preferred (networking.rs:12-23)
        if i["family"] == 6:
            return i["ip"], i["ifindex"], bcast(i)
    i = ifaces[0]
    return i["ip"], i["ifindex"], bcast(i)


def format_peer_table(self_addr: str, peer_states: dict, peers: dict) -> str:
    """The per-second status block (main.rs:198-225)."""
    lines = []
    for addr in sorted(peer_states):
        # Real-net entries are (state, latency-in-ms); sim entries are
        # (state, tick, latency-in-ticks) — latency is last either way, but
        # the unit differs (one tick = one protocol period = 1 s wall).
        entry = peer_states[addr]
        state, latency = entry[0], entry[-1]
        unit = "tk" if len(entry) == 3 else "ms"
        ident = peers.get(addr, b"")
        ident_s = ident.decode("utf-8", "replace") if isinstance(ident, bytes) else str(ident)
        me = " (me)" if addr == self_addr else ""
        lat = f"{latency:7.1f}{unit}" if isinstance(latency, (int, float)) else "        -"
        lines.append(f"  {addr:<28} {state:<22} {lat}  {ident_s}{me}")
    return "\n".join(lines)


# Interactive-mode spinner (main.rs:134-136, 226-244): a braille-dot cycle —
# the standard "dots" spinner — shown only when stdin is a terminal.
_SPINNER_FRAMES = "⣾⣽⣻⢿⡿⣟⣯⣷"


def _spawn_stdin_reader():
    """Daemon thread feeding stdin chars to a queue (main.rs:247-258): in
    interactive mode, typing anything (terminal line discipline applies, so
    followed by Enter) triggers a full status dump."""
    import queue
    import threading

    q: "queue.Queue[str]" = queue.Queue()

    def reader() -> None:
        while True:
            ch = sys.stdin.read(1)
            if not ch:  # EOF
                return
            q.put(ch)

    threading.Thread(target=reader, daemon=True).start()
    return q


def _print_status(node, self_addr: str) -> None:
    """Fingerprint + peer table + terminal title (main.rs:179-225)."""
    states = node.peer_states()
    fp = node.fingerprint()
    # Terminal title: "{addr} {n} {fp:08x}" (main.rs:189-192).
    sys.stdout.write(f"\x1b]0;{self_addr} {len(states)} {fp:08x}\x07")
    print(f"{len(states)} peers, fingerprint {fp:08x}")
    print(format_peer_table(self_addr, states, node.peers()))


def run_real(args) -> int:
    from kaboodle_tpu.transport import RealKaboodle, discover_mesh_member

    ip, idx, bcast_ip = resolve_interface(args.interface)

    if args.probe:
        # Probe mode (main.rs:70-84): find one member, print, exit. The
        # default retries forever like the reference's discover_mesh_member
        # (discovery.rs:51-72); --probe-timeout bounds it.
        res = discover_mesh_member(
            args.port, interface_ip=ip, broadcast_ip=bcast_ip, iface_index=idx,
            total_timeout_ms=int(args.probe_timeout * 1000),
        )
        if res is None:
            print("no mesh member found", file=sys.stderr)
            return 1
        addr, identity = res
        print(f"{addr} {identity.decode('utf-8', 'replace')}")
        return 0

    identity = (args.identity or f"kaboodle-{int(time.time())}").encode()
    node = RealKaboodle(
        identity=identity,
        broadcast_port=args.port,
        interface_ip=ip,
        broadcast_ip=bcast_ip,
        iface_index=idx,
        period_ms=args.period_ms,
        ping_timeout_ms=2 * args.period_ms,
        share_age_ms=10 * args.period_ms,
        rebroadcast_ms=10 * args.period_ms,
    )
    node.start()
    node.ping_addrs(args.ping)
    self_addr = node.self_addr()
    print(f"self: {self_addr} on {ip} (port {args.port})")

    # Event-driven output (main.rs:144-225): joins/leaves print as they
    # arrive; the full status block only on fingerprint change or a
    # keypress. The spinner runs only when stdin is a terminal.
    discovery = node.discover_peers()
    departures = node.discover_departures()
    fp_changes = node.discover_fingerprint_changes()
    # Both ends must be terminals: stdin for the dump trigger (don't steal
    # keystrokes a pipeline owns), stdout for the animation (don't corrupt
    # piped/teed output with \r frames).
    spin = sys.stdin.isatty() and sys.stdout.isatty()
    stdin_q = _spawn_stdin_reader() if spin else None
    frame = 0
    deadline = time.time() + args.duration if args.duration else None
    period_s = min(args.period_ms / 1000.0, 1.0)
    try:
        while deadline is None or time.time() < deadline:
            # One smooth spinner rotation per period beats one frame per
            # second (the protocol only does work once a period, the
            # animation shouldn't look like it hung — main.rs:226-237).
            if spin:
                for _ in range(10):
                    sys.stdout.write(f"\r{_SPINNER_FRAMES[frame]} ")
                    sys.stdout.flush()
                    frame = (frame + 1) % len(_SPINNER_FRAMES)
                    time.sleep(period_s / 10)
            else:
                time.sleep(period_s)
            node.poll_events()

            emitted = False

            def clear_spinner() -> None:
                nonlocal emitted
                if spin and not emitted:
                    sys.stdout.write("\r  \r")  # erase the spinner frame
                    emitted = True

            while discovery:
                addr, ident = discovery.popleft()
                clear_spinner()
                ident_s = ident.decode("utf-8", "replace")
                print(f"+ {addr}" + (f" ({ident_s})" if ident_s else ""))
            while departures:
                clear_spinner()
                print(f"- {departures.popleft()}")
            new_fp = None
            while fp_changes:  # drain: only the newest value matters
                new_fp = fp_changes.popleft()
            dump = False
            if stdin_q is not None:
                while not stdin_q.empty():
                    stdin_q.get_nowait()
                    dump = True
            if new_fp is not None or dump:
                clear_spinner()
                _print_status(node, self_addr)
    except KeyboardInterrupt:
        pass
    finally:
        try:
            # Exit summary: the final fingerprint/table even if the last
            # change predated the last period (keeps demo2's interleaved
            # output meaningful and the live test's final lines comparable).
            # Best-effort: never let a dead engine's status dump shadow the
            # exception that actually ended the loop.
            if spin:
                sys.stdout.write("\r  \r")
            _print_status(node, self_addr)
        except Exception:
            pass
        finally:
            if node.is_running:
                node.stop()
            node.close()
    return 0


def run_sim(args) -> int:
    import jax.numpy as jnp
    import numpy as np

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim import Scenario, baseline_scenario, init_state, simulate

    if args.sim_scenario:
        sc = baseline_scenario(args.sim_scenario, n=args.sim or None,
                               ticks=args.ticks, seed=args.seed)
    else:
        sc = Scenario(n=args.sim, ticks=args.ticks, seed=args.seed)
    state = init_state(sc.n, seed=args.seed, alive=jnp.asarray(sc.initial_alive()))
    telemetry = args.telemetry is not None
    if args.warp:
        # Event-horizon fast-forward: only the dense ticks produce metrics
        # (leaped spans are provably converged/quiet), so the summary reports
        # both counts plus end-state convergence (kaboodle_tpu.warp). With
        # --telemetry the leaped spans still contribute counter totals via
        # the closed form (telemetry.counters.leap_counters).
        from kaboodle_tpu.sim.runner import state_converged
        from kaboodle_tpu.warp.runner import (
            WarpLedger,
            simulate_warped,
            span_memo,
        )

        hybrid = not args.no_warp_hybrid
        memo = None if args.no_warp_memo else span_memo
        ledger = WarpLedger()
        t0 = time.perf_counter()
        if telemetry:
            final, dense_ticks, stacked, totals = simulate_warped(
                state, sc.build(), SwimConfig(), faulty=True, telemetry=True,
                hybrid=hybrid, ledger=ledger, memo=memo,
                warp_mode=args.warp_mode,
            )
            m = stacked.metrics if stacked is not None else None
            counters = stacked.counters if stacked is not None else None
        else:
            final, dense_ticks, m = simulate_warped(
                state, sc.build(), SwimConfig(), faulty=True,
                hybrid=hybrid, ledger=ledger, memo=memo,
                warp_mode=args.warp_mode,
            )
            counters = totals = None
        final_conv = bool(state_converged(final))
        wall = time.perf_counter() - t0
        per_class = ledger.per_class()
        out = {
            "n_peers": sc.n,
            "ticks": sc.ticks,
            "warp": True,
            "warp_hybrid": hybrid,
            "warp_mode": args.warp_mode,
            "warp_memo": memo is not None,
            "dense_ticks_executed": int(dense_ticks.size),
            "leaped_ticks": int(sc.ticks - dense_ticks.size),
            "leap_classes": {
                str(key): agg for key, agg in sorted(per_class.items())
            },
            "final_converged": final_conv,
            "wall_s": round(wall, 3),
        }
        if memo is not None:
            ms = memo.stats()
            out["warp_memo_stats"] = {
                "hits": ms["hits"], "misses": ms["misses"],
                "entries": ms["entries"], "bytes": ms["bytes"],
                "evictions": ms["evictions"],
                "hit_rate": round(ms["hit_rate"], 4),
            }
        if totals is not None:
            out["counter_totals"] = totals
        _write_sim_manifests(args, out, m, counters, ticks=dense_ticks,
                             warp_ledger=ledger)
        print(json.dumps(out))
        return 0 if out["final_converged"] else 2
    t0 = time.perf_counter()
    counters = recorder = None
    if telemetry:
        from kaboodle_tpu.sim.runner import simulate_with_telemetry
        from kaboodle_tpu.telemetry import counters_totals

        final, m, counters, recorder = simulate_with_telemetry(
            state, sc.build(), SwimConfig(),
            recorder_len=min(32, max(1, sc.ticks)),
        )
    else:
        final, m = simulate(state, sc.build(), SwimConfig())
    conv = np.asarray(m.converged)
    wall = time.perf_counter() - t0
    first = int(np.argmax(conv)) if conv.any() else -1
    out = {
        "n_peers": sc.n,
        "ticks": sc.ticks,
        "first_converged_tick": first,
        "final_converged": bool(conv[-1]),
        "final_agree_fraction": float(np.asarray(m.agree_fraction)[-1]),
        "messages_delivered": int(np.asarray(m.messages_delivered).sum()),
        "wall_s": round(wall, 3),
    }
    if counters is not None:
        out["counter_totals"] = counters_totals(counters)
    _write_sim_manifests(args, out, m, counters, recorder=recorder)
    print(json.dumps(out))
    return 0 if out["final_converged"] else 2


def _write_sim_manifests(args, out, metrics, counters, ticks=None,
                         recorder=None, warp_ledger=None) -> None:
    """The sim lane's manifest outputs (telemetry/manifest.py schema).

    ``--telemetry PATH`` gets the full manifest: a ``run`` record (the same
    summary dict the CLI prints), per-tick records with counters, and the
    flight-recorder dump — plus, for warped runs, one ``warp_spans``
    record per signature class (the per-class leap counters the
    summarizer aggregates) and one ``warp_blocked`` record per blocking
    term combo (the why-dense histogram). ``--metrics-jsonl PATH`` gets metrics-only
    ``tick`` records — the lightweight lane that needs no telemetry build.
    Both may be given; they are independent files.
    """
    if args.metrics_jsonl is None and args.telemetry is None:
        return
    from kaboodle_tpu.telemetry import ManifestWriter

    if args.telemetry is not None:
        with ManifestWriter(args.telemetry) as w:
            w.write("run", metric="sim_run", **out)
            if metrics is not None:
                w.write_tick_metrics(metrics, counters=counters, ticks=ticks)
            if recorder is not None:
                w.write_recorder(recorder)
            if warp_ledger is not None:
                for key, agg in sorted(warp_ledger.per_class().items()):
                    w.write("warp_spans", class_key=int(key), **agg)
                # Why-dense attribution: one record per blocking term combo
                # (pseudo-terms 'scheduled_event' / 'short_span' included).
                for term, agg in sorted(
                    warp_ledger.blocked_histogram().items()
                ):
                    w.write("warp_blocked", term=term, **agg)
        print(f"telemetry manifest: {args.telemetry}", file=sys.stderr)
    if args.metrics_jsonl is not None and metrics is not None:
        with ManifestWriter(args.metrics_jsonl) as w:
            w.write_tick_metrics(metrics, ticks=ticks)
        print(f"metrics manifest: {args.metrics_jsonl}", file=sys.stderr)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "fleet":
        # Ensemble sweep subcommand — its own parser (fleet/bench.py), so
        # the flag surfaces of the demo app and the sweep tool stay
        # independent.
        from kaboodle_tpu.fleet.bench import main as fleet_main

        return fleet_main(argv[1:])
    if argv and argv[0] == "telemetry":
        # Manifest summarizer/exporter subcommand (telemetry/summary.py):
        # host-side only — reads JSONL manifests, never dispatches a
        # device program.
        from kaboodle_tpu.telemetry.summary import main as telemetry_main

        return telemetry_main(argv[1:])
    if argv and argv[0] == "serve":
        # Gossip-as-a-service subcommand (serve/server.py): a resident
        # lane-pool simulation server over JSON-over-TCP. ``--dryrun``
        # routes to the in-process CI exercise.
        from kaboodle_tpu.serve.server import main as serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "serve-load":
        # Load driver for the serve server (serve/loadgen.py): closed+
        # open-loop phases against an in-process server, banks
        # BENCH_serve.json and gates on zero steady-phase compiles.
        from kaboodle_tpu.serve.loadgen import main as loadgen_main

        return loadgen_main(argv[1:])
    if argv and argv[0] == "fed-load":
        # Federation load + chaos driver (serve/federation/fedload.py):
        # two engines + consistent-hash router on loopback, SLO levels at
        # multiples of the serve baseline rate, kill-one-engine failover;
        # banks BENCH_fedserve.json. ``--dryrun`` is the CI lane.
        from kaboodle_tpu.serve.federation.fedload import main as fedload_main

        return fedload_main(argv[1:])
    if argv and argv[0] == "costscope":
        # Compiler/hardware-plane observatory (costscope/cli.py): static
        # cost+memory extraction over the graftscan registry gated against
        # .costscope_baseline.json, the collective-bytes roofline report,
        # and the ICI microbench.
        from kaboodle_tpu.costscope.cli import main as costscope_main

        return costscope_main(argv[1:])
    if argv and argv[0] == "sparse":
        # Blocked-sparse engine dryrun (sparseplane/dryrun.py): toy-N
        # stat check against the dense oracle + capped million-peer
        # smoke. The banked numbers live in `bench.py --sparse`.
        from kaboodle_tpu.sparseplane.dryrun import main as sparse_main

        return sparse_main(argv[1:])
    if argv and argv[0] == "phasegraph":
        # Derived-engine dryrun subcommand (phasegraph/dryrun.py): build
        # every engine the planner derives from the op graph at toy N,
        # run one tick each, diff bit-for-bit against dense.
        from kaboodle_tpu.phasegraph.dryrun import main as phasegraph_main

        return phasegraph_main(argv[1:])
    args = build_parser().parse_args(argv)
    try:
        if args.sim or args.sim_scenario:
            return run_sim(args)
        return run_real(args)
    except KaboodleError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
