"""Protocol configuration: the reference's tunable surface as one hashable config.

The reference hard-codes its constants in ``src/kaboodle.rs`` (wall-clock
durations) and ``src/discovery.rs``. The simulator is discrete-time, so every
duration is re-derived in *ticks* with ``PROTOCOL_PERIOD == 1 tick``
(kaboodle.rs:38). The mapping is documented per-field below.

``SwimConfig`` is a frozen (hashable) dataclass so it can be passed as a static
argument to ``jax.jit`` — changing a protocol constant recompiles the kernel,
which is exactly the XLA-friendly behavior we want (constants fold into the
compiled program).

Static-vs-traced is also the fleet contract (kaboodle_tpu/fleet): every field
here selects the ONE compiled program an ensemble shares, so SwimConfig values
cannot vary across fleet members — only traced per-member inputs (the
``TickInputs.drop_rate`` knob, the PRNG seed axis) can. A/B over a config
field is two fleet dispatches.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class SwimConfig:
    """All SWIM protocol constants plus parity flags for the reference's quirks.

    Reference citations point into /root/reference/src/.
    """

    # --- timing (reference wall-ms -> ticks, at 1000 ms/tick) ---------------
    # PROTOCOL_PERIOD = 1000 ms (kaboodle.rs:38). One tick IS the period.
    # PING_TIMEOUT = 2000 ms (kaboodle.rs:62): how long a peer stays in
    # WaitingForPing / WaitingForIndirectPing before escalation / removal.
    ping_timeout_ticks: int = 2
    # MAX_PEER_SHARE_AGE = 10000 ms (kaboodle.rs:49): only peers heard from
    # this recently are shared in KnownPeersRequest replies (kaboodle.rs:483-501)
    # and gossip-learned peers are back-dated by exactly this much so they are
    # never re-shared (kaboodle.rs:459-470, quirk Q6).
    max_peer_share_age_ticks: int = 10
    # REBROADCAST_INTERVAL = 10000 ms (kaboodle.rs:65): re-announce Join while
    # lonely (kaboodle.rs:228-251).
    rebroadcast_interval_ticks: int = 10

    # --- fan-outs -----------------------------------------------------------
    # NUM_INDIRECT_PING_PEERS (kaboodle.rs:52): k proxies per ping-req escalation.
    num_indirect_ping_peers: int = 3
    # NUM_CANDIDATE_TARGET_PEERS (kaboodle.rs:57): ping target is a uniform
    # choice among the 5 longest-unheard Known peers (kaboodle.rs:655-675).
    num_candidate_target_peers: int = 5

    # --- payload bounds -----------------------------------------------------
    # The reference trims Join-response KnownPeers payloads until they fit the
    # 10240-byte receive buffer (kaboodle.rs:373-383), ~300 entries at demo
    # identity sizes. 0 disables the cap. NOTE (quirk Q12): the reference does
    # NOT trim KnownPeersRequest replies (kaboodle.rs:483-501), so we only cap
    # the join-response path.
    max_share_peers: int = 300

    # --- bootstrap ----------------------------------------------------------
    # Join broadcasts (kaboodle.rs:228-251) make boot convergence instant on a
    # shared broadcast domain: every peer learns every peer in one tick. False
    # disables them (compiled out), modeling a mesh with no broadcast medium —
    # membership then spreads only via direct traffic + anti-entropy
    # (kaboodle.rs:707-740), the "gossip boot" the benchmark measures. Pair
    # with ``init_state(ring_contacts=...)`` seed contacts.
    join_broadcast_enabled: bool = True

    # --- protocol extensions (not in the reference; defaults are faithful) --
    # Q6 back-dating (kaboodle.rs:459-470) inserts gossip-learned peers as if
    # last heard MAX_PEER_SHARE_AGE ago, so they are never re-shared before
    # direct contact — the anti-echo that stops departed peers circulating
    # forever, at the cost of O(N)-tick membership spread (SEMANTICS §6b).
    # False = "epidemic boot": learned peers get fresh stamps and re-share
    # immediately, so anti-entropy pulls double knowledge per tick and a
    # broadcast-free boot converges in ~O(log N) ticks. Use for bootstrap
    # benchmarks/meshes without churn; the echo protection is off.
    backdate_gossip_inserts: bool = True

    # --- parity flags for behavioral quirks (SURVEY.md §8) ------------------
    # Q1/Q11: an inbound datagram marks its *sender* Known (kaboodle.rs:408-415);
    # a forwarded indirect-ping Ack therefore resurrects the proxy, NOT the
    # suspect — the suspect's suspicion is not cleared by the indirect path
    # (kaboodle.rs:417-447). True reproduces the reference; False implements the
    # SWIM-paper-intended semantics (forwarded ack clears suspicion).
    faithful_indirect_ack: bool = True
    # Q3: Failed broadcasts are inert in the reference because the broadcast
    # source address is never a known member (kaboodle.rs:268-283). True keeps
    # them inert; False implements the intended removal-on-Failed.
    faithful_failed_broadcast: bool = True

    # --- simulator-only knobs ----------------------------------------------
    # Replace all random draws (ping-target choice, proxy choice, broadcast-
    # reply Bernoulli) with deterministic lowest-index / always-respond picks.
    # Used for exact oracle-vs-kernel trajectory equality tests; the reference
    # seeds ChaChaRng from entropy (kaboodle.rs:164) so exact-sequence parity
    # with Rust is a non-goal (SURVEY.md §7).
    deterministic: bool = False
    # Compute the per-tick fingerprint/count reductions with the fused Pallas
    # kernel (ops/fused_fp.py) instead of the jnp formulation — bit-exact,
    # one guaranteed HBM pass, no [N, N] intermediates. Single-device only
    # (the GSPMD path keeps the jnp form, which XLA partitions row-locally);
    # requires N % 128 == 0. Off TPU it runs in pallas interpreter mode
    # (correct but slow). Demoted to tested-but-off in round 5: the
    # round-4c scan-amortized audit measured the jnp formulation faster
    # in-context (PERF.md "Pallas policy"), so bench no longer enables the
    # per-stage kernels anywhere.
    use_pallas_fp: bool = False
    # How the ping-target draw finds each row's oldest-k Known peers
    # (kaboodle.rs:661-675): "topk" = jax.lax.top_k (sort-based on TPU),
    # "iter" = k rounds of lexicographic min-reduction over (timer, index) —
    # identical results (both are the stable k-smallest; equality pinned in
    # tests/test_sampling.py), but the iterative form avoids sorting an
    # [N, N] matrix per tick, which dominates the tick on TPU at large N.
    oldest_k_method: str = "iter"
    # Compute the oldest-k candidates (eligibility + all k min-reduction
    # rounds) in one fused Pallas pass over the state/timer tiles instead of
    # k+1 jnp passes — bit-exact with the "iter" method (and so with stable
    # top_k); single-device, N % 128 == 0, interpret-mode off TPU, like
    # use_pallas_fp. Demoted to tested-but-off (see use_pallas_fp note;
    # this kernel lost its scan-amortized A/B 8x — PERF.md).
    use_pallas_oldest_k: bool = False
    # Compute the phase-A row statistics (membership count, timed-suspect
    # argmin, proxy-candidate existence) in one fused Pallas pass over
    # (state, timer) instead of 3-4 jnp passes — bit-exact
    # (tests/test_fused_suspicion.py); same constraints as the other fused
    # kernels. Demoted to tested-but-off (see use_pallas_fp note).
    use_pallas_suspicion: bool = False
    # Fault-free builds compile a two-branch tick: a lean path for ticks with
    # no join broadcast and no suspicion activity (the overwhelming majority
    # of every boot/steady/calm-recovery scan) that computes all delivery
    # masks from O(N) vectors and applies them in one composed write chain,
    # and the full path for everything else, selected per tick by lax.cond.
    # Bit-exact with the full path (tests/test_fast_path.py); the on-TPU
    # phase decomposition that motivated it is in PERF.md (round 4: the full
    # tick spends ~9 combined HBM sweeps where the lean ticks need ~3).
    fast_path: bool = True

    def __post_init__(self) -> None:
        if self.oldest_k_method not in ("topk", "iter"):
            raise ValueError("oldest_k_method must be 'topk' or 'iter'")
        if self.ping_timeout_ticks < 1:
            raise ValueError("ping_timeout_ticks must be >= 1")
        if self.num_indirect_ping_peers < 1:
            raise ValueError("num_indirect_ping_peers must be >= 1")
        if self.num_candidate_target_peers < 1:
            raise ValueError("num_candidate_target_peers must be >= 1")


# Reference wire/transport constants, kept for the interop edge (see
# kaboodle_tpu.transport). Values cited from the reference sources.
INCOMING_BUFFER_SIZE = 10240  # kaboodle.rs:43
DISCOVERY_BUFFER_SIZE = 1024  # discovery.rs:16
DEFAULT_BROADCAST_PORT = 7475  # justfile run2x2 demo port
IPV6_MULTICAST_GROUP = "ff02::1213:1989"  # networking.rs:86
PROBE_BACKOFF_START_MS = 1000  # discovery.rs:19
PROBE_BACKOFF_MULTIPLIER = 1.25  # discovery.rs:22
PROBE_BACKOFF_CAP_MS = 10000  # discovery.rs:25
LATENCY_EWMA_MOST_RECENT_WEIGHT = 0.8  # kaboodle.rs:810
