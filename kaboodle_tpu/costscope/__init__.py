"""costscope — compiler/hardware-plane observatory (ISSUE 15).

Three planes, all riding the graftscan entry-point registry so every
derived engine is covered for free:

- static cost plane (`extract`): per-entry `cost_analysis()` /
  `memory_analysis()` numbers, gated against the committed
  `.costscope_baseline.json` with graftlint-style shrink-only semantics;
- collective-bytes audit (`collectives`): walk the compiled HLO of the
  sharded twins and attribute bytes-on-ICI per dispatch per collective;
- roofline report (`roofline`): combine the static bytes with the banked
  wall-times from BENCH_*.json to place each kernel against the HBM/ICI
  floors PERF.md reasons about;
- ICI microbench (`icibench`): time the two protocol collectives
  (fingerprint-agreement all-reduce, union reduce-scatter) standalone.

Everything static runs on the CPU backend — no TPU window needed.
"""

from kaboodle_tpu.costscope.baseline import (  # noqa: F401
    DEFAULT_BASELINE,
    gate_measurements,
    load_baseline,
    write_baseline,
)
from kaboodle_tpu.costscope.collectives import (  # noqa: F401
    collective_audit,
    parse_collectives,
)
from kaboodle_tpu.costscope.extract import (  # noqa: F401
    cost_record,
    extract_entries,
    static_peak_bytes,
)
