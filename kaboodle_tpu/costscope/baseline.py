"""Committed cost baseline + graftlint-style shrink-only gating.

`.costscope_baseline.json` holds the per-entry static records for the
full registry.  Gate semantics mirror `analysis/cli.py`:

- an entry measured but absent from the baseline fails (the surface can
  only grow through an explicit `--write-baseline` commit);
- a gated field growing past tolerance fails — the CI regression gate on
  bytes-per-dispatch / peak-HBM / ICI bytes;
- under `--no-baseline-growth`, stale baseline entries (no longer in the
  registry) and significant shrinks also fail: improvements must be
  banked, so the baseline only ever ratchets down.

Tolerance absorbs compiler jitter across XLA point releases / host CPUs;
the gate is for shape-class regressions (a doubled dtype is +100%, far
outside the band).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

DEFAULT_BASELINE = ".costscope_baseline.json"
BASELINE_SCHEMA = "kaboodle-costscope/1"

# Fields the gate watches, each with (relative tolerance, absolute floor
# in bytes) — small entries wobble more in relative terms.
GATED_FIELDS: dict[str, tuple[float, int]] = {
    "bytes_accessed": (0.05, 4096),
    "peak_bytes": (0.05, 4096),
    "ici_bytes": (0.05, 1024),
}


def load_baseline(path: str | Path) -> dict[str, Any] | None:
    """Load a baseline file; None if absent; ValueError on bad schema."""
    p = Path(path)
    if not p.exists():
        return None
    data = json.loads(p.read_text())
    if not isinstance(data, dict) or data.get("schema") != BASELINE_SCHEMA:
        raise ValueError(f"{p}: not a {BASELINE_SCHEMA} baseline")
    entries = data.get("entries")
    if not isinstance(entries, dict):
        raise ValueError(f"{p}: missing 'entries' map")
    return data


def write_baseline(path: str | Path, measured: dict[str, dict[str, Any]]) -> None:
    payload = {"schema": BASELINE_SCHEMA, "entries": measured}
    Path(path).write_text(json.dumps(payload, indent=1, sort_keys=True) + "\n")


def _out_of_band(new: int, old: int, rel: float, floor: int) -> bool:
    return abs(new - old) > max(rel * max(old, 1), floor)


def gate_measurements(
    measured: dict[str, dict[str, Any]],
    baseline: dict[str, Any] | None,
    *,
    no_growth: bool = False,
    subset: bool = False,
) -> list[str]:
    """Compare measured records against the baseline; return failures.

    `subset` marks a `--entry`-filtered run: stale-entry checking is
    skipped because the live set is deliberately partial.
    """
    failures: list[str] = []
    if baseline is None:
        for name in sorted(measured):
            failures.append(
                f"{name}: no baseline — run with --write-baseline and commit "
                f"{DEFAULT_BASELINE}"
            )
        return failures
    base_entries = baseline["entries"]
    for name in sorted(measured):
        rec = measured[name]
        base = base_entries.get(name)
        if base is None:
            failures.append(
                f"{name}: entry not in baseline (new surface — bank it with "
                "--write-baseline)"
            )
            continue
        for field, (rel, floor) in GATED_FIELDS.items():
            new = int(rec.get(field, 0))
            old = int(base.get(field, 0))
            if not _out_of_band(new, old, rel, floor):
                continue
            if new > old:
                failures.append(
                    f"{name}: {field} grew {old} -> {new} "
                    f"({100.0 * (new - old) / max(old, 1):+.1f}%) — compiler-plane "
                    "regression; fix it or re-bank deliberately"
                )
            elif no_growth:
                failures.append(
                    f"{name}: {field} shrank {old} -> {new} — improvement must be "
                    "banked (--write-baseline) so the gate ratchets down"
                )
    if no_growth and not subset:
        live = set(measured)
        for name in sorted(set(base_entries) - live):
            failures.append(
                f"{name}: stale baseline entry (not in registry) — delete it via "
                "--write-baseline"
            )
    return failures
