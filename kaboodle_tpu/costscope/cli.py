"""`python -m kaboodle_tpu costscope` — the compiler-plane front door.

Modes (exit codes mirror graftlint: 0 clean, 1 findings/regression,
2 usage or format error):

- default: extract the static records for the registry (or an `--entry`
  subset) and gate them against `.costscope_baseline.json`;
- `--write-baseline`: re-bank the baseline instead of gating;
- `--report`: roofline report from the committed baseline + banked
  BENCH_*.json walls — no compiles, no hardware;
- `--icibench [--dryrun]`: time the two protocol collectives; on real
  multi-chip hardware banks MULTICHIP_ici.json.
"""

from __future__ import annotations

import argparse
import json
import sys


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kaboodle_tpu costscope",
        description="XLA cost/memory telemetry, collective-bytes audit, "
        "roofline gate, ICI microbench",
    )
    p.add_argument(
        "--entry",
        action="append",
        default=None,
        help="restrict to registry entries (repeatable / comma-separated); "
        "stale-entry checking is skipped for subsets",
    )
    p.add_argument(
        "--baseline",
        default=None,
        help="baseline path (default: .costscope_baseline.json)",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="re-bank the baseline from this run instead of gating",
    )
    p.add_argument(
        "--no-baseline-growth",
        action="store_true",
        help="shrink-only ratchet: stale entries and unbanked improvements "
        "also fail (the CI invocation)",
    )
    p.add_argument("--report", action="store_true", help="roofline report mode")
    p.add_argument(
        "--icibench", action="store_true", help="ICI microbench mode"
    )
    p.add_argument(
        "--dryrun",
        action="store_true",
        help="icibench: small deterministic CPU sweep, no banking",
    )
    p.add_argument(
        "--repeats", type=int, default=3, help="icibench: timing repeats"
    )
    p.add_argument(
        "--json", default=None, help="also write the mode's JSON payload here"
    )
    p.add_argument(
        "--manifest",
        default=None,
        help="append per-entry `costscope` records to this "
        "kaboodle-telemetry/1 manifest",
    )
    return p


def _entry_names(args) -> list[str] | None:
    if not args.entry:
        return None
    names: list[str] = []
    for chunk in args.entry:
        names.extend(n for n in chunk.split(",") if n)
    return names or None


def _dump_json(path: str | None, payload) -> None:
    if path:
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")


def _write_manifest(path: str, measured: dict) -> None:
    from kaboodle_tpu.telemetry.manifest import ManifestWriter

    with ManifestWriter(path) as w:
        for name in sorted(measured):
            rec = measured[name]
            w.write(
                "costscope",
                entry=name,
                flops=rec["flops"],
                bytes_accessed=rec["bytes_accessed"],
                peak_bytes=rec["peak_bytes"],
                ici_bytes=rec["ici_bytes"],
                sharded=rec["sharded"],
            )


def _run_gate(args) -> int:
    from kaboodle_tpu.costscope.baseline import (
        DEFAULT_BASELINE,
        gate_measurements,
        load_baseline,
        write_baseline,
    )
    from kaboodle_tpu.costscope.extract import extract_entries

    path = args.baseline or DEFAULT_BASELINE
    names = _entry_names(args)
    try:
        measured = extract_entries(names)
    except KeyError as e:
        print(f"costscope: unknown entry {e}", file=sys.stderr)
        return 2
    _dump_json(args.json, measured)
    if args.manifest:
        _write_manifest(args.manifest, measured)
    if args.write_baseline:
        if names:
            # Subset re-bank: merge into the existing baseline.
            existing = load_baseline(path)
            merged = dict(existing["entries"]) if existing else {}
            merged.update(measured)
            write_baseline(path, merged)
        else:
            write_baseline(path, measured)
        print(f"costscope: banked {len(measured)} entries -> {path}")
        return 0
    try:
        baseline = load_baseline(path)
    except ValueError as e:
        print(f"costscope: {e}", file=sys.stderr)
        return 2
    failures = gate_measurements(
        measured,
        baseline,
        no_growth=args.no_baseline_growth,
        subset=names is not None,
    )
    for f in failures:
        print(f"costscope: {f}")
    n_shard = sum(1 for r in measured.values() if r["sharded"])
    print(
        f"costscope: {len(measured)} entries gated vs {path} "
        f"({n_shard} sharded), {len(failures)} failure(s)"
    )
    return 1 if failures else 0


def _run_report(args) -> int:
    from kaboodle_tpu.costscope.baseline import DEFAULT_BASELINE, load_baseline
    from kaboodle_tpu.costscope.roofline import (
        render_report,
        roofline_from_baseline,
    )

    path = args.baseline or DEFAULT_BASELINE
    try:
        baseline = load_baseline(path)
    except ValueError as e:
        print(f"costscope: {e}", file=sys.stderr)
        return 2
    if baseline is None:
        print(
            f"costscope: no baseline at {path} — run the extract first",
            file=sys.stderr,
        )
        return 2
    names = _entry_names(args)
    if names:
        missing = [n for n in names if n not in baseline["entries"]]
        if missing:
            print(f"costscope: not in baseline: {missing}", file=sys.stderr)
            return 2
        baseline = {
            **baseline,
            "entries": {n: baseline["entries"][n] for n in names},
        }
    report = roofline_from_baseline(baseline)
    print(render_report(report))
    _dump_json(args.json, report)
    return 0


def _run_icibench(args) -> int:
    from kaboodle_tpu.costscope.icibench import (
        BANK_PATH,
        DRYRUN_SIZES,
        HW_SIZES,
        bank,
        render,
        run_sweep,
    )

    sizes = DRYRUN_SIZES if args.dryrun else HW_SIZES
    report = run_sweep(sizes, repeats=1 if args.dryrun else args.repeats)
    print(render(report))
    _dump_json(args.json, report)
    if not args.dryrun and report["backend"] == "tpu" and report["n_devices"] > 1:
        bank(report, BANK_PATH)
        print(f"icibench: banked -> {BANK_PATH}")
    elif not args.dryrun:
        print(
            "icibench: not multi-chip TPU "
            f"(backend={report['backend']}, devices={report['n_devices']}) "
            "— nothing banked"
        )
    return 0


def main(argv: list[str] | None = None) -> int:
    from kaboodle_tpu.costscope.extract import prepare_backend

    args = _build_parser().parse_args(argv)
    if args.report and args.icibench:
        print("costscope: pick one of --report / --icibench", file=sys.stderr)
        return 2
    if not args.report:
        # Every compiling mode needs the CPU-pinned virtual mesh for the
        # sharded twins; must run before any backend touch.
        prepare_backend()
    if args.report:
        return _run_report(args)
    if args.icibench:
        return _run_icibench(args)
    return _run_gate(args)


if __name__ == "__main__":
    raise SystemExit(main())
