"""Collective-bytes audit: walk compiled HLO, attribute bytes-on-ICI.

The sharded twins pay exactly the cross-chip traffic visible in their
optimized HLO — lines like

    %all-gather.66 = u32[32]{0} all-gather(u32[4]{0} %bitcast.52),
        channel_id=167, replica_groups=[1,8]<=[8], dimensions={0}

We parse the result shape(s), the collective kind and the replica-group
size D, then charge per-chip ICI bytes with the standard ring-algorithm
attribution (R = result bytes per chip):

- all-reduce:          2 * R * (D - 1) / D   (reduce-scatter + all-gather)
- all-gather:          R * (D - 1) / D       (R is the gathered size)
- reduce-scatter:      R * (D - 1)           (R is the shard; total S = R*D)
- all-to-all:          R * (D - 1) / D
- collective-permute:  R

Static attribution is per compiled occurrence: a collective inside a
`while` body counts once, so these numbers are bytes *per dispatch* of
the entry (one tick / one leap), which is the unit PERF.md reasons in.
"""

from __future__ import annotations

import math
import re
from typing import Any

COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

_DTYPE_BYTES = {
    "pred": 1,
    "s8": 1,
    "u8": 1,
    "s16": 2,
    "u16": 2,
    "f16": 2,
    "bf16": 2,
    "s32": 4,
    "u32": 4,
    "f32": 4,
    "s64": 8,
    "u64": 8,
    "f64": 8,
    "c64": 8,
    "c128": 16,
}

# One HLO instruction: "%name = <result-type> <kind>(operands), attrs".
# -start/-done async pairs appear on some backends; only the -start (or
# the plain sync form) carries the transfer, -done is shape-only.
_INSTR_RE = re.compile(
    r"=\s*(?P<result>\(?[^=]*?)\s*"
    r"(?P<kind>" + "|".join(re.escape(k) for k in COLLECTIVE_KINDS) + r")"
    r"(?P<variant>-start|-done)?\("
)

_SHAPE_RE = re.compile(r"(?P<dtype>[a-z]+\d*)\[(?P<dims>[0-9,]*)\]")

_GROUP_PAIR_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
_GROUP_SET_RE = re.compile(r"replica_groups=\{\{([^}]*)\}")


def _shape_bytes(result: str) -> int:
    """Total bytes across every `dtype[dims]` group in a result type."""
    total = 0
    for m in _SHAPE_RE.finditer(result):
        size = _DTYPE_BYTES.get(m.group("dtype"))
        if size is None:
            continue
        dims = m.group("dims")
        n = 1
        if dims:
            n = math.prod(int(d) for d in dims.split(",") if d)
        total += n * size
    return total


def _group_size(line: str, n_devices: int) -> int:
    """Participants per replica group (D in the ring formulas)."""
    m = _GROUP_PAIR_RE.search(line)
    if m:
        # iota form [G,D]<=[N]: D participants in each of G groups.
        return max(int(m.group(2)), 1)
    m = _GROUP_SET_RE.search(line)
    if m:
        first = [d for d in m.group(1).split(",") if d.strip()]
        return max(len(first), 1)
    return max(n_devices, 1)


def _ici_bytes(kind: str, result_bytes: int, d: int) -> int:
    if d <= 1:
        return 0
    if kind == "all-reduce":
        return int(2 * result_bytes * (d - 1) / d)
    if kind == "all-gather":
        return int(result_bytes * (d - 1) / d)
    if kind == "reduce-scatter":
        return int(result_bytes * (d - 1))
    if kind == "all-to-all":
        return int(result_bytes * (d - 1) / d)
    return int(result_bytes)  # collective-permute: the whole buffer moves


def parse_collectives(hlo_text: str, n_devices: int = 1) -> list[dict[str, Any]]:
    """Parse every byte-moving collective instruction out of HLO text."""
    out: list[dict[str, Any]] = []
    for line in hlo_text.splitlines():
        m = _INSTR_RE.search(line)
        if m is None or m.group("variant") == "-done":
            continue
        kind = m.group("kind")
        result_bytes = _shape_bytes(m.group("result"))
        d = _group_size(line, n_devices)
        out.append(
            {
                "kind": kind,
                "result_bytes": result_bytes,
                "group_size": d,
                "ici_bytes": _ici_bytes(kind, result_bytes, d),
            }
        )
    return out


def collective_audit(compiled: Any) -> dict[str, Any]:
    """Audit one compiled executable: per-kind counts + total ICI bytes."""
    try:
        n_devices = len(compiled.input_shardings[0][0].mesh.devices.flat)  # type: ignore[index]
    except Exception:
        n_devices = 1
    rows = parse_collectives(compiled.as_text(), n_devices=n_devices)
    counts: dict[str, dict[str, int]] = {}
    total = 0
    for row in rows:
        agg = counts.setdefault(
            row["kind"], {"count": 0, "result_bytes": 0, "ici_bytes": 0}
        )
        agg["count"] += 1
        agg["result_bytes"] += row["result_bytes"]
        agg["ici_bytes"] += row["ici_bytes"]
        total += row["ici_bytes"]
    return {"ici_bytes": total, "counts": counts}
