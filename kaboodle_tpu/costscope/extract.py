"""Static cost plane: AOT-compile registry entries, extract XLA telemetry.

Every kernel the repo ships is reachable through the graftscan entry-point
registry (`analysis/ir/registry.py`), so one walk covers dense, fused,
chunked, sharded, fleet, warp and serve engines.  For each entry we lower
and compile on the CPU backend and pull out:

- `cost_analysis()`: FLOPs and bytes-accessed of the optimized HLO;
- `memory_analysis()`: argument / output / temp / generated-code bytes,
  from which a static peak is derived (what the program needs resident,
  aliased buffers counted once);
- the collective walk (`collectives.collective_audit`): bytes-on-ICI.

The numbers are trace-scale (registry TRACE_N=32) — they gate *shape*
regressions (a doubled dtype, a materialized [N, N] temp, a new
collective), not absolute production footprints.
"""

from __future__ import annotations

import os
from typing import Any

MESH_DEVICES = 8


def prepare_backend(devices: int = MESH_DEVICES) -> None:
    """Pin CPU + virtual multi-device mesh before backend init.

    Safe to call after `import jax` as long as no backend has been
    created yet (same contract as analysis/ir/scan._prepare_backend);
    the sharded registry entries need `devices` visible devices.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={devices}"
        ).strip()
    try:
        from axon_guard import strip_axon_plugin

        strip_axon_plugin()
    except ImportError:
        pass


def static_peak_bytes(mem: Any) -> int:
    """Static peak-resident estimate from a CompiledMemoryStats.

    argument + output + temp, with donated/aliased bytes counted once.
    This is what `peak_hbm_mib_static` in bench captures is derived from
    when the runtime `memory_stats()` comes back empty (the tunnel case).
    """
    peak = (
        int(mem.argument_size_in_bytes)
        + int(mem.output_size_in_bytes)
        + int(mem.temp_size_in_bytes)
        - int(getattr(mem, "alias_size_in_bytes", 0))
    )
    return max(peak, 0)


def compile_entry(entry: Any) -> Any:
    """Lower + compile one registry entry with the AOT API."""
    import jax

    fn, example_args = entry.build()
    return jax.jit(fn).lower(*example_args).compile()


def cost_record(entry: Any, compiled: Any = None) -> dict[str, Any]:
    """Extract the full static record for one entry.

    `cost_analysis()` returns a list on jax 0.4.x — element 0 holds the
    dict; keys of interest are 'flops' and 'bytes accessed'.
    """
    from kaboodle_tpu.costscope.collectives import collective_audit

    comp = compiled if compiled is not None else compile_entry(entry)
    ca = comp.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    mem = comp.memory_analysis()
    audit = collective_audit(comp)
    return {
        "flops": int(ca.get("flops", 0)),
        "bytes_accessed": int(ca.get("bytes accessed", 0)),
        "argument_bytes": int(mem.argument_size_in_bytes),
        "output_bytes": int(mem.output_size_in_bytes),
        "temp_bytes": int(mem.temp_size_in_bytes),
        "generated_code_bytes": int(mem.generated_code_size_in_bytes),
        "peak_bytes": static_peak_bytes(mem),
        "ici_bytes": int(audit["ici_bytes"]),
        "collectives": audit["counts"],
        "sharded": bool(entry.sharded),
    }


def extract_entries(names: list[str] | None = None) -> dict[str, dict[str, Any]]:
    """Walk the registry (or a named subset) and extract every record.

    Entries are compiled one at a time; a failure aborts loudly rather
    than silently shrinking the gated surface.
    """
    from kaboodle_tpu.analysis.ir.registry import ENTRY_POINTS, select_entries

    entries = select_entries(names) if names else list(ENTRY_POINTS)
    out: dict[str, dict[str, Any]] = {}
    for entry in entries:
        out[entry.name] = cost_record(entry)
    return out
