"""ICI microbench: the two collectives the protocol actually pays.

ROADMAP item 4(b): before a Swing-style schedule (PAPERS.md 2401.09356)
can be justified, measure what XLA's own choice costs for

1. the fingerprint-agreement all-reduce — the convergence check reduces
   per-row uint32 fingerprints to a global (min, max) pair every tick
   (`sharded_convergence_check`); expressed here as shard-local min/max
   + `lax.pmin`/`lax.pmax` over the peer axis, exactly the reduction
   GSPMD inserts for the check;
2. the union reduce-scatter — the join-gossip contraction runs over the
   sharded axis, so GSPMD reduce-scatters int32 partial unions; here
   each chip contributes a full [rows, cols] partial and keeps its row
   block of the sum (`lax.psum_scatter`).

On CPU (`--dryrun`) the sweep runs the small sizes deterministically and
asserts correctness — CI coverage for the harness itself.  On real
multi-chip hardware it banks `MULTICHIP_ici.json` (the same artifact
shape the TPU watcher banks), closing the "B unmeasured" unknown in
PERF.md's round-5 projection.

Times are whole-dispatch walls (best of `repeats`), so small sizes are
dispatch-overhead-dominated; the large-size asymptote is the bandwidth
estimate.  Bytes-on-ICI use the same ring attribution as the static
audit (collectives.py) so the two planes are directly comparable.
"""

from __future__ import annotations

import json
import time
from typing import Any

from kaboodle_tpu.costscope.collectives import _ici_bytes

DRYRUN_SIZES = (256, 1024)
HW_SIZES = (1 << 10, 1 << 14, 1 << 18, 1 << 22)
UNION_COLS = 64
BANK_PATH = "MULTICHIP_ici.json"


def _mesh(n_devices: int | None = None):
    from kaboodle_tpu.parallel.mesh import make_mesh

    return make_mesh(n_devices)


def make_agreement_allreduce(mesh):
    """uint32[n] fingerprints -> replicated (min, max) over the peer axis."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from kaboodle_tpu.parallel.mesh import PEER_AXIS

    def body(fp):
        lo = jax.lax.pmin(jnp.min(fp), PEER_AXIS)
        hi = jax.lax.pmax(jnp.max(fp), PEER_AXIS)
        return lo, hi

    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=P(PEER_AXIS), out_specs=(P(), P())
        )
    )


def make_union_reduce_scatter(mesh):
    """int32[D, rows, cols] partials -> summed [rows, cols], row-scattered."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from kaboodle_tpu.parallel.mesh import PEER_AXIS

    def body(part):
        # Each device holds one [1, rows, cols] partial; psum_scatter sums
        # across the axis and leaves this device its rows/D block.
        return jax.lax.psum_scatter(
            part[0], PEER_AXIS, scatter_dimension=0, tiled=True
        )

    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=P(PEER_AXIS, None, None),
            out_specs=P(PEER_AXIS, None),
        )
    )


def _time_best(fn, args, repeats: int) -> float:
    import jax

    best = float("inf")
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best


def run_sweep(
    sizes: tuple[int, ...],
    n_devices: int | None = None,
    repeats: int = 3,
    check: bool = True,
) -> dict[str, Any]:
    """Time both collectives across `sizes`; optionally assert correctness."""
    import jax
    import jax.numpy as jnp

    mesh = _mesh(n_devices)
    d = mesh.size
    agree = make_agreement_allreduce(mesh)
    union = make_union_reduce_scatter(mesh)
    results: list[dict[str, Any]] = []
    for n in sizes:
        n = max(n, d)
        n -= n % d  # shard-divisible
        fp = jnp.arange(n, dtype=jnp.uint32) + jnp.uint32(7)
        lo, hi = agree(fp)
        if check:
            assert int(lo) == 7 and int(hi) == n + 6, (int(lo), int(hi))
        fp_bytes = n * 4
        results.append(
            {
                "collective": "agreement_all_reduce",
                "n": int(n),
                "payload_bytes": fp_bytes,
                # the check reduces to scalars, but the traffic XLA pays in
                # the real tick is the [N] uint32 min+max all-reduce pair
                "ici_bytes_ring": 2 * _ici_bytes("all-reduce", fp_bytes, d),
                "wall_s_best": round(_time_best(agree, (fp,), repeats), 9),
            }
        )
        rows = max(d, n // UNION_COLS)
        rows -= rows % d
        part = jnp.ones((d, rows, UNION_COLS), dtype=jnp.int32)
        out = union(part)
        if check:
            assert out.shape == (rows, UNION_COLS), out.shape
            assert int(jnp.min(out)) == d == int(jnp.max(out)), (
                int(jnp.min(out)),
                d,
            )
        total_bytes = rows * UNION_COLS * 4
        results.append(
            {
                "collective": "union_reduce_scatter",
                "n": int(rows),
                "payload_bytes": total_bytes,
                "ici_bytes_ring": _ici_bytes(
                    "reduce-scatter", total_bytes // d, d
                ),
                "wall_s_best": round(_time_best(union, (part,), repeats), 9),
            }
        )
    for r in results:
        wall = r["wall_s_best"]
        r["gbps_ring"] = round(r["ici_bytes_ring"] / wall / 1e9, 3) if wall else None
    return {
        "schema": "kaboodle-costscope-ici/1",
        "backend": jax.default_backend(),
        "n_devices": int(d),
        "repeats": int(repeats),
        "results": results,
    }


def bank(report: dict[str, Any], path: str = BANK_PATH) -> None:
    with open(path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")


def render(report: dict[str, Any]) -> str:
    lines = [
        f"icibench — backend={report['backend']} devices={report['n_devices']} "
        f"(best of {report['repeats']})",
        f"{'collective':<24} {'n':>9} {'payload':>12} {'ICI bytes':>11} "
        f"{'wall':>10} {'ring GB/s':>10}",
    ]
    for r in report["results"]:
        lines.append(
            f"{r['collective']:<24} {r['n']:>9} {r['payload_bytes']:>12} "
            f"{r['ici_bytes_ring']:>11} {r['wall_s_best'] * 1e3:>8.3f}ms "
            f"{r['gbps_ring'] if r['gbps_ring'] is not None else 'n/a':>10}"
        )
    return "\n".join(lines)
