"""Roofline report: static bytes vs banked wall-times, no hardware needed.

Combines the committed `.costscope_baseline.json` (trace-scale static
bytes / FLOPs / ICI bytes per entry) with the wall-times already banked
in BENCH_*.json to place each kernel against the floors PERF.md reasons
about:

- HBM floor: the measured effective sweep bandwidth (~271 GB/s through
  this stack on the v5e capture, PERF.md round-5) — static bytes / BW is
  the floor time a dispatch cannot beat;
- ICI floor: per-chip link bandwidth is the one *remaining* unknown
  (public order 1e2 GB/s); the report quotes floor times at the 50 and
  100 GB/s bookends until `icibench` banks a measurement.

Banked walls come from any BENCH_*.json record carrying the
`simulated_peers_ticks_per_sec_per_chip` metric (s/tick/chip =
n_peers / value), so the report runs entirely from committed artifacts.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any

# Measured effective HBM bandwidth (PERF.md round-5: int8 [N,N] r+w sweep
# 1.93 ms at N=16,384 => ~271 GB/s through this stack).
EFFECTIVE_HBM_GBPS = 271.0
# ICI bookends: per-chip link bandwidth is unmeasured (ROADMAP item 4b).
ICI_GBPS_BOOKENDS = (50.0, 100.0)

WALL_METRIC = "simulated_peers_ticks_per_sec_per_chip"


def load_bench_walls(root: str = ".") -> list[dict[str, Any]]:
    """Scan BENCH_*.json for banked per-tick wall-times."""
    walls: list[dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_*.json"))):
        try:
            data = json.loads(open(path).read())
        except (OSError, ValueError):
            continue
        records = data if isinstance(data, list) else [data]
        for rec in records:
            if not isinstance(rec, dict) or rec.get("metric") != WALL_METRIC:
                continue
            value = rec.get("value") or 0
            n_peers = rec.get("n_peers") or 0
            if value <= 0 or n_peers <= 0:
                continue
            walls.append(
                {
                    "source": os.path.basename(path),
                    "backend": rec.get("backend", "?"),
                    "n_peers": int(n_peers),
                    "peers_ticks_per_s": float(value),
                    "s_per_tick": n_peers / float(value),
                }
            )
    return walls


def _fmt_bytes(n: int) -> str:
    if n >= 1 << 20:
        return f"{n / (1 << 20):.2f} MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f} KiB"
    return f"{n} B"


def build_report(
    entries: dict[str, dict[str, Any]],
    walls: list[dict[str, Any]],
    trace_n: int,
) -> dict[str, Any]:
    """Assemble the roofline structure from static records + banked walls."""
    rows = []
    for name in sorted(entries):
        rec = entries[name]
        bytes_accessed = int(rec.get("bytes_accessed", 0))
        flops = int(rec.get("flops", 0))
        ici = int(rec.get("ici_bytes", 0))
        row: dict[str, Any] = {
            "entry": name,
            "flops": flops,
            "bytes_accessed": bytes_accessed,
            "peak_bytes": int(rec.get("peak_bytes", 0)),
            "ici_bytes": ici,
            "sharded": bool(rec.get("sharded", False)),
            "intensity_flops_per_byte": (
                round(flops / bytes_accessed, 4) if bytes_accessed else 0.0
            ),
            "hbm_floor_us": round(bytes_accessed / EFFECTIVE_HBM_GBPS / 1e3, 3),
        }
        if ici:
            row["ici_floor_us"] = {
                f"{int(b)}GBps": round(ici / b / 1e3, 3) for b in ICI_GBPS_BOOKENDS
            }
        rows.append(row)

    # Place the banked steady-tick walls against the HBM floor: the static
    # bytes are trace-scale, and the dominant traffic is the [N, N] state
    # sweeps, so scale by (n_peers / TRACE_N)^2 to the capture's N.
    placements = []
    tick_entries = [r for r in rows if r["entry"].endswith("tick.faulty")]
    for wall in walls:
        for r in tick_entries:
            scale = (wall["n_peers"] / trace_n) ** 2
            floor_s = r["bytes_accessed"] * scale / (EFFECTIVE_HBM_GBPS * 1e9)
            placements.append(
                {
                    "entry": r["entry"],
                    "source": wall["source"],
                    "backend": wall["backend"],
                    "n_peers": wall["n_peers"],
                    "wall_s_per_tick": round(wall["s_per_tick"], 6),
                    "hbm_floor_s_per_tick": round(floor_s, 6),
                    "wall_over_floor": (
                        round(wall["s_per_tick"] / floor_s, 2)
                        if floor_s > 0
                        else None
                    ),
                }
            )
    return {
        "schema": "kaboodle-costscope-roofline/1",
        "trace_n": trace_n,
        "effective_hbm_gbps": EFFECTIVE_HBM_GBPS,
        "ici_gbps_bookends": list(ICI_GBPS_BOOKENDS),
        "entries": rows,
        "banked_walls": walls,
        "placements": placements,
    }


def render_report(report: dict[str, Any]) -> str:
    """Human table: the per-entry static plane, then banked placements."""
    lines = [
        f"costscope roofline — trace N={report['trace_n']}, "
        f"HBM {report['effective_hbm_gbps']:.0f} GB/s effective, "
        f"ICI bookends {report['ici_gbps_bookends']} GB/s",
        "",
        f"{'entry':<34} {'flops':>12} {'bytes':>12} {'peak':>12} "
        f"{'ICI':>10} {'fl/B':>7} {'HBMfloor':>9}",
    ]
    for r in report["entries"]:
        lines.append(
            f"{r['entry']:<34} {r['flops']:>12} "
            f"{_fmt_bytes(r['bytes_accessed']):>12} "
            f"{_fmt_bytes(r['peak_bytes']):>12} "
            f"{_fmt_bytes(r['ici_bytes']):>10} "
            f"{r['intensity_flops_per_byte']:>7.3f} "
            f"{r['hbm_floor_us']:>7.1f}us"
        )
    if report["placements"]:
        lines += ["", "banked walls vs HBM floor (bytes scaled (N/traceN)^2):"]
        for p in report["placements"]:
            ratio = p["wall_over_floor"]
            lines.append(
                f"  {p['source']:<24} {p['backend']:<5} N={p['n_peers']:<7} "
                f"wall {p['wall_s_per_tick'] * 1e3:8.3f} ms/tick   "
                f"floor {p['hbm_floor_s_per_tick'] * 1e3:8.3f} ms   "
                f"wall/floor {ratio if ratio is not None else 'n/a'}"
            )
    elif not report["banked_walls"]:
        lines += ["", "no banked BENCH_*.json walls found — static plane only"]
    sharded = [r for r in report["entries"] if r["sharded"]]
    if sharded:
        lines += ["", "per-collective ICI floors (sharded entries):"]
        for r in sharded:
            floors = r.get("ici_floor_us")
            if floors:
                body = ", ".join(f"{k}: {v}us" for k, v in floors.items())
                lines.append(f"  {r['entry']:<34} {body}")
    return "\n".join(lines)


def roofline_from_baseline(
    baseline: dict[str, Any], root: str = "."
) -> dict[str, Any]:
    from kaboodle_tpu.analysis.ir.registry import TRACE_N

    walls = load_bench_walls(root)
    return build_report(baseline["entries"], walls, trace_n=TRACE_N)
