"""Error types mirroring the reference's ``KaboodleError`` enum (errors.rs:8-24).

Python exceptions replace the Rust enum; variants map one-to-one. ``IoError``
wraps OS-level failures from the real transport (the simulator cannot produce
them), and the two interface errors only arise in the interop path
(networking.rs:12-23, 68-119).
"""

from __future__ import annotations


class KaboodleError(Exception):
    """Base class for all framework errors (errors.rs:8)."""


class InvalidOperation(KaboodleError):
    """Operation not valid in the current lifecycle state — e.g. starting a
    running instance or stopping a stopped one (errors.rs:10-11)."""


class IoError(KaboodleError):
    """Wrapped OS/transport error (errors.rs:13-14)."""


class NoAvailableInterfaces(KaboodleError):
    """No usable non-loopback network interface (errors.rs:16-17)."""


class UnableToFindInterfaceNumber(KaboodleError):
    """Interface index lookup failed for IPv6 multicast join (errors.rs:19-20)."""


class StoppingFailed(KaboodleError):
    """The protocol loop did not acknowledge cancellation (errors.rs:22-23)."""


class ConvergenceTimeout(KaboodleError):
    """Simulator-specific (no reference equivalent): a bounded convergence
    drive ended without fingerprint agreement."""


class CheckpointError(KaboodleError):
    """A checkpoint file could not be read back as a kaboodle checkpoint —
    missing, truncated, not a zip/npz at all, wrong marker, or missing
    entries. Raised instead of the raw ``zipfile``/``KeyError``/``OSError``
    so callers (the serve restore path above all) can degrade a single
    request instead of tearing down the host loop."""
