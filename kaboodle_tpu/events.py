"""Event derivation: membership diffs -> discovered / departed / fingerprint events.

The reference implements this with a channel pipeline: every mutation of the
shared ``ObservableHashMap`` emits Added/Updated/Removed to an observer task
(observable_hashmap.rs:84-142) which batches bursts, derives semantic events,
and fans them out to consumer channels (events.rs:18-125). In the simulator
membership is a tensor and a tick is the natural batch, so the whole pipeline
collapses to a pure diff between consecutive snapshots — no channels, no
locks, no observer registry. The facade (kaboodle_tpu.api) owns delivery.

Semantics preserved from events.rs:

- *Batching*: all changes within one feed are one batch; a peer removed and
  re-added inside the batch produces no event (events.rs:88-99) — with tensor
  diffs this holds by construction, since only the net change is visible.
- *Updated is ignored unless the identity changed* (events.rs:80-87); an
  identity change re-announces the peer on the discovery stream with its new
  identity.
- *Fingerprint dedup* (events.rs:103-122): a FingerprintChanged event fires
  only when the recomputed fingerprint differs from the last announced one.
- *Quirk Q10*: the fingerprint of an empty map is 0 and is deliberately never
  announced (events.rs:110-117).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from kaboodle_tpu.oracle.fingerprint import mix_fingerprint


@dataclasses.dataclass(frozen=True)
class PeerDiscovered:
    """A peer entered the map, or re-announced with a new identity
    (events.rs:59-87)."""

    peer: int
    identity: int


@dataclasses.dataclass(frozen=True)
class PeerDeparted:
    """A peer left the map (events.rs:88-99)."""

    peer: int


@dataclasses.dataclass(frozen=True)
class FingerprintChanged:
    """The observer's mesh fingerprint changed (events.rs:103-122)."""

    fingerprint: int


Event = PeerDiscovered | PeerDeparted | FingerprintChanged


class EventTap:
    """Derives the reference's three event streams for one observer's row.

    Feed consecutive membership snapshots (one per tick, or coarser — any
    cadence is a valid batch boundary); get the semantic events of each batch.
    """

    def __init__(self) -> None:
        self._member: np.ndarray | None = None  # bool [N]
        self._identity: np.ndarray | None = None  # uint32 [N]
        self._announced_fp: int = 0  # Q10: empty-map fp 0 counts as announced

    def feed(self, member, identities, fingerprint: int | None = None) -> list[Event]:
        """Diff against the previous snapshot; return this batch's events.

        Args:
          member: bool [N] — the observer's current membership row.
          identities: uint32 [N] — identity words (only entries where
            ``member`` is True are read).
          fingerprint: precomputed row fingerprint (must equal
            ``mix_fingerprint`` of the row — the kernel/ops value qualifies);
            computed host-side when omitted.
        """
        member = np.asarray(member, dtype=bool)
        identities = np.asarray(identities, dtype=np.uint32)
        events: list[Event] = []

        if self._member is None:
            added = np.flatnonzero(member)
            changed = np.empty(0, dtype=np.int64)
            removed = np.empty(0, dtype=np.int64)
        else:
            added = np.flatnonzero(member & ~self._member)
            removed = np.flatnonzero(self._member & ~member)
            stayed = member & self._member
            changed = np.flatnonzero(stayed & (identities != self._identity))

        for p in added:
            events.append(PeerDiscovered(int(p), int(identities[p])))
        for p in changed:
            events.append(PeerDiscovered(int(p), int(identities[p])))
        for p in removed:
            events.append(PeerDeparted(int(p)))

        if fingerprint is None:
            fingerprint = mix_fingerprint(
                {int(p): int(identities[p]) for p in np.flatnonzero(member)}
            )
        fp = fingerprint
        if fp != self._announced_fp and member.any():
            events.append(FingerprintChanged(fp))
            self._announced_fp = fp

        self._member = member.copy()
        self._identity = identities.copy()
        return events


def membership_diff(prev_member, member):
    """Vectorized whole-mesh diff: (added, removed) bool [N, N] masks.

    ``added[i, j]``: peer i discovered peer j this step; ``removed[i, j]``:
    peer i dropped peer j. The tensor form of the per-observer streams — used
    for mesh-wide observability (SURVEY.md §5 structured metrics).
    """
    prev_member = np.asarray(prev_member, dtype=bool)
    member = np.asarray(member, dtype=bool)
    return member & ~prev_member, prev_member & ~member
