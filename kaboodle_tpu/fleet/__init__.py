"""fleet — batched ensemble simulation with on-device Monte Carlo statistics.

The ensemble as a tensor axis: E independent SWIM meshes stacked along a
leading ``[E]`` axis, advanced in lockstep by the vmapped tick kernel, with
convergence statistics computed as device reductions. See fleet/core.py for
the design, fleet/stats.py for the statistics layer, fleet/sharding.py for
the GSPMD ensemble (and ``E x peers``) distribution, and fleet/bench.py for
the sweep CLI (``python -m kaboodle_tpu fleet``).

Exports resolve lazily (PEP 562): importing this package must NOT import
jax, so the sweep CLI's ``--platform cpu`` pin (fleet/bench.py, which strips
the axon tunnel plugin before jax loads — a wedged tunnel hangs ``import
jax`` itself, axon_guard.py) can run before any backend-touching module
executes. ``from kaboodle_tpu.fleet import X`` works as usual; the owning
submodule loads on first access.
"""

import importlib

_EXPORTS = {
    # core
    "FleetState": "core",
    "fleet_converge_loop": "core",
    "fleet_idle_inputs": "core",
    "init_fleet": "core",
    "make_fleet_tick_fn": "core",
    "member_state": "core",
    "run_fleet_until_converged": "core",
    "scan_axis_first": "core",
    "simulate_fleet": "core",
    "stack_member_inputs": "core",
    # sharding
    "ENSEMBLE_AXIS": "sharding",
    "fleet_inputs_specs": "sharding",
    "fleet_state_specs": "sharding",
    "make_fleet_mesh": "sharding",
    "make_sharded_fleet_tick": "sharding",
    "run_fleet_until_converged_sharded": "sharding",
    "shard_fleet": "sharding",
    "shard_fleet_inputs": "sharding",
    "simulate_fleet_sharded": "sharding",
    # stats
    "agree_fraction_trajectory": "stats",
    "convergence_quantiles": "stats",
    "knob_marginals": "stats",
    "knob_quantiles": "stats",
    "survival_curve": "stats",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    try:
        submodule = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module 'kaboodle_tpu.fleet' has no attribute {name!r}"
        ) from None
    return getattr(
        importlib.import_module(f"kaboodle_tpu.fleet.{submodule}"), name
    )


def __dir__():
    return __all__
