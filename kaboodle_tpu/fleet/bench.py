"""Sweep front-end: knob grids over the ensemble, one dispatch, one table.

    python -m kaboodle_tpu fleet --sweep drop_rate=0:0.3:16 --ensemble 1024
    python -m kaboodle_tpu fleet --ensemble 256 --n 256 --seeds-only

A sweep spec ``knob=start:stop:steps`` lays a linspace grid over the
ensemble: ``E // steps`` members per grid point (E is trimmed to a multiple
of ``steps`` with a note), each member seeded independently, all advanced by
ONE :func:`kaboodle_tpu.fleet.run_fleet_until_converged` dispatch. The
statistics — overall + per-knob convergence-tick quantiles, converged
fractions, survival curve — come out of fleet/stats.py as device reductions;
the host sees only the table. ``--seeds-only`` (no knob grid) measures pure
seed sensitivity at a fixed configuration.

Sweepable knobs are the *traced* per-member scalars (today: ``drop_rate``).
Static protocol flags (SwimConfig fields) select the compiled program and
cannot vary within a fleet — A/B a static flag by invoking the sweep once
per arm (``--flag deterministic`` etc. pins the arm for this invocation).

Output contract mirrors bench.py: a human table on stdout, then one compact
single-line JSON summary as the LAST line (machine consumers take the tail).
Exit code 0 for any completed measurement — a zero converged fraction is a
valid result for a harsh regime (read ``converged_fraction`` from the tail
line), not a failure; nonzero exits are reserved for real errors (bad flags,
backend failures).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _pin_cpu() -> None:
    """Pin JAX to the CPU backend before any backend initializes (same
    pattern as bench.py: strip the tunnel plugin first — a wedged tunnel
    can hang `import jax` itself)."""
    import os

    try:
        from axon_guard import strip_axon_plugin

        strip_axon_plugin()
    except ImportError:  # installed-package runs without the repo root
        pass
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def parse_sweep(spec: str):
    """``knob=start:stop:steps`` -> (knob, float32 grid). Only per-member
    traced knobs are sweepable; see module docstring."""
    import numpy as np

    try:
        knob, rng = spec.split("=", 1)
        start_s, stop_s, steps_s = rng.split(":")
        start, stop, steps = float(start_s), float(stop_s), int(steps_s)
    except ValueError:
        raise SystemExit(
            f"bad --sweep spec {spec!r} (want knob=start:stop:steps)"
        ) from None
    if knob != "drop_rate":
        raise SystemExit(
            f"unknown sweep knob {knob!r}; sweepable per-member knobs: drop_rate"
        )
    if steps < 1:
        raise SystemExit("--sweep needs steps >= 1")
    return knob, np.linspace(start, stop, steps, dtype=np.float32)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kaboodle_tpu fleet",
        description="batched ensemble sweep: E meshes, one dispatch, "
        "convergence statistics on device",
    )
    p.add_argument("--ensemble", type=int, default=256, metavar="E",
                   help="ensemble members (default 256)")
    p.add_argument("--n", type=int, default=256, help="peers per mesh")
    p.add_argument("--sweep", default=None, metavar="KNOB=A:B:STEPS",
                   help="per-member knob grid, e.g. drop_rate=0:0.3:16")
    p.add_argument("--seeds-only", action="store_true",
                   help="no knob grid: pure seed-sensitivity ensemble")
    p.add_argument("--max-ticks", type=int, default=64)
    p.add_argument("--seed", type=int, default=0, help="base seed (member e "
                   "gets seed + e)")
    p.add_argument("--boot", choices=["broadcast", "gossip", "epidemic"],
                   default="broadcast",
                   help="broadcast (1-tick join avalanche), gossip "
                   "(broadcast-free, Q6 back-dating), epidemic (broadcast-"
                   "free, fresh gossip stamps)")
    p.add_argument("--quantiles", type=float, nargs="*",
                   default=[0.5, 0.9, 0.99])
    p.add_argument("--deterministic", action="store_true",
                   help="deterministic protocol draws (A/B arm pin)")
    p.add_argument("--shard", choices=["auto", "none", "ensemble"],
                   default="auto",
                   help="shard the ensemble axis over the local devices "
                   "(auto: when >1 device and E divides)")
    p.add_argument("--platform", choices=["cpu"], default=None,
                   help="pin the JAX platform (avoids touching a possibly-"
                   "wedged accelerator plugin)")
    p.add_argument("--telemetry", nargs="?", const="fleet-telemetry.jsonl",
                   default=None, metavar="PATH",
                   help="write the sweep result as a JSONL run manifest "
                        "(telemetry/manifest.py schema; one 'run' record "
                        "plus one 'sweep_bucket' record per knob value)")
    return p


def run_sweep(args) -> dict:
    import jax
    import numpy as np

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.fleet import (
        convergence_quantiles,
        init_fleet,
        knob_marginals,
        knob_quantiles,
        run_fleet_until_converged,
        survival_curve,
    )

    ensemble = args.ensemble
    knob_name, grid = (None, None)
    if args.sweep and args.seeds_only:
        # Silently dropping either flag would hand back the wrong
        # measurement with exit code 0 — refuse the contradiction.
        raise SystemExit("--sweep and --seeds-only are mutually exclusive")
    if args.sweep:
        knob_name, grid = parse_sweep(args.sweep)
        steps = grid.shape[0]
        if ensemble < steps:
            raise SystemExit(
                f"--ensemble {ensemble} < sweep steps {steps}: need at "
                "least one member per grid point (shrink the grid or grow "
                "the ensemble)")
        if ensemble % steps:
            trimmed = ensemble - ensemble % steps
            print(f"fleet: --ensemble {ensemble} trimmed to {trimmed} "
                  f"(multiple of {steps} grid points)", file=sys.stderr)
            ensemble = trimmed
        knob = np.repeat(grid, ensemble // steps)
    else:
        knob = np.zeros((ensemble,), dtype=np.float32)

    cfg = SwimConfig(
        deterministic=args.deterministic,
        join_broadcast_enabled=args.boot == "broadcast",
        backdate_gossip_inserts=args.boot != "epidemic",
    )
    # Lean state: the fleet resident is E x one mesh; latency EWMA and
    # per-row identity views are statistics-irrelevant here.
    fleet = init_fleet(
        args.n, ensemble,
        seeds=args.seed + np.arange(ensemble, dtype=np.int64),
        drop_rates=knob,
        track_latency=False, instant_identity=True,
        ring_contacts=0 if args.boot == "broadcast" else 2,
    )
    faulty = bool(np.any(knob > 0))

    n_dev = jax.local_device_count()
    sharded = (args.shard == "ensemble"
               or (args.shard == "auto" and n_dev > 1 and ensemble % n_dev == 0))
    if sharded:
        from kaboodle_tpu.fleet import (
            make_fleet_mesh,
            run_fleet_until_converged_sharded,
            shard_fleet,
        )

        mesh = make_fleet_mesh()
        fleet = shard_fleet(fleet, mesh)
        t0 = time.perf_counter()
        fleet, conv_tick, done = run_fleet_until_converged_sharded(
            fleet, cfg, mesh, max_ticks=args.max_ticks, faulty=faulty)
    else:
        t0 = time.perf_counter()
        fleet, conv_tick, done = run_fleet_until_converged(
            fleet, cfg, max_ticks=args.max_ticks, faulty=faulty)

    qs = tuple(args.quantiles)
    overall_q = convergence_quantiles(conv_tick, done, qs=qs)
    surv = survival_curve(conv_tick, done, max_ticks=args.max_ticks)
    per_knob = None
    if grid is not None:
        values = np.asarray(grid, dtype=np.float32)
        marg = knob_marginals(knob, values, conv_tick, done)
        kq = knob_quantiles(knob, values, conv_tick, done, qs=qs)
        per_knob = (values, marg, kq)
    # ONE host fetch at the end: everything above is device-side.
    conv_frac = float(np.mean(np.asarray(done)))
    wall = time.perf_counter() - t0

    qcols = "  ".join(f"p{int(q * 100):<4}" for q in qs)
    print(f"fleet: E={ensemble} N={args.n} boot={args.boot} "
          f"max_ticks={args.max_ticks} backend={jax.default_backend()}"
          f"{' sharded' if sharded else ''}")
    print(f"{'knob':>10}  {'members':>7}  {'conv%':>6}  {'mean':>6}  {qcols}")
    overall_qv = np.asarray(overall_q)

    def qfmt(row):
        return "  ".join(f"{v:5.1f}" for v in row)

    if per_knob is not None:
        values, marg, kq = per_knob
        members = np.asarray(marg["members"])
        fracs = np.asarray(marg["converged_fraction"])
        means = np.asarray(marg["mean_conv_tick"])
        kqv = np.asarray(kq)
        for b, v in enumerate(values):
            print(f"{knob_name}={v:<6.3f}  {members[b]:>7}  "
                  f"{100 * fracs[b]:>5.1f}%  {means[b]:>6.1f}  {qfmt(kqv[b])}")
    print(f"{'ALL':>10}  {ensemble:>7}  {100 * conv_frac:>5.1f}%  "
          f"{'':>6}  {qfmt(overall_qv)}")

    line = {
        "metric": "fleet_convergence_quantiles",
        "ensemble": ensemble,
        "n_peers": args.n,
        "boot": args.boot,
        "sweep": args.sweep if grid is not None else None,
        "faulty": faulty,
        "sharded": sharded,
        "backend": jax.default_backend(),
        "converged_fraction": round(conv_frac, 4),
        "quantiles": {f"p{int(q * 100)}": round(float(v), 2)
                      for q, v in zip(qs, overall_qv)},
        "survival_tail": round(float(np.asarray(surv)[-1]), 4),
        "max_ticks": args.max_ticks,
        "wall_s": round(wall, 3),
    }
    if per_knob is not None:
        values, marg, kq = per_knob
        line["per_knob"] = [
            {
                "knob": knob_name,
                "value": round(float(v), 4),
                "members": int(np.asarray(marg["members"])[b]),
                "converged_fraction": round(
                    float(np.asarray(marg["converged_fraction"])[b]), 4),
                "mean_conv_tick": round(
                    float(np.asarray(marg["mean_conv_tick"])[b]), 2),
                "quantiles": {f"p{int(q * 100)}": round(float(x), 2)
                              for q, x in zip(qs, np.asarray(kq)[b])},
            }
            for b, v in enumerate(values)
        ]
    return line


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.platform == "cpu":
        _pin_cpu()
    line = run_sweep(args)
    if args.telemetry is not None:
        # The sweep's machine output as a run manifest: the same dict the
        # tail line prints, schema-tagged so the summarizer and any other
        # manifest consumer can ingest it alongside sim/bench manifests.
        from kaboodle_tpu.telemetry import ManifestWriter

        with ManifestWriter(args.telemetry) as w:
            w.write("run", **{k: v for k, v in line.items() if k != "per_knob"})
            for bucket in line.get("per_knob") or []:
                w.write("sweep_bucket", **bucket)
        print(f"telemetry manifest: {args.telemetry}", file=sys.stderr)
    print(json.dumps(line))
    # A completed measurement is success even when nothing converged (the
    # non-convergent region of a sweep is a designed outcome, not an error).
    return 0


if __name__ == "__main__":
    sys.exit(main())
