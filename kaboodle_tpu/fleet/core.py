"""Batched ensemble simulation: E independent SWIM meshes as one tensor axis.

Every engine in the repo — the dense kernel (sim/kernel.py), the chunked
twin (sim/chunked.py), the sharded twin (parallel/mesh.py) — advances
exactly ONE mesh per dispatch, so any statistical question (convergence-time
distribution vs drop rate, recovery curves under churn, seed sensitivity)
costs one full dispatch per sample and leaves the batch dimension idle.
This module makes the *ensemble* a tensor axis: a :class:`FleetState` stacks
E complete ``MeshState`` pytrees along a leading ``[E]`` axis and the tick
kernel is ``jax.vmap``-ped over it, so thousands of independent meshes
advance in lockstep inside one XLA program (the data-oriented batched-
simulator design of Potato, arXiv:2308.12698 — the ensemble is data, not a
host loop).

Members share every *static* property (N, protocol config, state variant —
one compiled program) and vary in everything *traced*: the per-member PRNG
key (seed axis) and per-member scalar knobs such as ``drop_rate`` (one
float per member, broadcast by the vmapped kernel into that member's
delivery gate). Static protocol flags (``SwimConfig``) cannot vary within a
fleet — they select the compiled program; an A/B over a static flag is two
fleet dispatches (see fleet/bench.py).

Parity contract (tests/test_fleet.py): member ``k`` of a fleet is BIT-EXACT
with a standalone single-mesh run from ``init_state(n, seed=seeds[k])`` —
``vmap`` only batches the same per-row ops the single-mesh kernel runs, all
integer state is exact, and the per-member PRNG streams are the standalone
streams. The oracle cross-checks therefore extend to every fleet member by
sampling: whatever parity the single-mesh kernel has (PARITY.md), the fleet
inherits.

Convergence-bounded runs use a MASKED while_loop
(:func:`run_fleet_until_converged`): the fleet keeps dispatching ticks while
any member is unconverged, but a member that has already hit fingerprint
agreement is frozen — its carried state stops updating at exactly the tick
it converged (matching what a standalone ``run_until_converged`` would have
returned), and its convergence tick is recorded on-device in an ``[E]``
vector. The loop cost is max(member ticks), not sum.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.sim.runner import state_converged
from kaboodle_tpu.sim.state import (
    MeshState,
    TickInputs,
    TickMetrics,
    idle_inputs,
    init_state,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class FleetState:
    """E stacked meshes + the per-member knob vector.

    ``mesh`` is a ``MeshState`` whose every leaf carries a leading ``[E]``
    axis (``state``: int8 ``[E, N, N]``, ``tick``: int32 ``[E]``, ``key``:
    ``[E, 2]``, ...). ``drop_rate`` is the per-member uniform message-drop
    knob consumed by faulty-mode runs (float32 ``[E]``; inert under
    ``faulty=False``, like the single-mesh kernel).
    """

    mesh: MeshState
    drop_rate: jax.Array  # float32 [E]

    @property
    def ensemble(self) -> int:
        return self.mesh.alive.shape[0]

    @property
    def n(self) -> int:
        return self.mesh.state.shape[-1]


def init_fleet(
    n: int,
    ensemble: int,
    seeds: jax.Array | None = None,
    drop_rates: jax.Array | None = None,
    **init_state_kwargs,
) -> FleetState:
    """Fresh E-member fleet: every member is ``init_state(n, seed=seeds[e])``.

    All non-key state is identical across members at init (one broadcast, no
    E-fold host loop); the per-member keys are ``vmap(PRNGKey)(seeds)``,
    bit-identical to the keys the standalone inits would hold. ``seeds``
    defaults to ``0..E-1``; ``drop_rates`` defaults to all-zero.
    ``init_state_kwargs`` pass through (``ring_contacts``, ``track_latency``,
    ``instant_identity``, ``timer_dtype``, ``announced`` — the lean-state
    knobs matter at fleet scale: the resident is E times one mesh).
    """
    if ensemble < 1:
        raise ValueError("need ensemble >= 1")
    if seeds is None:
        seeds = jnp.arange(ensemble, dtype=jnp.int32)
    seeds = jnp.asarray(seeds)
    if seeds.shape != (ensemble,):
        raise ValueError(f"seeds must be [{ensemble}], got {seeds.shape}")
    if drop_rates is None:
        drop_rates = jnp.zeros((ensemble,), dtype=jnp.float32)
    drop_rates = jnp.asarray(drop_rates, dtype=jnp.float32)
    if drop_rates.shape != (ensemble,):
        raise ValueError(f"drop_rates must be [{ensemble}], got {drop_rates.shape}")
    base = init_state(n, seed=0, **init_state_kwargs)
    stacked = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (ensemble,) + x.shape), base
    )
    keys = jax.vmap(jax.random.PRNGKey)(seeds)
    return FleetState(
        mesh=dataclasses.replace(stacked, key=keys), drop_rate=drop_rates
    )


def member_state(fleet: FleetState, e: int) -> MeshState:
    """Member ``e``'s mesh as a standalone (unstacked) ``MeshState``."""
    return jax.tree.map(lambda x: x[e], fleet.mesh)


def fleet_idle_inputs(
    n: int,
    ensemble: int,
    ticks: int | None = None,
    drop_rate: jax.Array | None = None,
) -> TickInputs:
    """No-fault per-member inputs, ``[E, ...]`` (``[T, E, ...]`` with ticks).

    ``drop_rate`` is the per-member knob vector (float32 ``[E]``, default
    zero) — the one scalar input that varies across the ensemble; it is
    broadcast along the scan axis when ``ticks`` is given.

    Derived from the single-mesh :func:`~kaboodle_tpu.sim.state.idle_inputs`
    by broadcasting, so a new ``TickInputs`` field keeps ONE idle
    definition.
    """
    if drop_rate is None:
        drop_rate = jnp.zeros((ensemble,), dtype=jnp.float32)
    drop_rate = jnp.asarray(drop_rate, dtype=jnp.float32)

    def stack(x):
        return jnp.broadcast_to(x[None], (ensemble,) + x.shape)

    inputs = dataclasses.replace(
        jax.tree.map(stack, idle_inputs(n)), drop_rate=drop_rate
    )
    if ticks is not None:
        inputs = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (ticks,) + x.shape), inputs
        )
    return inputs


def stack_member_inputs(inputs: list[TickInputs]) -> TickInputs:
    """Stack per-member ``TickInputs`` (e.g. from the Scenario DSL) along a
    new ``[E]`` axis. Stacked-[T] schedules stack to ``[E, T, ...]`` — pass
    through :func:`scan_axis_first` before scanning."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *inputs)


def scan_axis_first(inputs: TickInputs) -> TickInputs:
    """``[E, T, ...]`` member-major inputs -> the ``[T, E, ...]`` scan layout."""
    return jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), inputs)


def freeze_members(active: jax.Array, old: MeshState, new: MeshState) -> MeshState:
    """Per-member carry select: advance to ``new`` where ``active[e]``.

    The masked-lockstep freeze: a member with ``active[e] == False`` keeps
    EVERY leaf of its old carry (state, timers, tick counter, PRNG key), so
    its trajectory is bit-identical to never having dispatched the tick.
    Shared by :func:`fleet_converge_loop` (freezing converged members) and
    the per-member warp runner (freezing members that are mid-leap or done
    while others still need dense ticks — warp/runner.py).
    """
    ensemble = active.shape[0]

    def sel(o, n):
        return jnp.where(active.reshape((ensemble,) + (1,) * (n.ndim - 1)), n, o)

    return jax.tree.map(sel, old, new)


def make_fleet_tick_fn(cfg: SwimConfig, faulty: bool = True, telemetry: bool = False):
    """The phase-graph fleet derivation: the dense tick vmapped over ``[E]``.

    One compiled program advances all E members a tick; every ``lax.cond``
    the kernel gates rare phases with batches to a select under ``vmap``
    (both branches execute for the whole fleet whenever any member needs
    one — the lockstep price of batching; the [E]-wide masks keep the
    results exact). For that reason the fleet build compiles the FULL
    program only — see :func:`kaboodle_tpu.phasegraph.derive.make_fleet_tick`
    for the derivation and the exactness argument.

    ``telemetry=True`` vmaps the telemetry-plane tick: member ``e``'s
    ``ProtocolCounters`` / fingerprint digests are bit-exact with a
    standalone telemetry run from the same seed, by the same argument as
    the state parity contract (vmap batches the identical per-row ops).
    """
    from kaboodle_tpu.phasegraph.derive import make_fleet_tick

    return make_fleet_tick(cfg, faulty=faulty, telemetry=telemetry)


@functools.partial(jax.jit, static_argnames=("cfg", "faulty", "telemetry"))
def simulate_fleet(
    fleet: FleetState,
    inputs: TickInputs,
    cfg: SwimConfig,
    faulty: bool = True,
    telemetry: bool = False,
) -> tuple[FleetState, TickMetrics]:
    """Scan the vmapped tick over ``[T, E, ...]`` stacked inputs.

    The ensemble twin of :func:`kaboodle_tpu.sim.runner.simulate`: one
    ``lax.scan`` dispatch advances all members T ticks and returns per-tick
    per-member metrics (``TickMetrics`` leaves shaped ``[T, E]`` — the raw
    material of fleet/stats.py's trajectory reductions). With
    ``telemetry=True`` the second element is the stacked ``TickTelemetry``
    instead (metrics + per-member-per-tick ``ProtocolCounters`` shaped
    ``[T, E]``, digests ``[T, E, N]``).
    """
    vtick = make_fleet_tick_fn(cfg, faulty=faulty, telemetry=telemetry)
    mesh, metrics = jax.lax.scan(vtick, fleet.mesh, inputs)
    return dataclasses.replace(fleet, mesh=mesh), metrics


def fleet_converge_loop(
    mesh: MeshState,
    vtick,
    idle: TickInputs,
    max_ticks: int,
) -> tuple[MeshState, jax.Array, jax.Array]:
    """Masked ``lax.while_loop`` of a vmapped tick until every member agrees.

    The ensemble generalization of :func:`kaboodle_tpu.sim.runner.
    converge_loop`: the loop runs while any member is unconverged (and
    ``i < max_ticks``), and a converged member's carry is frozen by an
    ``[E]``-mask select — its state stops at exactly the end-of-tick state
    where its fingerprints first agreed, so member trajectories match what
    standalone convergence runs would return (tests/test_fleet.py pins it).
    Shared by the single-device and sharded entry points (fleet/sharding.py
    wraps its constrained tick around this).

    Returns ``(final_mesh, conv_tick, converged)``: ``conv_tick[e]`` is the
    tick count at which member e converged (== the standalone run's
    ``ticks_run``), ``max_ticks`` where it never did. Like the standalone
    loop, agreement is also tested at entry: a member already converged at
    tick 0 freezes immediately and reports ``conv_tick == 0``.
    """
    done0 = jax.vmap(state_converged)(mesh)

    def cond(carry):
        _, _, done, i = carry
        return jnp.any(~done) & (i < max_ticks)

    def body(carry):
        st, conv_tick, done, i = carry
        new_st, m = vtick(st, idle)
        # Freeze finished members: their carry (state, timer, tick counter,
        # PRNG key — every leaf) must stop at the convergence tick.
        st = freeze_members(~done, st, new_st)
        conv_tick = jnp.where(~done & m.converged, i + 1, conv_tick)
        return st, conv_tick, done | m.converged, i + 1

    mesh, conv_tick, done, _ = jax.lax.while_loop(
        cond,
        body,
        (
            mesh,
            jnp.where(done0, 0, max_ticks).astype(jnp.int32),
            done0,
            jnp.int32(0),
        ),
    )
    return mesh, conv_tick, done


@functools.partial(jax.jit, static_argnames=("cfg", "max_ticks", "faulty"))
def run_fleet_until_converged(
    fleet: FleetState,
    cfg: SwimConfig,
    max_ticks: int = 64,
    faulty: bool = False,
) -> tuple[FleetState, jax.Array, jax.Array]:
    """Tick the whole fleet until every member converges (or ``max_ticks``).

    ONE dispatch for the whole ensemble; each member's convergence tick is
    recorded on-device (``[E]`` int32 — feed fleet/stats.py, never a
    per-member host round-trip). ``faulty=False`` compiles the fault-free
    kernel (``fleet.drop_rate`` inert, the ``run_until_converged`` twin);
    ``faulty=True`` compiles the fault path so the per-member ``drop_rate``
    knob gates delivery — the drop-rate-sweep mode of fleet/bench.py.
    """
    vtick = make_fleet_tick_fn(cfg, faulty=faulty)
    idle = fleet_idle_inputs(fleet.n, fleet.ensemble, drop_rate=fleet.drop_rate)
    mesh, conv_tick, done = fleet_converge_loop(fleet.mesh, vtick, idle, max_ticks)
    return dataclasses.replace(fleet, mesh=mesh), conv_tick, done
