"""Ensemble sharding: the ``[E]`` axis over the device mesh, via GSPMD.

Same design as the peer-sharding layer this rides on (parallel/mesh.py):
write global-view array programs, pin the layout with ``NamedSharding`` /
``with_sharding_constraint``, and let XLA's SPMD partitioner insert the
collectives. Two layouts:

- **1-D ensemble mesh** (:func:`make_fleet_mesh`): the ``[E]`` axis split
  across all chips, each member entirely on one device. Members are
  independent, so the tick partitions with ZERO cross-device traffic —
  embarrassingly-parallel Monte Carlo; only the fleet-wide convergence
  reductions (``any(~done)``, the stats layer's quantile sorts) cross the
  ICI. The default for big-E sweeps.
- **2-D ``E x peers`` mesh** (:func:`make_fleet_mesh`, ``peer_devices > 1``):
  ensemble on one mesh axis, the peer (row) axis on the other — for big-N
  members whose single-mesh state exceeds one chip. Each member's tick then
  partitions exactly like the single-mesh sharded twin (row-local
  reductions + peer-axis collectives), replicated independently along the
  ensemble axis.

The specs are the peer-layer's ``state_specs`` with the ensemble axis
prepended (peer entries dropped on the 1-D mesh), so the two layers cannot
drift: a new ``MeshState`` field gets its fleet placement from the same
single source of truth.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.fleet.core import (
    FleetState,
    fleet_converge_loop,
    fleet_idle_inputs,
    make_fleet_tick_fn,
)
from kaboodle_tpu.parallel.mesh import PEER_AXIS, inputs_specs, state_specs
from kaboodle_tpu.sim.state import MeshState, TickInputs

ENSEMBLE_AXIS = "ensemble"


def make_fleet_mesh(
    ensemble_devices: int | None = None,
    peer_devices: int = 1,
    devices=None,
) -> Mesh:
    """Device mesh for a fleet: 1-D over ``ensemble``, or 2-D ``E x peers``.

    ``peer_devices == 1`` (default) gives the 1-D ensemble mesh over
    ``ensemble_devices`` chips (all local devices by default). With
    ``peer_devices > 1`` the devices reshape to
    ``(ensemble_devices, peer_devices)`` — device order keeps each member's
    peer group contiguous, so the heavy per-member row traffic stays on the
    fastest links and only the tiny fleet-wide reductions span the ensemble
    axis.
    """
    if devices is None:
        devices = jax.devices()
    if ensemble_devices is None:
        if len(devices) % peer_devices != 0:
            raise ValueError(
                f"{len(devices)} devices not divisible by peer_devices={peer_devices}"
            )
        ensemble_devices = len(devices) // peer_devices
    total = ensemble_devices * peer_devices
    if total > len(devices):
        raise ValueError(f"asked for {total} devices, have {len(devices)}")
    devs = np.asarray(devices[:total])
    if peer_devices == 1:
        return Mesh(devs, (ENSEMBLE_AXIS,))
    return Mesh(
        devs.reshape(ensemble_devices, peer_devices), (ENSEMBLE_AXIS, PEER_AXIS)
    )


def _stacked(spec: P, peers_sharded: bool) -> P:
    """Prepend the ensemble axis to a peer-layer spec; on a 1-D ensemble
    mesh the peer entries collapse to None (the axis does not exist)."""
    parts = tuple(spec) if peers_sharded else tuple(None for _ in tuple(spec))
    return P(ENSEMBLE_AXIS, *parts)


def fleet_state_specs(fleet: FleetState | None = None, peers_sharded: bool = False):
    """PartitionSpecs for a FleetState (see module docstring)."""
    mesh_state = fleet.mesh if fleet is not None else None
    base = state_specs(mesh_state)
    mesh_specs = jax.tree.map(
        lambda s: _stacked(s, peers_sharded), base, is_leaf=lambda x: isinstance(x, P)
    )
    return FleetState(mesh=mesh_specs, drop_rate=P(ENSEMBLE_AXIS))


def fleet_inputs_specs(
    stacked: bool = False,
    with_drop_ok: bool = False,
    peers_sharded: bool = False,
) -> TickInputs:
    """PartitionSpecs for fleet TickInputs (``[E, ...]``; ``stacked`` adds
    the leading scan [T] axis: ``[T, E, ...]``)."""
    lead = (None,) if stacked else ()
    base = inputs_specs(stacked=False, with_drop_ok=with_drop_ok)
    return jax.tree.map(
        lambda s: P(*lead, *tuple(_stacked(s, peers_sharded))),
        base,
        is_leaf=lambda x: isinstance(x, P),
    )


def _named(mesh: Mesh, specs):
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs, is_leaf=lambda x: isinstance(x, P)
    )


def _check_fleet_divisible(ensemble: int, n: int, mesh: Mesh) -> None:
    e_dev = mesh.shape[ENSEMBLE_AXIS]
    if ensemble % e_dev != 0:
        raise ValueError(f"E={ensemble} not divisible by ensemble mesh size {e_dev}")
    if PEER_AXIS in mesh.axis_names and n % mesh.shape[PEER_AXIS] != 0:
        raise ValueError(
            f"N={n} not divisible by peer mesh size {mesh.shape[PEER_AXIS]}"
        )


def shard_fleet(fleet: FleetState, mesh: Mesh) -> FleetState:
    """Place a FleetState on the mesh (ensemble axis split; rows too on a
    2-D ``E x peers`` mesh)."""
    _check_fleet_divisible(fleet.ensemble, fleet.n, mesh)
    peers = PEER_AXIS in mesh.axis_names
    return jax.device_put(fleet, _named(mesh, fleet_state_specs(fleet, peers)))


def shard_fleet_inputs(
    inputs: TickInputs, mesh: Mesh, stacked: bool = False
) -> TickInputs:
    """Place fleet TickInputs on the mesh (``stacked=True`` for [T, E, ...])."""
    peers = PEER_AXIS in mesh.axis_names
    specs = fleet_inputs_specs(
        stacked=stacked, with_drop_ok=inputs.drop_ok is not None, peers_sharded=peers
    )
    return jax.device_put(inputs, _named(mesh, specs))


def make_fleet_constrainer(mesh: Mesh):
    """``stacked MeshState -> same state, pinned to the fleet layout``.

    Specs are derived from the (traced) carry itself, so the optional
    fields' presence always matches the tree structure (the same contract
    as ``parallel.mesh.make_sharded_tick``). This is the one constraint
    every sharded fleet program applies to its mesh carry/output — the
    sharded tick, the sharded serve step and the sharded leap all pin
    through here, so their layouts cannot drift apart (drift would hand
    jit differently-sharded inputs next dispatch and mint a recompile)."""
    peers = PEER_AXIS in mesh.axis_names

    def constrain(st: MeshState) -> MeshState:
        specs = jax.tree.map(
            lambda s: _stacked(s, peers),
            state_specs(st),
            is_leaf=lambda x: isinstance(x, P),
        )
        return jax.tree.map(
            jax.lax.with_sharding_constraint, st, _named(mesh, specs)
        )

    return constrain


def fleet_vector_sharding(mesh: Mesh) -> NamedSharding:
    """Placement for per-member ``[E]`` vectors (drop knobs, generation
    counters): split along the ensemble axis, like ``drop_rate`` in
    :func:`fleet_state_specs`."""
    return NamedSharding(mesh, P(ENSEMBLE_AXIS))


def make_sharded_fleet_tick(cfg: SwimConfig, mesh: Mesh, faulty: bool = True):
    """Vmapped tick whose output carry is constrained back onto the mesh
    layout — the fleet twin of ``parallel.mesh.make_sharded_tick`` (stable
    per-tick partitioning under scan/while_loop)."""
    vtick = make_fleet_tick_fn(cfg, faulty=faulty)
    constrain = make_fleet_constrainer(mesh)

    def sharded_tick(st: MeshState, inp: TickInputs):
        st, m = vtick(st, inp)
        st = constrain(st)
        return st, m

    return sharded_tick


@functools.partial(jax.jit, static_argnames=("cfg", "mesh", "faulty"))
def simulate_fleet_sharded(
    fleet: FleetState,
    inputs: TickInputs,
    cfg: SwimConfig,
    mesh: Mesh,
    faulty: bool = True,
):
    """Sharded twin of :func:`kaboodle_tpu.fleet.simulate_fleet`."""
    tick = make_sharded_fleet_tick(cfg, mesh, faulty=faulty)
    new_mesh, metrics = jax.lax.scan(tick, fleet.mesh, inputs)
    return dataclasses.replace(fleet, mesh=new_mesh), metrics


@functools.partial(jax.jit, static_argnames=("cfg", "mesh", "max_ticks", "faulty"))
def run_fleet_until_converged_sharded(
    fleet: FleetState,
    cfg: SwimConfig,
    mesh: Mesh,
    max_ticks: int = 64,
    faulty: bool = False,
):
    """Sharded twin of :func:`kaboodle_tpu.fleet.run_fleet_until_converged`.

    The masked convergence loop's fleet-wide ``any(~done)`` predicate is the
    only per-iteration cross-ensemble reduction — on a 1-D ensemble mesh the
    whole tick body partitions collective-free.
    """
    tick = make_sharded_fleet_tick(cfg, mesh, faulty=faulty)
    peers = PEER_AXIS in mesh.axis_names
    idle = fleet_idle_inputs(fleet.n, fleet.ensemble, drop_rate=fleet.drop_rate)
    idle = jax.tree.map(
        jax.lax.with_sharding_constraint,
        idle,
        _named(mesh, fleet_inputs_specs(peers_sharded=peers)),
    )
    new_mesh, conv_tick, done = fleet_converge_loop(fleet.mesh, tick, idle, max_ticks)
    return dataclasses.replace(fleet, mesh=new_mesh), conv_tick, done
