"""On-device Monte Carlo statistics over the ensemble axis.

Everything here is a reduction over ``[E]`` (or ``[T, E]``) device arrays —
quantiles, survival curves, trajectory envelopes, per-knob marginals — so a
million-member sweep hands the host a handful of scalars, never E per-member
round-trips (the memoized-sweep lesson of arXiv:2602.10615: the statistic is
the product, the trajectories are intermediates). Inputs are exactly what
the fleet runners emit: ``conv_tick``/``converged`` ``[E]`` vectors from
:func:`kaboodle_tpu.fleet.run_fleet_until_converged`, stacked ``TickMetrics``
(leaves ``[T, E]``) from :func:`kaboodle_tpu.fleet.simulate_fleet`, and the
per-member knob vector the sweep assigned.

Quantile semantics: linear interpolation over the sorted converged subset
(NumPy's default ``np.quantile`` method), computed in float32 on device;
``NaN`` where no member converged. tests/test_fleet.py pins every reduction
against a NumPy host recompute.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from kaboodle_tpu.sim.state import TickMetrics

_I32MAX = jnp.iinfo(jnp.int32).max


def _masked_quantiles(values: jax.Array, mask: jax.Array, qs: tuple) -> jax.Array:
    """Linear-interpolation quantiles of ``values[mask]``, float32 [len(qs)].

    Sort with masked-out entries pushed to +inf position, then interpolate
    between the two order statistics bracketing ``q * (m - 1)`` where m is
    the mask count — NumPy's default method on the selected subset. NaN when
    the mask is empty.
    """
    e = values.shape[0]
    s = jnp.sort(jnp.where(mask, values, jnp.int32(_I32MAX))).astype(jnp.float32)
    m = jnp.sum(mask, dtype=jnp.int32)
    q = jnp.asarray(qs, dtype=jnp.float32)
    pos = q * jnp.maximum(m - 1, 0).astype(jnp.float32)
    lo = jnp.floor(pos).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, jnp.maximum(m - 1, 0))
    frac = pos - lo.astype(jnp.float32)
    lo = jnp.clip(lo, 0, e - 1)
    hi = jnp.clip(hi, 0, e - 1)
    v = s[lo] * (1.0 - frac) + s[hi] * frac
    return jnp.where(m > 0, v, jnp.float32(jnp.nan))


@functools.partial(jax.jit, static_argnames=("qs",))
def convergence_quantiles(
    conv_tick: jax.Array,
    converged: jax.Array,
    qs: tuple = (0.5, 0.9, 0.99),
) -> jax.Array:
    """Quantiles of the convergence-tick distribution over converged members.

    float32 ``[len(qs)]``; NaN where nothing converged. One sort + gathers
    on device — the headline numbers of a sweep.
    """
    return _masked_quantiles(conv_tick, converged, qs)


@functools.partial(jax.jit, static_argnames=("max_ticks",))
def survival_curve(
    conv_tick: jax.Array,
    converged: jax.Array,
    max_ticks: int,
) -> jax.Array:
    """Fraction of members still unconverged after each tick.

    float32 ``[max_ticks + 1]``: entry t is P(member unconverged after t
    ticks) — 1.0 at t=0 (convergence is judged end-of-tick, so ``conv_tick
    >= 1``), stepping down toward the never-converged fraction.
    """
    e = conv_tick.shape[0]
    t = jnp.arange(max_ticks + 1, dtype=jnp.int32)
    unconv = ~converged[None, :] | (conv_tick[None, :] > t[:, None])
    return jnp.sum(unconv, axis=-1, dtype=jnp.float32) / jnp.float32(e)


@jax.jit
def agree_fraction_trajectory(metrics: TickMetrics) -> dict:
    """Ensemble envelope of the agree-fraction trajectory.

    From stacked fleet metrics (leaves ``[T, E]``): the per-tick mean, min,
    and max over members of ``agree_fraction``, plus the per-tick converged
    member fraction — each float32 ``[T]``. The on-device summary of "how
    does agreement build" across the whole ensemble.
    """
    af = metrics.agree_fraction
    e = af.shape[-1]
    return {
        "mean": jnp.mean(af, axis=-1),
        "min": jnp.min(af, axis=-1),
        "max": jnp.max(af, axis=-1),
        "converged_fraction": jnp.sum(metrics.converged, axis=-1, dtype=jnp.float32)
        / jnp.float32(e),
    }


@jax.jit
def knob_marginals(
    knob: jax.Array,
    values: jax.Array,
    conv_tick: jax.Array,
    converged: jax.Array,
) -> dict:
    """Per-knob-value marginals of the convergence outcome.

    ``knob`` float32 ``[E]`` is each member's knob setting; ``values``
    float32 ``[B]`` the sweep grid (members are assigned grid points
    exactly, so membership is an equality test — fleet/bench.py's layout).
    Returns per-bin device vectors (``[B]``): member count, converged
    fraction, and mean convergence tick over the bin's converged members
    (NaN where none converged). One one-hot contraction, no host loop.
    """
    onehot = knob[:, None] == values[None, :]  # [E, B]
    members = jnp.sum(onehot, axis=0, dtype=jnp.int32)
    conv_hot = onehot & converged[:, None]
    conv_count = jnp.sum(conv_hot, axis=0, dtype=jnp.int32)
    tick_sum = jnp.sum(
        jnp.where(conv_hot, conv_tick[:, None], 0).astype(jnp.float32), axis=0
    )
    mean_tick = jnp.where(
        conv_count > 0,
        tick_sum / jnp.maximum(conv_count, 1).astype(jnp.float32),
        jnp.float32(jnp.nan),
    )
    frac = conv_count.astype(jnp.float32) / jnp.maximum(members, 1).astype(jnp.float32)
    return {
        "members": members,
        "converged_fraction": frac,
        "mean_conv_tick": mean_tick,
    }


@functools.partial(jax.jit, static_argnames=("qs",))
def knob_quantiles(
    knob: jax.Array,
    values: jax.Array,
    conv_tick: jax.Array,
    converged: jax.Array,
    qs: tuple = (0.5, 0.9, 0.99),
) -> jax.Array:
    """Convergence-tick quantiles per knob value: float32 ``[B, len(qs)]``.

    The per-bin twin of :func:`convergence_quantiles`, vmapped over the
    sweep grid — the body of the sweep CLI's quantile table.
    """
    return jax.vmap(
        lambda v: _masked_quantiles(conv_tick, converged & (knob == v), qs)
    )(values)
