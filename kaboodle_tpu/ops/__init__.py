"""Vectorized TPU ops: CRC-32, fingerprint hashing, masked sampling."""

from kaboodle_tpu.ops.crc32 import crc32, crc32_update_bytes, membership_crc32
from kaboodle_tpu.ops.hashing import mix32, peer_record_hash, membership_fingerprint
from kaboodle_tpu.ops.sampling import (
    choose_one_of_oldest_k,
    choose_k_members,
    bernoulli_matrix,
    broadcast_reply_prob,
)

__all__ = [
    "crc32",
    "crc32_update_bytes",
    "membership_crc32",
    "mix32",
    "peer_record_hash",
    "membership_fingerprint",
    "choose_one_of_oldest_k",
    "choose_k_members",
    "bernoulli_matrix",
    "broadcast_reply_prob",
]
