"""Vectorized CRC-32 (IEEE, reflected poly 0xEDB88320) for TPU.

The reference fingerprints its membership map with ``crc32fast``
(kaboodle.rs:71-83): peers sorted by address, then for each peer the CRC is
updated with the address string bytes and the identity bytes. ``crc32fast``
computes standard CRC-32 (same as Python's ``zlib.crc32``), so ``zlib`` is the
test oracle for every kernel here.

TPU design notes:
- The byte-wise update ``s' = (s >> 8) ^ LUT[(s ^ byte) & 0xFF]`` is sequential
  in the *byte* axis but embarrassingly parallel across rows — so we lay data
  out as ``[rows, bytes]`` and ``lax.scan`` over bytes with a 256-entry
  ``uint32`` table gather per step. For fixed-width records (the simulator's
  case) the scan length is the record width, not the membership size.
- The membership fingerprint additionally folds a *masked variable-length*
  sequence (only members contribute). CRC has no cheap commutative form, so
  :func:`membership_crc32` scans over the peer axis and uses ``where(mask)`` to
  skip non-members. That is O(N) sequential steps — fine for parity tests at
  small N; the production convergence check uses the commutative mix-hash in
  :mod:`kaboodle_tpu.ops.hashing` instead (SURVEY.md §7 explicitly allows
  swapping the internal hash and keeping CRC-32 at the interop boundary).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

_POLY = 0xEDB88320


def _make_table() -> np.ndarray:
    table = np.zeros(256, dtype=np.uint32)
    for n in range(256):
        c = np.uint32(n)
        for _ in range(8):
            c = (c >> np.uint32(1)) ^ (np.uint32(_POLY) if c & np.uint32(1) else np.uint32(0))
        table[n] = c
    return table


# Module-level constant; becomes an XLA constant folded into compiled programs.
CRC_TABLE = _make_table()


def crc32_update_bytes(state: jax.Array, data: jax.Array, mask: jax.Array | None = None) -> jax.Array:
    """Fold ``data`` bytes into running CRC states, vectorized across rows.

    Args:
      state: uint32 ``[...]`` running CRC state (pre-inverted, i.e. raw register).
      data: uint8 ``[..., K]`` bytes to fold, row-aligned with ``state``.
      mask: optional bool ``[..., K]``; False bytes are skipped (state unchanged).

    Returns uint32 ``[...]`` updated raw CRC register.
    """
    table = jnp.asarray(CRC_TABLE)
    data = data.astype(jnp.uint32)

    def step(s, xs):
        byte, m = xs
        idx = (s ^ byte) & jnp.uint32(0xFF)
        new = (s >> jnp.uint32(8)) ^ table[idx]
        if m is not None:
            new = jnp.where(m, new, s)
        return new, None

    if mask is None:
        out, _ = jax.lax.scan(lambda s, b: step(s, (b, None)), state, jnp.moveaxis(data, -1, 0))
        return out
    out, _ = jax.lax.scan(step, state, (jnp.moveaxis(data, -1, 0), jnp.moveaxis(mask, -1, 0)))
    return out


def crc32(data: jax.Array, mask: jax.Array | None = None) -> jax.Array:
    """CRC-32 of each row of ``data`` (uint8 ``[..., K]``) -> uint32 ``[...]``.

    Matches ``zlib.crc32`` on the unmasked bytes of each row.
    """
    init = jnp.full(data.shape[:-1], 0xFFFFFFFF, dtype=jnp.uint32)
    out = crc32_update_bytes(init, data, mask)
    return out ^ jnp.uint32(0xFFFFFFFF)


def record_bytes(peer_ids: jax.Array, identities: jax.Array) -> jax.Array:
    """Fixed-width byte records for the simulator's CRC-parity fingerprint.

    The simulator's canonical record for peer j is 8 bytes: big-endian uint32
    peer index followed by big-endian uint32 identity word. (The reference
    hashes the *address string* + identity bytes, kaboodle.rs:77-79; simulated
    peers are dense indices, so this fixed-width encoding is the sim-canonical
    equivalent. Byte-exact interop CRC lives in kaboodle_tpu.transport.)

    Args:
      peer_ids: int32/uint32 ``[N]``.
      identities: uint32 ``[N]``.
    Returns uint8 ``[N, 8]``.
    """
    pid = peer_ids.astype(jnp.uint32)
    idn = identities.astype(jnp.uint32)

    def be_bytes(x):
        return jnp.stack(
            [
                (x >> jnp.uint32(24)) & jnp.uint32(0xFF),
                (x >> jnp.uint32(16)) & jnp.uint32(0xFF),
                (x >> jnp.uint32(8)) & jnp.uint32(0xFF),
                x & jnp.uint32(0xFF),
            ],
            axis=-1,
        )

    return jnp.concatenate([be_bytes(pid), be_bytes(idn)], axis=-1).astype(jnp.uint8)


def membership_crc32(member: jax.Array, identities: jax.Array) -> jax.Array:
    """Order-sensitive CRC-32 fingerprint of each peer's membership row.

    ``fingerprint[i] = crc32(concat over j ascending where member[i, j] of
    record_bytes(j, identities[j]))`` — the sim-canonical analogue of
    ``generate_fingerprint`` (kaboodle.rs:71-83): the reference sorts peers by
    address; here peer identity IS the dense index so ascending index order is
    the sort order.

    Sequential in N (scan over the peer axis), vectorized across the N rows.
    Use for parity tests / small N; production uses the commutative mix-hash.

    Args:
      member: bool ``[N, N]`` (member[i, j]: does i know j).
      identities: uint32 ``[N]``.
    Returns uint32 ``[N]``.
    """
    n = member.shape[-1]
    recs = record_bytes(jnp.arange(n, dtype=jnp.uint32), identities)  # [N, 8]

    def step(state, j):
        m = member[:, j]  # [N] does each row include peer j
        mask = jnp.broadcast_to(m[:, None], (n, 8))
        return crc32_update_bytes(state, jnp.broadcast_to(recs[j], (n, 8)), mask), None

    init = jnp.full((n,), 0xFFFFFFFF, dtype=jnp.uint32)
    out, _ = jax.lax.scan(step, init, jnp.arange(n, dtype=jnp.int32))
    return out ^ jnp.uint32(0xFFFFFFFF)
