"""Pallas TPU kernel: fused membership fingerprint + count row-reduction.

The hottest per-tick op at large N is the fingerprint pass (kernel.py
``fp_count``): read ``state`` int8 ``[N, N]``, mask, hash, and row-reduce.
Expressed in jnp that is a compare (int8 -> bool), a hash over a ``[N, N]``
uint32 tensor (id-view mode), a select, and a sum — XLA fuses most of it, but
this kernel guarantees ONE pass over HBM with everything (member test, record
hash, masked add, count) done in VMEM per tile, and no ``[N, N]``
intermediates at all:

    fp[i]    = sum_{j : state[i,j] > 0} mix32(mix32(j ^ GOLDEN) ^ id[i,j])
    count[i] = |{j : state[i,j] > 0}|

Bit-exact with :func:`kaboodle_tpu.ops.hashing.membership_fingerprint` over
``state > 0`` (uint32 wraparound sums are associative and commutative, so the
tiled accumulation order cannot change the result — asserted in
tests/test_fused_fp.py).

Used by the tick kernel when ``SwimConfig(use_pallas_fp=True)`` (bench
enables it on the single-chip TPU path). Off TPU the op runs in pallas
interpreter mode, so the flag is correct — just not fast — everywhere; the
GSPMD sharded path keeps the jnp formulation, whose row-local reduction XLA
partitions with no collectives.

Reference anchor: the fingerprint this accelerates is the convergence signal
of kaboodle.rs:71-83 (see ops/hashing.py for the commutative redesign).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kaboodle_tpu.ops.hashing import peer_record_hash

# Per-input VMEM budget for the id-view block (bytes). Two inputs of this
# size plus the int8 state block and temporaries stay well under the ~16 MiB
# of VMEM even with double-buffered pipelining.
_VMEM_BLOCK_BYTES = 2 * 1024 * 1024


def _masked_wrap_sum(member, h):
    # Mosaic has no unsigned reductions; sum in int32 via bitcast — two's
    # complement wraparound addition is bit-identical to uint32 modular
    # addition, so the result is still exact.
    masked = jax.lax.bitcast_convert_type(
        jnp.where(member, h, jnp.uint32(0)), jnp.int32
    )
    return jax.lax.bitcast_convert_type(
        jnp.sum(masked, axis=1, keepdims=True, dtype=jnp.int32), jnp.uint32
    )


def _kernel_idv(state_ref, idv_ref, fp_ref, cnt_ref):
    # Upcast in VMEM: Mosaic on v5e lacks sub-32-bit vector compares.
    member = state_ref[:].astype(jnp.int32) > 0
    # The canonical record hash (ops.hashing) is plain jnp, so it runs inside
    # the kernel body unchanged — one definition for both formulations.
    pid = jax.lax.broadcasted_iota(jnp.uint32, idv_ref.shape, 1)
    h = peer_record_hash(pid, idv_ref[:])
    fp_ref[:] = _masked_wrap_sum(member, h)
    # dtype spelled: integer sums promote to the platform int under
    # jax_enable_x64 and the output ref is pinned int32 (graftscan KB401).
    cnt_ref[:] = jnp.sum(member.astype(jnp.int32), axis=1, keepdims=True, dtype=jnp.int32)


def _kernel_hash(state_ref, hash_ref, fp_ref, cnt_ref):
    member = state_ref[:].astype(jnp.int32) > 0
    h = jnp.broadcast_to(hash_ref[:], member.shape)
    fp_ref[:] = _masked_wrap_sum(member, h)
    # dtype spelled: integer sums promote to the platform int under
    # jax_enable_x64 and the output ref is pinned int32 (graftscan KB401).
    cnt_ref[:] = jnp.sum(member.astype(jnp.int32), axis=1, keepdims=True, dtype=jnp.int32)


def _block_rows(n: int, bytes_per_cell: int) -> int:
    rows = _VMEM_BLOCK_BYTES // max(n * bytes_per_cell, 1)
    # Clamp to the int8 sublane tile (32) at the low end so state blocks stay
    # tile-aligned, and keep the grid non-trivial at small N.
    return int(max(32, min(rows, 512, n)))


def pallas_supported(n: int) -> bool:
    """Shapes the kernel handles: lane-aligned square state (N % 128 == 0)."""
    return n % 128 == 0


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_fp_count(
    state: jax.Array,
    identity: jax.Array,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Row fingerprints + membership counts of ``state`` in one fused pass.

    Args:
      state: int8 ``[N, N]`` spec state codes (member == code > 0).
      identity: uint32 ``[N, N]`` per-row identity views (``MeshState.id_view``)
        or uint32 ``[N]`` precomputed *record hashes* (the instant-identity
        mode — NB: unlike membership_fingerprint this is the hashed vector,
        exactly what the tick kernel precomputes as ``rec_hash``).
      interpret: force pallas interpreter mode; default auto (True off-TPU).

    Returns ``(fp uint32 [N], count int32 [N])``.
    """
    n = state.shape[-1]
    if not pallas_supported(n):
        raise ValueError(f"fused_fp_count needs N % 128 == 0, got {n}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    idv_mode = identity.ndim == 2
    bn = _block_rows(n, 4 if idv_mode else 1)
    grid = ((n + bn - 1) // bn,)
    out_shape = (
        jax.ShapeDtypeStruct((n, 1), jnp.uint32),
        jax.ShapeDtypeStruct((n, 1), jnp.int32),
    )
    out_specs = (
        pl.BlockSpec((bn, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((bn, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
    )
    row_block = pl.BlockSpec((bn, n), lambda i: (i, 0), memory_space=pltpu.VMEM)
    if idv_mode:
        fp, cnt = pl.pallas_call(
            _kernel_idv,
            grid=grid,
            in_specs=[row_block, row_block],
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
        )(state, identity.astype(jnp.uint32))
    else:
        hash_block = pl.BlockSpec((1, n), lambda i: (0, 0), memory_space=pltpu.VMEM)
        fp, cnt = pl.pallas_call(
            _kernel_hash,
            grid=grid,
            in_specs=[row_block, hash_block],
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
        )(state, identity.astype(jnp.uint32)[None, :])
    return fp[:, 0], cnt[:, 0]
