"""Pallas TPU kernel: fused oldest-k ping-target candidates in one HBM pass.

The ping-target draw (kaboodle.rs:661-675: sort Known peers by last-heard,
take the oldest 5, pick one) is, after the scatter-free mark rewrite, the
tick's biggest remaining HBM consumer: the jnp ``iter`` formulation
(ops/sampling._stable_k_smallest_iter) reads the ``[N, N]`` timer matrix once
per round (k=5 rounds) plus an eligibility pass over ``state``. This kernel
does the whole thing — eligibility (alive row, ``state == KNOWN``, not self),
k rounds of lexicographic (timer, index) min-reduction — inside VMEM per row
tile: ONE read of ``state`` (int8) and ``timer`` (int16/int32), no ``[N, N]``
eligibility mask ever materialized.

Bit-exact with ``_stable_k_smallest_iter`` over the same eligibility
(asserted in tests/test_fused_oldest_k.py), hence with stable ``top_k`` —
including the sentinel edge: a timer equal to the timer dtype's max is
treated as invalid, exactly as the jnp path's masking makes it.

Mosaic v5e constraints honored (see ops/fused_fp.py): all in-kernel vector
compares/reductions run in int32 (sub-32-bit compares and unsigned
reductions do not lower).

Reference anchor: selection semantics kaboodle.rs:655-675; SWIM's
round-robin-ish completeness bound derives from it (SURVEY §6).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kaboodle_tpu.ops.pallas_util import pick_row_block
from kaboodle_tpu.spec import KNOWN


def _make_kernel(k: int, n: int, tmax: int):
    def kernel(state_ref, timer_ref, alive_ref, out_idx_ref, out_valid_ref):
        S = state_ref[:].astype(jnp.int32)  # [bn, N]
        T = timer_ref[:].astype(jnp.int32)
        alive = alive_ref[:].astype(jnp.int32)  # [bn, 1]
        bn = S.shape[0]
        base = pl.program_id(0) * bn
        col = jax.lax.broadcasted_iota(jnp.int32, (bn, n), 1)
        row = base + jax.lax.broadcasted_iota(jnp.int32, (bn, n), 0)
        # A timer at the dtype's max is indistinguishable from the jnp
        # formulation's ineligibility sentinel (sampling.choose_one_of_oldest_k
        # masks with iinfo(timer.dtype).max), so it is invalid there; exclude
        # it here too so the two stay bit-exact without relying on the
        # timers-below-dtype-max contract (init_state enforces it, but the
        # kernel must not silently depend on its callers).
        elig = (
            (alive > 0) & (S == KNOWN) & (col != row) & (T != jnp.int32(tmax))
        )

        NMAX = jnp.int32(jnp.iinfo(jnp.int32).max)
        big_i = jnp.int32(n)
        prev_t = jnp.full((bn, 1), jnp.iinfo(jnp.int32).min, jnp.int32)
        prev_i = jnp.full((bn, 1), -1, jnp.int32)
        for r in range(k):
            after_prev = (T > prev_t) | ((T == prev_t) & (col > prev_i))
            cand = elig & after_prev
            t_r = jnp.min(jnp.where(cand, T, NMAX), axis=1, keepdims=True)
            i_r = jnp.min(
                jnp.where(cand & (T == t_r), col, big_i), axis=1, keepdims=True
            )
            out_idx_ref[:, r : r + 1] = jnp.minimum(i_r, n - 1)
            out_valid_ref[:, r : r + 1] = (t_r != NMAX).astype(jnp.int32)
            prev_t, prev_i = t_r, i_r

    return kernel


def pallas_oldest_k_supported(n: int) -> bool:
    """Lane-aligned square state, like the fused fingerprint kernel."""
    return n % 128 == 0


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def fused_oldest_k(
    state: jax.Array,
    timer: jax.Array,
    alive: jax.Array,
    k: int,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Per row: indices of the k smallest timers among eligible peers.

    Eligibility is computed in-kernel: ``alive[i] & (state[i, j] == KNOWN) &
    (j != i)`` — exactly the tick kernel's ping-target mask.

    Args:
      state: int8 ``[N, N]`` spec state codes.
      timer: int16/int32 ``[N, N]`` last-heard ticks.
      alive: bool ``[N]``.
      k: candidate count (NUM_CANDIDATE_TARGET_PEERS; 1 in deterministic mode).

    Returns ``(idx int32 [N, k], valid bool [N, k])`` — identical contract to
    ops.sampling._stable_k_smallest_iter over the same eligibility.
    """
    n = state.shape[-1]
    if not pallas_oldest_k_supported(n):
        raise ValueError(f"fused_oldest_k needs N % 128 == 0, got {n}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # The kernel's live set is dominated by int32 working copies of the
    # [bn, N] tiles (state+timer upcasts, iotas, round masks — ~8 of them),
    # so budget 8 x int32 per cell; then take the largest sublane-aligned
    # (multiple-of-8) EXACT divisor of n within budget, so there is never a
    # padded partial last block.
    bn = pick_row_block(n)
    grid = ((n + bn - 1) // bn,)
    row_block = lambda cells: pl.BlockSpec(  # noqa: E731
        (bn, cells), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    idx, valid = pl.pallas_call(
        _make_kernel(k, n, int(jnp.iinfo(timer.dtype).max)),
        grid=grid,
        in_specs=[
            row_block(n),
            row_block(n),
            pl.BlockSpec((bn, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=(row_block(k), row_block(k)),
        out_shape=(
            jax.ShapeDtypeStruct((n, k), jnp.int32),
            jax.ShapeDtypeStruct((n, k), jnp.int32),
        ),
        interpret=interpret,
    )(state, timer, alive.astype(jnp.int32)[:, None])
    return idx, valid > 0
