"""Pallas TPU kernel: fused phase-A row statistics in one HBM pass.

Phase A of the tick (kaboodle.rs:746-757) needs four per-row reductions over
the same two matrices before any state is written:

  count[i]     = |{j : state[i,j] > 0}|                   (lonely test, A1)
  has_timed[i] = any WaitingForPing cell aged >= timeout   (A2 suspicion)
  jstar[i]     = that set's oldest cell, ties to lower j   (A2 escalation)
  has_cand[i]  = any Known cell j != i                     (A2 proxy pool)

As jnp these are 3-4 fused XLA passes over ``state`` + ``timer``; here they
run inside VMEM per row tile — ONE read of each matrix for the whole phase.
Bit-exact with the jnp formulation (tests/test_fused_suspicion.py, including
whole-tick trajectory parity via SwimConfig.use_pallas_suspicion).

Mosaic v5e constraints as in ops/fused_fp.py: all vector compares and
reductions in int32.

Reference anchors: lonely test kaboodle.rs:228-251; suspicion scan
kaboodle.rs:558-605 (oldest timed-out WaitingForPing, D1 single escalation).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kaboodle_tpu.ops.pallas_util import pick_row_block
from kaboodle_tpu.spec import KNOWN, WAITING_FOR_INDIRECT_PING, WAITING_FOR_PING


def _make_kernel(n: int):
    def kernel(state_ref, timer_ref, alive_ref, thr_ref,
               cnt_ref, jstar_ref, timed_ref, cand_ref, wfip_ref):
        S = state_ref[:].astype(jnp.int32)  # [bn, N]
        T = timer_ref[:].astype(jnp.int32)
        alive = alive_ref[:].astype(jnp.int32) > 0  # [bn, 1]
        thr = thr_ref[:]  # [bn, 1] int32: t - ping_timeout_ticks
        bn = S.shape[0]
        base = pl.program_id(0) * bn
        col = jax.lax.broadcasted_iota(jnp.int32, (bn, n), 1)
        row = base + jax.lax.broadcasted_iota(jnp.int32, (bn, n), 0)

        # dtype spelled on the sum: integer sums promote to the platform int
        # under jax_enable_x64, and the kernel's output ref is pinned int32
        # (graftscan KB401 — the x64 trace snaps on the mismatch).
        cnt_ref[:] = jnp.sum(
            (S > 0).astype(jnp.int32), axis=1, keepdims=True, dtype=jnp.int32
        )

        NMAX = jnp.int32(jnp.iinfo(jnp.int32).max)
        timed = alive & (S == WAITING_FOR_PING) & (T <= thr)
        t_min = jnp.min(jnp.where(timed, T, NMAX), axis=1, keepdims=True)
        jstar = jnp.min(
            jnp.where(timed & (T == t_min), col, jnp.int32(n)),
            axis=1,
            keepdims=True,
        )
        timed_any = (t_min != NMAX).astype(jnp.int32)
        timed_ref[:] = timed_any
        jstar_ref[:] = jnp.where(timed_any > 0, jnp.minimum(jstar, n - 1), -1)

        cand = (S == KNOWN) & (col != row)
        cand_ref[:] = jnp.max(cand.astype(jnp.int32), axis=1, keepdims=True)

        # Aged WaitingForIndirectPing cells (the A2 removal set, kaboodle.rs:
        # 617-627): their any-per-row rides this pass so the tick can gate the
        # whole A2 write phase without an extra read of state/timer.
        wfip = alive & (S == WAITING_FOR_INDIRECT_PING) & (T <= thr)
        wfip_ref[:] = jnp.max(wfip.astype(jnp.int32), axis=1, keepdims=True)

    return kernel


def pallas_suspicion_supported(n: int) -> bool:
    """Same shape rule as the other fused kernels."""
    return n % 128 == 0


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_suspicion(
    state: jax.Array,
    timer: jax.Array,
    alive: jax.Array,
    timed_threshold: jax.Array,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Phase-A row stats of ``(state, timer)`` in one fused pass.

    Args:
      state: int8 ``[N, N]`` spec state codes.
      timer: int16/int32 ``[N, N]`` state-entry ticks.
      alive: bool ``[N]``.
      timed_threshold: int32 scalar ``t - ping_timeout_ticks`` — a
        WaitingForPing cell is timed out iff ``timer <= timed_threshold``.

    Returns ``(count int32 [N], jstar int32 [N] (-1 = none),
    has_timed bool [N], has_cand bool [N], has_timed_wfip bool [N])``
    matching the tick kernel's jnp formulation exactly (suspicion judged on
    alive rows only); ``has_timed_wfip`` is the row-wise any of the A2
    removal set (aged WaitingForIndirectPing cells).
    """
    n = state.shape[-1]
    if not pallas_suspicion_supported(n):
        raise ValueError(f"fused_suspicion needs N % 128 == 0, got {n}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    bn = pick_row_block(n)
    grid = ((n + bn - 1) // bn,)
    row_block = lambda cells: pl.BlockSpec(  # noqa: E731
        (bn, cells), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    vec = jnp.broadcast_to(jnp.asarray(timed_threshold, jnp.int32), (n,))
    cnt, jstar, timed, cand_, wfip = pl.pallas_call(
        _make_kernel(n),
        grid=grid,
        in_specs=[row_block(n), row_block(n), row_block(1), row_block(1)],
        out_specs=(row_block(1),) * 5,
        out_shape=tuple(jax.ShapeDtypeStruct((n, 1), jnp.int32) for _ in range(5)),
        interpret=interpret,
    )(state, timer, alive.astype(jnp.int32)[:, None], vec[:, None])
    return cnt[:, 0], jstar[:, 0], timed[:, 0] > 0, cand_[:, 0] > 0, wfip[:, 0] > 0
