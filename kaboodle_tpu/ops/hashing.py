"""Commutative membership fingerprint: the production convergence hash.

The reference's fingerprint is an order-sensitive CRC-32 over sorted peers
(kaboodle.rs:71-83). Its only job is *equality testing* between two peers'
views of the mesh (kaboodle.rs:68-70 says so explicitly) — so the simulator's
production hash is a commutative 32-bit sum of per-peer record hashes:

    fingerprint[i] = sum_{j : member[i,j]} mix32(j, identity[j])  (mod 2^32)

Commutativity turns the fingerprint into a masked row-reduction — one
bandwidth-bound pass over the ``[N, N]`` membership tensor with a precomputed
``[N]`` vector of record hashes — instead of an O(N)-step sequential CRC scan.
Equality semantics are preserved (identical membership+identities => identical
fingerprint; differing views collide with probability ~2^-32). CRC-32 remains
available for parity tests (ops.crc32) and is byte-exact at the wire-interop
boundary (kaboodle_tpu.transport).

``mix32`` is the splitmix32 finalizer — a bijective avalanche mixer, the moral
equivalent of crc32's diffusion for this purpose.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def mix32(x: jax.Array) -> jax.Array:
    """splitmix32 finalizer; bijective on uint32."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> jnp.uint32(15))
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> jnp.uint32(16))
    return x


def peer_record_hash(peer_ids: jax.Array, identities: jax.Array) -> jax.Array:
    """32-bit hash of one (peer id, identity) record. uint32 ``[N]``.

    Two mixing rounds so that neither field can cancel the other under the
    commutative sum.
    """
    pid = peer_ids.astype(jnp.uint32)
    idn = identities.astype(jnp.uint32)
    return mix32(mix32(pid ^ jnp.uint32(0x9E3779B9)) ^ idn)


def membership_fingerprint(member: jax.Array, identities: jax.Array) -> jax.Array:
    """Commutative fingerprint of each row of the membership tensor.

    Args:
      member: bool ``[N, N]``; member[i, j] == peer i has peer j in its map.
      identities: uint32 ``[N]`` global identity word per peer, or ``[N, N]``
        per-row identity *views* (``MeshState.id_view`` — row i hashes the
        identities it has actually seen, the traffic-driven model).
    Returns uint32 ``[N]``: fingerprint of each peer's view.

    Replaces ``generate_fingerprint`` (kaboodle.rs:71-83) for on-device use;
    wraparound uint32 addition == mod 2^32.
    """
    pid = jnp.arange(member.shape[-1], dtype=jnp.uint32)
    if identities.ndim == 2:
        h = peer_record_hash(pid[None, :], identities)  # [N, N]
        contrib = jnp.where(member, h, jnp.uint32(0))
    else:
        h = peer_record_hash(pid, identities)
        contrib = jnp.where(member, h[None, :], jnp.uint32(0))
    return jnp.sum(contrib, axis=-1, dtype=jnp.uint32)


def fingerprint_agreement(alive, fp):
    """Global fingerprint-agreement reduction over the alive rows.

    THE convergence predicate of the whole framework — the tick kernel's
    end-of-tick metrics and the standalone
    :func:`kaboodle_tpu.parallel.sharded_convergence_check` both reduce
    through this one helper so the definition cannot drift (the sentinels
    make dead rows neutral for both extremes). Under GSPMD the min/max/sum
    partition into per-shard reductions combined over the peer axis — the
    BASELINE config-4 "ICI all-reduce" check.

    Returns ``(converged, fp_min, fp_max, n_alive)``.
    """
    fp_min = jnp.min(jnp.where(alive, fp, jnp.uint32(0xFFFFFFFF)))
    fp_max = jnp.max(jnp.where(alive, fp, jnp.uint32(0)))
    n_alive = jnp.sum(alive, dtype=jnp.int32)
    return (fp_min == fp_max) & (n_alive > 0), fp_min, fp_max, n_alive
