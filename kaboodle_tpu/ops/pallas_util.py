"""Shared row-tiling policy for the int32-upcast fused Pallas kernels.

fused_oldest_k and fused_suspicion upcast their [bn, N] tiles to int32 in
VMEM and keep ~8 working copies live, so both budget 8 x int32 per cell and
need bn to divide N exactly (no padded partial block — Mosaic pads reads,
but a padded block would also run the reduction over garbage lanes whose
outputs are then dropped; exact division keeps every block meaningful and
was the fix for a pathological interpret-mode slowdown at non-power-of-two
N). fused_fp keeps its own narrower policy (no int32 upcast of the wide
input), proven on real hardware.
"""

from __future__ import annotations

_VMEM_BLOCK_BYTES = 2 * 1024 * 1024


def pick_row_block(n: int) -> int:
    """Largest sublane-aligned (multiple-of-8) exact divisor of ``n`` whose
    int32 working set (~8 copies of [bn, n]) fits the VMEM budget."""
    budget = int(max(8, min(_VMEM_BLOCK_BYTES // (n * 8 * 4), 512, n)))
    for cand in range(budget - budget % 8, 7, -8):
        if n % cand == 0:
            return cand
    return 8
