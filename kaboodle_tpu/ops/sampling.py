"""Masked random-selection primitives for the tick kernel.

The reference makes three kinds of random draws per tick (ChaChaRng,
kaboodle.rs:164):

1. ping target = uniform choice among the 5 longest-unheard Known peers
   (``sort -> take(5) -> choose``, kaboodle.rs:661-675);
2. indirect-ping proxies = ``choose_multiple`` of 3 distinct non-suspected
   peers (kaboodle.rs:595-597);
3. broadcast-reply Bernoulli with probability ``max(1, 100 - n^2)/100``
   (kaboodle.rs:344-353).

Here each draw is a fixed-shape batched op over all N simulated peers at once.
Exact ChaCha sequence parity is a non-goal (SURVEY.md §7); distributional
parity is tested in tests/test_sampling.py. Every op has a ``deterministic``
mode (lowest-index / always-true) used for exact oracle-vs-kernel trajectory
tests.

Static-by-contract flags: ``deterministic`` (and ``method``) select which
program gets traced — callers always pass Python bools/strings (the tick
kernels bake ``cfg.deterministic`` in at build time), never tracers. The
``# graftlint: traced`` pragmas below keep the KB2xx tracer rules live on
these functions (they are traced from kernel.py/chunked.py, which
per-module reachability cannot see); the resulting KB201 findings on the
specialization branches are baselined with exactly this contract as the
justification (.graftlint_baseline.json).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _stable_k_smallest_topk(scores: jax.Array, k: int, tmax) -> tuple[jax.Array, jax.Array]:  # graftlint: traced
    """(idx, valid) of the k smallest scores per row via sort-based top_k.

    Negation overflows at the dtype minimum (-(-32768) == -32768 in int16),
    which would sort dtype-min scores as the *largest*; timers can go
    slightly negative (Q6 back-dating near tick 0), so widen int16 before
    negating. int32 scores keep the documented timer precondition
    (> INT32_MIN, trivially true for tick stamps)."""
    wide = scores.astype(jnp.int32) if scores.dtype == jnp.int16 else scores
    neg_vals, idx = jax.lax.top_k(-wide, k)  # [N, k]
    # Sentinel test stays in jnp: converting tmax through a numpy scalar
    # (wide.dtype.type(tmax)) breaks under jit when tmax traces (int16 path).
    return idx.astype(jnp.int32), neg_vals != -jnp.asarray(tmax, wide.dtype)


def _stable_k_smallest_iter(scores: jax.Array, k: int, tmax) -> tuple[jax.Array, jax.Array]:  # graftlint: traced
    """(idx, valid) of the k smallest scores per row, ties toward lower index.

    k rounds of lexicographic min-reduction over (score, index): round r
    restricts to entries strictly greater (lex) than round r-1's pick and
    takes the min. Identical to stable top_k (equality pinned in
    tests/test_sampling.py), but each round is a fused masked reduction —
    no [N, N] sort, which is what top_k lowers to on TPU and what dominated
    the tick at large N.
    """
    n = scores.shape[-1]
    idxr = jnp.arange(n, dtype=jnp.int32)[None, :]
    big_i = jnp.int32(n)  # index sentinel > any real column
    prev_s = jnp.full(scores.shape[:-1], jnp.iinfo(scores.dtype).min, scores.dtype)
    prev_i = jnp.full(scores.shape[:-1], -1, jnp.int32)
    out_i, out_v = [], []
    for _ in range(k):
        after_prev = (scores > prev_s[:, None]) | (
            (scores == prev_s[:, None]) & (idxr > prev_i[:, None])
        )
        s_r = jnp.min(jnp.where(after_prev, scores, tmax), axis=-1)
        i_r = jnp.min(
            jnp.where(after_prev & (scores == s_r[:, None]), idxr, big_i), axis=-1
        )
        # A row is exhausted when only sentinel scores remain; sentinel cells
        # themselves are still ordered by index, matching top_k's tail.
        out_i.append(i_r)
        out_v.append(s_r != tmax)
        prev_s, prev_i = s_r, i_r
    idx = jnp.minimum(jnp.stack(out_i, axis=-1), n - 1)  # big_i only where invalid
    return idx, jnp.stack(out_v, axis=-1)


def choose_one_of_oldest_k(  # graftlint: traced
    timer: jax.Array,
    eligible: jax.Array,
    k: int,
    key: jax.Array,
    deterministic: bool = False,
    method: str = "iter",
) -> jax.Array:
    """Per row: uniform choice among the k eligible entries with smallest timer.

    Mirrors ping-target selection (kaboodle.rs:661-675): sort Known peers by
    last-heard tick, take the oldest ``k``, pick one uniformly. Ties break
    toward the lower index (top_k is stable), matching the oracle's stable sort.

    Args:
      timer: int32 or int16 ``[N, N]`` last-heard tick (row i's view of
        peer j; int16 in the lean-memory mode, MEMORY_PLAN.md).
      eligible: bool ``[N, N]`` candidate mask (Known, not self).
      k: NUM_CANDIDATE_TARGET_PEERS.
      key: PRNG key.
      deterministic: pick the single oldest instead of randomizing.
      method: "topk" (sort-based) or "iter" (k fused min-reductions) — same
        results, different TPU cost profile (see SwimConfig.oldest_k_method).

    Returns int32 ``[N]``: chosen column per row, or -1 if the row has no
    eligible entries.
    """
    n = timer.shape[-1]
    k = min(k, n)
    # The "ineligible" sentinel must live in the timer's dtype (int16 in the
    # lean-memory mode): a bare INT32_MAX would wrap to -1 and make
    # ineligible entries look like the oldest candidates.
    tmax = jnp.asarray(jnp.iinfo(timer.dtype).max, dtype=timer.dtype)
    scores = jnp.where(eligible, timer, tmax)
    if deterministic and method == "iter":
        # Only the single oldest is consumed — one lex-min round suffices.
        idx, valid = _stable_k_smallest_iter(scores, 1, tmax)
        return jnp.where(valid[:, 0], idx[:, 0], -1).astype(jnp.int32)
    pick = _stable_k_smallest_iter if method == "iter" else _stable_k_smallest_topk
    idx, valid = pick(scores, k, tmax)
    return choose_among_candidates(idx, valid, key, deterministic)


def choose_among_candidates(  # graftlint: traced
    idx: jax.Array,
    valid: jax.Array,
    key: jax.Array,
    deterministic: bool = False,
) -> jax.Array:
    """Uniform pick per row from ``(idx, valid)`` candidate lists ``[N, k]``.

    The selection tail shared by every oldest-k formulation (jnp iter/topk
    above, the fused Pallas kernel in ops/fused_oldest_k.py) — one draw per
    row, so identical keys give identical picks across formulations. Returns
    int32 ``[N]``, -1 where a row has no valid candidate."""
    if deterministic:
        return pick_candidate(idx, valid, None)
    # dtype pinned: the x32 default, spelled so the draw stays f32 under
    # jax_enable_x64 (graftscan KB401 — an f64 draw here would also break
    # draw parity with the warp leap's batched uniforms).
    return pick_candidate(
        idx, valid, jax.random.uniform(key, (idx.shape[0],), dtype=jnp.float32)
    )


def pick_candidate(  # graftlint: traced
    idx: jax.Array,
    valid: jax.Array,
    u: jax.Array | None,
) -> jax.Array:
    """The draw-free core of :func:`choose_among_candidates`.

    ``u`` is the per-row uniform sample in [0, 1) — or ``None`` for the
    deterministic lowest-priority pick. Split out so the warp leap kernel
    (kaboodle_tpu/warp/leap.py) can batch all k ticks' uniforms up front
    (counter-based PRNG) and feed them back through the EXACT tail the dense
    kernel uses: same uniform in, bit-identical target out."""
    count = jnp.sum(valid, axis=-1)  # [N]
    if u is None:
        choice = jnp.zeros(idx.shape[0], dtype=jnp.int32)
    else:
        choice = jnp.floor(u * count.astype(jnp.float32)).astype(jnp.int32)
        choice = jnp.minimum(choice, jnp.maximum(count - 1, 0))
    chosen = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0]
    return jnp.where(count > 0, chosen, -1).astype(jnp.int32)


def choose_k_members(  # graftlint: traced
    eligible: jax.Array,
    k: int,
    key: jax.Array,
    deterministic: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Per row: up to ``k`` distinct members of the mask, uniformly at random.

    Mirrors proxy selection for indirect pings (``choose_multiple``,
    kaboodle.rs:595-597): if a row has fewer than ``k`` eligible entries it
    returns all of them, like the reference.

    Implementation: Gumbel-top-k over the mask — an exact uniform sample of a
    size-k subset, fully vectorized (no per-row loops).

    Returns:
      idx: int32 ``[N, k]`` chosen columns (undefined where not valid).
      valid: bool ``[N, k]``.
    """
    n = eligible.shape[-1]
    k = min(k, n)
    if deterministic:
        scores = jnp.where(eligible, -jnp.arange(n, dtype=jnp.float32)[None, :], -jnp.inf)
    else:
        g = jax.random.gumbel(key, eligible.shape, dtype=jnp.float32)
        scores = jnp.where(eligible, g, -jnp.inf)
    vals, idx = jax.lax.top_k(scores, k)
    valid = vals > -jnp.inf
    return idx.astype(jnp.int32), valid


def bernoulli_matrix(  # graftlint: traced
    key: jax.Array,
    prob: jax.Array,
    shape: tuple[int, ...],
    deterministic: bool = False,
) -> jax.Array:
    """Bernoulli draws with per-element (broadcastable) probabilities.

    Used for the broadcast-reply dampening curve (kaboodle.rs:344-353). In
    deterministic mode every draw with positive probability succeeds (the
    reference's probabilities are always >= 1%).
    """
    if deterministic:
        return jnp.broadcast_to(prob > 0, shape)
    u = jax.random.uniform(key, shape, dtype=jnp.float32)  # pinned (KB401)
    return u < jnp.broadcast_to(prob, shape)


def broadcast_reply_prob(num_known: jax.Array) -> jax.Array:  # graftlint: traced
    """The reply-dampening curve ``max(1, 100 - n^2)/100`` with ``n = len - 2``.

    ``num_known`` is the receiver's membership-map size *including itself*
    (kaboodle.rs:344: ``num_other_peers = len - 2`` — minus self and sender);
    ``n <= 0`` means certain reply (kaboodle.rs:345-348).
    """
    n_other = num_known.astype(jnp.int32) - 2
    # The curve hits its 1% floor at n_other = 10; clamp before squaring so the
    # int32 square cannot overflow at mesh sizes >= 46341 (reference computes
    # in i64, kaboodle.rs:344-351).
    n_clamped = jnp.minimum(n_other, 10)
    pct = jnp.maximum(1, 100 - n_clamped * n_clamped).astype(jnp.float32) / 100.0
    return jnp.where(n_other <= 0, 1.0, pct)
