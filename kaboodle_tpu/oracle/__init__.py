"""Reference-faithful per-peer SWIM engine (pure Python, transport-agnostic).

This is the framework's executable spec: a direct, readable implementation of
the reference protocol engine (src/kaboodle.rs) used as
- the oracle that the vectorized JAX kernel (kaboodle_tpu.sim) is tested
  against, via the deterministic lockstep harness in ``lockstep.py``;
- the state machine behind the real-network UDP transport
  (kaboodle_tpu.transport), where it runs against a wall clock.
"""

from kaboodle_tpu.oracle.engine import PeerEngine, PeerRecord, Outbox
from kaboodle_tpu.oracle.engine import (
    Ping,
    PingRequest,
    Ack,
    KnownPeersMsg,
    KnownPeersRequest,
    Join,
    Failed,
    Probe,
    ProbeResponse,
)
from kaboodle_tpu.oracle.fingerprint import mix_fingerprint, crc_fingerprint
from kaboodle_tpu.oracle.lockstep import LockstepMesh

__all__ = [
    "PeerEngine",
    "PeerRecord",
    "Outbox",
    "Ping",
    "PingRequest",
    "Ack",
    "KnownPeersMsg",
    "KnownPeersRequest",
    "Join",
    "Failed",
    "Probe",
    "ProbeResponse",
    "mix_fingerprint",
    "crc_fingerprint",
    "LockstepMesh",
]
