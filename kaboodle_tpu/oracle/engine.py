"""One SWIM peer's state machine — a faithful re-expression of kaboodle.rs.

Transport-agnostic and clock-agnostic: every entry point takes ``now`` (ticks
in the simulator, seconds against a wall clock in the real transport) and
returns an :class:`Outbox` of messages to deliver. Citations reference
/root/reference/src/kaboodle.rs unless noted.

Semantics preserved exactly (SURVEY.md §8 quirk numbers):
- Q1: ANY inbound unicast marks its sender Known(now) before dispatch
  (kaboodle.rs:408-415) — the only mechanism that clears suspicion.
- Q11 (faithful_indirect_ack): a forwarded indirect-ping Ack resurrects the
  *proxy* that forwarded it, not the suspect named inside it.
- Q3 (faithful_failed_broadcast): Failed broadcasts require the broadcast
  source address to be a known member, which never holds for real sockets
  (kaboodle.rs:268-283) — so they are inert by default.
- Q5: join-triggered shares send the whole map (self included, no age filter,
  kaboodle.rs:362-369); KnownPeersRequest replies filter by Known-state,
  10-tick age, and exclude self+requester (kaboodle.rs:483-501).
- Q6: gossip-learned peers are inserted back-dated by MAX_PEER_SHARE_AGE so
  they are never re-shared before direct contact (kaboodle.rs:459-470).
- Q8: stop() does not announce departure (lib.rs:159-183).

Documented deviations from the reference (see PARITY.md):
- D1: at most one WaitingForPing escalation per tick (unreachable under normal
  dynamics, which enter suspicion at <= 1 peer/tick).
- D2: at most one anti-entropy KnownPeersRequest issued per tick, chosen from
  the tick's sync candidates in arrival order.
- D3: curious-peer (indirect-ping relay) entries live for one tick instead of
  lingering until an eventual ack.
- D7: a forwarded Ack never re-forwards (one relay hop per tick), bounding the
  in-tick delivery chain at four calls. The reference's reactive loop would
  relay again if time remained in the period (kaboodle.rs:762-778).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Optional

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.spec import KNOWN, WAITING_FOR_INDIRECT_PING, WAITING_FOR_PING
from kaboodle_tpu.oracle.fingerprint import mix_fingerprint


# --- wire messages (structs.rs:64-116) --------------------------------------


@dataclasses.dataclass(frozen=True)
class Ping:  # SwimMessage::Ping (structs.rs:97)
    pass


@dataclasses.dataclass(frozen=True)
class PingRequest:  # SwimMessage::PingRequest (structs.rs:101)
    target: object


@dataclasses.dataclass(frozen=True)
class Ack:  # SwimMessage::Ack (structs.rs:103-107)
    peer: object
    mesh_fingerprint: int
    num_peers: int
    # D7: set on curious-peer relays; a forwarded Ack never re-forwards (the
    # reference's real-time loop would relay again if time remained in the
    # period, kaboodle.rs:762-778 — the lockstep model caps the relay at one
    # hop so the chain depth is fixed at four delivery calls).
    forwarded: bool = False


@dataclasses.dataclass(frozen=True)
class KnownPeersMsg:  # SwimMessage::KnownPeers (structs.rs:110)
    peers: tuple  # tuple of (addr, identity) pairs


@dataclasses.dataclass(frozen=True)
class KnownPeersRequest:  # SwimMessage::KnownPeersRequest (structs.rs:112-115)
    mesh_fingerprint: int
    num_peers: int


@dataclasses.dataclass(frozen=True)
class Join:  # SwimBroadcast::Join (structs.rs:67)
    addr: object
    identity: object


@dataclasses.dataclass(frozen=True)
class Failed:  # SwimBroadcast::Failed (structs.rs:69)
    peer: object


@dataclasses.dataclass(frozen=True)
class Probe:  # SwimBroadcast::Probe (structs.rs:72)
    addr: object


@dataclasses.dataclass(frozen=True)
class ProbeResponse:  # structs.rs:88-90
    identity: object


@dataclasses.dataclass
class PeerRecord:
    """PeerInfo (structs.rs:17-22): identity + state code + state timestamp.

    The reference stores the timestamp inside the PeerState variant; ``since``
    is that Instant (last-heard for Known, sent-at for the WaitingFor states).
    """

    identity: object
    state: int
    since: float
    latency: Optional[float] = None


class Outbox:
    """Messages produced by one handler invocation."""

    def __init__(self) -> None:
        self.unicasts: list[tuple[object, object]] = []  # (dest addr, msg)
        self.broadcasts: list[object] = []

    def send(self, dest: object, msg: object) -> None:
        self.unicasts.append((dest, msg))

    def broadcast(self, msg: object) -> None:
        self.broadcasts.append(msg)

    def extend(self, other: "Outbox") -> None:
        self.unicasts.extend(other.unicasts)
        self.broadcasts.extend(other.broadcasts)


def addr_key(addr: object):
    """Total order over peer addresses: numeric for simulated (int) peers —
    matching the kernel's index order — and string form otherwise (the
    reference sorts SocketAddrs, kaboodle.rs:72-73)."""
    return (0, addr, "") if isinstance(addr, int) else (1, 0, str(addr))


def _default_fingerprint(members: dict) -> int:
    return mix_fingerprint(members)


class PeerEngine:
    """KaboodleInner (kaboodle.rs:91-786) minus sockets, tasks, and mutexes."""

    def __init__(
        self,
        addr: object,
        identity: object,
        cfg: SwimConfig,
        now: float = 0,
        fingerprint_fn: Optional[Callable[[dict], int]] = None,
        seed: Optional[int] = None,
    ) -> None:
        self.addr = addr
        self.identity = identity
        self.cfg = cfg
        # Maps addr -> PeerRecord; self inserted as Known(now) (kaboodle.rs:144-152).
        self.known: dict = {addr: PeerRecord(identity, KNOWN, now)}
        # curious_peers: target addr -> list of requester addrs (kaboodle.rs:99-101).
        self.curious: dict = {}
        self.last_broadcast_time: Optional[float] = None  # kaboodle.rs:102-103
        self.pending_manual_pings: list = []  # ping_request_rx (kaboodle.rs:107)
        self._fingerprint_fn = fingerprint_fn or _default_fingerprint
        self._rng = random.Random(seed)
        # D2: sync candidates observed this tick, in arrival order.
        self._sync_candidates: list[tuple[object, int, int]] = []
        # Telemetry tallies (kaboodle_tpu.telemetry counter parity): the
        # lockstep harness zeroes these each tick and reads them after the
        # active phase — standalone (real-transport) use ignores them.
        self.last_escalated = 0
        self.last_removed = 0
        # Lockstep-only bookkeeping: the membership snapshot (addr ->
        # identity) at the start of the current broadcast round and the joins
        # (addr, identity) accepted during it. Under the harness, join-reply
        # shares are built from snapshot + joins-so-far (deviation D9: a
        # same-tick Failed delivery does not retroactively shrink a share —
        # the reference's outcome there depends on UDP arrival order, and
        # this is the ordering the O(N^2) kernel implements). None =>
        # standalone use (real transport): share the live map.
        self._round_base: Optional[dict] = None
        self._round_joins: list = []

    # --- queries (lib.rs:301-354) -------------------------------------------

    def fingerprint(self) -> int:
        return self._fingerprint_fn({a: r.identity for a, r in self.known.items()})

    def num_peers(self) -> int:
        return len(self.known)

    def peers(self) -> list:
        return sorted((a for a in self.known), key=addr_key)

    # --- internal helpers ----------------------------------------------------

    def _mark_known(self, sender: object, identity: object, now: float) -> None:
        """Q1: unconditional insert-as-Known of a datagram's sender
        (kaboodle.rs:408-415), with the latency EWMA of kaboodle.rs:789-817."""
        prev = self.known.get(sender)
        latency = None
        if prev is not None:
            if prev.state in (WAITING_FOR_PING, WAITING_FOR_INDIRECT_PING):
                sample = now - prev.since
                if prev.latency is None:
                    latency = sample
                else:
                    latency = 0.8 * sample + 0.2 * prev.latency  # kaboodle.rs:810-814
            else:
                latency = prev.latency
        self.known[sender] = PeerRecord(identity, KNOWN, now, latency)

    def _remove(self, peer: object) -> None:
        self.known.pop(peer, None)
        self.curious.pop(peer, None)  # kaboodle.rs:643-644

    def _should_respond_to_broadcast(self) -> bool:
        """Reply-dampening curve (kaboodle.rs:333-354)."""
        n_other = len(self.known) - 2
        if n_other <= 0:
            return True
        pct = max(1, 100 - min(n_other, 10) ** 2) / 100.0
        if self.cfg.deterministic:
            return True  # pct is always > 0
        return self._rng.random() < pct

    def _share_snapshot_join(self) -> list[tuple[object, object]]:
        """Q5: join-triggered share — whole map, self included, no age filter
        (kaboodle.rs:362-369), trimmed to max_share_peers (kaboodle.rs:373-383
        trims randomly until the payload fits the 10 KiB buffer)."""
        if self._round_base is None:
            entries = [(a, r.identity) for a, r in self.known.items()]
        else:
            # D9 (lockstep contract, aligned with the kernel's share_base /
            # term2): start-of-round snapshot plus joins accepted so far —
            # NOT the live map, which same-tick Failed deliveries may already
            # have shrunk. Identities refresh from the live record when one
            # exists (D-ID1).
            merged = dict(self._round_base)
            for a, ident in self._round_joins:
                merged[a] = ident
            entries = [
                (a, self.known[a].identity if a in self.known else ident)
                for a, ident in merged.items()
            ]
        cap = self.cfg.max_share_peers
        if cap and len(entries) > cap:
            if self.cfg.deterministic:
                if self._round_base is None:
                    # Standalone use: cap to the lowest-addressed entries.
                    entries.sort(key=lambda e: addr_key(e[0]))
                    entries = entries[:cap]
                else:
                    # D5 (aligned with the kernel): cap to the lowest-index
                    # members of the start-of-round map, plus — uncapped —
                    # every joiner accepted so far this round (the kernel's
                    # term2 adds this round's Join origins outside the cap).
                    base = sorted(
                        (e for e in entries if e[0] in self._round_base),
                        key=lambda e: addr_key(e[0]),
                    )[:cap]
                    base_addrs = {a for a, _ in base}
                    joins = {a for a, _ in self._round_joins}
                    extra = [
                        e for e in entries
                        if e[0] in joins and e[0] not in base_addrs
                    ]
                    entries = base + extra
            else:
                entries = self._rng.sample(entries, cap)
        return entries

    def _share_snapshot_filtered(self, requester: object, now: float) -> list:
        """KnownPeersRequest reply: Known-state peers heard from within
        MAX_PEER_SHARE_AGE, excluding self and the requester
        (kaboodle.rs:483-501). Not trimmed (quirk Q12)."""
        max_age = self.cfg.max_peer_share_age_ticks
        return [
            (a, r.identity)
            for a, r in self.known.items()
            if r.state == KNOWN
            and a != self.addr
            and a != requester
            and (now - r.since) < max_age
        ]

    def _maybe_sync_known_peers(self, peer: object, their_fp: int, their_n: int) -> None:
        """Record an anti-entropy sync candidate (kaboodle.rs:707-740); D2
        resolves at most one per tick via take_sync_request()."""
        self._sync_candidates.append((peer, their_fp, their_n))

    # --- tick active phase (kaboodle.rs:746-757) ------------------------------

    def active_phase(self, now: float) -> Outbox:
        out = Outbox()
        self._maybe_broadcast_join(now, out)
        self._handle_suspected_peers(now, out)
        self._ping_random_peer(now, out)
        self._handle_manual_ping_requests(out)
        return out

    def _maybe_broadcast_join(self, now: float, out: Outbox) -> None:
        """kaboodle.rs:228-251: first call always broadcasts; afterwards only
        while lonely and >= REBROADCAST_INTERVAL since the last broadcast.
        With ``join_broadcast_enabled=False`` (the gossip boot — no broadcast
        medium) the whole mechanism is disabled."""
        if not self.cfg.join_broadcast_enabled:
            return
        if self.last_broadcast_time is not None:
            lonely = len(self.known) <= 1
            waited = (now - self.last_broadcast_time) >= self.cfg.rebroadcast_interval_ticks
            if not (lonely and waited):
                return
        self.last_broadcast_time = now
        out.broadcast(Join(self.addr, self.identity))

    def _handle_suspected_peers(self, now: float, out: Outbox) -> None:
        """kaboodle.rs:558-653. D1: escalate at most one WaitingForPing."""
        timeout = self.cfg.ping_timeout_ticks
        removed: list = []
        # Stable iteration order for deterministic parity with the kernel.
        items = sorted(self.known.items(), key=lambda kv: addr_key(kv[0]))

        waiting_timed_out = [
            (a, r) for a, r in items if r.state == WAITING_FOR_PING and (now - r.since) >= timeout
        ]
        # D1: oldest first (ties toward lower addr via the stable sort above).
        waiting_timed_out.sort(key=lambda kv: kv[1].since)
        for peer, _rec in waiting_timed_out[:1]:
            candidates = [
                a for a, r in items if a != self.addr and r.state == KNOWN
            ]
            if not candidates:
                # No one to ask — give up immediately (kaboodle.rs:599-605).
                removed.append(peer)
                continue
            k = self.cfg.num_indirect_ping_peers
            if self.cfg.deterministic:
                proxies = candidates[: k]
            else:
                proxies = self._rng.sample(candidates, min(k, len(candidates)))
            self.known[peer] = dataclasses.replace(
                self.known[peer], state=WAITING_FOR_INDIRECT_PING, since=now
            )  # kaboodle.rs:631-639
            self.last_escalated += 1
            for proxy in proxies:
                out.send(proxy, PingRequest(peer))

        for peer, rec in items:
            if rec.state == WAITING_FOR_INDIRECT_PING and (now - rec.since) >= timeout:
                removed.append(peer)  # kaboodle.rs:617-627

        self.last_removed += len(removed)
        for peer in removed:
            self._remove(peer)
            out.broadcast(Failed(peer))  # kaboodle.rs:641-652

    def _ping_random_peer(self, now: float, out: Outbox) -> None:
        """kaboodle.rs:655-703: uniform choice among the oldest
        NUM_CANDIDATE_TARGET_PEERS Known peers."""
        candidates = [
            (r.since, addr_key(a), a)
            for a, r in self.known.items()
            if a != self.addr and r.state == KNOWN
        ]
        if not candidates:
            return
        candidates.sort(key=lambda t: (t[0], t[1]))
        pool = candidates[: self.cfg.num_candidate_target_peers]
        if self.cfg.deterministic:
            target = pool[0][2]
        else:
            target = self._rng.choice(pool)[2]
        self.known[target] = dataclasses.replace(
            self.known[target], state=WAITING_FOR_PING, since=now
        )
        out.send(target, Ping())

    def _handle_manual_ping_requests(self, out: Outbox) -> None:
        """kaboodle.rs:550-556: drain the manual ping queue; no state change."""
        for target in self.pending_manual_pings:
            out.send(target, Ping())
        self.pending_manual_pings = []

    # --- inbound unicast (kaboodle.rs:394-548) --------------------------------

    def on_unicast(self, sender: object, sender_identity: object, msg: object, now: float) -> Outbox:
        """Real-transport entry point: mark the sender (Q1) then dispatch."""
        self.mark_sender(sender, sender_identity, now)
        return self.dispatch_unicast(sender, msg, now)

    def mark_sender(self, sender: object, sender_identity: object, now: float) -> None:
        """Q1 half of message handling (kaboodle.rs:408-415). The lockstep
        harness applies all of a round's marks before any dispatch, so that
        Ack payloads (fingerprint/num_peers) see the round's full insert set —
        the kernel computes a round's effects as one tensor op."""
        self._mark_known(sender, sender_identity, now)

    def dispatch_unicast(self, sender: object, msg: object, now: float) -> Outbox:
        out = Outbox()
        if isinstance(msg, Ack):
            # D7: only first-generation Acks pop-and-forward the curious list.
            observers = [] if msg.forwarded else self.curious.pop(msg.peer, [])
            for observer in observers:  # forward to curious peers (kaboodle.rs:423-443)
                out.send(observer, Ack(msg.peer, msg.mesh_fingerprint, msg.num_peers, forwarded=True))
            if not self.cfg.faithful_indirect_ack and msg.peer in self.known:
                # Intended-SWIM mode: a forwarded ack clears the suspect too.
                rec = self.known[msg.peer]
                if rec.state in (WAITING_FOR_PING, WAITING_FOR_INDIRECT_PING):
                    self.known[msg.peer] = dataclasses.replace(rec, state=KNOWN, since=now)
            self._maybe_sync_known_peers(msg.peer, msg.mesh_fingerprint, msg.num_peers)

        elif isinstance(msg, KnownPeersMsg):
            # Q6: insert unknown peers back-dated (kaboodle.rs:448-472).
            # With backdate_gossip_inserts=False (epidemic boot extension),
            # learned peers get fresh stamps and re-share immediately.
            stamp = now - (
                self.cfg.max_peer_share_age_ticks
                if self.cfg.backdate_gossip_inserts
                else 0
            )
            for addr, identity in msg.peers:
                if addr not in self.known:
                    self.known[addr] = PeerRecord(identity, KNOWN, stamp)

        elif isinstance(msg, KnownPeersRequest):
            share = self._share_snapshot_filtered(sender, now)
            out.send(sender, KnownPeersMsg(tuple(share)))  # kaboodle.rs:503-508
            self._maybe_sync_known_peers(sender, msg.mesh_fingerprint, msg.num_peers)

        elif isinstance(msg, Ping):
            out.send(
                sender,
                Ack(self.addr, self.fingerprint(), self.num_peers()),
            )  # kaboodle.rs:513-532

        elif isinstance(msg, PingRequest):
            observers = self.curious.setdefault(msg.target, [])
            if sender not in observers:
                observers.append(sender)  # kaboodle.rs:533-540
            out.send(msg.target, Ping())  # kaboodle.rs:542-544

        return out

    # --- inbound broadcast (kaboodle.rs:256-311) ------------------------------

    def on_broadcast(self, origin: object, msg: object, now: float) -> Outbox:
        """``origin`` is the broadcast datagram's source address. For real
        sockets this is never a member address (quirk Q3)."""
        out = Outbox()
        if isinstance(msg, Failed):
            if msg.peer == self.addr:
                return out  # kaboodle.rs:269-273
            if self.cfg.faithful_failed_broadcast:
                # Source-membership check that never passes with real sockets
                # (kaboodle.rs:276-281): inert unless origin happens to be known.
                if origin in self.known and origin != msg.peer:
                    self._remove(msg.peer)
            else:
                self._remove(msg.peer)
        elif isinstance(msg, Join):
            if msg.addr == self.addr:
                return out  # kaboodle.rs:285-287
            prev = self.known.get(msg.addr)
            is_new = prev is None
            latency = prev.latency if prev else None  # kaboodle.rs:291-297
            self.known[msg.addr] = PeerRecord(msg.identity, KNOWN, now, latency)
            if self._round_base is not None:
                # D5 bookkeeping only under the lockstep harness (which resets
                # both fields every round); a standalone engine must not
                # accumulate join addresses forever.
                self._round_joins.append((msg.addr, msg.identity))
            if is_new and self._should_respond_to_broadcast():
                share = self._share_snapshot_join()
                if share:
                    out.send(msg.addr, KnownPeersMsg(tuple(share)))  # kaboodle.rs:356-392
        elif isinstance(msg, Probe):
            if self._should_respond_to_broadcast():
                out.send(msg.addr, ProbeResponse(self.identity))  # kaboodle.rs:313-331
        return out

    # --- end-of-tick anti-entropy (D2) ---------------------------------------

    def take_sync_request(self) -> Optional[tuple[object, KnownPeersRequest]]:
        """Resolve this tick's sync candidates into at most one
        KnownPeersRequest (kaboodle.rs:707-740): fire for the first candidate
        whose fingerprint differs from ours and whose num_peers >= ours."""
        candidates, self._sync_candidates = self._sync_candidates, []
        our_fp = self.fingerprint()
        our_n = self.num_peers()
        for peer, their_fp, their_n in candidates:
            if their_fp == our_fp:
                continue
            if our_n > their_n:
                continue  # they'll ask us instead (kaboodle.rs:722-726)
            return peer, KnownPeersRequest(our_fp, our_n)
        return None
