"""Host-side fingerprint functions matching the two on-device variants.

- :func:`mix_fingerprint` — the production commutative hash; bit-exact with
  ``kaboodle_tpu.ops.hashing.membership_fingerprint`` (tested in
  tests/test_oracle.py).
- :func:`crc_fingerprint` — the reference's exact CRC-32 semantics
  (kaboodle.rs:71-83): peers sorted, CRC over address-string bytes + identity
  bytes. Used by the real-network engine for wire interop with actual kaboodle
  instances; ``addr_bytes`` controls the address encoding (string form for real
  sockets, fixed-width records for the simulator's index space).
"""

from __future__ import annotations

import zlib
from typing import Callable, Mapping

_M1 = 0x7FEB352D
_M2 = 0x846CA68B
_MASK = 0xFFFFFFFF


def mix32_py(x: int) -> int:
    """splitmix32 finalizer; bit-exact with ops.hashing.mix32."""
    x &= _MASK
    x ^= x >> 16
    x = (x * _M1) & _MASK
    x ^= x >> 15
    x = (x * _M2) & _MASK
    x ^= x >> 16
    return x


def peer_record_hash_py(peer_id: int, identity: int) -> int:
    """Bit-exact with ops.hashing.peer_record_hash."""
    return mix32_py(mix32_py((peer_id ^ 0x9E3779B9) & _MASK) ^ (identity & _MASK))


def mix_fingerprint(members: Mapping[int, int]) -> int:
    """Commutative fingerprint over {peer_id: identity_word} (mod 2^32)."""
    total = 0
    for pid, idn in members.items():
        total = (total + peer_record_hash_py(pid, idn)) & _MASK
    return total


def default_addr_bytes(addr) -> bytes:
    """Address encoding for CRC fingerprints.

    Integers (simulated peers) use the sim-canonical 4-byte big-endian record
    prefix (see ops.crc32.record_bytes); anything else is stringified like the
    reference's ``peer.to_string()`` (kaboodle.rs:78).
    """
    if isinstance(addr, int):
        return addr.to_bytes(4, "big")
    return str(addr).encode()


def socket_addr_sort_key(addr: object):
    """Rust ``SocketAddr`` ``Ord`` order (the sort of kaboodle.rs:72-73):
    V4 variants before V6, then IP numerically (big-endian octets), then port.
    Integers (simulated peers) sort first, numerically; unparseable addresses
    fall back to their string form, after all real sockets."""
    if isinstance(addr, int):
        return (0, 0, b"", addr)
    s = str(addr)
    try:
        import ipaddress

        if s.startswith("["):  # "[v6]:port"
            host, port = s[1:].rsplit("]:", 1)
        else:
            host, port = s.rsplit(":", 1)
        ip = ipaddress.ip_address(host)
        return (1, ip.version, ip.packed, int(port))
    except ValueError:
        return (2, 0, s.encode(), 0)


def crc_fingerprint(
    members: Mapping[object, bytes],
    addr_bytes: Callable[[object], bytes] = default_addr_bytes,
) -> int:
    """Reference-exact fingerprint: CRC-32 over sorted (addr, identity) records.

    ``members`` maps address -> identity bytes. Sorting matches Rust's
    ``SocketAddr`` ordering (the reference sorts SocketAddrs, kaboodle.rs:72-73)
    — numeric IP order, not lexicographic strings.
    """
    crc = 0
    for addr in sorted(members.keys(), key=socket_addr_sort_key):
        crc = zlib.crc32(addr_bytes(addr), crc)
        ident = members[addr]
        if isinstance(ident, int):
            ident = ident.to_bytes(4, "big")
        crc = zlib.crc32(ident, crc)
    return crc
