"""Deterministic lockstep mesh: N oracle engines + in-memory transport.

This harness defines the framework's discrete-time delivery model — the
executable contract that the JAX tick kernel (kaboodle_tpu.sim) reproduces
tensor-wise. One tick is the reference protocol period (kaboodle.rs:746-779):
the active half runs first, then request/reply chains resolve *within* the
tick in a fixed number of delivery rounds, mirroring the reference's reactive
half where a ping and its ack complete inside one 1-second period
(kaboodle.rs:762-778). See SEMANTICS.md for the full round model.

Round structure per tick t:
  A  active phase: join/failed broadcasts + Pings + PingRequests queued
  B  broadcast delivery (Join/Failed/Probe) -> join-response KnownPeers queued
  C  delivery: Ping -> Ack queued; PingRequest -> proxy Ping queued
  D  delivery: Ack (direct), proxy Ping -> Ack queued, join-response KnownPeers
  E  delivery: proxy's Ack from target -> forwarded Acks queued
  F  delivery: forwarded Acks
  G  anti-entropy: each peer resolves <= 1 KnownPeersRequest (deviation D2);
     request + filtered reply resolve within the round
Within each round: all sender-marks (Q1) are applied first, then mutating
messages (Ack, KnownPeers), then reply-generating ones (KnownPeersRequest,
Ping, PingRequest), each in sender-address order — the same serialization the
vectorized kernel implements.

Delivery faults (drop masks, partitions, dead peers) gate every delivery via
``delivery_ok``; a dead peer neither acts, receives, nor replies.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.oracle.engine import (
    Ack,
    Join,
    KnownPeersMsg,
    KnownPeersRequest,
    PeerEngine,
    Ping,
    PingRequest,
    ProbeResponse,
    addr_key,
)

# Dispatch ordering within a round: mutators before repliers (see module doc).
_TYPE_ORDER = {
    Ack: 0,
    KnownPeersMsg: 1,
    KnownPeersRequest: 2,
    Ping: 3,
    PingRequest: 4,
    ProbeResponse: 5,
}


class LockstepMesh:
    """N simulated peers with integer addresses 0..N-1 gossiping in lockstep."""

    def __init__(
        self,
        n: int,
        cfg: Optional[SwimConfig] = None,
        identities: Optional[list[int]] = None,
        delivery_ok: Optional[Callable[[int, int, int], bool]] = None,
        seed: int = 0,
        alive: Optional[list[bool]] = None,
        ring_contacts: int = 0,
    ) -> None:
        self.n = n
        self.cfg = cfg or SwimConfig()
        self.identities = list(identities) if identities else [i + 1 for i in range(n)]
        self.tick_count = 0
        self._seed = seed
        # delivery_ok(sender, receiver, tick) gates unicasts and broadcasts.
        self.delivery_ok = delivery_ok or (lambda s, r, t: True)
        self.alive = list(alive) if alive else [True] * n
        self.engines: list[PeerEngine] = [
            PeerEngine(i, self.identities[i], self.cfg, now=0, seed=seed * 100003 + i)
            for i in range(n)
        ]
        # Gossip-boot seed contacts (init_state(ring_contacts=...) twin): peer
        # i additionally knows peers (i+1..i+c) mod n as Known(0).
        if ring_contacts:
            if ring_contacts >= n:
                raise ValueError("ring_contacts must be < n")
            from kaboodle_tpu.oracle.engine import PeerRecord
            from kaboodle_tpu.spec import KNOWN

            for i, eng in enumerate(self.engines):
                for d in range(1, ring_contacts + 1):
                    j = (i + d) % n
                    eng.known[j] = PeerRecord(self.identities[j], KNOWN, 0)
        # Message log of the current tick, for tests/metrics.
        self.last_tick_messages = 0
        # Telemetry tallies of the current tick: the oracle side of the
        # ProtocolCounters parity contract (kaboodle_tpu/telemetry/counters.py
        # for the definitions; tests/test_fuzz_parity.py pins kernel == this).
        self.last_tick_counters: dict[str, int] = {}

    # --- churn ---------------------------------------------------------------

    def kill(self, i: int) -> None:
        """Silent leave (quirk Q8: stop() does not announce departure)."""
        self.alive[i] = False

    def revive(self, i: int, identity: Optional[int] = None) -> None:
        """Rejoin with fresh state: the peer knows only itself and will
        broadcast Join on its next active phase (kaboodle.rs:228-251)."""
        if identity is not None:
            self.identities[i] = identity
        self.alive[i] = True
        # Fresh RNG stream derived from the mesh seed, disjoint from the
        # constructor's streams (seed*100003 + i, i < n) and from earlier
        # revivals of the same peer.
        self.engines[i] = PeerEngine(
            i,
            self.identities[i],
            self.cfg,
            now=self.tick_count,
            seed=self._seed * 100003 + (self.tick_count + 1) * self.n + i,
        )

    # --- delivery plumbing ---------------------------------------------------

    def _deliver_round(self, unicasts: list[tuple[int, int, object]], now: int) -> list:
        """Deliver one round of (sender, dest, msg); returns next round's sends."""
        delivered: list[tuple[int, int, object]] = []
        for sender, dest, msg in unicasts:
            if not (0 <= dest < self.n) or not self.alive[dest] or not self.alive[sender]:
                continue
            if dest == sender:
                # D8: the in-memory transport does not loop back self-sends
                # (reachable only via a manual self-ping; real UDP would).
                continue
            if not self.delivery_ok(sender, dest, now):
                continue
            delivered.append((sender, dest, msg))
        self.last_tick_messages += len(delivered)

        # Pass 1: marks (Q1), any inbound datagram resurrects its sender.
        for sender, dest, _msg in delivered:
            self.engines[dest].mark_sender(sender, self.engines[sender].identity, now)

        # Pass 2: dispatch in (type order, sender order) per receiver.
        delivered.sort(key=lambda x: (x[1], _TYPE_ORDER[type(x[2])], addr_key(x[0])))
        next_round: list[tuple[int, int, object]] = []
        for sender, dest, msg in delivered:
            out = self.engines[dest].dispatch_unicast(sender, msg, now)
            next_round.extend((dest, d, m) for d, m in out.unicasts)
            assert not out.broadcasts
        return next_round

    def _deliver_broadcasts(self, broadcasts: list[tuple[int, object]], now: int) -> list:
        """Deliver broadcasts to every alive peer (including the origin, whose
        engine skips its own Join/Failed, kaboodle.rs:269-273, 285-287)."""
        next_round: list[tuple[int, int, object]] = []
        for origin, msg in broadcasts:
            if not self.alive[origin]:
                continue
            for r in range(self.n):
                if not self.alive[r] or not self.delivery_ok(origin, r, now):
                    continue
                # Real broadcasts arrive from the broadcast socket's address,
                # which is never a member (quirk Q3): origin=None models that.
                out = self.engines[r].on_broadcast(None, msg, now)
                next_round.extend((r, d, m) for d, m in out.unicasts)
                assert not out.broadcasts
        return next_round

    # --- the tick ------------------------------------------------------------

    def tick(self) -> None:
        now = self.tick_count
        self.last_tick_messages = 0
        # Pre-tick snapshot for the pre/post telemetry counters. Callers
        # apply kill/revive *before* tick(), so this is the post-churn state
        # — the same S0 the kernel's counters snapshot.
        pre_state = self.state_matrix()
        gossip_records = 0

        # A: active phase.
        for eng in self.engines:
            eng.last_escalated = 0
            eng.last_removed = 0
        broadcasts: list[tuple[int, object]] = []
        round1: list[tuple[int, int, object]] = []
        for i, eng in enumerate(self.engines):
            if not self.alive[i]:
                continue
            out = eng.active_phase(now)
            broadcasts.extend((i, b) for b in out.broadcasts)
            round1.extend((i, d, m) for d, m in out.unicasts)

        def n_sent(msgs, typ):
            return sum(1 for (_, _, m) in msgs if isinstance(m, typ))

        c = {
            "suspicions_raised": sum(e.last_escalated for e in self.engines),
            "deaths_declared": sum(e.last_removed for e in self.engines),
            # "Sent" counts datagrams entering the transport, post the D8
            # validity filter (self-sends / out-of-range never enter it) —
            # matching the kernel's ``man_tgt`` gate; random and proxy pings
            # are valid by construction.
            "pings_sent": sum(
                1
                for (s, d, m) in round1
                if isinstance(m, Ping) and 0 <= d < self.n and d != s
            ),
            "ping_reqs_sent": n_sent(round1, PingRequest),
        }

        # B: broadcast delivery; join responses land with round 2. Each
        # engine's D5 snapshot (start-of-round membership + joins accepted so
        # far) is what the aligned share-cap trims against.
        for eng in self.engines:
            eng._round_base = {a: r.identity for a, r in eng.known.items()}
            eng._round_joins = []
        join_responses = self._deliver_broadcasts(broadcasts, now)
        # Join dissemination: deliveries of Join datagrams (origin != self;
        # the same gates _deliver_broadcasts applies).
        c["joins_disseminated"] = sum(
            sum(
                1
                for r in range(self.n)
                if r != origin
                and self.alive[r]
                and self.delivery_ok(origin, r, now)
            )
            for origin, msg in broadcasts
            if isinstance(msg, Join) and self.alive[origin]
        )
        gossip_records += sum(
            len(m.peers) for (_, _, m) in join_responses
            if isinstance(m, KnownPeersMsg)
        )

        # C..F: four unicast delivery rounds resolve the ping / ping-req /
        # ack / forwarded-ack chains within the tick.
        round2 = self._deliver_round(round1, now)
        round3 = self._deliver_round(round2 + join_responses, now)
        round4 = self._deliver_round(round3, now)
        leftovers = self._deliver_round(round4, now)
        # The chain is at most 4 deep (ping-req -> proxy ping -> ack ->
        # forwarded ack); anything further would break kernel parity.
        assert not leftovers, f"unexpected round-5 messages: {leftovers}"
        # Proxy pings dispatch on a delivered PingRequest (round 2); acks on
        # any delivered ping, plus call-3 coincidence pops and call-4 relays.
        c["pings_sent"] += n_sent(round2, Ping)
        c["acks_sent"] = (
            n_sent(round2, Ack) + n_sent(round3, Ack) + n_sent(round4, Ack)
        )

        # G: anti-entropy resolution (deviation D2: <= 1 request per peer).
        requests: list[tuple[int, int, KnownPeersRequest]] = []
        for i, eng in enumerate(self.engines):
            if not self.alive[i]:
                eng._sync_candidates = []
                continue
            req = eng.take_sync_request()
            if req is not None:
                partner, msg = req
                requests.append((i, partner, msg))
        replies = self._deliver_round(requests, now)
        final = self._deliver_round(replies, now)
        assert all(isinstance(m, KnownPeersMsg) for (_, _, m) in replies)
        assert not final
        gossip_records += sum(len(m.peers) for (_, _, m) in replies)

        # D3: curious-peer relay entries do not outlive the tick (the kernel
        # resolves the whole indirect-ping chain in-tick and stores nothing).
        for eng in self.engines:
            eng.curious.clear()

        # Pre/post counters + the modeled gossip byte total (RECORD_BYTES
        # per (addr, identity) record, modular uint32 like the kernel's).
        post_state = self.state_matrix()
        from kaboodle_tpu.spec import KNOWN, WAITING_FOR_INDIRECT_PING, WAITING_FOR_PING
        from kaboodle_tpu.telemetry.counters import RECORD_BYTES

        c["suspicions_refuted"] = int(
            (
                (pre_state == WAITING_FOR_INDIRECT_PING) & (post_state == KNOWN)
            ).sum()
        )
        alive_rows = np.asarray(self.alive, dtype=bool)[:, None]
        c["armed_timers"] = int(
            (
                alive_rows
                & (
                    (post_state == WAITING_FOR_PING)
                    | (post_state == WAITING_FOR_INDIRECT_PING)
                )
            ).sum()
        )
        c["gossip_bytes"] = (RECORD_BYTES * gossip_records) % (1 << 32)
        self.last_tick_counters = c

        self.tick_count += 1

    def run(self, ticks: int) -> None:
        for _ in range(ticks):
            self.tick()

    # --- observers -----------------------------------------------------------

    def fingerprints(self) -> list[int]:
        return [e.fingerprint() for e in self.engines]

    def converged(self) -> bool:
        """All alive peers agree on the mesh fingerprint (the reference's
        convergence signal, README.md:19-29)."""
        fps = {e.fingerprint() for e, a in zip(self.engines, self.alive) if a}
        return len(fps) <= 1

    def state_matrix(self) -> np.ndarray:
        """int8 [N, N] of spec state codes: row i = peer i's view."""
        m = np.zeros((self.n, self.n), dtype=np.int8)
        for i, eng in enumerate(self.engines):
            for a, rec in eng.known.items():
                if isinstance(a, int) and 0 <= a < self.n:
                    m[i, a] = rec.state
        return m

    def timer_matrix(self) -> np.ndarray:
        """int32 [N, N] of state timestamps (ticks); 0 where not a member."""
        m = np.zeros((self.n, self.n), dtype=np.int32)
        for i, eng in enumerate(self.engines):
            for a, rec in eng.known.items():
                if isinstance(a, int) and 0 <= a < self.n:
                    m[i, a] = int(rec.since)
        return m
