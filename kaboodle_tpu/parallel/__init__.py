"""Device-mesh sharding for the simulator (the framework's scale-out layer)."""

from kaboodle_tpu.parallel.mesh import (
    PEER_AXIS,
    inputs_specs,
    make_mesh,
    make_multihost_mesh,
    make_sharded_tick,
    run_until_converged_sharded,
    sharded_convergence_check,
    shard_inputs,
    shard_state,
    simulate_sharded,
    state_specs,
)

__all__ = [
    "PEER_AXIS",
    "inputs_specs",
    "make_mesh",
    "make_multihost_mesh",
    "make_sharded_tick",
    "run_until_converged_sharded",
    "sharded_convergence_check",
    "shard_inputs",
    "shard_state",
    "simulate_sharded",
    "state_specs",
]
