"""Device-mesh sharding for the simulator (the framework's scale-out layer)."""

from kaboodle_tpu.parallel.mesh import (
    PEER_AXIS,
    constrain_state,
    inputs_specs,
    make_mesh,
    make_multihost_mesh,
    make_sharded_tick,
    row_matrix_sharding,
    run_until_converged_sharded,
    sharded_convergence_check,
    shard_inputs,
    shard_state,
    simulate_sharded,
    state_specs,
)

__all__ = [
    "PEER_AXIS",
    "constrain_state",
    "inputs_specs",
    "make_mesh",
    "make_multihost_mesh",
    "make_sharded_tick",
    "row_matrix_sharding",
    "run_until_converged_sharded",
    "sharded_convergence_check",
    "shard_inputs",
    "shard_state",
    "simulate_sharded",
    "state_specs",
]
