"""Sharding layer: the N-peer axis distributed across a TPU device mesh.

The reference scales only by adding OS processes — one per peer, four panes in
the zellij demo (justfile:10-15, 2x2-layout.kdl). The simulator's scale axis is
the same N, but as the leading axis of the ``[N, N]`` state tensors; this
module shards that axis across chips (SURVEY.md §5 "long-context" slot: peer
sharding is this project's context parallelism).

Design: **GSPMD, not hand-rolled collectives.** The tick kernel
(kaboodle_tpu.sim.kernel) is written as global-view array ops; here we

- place every row-indexed tensor with ``NamedSharding(mesh, P('peers', ...))``,
- re-pin the carry's sharding each tick with ``with_sharding_constraint`` so
  the layout is stable under ``lax.scan``,

and let XLA's SPMD partitioner insert the ICI collectives: the join-gossip
boolean matmuls become all-gathers + local matmuls, the row broadcasts of
``rec_hash``/fingerprints become all-gathers, cross-shard scatter-marks become
all-to-alls, and the convergence min/max reduction becomes an all-reduce —
exactly the mapping SURVEY.md §2.3 calls for, without a line of manual
``psum``. Scaling beyond one slice rides the same specs over DCN.

A mesh here is ``Mesh(devices, ('peers',))``; the model-parallel axis is
deliberately absent (there is no hidden dimension to shard — membership rows
are the only big axis).
"""

from __future__ import annotations

import functools

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.sim.runner import converge_loop
from kaboodle_tpu.sim.state import MeshState, TickInputs

PEER_AXIS = "peers"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D device mesh over the peer axis (all local devices by default)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(f"asked for {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (PEER_AXIS,))


def make_multihost_mesh(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> Mesh:
    """Peer-axis mesh over every device in a multi-host (multi-slice) job.

    Scaling beyond one slice is the same program over a bigger mesh: the peer
    axis spans all global devices, device order keeps each host's devices
    contiguous (so the heavy row-wise traffic stays on ICI within a slice and
    only the collectives' inter-slice hops ride DCN — the SURVEY.md §2.3
    mapping). Call once per process; under standard TPU pod launchers
    (GKE/xmanager-style env vars) ``jax.distributed.initialize()`` needs no
    arguments and the explicit parameters are for bring-your-own-cluster
    setups.

    Single-process runs (tests, one host) skip distributed init entirely and
    return the same mesh as :func:`make_mesh`.
    """
    if coordinator_address or (num_processes or 0) > 1:
        import os

        platforms = os.environ.get("JAX_PLATFORMS", "").lower()
        cpu_run = "cpu" in [p.strip() for p in platforms.split(",") if p]
        if cpu_run:
            # CPU clusters (the multi-host test rig, tests/test_multihost.py,
            # or any CPU-only multi-machine run) need an explicit
            # cross-process collectives implementation — without one the
            # first collective hangs. Nothing backend-touching may run before
            # initialize(), so detection is env-only, and positive: only a
            # run that *explicitly* pins JAX_PLATFORMS=cpu gets gloo
            # (bring-your-own CPU clusters must set it — multihost_worker.py
            # does). Accelerator pods bring their own collectives (ICI/DCN)
            # and never match; the flag would be a no-op for them anyway
            # (it only affects the CPU backend), but a positive gate beats
            # relying on that.
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except AttributeError:  # renamed/absent in other jax versions
                pass
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    else:
        # Zero-argument path: under pod launchers initialize() picks the
        # cluster up from the environment; on a plain single host (or if the
        # backend/distributed runtime is already up) it raises and we proceed
        # with whatever devices exist. NB: nothing backend-touching (e.g.
        # jax.process_count()) may run before this call — initializing the
        # backend first would make distributed init impossible.
        try:
            jax.distributed.initialize()
        except (RuntimeError, ValueError):
            pass
    # jax.devices() is globally consistent and host-contiguous across
    # processes — exactly the order we want on the peer axis.
    return Mesh(np.asarray(jax.devices()), (PEER_AXIS,))


def state_specs(state: MeshState | None = None) -> MeshState:
    """PartitionSpecs for MeshState: row axis sharded, control scalars replicated.

    The optional ``[N, N]`` fields (``latency``, ``id_view``) get specs only
    when present in ``state`` — a ``None`` leaf is an *empty subtree* in a
    pytree, so the spec tree's structure must mirror the state's exactly or
    every tree-mapped placement/constraint raises. With no ``state`` given,
    both optional fields are assumed present (the default ``init_state``).

    Single source of truth for MeshState placement: the fleet layer
    (kaboodle_tpu/fleet/sharding.py) derives its stacked-``[E]`` specs by
    transforming these (ensemble axis prepended), so a new MeshState field
    added here is automatically placed correctly fleet-wide."""
    row2 = P(PEER_AXIS, None)
    row1 = P(PEER_AXIS)
    rep = P()
    has_latency = state.latency is not None if state is not None else True
    has_id_view = state.id_view is not None if state is not None else True
    return MeshState(
        state=row2,
        timer=row2,
        alive=row1,
        identity=row1,
        never_broadcast=row1,
        last_broadcast=row1,
        kpr_partner=row1,
        kpr_fp=row1,
        kpr_n=row1,
        tick=rep,
        key=rep,
        latency=row2 if has_latency else None,
        id_view=row2 if has_id_view else None,
    )


def inputs_specs(stacked: bool = False, with_drop_ok: bool = False) -> TickInputs:
    """PartitionSpecs for TickInputs; ``stacked`` adds the leading scan [T] axis."""
    lead = (None,) if stacked else ()
    row1 = P(*lead, PEER_AXIS)
    row2 = P(*lead, PEER_AXIS, None)
    rep = P(*lead) if stacked else P()
    return TickInputs(
        kill=row1,
        revive=row1,
        partition=row1,
        drop_rate=rep,
        manual_target=row1,
        drop_ok=row2 if with_drop_ok else None,
    )


def _named(mesh: Mesh, specs):
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs, is_leaf=lambda x: isinstance(x, P)
    )


def _check_divisible(n: int, mesh: Mesh) -> None:
    if n % mesh.size != 0:
        raise ValueError(f"N={n} not divisible by mesh size {mesh.size}")


def shard_state(state: MeshState, mesh: Mesh) -> MeshState:
    """Place a MeshState on the mesh (row axis split across ``peers``)."""
    _check_divisible(state.state.shape[0], mesh)
    return jax.device_put(state, _named(mesh, state_specs(state)))


def shard_inputs(inputs: TickInputs, mesh: Mesh, stacked: bool = False) -> TickInputs:
    """Place TickInputs on the mesh; pass ``stacked=True`` for scan-stacked [T, ...]."""
    _check_divisible(inputs.kill.shape[-1], mesh)
    specs = inputs_specs(stacked=stacked, with_drop_ok=inputs.drop_ok is not None)
    return jax.device_put(inputs, _named(mesh, specs))


def constrain_state(st: MeshState, mesh: Mesh) -> MeshState:
    """Pin a (traced) MeshState onto the mesh layout via sharding constraints.

    Specs are derived from the state itself, so the optional fields'
    presence — static at trace time — always matches the tree structure.
    Shared by the per-tick carry constraint below and the warp runner's
    sharded leap (kaboodle_tpu/warp/runner.py), so both programs keep the
    same GSPMD placement."""
    shardings = _named(mesh, state_specs(st))
    return jax.tree.map(jax.lax.with_sharding_constraint, st, shardings)


def row_matrix_sharding(mesh: Mesh) -> NamedSharding:
    """The ``[N, N]`` row-sharded placement (``P('peers', None)``) as a
    NamedSharding — the constraint the warp leap applies to its score/latency
    scan carries so the leap partitions like the tick kernel's tensors."""
    return NamedSharding(mesh, P(PEER_AXIS, None))


def make_sharded_tick(
    cfg: SwimConfig, mesh: Mesh, faulty: bool = True, telemetry: bool = False
):
    """Tick fn whose output carry is constrained back onto the mesh layout.

    The constraint after every tick keeps the scan carry's sharding fixed, so
    XLA partitions each tick identically instead of re-deciding layouts.
    ``telemetry=True`` selects the telemetry-plane tick (the outputs are
    per-tick scalars plus an [N] digest vector, which GSPMD reduces/gathers
    like the existing metrics — only the constrained carry needs the pin).
    Derived via :func:`kaboodle_tpu.phasegraph.derive.make_sharded_tick`
    (lazy import: this module provides ``constrain_state`` to it).
    """
    from kaboodle_tpu.phasegraph.derive import make_sharded_tick as _derive

    return _derive(cfg, mesh, faulty=faulty, telemetry=telemetry)


@functools.partial(jax.jit, static_argnames=("cfg", "mesh", "faulty"))
def simulate_sharded(
    state: MeshState,
    inputs: TickInputs,
    cfg: SwimConfig,
    mesh: Mesh,
    faulty: bool = True,
):
    """Sharded twin of :func:`kaboodle_tpu.sim.runner.simulate` (lax.scan)."""
    tick = make_sharded_tick(cfg, mesh, faulty=faulty)
    return jax.lax.scan(tick, state, inputs)


@jax.jit
def sharded_convergence_check(state: MeshState):
    """Fingerprint agreement of a (possibly sharded) mesh WITHOUT a tick.

    Computes each row's fingerprint (the same wraparound uint32 sum of
    per-member record-hash words the tick kernel reduces at the end of
    every tick — kernel.py ``fp_count``/``_finish``) and tests min == max
    over alive rows. Under GSPMD the row reduction stays shard-local and
    the min/max combine across the peer axis — the BASELINE config-4
    "ICI all-reduce fingerprint check" as a standalone O(one state read)
    program. Exists for scales where even a single full tick exceeds the
    host: the N=65,536 emulated-mesh proof asserts the converged-init
    state through this (SCALE_PROOF.md), whose peak footprint is the
    masked-contribution read instead of a whole tick's working set.

    Returns ``(converged, fp_min, fp_max, n_alive)``.
    """
    from kaboodle_tpu.sim.runner import state_agreement

    return state_agreement(state)


@functools.partial(jax.jit, static_argnames=("cfg", "mesh", "max_ticks"))
def run_until_converged_sharded(
    state: MeshState,
    cfg: SwimConfig,
    mesh: Mesh,
    max_ticks: int = 64,
):
    """Sharded twin of :func:`kaboodle_tpu.sim.runner.run_until_converged`.

    The convergence test (fingerprint min == max over alive peers) partitions
    into a per-shard reduction + ICI all-reduce — the BASELINE.json config-4
    "ICI all-reduce fingerprint check"."""
    return converge_loop(state, make_sharded_tick(cfg, mesh, faulty=False), max_ticks)
