"""Phase-graph tick IR: one op graph, five derived engines.

The SWIM tick is declared ONCE as a graph of composable phase ops
(:mod:`~kaboodle_tpu.phasegraph.ops`), each declaring the state fields it
reads and writes, the tick-local values it produces and consumes, and the
traced activity mask that gates its real work. A planner
(:mod:`~kaboodle_tpu.phasegraph.plan`) composes the graph per build:

- ``full`` — the multi-pass program (one pass per cond-gated phase), the
  shape every engine ran before this module existed;
- ``fused`` — the 2-pass steady-tick program: ops whose activity is
  excluded by the dispatch predicate are *pruned* (the predicate terms are
  derived from the pruned ops' own activity declarations), and the
  survivors' masks *fold* into one elementwise where chain — no cond-gated
  identity branches, ~3 HBM sweep-equivalents instead of ~9;
- ``span`` — the warp-leap derivation: ops that are provably fixed points
  inside a quiescent span are pruned, the survivors degenerate to the
  timer-restamp/latency-decay forms the leap kernel batches;
- ``blocked`` — the chunked derivation: the full pass order with every
  [N, N] pass re-expressed over row blocks (O(block·N) transients).

The executable engines are all derived from this one graph
(:mod:`~kaboodle_tpu.phasegraph.derive`): the dense tick (full+fused under
a per-tick dispatch), the standalone fused fast path, the chunked
row-blocked twin, the GSPMD-sharded twin, the vmapped fleet tick, and the
warp leap. ``sim/kernel.py``, ``sim/chunked.py``, ``warp/leap.py`` and
``fleet/core.py`` are thin shims over these derivations — the four
hand-specialized protocol copies they used to hold are deleted.
"""

from kaboodle_tpu.phasegraph.graph import TickGraph, build_graph
from kaboodle_tpu.phasegraph.ops import PhaseOp
from kaboodle_tpu.phasegraph.plan import Pass, TickProgram, plan

__all__ = [
    "PhaseOp",
    "TickGraph",
    "build_graph",
    "Pass",
    "TickProgram",
    "plan",
]
