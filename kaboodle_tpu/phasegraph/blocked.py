"""Row-blocked tick kernel: the phase graph's BLOCKED program — the
transient-bounded derivation of the tick op graph.

This engine executes ``plan(graph, "blocked")``: the full program's pass
order (same ops, same phase semantics as exec.py — ``make_chunked_tick_fn``
pins its op coverage against the planner at build time and exposes the
program on the returned function), with every [N, N] pass re-expressed as
a ``lax.map`` over row blocks. The derivation is a LAYOUT, not new
protocol logic: a phase op lands here exactly once, in the same graph
order the dense engine executes.

The flagship dense program (phasegraph/exec.py) expresses every phase as
whole-``[N, N]`` tensor ops. That is the right shape for XLA:TPU, which fuses the passes into
a few HBM sweeps — but XLA:CPU materializes far more of the intermediates,
and at N = 65,536 a single tick's live temporaries exceed even a 125 GiB
host (SCALE_PROOF.md attempts 1-6 and 8: every full-tick attempt at that N
was OOM-killed; the join-gossip union's O(N^3) matmul operands are the worst
case but even kill-only ticks die on the int32 [N, N] temporaries).

This module re-expresses the SAME tick (kaboodle.rs:746-786; the lockstep
round structure in kernel.py's docstring) as a sequence of passes, each a
``lax.map`` over row blocks of size ``block``:

- every [N, N] read/write touches one ``[block, N]`` slice at a time, so
  peak transients are O(block·N) instead of O(N^2)·live-temps;
- the O(N^3) join-gossip union and the intended-semantics Failed-broadcast
  delivery become two-level blocked contractions (receiver-axis blocks,
  inner reduction over sender blocks) — peak operand footprint O(block·N)
  plus the two join-tick residents noted below;
- cross-row phases (the anti-entropy share gather, the union) read the
  pass-input snapshot, which is exactly the two-pass delivery serialization
  the lockstep oracle defines (``lax.map`` bodies are functional: every
  block of one pass reads the same input state).

Parity contract (tests/test_chunked.py):

- **Bit-exact** with ``make_tick_fn`` whenever the tick consumes only
  per-row draws — all of deterministic mode, and random mode on ticks with
  no escalation, no join reply, and no random drop (the ping-target draw is
  per-row and reproduces exactly).
- **D10 (documented deviation):** the matrix-shaped draws (escalation proxy
  gumbel, join-reply Bernoulli, random drop) are generated per block from
  ``fold_in(key, block_index)`` — same counter-based PRNG family, different
  stream layout, so random-mode trajectories through those branches are
  distributionally (not samplewise) equivalent, the same caveat as D6.
  Tests needing exact faulty parity pass an explicit ``drop_ok``.

Memory (N = 65,536, lean+int16, quiet faulty tick): 12 GiB resident state
+ 12 GiB pass outputs + O(block·N) temporaries ≈ 24-26 GiB — vs the >125 GiB
the whole-tensor kernel demands on XLA:CPU. Join-bearing ticks add two
[N, N] bool residents (``reply_del``, ``gossip``) ≈ +8 GiB, the documented
2-3x boot-tick budget (MEMORY_PLAN.md). Intended-semantics Failed delivery
(non-default) adds the ``rem``/``fail_del`` residents on removal ticks.

Single-address-space by design: the blocked passes slice the row axis
dynamically, which would fight GSPMD's static row sharding — use the
whole-tensor kernel for sharded meshes (it is the right program there) and
this one where a single device/host must bound its transients: the
emulating-host scale proofs, and single-chip TPU runs near the HBM ceiling.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.ops.hashing import fingerprint_agreement, peer_record_hash
from kaboodle_tpu.phasegraph.graph import build_graph
from kaboodle_tpu.phasegraph import rng as pg_rng
from kaboodle_tpu.phasegraph.plan import plan
from kaboodle_tpu.ops.sampling import (
    _stable_k_smallest_iter,
    bernoulli_matrix,
    broadcast_reply_prob,
    choose_among_candidates,
    choose_k_members,
)
from kaboodle_tpu.sim.state import MeshState, TickInputs, TickMetrics
from kaboodle_tpu.spec import KNOWN, WAITING_FOR_INDIRECT_PING, WAITING_FOR_PING
from kaboodle_tpu.telemetry.counters import (
    RECORD_BYTES,
    ProtocolCounters,
    TickTelemetry,
)

_I32MAX = jnp.iinfo(jnp.int32).max


def _slice_rows(a: jax.Array, s0: jax.Array, block: int) -> jax.Array:
    return jax.lax.dynamic_slice_in_dim(a, s0, block, axis=0)


def _slice_cols(a: jax.Array, s0: jax.Array, block: int) -> jax.Array:
    return jax.lax.dynamic_slice_in_dim(a, s0, block, axis=1)


def _int8_matmul(a: jax.Array, b: jax.Array) -> jax.Array:
    """int32 accumulation of a boolean AND-OR contraction (MXU-friendly)."""
    return jax.lax.dot_general(
        a.astype(jnp.int8), b.astype(jnp.int8),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32,
    )


def make_chunked_tick_fn(
    cfg: SwimConfig,
    faulty: bool = True,
    block: int = 1024,
    drop: bool = True,
    boot_union: bool = False,
    telemetry: bool = False,
) -> Callable[[MeshState, TickInputs], tuple[MeshState, TickMetrics]]:
    """Build the row-blocked tick for a given config (see module docstring).

    ``block`` must divide N (checked at trace time). ``drop=False`` compiles
    out the random-drop resident entirely — callers that guarantee
    ``drop_rate == 0`` (the at-scale proofs) use it to avoid materializing
    an [N, N] gate matrix; an explicit ``inp.drop_ok`` still applies.
    With ``drop=True`` the per-block uniform draws are gated on
    ``drop_rate > 0`` in-graph (zero-rate ticks skip the RNG sweep), but the
    [N, N] bool gate resident itself is part of the compiled program
    (~4 GiB at N=65,536) — the advertised O(block·N) transient bound
    requires ``drop=False``.
    The Pallas stage kernels and the fast/slow split do not apply here
    (this path is its own memory-bound formulation); every other config
    flag behaves exactly as in ``make_tick_fn``.

    ``telemetry=True`` is the telemetry-plane build (the chunked half of the
    ``make_tick_fn`` contract): returns ``(state, TickTelemetry)`` with the
    same :class:`ProtocolCounters` definitions, every added reduction either
    O(block·N)-blocked or gated on the phase that feeds it, counters
    bit-exact with the dense telemetry build wherever state parity holds.

    ``boot_union=True`` replaces the O(N^3) join-gossip contraction with
    its closed form for the fresh broadcast-boot avalanche. PRECONDITION
    (caller-owned, tested, NOT checked in-graph beyond the build-time
    ``faulty`` guard below): a FAULT-FREE tick (everyone alive, no
    drop/partition input — ``faulty=True`` is therefore never valid with it
    and raises at build time) whose start-of-round membership maps are
    exactly the singletons {self} — i.e. tick 0 of a broadcast boot from
    ``init_state(ring_contacts=0)``. There,
    ``member_a == eye`` collapses the share term to ``reply_del.T`` and
    the joiner-prefix term to a reply-count comparison:

        gossip[o, j] = reply_del[j, o]
                     | (join_b[j] & (cnt[o] - reply_del[j, o] > 0) & (j <= o))

    with ``cnt[o] = sum_r reply_del[r, o]`` — pure elementwise over the
    reply transpose, no contraction. Bit-exact with the dense union on
    that tick (tests/test_chunked.py pins it); on any other tick shape the
    result is undefined. This is the union form the PERF.md north-star
    projection budgets for the < 2 s avalanche on a v5e-8.
    """

    det = cfg.deterministic
    if boot_union and faulty:
        # The closed form assumes every Join delivers everywhere (no drop /
        # partition / dead peers); a faulty build can never satisfy that, so
        # this combination is silently-wrong-by-construction (ADVICE r5).
        raise ValueError(
            "boot_union=True requires faulty=False: the closed-form join "
            "union assumes fault-free delivery on the boot tick"
        )

    # The blocked program derives from the same graph as the dense engine:
    # same ops, same order, row-blocked layout. A graph op this module does
    # not implement is a loud build error (the op sets below mirror
    # exec.py's _PROLOGUE_OPS/_FULL_TAIL_OPS coverage check).
    graph = build_graph(cfg, faulty=faulty, telemetry=telemetry)
    prog = plan(graph, "blocked")
    _known = {
        "rng_streams", "churn", "delivery_gate", "row_stats", "join_gate",
        "manual_targets", "suspicion", "probe_draw", "join_insert",
        "failed_delivery", "join_replies", "call1", "call2", "calls34",
        "anti_entropy", "counters", "finish",
    }
    _unknown = set(prog.op_names()) - _known
    if _unknown:
        raise NotImplementedError(
            f"graph ops without blocked bodies: {sorted(_unknown)}"
        )

    # Traced from other modules (jit call sites in the scale-proof scripts
    # and tests) — same pragma rationale as kernel.py's tick.
    def tick(st: MeshState, inp: TickInputs) -> tuple[MeshState, TickMetrics]:  # graftlint: traced
        n = st.state.shape[-1]
        if n % block != 0:
            raise ValueError(f"block {block} does not divide N={n}")
        nb = n // block
        starts = jnp.arange(nb, dtype=jnp.int32) * block

        t = st.tick
        idx = jnp.arange(n, dtype=jnp.int32)
        # Counter-keyed draw rows (Warp 3.0, same derivation as exec.py);
        # block-scoped draws fold the block index on top, so a [block, n]
        # draw is keyed (key, tick, stream, block, row, col) — never chained.
        key_proxy, key_ping, key_bern, key_drop = pg_rng.tick_draw_keys(st.key, t)
        key_next = st.key

        S, T = st.state, st.timer
        tT = t.astype(T.dtype)
        TMAX = int(jnp.iinfo(T.dtype).max)
        lat, idv = st.latency, st.id_view
        has_lat = lat is not None
        has_idv = idv is not None
        alive, never_b, last_b = st.alive, st.never_broadcast, st.last_broadcast
        id_row = st.identity[None, :]
        rec_hash = peer_record_hash(idx.astype(jnp.uint32), st.identity)
        INF = jnp.int32(_I32MAX)
        gossip_backdate = (
            cfg.max_peer_share_age_ticks if cfg.backdate_gossip_inserts else 0
        )

        def blk_idx(s0):
            """Global row indices of the block starting at s0: int32 [B]."""
            return s0 + jnp.arange(block, dtype=jnp.int32)

        def blk_eye(s0):
            return blk_idx(s0)[:, None] == idx[None, :]

        def pmap_blocks(body):
            """lax.map of ``body(s0)`` over the row blocks; leaves reshaped
            from [nb, B, ...] back to [N, ...]."""
            out = jax.lax.map(body, starts)
            return jax.tree.map(lambda a: a.reshape((n,) + a.shape[2:]), out)

        def state_rows(SS, TT, ll, vv, s0):
            return (
                _slice_rows(SS, s0, block),
                _slice_rows(TT, s0, block),
                _slice_rows(ll, s0, block) if has_lat else None,
                _slice_rows(vv, s0, block) if has_idv else None,
            )

        # ---- churn (vector part + gated row resets; kernel.py Q8) ----------
        if faulty:
            alive = (alive & ~inp.kill) | inp.revive
            rv = inp.revive

            def _churn_rows(s0):
                Sb, Tb, lb, vb = state_rows(S, T, lat, idv, s0)
                eye_b = blk_eye(s0)
                rv_b = rv[blk_idx(s0)][:, None]
                Sb = jnp.where(rv_b, jnp.where(eye_b, jnp.int8(KNOWN), jnp.int8(0)), Sb)
                Tb = jnp.where(rv_b, jnp.where(eye_b, tT, jnp.zeros((), T.dtype)), Tb)
                out = [Sb, Tb]
                if has_lat:
                    out.append(jnp.where(rv_b, jnp.nan, lb))
                if has_idv:
                    out.append(jnp.where(rv_b, jnp.where(eye_b, id_row, jnp.uint32(0)), vb))
                return tuple(out)

            def _apply_churn(args):
                return pmap_blocks(_churn_rows)

            def _no_churn(args):
                return args

            churned = jax.lax.cond(
                jnp.any(rv), _apply_churn, _no_churn,
                tuple(x for x in (S, T, lat, idv) if x is not None),
            )
            it = iter(churned)
            S, T = next(it), next(it)
            lat = next(it) if has_lat else None
            idv = next(it) if has_idv else None
            never_b = never_b | rv
        else:
            rv = jnp.zeros((n,), dtype=bool)

        # ---- delivery gate -------------------------------------------------
        # Vector factors (aliveness, partition) stay vectors; the random-drop
        # factor becomes ONE bool resident built per block (D10 streams) so
        # edge gathers and block slices read the same tick-consistent gates.
        if faulty:
            part = inp.partition

            def _vec_ok(s, d):
                return (alive[jnp.clip(s, 0)] & alive[jnp.clip(d, 0)]
                        & (part[jnp.clip(s, 0)] == part[jnp.clip(d, 0)]))

            if inp.drop_ok is not None:
                drop_mat = inp.drop_ok
            elif drop:
                def _drop_rows(s0):
                    bi = s0 // block
                    u = jax.random.uniform(
                        jax.random.fold_in(key_drop, bi), (block, n),
                        dtype=jnp.float32)
                    return u >= inp.drop_rate

                # Gate the per-block uniform draws on the (traced) rate, as
                # kernel.py does: a drop=True caller running a zero-rate tick
                # (churn/partition-only schedules) skips the RNG sweep and its
                # float temporaries entirely. The [N, N] bool resident itself
                # is a property of the drop=True build (the cond's all-True
                # branch still produces it) — callers that need the module's
                # advertised O(block*N) bound must pass drop=False.
                drop_mat = jax.lax.cond(
                    inp.drop_rate > 0,
                    lambda: pmap_blocks(_drop_rows),
                    lambda: jnp.ones((n, n), dtype=bool),
                )
            else:
                drop_mat = None

            def ok_edge(s, d):
                e = _vec_ok(s, d)
                if drop_mat is not None:
                    e &= drop_mat[jnp.clip(s, 0), jnp.clip(d, 0)]
                return e

            def ok_rows(s0):
                """ok[s, d] for the s-block: delivery FROM these rows."""
                o = (alive[blk_idx(s0)][:, None] & alive[None, :]
                     & (part[blk_idx(s0)][:, None] == part[None, :]))
                if drop_mat is not None:
                    o &= _slice_rows(drop_mat, s0, block)
                return o

            def okT_rows(s0):
                """ok[s, r] transposed to [B(r), N(s)]: delivery INTO the block."""
                o = (alive[None, :] & alive[blk_idx(s0)][:, None]
                     & (part[None, :] == part[blk_idx(s0)][:, None]))
                if drop_mat is not None:
                    o &= _slice_cols(drop_mat, s0, block).T
                return o
        else:

            def ok_edge(s, d):
                return alive[jnp.clip(s, 0)] & alive[jnp.clip(d, 0)]

            def ok_rows(s0):
                return alive[blk_idx(s0)][:, None] & alive[None, :]

            def okT_rows(s0):
                return alive[None, :] & alive[blk_idx(s0)][:, None]

        # ---- phase-A row stats on the post-churn snapshot ------------------
        # (kernel.py "Phase-A row stats"; same formulas, blocked.)
        S0, T0 = S, T

        def _stats_rows(s0):
            Sb = _slice_rows(S0, s0, block)
            Tb = _slice_rows(T0, s0, block)
            al_b = alive[blk_idx(s0)][:, None]
            age_b = t - Tb
            eye_b = blk_eye(s0)
            row_count = jnp.sum(Sb > 0, axis=-1, dtype=jnp.int32)
            timed_wfp = al_b & (Sb == WAITING_FOR_PING) & (
                age_b >= cfg.ping_timeout_ticks
            )
            has_timed = jnp.any(timed_wfp, axis=-1)
            wfip_any = jnp.any(
                al_b & (Sb == WAITING_FOR_INDIRECT_PING)
                & (age_b >= cfg.ping_timeout_ticks),
                axis=-1,
            )
            # D1 escalation pick: oldest timed-out WaitingForPing, ties to
            # the lower index (kernel.py _rest's jstar).
            tsel = jnp.where(timed_wfp, Tb, TMAX)
            min_t = jnp.min(tsel, axis=-1)
            jstar_mask = timed_wfp & (Tb == min_t[:, None])
            jstar = jnp.min(jnp.where(jstar_mask, idx[None, :], _I32MAX), axis=-1)
            jstar = jnp.where(has_timed, jstar, -1).astype(jnp.int32)
            has_cand = jnp.any((Sb == KNOWN) & ~eye_b, axis=-1)
            return row_count, has_timed, wfip_any, jstar, has_cand

        row_count0, has_timed, wfip_any, jstar, has_cand = pmap_blocks(_stats_rows)
        any_a2 = jnp.any(wfip_any) | jnp.any(has_timed)
        escalate = has_timed & has_cand
        insta_remove = has_timed & ~has_cand
        any_esc = jnp.any(escalate)
        any_rem = jnp.any(wfip_any) | jnp.any(insta_remove)

        # ---- A1 join broadcast throttle (vectors; kaboodle.rs:228-251) -----
        if cfg.join_broadcast_enabled:
            lonely = row_count0 <= 1
            join_b = alive & (
                never_b | (lonely & ((t - last_b) >= cfg.rebroadcast_interval_ticks))
            )
            last_b = jnp.where(join_b, t, last_b)
            never_b = never_b & ~join_b
            any_join = jnp.any(join_b)
        else:
            join_b = jnp.zeros((n,), dtype=bool)
            any_join = jnp.bool_(False)

        man_tgt = jnp.where(
            alive & (inp.manual_target != idx) & (inp.manual_target < n),
            inp.manual_target,
            -1,
        )

        # ---- proxies (escalation-gated; D10 streams in random mode) --------
        kprox = min(cfg.num_indirect_ping_peers, n)

        def _proxy_rows(s0):
            Sb = _slice_rows(S0, s0, block)
            known_cand = (Sb == KNOWN) & ~blk_eye(s0)
            return choose_k_members(
                known_cand, cfg.num_indirect_ping_peers,
                jax.random.fold_in(key_proxy, s0 // block), det,
            )

        proxies, proxies_valid = jax.lax.cond(
            any_esc,
            lambda: pmap_blocks(_proxy_rows),
            lambda: (jnp.zeros((n, kprox), jnp.int32), jnp.zeros((n, kprox), bool)),
        )
        proxies_valid &= escalate[:, None]

        # ---- A2 apply (gated write pass; kaboodle.rs:558-653) --------------
        jstar_is = lambda s0: idx[None, :] == jstar[blk_idx(s0)][:, None]  # noqa: E731

        def _a2_rows(s0):
            Sb, Tb, lb, _ = state_rows(S, T, lat, idv, s0)
            al_b = alive[blk_idx(s0)][:, None]
            age_b = t - Tb
            rem = al_b & (Sb == WAITING_FOR_INDIRECT_PING) & (
                age_b >= cfg.ping_timeout_ticks
            )
            jcell = jstar_is(s0)
            rem = rem | (insta_remove[blk_idx(s0)][:, None] & jcell)
            Sb2 = jnp.where(rem, jnp.int8(0), Sb)
            esc_cell = escalate[blk_idx(s0)][:, None] & jcell
            Sb2 = jnp.where(esc_cell, jnp.int8(WAITING_FOR_INDIRECT_PING), Sb2)
            Tb2 = jnp.where(esc_cell, tT, Tb)
            if has_lat:
                return Sb2, Tb2, jnp.where(rem, jnp.nan, lb)
            return Sb2, Tb2

        def _apply_a2(args):
            return pmap_blocks(_a2_rows)

        a2d = jax.lax.cond(
            any_a2, _apply_a2, lambda a: a,
            tuple(x for x in (S, T, lat) if x is not None),
        )
        it = iter(a2d)
        S, T = next(it), next(it)
        lat = next(it) if has_lat else None

        # ---- intended-semantics Failed delivery (non-default; Q3 off) ------
        # fail_del[r, j] from the same formulas as kernel.py _fail_del, as a
        # blocked contraction over the remover axis. ``rem`` is recomputed
        # per sender block from the PRE-A2 snapshot (S0/T0 still alias it).
        fail_del = None
        if not cfg.faithful_failed_broadcast:

            def _rem_block(i0):
                Sb = _slice_rows(S0, i0, block)
                Tb = _slice_rows(T0, i0, block)
                al_b = alive[blk_idx(i0)][:, None]
                age_b = t - Tb
                r = al_b & (Sb == WAITING_FOR_INDIRECT_PING) & (
                    age_b >= cfg.ping_timeout_ticks
                )
                return r | (insta_remove[blk_idx(i0)][:, None] & jstar_is(i0))

            def _fail_rows(s0):
                # [B, N] fail-delivery block for receiver rows s0..s0+B.
                okT_b = okT_rows(s0)  # [B(r), N(i)] origin i delivers to r

                def _accum(carry, i0):
                    gt, anyv = carry
                    rem_b = _rem_block(i0)  # [Bi, N(j)]
                    rem_gt = rem_b & (blk_idx(i0)[:, None] > idx[None, :])
                    okT_slice = jax.lax.dynamic_slice_in_dim(
                        okT_b, i0, block, axis=1)
                    gt = gt + _int8_matmul(okT_slice, rem_gt)
                    anyv = anyv + _int8_matmul(okT_slice, rem_b)
                    return (gt, anyv), None

                z = jnp.zeros((block, n), jnp.int32)
                (gt, anyv), _ = jax.lax.scan(_accum, (z, z), starts)
                Jm_b = (join_b[None, :] & okT_b & ~blk_eye(s0)
                        if cfg.join_broadcast_enabled
                        else jnp.zeros((block, n), bool))
                return ~blk_eye(s0) & jnp.where(Jm_b, gt > 0, anyv > 0)

            fail_del = jax.lax.cond(
                any_rem,
                lambda: pmap_blocks(_fail_rows),
                lambda: jnp.zeros((n, n), dtype=bool),
            )

        # ---- A3 ping-target candidates (read pass) + the per-row draw ------
        def _a3_rows(s0):
            Sb = _slice_rows(S, s0, block)
            Tb = _slice_rows(T, s0, block)
            elig = alive[blk_idx(s0)][:, None] & (Sb == KNOWN) & ~blk_eye(s0)
            tmax = jnp.asarray(jnp.iinfo(Tb.dtype).max, dtype=Tb.dtype)
            scores = jnp.where(elig, Tb, tmax)
            kk = 1 if det else min(cfg.num_candidate_target_peers, n)
            return _stable_k_smallest_iter(scores, kk, tmax)

        cand_idx, cand_valid = pmap_blocks(_a3_rows)
        # The per-row uniform pick over the concatenated candidate lists is
        # samplewise identical to the unchunked kernel (same key, same rows).
        ping_tgt = choose_among_candidates(cand_idx, cand_valid, key_ping, det)
        has_ping = ping_tgt >= 0

        # ---- delivery vectors (kernel.py calls 1-4 plumbing) ---------------
        ok_ping = has_ping & ok_edge(idx, ping_tgt)
        ok_man = (man_tgt >= 0) & ok_edge(idx, man_tgt)
        del_pr = proxies_valid & ok_edge(idx[:, None], proxies)
        del_ack = ok_ping & ok_edge(ping_tgt, idx)
        del_ack_man = ok_man & ok_edge(man_tgt, idx)
        ok_p2x = ok_edge(proxies, jstar[:, None])
        del_pping = del_pr & ok_p2x
        del_pack = del_pping & ok_edge(jstar[:, None], proxies)
        p_tgt = ping_tgt[jnp.clip(proxies, 0)]
        p_man = man_tgt[jnp.clip(proxies, 0)]
        p_got_direct = del_ack[jnp.clip(proxies, 0)]
        p_got_man = del_ack_man[jnp.clip(proxies, 0)]
        pop_hit = ((p_tgt == jstar[:, None]) & p_got_direct) | (
            (p_man == jstar[:, None]) & p_got_man
        )
        fwd_c = del_pr & pop_hit
        del_fwd_c = fwd_c & ok_edge(proxies, idx[:, None])
        fwd = del_pack & ~pop_hit
        del_fwd = fwd & ok_edge(proxies, idx[:, None])

        # ---- composed write pass: A3 write -> B (Jm, fail) -> wave 1 ->
        # wave 2 -> gossip insert, with exact per-wave (fp, count) deltas
        # (the kernel.py _fast composition, generalized to the full tick;
        # marks write (KNOWN, now, sender identity) in both waves so the
        # last-writer chain is order-free — see kernel.py for the proofs).
        # Two builds under one cond: the join build materializes the
        # reply_del/gossip residents and threads them through; the plain
        # build (every no-join tick) never touches an [N, N] join buffer.
        def _make_compose(with_join, reply_del=None, gossip=None):
            def _compose_rows(s0):
                Sb, Tb, lb, vb = state_rows(S, T, lat, idv, s0)
                gi = blk_idx(s0)
                eye_b = blk_eye(s0)

                tgt_cell = (idx[None, :] == ping_tgt[gi][:, None]) & has_ping[gi][:, None]
                # mark1[d, s]: datagrams LANDING at rows d of this block.
                m1 = ((gi[:, None] == ping_tgt[None, :]) & ok_ping[None, :]) | (
                    (gi[:, None] == man_tgt[None, :]) & ok_man[None, :]
                )
                for kk in range(proxies.shape[-1]):
                    m1 |= (gi[:, None] == proxies[None, :, kk]) & del_pr[None, :, kk]
                # mark2[s, d]: rows s of this block marking their own targets.
                m2 = ((idx[None, :] == ping_tgt[gi][:, None]) & del_ack[gi][:, None]) | (
                    (idx[None, :] == man_tgt[gi][:, None]) & del_ack_man[gi][:, None]
                )
                if with_join:
                    # joiner o (rows of this block) marks each responder r.
                    m2 |= jax.lax.dynamic_slice_in_dim(reply_del, s0, block, axis=1).T

                def _esc_scatter():
                    # suspect jstar[i] (a row of this block) marks proxy p.
                    rows = jnp.clip(jstar - s0, 0, block - 1)
                    inb = (jstar >= s0) & (jstar < s0 + block)
                    val = del_pping & inb[:, None]
                    z = jnp.zeros((block, n), dtype=bool)
                    return z.at[
                        jnp.broadcast_to(rows[:, None], proxies.shape),
                        jnp.clip(proxies, 0),
                    ].max(val)

                m2 |= jax.lax.cond(
                    any_esc, _esc_scatter, lambda: jnp.zeros((block, n), bool))

                if with_join:
                    Jm_b = join_b[None, :] & okT_rows(s0) & ~eye_b
                else:
                    Jm_b = jnp.zeros((block, n), bool)
                fail_b = (_slice_rows(fail_del, s0, block)
                          if fail_del is not None else None)

                # State after A3 + B (the wave-1 read state).
                S_B = jnp.where(Jm_b, jnp.int8(KNOWN),
                                jnp.where(tgt_cell, jnp.int8(WAITING_FOR_PING), Sb))
                T_B = jnp.where(Jm_b | tgt_cell, tT, Tb)
                if fail_b is not None:
                    S_B = jnp.where(fail_b, jnp.int8(0), S_B)
                m_B = S_B > 0
                vb_B = jnp.where(Jm_b, id_row, vb) if has_idv else None

                # fp0/n0 on the post-B state (kernel.py fp0 = fp_count post-B).
                u_row_b = jnp.broadcast_to(idx.astype(jnp.uint32)[None, :], (block, n))
                if has_idv:
                    old = jnp.where(m_B, peer_record_hash(u_row_b, vb_B), jnp.uint32(0))
                    fp0 = jnp.sum(old, axis=-1, dtype=jnp.uint32)
                    dfp1 = jnp.sum(jnp.where(m1, rec_hash[None, :] - old, jnp.uint32(0)),
                                   axis=-1, dtype=jnp.uint32)
                    hash1 = jnp.where(m1, rec_hash[None, :], old)
                    dfp2 = jnp.sum(jnp.where(m2, rec_hash[None, :] - hash1, jnp.uint32(0)),
                                   axis=-1, dtype=jnp.uint32)
                else:
                    fp0 = jnp.sum(jnp.where(m_B, rec_hash[None, :], jnp.uint32(0)),
                                  axis=-1, dtype=jnp.uint32)
                    dfp1 = jnp.sum(
                        jnp.where(m1 & ~m_B, rec_hash[None, :], jnp.uint32(0)),
                        axis=-1, dtype=jnp.uint32)
                    dfp2 = jnp.sum(
                        jnp.where(m2 & ~(m_B | m1), rec_hash[None, :], jnp.uint32(0)),
                        axis=-1, dtype=jnp.uint32)
                n0 = jnp.sum(m_B, axis=-1, dtype=jnp.int32)
                dn1 = jnp.sum(m1 & ~m_B, axis=-1, dtype=jnp.int32)
                dn2 = jnp.sum(m2 & ~(m_B | m1), axis=-1, dtype=jnp.int32)

                # Latency EWMA with wave ordering (kernel.py apply_marks).
                if has_lat:
                    if fail_b is not None:
                        lb = jnp.where(fail_b, jnp.nan, lb)
                    waiting1 = (S_B == WAITING_FOR_PING) | (
                        S_B == WAITING_FOR_INDIRECT_PING)
                    sample1 = (t - T_B).astype(jnp.float32)
                    upd1 = jnp.where(jnp.isnan(lb), sample1,
                                     jnp.float32(0.8) * sample1 + jnp.float32(0.2) * lb)
                    lb1 = jnp.where(m1 & waiting1, upd1, lb)
                    S_1 = jnp.where(m1, jnp.int8(KNOWN), S_B)
                    T_1 = jnp.where(m1, tT, T_B)
                    waiting2 = (S_1 == WAITING_FOR_PING) | (
                        S_1 == WAITING_FOR_INDIRECT_PING)
                    sample2 = (t - T_1).astype(jnp.float32)
                    upd2 = jnp.where(jnp.isnan(lb1), sample2,
                                     jnp.float32(0.8) * sample2 + jnp.float32(0.2) * lb1)
                    lb = jnp.where(m2 & waiting2, upd2, lb1)

                markK = m1 | m2
                S_2 = jnp.where(markK, jnp.int8(KNOWN), S_B)
                T_2 = jnp.where(markK, tT, T_B)
                if has_idv:
                    vb = jnp.where(markK, id_row, vb_B)

                if with_join:
                    g_b = _slice_rows(gossip, s0, block)
                    g_ins = g_b & ~(S_2 > 0)
                    S_2 = jnp.where(g_ins, jnp.int8(KNOWN), S_2)
                    T_2 = jnp.where(g_ins, tT - gossip_backdate, T_2)
                    if has_idv:
                        vb = jnp.where(g_ins, id_row, vb)

                out = [S_2, T_2, fp0, n0, dfp1, dn1, dfp2, dn2]
                if has_lat:
                    out.append(lb)
                if has_idv:
                    out.append(vb)
                return tuple(out)

            return _compose_rows

        def _compose_plain():
            res = pmap_blocks(_make_compose(False))
            out = res + (jnp.int32(0),)  # join-reply message count
            if telemetry:
                out = out + (jnp.int32(0),)  # join-share records sent
            return out

        def _compose_with_join():
            # row_count_a: membership counts on the post-A2 state (A3 moves
            # no membership, so this equals kernel.py's post-A3 count).
            row_count_a = pmap_blocks(
                lambda s0: jnp.sum(_slice_rows(S, s0, block) > 0,
                                   axis=-1, dtype=jnp.int32))

            def _reply_rows(s0):
                # reply_del[r, o] for responder rows r (kaboodle.rs:333-392).
                ok_b = ok_rows(s0)  # [B(r), N(o)] r -> o unicast gate
                Jm_b = join_b[None, :] & okT_rows(s0) & ~blk_eye(s0)
                member_b = _slice_rows(S, s0, block) > 0
                is_new = Jm_b & ~member_b
                n_after = (row_count_a[blk_idx(s0)][:, None]
                           + jnp.cumsum(is_new.astype(jnp.int32), axis=1))
                reply_p = broadcast_reply_prob(n_after)
                bern = bernoulli_matrix(
                    jax.random.fold_in(key_bern, s0 // block),
                    reply_p, (block, n), det,
                )
                reply = is_new & bern
                if not telemetry:
                    return reply & ok_b
                # Records in the join-response shares SENT from these rows
                # (kernel.py _join_replies' telemetry arithmetic, blocked):
                # the ``reply`` gate (not reply & ok_b — the response unicast
                # may still drop), sequential-map size uncapped, D5 cap model
                # over it.
                if cfg.max_share_peers:
                    cap = jnp.int32(cfg.max_share_peers)
                    within_cap = (
                        jnp.cumsum(member_b.astype(jnp.int32), axis=1) <= cap
                    )
                    base_c = member_b & within_cap
                    clen = jnp.minimum(
                        row_count_a[blk_idx(s0)], cap
                    )[:, None] + jnp.cumsum(
                        (Jm_b & ~base_c).astype(jnp.int32), axis=1
                    )
                    rec_cnt = jnp.where(n_after <= cap, n_after, clen)
                else:
                    rec_cnt = n_after
                recs = jnp.sum(
                    jnp.where(reply, rec_cnt, 0), axis=-1, dtype=jnp.int32
                )
                return reply & ok_b, recs

            if telemetry:
                reply_del, join_rec_rows = pmap_blocks(_reply_rows)
                join_records = jnp.sum(join_rec_rows, dtype=jnp.int32)
            else:
                reply_del = pmap_blocks(_reply_rows)

            if boot_union:
                # Closed-form avalanche union (see make_chunked_tick_fn
                # docstring for the derivation and its precondition).
                cnt = jnp.sum(reply_del.astype(jnp.int32), axis=0)  # [N(o)]

                def _union_rows_boot(s0):
                    gi = blk_idx(s0)
                    repT = jax.lax.dynamic_slice_in_dim(
                        reply_del, s0, block, axis=1).T  # [B(o), N(j)]
                    others = (cnt[gi][:, None] - repT.astype(jnp.int32)) > 0
                    tri = idx[None, :] <= gi[:, None]  # j <= o
                    return repT | (join_b[None, :] & others & tri)

                gossip = pmap_blocks(_union_rows_boot)
                res = pmap_blocks(_make_compose(True, reply_del, gossip))
                out = res + (jnp.sum(reply_del, dtype=jnp.int32),)
                if telemetry:
                    out = out + (join_records,)
                return out

            # Gate the O(N^3) contraction on a reply actually existing (same
            # rationale as kernel.py _join_replies: a rebroadcast into a
            # full mesh yields zero new joiners, zero replies, and an
            # all-False contraction that still costs the full dense time).
            any_reply = jnp.any(reply_del)

            def _union_rows(s0):
                # gossip[o, j] for joiner rows o: OR over responders r of
                # reply_del[r, o] & (share_base[r, j] | (Jm[r, j] & j <= o)).
                def _accum(acc, r0):
                    t1, t2 = acc
                    rep_T = jax.lax.dynamic_slice_in_dim(
                        reply_del, r0, block, axis=0)
                    rep_T = jax.lax.dynamic_slice_in_dim(
                        rep_T, s0, block, axis=1).T  # [B(o), B(r)]
                    member_r = _slice_rows(S, r0, block) > 0
                    share_base = member_r
                    if cfg.max_share_peers and n > cfg.max_share_peers:
                        within = (jnp.cumsum(member_r.astype(jnp.int32), axis=1)
                                  <= cfg.max_share_peers)
                        share_base = member_r & within
                    Jm_r = join_b[None, :] & okT_rows(r0) & ~blk_eye(r0)
                    t1 = t1 + _int8_matmul(rep_T, share_base)
                    t2 = t2 + _int8_matmul(rep_T, Jm_r)
                    return (t1, t2), None

                z = jnp.zeros((block, n), jnp.int32)
                (t1, t2), _ = jax.lax.scan(_accum, (z, z), starts)
                tri = idx[None, :] <= blk_idx(s0)[:, None]  # j <= o
                return (t1 > 0) | ((t2 > 0) & tri)

            gossip = jax.lax.cond(
                any_reply,
                lambda: pmap_blocks(_union_rows),
                lambda: jnp.zeros((n, n), dtype=bool),
            )
            res = pmap_blocks(_make_compose(True, reply_del, gossip))
            out = res + (jnp.sum(reply_del, dtype=jnp.int32),)
            if telemetry:
                out = out + (join_records,)
            return out

        if cfg.join_broadcast_enabled:
            comp = jax.lax.cond(any_join, _compose_with_join, _compose_plain)
        else:
            comp = _compose_plain()
        it = iter(comp)
        S, T = next(it), next(it)
        fp0, n0, dfp1, dn1, dfp2, dn2 = (next(it) for _ in range(6))
        lat = next(it) if has_lat else lat
        idv = next(it) if has_idv else idv
        if telemetry:
            msgs_join, join_records = comp[-2], comp[-1]
        else:
            msgs_join = comp[-1]
        fp1, n1 = fp0 + dfp1, n0 + dn1

        # ---- fp2 (escalation-gated full read; kernel.py fp2) ---------------
        def _fp_rows_of(SS, VV):
            def _fp_rows(s0):
                Sb = _slice_rows(SS, s0, block)
                member = Sb > 0
                if has_idv:
                    u_row_b = jnp.broadcast_to(
                        idx.astype(jnp.uint32)[None, :], (block, n))
                    contrib = jnp.where(
                        member,
                        peer_record_hash(u_row_b, _slice_rows(VV, s0, block)),
                        jnp.uint32(0))
                else:
                    contrib = jnp.where(member, rec_hash[None, :], jnp.uint32(0))
                return (jnp.sum(contrib, axis=-1, dtype=jnp.uint32),
                        jnp.sum(member, axis=-1, dtype=jnp.int32))
            return _fp_rows

        fp2, n2 = jax.lax.cond(
            any_esc,
            lambda: pmap_blocks(_fp_rows_of(S, idv)),
            lambda: (jnp.zeros((n,), jnp.uint32), jnp.zeros((n,), jnp.int32)),
        )

        # ---- calls 3 + 4 (escalation-gated scatter waves) -------------------
        def _calls34_rows(s0):
            Sb, Tb, lb, vb = state_rows(S, T, lat, idv, s0)
            gi = blk_idx(s0)

            def _inblk_scatter(rows, cols, vals):
                rr = jnp.clip(rows - s0, 0, block - 1)
                inb = (rows >= s0) & (rows < s0 + block)
                z = jnp.zeros((block, n), dtype=bool)
                return z.at[rr, jnp.clip(cols, 0)].max(vals & inb)

            # mark3: proxy marks suspect + suspector marks pinger-proxy.
            jb = jnp.broadcast_to(jstar[:, None], proxies.shape)
            ib = jnp.broadcast_to(idx[:, None], proxies.shape)
            mark3 = _inblk_scatter(proxies, jb, del_pack)
            mark3 |= _inblk_scatter(ib, proxies, del_fwd_c)

            def _apply(Sb, Tb, lb, vb, mark):
                if has_lat:
                    waiting = (Sb == WAITING_FOR_PING) | (
                        Sb == WAITING_FOR_INDIRECT_PING)
                    sample = (t - Tb).astype(jnp.float32)
                    upd = jnp.where(jnp.isnan(lb), sample,
                                    jnp.float32(0.8) * sample
                                    + jnp.float32(0.2) * lb)
                    lb = jnp.where(mark & waiting, upd, lb)
                if has_idv:
                    vb = jnp.where(mark, id_row, vb)
                Sb = jnp.where(mark, jnp.int8(KNOWN), Sb)
                Tb = jnp.where(mark, tT, Tb)
                return Sb, Tb, lb, vb

            Sb, Tb, lb, vb = _apply(Sb, Tb, lb, vb, mark3)
            mark4 = _inblk_scatter(ib, proxies, del_fwd)
            Sb, Tb, lb, vb = _apply(Sb, Tb, lb, vb, mark4)
            if not cfg.faithful_indirect_ack:
                cleared = jnp.any((del_fwd | del_fwd_c)[gi], axis=-1)
                jcell = idx[None, :] == jstar[gi][:, None]
                clr = cleared[:, None] & jcell & (Sb > 0)
                Sb = jnp.where(clr, jnp.int8(KNOWN), Sb)
                Tb = jnp.where(clr, tT, Tb)
            return tuple(x for x in (Sb, Tb, lb, vb) if x is not None)

        c34 = jax.lax.cond(
            any_esc,
            lambda a: pmap_blocks(_calls34_rows),
            lambda a: a,
            tuple(x for x in (S, T, lat, idv) if x is not None),
        )
        it = iter(c34)
        S, T = next(it), next(it)
        lat = next(it) if has_lat else None
        idv = next(it) if has_idv else None

        # ---- fp_g (kernel.py: delta chain on quiet ticks, recompute else) --
        fp_g, n_g = jax.lax.cond(
            any_join | any_esc,
            lambda: pmap_blocks(_fp_rows_of(S, idv)),
            lambda: (fp1 + dfp2, n1 + dn2),
        )

        # ---- anti-entropy candidate selection (kaboodle.rs:707-740) --------
        def _prio0_rows(s0):
            gi = blk_idx(s0)
            m0 = ((st.kpr_partner[None, :] == gi[:, None])
                  & alive[gi][:, None] & ~rv[gi][:, None])
            match0 = m0 & (st.kpr_fp[None, :] != fp_g[gi][:, None]) & (
                n_g[gi][:, None] <= st.kpr_n[None, :]
            )
            return jnp.min(jnp.where(match0, idx[None, :], INF), axis=-1)

        prio0 = pmap_blocks(_prio0_rows)
        peer0 = prio0

        base1 = jnp.int32(n)
        m_d = del_ack & (fp1[jnp.clip(ping_tgt, 0)] != fp_g) & (
            n_g <= n1[jnp.clip(ping_tgt, 0)]
        )
        m_m = del_ack_man & (fp1[jnp.clip(man_tgt, 0)] != fp_g) & (
            n_g <= n1[jnp.clip(man_tgt, 0)]
        )
        prio_d = jnp.where(m_d, base1 + ping_tgt, INF)
        prio_m = jnp.where(m_m, base1 + man_tgt, INF)
        prio1 = jnp.minimum(prio_d, prio_m)
        peer1 = jnp.where(prio_d <= prio_m, ping_tgt, man_tgt)

        base2 = jnp.int32(2 * n)
        x_fp2 = fp2[jnp.clip(jstar, 0)]
        x_n2 = n2[jnp.clip(jstar, 0)]
        m_px = del_pack & (x_fp2[:, None] != fp_g[jnp.clip(proxies, 0)]) & (
            n_g[jnp.clip(proxies, 0)] <= x_n2[:, None]
        )
        prio_proxy = jnp.full((n,), INF, dtype=jnp.int32).at[jnp.clip(proxies, 0)].min(
            jnp.where(m_px, base2 + jstar[:, None], INF)
        )
        peer_proxy = prio_proxy - base2
        x_fp1 = fp1[jnp.clip(jstar, 0)]
        x_n1 = n1[jnp.clip(jstar, 0)]
        m_cf = del_fwd_c & (x_fp1[:, None] != fp_g[:, None]) & (
            n_g[:, None] <= x_n1[:, None]
        )
        prio_coinc = jnp.min(jnp.where(m_cf, base2 + proxies, INF), axis=-1)
        prio2 = jnp.minimum(prio_proxy, prio_coinc)
        peer2 = jnp.where(prio_proxy <= prio_coinc, peer_proxy, jstar)

        base3 = jnp.int32(3 * n)
        m_f = del_fwd & (x_fp2[:, None] != fp_g[:, None]) & (
            n_g[:, None] <= x_n2[:, None]
        )
        prio3 = jnp.min(jnp.where(m_f, base3 + proxies, INF), axis=-1)
        peer3 = jstar

        best = jnp.minimum(jnp.minimum(prio0, prio1), jnp.minimum(prio2, prio3))
        partner = jnp.where(
            best == prio0, peer0,
            jnp.where(best == prio1, peer1, jnp.where(best == prio2, peer2, peer3)),
        ).astype(jnp.int32)
        has_req = (best != INF) & alive
        partner = jnp.where(has_req, partner, -1)
        del_kpr = has_req & ok_edge(idx, partner)
        del_rep = del_kpr & ok_edge(partner, idx)

        # ---- call-G apply (gated; two passes so the share snapshot is the
        # post-mark_g state, exactly the oracle's two-pass order) ------------
        def _g1_rows(s0):
            Sb, Tb, lb, vb = state_rows(S, T, lat, idv, s0)
            gi = blk_idx(s0)
            mark_g = (gi[:, None] == partner[None, :]) & del_kpr[None, :]
            if has_lat:
                waiting = (Sb == WAITING_FOR_PING) | (
                    Sb == WAITING_FOR_INDIRECT_PING)
                sample = (t - Tb).astype(jnp.float32)
                upd = jnp.where(jnp.isnan(lb), sample,
                                jnp.float32(0.8) * sample + jnp.float32(0.2) * lb)
                lb = jnp.where(mark_g & waiting, upd, lb)
            if has_idv:
                vb = jnp.where(mark_g, id_row, vb)
            Sb = jnp.where(mark_g, jnp.int8(KNOWN), Sb)
            Tb = jnp.where(mark_g, tT, Tb)
            return tuple(x for x in (Sb, Tb, lb, vb) if x is not None)

        def _g_phase(args):
            g1 = pmap_blocks(_g1_rows)
            it = iter(g1)
            S1, T1 = next(it), next(it)
            l1 = next(it) if has_lat else None
            v1 = next(it) if has_idv else None

            def _g2_rows(s0):
                Sb = _slice_rows(S1, s0, block)
                Tb = _slice_rows(T1, s0, block)
                vb = _slice_rows(v1, s0, block) if has_idv else None
                gi = blk_idx(s0)
                pt = partner[gi]
                mark_rep = (idx[None, :] == pt[:, None]) & del_rep[gi][:, None]
                Sb2 = jnp.where(mark_rep, jnp.int8(KNOWN), Sb)
                Tb2 = jnp.where(mark_rep, tT, Tb)
                # Filtered share from the partner's post-mark_g row
                # (kaboodle.rs:483-501): reads the PASS INPUT (= post-G1,
                # pre-mark_rep) rows, the exact S_share snapshot.
                Sg = S1[jnp.clip(pt, 0)]
                Tg = T1[jnp.clip(pt, 0)]
                share = (Sg == KNOWN) & (idx[None, :] != pt[:, None]) & (
                    (t - Tg) < cfg.max_peer_share_age_ticks
                )
                rep_ins = (del_rep[gi][:, None] & share
                           & ~blk_eye(s0) & ~(Sb2 > 0))
                Sb2 = jnp.where(rep_ins, jnp.int8(KNOWN), Sb2)
                Tb2 = jnp.where(rep_ins, tT - gossip_backdate, Tb2)
                out = [Sb2, Tb2]
                if has_idv:
                    out.append(jnp.where(rep_ins, id_row, vb))
                if telemetry:
                    # Records in the replies these requesters' partners SENT
                    # (kernel.py _g_apply telemetry): every delivered request
                    # is answered; ``share`` already excludes the partner's
                    # self-entry, the requester's own column is subtracted.
                    own = share[jnp.arange(block, dtype=jnp.int32), gi]
                    out.append(jnp.where(
                        del_kpr[gi],
                        jnp.sum(share, axis=-1, dtype=jnp.int32)
                        - own.astype(jnp.int32),
                        0,
                    ))
                return tuple(out)

            g2 = pmap_blocks(_g2_rows)
            it = iter(g2)
            S2, T2 = next(it), next(it)
            v2 = next(it) if has_idv else None
            ae_rows = next(it) if telemetry else None
            fp_f, n_f = pmap_blocks(_fp_rows_of(S2, v2))
            out = [S2, T2, fp_f, n_f]
            if has_lat:
                out.append(l1)
            if has_idv:
                out.append(v2)
            if telemetry:
                out.append(jnp.sum(ae_rows, dtype=jnp.int32))
            return tuple(out)

        def _g_skip(args):
            out = [S, T, fp_g, n_g]
            if has_lat:
                out.append(lat)
            if has_idv:
                out.append(idv)
            if telemetry:
                out.append(jnp.int32(0))
            return tuple(out)

        gph = jax.lax.cond(jnp.any(del_kpr), _g_phase, _g_skip, ())
        it = iter(gph)
        S, T, fp_f, n_f = next(it), next(it), next(it), next(it)
        lat = next(it) if has_lat else None
        idv = next(it) if has_idv else None
        ae_records = gph[-1] if telemetry else None

        # ---- metrics + next state (kernel.py _finish) ----------------------
        msgs = (
            jnp.sum(ok_ping, dtype=jnp.int32)
            + jnp.sum(ok_man, dtype=jnp.int32)
            + jnp.sum(del_pr, dtype=jnp.int32)
            + jnp.sum(del_ack, dtype=jnp.int32)
            + jnp.sum(del_ack_man, dtype=jnp.int32)
            + jnp.sum(del_pping, dtype=jnp.int32)
            + jnp.sum(del_pack, dtype=jnp.int32)
            + jnp.sum(del_fwd_c, dtype=jnp.int32)
            + jnp.sum(del_fwd, dtype=jnp.int32)
            + jnp.sum(del_kpr, dtype=jnp.int32)
            + jnp.sum(del_rep, dtype=jnp.int32)
            + msgs_join
        )

        converged, fpa_min, fpa_max, n_alive = fingerprint_agreement(alive, fp_f)
        agree = jnp.sum(alive & (fp_f == fpa_min), dtype=jnp.int32)
        new_state = MeshState(
            state=S, timer=T, alive=alive, identity=st.identity,
            never_broadcast=never_b, last_broadcast=last_b,
            kpr_partner=jnp.where(del_kpr, partner, -1),
            kpr_fp=fp_g, kpr_n=n_g, tick=t + 1, key=key_next,
            latency=lat, id_view=idv,
        )
        metrics = TickMetrics(
            messages_delivered=msgs,
            converged=converged,
            agree_fraction=agree.astype(jnp.float32) / jnp.maximum(n_alive, 1),
            # f32 accumulation: an int32 sum wraps at the N=65,536 scale this
            # kernel exists for (65,536 alive x 65,536 members > 2^31); the
            # ~1e-7 relative f32 error is noise on a mean.
            mean_membership=jnp.sum(jnp.where(alive, n_f, 0).astype(jnp.float32))
            / jnp.maximum(n_alive, 1),
            fingerprint_min=fpa_min,
            fingerprint_max=fpa_max,
        )
        if not telemetry:
            return new_state, metrics

        # ---- telemetry counters (kernel.py's definitions, blocked) ---------
        # A2 removals, recomputed from the pre-tick snapshot only on ticks
        # where A2 fired (the two terms are disjoint — kernel.py note).
        def _wfip_cells(s0):
            Sb = _slice_rows(S0, s0, block)
            Tb = _slice_rows(T0, s0, block)
            return jnp.sum(
                alive[blk_idx(s0)][:, None]
                & (Sb == WAITING_FOR_INDIRECT_PING)
                & ((t - Tb) >= cfg.ping_timeout_ticks),
                axis=-1,
                dtype=jnp.int32,
            )

        deaths = jax.lax.cond(
            any_a2,
            lambda: jnp.sum(pmap_blocks(_wfip_cells), dtype=jnp.int32)
            + jnp.sum(insta_remove, dtype=jnp.int32),
            lambda: jnp.int32(0),
        )
        if cfg.join_broadcast_enabled:
            joins_diss = jax.lax.cond(
                any_join,
                lambda: jnp.sum(
                    pmap_blocks(
                        lambda s0: jnp.sum(
                            join_b[None, :] & okT_rows(s0) & ~blk_eye(s0),
                            axis=-1,
                            dtype=jnp.int32,
                        )
                    ),
                    dtype=jnp.int32,
                ),
                lambda: jnp.int32(0),
            )
        else:
            joins_diss = jnp.int32(0)
        counters = ProtocolCounters(
            pings_sent=jnp.sum(has_ping, dtype=jnp.int32)
            + jnp.sum(man_tgt >= 0, dtype=jnp.int32)
            + jnp.sum(del_pr, dtype=jnp.int32),
            acks_sent=jnp.sum(ok_ping, dtype=jnp.int32)
            + jnp.sum(ok_man, dtype=jnp.int32)
            + jnp.sum(del_pping, dtype=jnp.int32)
            + jnp.sum(fwd, dtype=jnp.int32)
            + jnp.sum(fwd_c, dtype=jnp.int32),
            ping_reqs_sent=jnp.sum(proxies_valid, dtype=jnp.int32),
            suspicions_raised=jnp.sum(escalate, dtype=jnp.int32),
            suspicions_refuted=jnp.sum(
                (S0 == WAITING_FOR_INDIRECT_PING) & (S == KNOWN),
                dtype=jnp.int32,
            ),
            deaths_declared=deaths,
            joins_disseminated=joins_diss,
            gossip_bytes=jnp.uint32(RECORD_BYTES)
            * (ae_records + join_records).astype(jnp.uint32),
            armed_timers=jnp.sum(
                alive[:, None]
                & ((S == WAITING_FOR_PING) | (S == WAITING_FOR_INDIRECT_PING)),
                dtype=jnp.int32,
            ),
        )
        return new_state, TickTelemetry(metrics=metrics, counters=counters, fp=fp_f)

    # Program metadata for derived consumers (trace slices, registry, dryrun).
    tick.graph = graph
    tick.programs = {"blocked": prog}
    return tick
