"""Derive the five engines from the one tick op graph.

Every compiled-program family the simulator dispatches is built HERE, from
the same :func:`~kaboodle_tpu.phasegraph.graph.build_graph` output:

==========  ==============================================================
engine      derivation
==========  ==============================================================
dense       exec.py's full + fused programs under the planner-derived
            per-tick dispatch (``make_dense_tick``); ``make_fused_tick``
            pins the 2-pass fused program alone (A/B + audit builds)
chunked     the blocked program: same ops/order, row-blocked layout
            (``make_chunked_tick`` -> blocked.py)
sharded     the dense tick wrapped in GSPMD sharding constraints so the
            scan carry keeps one placement (``make_sharded_tick``)
fleet       the dense tick vmapped over a leading ensemble axis
            (``make_fleet_tick``); every ``lax.cond`` batches to a select,
            so the fleet build compiles the FULL program only — under vmap
            both dispatch branches would execute for the whole ensemble
            every tick, making the fused branch pure added work. Values
            are unchanged either way (the dispatch is bit-exact by
            contract), so member-vs-standalone parity holds.
warp        the span program: invariant ops pruned, survivors batched as
            one k-tick scan (``make_warp_leap`` -> span.py)
sparse      the blocked_topk-layout program: [N, K] neighbor blocks,
            counter-based draws, bounded block repair
            (``make_sparse_tick`` -> sparseplane/kernel.py) —
            distributional twin of the dense oracle, stat-pinned
==========  ==============================================================

``fleet/core.py``, ``parallel/mesh.py``, ``sim/kernel.py``,
``sim/chunked.py`` and ``warp/leap.py`` are shims/wrappers over these
builders — the protocol logic exists once, under ``phasegraph/``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from kaboodle_tpu.config import SwimConfig

# The executable-engine imports are lazy (inside each builder): exec.py /
# blocked.py / span.py import sim.state, whose package __init__ imports the
# sim.kernel/sim.chunked shims, which import back into this package — an
# eager import here would hit those modules half-initialized when a caller
# enters through `kaboodle_tpu.phasegraph` first.

# The engine names, for dryrun/docs enumeration.
ENGINES = (
    "dense", "fused", "chunked", "sharded", "fleet", "warp", "serve", "sparse",
)


def make_dense_tick(
    cfg: SwimConfig, faulty: bool = True, telemetry: bool = False
) -> Callable:
    """The production dense tick: full + fused programs, per-tick dispatch."""
    from kaboodle_tpu.phasegraph.exec import make_tick_fn

    return make_tick_fn(cfg, faulty=faulty, telemetry=telemetry)


def make_fused_tick(
    cfg: SwimConfig, faulty: bool = True, telemetry: bool = False
) -> Callable:
    """The standalone 2-pass fused program (no dispatch guard).

    Callers own the precondition that every tick satisfies the dispatch
    predicate (no Join broadcast, no suspicion activity); bench's
    ``--fastpath-ab`` lane bit-checks it against the dispatched build
    before reporting a number.
    """
    from kaboodle_tpu.phasegraph.exec import make_tick_fn

    return make_tick_fn(cfg, faulty=faulty, telemetry=telemetry, program="fused")


def make_chunked_tick(
    cfg: SwimConfig,
    faulty: bool = True,
    block: int = 1024,
    drop: bool = True,
    boot_union: bool = False,
    telemetry: bool = False,
) -> Callable:
    """The row-blocked (O(block·N)-transient) derivation."""
    from kaboodle_tpu.phasegraph.blocked import make_chunked_tick_fn

    return make_chunked_tick_fn(
        cfg, faulty=faulty, block=block, drop=drop, boot_union=boot_union,
        telemetry=telemetry,
    )


def make_fleet_tick(
    cfg: SwimConfig, faulty: bool = True, telemetry: bool = False
) -> Callable:
    """The dense tick vmapped over the leading ensemble axis.

    Compiles the FULL program only (``fast_path=False``): under ``vmap``
    the dispatch ``lax.cond`` batches to a select that executes BOTH
    branches for the whole ensemble whenever any member needs the full
    path — on fault scenarios that is nearly every tick, so the fused
    branch would be pure added sweeps. The dispatch is bit-exact by
    contract (tests/test_fast_path.py), so member trajectories are
    unchanged and the member-vs-standalone parity pins hold.

    The fused Pallas stage kernels do not batch — they are demoted-off by
    default (PERF.md "Pallas policy") and rejected here so a config that
    re-enables them fails loudly instead of miscompiling under vmap.
    """
    import jax

    if cfg.use_pallas_fp or cfg.use_pallas_oldest_k or cfg.use_pallas_suspicion:
        raise ValueError(
            "fleet: the fused Pallas stage kernels do not support vmap; "
            "use the default jnp formulations (use_pallas_*=False)"
        )
    from kaboodle_tpu.phasegraph.exec import make_tick_fn

    vcfg = dataclasses.replace(cfg, fast_path=False)
    vtick = jax.vmap(make_tick_fn(vcfg, faulty=faulty, telemetry=telemetry))

    # Named scope for jax.profiler captures (metadata only; wraps the
    # whole vmapped dispatch so fleet ops group under one label).
    @jax.named_scope("kaboodle:fleet_tick")
    def fleet_tick(mesh, inputs):
        return vtick(mesh, inputs)

    return fleet_tick


def make_sharded_tick(
    cfg: SwimConfig, mesh, faulty: bool = True, telemetry: bool = False
) -> Callable:
    """The dense tick with its carry constrained onto the device mesh.

    The constraint after every tick keeps the scan carry's sharding fixed,
    so XLA partitions each tick identically instead of re-deciding
    layouts. The dispatch predicate is a global reduction the partitioner
    all-reduces; both programs partition row-locally like before.
    """
    from kaboodle_tpu.parallel.mesh import constrain_state
    from kaboodle_tpu.phasegraph.exec import make_tick_fn

    tick = make_tick_fn(cfg, faulty=faulty, telemetry=telemetry)

    def sharded_tick(st, inp):
        st, m = tick(st, inp)
        st = constrain_state(st, mesh)
        return st, m

    sharded_tick.graph = tick.graph
    sharded_tick.programs = tick.programs
    return sharded_tick


@dataclasses.dataclass
class ServeStepOut:
    """Per-lane outputs of one serve step chunk (all leaves ``[E]``).

    ``conv_tick`` is the ticks-run count at the lane's FIRST observed
    fingerprint agreement (``-1`` until then) — for a converge-mode lane it
    equals the ``ticks_run`` a standalone ``run_until_converged`` of the
    same seed would report. ``counters`` is the chunk's per-lane
    ``ProtocolCounters`` delta (``None`` unless the step was built with
    ``telemetry=True``); frozen lanes contribute zero.
    """

    remaining: object  # int32 [E] tick budget left
    ticks_run: object  # int32 [E] ticks executed since admission
    conv_tick: object  # int32 [E] ticks_run at first agreement, -1 before
    done: object  # bool [E] lane finished (converged or budget exhausted)
    messages: object  # int32 [E] unicasts delivered this chunk
    counters: object | None = None  # ProtocolCounters with [E] leaves


# ServeStepOut crosses the jit boundary of the serve step program, so it
# must be a pytree (registered AFTER the class body: the dataclass carries
# an optional-None leaf like MeshState.latency).
import jax.tree_util as _jtu  # noqa: E402

_jtu.register_dataclass(
    ServeStepOut,
    data_fields=(
        "remaining", "ticks_run", "conv_tick", "done", "messages", "counters"
    ),
    meta_fields=(),
)


def make_serve_step(
    cfg: SwimConfig,
    chunk: int,
    faulty: bool = False,
    telemetry: bool = False,
    constrain: Callable | None = None,
) -> Callable:
    """The serving engine's resident program: a masked fleet converge chunk.

    ONE compiled program advances a whole lane pool up to ``chunk`` ticks:
    every lane that is occupied (``active``), unfinished and within budget
    ticks in lockstep through the vmapped fleet tick; everything else —
    free lanes, freshly converged converge-mode lanes, exhausted budgets —
    freezes bit-exactly via ``fleet.core.freeze_members``. The whole
    admission surface is TRACED (per-lane drop knob, activity masks, tick
    budgets, run counters), so the serving loop re-dispatches this one
    program forever: admitting, retiring and re-seeding lanes never
    recompiles (the zero-recompile-after-warmup contract, pinned by
    tests/test_fuzz_parity.py and the serve dryrun).

    Per-lane semantics (``until_conv`` selects the request mode):

    - converge mode (``until_conv[e]``): the lane freezes at the end-of-tick
      state where its fingerprints first agree — the exact
      ``run_until_converged`` contract, so a lane admitted mid-flight is
      bit-exact with the standalone run of the same seed (with
      ``remaining`` as the ``max_ticks`` bound).
    - horizon mode (``~until_conv[e]``): the lane runs exactly its budget
      (convergence observed and recorded, not a stop condition) — the
      ``simulate``/``run_warped`` contract.

    Like the fleet tick it wraps, the build is full-program-only under vmap
    and the per-lane ``drop_rate`` knob is inert unless ``faulty=True``.
    ``telemetry=True`` derives from the telemetry-plane fleet tick and
    accumulates each lane's exact ``ProtocolCounters`` over the ticks it
    actually advanced (frozen ticks contribute zero).

    ``constrain`` (stacked mesh -> stacked mesh) pins the lane-pool carry
    onto a device mesh after every tick — the GSPMD hook
    :func:`make_sharded_serve_step` fills in. Applied at loop entry AND in
    the body, so the while_loop carry holds ONE placement from iteration
    zero (the ``make_sharded_tick`` stability argument, lane-pool shaped).

    Returns ``serve_step(mesh, drop_rate, active, until_conv, remaining,
    ticks_run, conv_tick) -> (mesh, ServeStepOut)``.
    """
    import jax
    import jax.numpy as jnp

    from kaboodle_tpu.fleet.core import fleet_idle_inputs, freeze_members
    from kaboodle_tpu.sim.runner import state_converged

    if chunk < 1:
        raise ValueError("serve step chunk must be >= 1")
    vtick = make_fleet_tick(cfg, faulty=faulty, telemetry=telemetry)
    vconv = jax.vmap(state_converged)

    def serve_step(mesh, drop_rate, active, until_conv, remaining,
                   ticks_run, conv_tick):
        ensemble = active.shape[0]
        n = mesh.state.shape[-1]
        idle = fleet_idle_inputs(n, ensemble, drop_rate=drop_rate)
        # Entry test, like the standalone converge loop: a converge-mode
        # lane already at agreement freezes immediately with conv_tick ==
        # ticks_run (0 for a freshly admitted converged-init lane).
        conv0 = vconv(mesh)
        conv_tick = jnp.where(
            active & conv0 & (conv_tick < 0), ticks_run, conv_tick
        )
        done0 = (~active) | (until_conv & conv0) | (remaining <= 0)
        messages0 = jnp.zeros((ensemble,), jnp.int32)
        if telemetry:
            from kaboodle_tpu.telemetry.counters import zero_counters

            counters0 = jax.tree.map(
                lambda z: jnp.broadcast_to(z, (ensemble,)), zero_counters()
            )
        else:
            counters0 = None

        def cond(carry):
            done, i = carry[4], carry[7]
            return jnp.any(~done) & (i < chunk)

        def body(carry):
            mesh, remaining, ticks_run, conv_tick, done, messages, ctr, i = carry
            new, out = vtick(mesh, idle)
            m = out.metrics if telemetry else out
            adv = ~done
            mesh = freeze_members(adv, mesh, new)
            if constrain is not None:
                mesh = constrain(mesh)
            ticks_run = jnp.where(adv, ticks_run + 1, ticks_run)
            remaining = jnp.where(adv, remaining - 1, remaining)
            messages = messages + jnp.where(adv, m.messages_delivered, 0)
            if telemetry:
                ctr = jax.tree.map(
                    lambda t, c: t + jnp.where(adv, c, 0).astype(t.dtype),
                    ctr, out.counters,
                )
            conv_now = adv & m.converged
            conv_tick = jnp.where(conv_now & (conv_tick < 0),
                                  ticks_run, conv_tick)
            done = done | (until_conv & conv_now) | (remaining <= 0)
            return mesh, remaining, ticks_run, conv_tick, done, messages, ctr, i + 1

        if constrain is not None:
            mesh = constrain(mesh)
        mesh, remaining, ticks_run, conv_tick, done, messages, ctr, _ = (
            jax.lax.while_loop(
                cond,
                body,
                (mesh, remaining, ticks_run, conv_tick, done0, messages0,
                 counters0, jnp.int32(0)),
            )
        )
        return mesh, ServeStepOut(
            remaining=remaining, ticks_run=ticks_run, conv_tick=conv_tick,
            done=done, messages=messages, counters=ctr,
        )

    return serve_step


def make_sharded_serve_step(
    cfg: SwimConfig,
    chunk: int,
    mesh,
    faulty: bool = False,
    telemetry: bool = False,
) -> Callable:
    """The serve step with its lane-pool carry pinned onto a fleet mesh.

    The GSPMD twin of :func:`make_serve_step` — same program, same
    bit-exact per-lane semantics, but every while_loop iteration constrains
    the stacked ``[E, ...]`` mesh back onto the device mesh's fleet layout
    (``fleet.sharding.make_fleet_constrainer``): the ``[E]`` lane axis
    splits across the ensemble mesh axis and, on a 2-D ``E x peers`` mesh,
    each lane's ``[N]`` rows split across the peer axis. XLA then
    partitions every iteration identically — lanes tick device-locally;
    only the loop's ``any(~done)`` predicate (and, on 2-D, the per-member
    row collectives) cross the ICI. The output mesh carries the same
    placement it came in with, so round-over-round re-dispatch never hands
    jit a fresh input sharding (the sharded pool's zero-recompile leg).
    """
    from kaboodle_tpu.fleet.sharding import make_fleet_constrainer

    return make_serve_step(
        cfg, chunk, faulty=faulty, telemetry=telemetry,
        constrain=make_fleet_constrainer(mesh),
    )


def make_sparse_tick(cfg: SwimConfig, spec, faulty: bool = True) -> Callable:
    """The blocked_topk-layout tick: [N, K] neighbor blocks, counter RNG.

    Derived from the blocked-layout op graph (``build_graph(cfg,
    layout="blocked_topk")`` + ``plan(graph, "sparse")``); the executable
    body is sparseplane/kernel.py, whose tail pass grouping is asserted
    here against the planned program so the kernel and the planner cannot
    drift silently.  ``spec`` is a hashable
    :class:`~kaboodle_tpu.sparseplane.state.SparseSpec` (block width K,
    gossip fanout, boot contacts, timer dtype).  Operates on
    ``SparseState``/``SparseTickInputs`` — distributionally equivalent to
    the dense oracle, stat-pinned rather than bit-exact
    (tests/test_fuzz_parity.py).
    """
    from kaboodle_tpu.phasegraph.graph import build_graph
    from kaboodle_tpu.phasegraph.plan import plan
    from kaboodle_tpu.sparseplane.kernel import (
        SPARSE_TAIL_PASSES,
        make_sparse_tick_fn,
    )

    graph = build_graph(cfg, faulty=faulty, layout="blocked_topk")
    program = plan(graph, "sparse")
    planned = tuple(p.name for p in program.tail)
    implemented = tuple(n for n in SPARSE_TAIL_PASSES if n in planned)
    if planned != implemented:
        raise AssertionError(
            f"sparse plan/kernel drift: planner tail {planned}, kernel "
            f"implements {SPARSE_TAIL_PASSES}"
        )
    tick = make_sparse_tick_fn(cfg, spec, faulty=faulty)
    tick.graph = graph
    tick.programs = {"sparse": program}
    return tick


def make_warp_leap(
    cfg: SwimConfig,
    k: int,
    constrain: Callable | None = None,
    hybrid: bool = False,
    masked: bool = False,
) -> Callable:
    """The span / hybrid program: k (near-)quiescent ticks as one scan.

    ``hybrid=True`` derives the Warp 2.0 near-quiescent program (strict
    span + sterile anti-entropy — ``plan(graph, "hybrid")``);
    ``masked=True`` makes the span length a traced ``k_m <= k`` so the
    fleet runner can vmap one program over per-member horizons."""
    from kaboodle_tpu.phasegraph.span import make_leap_fn

    return make_leap_fn(cfg, k, constrain=constrain, hybrid=hybrid, masked=masked)
