"""Derive the five engines from the one tick op graph.

Every compiled-program family the simulator dispatches is built HERE, from
the same :func:`~kaboodle_tpu.phasegraph.graph.build_graph` output:

==========  ==============================================================
engine      derivation
==========  ==============================================================
dense       exec.py's full + fused programs under the planner-derived
            per-tick dispatch (``make_dense_tick``); ``make_fused_tick``
            pins the 2-pass fused program alone (A/B + audit builds)
chunked     the blocked program: same ops/order, row-blocked layout
            (``make_chunked_tick`` -> blocked.py)
sharded     the dense tick wrapped in GSPMD sharding constraints so the
            scan carry keeps one placement (``make_sharded_tick``)
fleet       the dense tick vmapped over a leading ensemble axis
            (``make_fleet_tick``); every ``lax.cond`` batches to a select,
            so the fleet build compiles the FULL program only — under vmap
            both dispatch branches would execute for the whole ensemble
            every tick, making the fused branch pure added work. Values
            are unchanged either way (the dispatch is bit-exact by
            contract), so member-vs-standalone parity holds.
warp        the span program: invariant ops pruned, survivors batched as
            one k-tick scan (``make_warp_leap`` -> span.py)
==========  ==============================================================

``fleet/core.py``, ``parallel/mesh.py``, ``sim/kernel.py``,
``sim/chunked.py`` and ``warp/leap.py`` are shims/wrappers over these
builders — the protocol logic exists once, under ``phasegraph/``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from kaboodle_tpu.config import SwimConfig

# The executable-engine imports are lazy (inside each builder): exec.py /
# blocked.py / span.py import sim.state, whose package __init__ imports the
# sim.kernel/sim.chunked shims, which import back into this package — an
# eager import here would hit those modules half-initialized when a caller
# enters through `kaboodle_tpu.phasegraph` first.

# The engine names, for dryrun/docs enumeration.
ENGINES = ("dense", "fused", "chunked", "sharded", "fleet", "warp")


def make_dense_tick(
    cfg: SwimConfig, faulty: bool = True, telemetry: bool = False
) -> Callable:
    """The production dense tick: full + fused programs, per-tick dispatch."""
    from kaboodle_tpu.phasegraph.exec import make_tick_fn

    return make_tick_fn(cfg, faulty=faulty, telemetry=telemetry)


def make_fused_tick(
    cfg: SwimConfig, faulty: bool = True, telemetry: bool = False
) -> Callable:
    """The standalone 2-pass fused program (no dispatch guard).

    Callers own the precondition that every tick satisfies the dispatch
    predicate (no Join broadcast, no suspicion activity); bench's
    ``--fastpath-ab`` lane bit-checks it against the dispatched build
    before reporting a number.
    """
    from kaboodle_tpu.phasegraph.exec import make_tick_fn

    return make_tick_fn(cfg, faulty=faulty, telemetry=telemetry, program="fused")


def make_chunked_tick(
    cfg: SwimConfig,
    faulty: bool = True,
    block: int = 1024,
    drop: bool = True,
    boot_union: bool = False,
    telemetry: bool = False,
) -> Callable:
    """The row-blocked (O(block·N)-transient) derivation."""
    from kaboodle_tpu.phasegraph.blocked import make_chunked_tick_fn

    return make_chunked_tick_fn(
        cfg, faulty=faulty, block=block, drop=drop, boot_union=boot_union,
        telemetry=telemetry,
    )


def make_fleet_tick(
    cfg: SwimConfig, faulty: bool = True, telemetry: bool = False
) -> Callable:
    """The dense tick vmapped over the leading ensemble axis.

    Compiles the FULL program only (``fast_path=False``): under ``vmap``
    the dispatch ``lax.cond`` batches to a select that executes BOTH
    branches for the whole ensemble whenever any member needs the full
    path — on fault scenarios that is nearly every tick, so the fused
    branch would be pure added sweeps. The dispatch is bit-exact by
    contract (tests/test_fast_path.py), so member trajectories are
    unchanged and the member-vs-standalone parity pins hold.

    The fused Pallas stage kernels do not batch — they are demoted-off by
    default (PERF.md "Pallas policy") and rejected here so a config that
    re-enables them fails loudly instead of miscompiling under vmap.
    """
    import jax

    if cfg.use_pallas_fp or cfg.use_pallas_oldest_k or cfg.use_pallas_suspicion:
        raise ValueError(
            "fleet: the fused Pallas stage kernels do not support vmap; "
            "use the default jnp formulations (use_pallas_*=False)"
        )
    from kaboodle_tpu.phasegraph.exec import make_tick_fn

    vcfg = dataclasses.replace(cfg, fast_path=False)
    vtick = jax.vmap(make_tick_fn(vcfg, faulty=faulty, telemetry=telemetry))

    # Named scope for jax.profiler captures (metadata only; wraps the
    # whole vmapped dispatch so fleet ops group under one label).
    @jax.named_scope("kaboodle:fleet_tick")
    def fleet_tick(mesh, inputs):
        return vtick(mesh, inputs)

    return fleet_tick


def make_sharded_tick(
    cfg: SwimConfig, mesh, faulty: bool = True, telemetry: bool = False
) -> Callable:
    """The dense tick with its carry constrained onto the device mesh.

    The constraint after every tick keeps the scan carry's sharding fixed,
    so XLA partitions each tick identically instead of re-deciding
    layouts. The dispatch predicate is a global reduction the partitioner
    all-reduces; both programs partition row-locally like before.
    """
    from kaboodle_tpu.parallel.mesh import constrain_state
    from kaboodle_tpu.phasegraph.exec import make_tick_fn

    tick = make_tick_fn(cfg, faulty=faulty, telemetry=telemetry)

    def sharded_tick(st, inp):
        st, m = tick(st, inp)
        st = constrain_state(st, mesh)
        return st, m

    sharded_tick.graph = tick.graph
    sharded_tick.programs = tick.programs
    return sharded_tick


def make_warp_leap(
    cfg: SwimConfig,
    k: int,
    constrain: Callable | None = None,
    hybrid: bool = False,
    masked: bool = False,
) -> Callable:
    """The span / hybrid program: k (near-)quiescent ticks as one scan.

    ``hybrid=True`` derives the Warp 2.0 near-quiescent program (strict
    span + sterile anti-entropy — ``plan(graph, "hybrid")``);
    ``masked=True`` makes the span length a traced ``k_m <= k`` so the
    fleet runner can vmap one program over per-member horizons."""
    from kaboodle_tpu.phasegraph.span import make_leap_fn

    return make_leap_fn(cfg, k, constrain=constrain, hybrid=hybrid, masked=masked)
