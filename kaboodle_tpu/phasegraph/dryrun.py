"""``python -m kaboodle_tpu phasegraph`` — derive all engines, diff vs dense.

The CI end-to-end proof of the phase-graph contract (ISSUE 7): every
executable engine the planner derives from the one op graph — dense
(full+fused dispatch), standalone fused, chunked (row-blocked), sharded,
fleet (vmapped), warp leap — is built at toy N, runs real ticks, and its
outputs are diffed bit-for-bit against the dense derivation:

- chunked / sharded: one faulty tick from the same state + inputs;
- fleet: member 0 of a vmapped tick vs a standalone dense tick of the same
  member state (the full-program-only derivation vs the dispatched build —
  exactly the exactness argument derive.make_fleet_tick documents);
- fused: one steady tick on a converged mesh vs the dispatched build (the
  dispatch predicate must route that tick to the same values);
- warp: a k-tick leap vs k dense fault-free ticks on a quiescent mesh.

Any mismatch exits nonzero. This is a *dryrun* (wiring + exactness at toy
scale, seconds on CPU); the at-scale exactness contracts live in the parity
suites (tests/test_kernel_parity.py, test_warp.py, test_fleet.py,
test_fuzz_parity.py) and the measured numbers in ``bench.py --fastpath-ab``.
"""

from __future__ import annotations

import argparse
import json
import sys


def _state_equal(a, b) -> bool:
    # The canonical predicate (kaboodle_tpu.profiling.leaf_equal) — the
    # same definition of "bit-exact" the bench A/B lanes gate on.
    from kaboodle_tpu.profiling import state_equal

    return state_equal(a, b)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="kaboodle_tpu phasegraph",
        description="build every phase-graph-derived engine at toy N, run "
                    "one tick each, diff against the dense derivation",
    )
    p.add_argument("--n", type=int, default=32, help="toy mesh size")
    p.add_argument("--ensemble", type=int, default=4, help="fleet width E")
    p.add_argument("--leap", type=int, default=4, help="warp span length k")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.fleet.core import fleet_idle_inputs, init_fleet
    from kaboodle_tpu.parallel.mesh import make_mesh
    from kaboodle_tpu.phasegraph import build_graph, plan
    from kaboodle_tpu.phasegraph.derive import (
        make_chunked_tick,
        make_dense_tick,
        make_fleet_tick,
        make_fused_tick,
        make_sharded_tick,
        make_warp_leap,
    )
    from kaboodle_tpu.sim.state import idle_inputs, init_state

    n, e, k = args.n, args.ensemble, args.leap
    cfg = SwimConfig(deterministic=True)
    results: dict[str, bool] = {}

    # The plans, straight from the planner (the op/pass summary CI logs).
    graph = build_graph(cfg, faulty=True)
    for mode in ("full", "fused"):
        prog = plan(graph, mode)
        print(f"phasegraph: plan {mode}: {len(prog.passes)} passes, "
              f"{len(prog.pruned)} pruned, ops="
              f"{','.join(prog.op_names())}")

    # dense (the reference arm) — one faulty tick from a cold boot state.
    st = init_state(n, seed=0)
    idle = idle_inputs(n)
    dense = make_dense_tick(cfg, faulty=True)
    st_d, m_d = jax.jit(dense)(st, idle)

    # chunked: same tick, row-blocked layout.
    chunked = make_chunked_tick(cfg, faulty=True, block=n // 2)
    st_c, m_c = jax.jit(chunked)(st, idle)
    results["chunked"] = _state_equal(st_d, st_c) and _state_equal(m_d, m_c)

    # sharded: same tick, carry constrained onto the device mesh.
    sharded = make_sharded_tick(cfg, make_mesh(len(jax.devices())), faulty=True)
    st_s, m_s = jax.jit(sharded)(st, idle)
    results["sharded"] = _state_equal(st_d, st_s) and _state_equal(m_d, m_s)

    # fleet: member 0 of the vmapped tick vs a standalone dense tick of the
    # same member state (the full-only derivation vs the dispatched build).
    fleet = init_fleet(n, e)
    ftick = make_fleet_tick(cfg, faulty=True)
    fm, _ = jax.jit(ftick)(fleet.mesh, fleet_idle_inputs(n, e))
    m0 = jax.tree.map(lambda x: x[0], fleet.mesh)
    st_m0, _ = jax.jit(dense)(m0, idle)
    results["fleet"] = _state_equal(jax.tree.map(lambda x: x[0], fm), st_m0)

    # fused: one steady tick on a converged mesh — the standalone 2-pass
    # program must equal the dispatched build routing the same tick.
    conv = init_state(n, seed=1, ring_contacts=n - 1, announced=True)
    fused = make_fused_tick(cfg, faulty=True)
    st_f, m_f = jax.jit(fused)(conv, idle)
    st_dd, m_dd = jax.jit(dense)(conv, idle)
    results["fused"] = _state_equal(st_dd, st_f) and _state_equal(m_dd, m_f)

    # warp: a k-tick leap vs k dense fault-free ticks on the quiescent mesh.
    leap = make_warp_leap(cfg, k)
    st_w = jax.jit(leap)(conv)
    st_k = conv
    dense_ff = make_dense_tick(cfg, faulty=False)
    step = jax.jit(dense_ff)
    for _ in range(k):
        st_k, _ = step(st_k, idle)
    results["warp"] = _state_equal(st_w, st_k)

    # hybrid: the Warp 2.0 near-quiescent span program. On a strictly
    # quiescent mesh it must degenerate bit-exactly to the same k dense
    # ticks (no anti-entropy candidate ever matches); the masked build at
    # a traced k_m == k must agree too, and k_m == 0 must be the identity.
    hybrid = make_warp_leap(cfg, k, hybrid=True)
    results["hybrid"] = _state_equal(jax.jit(hybrid)(conv), st_k)
    masked = jax.jit(make_warp_leap(cfg, k, hybrid=True, masked=True))
    results["hybrid_masked"] = _state_equal(
        masked(conv, jnp.int32(k)), st_k
    ) and _state_equal(masked(conv, jnp.int32(0)), conv)

    ok = all(results.values())
    for name, good in results.items():
        print(f"phasegraph: {name:8s} vs dense: {'bit-exact' if good else 'MISMATCH'}")
    print(json.dumps({
        "metric": "phasegraph_dryrun",
        "n": n, "ensemble": e, "leap": k,
        "engines": {name: ("ok" if good else "mismatch")
                    for name, good in results.items()},
        "ok": ok,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
