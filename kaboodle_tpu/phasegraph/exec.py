"""The executable tick: the phase graph's dense (full + fused) programs.

This module holds the ONE implementation of the SWIM tick's phase ops —
every other engine derives from it (derive.py): the chunked twin re-lays
the same passes over row blocks (blocked.py), the fleet tick vmaps it, the
sharded tick wraps it in GSPMD constraints, and the warp leap executes the
planner's span program (span.py). ``sim/kernel.py`` is a shim.

``make_tick_fn`` composes the two planned programs for a build
(plan.py): the FULL program (one pass per cond-gated phase — the
pass-count-bound shape, ~9 HBM sweep-equivalents on active ticks) and the
FUSED program (the 2-pass steady-tick program: one draw pass + one update
pass whose masks fold into a single elementwise where chain, ~3
sweep-equivalents). The per-tick dispatch predicate between them is
DERIVED from the planner: ``plan(graph, "fused").pred_terms`` names the
activity symbols (``any_a2``, ``any_join``) whose disjunction guarantees
every pruned op is inactive, so the fused program is taken exactly when it
is bit-exact. Since the phase-graph refactor the dispatch applies to BOTH
fault-free and faulty builds (churn and the delivery gate are prologue
ops, shared by the two branches; drop/partition/kill/revive traffic flows
through the fused update's edge gathers unchanged) — quiet faulty ticks,
the overwhelming majority of every fault scenario's span, no longer pay
the full path's cond boundaries.

The protocol semantics below are the TPU-native re-expression of the
reference's loop
(kaboodle.rs:746-786): where the reference runs one tokio task per OS process
per peer, here all N peers advance together, one tick per kernel invocation,
with every per-peer branch turned into a masked tensor op. The kernel is the
executable twin of :class:`kaboodle_tpu.oracle.lockstep.LockstepMesh` — the
round structure below mirrors its docstring, and
``tests/test_kernel_parity.py`` pins exact state equality per tick in
deterministic mode.

Round structure per tick t (lockstep.py round letters):
  A  active phase (kaboodle.rs:746-757): Join broadcasts, suspicion handling
     (escalation to indirect ping / removals), random ping, manual pings.
  B  broadcast delivery: Join inserts at every receiver + join-response
     KnownPeers queued (kaboodle.rs:256-311).
  1  call 1: deliver active-phase Pings + PingRequests; Acks + proxy Pings
     queued (kaboodle.rs:513-545).
  2  call 2: deliver direct Acks, proxy Pings, join responses; target Acks
     queued; gossip-learned peers inserted back-dated (Q6, kaboodle.rs:448-472).
  3  call 3: deliver targets' Acks to proxies; forwarded Acks queued to the
     curious suspectors (kaboodle.rs:418-447).
  4  call 4: deliver forwarded Acks.
  G  anti-entropy: each peer resolves <= 1 KnownPeersRequest (deviation D2,
     kaboodle.rs:707-740); request + filtered reply resolve within the tick.

Within each delivery call, all sender-marks (Q1: any inbound datagram marks
its sender Known(now), kaboodle.rs:408-415) apply before any dispatch — the
same serialization the lockstep oracle implements with its two-pass
``_deliver_round``.

Documented deviations beyond the oracle's D1-D3 (see PARITY.md):
- D5: when a join-response share exceeds ``max_share_peers``, the kernel caps
  to the lowest-index members of the responder's start-of-round map (the
  oracle trims the exact per-joiner snapshot). Inactive when N <= cap.
- D6: in random (non-deterministic) mode, the join-reply Bernoulli probability
  uses the exact sequential map size (a cumulative sum over joiners, matching
  kaboodle.rs:344-353 processing order), but the random draws themselves are
  counter-based `jax.random`, so random-mode parity with the oracle is
  distributional, not samplewise.

Memory/layout notes (TPU):
- ``state`` int8 and ``timer`` int32 (int16 in lean mode) are the only
  mandatory [N, N] residents; every
  message "queue" is O(N) or O(N·k) (the per-tick fan-outs are bounded by the
  protocol: 1 ping, k=3 ping-reqs, 1 anti-entropy request per peer).
- The only O(N^3) work is the join-response gossip union (and, in
  intended-semantics mode, the Failed-broadcast delivery), expressed as int8
  matmuls (MXU-friendly) and skipped via ``lax.cond`` on ticks with no Join
  broadcast (resp. no removal).
- Everything is static-shaped; the whole tick jits into one XLA program and
  rolls under ``lax.scan`` (runner.py).
- ``fast_path`` builds (faulty or not) compile a TWO-BRANCH tick selected
  by ``lax.cond`` per tick: ticks where the planner-derived predicate is
  False (no Join broadcast, no suspicion activity — the overwhelming
  majority of boot, steady state, and calm recovery) take ``_fast``, the
  fused program, whose delivery masks all derive from O(N) vectors so the
  [N, N] work collapses to the stats read, the eligibility/draw read, and
  one composed write chain; everything else takes ``_rest``, the full
  path. The split exists because the round-4 on-TPU phase decomposition
  (PERF.md) showed the full path's per-phase ``cond`` boundaries force ~9
  materialized HBM sweeps where these ticks need ~3.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.ops.fused_fp import fused_fp_count, pallas_supported
from kaboodle_tpu.ops.fused_oldest_k import fused_oldest_k, pallas_oldest_k_supported
from kaboodle_tpu.ops.fused_suspicion import fused_suspicion, pallas_suspicion_supported
from kaboodle_tpu.ops.hashing import fingerprint_agreement, peer_record_hash
from kaboodle_tpu.ops.sampling import (
    bernoulli_matrix,
    broadcast_reply_prob,
    choose_among_candidates,
    choose_k_members,
    choose_one_of_oldest_k,
)
from kaboodle_tpu.phasegraph.graph import build_graph
from kaboodle_tpu.phasegraph import rng as pg_rng
from kaboodle_tpu.phasegraph.plan import plan
from kaboodle_tpu.sim.state import MeshState, TickInputs, TickMetrics
from kaboodle_tpu.spec import KNOWN, WAITING_FOR_INDIRECT_PING, WAITING_FOR_PING
from kaboodle_tpu.telemetry.counters import (
    RECORD_BYTES,
    ProtocolCounters,
    TickTelemetry,
)

_I32MAX = jnp.iinfo(jnp.int32).max


def _bool_matmul(a: jax.Array, b: jax.Array) -> jax.Array:
    """Boolean OR-matmul: (a @ b) > 0 with int8 inputs, int32 accumulation.

    int8 x int8 -> int32 rides the MXU on TPU (v5e runs int8 at 2x bf16)."""
    acc = jax.lax.dot_general(
        a.astype(jnp.int8),
        b.astype(jnp.int8),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return acc > 0


def _scatter_or(dst: jax.Array, rows: jax.Array, cols: jax.Array, val: jax.Array) -> jax.Array:
    """dst[rows, cols] |= val with -1-safe indices (val must be False there).

    XLA lowers a dynamic-index scatter to a sequential per-update loop on
    TPU, so this is reserved for escalation-gated paths (which are compiled
    out of steady-state ticks); the per-tick hot marks use the dense one-hot
    forms below, which fuse into their consuming ``where`` passes."""
    return dst.at[jnp.clip(rows, 0), jnp.clip(cols, 0)].max(val)


def _col_mark(idx: jax.Array, tgt: jax.Array, val: jax.Array) -> jax.Array:
    """mark[d, s] = (tgt[s] == d) & val[s] — sender s's datagram lands at its
    target. tgt == -1 never matches (idx >= 0), so no clipping is needed."""
    return (idx[:, None] == tgt[None, :]) & val[None, :]


def _row_mark(idx: jax.Array, tgt: jax.Array, val: jax.Array) -> jax.Array:
    """mark[s, d] = (tgt[s] == d) & val[s] — row s marks its own target."""
    return (idx[None, :] == tgt[:, None]) & val[:, None]


def _gather_edge(mat: jax.Array, rows: jax.Array, cols: jax.Array) -> jax.Array:
    """mat[rows, cols] with -1-safe (clipped) indices."""
    return mat[jnp.clip(rows, 0), jnp.clip(cols, 0)]


# The op bodies this module implements, by program region: the shared
# prologue runs before the dispatch, ``_rest`` executes the full program's
# tail in graph order, ``_fast`` executes the fused program's two passes
# (the A3 draw, then the folded update chain). ``_check_programs`` pins
# implementation coverage to the planner's output at build time, so a new
# graph op without a body here (or a planner regrouping the fold illegally)
# is a loud build error, never a silent semantic drift.
_PROLOGUE_OPS = frozenset(
    ("rng_streams", "churn", "delivery_gate", "row_stats", "join_gate",
     "manual_targets")
)
_FULL_TAIL_OPS = frozenset(
    ("suspicion", "probe_draw", "join_insert", "failed_delivery",
     "join_replies", "call1", "call2", "calls34", "anti_entropy",
     "counters", "finish")
)
_FUSED_DRAW_OPS = frozenset(("probe_draw",))
_FUSED_UPDATE_OPS = frozenset(
    ("call1", "call2", "anti_entropy", "counters", "finish")
)
_PRED_TERMS = frozenset(("any_a2", "any_join"))


def _check_programs(graph, full_prog, fused_prog) -> None:
    names = {op.name for op in graph.ops}
    unimplemented = names - _PROLOGUE_OPS - _FULL_TAIL_OPS
    if unimplemented:
        raise NotImplementedError(
            f"graph ops without exec bodies: {sorted(unimplemented)}"
        )
    for op in graph.prologue:
        if op.name not in _PROLOGUE_OPS:
            raise NotImplementedError(f"{op.name}: no prologue body")
    draw, update = fused_prog.tail
    if not (set(draw.op_names) <= _FUSED_DRAW_OPS
            and set(update.op_names) <= _FUSED_UPDATE_OPS):
        raise NotImplementedError(
            "fused plan groups ops this module does not fold: "
            f"draw={draw.op_names} update={update.op_names}"
        )
    if not set(fused_prog.pred_terms) <= _PRED_TERMS:
        raise NotImplementedError(
            f"unknown dispatch pred terms {fused_prog.pred_terms}"
        )
    if not set(graph.cut_labels) <= {"A", "c1", "c2", "c34", "G"}:
        raise NotImplementedError(f"unknown cut labels {graph.cut_labels}")


def make_tick_fn(
    cfg: SwimConfig,
    faulty: bool = True,
    _cut: str | None = None,
    telemetry: bool = False,
    program: str | None = None,
) -> Callable[[MeshState, TickInputs], tuple[MeshState, TickMetrics]]:
    """Build the jittable tick function for a given protocol config.

    ``cfg`` is baked in (static): protocol constants fold into the compiled
    program. ``faulty=False`` compiles out the churn/partition/drop paths for
    the fault-free fast path (bench configs 2 and 4).

    ``telemetry=True`` compiles the telemetry-plane build: the tick returns
    ``(state, TickTelemetry(metrics, counters, fp))`` instead of
    ``(state, TickMetrics)``, where ``counters`` is the
    :class:`~kaboodle_tpu.telemetry.counters.ProtocolCounters` pytree of
    this tick's protocol reductions and ``fp`` the end-of-tick per-member
    fingerprint vector (the flight recorder's digest plane). Every counter
    is a pure derived value of masks/states the tick already computes: the
    state trajectory is bit-identical with telemetry on or off, and the
    ``telemetry=False`` program is byte-for-byte today's (the flag only
    *adds* outputs). Counter semantics are pinned against the lockstep
    oracle's tallies by the counter-parity fuzz (tests/test_fuzz_parity.py).

    ``_cut`` is a perf-probe hook (scripts/tpu_stage_probe.py), not protocol
    surface: a static phase label ("A", "c1", "c2", "c34", "G") that truncates
    the compiled full path right after that phase, returning the partial state
    with zeroed metrics. Timing successive cuts under one scan isolates each
    phase's *in-context* cost — isolated stage microbenches mispredict what
    XLA fuses inside the real program. ``None`` (the default, and the only
    value any production path uses) compiles the normal tick; any other value
    also disables the fast/slow split so the probe times the full path.

    ``program`` selects which planned program to compile: ``None`` (the
    default, the production build) compiles the per-tick dispatch between
    the two; ``"full"`` compiles the full multi-pass program alone
    (equivalent to ``cfg.fast_path=False``); ``"fused"`` compiles the
    2-pass fused program ALONE, with no dispatch guard — callers own the
    precondition that every tick satisfies the dispatch predicate (no Join
    broadcast, no suspicion activity), which bench's ``--fastpath-ab``
    checks by bit-comparing against the dispatched build. The standalone
    fused build exists for the A/B lane and the graftscan registry (its
    pass structure is auditable in isolation); production always ships the
    dispatched build.
    """

    det = cfg.deterministic
    if _cut not in (None, "A", "c1", "c2", "c34", "G"):
        # A typoed label would silently compile the normal full tick and a
        # stage probe would bank a full-tick time as a phase-cut measurement.
        raise ValueError(f"unknown _cut label {_cut!r}")
    if telemetry and _cut is not None:
        # _cut returns partial state with zeroed metrics — counters over a
        # truncated tick would be meaningless numbers with real-looking names.
        raise ValueError("telemetry=True is incompatible with a _cut probe")
    if program not in (None, "full", "fused"):
        raise ValueError(f"unknown program {program!r}")
    if program == "fused" and _cut is not None:
        raise ValueError("_cut probes truncate the full program only")

    # The declarative twin of this module: the op graph for this build and
    # the two planned programs the dispatch below composes. The cut labels
    # and the dispatch predicate are DERIVED from the plan — exec.py's only
    # private knowledge is the op bodies themselves (keyed by op name; a
    # graph op without a body here fails the build-time check below, so the
    # metadata cannot drift from the implementation).
    graph = build_graph(cfg, faulty=faulty, telemetry=telemetry)
    full_prog = plan(graph, "full")
    fused_prog = plan(graph, "fused")
    _check_programs(graph, full_prog, fused_prog)

    # The closure is traced from ANOTHER module (runner.simulate's lax.scan /
    # the jax.jit call sites in tests and scripts), which per-module
    # reachability cannot see — the pragma keeps the KB2xx tracer rules live
    # on the hottest function in the repo. The named scope labels the tick's
    # ops in jax.profiler captures (name-stack metadata only — numerics and
    # compiled-program identity are unchanged).
    @jax.named_scope("kaboodle:tick")
    def tick(st: MeshState, inp: TickInputs) -> tuple[MeshState, TickMetrics]:  # graftlint: traced
        n = st.state.shape[-1]
        t = st.tick
        idx = jnp.arange(n, dtype=jnp.int32)
        eye = idx[:, None] == idx[None, :]
        # Counter-keyed draw rows (Warp 3.0): each key is a pure function of
        # (st.key, t, STREAM_TICK_*) — no chain advances, so the key plane
        # is constant and any tick's randomness replays from checkpointable
        # state alone (rng.py; keyscope classes these counter_keyed).
        key_proxy, key_ping, key_bern, key_drop = pg_rng.tick_draw_keys(st.key, t)
        key_next = st.key

        S, T = st.state, st.timer
        # Timer writes must stay in the timer's dtype (int32 default, int16
        # in the memory-lean mode — see MEMORY_PLAN.md): a bare `t` in a
        # where() would promote the whole [N, N] tensor to int32 and break
        # the scan carry. Comparisons (t - T) still compute in int32.
        tT = t.astype(T.dtype)
        TMAX = int(jnp.iinfo(T.dtype).max)
        lat, idv = st.latency, st.id_view
        has_lat = lat is not None
        has_idv = idv is not None
        alive, never_b, last_b = st.alive, st.never_broadcast, st.last_broadcast
        # Every in-tick identity write is the sender's *current* word — exactly
        # what the envelope would carry this period (structs.rs:77-83).
        id_row = st.identity[None, :]

        # ---- churn: silent kill (Q8) + revive-with-reset (lockstep.revive) ----
        if faulty:
            alive = (alive & ~inp.kill) | inp.revive
            rv = inp.revive
            S = jnp.where(rv[:, None], jnp.where(eye, jnp.int8(KNOWN), jnp.int8(0)), S)
            T = jnp.where(rv[:, None], jnp.where(eye, tT, jnp.zeros((), T.dtype)), T)
            if has_lat:
                lat = jnp.where(rv[:, None], jnp.nan, lat)
            if has_idv:
                idv = jnp.where(
                    rv[:, None], jnp.where(eye, id_row, jnp.uint32(0)), idv
                )
            never_b = never_b | rv
        else:
            rv = jnp.zeros((n,), dtype=bool)

        # ---- delivery gate for every message this tick ------------------------
        # ok[s, d]: sender alive, receiver alive, same partition group, not
        # dropped. The lockstep oracle's ``delivery_ok`` + aliveness checks.
        # In fault-free mode the gate factors as alive[s] & alive[d], so no
        # [N, N] matrix exists: edge checks are O(1) vector gathers
        # (``ok_edge``) and the full-matrix consumers (join delivery) use the
        # outer-product expression (``ok_outer``), which fuses.
        if faulty:
            ok = alive[:, None] & alive[None, :]
            ok &= inp.partition[:, None] == inp.partition[None, :]
            if inp.drop_ok is not None:
                ok &= inp.drop_ok
            else:
                # The [N, N] uniform draw is the single most expensive op of a
                # drop-free faulty tick — gate it on the (traced) rate so
                # churn/partition-only scenarios skip the RNG entirely.
                ok = jax.lax.cond(
                    inp.drop_rate > 0,
                    lambda ok: ok
                    & (
                        jax.random.uniform(key_drop, (n, n), dtype=jnp.float32)
                        >= inp.drop_rate
                    ),
                    lambda ok: ok,
                    ok,
                )

            def ok_edge(s, d):
                return _gather_edge(ok, s, d)

            def ok_outer():  # ok[s, d] as a full matrix (join/fail delivery)
                return ok
        else:

            def ok_edge(s, d):
                return alive[jnp.clip(s, 0)] & alive[jnp.clip(d, 0)]

            def ok_outer():
                return alive[:, None] & alive[None, :]

        # ---- Phase-A row stats on the pre-tick snapshot ----------------------
        # (the oracle's handle_suspected_peers iterates a snapshot taken at
        # entry, kaboodle.rs:558-653). The fused path computes the membership
        # count, the timed-out-suspect argmin, and proxy-candidate existence in
        # one Pallas pass over (S, T); the jnp path spells the same formulas
        # out (several fused XLA passes). Nothing writes S/T before the A2
        # apply, so the snapshot is just an alias.
        S0, T0 = S, T
        age0 = t - T0
        use_fused_susp = cfg.use_pallas_suspicion and pallas_suspicion_supported(n)
        if use_fused_susp:
            row_count0, jstar_pre, has_timed, has_cand_pre, wfip_any = fused_suspicion(
                S, T, alive, t - cfg.ping_timeout_ticks
            )
        else:
            # Only what the fast/slow dispatch pred and A1 need is computed
            # here (the fewest sibling reductions over one (S, T) read); the
            # slow-path-only stats — the escalation argmin and the proxy-
            # candidate test — are recomputed inside _rest, off the fast
            # ticks entirely.
            row_count0 = jnp.sum(S > 0, axis=-1, dtype=jnp.int32)
            has_timed = jnp.any(
                alive[:, None] & (S0 == WAITING_FOR_PING) & (
                    age0 >= cfg.ping_timeout_ticks
                ),
                axis=-1,
            )
            wfip_any = jnp.any(
                alive[:, None]
                & (S0 == WAITING_FOR_INDIRECT_PING)
                & (age0 >= cfg.ping_timeout_ticks),
                axis=-1,
            )
            jstar_pre = has_cand_pre = None
        # any(insta_remove) | any(escalate) == any(has_timed): the dispatch
        # pred does not need the has_cand split.
        any_a2 = jnp.any(wfip_any) | jnp.any(has_timed)

        # Q6 insert stamp offset, shared by the join-gossip and anti-entropy
        # reply inserts (0 = the epidemic-boot extension, config.py).
        gossip_backdate = (
            cfg.max_peer_share_age_ticks if cfg.backdate_gossip_inserts else 0
        )
        rec_hash = peer_record_hash(idx.astype(jnp.uint32), st.identity)
        u_row = jnp.broadcast_to(idx.astype(jnp.uint32)[None, :], (n, n))
        INF = jnp.int32(_I32MAX)

        def fp_count(S_now, idv_now):
            """Row fingerprints + membership counts at a point in the tick.

            With identity views, each row hashes the identities it has actually
            seen (engine.fingerprint() over its own records); otherwise the
            global ``rec_hash`` vector (instant-identity fast mode). With
            ``cfg.use_pallas_fp`` the whole pass (member test, hash, masked
            sum, count) runs as one fused Pallas kernel — bit-exact."""
            if cfg.use_pallas_fp and pallas_supported(n):
                return fused_fp_count(S_now, idv_now if has_idv else rec_hash)
            member = S_now > 0
            if has_idv:
                contrib = jnp.where(member, peer_record_hash(u_row, idv_now), jnp.uint32(0))
            else:
                contrib = jnp.where(member, rec_hash[None, :], jnp.uint32(0))
            fp = jnp.sum(contrib, axis=-1, dtype=jnp.uint32)
            return fp, jnp.sum(member, axis=-1, dtype=jnp.int32)

        def apply_marks(S, T, lat, idv, mark):
            """Q1 mark pass for one delivery wave: mark[d, s] == a datagram
            from s reached d this wave. Latency EWMA sampled where the marked
            entry was in a waiting state (kaboodle.rs:789-817, f32 like the
            oracle); identity view refreshed from the envelope."""
            if has_lat:
                waiting = (S == WAITING_FOR_PING) | (S == WAITING_FOR_INDIRECT_PING)
                sample = (t - T).astype(jnp.float32)
                upd = jnp.where(
                    jnp.isnan(lat),
                    sample,
                    jnp.float32(0.8) * sample + jnp.float32(0.2) * lat,
                )
                lat = jnp.where(mark & waiting, upd, lat)
            if has_idv:
                idv = jnp.where(mark, id_row, idv)
            S = jnp.where(mark, jnp.int8(KNOWN), S)
            T = jnp.where(mark, tT, T)
            return S, T, lat, idv

        def apply_marks_delta(S, T, lat, idv, mark):
            """apply_marks + the exact (fp, count) delta the wave causes.

            fp is a wraparound uint32 sum of per-member record-hash words, so
            a wave's effect is an exact additive delta: a marked cell's
            contribution becomes ``rec_hash[j]`` (the mark writes the sender's
            current identity word — ``hash(j, id_row) == rec_hash[j]``), and
            was ``hash(j, idv_old)`` if already a member, else 0. Summed in
            the same modular group as fp_count, so ``fp_before + delta`` is
            bit-equal to recomputing — letting steady-state ticks skip two
            full fingerprint reads (the A/B in PERF.md round 4). Marks never
            remove members, so the count delta is the new-member count.
            """
            member_b = S > 0
            newm = mark & ~member_b
            if has_idv:
                old = jnp.where(
                    member_b, peer_record_hash(u_row, idv), jnp.uint32(0)
                )
                dfp = jnp.sum(
                    jnp.where(mark, rec_hash[None, :] - old, jnp.uint32(0)),
                    axis=-1,
                    dtype=jnp.uint32,
                )
            else:
                dfp = jnp.sum(
                    jnp.where(newm, rec_hash[None, :], jnp.uint32(0)),
                    axis=-1,
                    dtype=jnp.uint32,
                )
            dn = jnp.sum(newm, axis=-1, dtype=jnp.int32)
            S, T, lat, idv = apply_marks(S, T, lat, idv, mark)
            return S, T, lat, idv, dfp, dn

        def _early_return(S, T, lat, idv):
            """_cut exit: partial state, zeroed metrics (same pytree shape)."""
            partial = MeshState(
                state=S, timer=T, alive=alive, identity=st.identity,
                never_broadcast=never_b, last_broadcast=last_b,
                kpr_partner=st.kpr_partner, kpr_fp=st.kpr_fp, kpr_n=st.kpr_n,
                tick=t + 1, key=key_next, latency=lat, id_view=idv,
            )
            metrics = TickMetrics(
                messages_delivered=jnp.zeros((), jnp.int32),
                converged=jnp.bool_(False),
                agree_fraction=jnp.zeros((), jnp.float32),
                mean_membership=jnp.zeros((), jnp.float32),
                fingerprint_min=jnp.zeros((), jnp.uint32),
                fingerprint_max=jnp.zeros((), jnp.uint32),
            )
            return partial, metrics

        # ================= A. Active phase (kaboodle.rs:746-757) ==============
        # A1: maybe_broadcast_join (kaboodle.rs:228-251): first call always
        # broadcasts; afterwards only while lonely and rebroadcast-interval old.
        # With broadcasts disabled (gossip boot) the whole block compiles out.
        if cfg.join_broadcast_enabled:
            lonely = row_count0 <= 1
            join_b = alive & (
                never_b | (lonely & ((t - last_b) >= cfg.rebroadcast_interval_ticks))
            )
            last_b = jnp.where(join_b, t, last_b)
            never_b = never_b & ~join_b
            any_join = jnp.any(join_b)
        else:
            join_b = jnp.zeros((n,), dtype=bool)
            any_join = jnp.bool_(False)

        # A4: manual pings (ping_addrs, kaboodle.rs:550-556): no state change at
        # the sender. Self-pings and out-of-range targets are dropped at the
        # transport (deviation D8, matching LockstepMesh._deliver_round's
        # ``0 <= dest < n`` guard — without this, clamped gathers would fake
        # an exchange with peer N-1). Shared by both tick branches.
        man_tgt = jnp.where(
            alive & (inp.manual_target != idx) & (inp.manual_target < n),
            inp.manual_target,
            -1,
        )

        def _anti_entropy(S, T, lat, idv, partner, del_kpr, del_rep, fp_g, n_g):
            """Call-G apply (kaboodle.rs:707-740), shared by both branches.

            Requests only flow while fingerprints disagree, so every call-G
            [N, N] pass — the marks, the share gather/insert, and the final
            fingerprint read — is gated on a request actually being delivered:
            on a converged steady-state tick nothing in here touches the
            state and fp_f is exactly fp_g."""

            def _g_apply(S, T, lat, idv):
                mark_g = _col_mark(idx, partner, del_kpr)  # partner marks requester
                S, T, lat, idv = apply_marks(S, T, lat, idv, mark_g)

                # Filtered reply share (kaboodle.rs:483-501): Known peers heard
                # from strictly within MAX_PEER_SHARE_AGE, excluding self (and
                # the requester — enforced receiver-side as j != i, same
                # effect). Computed post-marks, matching the oracle's two-pass
                # delivery. Not capped (Q12). The share snapshot is taken
                # before the requester-marks-partner write below (the oracle's
                # two-pass order): a partner's own fresh call-G marks must not
                # leak into the rows it shares this tick.
                S_share, T_share = S, T

                def _share_f():
                    return (S_share == KNOWN) & ~eye & (
                        (t - T_share) < cfg.max_peer_share_age_ticks
                    )

                if telemetry:
                    # Records in the replies SENT this tick: a partner answers
                    # every delivered request (del_kpr gates the send, not
                    # del_rep — the reply's own delivery may still drop), and
                    # the oracle's share additionally excludes the requester,
                    # subtracted per edge so counts match its share lists.
                    share_t = _share_f()
                    share_cnt = jnp.sum(share_t, axis=-1, dtype=jnp.int32)
                    ae_records = jnp.sum(
                        jnp.where(
                            del_kpr,
                            share_cnt[jnp.clip(partner, 0)]
                            - _gather_edge(share_t, partner, idx).astype(jnp.int32),
                            0,
                        ),
                        dtype=jnp.int32,
                    )
                mark_rep = _row_mark(idx, partner, del_rep)  # requester marks partner
                S = jnp.where(mark_rep, jnp.int8(KNOWN), S)
                T = jnp.where(mark_rep, tT, T)

                def _kpr_reply_insert(S, T, idv):
                    share_f = share_t if telemetry else _share_f()
                    srow = share_f[jnp.clip(partner, 0)]  # [N, N] gathered partner rows
                    rep_ins = del_rep[:, None] & srow & ~eye & ~(S > 0)
                    S2 = jnp.where(rep_ins, jnp.int8(KNOWN), S)
                    T2 = jnp.where(rep_ins, tT - gossip_backdate, T)
                    if has_idv:
                        # The reply carries (addr, identity) records
                        # (structs.rs:110); identity words resolve to the
                        # peers' current identities (D-ID1, like the
                        # join-gossip insert in _rest). Without this, a row
                        # re-filled after a revive keeps placeholder words and
                        # its fingerprint can never agree.
                        idv = jnp.where(rep_ins, id_row, idv)
                    return S2, T2, idv

                S, T, idv = jax.lax.cond(
                    jnp.any(del_rep),
                    _kpr_reply_insert,
                    lambda S, T, idv: (S, T, idv),
                    S, T, idv,
                )
                fp_f, n_f = fp_count(S, idv)
                if telemetry:
                    return S, T, lat, idv, fp_f, n_f, ae_records
                return S, T, lat, idv, fp_f, n_f

            if telemetry:
                return jax.lax.cond(
                    jnp.any(del_kpr),
                    _g_apply,
                    lambda S, T, lat, idv: (
                        S, T, lat, idv, fp_g, n_g, jnp.int32(0)
                    ),
                    S, T, lat, idv,
                )
            return jax.lax.cond(
                jnp.any(del_kpr),
                _g_apply,
                lambda S, T, lat, idv: (S, T, lat, idv, fp_g, n_g),
                S, T, lat, idv,
            )

        def _ae_phase01(fp_g, n_g, fp1, n1, del_ack, del_ack_man, ping_tgt):
            """Anti-entropy candidate phases 0-1, shared by both branches
            (kaboodle.rs:707-740 take_sync_request order). Phase 0: last
            tick's KnownPeersRequest senders (their candidates were recorded
            before this tick's acks arrived); phase 1: this tick's call-2
            direct + manual acks, sender == acked peer. Returns
            ``(prio0, peer0, prio1, peer1)``; phases 2-3 are escalation-borne
            and exist only in the full path."""
            m0 = (st.kpr_partner[None, :] == idx[:, None]) & alive[:, None] & ~rv[:, None]
            match0 = m0 & (st.kpr_fp[None, :] != fp_g[:, None]) & (
                n_g[:, None] <= st.kpr_n[None, :]
            )
            prio0 = jnp.min(jnp.where(match0, idx[None, :], INF), axis=-1)
            peer0 = prio0  # sender == candidate peer for KPR candidates

            base1 = jnp.int32(n)
            m_d = del_ack & (fp1[jnp.clip(ping_tgt, 0)] != fp_g) & (
                n_g <= n1[jnp.clip(ping_tgt, 0)]
            )
            m_m = del_ack_man & (fp1[jnp.clip(man_tgt, 0)] != fp_g) & (
                n_g <= n1[jnp.clip(man_tgt, 0)]
            )
            prio_d = jnp.where(m_d, base1 + ping_tgt, INF)
            prio_m = jnp.where(m_m, base1 + man_tgt, INF)
            prio1 = jnp.minimum(prio_d, prio_m)
            peer1 = jnp.where(prio_d <= prio_m, ping_tgt, man_tgt)
            return prio0, peer0, prio1, peer1

        def _finish(
            S, T, lat, idv, kpr_partner_new, fp_g, n_g, fp_f, n_f, msgs,
            counters=None,
        ):
            """Metrics + next-state assembly, shared by both branches.

            In telemetry builds ``counters`` carries the branch's event
            counts; the two pre/post-state counters — suspicions refuted
            (WaitingForIndirectPing at S0 -> Known now) and armed timers
            (waiting cells in alive rows at tick end) — are filled in here,
            where both snapshots are in scope, and the per-member ``fp_f``
            vector rides out as the flight-recorder digest plane."""
            converged, fpa_min, fpa_max, n_alive = fingerprint_agreement(
                alive, fp_f
            )
            agree = jnp.sum(alive & (fp_f == fpa_min), dtype=jnp.int32)

            new_state = MeshState(
                state=S,
                timer=T,
                alive=alive,
                identity=st.identity,
                never_broadcast=never_b,
                last_broadcast=last_b,
                kpr_partner=kpr_partner_new,
                kpr_fp=fp_g,
                kpr_n=n_g,
                tick=t + 1,
                key=key_next,
                latency=lat,
                id_view=idv,
            )
            metrics = TickMetrics(
                messages_delivered=msgs,
                converged=converged,
                agree_fraction=agree.astype(jnp.float32) / jnp.maximum(n_alive, 1),
                # f32 accumulation: an int32 sum wraps once alive x members
                # exceeds 2^31 (N > ~46,341 converged) — reachable now that
                # the chunked twin executes N=65,536 ticks; keep the two
                # kernels' metrics bit-comparable.
                mean_membership=jnp.sum(jnp.where(alive, n_f, 0).astype(jnp.float32))
                / jnp.maximum(n_alive, 1),
                fingerprint_min=fpa_min,
                fingerprint_max=fpa_max,
            )
            if telemetry:
                counters = dataclasses.replace(
                    counters,
                    suspicions_refuted=jnp.sum(
                        (S0 == WAITING_FOR_INDIRECT_PING) & (S == KNOWN),
                        dtype=jnp.int32,
                    ),
                    armed_timers=jnp.sum(
                        alive[:, None]
                        & ((S == WAITING_FOR_PING) | (S == WAITING_FOR_INDIRECT_PING)),
                        dtype=jnp.int32,
                    ),
                )
                return new_state, TickTelemetry(
                    metrics=metrics, counters=counters, fp=fp_f
                )
            return new_state, metrics

        def _rest(S=S, T=T, lat=lat, idv=idv):
            """The full program's tail: A2 suspicion handling onward, one
            pass per cond-gated phase (plan(graph, "full")). Taken by every
            tick where the planner-derived dispatch predicate fires (a Join
            broadcast or suspicion activity — faulty or not), and by every
            tick of ``fast_path=False`` / ``program="full"`` builds. The
            default args freeze the post-churn/post-A1 tensors."""
            # Slow-path-only phase-A stats (kaboodle.rs:558-653), recomputed
            # here from the same pre-tick snapshot so fast ticks never pay
            # for them. D1: escalate exactly one — the oldest timed-out
            # WaitingForPing entry, ties toward lower index; proxy candidates
            # are Known peers other than self (kaboodle.rs:595-605; the
            # suspect itself is WaitingForPing, excluded).
            if use_fused_susp:
                jstar, has_cand = jstar_pre, has_cand_pre
            else:
                timed_wfp = alive[:, None] & (S0 == WAITING_FOR_PING) & (
                    age0 >= cfg.ping_timeout_ticks
                )
                tsel = jnp.where(timed_wfp, T0, TMAX)
                min_t = jnp.min(tsel, axis=-1)
                jstar_mask = timed_wfp & (T0 == min_t[:, None])
                jstar = jnp.min(jnp.where(jstar_mask, idx[None, :], _I32MAX), axis=-1)
                jstar = jnp.where(has_timed, jstar, -1).astype(jnp.int32)
                has_cand = jnp.any((S0 == KNOWN) & ~eye, axis=-1)
            escalate = has_timed & has_cand
            insta_remove = has_timed & ~has_cand  # no proxies -> drop (:599-605)
            jstar_cell = idx[None, :] == jstar[:, None]
            any_rem = jnp.any(wfip_any) | jnp.any(insta_remove)

            # A2: handle_suspected_peers (kaboodle.rs:558-653) on the pre-tick
            # snapshot. Escalations are rare (none at all in fault-free steady
            # state), so the [N, N] gumbel + top_k proxy draw is gated; the
            # zero indices in the skip branch are inert because proxies_valid
            # is all-False then. The skip branch derives its shapes from the
            # draw itself so the two branches cannot drift apart.
            def _draw_proxies():
                # The candidate matrix lives only inside this rare branch (the
                # fused-suspicion path never materializes it outside).
                known_cand = (S0 == KNOWN) & ~eye
                return choose_k_members(known_cand, cfg.num_indirect_ping_peers, key_proxy, det)

            proxies, proxies_valid = jax.lax.cond(
                jnp.any(escalate),
                _draw_proxies,
                lambda: jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype), jax.eval_shape(_draw_proxies)
                ),
            )  # [N, k]
            proxies_valid &= escalate[:, None]

            # WaitingForIndirectPing timeouts -> removal (kaboodle.rs:617-627),
            # judged on the same pre-tick snapshot (an entry escalated this
            # tick is not removed this tick). The whole A2 write phase is a
            # no-op on suspicion-free ticks, so the [N, N] write pass is gated
            # out of them; the removal mask is rebuilt inside each gated
            # consumer so it is never materialized on clean ticks.
            def _a2_rem():
                r = alive[:, None] & (S0 == WAITING_FOR_INDIRECT_PING) & (
                    age0 >= cfg.ping_timeout_ticks
                )
                return r | (insta_remove[:, None] & jstar_cell)

            def _a2_apply(S, T, lat):
                rem = _a2_rem()
                S = jnp.where(rem, jnp.int8(0), S)
                if has_lat:
                    # _remove drops the whole record: a re-learned peer starts
                    # with no latency history (kaboodle.rs:643-644).
                    lat = jnp.where(rem, jnp.nan, lat)
                # The accompanying Failed broadcasts are inert in the reference
                # (quirk Q3) — modeled only in intended-semantics mode below.
                esc_cell = escalate[:, None] & jstar_cell
                S = jnp.where(esc_cell, jnp.int8(WAITING_FOR_INDIRECT_PING), S)
                T = jnp.where(esc_cell, tT, T)
                return S, T, lat

            S, T, lat = jax.lax.cond(
                any_a2, _a2_apply, lambda S, T, lat: (S, T, lat), S, T, lat
            )

            # A3: ping_random_peer (kaboodle.rs:655-703) on the post-A2 state.
            if cfg.use_pallas_oldest_k and pallas_oldest_k_supported(n):
                # Fused path: eligibility + all k rounds in one pass over
                # state/timer tiles — no [N, N] eligibility mask materialized.
                kk = 1 if det else cfg.num_candidate_target_peers
                cand_idx, cand_valid = fused_oldest_k(S, T, alive, kk)
                ping_tgt = choose_among_candidates(cand_idx, cand_valid, key_ping, det)
            else:
                elig = alive[:, None] & (S == KNOWN) & ~eye
                ping_tgt = choose_one_of_oldest_k(
                    T, elig, cfg.num_candidate_target_peers, key_ping, det,
                    method=cfg.oldest_k_method,
                )
            has_ping = ping_tgt >= 0
            tgt_cell = _row_mark(idx, ping_tgt, has_ping)
            S = jnp.where(tgt_cell, jnp.int8(WAITING_FOR_PING), S)
            T = jnp.where(tgt_cell, tT, T)

            if _cut == "A":
                return _early_return(S, T, lat, idv)

            member_a = S > 0
            row_count_a = jnp.sum(member_a, axis=-1, dtype=jnp.int32)

            # ============= B. Broadcast delivery (kaboodle.rs:256-311) ========
            # Join o accepted at r: Jm[r, o]. Receivers insert the joiner as
            # Known(now) with the broadcast identity, preserving a prior
            # latency (kaboodle.rs:284-304, :291-297).
            if cfg.join_broadcast_enabled:
                Jm = join_b[None, :] & ok_outer().T & ~eye  # [receiver, origin]
                is_new_ro = Jm & ~member_a
                S = jnp.where(Jm, jnp.int8(KNOWN), S)
                T = jnp.where(Jm, tT, T)
                if has_idv:
                    idv = jnp.where(Jm, id_row, idv)
            else:
                Jm = jnp.zeros((n, n), dtype=bool)
                is_new_ro = Jm

            if not cfg.faithful_failed_broadcast:
                # Failed(j) broadcast by i, delivered to r (r != j): remove j.
                # Broadcasts resolve in origin order (the lockstep contract),
                # so a same-tick Join(j) wins only against Failed origins
                # i < j; any delivering Failed origin i > j removes j after
                # the re-insert. (When Join(j) was not delivered at r, any
                # Failed origin removes.) O(N^3) matmuls, so skipped on
                # removal-free ticks like the gossip union below.
                def _fail_del(_):
                    rem = _a2_rem()
                    rem_gt = rem & (idx[:, None] > idx[None, :])  # [i, j]: i > j
                    fail_gt = _bool_matmul(ok_outer().T, rem_gt)  # [r, j]
                    fail_any = _bool_matmul(ok_outer().T, rem)  # [r, j]
                    return ~eye & jnp.where(Jm, fail_gt, fail_any)

                fail_del = jax.lax.cond(
                    any_rem,
                    _fail_del,
                    lambda _: jnp.zeros((n, n), dtype=bool),
                    operand=None,
                )
                S = jnp.where(fail_del, jnp.int8(0), S)
                if has_lat:
                    lat = jnp.where(fail_del, jnp.nan, lat)

            # Join responses (kaboodle.rs:333-392): r replies to each *new*
            # joiner with probability max(1, 100-n^2)% where n tracks the
            # sequentially growing map (cumulative inserts in origin order —
            # exact parity), and the accepted replies union into a gossip
            # share at the joiner. The whole block — [N, N] cumsums, the
            # Bernoulli draw, and the two boolean matmuls — is gated on a
            # join actually happening this tick (steady-state ticks have
            # none); the skip branch's all-False outputs are exactly what the
            # formulas produce with join_b all-False. With broadcasts
            # compiled out there is never a join, so the gate is static.
            def _join_replies():
                n_after = row_count_a[:, None] + jnp.cumsum(is_new_ro.astype(jnp.int32), axis=1)
                reply_p = broadcast_reply_prob(n_after)
                bern = bernoulli_matrix(key_bern, reply_p, (n, n), det)
                reply = is_new_ro & bern  # [r, o]
                reply_del_ = reply & ok_outer()  # response unicast r -> o gated like any message

                # Gossip union at joiner o (deliverable in call 2): the reply
                # share is r's map at reply time = start-of-round map +
                # joiners accepted with origin index <= o (the oracle's
                # sequential processing order):
                #   gossip[o, j] = OR_r reply_del[r,o] & (M_a[r,j] | (Jm[r,j] & j<=o))
                def _union():
                    share_base = member_a
                    if cfg.max_share_peers and n > cfg.max_share_peers:
                        # D5: cap to lowest-index members of the start-of-round map.
                        within_cap = (
                            jnp.cumsum(member_a.astype(jnp.int32), axis=1) <= cfg.max_share_peers
                        )
                        share_base = member_a & within_cap
                    term1 = _bool_matmul(reply_del_.T, share_base)  # [o, j]
                    term2 = _bool_matmul(reply_del_.T, Jm)  # [o, j]: OR_r reply_del[r,o] & Jm[r,j]
                    tri = idx[None, :] <= idx[:, None]  # j <= o
                    return term1 | (term2 & tri)

                # The O(N^3) union contracts reply_del: gate it on a reply
                # actually existing, not merely on a Join broadcast — a
                # rebroadcast into an already-full mesh (every survivor is
                # lonely-flagged never_broadcast at a fresh converged init,
                # and every revive re-announces) produces NO new joiners and
                # so no replies, and the dense contraction on all-False
                # operands was the dominant cost of exactly those ticks
                # (the 8,610 s revive tick in SCALE_PROOF.md).
                gossip_ = jax.lax.cond(
                    jnp.any(reply_del_),
                    _union,
                    lambda: jnp.zeros((n, n), dtype=bool),
                )
                if telemetry:
                    # Records in the join-response shares SENT (``reply``, not
                    # ``reply_del_`` — the response unicast may still drop).
                    # Uncapped, the share to joiner o is r's sequential map at
                    # reply time, whose size is exactly ``n_after`` (Q5/D9:
                    # start-of-round map union joins <= o). Over the D5 cap
                    # the share is the capped base plus — uncapped — this
                    # round's joiners not already in it, exactly the oracle's
                    # _share_snapshot_join arithmetic.
                    if cfg.max_share_peers:
                        cap = jnp.int32(cfg.max_share_peers)
                        within_cap_t = (
                            jnp.cumsum(member_a.astype(jnp.int32), axis=1) <= cap
                        )
                        base_c = member_a & within_cap_t
                        clen = jnp.minimum(row_count_a, cap)[:, None] + jnp.cumsum(
                            (Jm & ~base_c).astype(jnp.int32), axis=1
                        )
                        rec_cnt = jnp.where(n_after <= cap, n_after, clen)
                    else:
                        rec_cnt = n_after
                    join_records_ = jnp.sum(
                        jnp.where(reply, rec_cnt, 0), dtype=jnp.int32
                    )
                    return reply_del_, gossip_, join_records_
                return reply_del_, gossip_

            if cfg.join_broadcast_enabled:
                if telemetry:
                    reply_del, gossip, join_records = jax.lax.cond(
                        any_join,
                        _join_replies,
                        lambda: (
                            jnp.zeros((n, n), dtype=bool),
                            jnp.zeros((n, n), dtype=bool),
                            jnp.int32(0),
                        ),
                    )
                else:
                    reply_del, gossip = jax.lax.cond(
                        any_join,
                        _join_replies,
                        lambda: (jnp.zeros((n, n), dtype=bool), jnp.zeros((n, n), dtype=bool)),
                    )
            else:
                reply_del = gossip = jnp.zeros((n, n), dtype=bool)
                join_records = jnp.int32(0)

            # ============= Call 1: Pings + PingRequests =======================
            ok_ping = has_ping & ok_edge(idx, ping_tgt)
            ok_man = (man_tgt >= 0) & ok_edge(idx, man_tgt)
            del_pr = proxies_valid & ok_edge(idx[:, None], proxies)  # [N, k]

            # mark1[dest, sender]: dense one-hot compares (no scatter) — each
            # term fuses into apply_marks' where pass. The proxy terms are
            # all-False on escalation-free ticks but cost only fused compares,
            # not a gather.
            mark1 = _col_mark(idx, ping_tgt, ok_ping) | _col_mark(idx, man_tgt, ok_man)
            for kk in range(proxies.shape[-1]):
                mark1 |= _col_mark(idx, proxies[:, kk], del_pr[:, kk])
            # Base fingerprint once (post-A3: the A3 WaitingForPing write moves
            # no membership and no identity word, so this equals the pre-mark1
            # fp); every later fp point derives by exact per-wave deltas on
            # the fast path, with full recomputes only inside the
            # join/escalation branches.
            fp0, n0 = fp_count(S, idv)
            S, T, lat, idv, dfp1, dn1 = apply_marks_delta(S, T, lat, idv, mark1)
            fp1, n1 = fp0 + dfp1, n0 + dn1

            if _cut == "c1":
                return _early_return(S, T, lat, idv)

            # Queued by call-1 dispatch: direct Acks (kaboodle.rs:513-532) and
            # the proxies' Pings to the suspect (kaboodle.rs:533-545).
            del_ack = ok_ping & ok_edge(ping_tgt, idx)  # tgt -> pinger
            del_ack_man = ok_man & ok_edge(man_tgt, idx)
            ok_p2x = ok_edge(proxies, jstar[:, None])  # proxy -> suspect
            del_pping = del_pr & ok_p2x  # [N, k]

            # ============= Call 2: Acks, proxy Pings, join responses ==========
            mark2 = _row_mark(idx, ping_tgt, del_ack)  # pinger marks target
            mark2 |= _row_mark(idx, man_tgt, del_ack_man)
            mark2 |= reply_del.T  # joiner marks join-responder
            # Suspect-marks-proxy scatters on BOTH dims (jstar rows x proxy
            # cols), so it has no one-hot form; it is escalation-only, so gate
            # the scatter out of steady-state ticks.
            mark2 |= jax.lax.cond(
                jnp.any(escalate),
                lambda: _scatter_or(
                    jnp.zeros((n, n), dtype=bool),
                    jnp.broadcast_to(jstar[:, None], proxies.shape),
                    proxies,
                    del_pping,
                ),
                lambda: jnp.zeros((n, n), dtype=bool),
            )
            S, T, lat, idv, dfp2, dn2 = apply_marks_delta(S, T, lat, idv, mark2)

            # Gossip-learned peers insert back-dated (Q6) where still unknown,
            # with identity words resolved to the peers' current identities
            # (deviation D-ID1 — shared with the lockstep oracle; the native
            # engine carries the sharer's view faithfully).
            if cfg.join_broadcast_enabled:

                def _gossip_insert(S, T, idv):
                    gossip_new = gossip & ~(S > 0)
                    S = jnp.where(gossip_new, jnp.int8(KNOWN), S)
                    T = jnp.where(gossip_new, tT - gossip_backdate, T)
                    if has_idv:
                        idv = jnp.where(gossip_new, id_row, idv)
                    return S, T, idv

                S, T, idv = jax.lax.cond(
                    any_join, _gossip_insert, lambda S, T, idv: (S, T, idv), S, T, idv
                )

            # fp2/n2 feed only the indirect-ping ack payloads (call-3 acks at
            # proxies, call-4 forwards) — every consumer is masked by an
            # escalation-derived delivery, so the whole O(N^2) hash pass is
            # gated off on escalation-free ticks (all of fault-free steady
            # state).
            S_2, idv_2 = S, idv
            fp2, n2 = jax.lax.cond(
                jnp.any(escalate),
                lambda: fp_count(S_2, idv_2),
                lambda: (jnp.zeros((n,), jnp.uint32), jnp.zeros((n,), jnp.int32)),
            )

            if _cut == "c2":
                return _early_return(S, T, lat, idv)

            # Queued: the suspect's Acks back to the proxies.
            del_pack = del_pping & ok_edge(jstar[:, None], proxies)  # [N, k]

            # Coincidence forwarding (kaboodle.rs:418-443 pop semantics): if
            # proxy p's own direct or manual ping this tick targeted the same
            # suspect, p's call-2 Ack for it pops the curious entry and
            # forwards fp1-payload Acks in call 3; the call-3 proxy Ack then
            # finds curious empty.
            p_tgt = ping_tgt[jnp.clip(proxies, 0)]  # [N, k] the proxies' own ping targets
            p_man = man_tgt[jnp.clip(proxies, 0)]
            p_got_direct = del_ack[jnp.clip(proxies, 0)]
            p_got_man = del_ack_man[jnp.clip(proxies, 0)]
            pop_hit = ((p_tgt == jstar[:, None]) & p_got_direct) | (
                (p_man == jstar[:, None]) & p_got_man
            )
            fwd_c = del_pr & pop_hit  # proxy forwards its call-2 ack payload (fp1)
            del_fwd_c = fwd_c & ok_edge(proxies, idx[:, None])  # p -> suspector

            # Proxy forwards the suspect's Ack (fp2 payload) in call 4 unless
            # the curious entry was already popped by the call-2 coincidence.
            fwd = del_pack & ~pop_hit
            del_fwd = fwd & ok_edge(proxies, idx[:, None])  # [N, k] p -> suspector

            # ======== Calls 3 + 4: escalation-only delivery waves =============
            # Call 3: suspect Acks at proxies; call 4: forwarded Acks. Every
            # datagram in these waves descends from an escalation this tick,
            # so the mark scatters and full-matrix where-passes are gated out
            # of escalation-free ticks (all of fault-free steady state).
            def _calls34(S, T, lat, idv):
                mark3 = jnp.zeros((n, n), dtype=bool)
                mark3 = _scatter_or(
                    mark3, proxies, jnp.broadcast_to(jstar[:, None], proxies.shape), del_pack
                )  # proxy marks suspect — the proxy's own view resurrects (Q1)
                mark3 = _scatter_or(
                    mark3, idx[:, None], proxies, del_fwd_c
                )  # suspector marks pinger-proxy
                S, T, lat, idv = apply_marks(S, T, lat, idv, mark3)

                # Q11 (faithful_indirect_ack): the forwarded Ack's *sender* is
                # the proxy, so the suspector marks the proxy — the suspect
                # stays WaitingForIndirectPing (kaboodle.rs:408-415 applies to
                # the sender).
                mark4 = jnp.zeros((n, n), dtype=bool)
                mark4 = _scatter_or(mark4, idx[:, None], proxies, del_fwd)
                S, T, lat, idv = apply_marks(S, T, lat, idv, mark4)
                if not cfg.faithful_indirect_ack:
                    # Intended-SWIM mode: a forwarded ack clears the suspect too.
                    cleared = jnp.any(del_fwd | del_fwd_c, axis=-1)
                    clr_cell = cleared[:, None] & jstar_cell & (S > 0)
                    S = jnp.where(clr_cell, jnp.int8(KNOWN), S)
                    T = jnp.where(clr_cell, tT, T)
                return S, T, lat, idv

            S, T, lat, idv = jax.lax.cond(
                jnp.any(escalate),
                _calls34,
                lambda S, T, lat, idv: (S, T, lat, idv),
                S, T, lat, idv,
            )

            if _cut == "c34":
                return _early_return(S, T, lat, idv)

            # ============= G. Anti-entropy (kaboodle.rs:707-740) ==============
            # On ticks with no join and no escalation, nothing touched the
            # state between mark1 and here except mark2, so fp_g is the exact
            # delta chain; the join-gossip / calls-3-4 branches fall back to a
            # full recompute (they flip memberships with their own masks).
            S_g, idv_g = S, idv
            fp_g, n_g = jax.lax.cond(
                any_join | jnp.any(escalate),
                lambda: fp_count(S_g, idv_g),
                lambda: (fp1 + dfp2, n1 + dn2),
            )

            # Candidate priority = phase_base + sender index; first match wins
            # (take_sync_request scans in arrival order). Match condition:
            # their_fp != our_fp and our_n <= their_n (kaboodle.rs:717-726).
            prio0, peer0, prio1, peer1 = _ae_phase01(
                fp_g, n_g, fp1, n1, del_ack, del_ack_man, ping_tgt
            )

            # Phase 2 (call-3 acks): suspect acks at proxies (sender = suspect)
            # and coincidence forwards at suspectors (sender = pinger-proxy).
            base2 = jnp.int32(2 * n)
            x_fp2 = fp2[jnp.clip(jstar, 0)]  # [N] suspect's fp2 per suspector row
            x_n2 = n2[jnp.clip(jstar, 0)]
            # at proxy P: candidate (X, fp2[X], n2[X]) — scatter-min over edges.
            m_px = del_pack & (x_fp2[:, None] != fp_g[jnp.clip(proxies, 0)]) & (
                n_g[jnp.clip(proxies, 0)] <= x_n2[:, None]
            )
            prio_proxy = jnp.full((n,), INF, dtype=jnp.int32).at[jnp.clip(proxies, 0)].min(
                jnp.where(m_px, base2 + jstar[:, None], INF)
            )
            peer_proxy = prio_proxy - base2  # sender == X == candidate peer
            # at suspector s: candidate (X, fp1[X], n1[X]) via coincidence forward.
            x_fp1 = fp1[jnp.clip(jstar, 0)]
            x_n1 = n1[jnp.clip(jstar, 0)]
            m_cf = del_fwd_c & (x_fp1[:, None] != fp_g[:, None]) & (n_g[:, None] <= x_n1[:, None])
            prio_coinc = jnp.min(jnp.where(m_cf, base2 + proxies, INF), axis=-1)
            prio2 = jnp.minimum(prio_proxy, prio_coinc)
            peer2 = jnp.where(prio_proxy <= prio_coinc, peer_proxy, jstar)

            # Phase 3 (call-4 forwarded acks): candidate (X, fp2[X], n2[X]),
            # sender = forwarding proxy.
            base3 = jnp.int32(3 * n)
            m_f = del_fwd & (x_fp2[:, None] != fp_g[:, None]) & (n_g[:, None] <= x_n2[:, None])
            prio3 = jnp.min(jnp.where(m_f, base3 + proxies, INF), axis=-1)
            peer3 = jstar

            best = jnp.minimum(jnp.minimum(prio0, prio1), jnp.minimum(prio2, prio3))
            partner = jnp.where(
                best == prio0,
                peer0,
                jnp.where(best == prio1, peer1, jnp.where(best == prio2, peer2, peer3)),
            ).astype(jnp.int32)
            has_req = (best != INF) & alive
            partner = jnp.where(has_req, partner, -1)

            # KnownPeersRequest i -> partner, payload (fp_g[i], n_g[i]).
            del_kpr = has_req & ok_edge(idx, partner)
            del_rep = del_kpr & ok_edge(partner, idx)  # partner -> requester

            if _cut == "G":
                return _early_return(S, T, lat, idv)

            if telemetry:
                S, T, lat, idv, fp_f, n_f, ae_records = _anti_entropy(
                    S, T, lat, idv, partner, del_kpr, del_rep, fp_g, n_g
                )
            else:
                S, T, lat, idv, fp_f, n_f = _anti_entropy(
                    S, T, lat, idv, partner, del_kpr, del_rep, fp_g, n_g
                )

            msgs = (
                jnp.sum(ok_ping, dtype=jnp.int32)
                + jnp.sum(ok_man, dtype=jnp.int32)
                + jnp.sum(del_pr, dtype=jnp.int32)
                + jnp.sum(del_ack, dtype=jnp.int32)
                + jnp.sum(del_ack_man, dtype=jnp.int32)
                + jnp.sum(del_pping, dtype=jnp.int32)
                + jnp.sum(reply_del, dtype=jnp.int32)
                + jnp.sum(del_pack, dtype=jnp.int32)
                + jnp.sum(del_fwd_c, dtype=jnp.int32)
                + jnp.sum(del_fwd, dtype=jnp.int32)
                + jnp.sum(del_kpr, dtype=jnp.int32)
                + jnp.sum(del_rep, dtype=jnp.int32)
            )
            counters = None
            if telemetry:
                # A2 removals (WFIP timeouts + no-proxy insta-removes),
                # recomputed from the pre-tick snapshot only on ticks where
                # A2 fired — _a2_rem's two terms are disjoint (an insta row's
                # jstar cell is a timed-out WaitingForPing, never WFIP).
                deaths = jax.lax.cond(
                    any_a2,
                    lambda: jnp.sum(
                        alive[:, None]
                        & (S0 == WAITING_FOR_INDIRECT_PING)
                        & (age0 >= cfg.ping_timeout_ticks),
                        dtype=jnp.int32,
                    )
                    + jnp.sum(insta_remove, dtype=jnp.int32),
                    lambda: jnp.int32(0),
                )
                counters = ProtocolCounters(
                    pings_sent=jnp.sum(has_ping, dtype=jnp.int32)
                    + jnp.sum(man_tgt >= 0, dtype=jnp.int32)
                    + jnp.sum(del_pr, dtype=jnp.int32),
                    acks_sent=jnp.sum(ok_ping, dtype=jnp.int32)
                    + jnp.sum(ok_man, dtype=jnp.int32)
                    + jnp.sum(del_pping, dtype=jnp.int32)
                    + jnp.sum(fwd, dtype=jnp.int32)
                    + jnp.sum(fwd_c, dtype=jnp.int32),
                    ping_reqs_sent=jnp.sum(proxies_valid, dtype=jnp.int32),
                    suspicions_raised=jnp.sum(escalate, dtype=jnp.int32),
                    suspicions_refuted=jnp.int32(0),  # filled by _finish
                    deaths_declared=deaths,
                    joins_disseminated=jnp.sum(Jm, dtype=jnp.int32),
                    gossip_bytes=jnp.uint32(RECORD_BYTES)
                    * (ae_records + join_records).astype(jnp.uint32),
                    armed_timers=jnp.int32(0),  # filled by _finish
                )
            return _finish(
                S, T, lat, idv, jnp.where(del_kpr, partner, -1),
                fp_g, n_g, fp_f, n_f, msgs, counters,
            )

        def _fast(S=S, T=T, lat=lat, idv=idv):
            """The fused program: plan(graph, "fused")'s draw + update
            passes, for ticks with no Join broadcast and no suspicion
            activity (the planner-derived dispatch pred is False). Faulty
            builds take it too since the phase-graph refactor: churn and
            the delivery-gate matrix are prologue ops, and drops/partitions
            flow through the same ``ok_edge`` gathers the masks compose.

            On these ticks A2 is a no-op, there are no proxies, no join
            replies, no gossip inserts, and calls 3-4 carry nothing — the
            surviving traffic is the A3 ping, manual pings, their call-2
            acks, and the anti-entropy exchange, all with masks derived from
            O(N) vectors (one-hot compares). With no cond boundary between
            the reads and the writes, XLA fuses the whole update into one
            composed write chain over (S, T): the tick's [N, N] traffic is
            the phase-A stats read (above), the eligibility/draw read, and
            this one read+write — vs ~9 materialized sweeps through the full
            path (the round-4 on-TPU decomposition, PERF.md). Bit-exact with
            ``_rest`` on every tick where the pred is False
            (tests/test_fast_path.py fuzzes the fault-free equivalence;
            tests/test_phasegraph.py pins the faulty-build dispatch)."""
            # A3 on the unchanged state (A2 was a no-op this tick).
            elig = alive[:, None] & (S == KNOWN) & ~eye
            ping_tgt = choose_one_of_oldest_k(
                T, elig, cfg.num_candidate_target_peers, key_ping, det,
                method=cfg.oldest_k_method,
            )
            has_ping = ping_tgt >= 0

            # All of this tick's O(N) delivery plumbing, before any [N, N]
            # write exists.
            ok_ping = has_ping & ok_edge(idx, ping_tgt)
            ok_man = (man_tgt >= 0) & ok_edge(idx, man_tgt)
            del_ack = ok_ping & ok_edge(ping_tgt, idx)
            del_ack_man = ok_man & ok_edge(man_tgt, idx)

            # Composed single-pass update. The sequential semantics are
            # A3 write -> call-1 marks (+deltas) -> call-2 marks (+deltas),
            # which the full path expresses as three separate write passes;
            # here every mask is a one-hot outer form over the vectors above,
            # so the final cell value and both waves' exact (fp, count)
            # deltas are pure elementwise functions of the ORIGINAL (S, T)
            # plus those vectors — one read, one write, sibling reductions,
            # no intermediate [N, N] tensor for XLA to materialize.
            # Equivalences used (all pinned by tests/test_fast_path.py):
            #   - A3 changes neither membership (KNOWN -> WaitingForPing,
            #     both members) nor identity words, so fp0/n0 read the
            #     original S exactly as the full path's post-A3 fp_count;
            #   - wave-1/wave-2 overlap (mutual pings: cell (i, j) marked by
            #     j's ping in wave 1 and j's ack in wave 2) resolves by
            #     membership-after-wave-1 = member0 | mark1, matching the
            #     chained apply_marks_delta;
            #   - marks write (KNOWN, now, sender's current identity) in both
            #     waves, so last-writer composition is order-free.
            tgt_cell = _row_mark(idx, ping_tgt, has_ping)
            mark1 = _col_mark(idx, ping_tgt, ok_ping) | _col_mark(idx, man_tgt, ok_man)
            mark2 = _row_mark(idx, ping_tgt, del_ack) | _row_mark(idx, man_tgt, del_ack_man)
            markK = mark1 | mark2

            member0 = S > 0
            n0 = jnp.sum(member0, axis=-1, dtype=jnp.int32)
            member1 = member0 | mark1
            dn1 = jnp.sum(mark1 & ~member0, axis=-1, dtype=jnp.int32)
            dn2 = jnp.sum(mark2 & ~member1, axis=-1, dtype=jnp.int32)
            if has_idv:
                old_hash = jnp.where(
                    member0, peer_record_hash(u_row, idv), jnp.uint32(0)
                )
                fp0 = jnp.sum(old_hash, axis=-1, dtype=jnp.uint32)
                dfp1 = jnp.sum(
                    jnp.where(mark1, rec_hash[None, :] - old_hash, jnp.uint32(0)),
                    axis=-1, dtype=jnp.uint32,
                )
                hash1 = jnp.where(mark1, rec_hash[None, :], old_hash)
                dfp2 = jnp.sum(
                    jnp.where(mark2, rec_hash[None, :] - hash1, jnp.uint32(0)),
                    axis=-1, dtype=jnp.uint32,
                )
                idv = jnp.where(markK, id_row, idv)
            else:
                fp0 = jnp.sum(
                    jnp.where(member0, rec_hash[None, :], jnp.uint32(0)),
                    axis=-1, dtype=jnp.uint32,
                )
                dfp1 = jnp.sum(
                    jnp.where(mark1 & ~member0, rec_hash[None, :], jnp.uint32(0)),
                    axis=-1, dtype=jnp.uint32,
                )
                dfp2 = jnp.sum(
                    jnp.where(mark2 & ~member1, rec_hash[None, :], jnp.uint32(0)),
                    axis=-1, dtype=jnp.uint32,
                )
            if has_lat:
                # Wave-ordered EWMA sampling, composed: wave 1 samples where
                # the post-A3 state was waiting; wave 2 where the post-wave-1
                # state still was (a wave-1 mark clears it to Known).
                S_a3 = jnp.where(tgt_cell, jnp.int8(WAITING_FOR_PING), S)
                T_a3 = jnp.where(tgt_cell, tT, T)
                waiting1 = (S_a3 == WAITING_FOR_PING) | (
                    S_a3 == WAITING_FOR_INDIRECT_PING
                )
                sample1 = (t - T_a3).astype(jnp.float32)
                upd1 = jnp.where(
                    jnp.isnan(lat), sample1,
                    jnp.float32(0.8) * sample1 + jnp.float32(0.2) * lat,
                )
                lat1 = jnp.where(mark1 & waiting1, upd1, lat)
                S_1 = jnp.where(mark1, jnp.int8(KNOWN), S_a3)
                T_1 = jnp.where(mark1, tT, T_a3)
                waiting2 = (S_1 == WAITING_FOR_PING) | (
                    S_1 == WAITING_FOR_INDIRECT_PING
                )
                sample2 = (t - T_1).astype(jnp.float32)
                upd2 = jnp.where(
                    jnp.isnan(lat1), sample2,
                    jnp.float32(0.8) * sample2 + jnp.float32(0.2) * lat1,
                )
                lat = jnp.where(mark2 & waiting2, upd2, lat1)
            S = jnp.where(
                markK, jnp.int8(KNOWN),
                jnp.where(tgt_cell, jnp.int8(WAITING_FOR_PING), S),
            )
            T = jnp.where(markK | tgt_cell, tT, T)
            fp1, n1 = fp0 + dfp1, n0 + dn1
            fp_g, n_g = fp1 + dfp2, n1 + dn2

            # G: anti-entropy candidates — phases 0 and 1 only (phases 2-3
            # are escalation-borne and there is none). The phase-2/3
            # priorities are INF in _rest on these ticks, so the minimum and
            # the selected partner agree exactly.
            prio0, peer0, prio1, peer1 = _ae_phase01(
                fp_g, n_g, fp1, n1, del_ack, del_ack_man, ping_tgt
            )

            best = jnp.minimum(prio0, prio1)
            partner = jnp.where(best == prio0, peer0, peer1).astype(jnp.int32)
            has_req = (best != INF) & alive
            partner = jnp.where(has_req, partner, -1)
            del_kpr = has_req & ok_edge(idx, partner)
            del_rep = del_kpr & ok_edge(partner, idx)

            if telemetry:
                S, T, lat, idv, fp_f, n_f, ae_records = _anti_entropy(
                    S, T, lat, idv, partner, del_kpr, del_rep, fp_g, n_g
                )
            else:
                S, T, lat, idv, fp_f, n_f = _anti_entropy(
                    S, T, lat, idv, partner, del_kpr, del_rep, fp_g, n_g
                )
            msgs = (
                jnp.sum(ok_ping, dtype=jnp.int32)
                + jnp.sum(ok_man, dtype=jnp.int32)
                + jnp.sum(del_ack, dtype=jnp.int32)
                + jnp.sum(del_ack_man, dtype=jnp.int32)
                + jnp.sum(del_kpr, dtype=jnp.int32)
                + jnp.sum(del_rep, dtype=jnp.int32)
            )
            counters = None
            if telemetry:
                # Fast ticks carry no escalation, no join, no A2 removal —
                # the event counters those feed are structurally zero here
                # (the _rest formulas reduce to exactly these on such ticks,
                # so the lax.cond dispatch cannot make counters diverge).
                counters = ProtocolCounters(
                    pings_sent=jnp.sum(has_ping, dtype=jnp.int32)
                    + jnp.sum(man_tgt >= 0, dtype=jnp.int32),
                    acks_sent=jnp.sum(ok_ping, dtype=jnp.int32)
                    + jnp.sum(ok_man, dtype=jnp.int32),
                    ping_reqs_sent=jnp.int32(0),
                    suspicions_raised=jnp.int32(0),
                    suspicions_refuted=jnp.int32(0),  # filled by _finish
                    deaths_declared=jnp.int32(0),
                    joins_disseminated=jnp.int32(0),
                    gossip_bytes=jnp.uint32(RECORD_BYTES)
                    * ae_records.astype(jnp.uint32),
                    armed_timers=jnp.int32(0),  # filled by _finish
                )
            return _finish(
                S, T, lat, idv, jnp.where(del_kpr, partner, -1),
                fp_g, n_g, fp_f, n_f, msgs, counters,
            )

        # ---- dispatch ---------------------------------------------------------
        # The planner decides: the fused program is bit-exact exactly when
        # every pruned op is inactive, and the predicate below is the
        # disjunction of the pruned ops' declared activity terms (plan.py's
        # ``pred_terms`` — any_a2, any_join; with broadcasts compiled out
        # only any_a2 remains). Since the phase-graph refactor the dispatch
        # covers FAULTY builds too: churn and the delivery-gate matrix are
        # prologue ops shared by both branches, so quiet faulty ticks (no
        # suspicion, no join — the vast majority of every fault scenario's
        # steady span) take the 2-pass fused program instead of paying the
        # full path's ~9 cond-serialized sweeps. _cut probes always time
        # the full path; ``program`` pins one branch for A/B and audit.
        if program == "fused":
            return _fast()
        use_fast = cfg.fast_path and _cut is None and program is None
        if not use_fast:
            return _rest()
        pred_vals = {"any_a2": any_a2, "any_join": any_join}
        pred = pred_vals[fused_prog.pred_terms[0]]
        for term in fused_prog.pred_terms[1:]:
            pred = pred | pred_vals[term]
        return jax.lax.cond(pred, _rest, _fast)

    # Program metadata for derived consumers: the telemetry trace exporter's
    # per-phase slices, the graftscan registry's entry descriptions, and the
    # phasegraph dryrun all read the planned pass structure from here.
    tick.graph = graph
    tick.programs = {"full": full_prog, "fused": fused_prog}
    return tick
