"""Build + validate the tick graph for one static build.

``build_graph`` instantiates the op table for a ``(cfg, faulty, telemetry)``
build and validates its dataflow:

- every tick-local an op ``takes`` was ``given`` by an earlier op (the
  in-tick dataflow is a DAG in execution order);
- every tick-local is given exactly once (no ambiguous producers);
- prologue ops precede tail ops (the dispatch boundary is real);
- ``cut`` labels are unique and live on tail ops (the stage-probe
  truncation points).

The graph is pure metadata; planning (pass grouping, pruning, predicate
derivation) lives in plan.py.
"""

from __future__ import annotations

import dataclasses

from kaboodle_tpu.phasegraph.ops import LAYOUTS, PhaseOp, op_table


class GraphError(ValueError):
    """An op table whose declared dataflow is inconsistent."""


@dataclasses.dataclass(frozen=True)
class TickGraph:
    """One build's validated op graph, in execution order."""

    ops: tuple[PhaseOp, ...]
    faulty: bool
    telemetry: bool
    layout: str = "dense"

    def __post_init__(self) -> None:
        if self.layout not in LAYOUTS:
            raise GraphError(
                f"unknown layout {self.layout!r} (expected one of {LAYOUTS})"
            )
        given: set[str] = set()
        names: set[str] = set()
        seen_tail = False
        cuts: set[str] = set()
        for op in self.ops:
            if op.name in names:
                raise GraphError(f"duplicate op {op.name!r}")
            names.add(op.name)
            if op.stage == "tail":
                seen_tail = True
            elif seen_tail:
                raise GraphError(
                    f"{op.name}: prologue op after the dispatch boundary"
                )
            missing = op.takes - given
            if missing:
                raise GraphError(
                    f"{op.name}: takes {sorted(missing)} before any op gives them"
                )
            dup = op.gives & given
            if dup:
                raise GraphError(f"{op.name}: re-gives {sorted(dup)}")
            given |= op.gives
            if op.cut is not None:
                if op.stage != "tail":
                    raise GraphError(f"{op.name}: cut label on a prologue op")
                if op.cut in cuts:
                    raise GraphError(f"{op.name}: duplicate cut label {op.cut!r}")
                cuts.add(op.cut)

    def op(self, name: str) -> PhaseOp:
        for op in self.ops:
            if op.name == name:
                return op
        raise KeyError(name)

    @property
    def prologue(self) -> tuple[PhaseOp, ...]:
        return tuple(op for op in self.ops if op.stage == "prologue")

    @property
    def tail(self) -> tuple[PhaseOp, ...]:
        return tuple(op for op in self.ops if op.stage == "tail")

    @property
    def cut_labels(self) -> tuple[str, ...]:
        return tuple(op.cut for op in self.ops if op.cut is not None)


def build_graph(
    cfg, faulty: bool = True, telemetry: bool = False, layout: str = "dense"
) -> TickGraph:
    """The validated op graph for one static build.

    ``layout`` selects the plane format (``ops.LAYOUTS``): a
    ``blocked_topk`` graph carries the same op vocabulary re-fated for
    [N, K] neighbor blocks plus the ``block_repair`` tail op; dense-only
    ops survive in the table and are pruned (with reasons) by
    ``plan(graph, "sparse")``.
    """
    return TickGraph(
        ops=op_table(cfg, faulty=faulty, telemetry=telemetry, layout=layout),
        faulty=faulty,
        telemetry=telemetry,
        layout=layout,
    )
