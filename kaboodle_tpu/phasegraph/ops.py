"""The phase-op vocabulary: every SWIM tick phase as declarative metadata.

One :class:`PhaseOp` per protocol phase (the lockstep round letters of
sim/kernel.py's docstring), declaring

- the persistent state **fields** it reads and writes (``MeshState`` plane
  names — the planner validates fusion legality against these),
- the tick-local values it **gives** and **takes** (the dataflow between
  ops inside one tick — the planner validates produce-before-consume),
- its **activity** mask: the traced predicate under which the op does real
  work. Ops whose activity is guaranteed False by the fused-dispatch
  predicate are *pruned* from the fused program; the predicate itself is
  derived from the pruned ops' ``pred_term`` declarations (plan.py).
- ``mask_rank``: 1 if every write mask the op applies is derivable from
  O(N) vectors (one-hot outer compares — foldable into a composed where
  chain), 2 if it needs an [N, N] intermediate (matmuls, scatters on both
  dims) and therefore can never fold,
- its **span** fate inside a warp quiescent span: ``live`` (still runs),
  ``degenerate`` (collapses to the timer-restamp / latency-decay /
  ledger-fixed-point form the leap batches), or ``invariant`` (provably a
  no-op — horizon.py's quiescence predicate is exactly the conjunction of
  these invariance conditions),
- its **hybrid** fate inside a *near*-quiescent span (Warp 2.0): ``live``
  (runs as in the strict span), ``sterile`` (runs in a membership-moving-
  free closed form — anti-entropy traffic whose marks refresh timers and
  rewrite the ledger but provably insert nothing), or ``invariant``
  (excluded by the activity signature — ``sig_term`` names the signature
  bit whose truth would make the op active, so warp/horizon.py's
  signature vocabulary is derived from the ops, not hand-listed twice).

This module is pure metadata — no jax imports — so the planner and its
tests run at AST-adjacent cost. The executable bodies live in exec.py
(dense/fused), blocked.py (chunked) and span.py (leap), keyed by op name;
tests/test_phasegraph.py pins that every op in a planned program has
exactly one implementing pass in each derived engine.
"""

from __future__ import annotations

import dataclasses

# Persistent MeshState planes (state fields ops may read/write). "S"/"T"
# are the [N, N] membership-state and timer matrices; the rest are O(N).
FIELDS = (
    "S", "T", "lat", "idv", "alive", "identity", "never_b", "last_b",
    "kpr_partner", "kpr_fp", "kpr_n", "tick", "key",
)

# TickInputs planes (per-tick scenario inputs).
INPUTS = ("kill", "revive", "partition", "drop_rate", "drop_ok", "manual_target")

# Plane layouts. "dense" is the [N, N] formulation every engine ran since
# the seed; "blocked_topk" stores each row's membership view in a [N, K]
# top-K-neighbor block (K a pow2 knob) with counter-based threefry draws
# replacing every materialized [N, N] uniform — the million-peer format
# (kaboodle_tpu/sparseplane/).
LAYOUTS = ("dense", "blocked_topk")

# Which layouts each persistent plane supports, and what the dense plane
# decomposes into under blocked_topk. Planes listed dense-only name the
# subsystems a blocked world compiles out: the join plane needs a broadcast
# domain, latency/id-view tracking and the KPR ledger are [N, N]-shaped
# diagnostics whose blocked analogue is the ack-piggyback gossip share.
PLANE_LAYOUTS = {
    "S": ("dense", "blocked_topk"),
    "T": ("dense", "blocked_topk"),
    "lat": ("dense",),
    "idv": ("dense",),
    "alive": ("dense", "blocked_topk"),
    "identity": ("dense", "blocked_topk"),
    "never_b": ("dense",),
    "last_b": ("dense",),
    "kpr_partner": ("dense",),
    "kpr_fp": ("dense",),
    "kpr_n": ("dense",),
    "tick": ("dense", "blocked_topk"),
    "key": ("dense", "blocked_topk"),
}

# The blocked twins each dense plane decomposes into (SparseState fields):
# S -> (neighbor-index, per-slot state code), T -> per-slot timer, and the
# carried threefry key -> the checkpointable (seed, cursor) counter pair.
BLOCKED_PLANES = {
    "S": ("nbr_idx", "nbr_state"),
    "T": ("nbr_timer",),
    "key": ("seed", "cursor"),
}

# PhaseOp.sparse vocabulary: the op's fate in a blocked_topk build.
SPARSE_FATES = ("row", "block", "absent")

# The LEGACY dense 5-way key layout: what each row of the per-tick
# ``split(key, 5)`` fed before Warp 3.0 re-keyed the dense draws onto
# per-(key, tick, stream) counter keys (phasegraph/rng.py — the
# ``rng_streams`` op below). The row names survive as the draw-site
# vocabulary: keyscope (analysis/rng/) names sinks by these rows (via
# the split index of any remaining chain-coupled sink, or the
# STREAM_TICK_* id of a migrated one), and the warp ledger's why-dense
# terms join through them. Reordering or appending here is a
# provenance-visible event, never a silent desync.
KEY_LAYOUT = ("proxy", "ping", "bern", "drop", "next")
KEY_PROXY, KEY_PING, KEY_BERN, KEY_DROP, KEY_NEXT = range(len(KEY_LAYOUT))


def split_tick_keys(key):
    """LEGACY one-tick key fork: ``split(key, 5)`` rows in KEY_LAYOUT order.

    Returns ``(key_proxy, key_ping, key_bern, key_drop, key_next)``. No
    engine consumes this chain anymore (exec/blocked/span derive counter
    keys via ``rng.tick_draw_keys``); it remains the executable definition
    of the pre-Warp-3.0 scheme that keyscope's chain-sink naming and the
    migration notes reference. jax is imported locally — this module stays
    importable as pure metadata."""
    import jax

    return tuple(jax.random.split(key, len(KEY_LAYOUT)))


@dataclasses.dataclass(frozen=True)
class PhaseOp:
    """One composable tick phase (see module docstring for the vocabulary).

    ``stage`` splits the tick at the dispatch boundary: ``prologue`` ops run
    unconditionally before the fused/full branch select (churn, the delivery
    gate, the phase-A stats the predicate itself needs); ``tail`` ops are
    what the dispatch chooses between. ``cut`` is the stage-probe label
    (`make_tick_fn(_cut=...)`) that truncates the full program right after
    this op.
    """

    name: str
    phase: str  # lockstep round letter: "A", "B", "1".."4", "G", or "-"
    doc: str
    stage: str  # "prologue" | "tail"
    reads: frozenset = frozenset()
    writes: frozenset = frozenset()
    inputs: frozenset = frozenset()  # TickInputs planes consumed
    gives: frozenset = frozenset()  # tick-locals produced
    takes: frozenset = frozenset()  # tick-locals consumed
    activity: str = "always"  # human description of the traced gate
    pred_term: str | None = None  # dispatch-pred symbol that excludes it
    mask_rank: int = 1
    span: str = "invariant"  # "live" | "degenerate" | "invariant"
    hybrid: str = "invariant"  # "live" | "sterile" | "invariant"
    sig_term: str | None = None  # activity-signature bit that excludes it
    cut: str | None = None
    # The op's fate under the blocked_topk layout: "row" (O(N) logic
    # unchanged), "block" (re-expressed as segment gather/scatter over the
    # [N, K] blocks), or "absent" (dense-only — the sparse planner prunes
    # it with a reason).
    sparse: str = "absent"

    def __post_init__(self) -> None:
        if self.stage not in ("prologue", "tail"):
            raise ValueError(f"{self.name}: bad stage {self.stage!r}")
        if self.sparse not in SPARSE_FATES:
            raise ValueError(f"{self.name}: bad sparse fate {self.sparse!r}")
        if self.span not in ("live", "degenerate", "invariant"):
            raise ValueError(f"{self.name}: bad span fate {self.span!r}")
        if self.hybrid not in ("live", "sterile", "invariant"):
            raise ValueError(f"{self.name}: bad hybrid fate {self.hybrid!r}")
        if self.mask_rank not in (1, 2):
            raise ValueError(f"{self.name}: bad mask_rank {self.mask_rank!r}")
        unknown = (self.reads | self.writes) - set(FIELDS)
        if unknown:
            raise ValueError(f"{self.name}: unknown state fields {sorted(unknown)}")
        unknown = self.inputs - set(INPUTS)
        if unknown:
            raise ValueError(f"{self.name}: unknown inputs {sorted(unknown)}")


def _op(name, phase, doc, stage, *, reads=(), writes=(), inputs=(), gives=(),
        takes=(), activity="always", pred_term=None, mask_rank=1,
        span="invariant", hybrid=None, sig_term=None, cut=None,
        sparse="absent") -> PhaseOp:
    if hybrid is None:
        # Default: whatever still runs in a strict span also runs in the
        # hybrid one; strict-invariant ops stay excluded unless declared.
        hybrid = "live" if span in ("live", "degenerate") else "invariant"
    return PhaseOp(
        name=name, phase=phase, doc=doc, stage=stage,
        reads=frozenset(reads), writes=frozenset(writes),
        inputs=frozenset(inputs), gives=frozenset(gives),
        takes=frozenset(takes), activity=activity, pred_term=pred_term,
        mask_rank=mask_rank, span=span, hybrid=hybrid, sig_term=sig_term,
        cut=cut, sparse=sparse,
    )


def op_table(
    cfg, faulty: bool = True, telemetry: bool = False, layout: str = "dense"
) -> tuple[PhaseOp, ...]:
    """The tick's op graph for one static build, in execution order.

    Static config flags decide op *presence* (a disabled op is absent from
    the graph, exactly as its code is compiled out of the build): ``faulty``
    gates churn and the [N, N] delivery-gate matrix,
    ``cfg.join_broadcast_enabled`` gates the whole join plane,
    ``cfg.faithful_failed_broadcast`` gates the intended-semantics Failed
    delivery, ``telemetry`` gates the counter reductions.

    ``layout`` selects the plane format.  A ``blocked_topk`` build keeps the
    same op vocabulary (fates per op in each ``sparse=`` declaration), adds
    the ``block_repair`` tail op (the bounded per-tick neighbor-block edit
    pass), and rejects configs needing dense-only planes: the join
    broadcast has no domain in a blocked world, the intended-semantics
    failed broadcast and non-faithful indirect-ack attribution are [N, N]
    re-expressions no blocked kernel implements, and the telemetry counter
    plane is dense-only today.
    """
    if layout not in LAYOUTS:
        raise ValueError(f"unknown layout {layout!r} (expected one of {LAYOUTS})")
    if layout == "blocked_topk":
        if cfg.join_broadcast_enabled:
            raise ValueError(
                "blocked_topk: no broadcast domain — build the config with "
                "join_broadcast_enabled=False (ring-contact gossip boot "
                "replaces the join broadcast)"
            )
        if not cfg.faithful_failed_broadcast:
            raise ValueError(
                "blocked_topk requires faithful_failed_broadcast=True: the "
                "intended-semantics Failed replay is an [N, N, N] dense op"
            )
        if not cfg.faithful_indirect_ack:
            raise ValueError(
                "blocked_topk requires faithful_indirect_ack=True: only the "
                "faithful proxy-attribution (quirk Q11) has a blocked twin"
            )
        if telemetry:
            raise ValueError(
                "blocked_topk has no telemetry counter plane yet — build "
                "with telemetry=False"
            )
    ops: list[PhaseOp] = [
        _op(
            "rng_streams", "-",
            "Counter-keyed PRNG rows: fold (key, tick, STREAM_TICK_*) -> "
            "(proxy, ping, bern, drop) draw keys (phasegraph/rng.py); the "
            "carried key plane is constant — every draw is a pure function "
            "of checkpointable state, so spans leap without consuming a "
            "chain.",
            "prologue", reads=("key", "tick"), gives=("keys",),
            span="live", sparse="row",
        ),
    ]
    if faulty:
        ops.append(_op(
            "churn", "-",
            "Silent kill (Q8) + revive-with-reset: aliveness flips, revived "
            "rows reset to the singleton {self} map.",
            "prologue",
            reads=("alive", "S", "T", "lat", "idv", "identity", "never_b", "tick"),
            writes=("alive", "S", "T", "lat", "idv", "never_b"),
            inputs=("kill", "revive"), gives=("rv",),
            activity="any kill/revive scheduled this tick", sparse="row",
        ))
    ops.append(_op(
        "delivery_gate", "-",
        "ok[s, d]: sender+receiver alive, same partition group, not dropped "
        "(faulty builds materialize the matrix, the drop draw gated on "
        "drop_rate > 0; fault-free builds factor it as alive[s] & alive[d] "
        "so no [N, N] gate exists).",
        "prologue", reads=("alive",),
        inputs=("partition", "drop_rate", "drop_ok") if faulty else (),
        takes=("keys",), gives=("ok",),
        mask_rank=2 if faulty else 1,
    ))
    ops.append(_op(
        "row_stats", "A",
        "Phase-A row statistics on the pre-tick snapshot: membership count, "
        "timed-out-suspect existence, WFIP-timeout existence — one fused "
        "read of (S, T); also the raw material of the dispatch predicate.",
        "prologue", reads=("S", "T", "alive", "tick"),
        gives=("row_count0", "has_timed", "wfip_any", "any_a2"),
        span="invariant", sparse="block",
    ))
    if cfg.join_broadcast_enabled:
        ops.append(_op(
            "join_gate", "A1",
            "maybe_broadcast_join: first call always; afterwards only while "
            "lonely and rebroadcast-interval old.",
            "prologue",
            reads=("alive", "never_b", "last_b", "tick"),
            writes=("never_b", "last_b"),
            takes=("row_count0",), gives=("join_b", "any_join"),
            activity="a Join broadcast fires this tick", sig_term="any_join",
        ))
    ops.append(_op(
        "manual_targets", "A4",
        "Manual pings (ping_addrs): self/out-of-range targets dropped at "
        "the transport (D8).",
        "prologue", reads=("alive",), inputs=("manual_target",),
        gives=("man_tgt",),
        activity="a manual ping is scheduled",
    ))

    # ---- tail: what the fused/full dispatch chooses between ---------------
    ops.append(_op(
        "suspicion", "A2",
        "handle_suspected_peers on the pre-tick snapshot: WFIP timeouts and "
        "no-proxy timeouts remove, the oldest timed-out WaitingForPing "
        "escalates to k indirect-ping proxies.",
        "tail", reads=("S", "T", "alive"), writes=("S", "T", "lat"),
        takes=("keys", "has_timed", "wfip_any"),
        gives=("escalate", "insta_remove", "jstar", "proxies", "any_rem"),
        activity="any_a2: a timed-out suspicion exists", pred_term="any_a2",
        mask_rank=2, span="invariant", sig_term="any_a2", sparse="block",
    ))
    ops.append(_op(
        "probe_draw", "A3",
        "ping_random_peer: uniform among the 5 longest-unheard Known peers; "
        "the target cell arms WaitingForPing(now).",
        "tail", reads=("S", "T", "alive"), writes=("S", "T"),
        takes=("keys",), gives=("ping_tgt", "has_ping"),
        span="live", cut="A", sparse="block",
    ))
    if cfg.join_broadcast_enabled:
        ops.append(_op(
            "join_insert", "B",
            "Join broadcast delivery: every receiver inserts the joiner as "
            "Known(now) with the broadcast identity.",
            "tail", reads=("S", "T", "idv", "identity"),
            writes=("S", "T", "idv"),
            takes=("join_b", "any_join", "ok"), gives=("Jm", "is_new_ro"),
            activity="any_join", pred_term="any_join", mask_rank=2,
            span="invariant", sig_term="any_join",
        ))
    if not cfg.faithful_failed_broadcast:
        ops.append(_op(
            "failed_delivery", "B",
            "Intended-semantics Failed(j) broadcast delivery (Q3 off): "
            "removal wins against lower-origin Joins — O(N^3) matmuls, "
            "gated on a removal existing.",
            "tail", reads=("S", "lat"), writes=("S", "lat"),
            takes=("ok", "any_rem") + (("Jm",) if cfg.join_broadcast_enabled else ()),
            activity="any_rem: a removal happened this tick",
            pred_term="any_a2", mask_rank=2, span="invariant",
            sig_term="any_a2",
        ))
    if cfg.join_broadcast_enabled:
        ops.append(_op(
            "join_replies", "B",
            "Join responses (Bernoulli over the sequentially-growing map) "
            "and the O(N^3) gossip-share union at each joiner — gated on a "
            "reply actually existing.",
            "tail", reads=("S", "T"),
            takes=("keys", "ok", "Jm", "is_new_ro", "row_count0", "any_join"),
            gives=("reply_del", "gossip", "join_records"),
            activity="any_join", pred_term="any_join", mask_rank=2,
            span="invariant", sig_term="any_join",
        ))
    ops.append(_op(
        "call1", "1",
        "Delivery call 1: direct Pings, manual Pings, PingRequests land; "
        "Q1 sender-marks apply with exact (fp, count) deltas.",
        "tail", reads=("S", "T", "lat", "idv", "identity", "alive"),
        writes=("S", "T", "lat", "idv"),
        takes=("ping_tgt", "has_ping", "man_tgt", "ok", "proxies",
               "escalate", "jstar"),
        gives=("mark1", "ok_ping", "ok_man", "del_ack", "del_ack_man",
               "del_pr", "del_pping", "fp1", "n1"),
        span="degenerate", cut="c1", sparse="block",
    ))
    ops.append(_op(
        "call2", "2",
        "Delivery call 2: direct/manual Acks, proxy Pings, join responses "
        "land; gossip-learned peers insert back-dated (Q6).",
        "tail", reads=("S", "T", "lat", "idv", "identity", "alive"),
        writes=("S", "T", "lat", "idv"),
        takes=("ping_tgt", "man_tgt", "del_ack", "del_ack_man", "del_pping",
               "proxies", "escalate", "jstar", "ok")
        + (("reply_del", "gossip", "any_join") if cfg.join_broadcast_enabled else ()),
        gives=("fp2", "n2", "dfp2", "dn2"),
        span="degenerate", cut="c2", sparse="block",
    ))
    ops.append(_op(
        "calls34", "34",
        "Delivery calls 3+4: the suspect's Acks at proxies, coincidence and "
        "regular forwarded Acks at suspectors — every datagram descends "
        "from an escalation this tick.",
        "tail", reads=("S", "T", "lat", "idv", "identity", "alive"),
        writes=("S", "T", "lat", "idv"),
        takes=("escalate", "jstar", "proxies", "del_pr", "del_pping",
               "del_ack", "del_ack_man", "ping_tgt", "man_tgt", "ok"),
        gives=("del_pack", "fwd", "fwd_c", "del_fwd", "del_fwd_c"),
        activity="any escalation this tick", pred_term="any_a2",
        mask_rank=2, span="invariant", sig_term="any_a2", cut="c34",
        sparse="block",
    ))
    ops.append(_op(
        "anti_entropy", "G",
        "take_sync_request: arrival-order candidate priority over phases "
        "0-3, one KnownPeersRequest per peer, request + filtered reply "
        "resolve within the tick; [N, N] work gated on a request delivering.",
        "tail",
        reads=("S", "T", "lat", "idv", "identity", "alive",
               "kpr_partner", "kpr_fp", "kpr_n"),
        writes=("S", "T", "lat", "idv", "kpr_partner", "kpr_fp", "kpr_n"),
        takes=("fp1", "n1", "dfp2", "dn2", "fp2", "n2", "del_ack",
               "del_ack_man", "ping_tgt", "man_tgt", "ok", "rv")
        if faulty else
        ("fp1", "n1", "dfp2", "dn2", "fp2", "n2", "del_ack", "del_ack_man",
         "ping_tgt", "man_tgt", "ok"),
        gives=("partner", "del_kpr", "del_rep", "fp_g", "n_g", "fp_f",
               "n_f", "ae_records"),
        activity="fingerprints disagree somewhere", span="degenerate",
        hybrid="sterile", cut="G", sparse="block",
    ))
    if telemetry:
        ops.append(_op(
            "counters", "-",
            "ProtocolCounters: pure reductions over masks the tick already "
            "computed (pings/acks/ping-reqs sent, suspicions, deaths, joins, "
            "gossip bytes, armed timers) — state trajectory unchanged.",
            "tail",
            reads=("S", "alive"),
            takes=("has_ping", "man_tgt", "ok_ping", "ok_man", "del_pr",
                   "del_pping", "escalate", "insta_remove", "ae_records",
                   "join_records", "wfip_any")
            if cfg.join_broadcast_enabled else
            ("has_ping", "man_tgt", "ok_ping", "ok_man", "del_pr",
             "del_pping", "escalate", "insta_remove", "ae_records",
             "wfip_any"),
            gives=("counters",),
            span="degenerate",
        ))
    if layout == "blocked_topk":
        ops.append(_op(
            "block_repair", "-",
            "Bounded per-tick neighbor-block edits: the tick's insert "
            "candidates (ping sender-marks + gossip shares) fold into empty "
            "slots via rank-matched placement — static [N, C, K] shapes, so "
            "violent churn never recompiles (sparseplane/repair.py).",
            "tail", reads=("S", "T", "alive"), writes=("S", "T"),
            takes=("mark1", "ae_records"),
            activity="any insert candidate this tick", sparse="block",
        ))
    ops.append(_op(
        "finish", "-",
        "Metrics + next-state assembly: fingerprint agreement, mean "
        "membership, the anti-entropy ledger carry, tick+1, the carried key.",
        "tail",
        reads=("alive", "identity", "never_b", "last_b", "tick", "key"),
        writes=("tick", "key"),
        takes=("fp_f", "n_f", "del_kpr", "partner", "fp_g", "n_g")
        + (("counters",) if telemetry else ()),
        gives=("metrics",),
        span="degenerate", sparse="row",
    ))
    return tuple(ops)
