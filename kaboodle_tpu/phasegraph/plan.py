"""The planner: compose one tick graph into a derived engine's pass program.

``plan(graph, mode)`` groups the graph's ops into the passes one engine
executes:

- ``full`` — one pass per cond-gated phase, the multi-pass program every
  engine ran before the phase graph existed. This is the reference shape:
  rare-phase ops keep their in-graph activity gates, and each gate that
  carries (S, T) through an identity branch costs a materialized sweep —
  the pass-count bound PERF.md round-4c measured (~9 sweep-equivalents).
- ``fused`` — the 2-pass steady-tick program. Ops with ``mask_rank == 2``
  cannot fold (their write masks need [N, N] intermediates), so the
  planner *prunes* them and derives the **dispatch predicate** from their
  ``pred_term`` declarations: the fused program is only taken on ticks
  where every pruned op is provably inactive. The surviving tail ops fold
  into a draw pass (the A3 target draw) and ONE update pass whose masks
  compose into a single elementwise where chain — no cond boundaries, so
  XLA fuses the whole update into ~3 sweep-equivalents.
- ``span`` — the warp-leap derivation: inside a quiescent span every op
  whose span fate is ``invariant`` is pruned (horizon.py's quiescence
  predicate conjoins exactly these invariance conditions), ``degenerate``
  ops collapse to the timer-restamp / latency-decay / ledger-fixed-point
  forms, and the leap batches the k surviving draws as one scan.
- ``hybrid`` — the Warp 2.0 near-quiescent derivation: ops whose hybrid
  fate is ``invariant`` are pruned and their ``sig_term`` declarations
  flow into ``pred_terms`` — the activity-signature bits
  (warp/horizon.py) that must be clear for the hybrid span to be exact.
  ``sterile`` ops (anti-entropy) survive in a membership-moving-free
  closed form: timer marks + the kpr ledger carry, provably zero inserts
  under the signature's sterility bits. Everything a strict span runs,
  the hybrid span runs too — a strictly-quiescent state is just the
  hybrid class with no armed timers and agreed fingerprints, so the
  hybrid program is a strict superset that degenerates bit-exactly.
- ``blocked`` — the chunked derivation: the full pass order, each [N, N]
  pass re-expressed as a ``lax.map`` over row blocks (layout, not logic).
- ``sparse`` — the blocked_topk-layout derivation: dense-only ops (sparse
  fate ``absent``) are pruned with reasons, the surviving tail ops group
  into the six sparse passes (expiry / draw / exchange / gossip / repair /
  finish) the [N, K] kernel executes (sparseplane/kernel.py). Requires a
  graph built with ``layout="blocked_topk"``; conversely every other mode
  requires the dense layout.

The executable engines assemble themselves FROM these programs (exec.py
iterates the planned passes; derive.py builds all five engines), so op
presence, pass grouping, the dispatch predicate, and the telemetry pass
labels all have one source of truth.
"""

from __future__ import annotations

import dataclasses

from kaboodle_tpu.phasegraph.graph import GraphError, TickGraph
from kaboodle_tpu.phasegraph.ops import PhaseOp

MODES = ("full", "fused", "span", "blocked", "hybrid", "sparse")


@dataclasses.dataclass(frozen=True)
class Pass:
    """One executable pass: a named group of ops applied together.

    In the fused program a multi-op pass means the ops' write masks are
    FOLDED into one composed elementwise chain (legal only when every op
    has ``mask_rank == 1``); in the full/blocked programs passes execute
    sequentially with their own activity gates.
    """

    name: str
    ops: tuple[PhaseOp, ...]

    @property
    def op_names(self) -> tuple[str, ...]:
        return tuple(op.name for op in self.ops)


@dataclasses.dataclass(frozen=True)
class TickProgram:
    """One derived engine's composed program.

    ``prologue``/``tail`` are the planned pass lists; ``pruned`` maps each
    statically-or-predicate-excluded op to the reason it is absent;
    ``pred_terms`` (fused mode) are the activity symbols whose disjunction
    forms the dispatch predicate — derived from the pruned ops, so adding
    a new rare-phase op to the graph automatically extends the predicate
    that keeps the fused program exact.
    """

    mode: str
    prologue: tuple[Pass, ...]
    tail: tuple[Pass, ...]
    pruned: tuple[tuple[str, str], ...] = ()
    pred_terms: tuple[str, ...] = ()

    @property
    def passes(self) -> tuple[Pass, ...]:
        return self.prologue + self.tail

    def op_names(self) -> tuple[str, ...]:
        return tuple(op.name for p in self.passes for op in p.ops)

    def pass_of(self, op_name: str) -> str:
        for p in self.passes:
            if op_name in p.op_names:
                return p.name
        raise KeyError(op_name)

    def describe(self) -> dict:
        """JSON-able program summary (telemetry trace slices, docs, CLI)."""
        return {
            "mode": self.mode,
            "passes": [
                {"name": p.name, "stage": "prologue" if p in self.prologue else "tail",
                 "ops": list(p.op_names)}
                for p in self.passes
            ],
            "pruned": [{"op": name, "reason": why} for name, why in self.pruned],
            "pred_terms": list(self.pred_terms),
        }


def _single_passes(ops) -> tuple[Pass, ...]:
    return tuple(Pass(op.name, (op,)) for op in ops)


def _plan_full(graph: TickGraph) -> TickProgram:
    return TickProgram(
        mode="full",
        prologue=_single_passes(graph.prologue),
        tail=_single_passes(graph.tail),
    )


def _plan_fused(graph: TickGraph) -> TickProgram:
    pruned: list[tuple[str, str]] = []
    pred: list[str] = []
    draw: list[PhaseOp] = []
    update: list[PhaseOp] = []
    for op in graph.tail:
        if op.mask_rank == 2:
            if op.pred_term is None:
                raise GraphError(
                    f"{op.name}: mask_rank 2 but no pred_term — the op can "
                    "neither fold nor be excluded by the dispatch predicate"
                )
            pruned.append((op.name, f"excluded by dispatch pred ({op.pred_term})"))
            if op.pred_term not in pred:
                pred.append(op.pred_term)
        elif op.gives & {"ping_tgt", "has_ping"}:
            draw.append(op)
        else:
            update.append(op)
    if not draw or not update:
        raise GraphError("fused plan needs a draw op and at least one update op")
    return TickProgram(
        mode="fused",
        prologue=_single_passes(graph.prologue),
        tail=(Pass("draw", tuple(draw)), Pass("update", tuple(update))),
        pruned=tuple(pruned),
        pred_terms=tuple(pred),
    )


def _plan_span(graph: TickGraph) -> TickProgram:
    if graph.faulty:
        raise GraphError(
            "span programs derive from fault-free graphs: a quiescent span "
            "carries no scheduled events by definition (horizon.py)"
        )
    pruned: list[tuple[str, str]] = []
    live: list[PhaseOp] = []
    refresh: list[PhaseOp] = []
    ledger: list[PhaseOp] = []
    for op in graph.ops:
        if op.span == "invariant":
            pruned.append((op.name, "span fixed point (quiescence predicate)"))
        elif op.span == "live":
            live.append(op)
        elif op.name in ("call1", "call2"):
            refresh.append(op)
        else:
            # anti_entropy / counters / finish degenerate to once-per-span
            # closed forms: the ledger fixed point, leap_counters, tick+k.
            ledger.append(op)
    return TickProgram(
        mode="span",
        prologue=(),
        tail=(
            Pass("draw", tuple(live)),
            Pass("refresh", tuple(refresh)),
            Pass("ledger", tuple(ledger)),
        ),
        pruned=tuple(pruned),
    )


def _plan_hybrid(graph: TickGraph) -> TickProgram:
    if graph.faulty:
        raise GraphError(
            "hybrid programs derive from fault-free graphs: a near-quiescent "
            "span carries no scheduled events by definition (horizon.py)"
        )
    pruned: list[tuple[str, str]] = []
    pred: list[str] = []
    live: list[PhaseOp] = []
    refresh: list[PhaseOp] = []
    sterile: list[PhaseOp] = []
    ledger: list[PhaseOp] = []
    for op in graph.ops:
        if op.hybrid == "invariant":
            why = (
                f"excluded by signature bit ({op.sig_term})"
                if op.sig_term is not None
                else "span fixed point (signature sterility terms)"
            )
            pruned.append((op.name, why))
            if op.sig_term is not None and op.sig_term not in pred:
                pred.append(op.sig_term)
        elif op.hybrid == "sterile":
            sterile.append(op)
        elif op.name in ("call1", "call2"):
            refresh.append(op)
        elif op.span == "live":
            live.append(op)
        else:
            # finish (and counters in telemetry graphs) degenerate to
            # once-per-span closed forms, as in the strict span plan.
            ledger.append(op)
    return TickProgram(
        mode="hybrid",
        prologue=(),
        tail=(
            Pass("draw", tuple(live)),
            Pass("refresh", tuple(refresh)),
            Pass("ae", tuple(sterile)),
            Pass("ledger", tuple(ledger)),
        ),
        pruned=tuple(pruned),
        pred_terms=tuple(pred),
    )


def _plan_blocked(graph: TickGraph) -> TickProgram:
    full = _plan_full(graph)
    return dataclasses.replace(full, mode="blocked")


# Per-op pruning reasons for dense-only ops in a blocked_topk graph; ops
# not listed fall back to the generic reason.
_SPARSE_PRUNE_REASONS = {
    "delivery_gate": "counter-draw bernoullis replace the materialized [N, N] gate",
    "manual_targets": "manual pings have no blocked surface yet",
}

# Tail pass each sparse-surviving op belongs to, in kernel execution order.
# A new tail op declared sparse != "absent" MUST be added here — the
# planner refuses to guess, so kernel/plan drift is a build error.
_SPARSE_TAIL_GROUPS = (
    ("expiry", ("suspicion",)),
    ("draw", ("probe_draw",)),
    ("exchange", ("call1", "call2", "calls34")),
    ("gossip", ("anti_entropy",)),
    ("repair", ("block_repair",)),
    ("finish", ("finish",)),
)


def _plan_sparse(graph: TickGraph) -> TickProgram:
    if graph.layout != "blocked_topk":
        raise GraphError(
            "sparse programs derive from blocked_topk graphs: "
            "build_graph(cfg, layout='blocked_topk')"
        )
    pruned: list[tuple[str, str]] = []
    prologue: list[PhaseOp] = []
    group_of = {op: name for name, ops in _SPARSE_TAIL_GROUPS for op in ops}
    grouped: dict[str, list[PhaseOp]] = {name: [] for name, _ in _SPARSE_TAIL_GROUPS}
    for op in graph.ops:
        if op.sparse == "absent":
            pruned.append((
                op.name,
                _SPARSE_PRUNE_REASONS.get(
                    op.name, "dense-only op (sparse fate 'absent')"
                ),
            ))
        elif op.stage == "prologue":
            prologue.append(op)
        elif op.name not in group_of:
            raise GraphError(
                f"{op.name}: sparse fate {op.sparse!r} but no sparse tail "
                "pass claims it (_SPARSE_TAIL_GROUPS)"
            )
        else:
            grouped[group_of[op.name]].append(op)
    tail = tuple(
        Pass(name, tuple(grouped[name]))
        for name, _ in _SPARSE_TAIL_GROUPS
        if grouped[name]
    )
    return TickProgram(
        mode="sparse",
        prologue=_single_passes(prologue),
        tail=tail,
        pruned=tuple(pruned),
    )


def plan(graph: TickGraph, mode: str) -> TickProgram:
    """Compose ``graph`` into the given engine mode's program."""
    if mode not in MODES:
        raise ValueError(f"unknown plan mode {mode!r} (expected one of {MODES})")
    if mode != "sparse" and graph.layout != "dense":
        raise GraphError(
            f"{mode} programs derive from dense-layout graphs; a "
            "blocked_topk graph plans only with mode='sparse'"
        )
    return {
        "full": _plan_full,
        "fused": _plan_fused,
        "span": _plan_span,
        "blocked": _plan_blocked,
        "hybrid": _plan_hybrid,
        "sparse": _plan_sparse,
    }[mode](graph)
