"""Counter-based threefry streams shared by every derived engine.

Warp 3.0 generalizes the sparseplane ``(seed, cursor, stream)`` discipline
into the phasegraph IR itself.  Two key shapes coexist:

- **Sparse counter pair** (``SparseState.seed``/``cursor``): the blocked
  engine derives every draw from ``stream_key(seed, cursor, stream)`` —
  unchanged, now hosted here (``sparseplane/rng.py`` re-exports).

- **Dense carried key** (``MeshState.key``, a raw ``uint32[2]``): the
  legacy scheme *split* the key five ways each tick and threaded the
  remainder forward (``KEY_LAYOUT`` in phasegraph/ops.py), chaining every
  draw to every prior tick's draws — the chain-coupled class that kept
  drain seasons dense (KEYSCOPE_LEAP.json, ROADMAP item 2).  The counter
  scheme instead derives each tick's draw keys as a pure function of
  ``(key, tick, stream)``:

      tick_key(stream) = fold_in(fold_in(PRNGKey(key[0] ^ key[1]), tick),
                                 stream)

  so the carried key plane is a *constant* (``key_next = key``) and any
  tick's randomness is recomputable from checkpointable state alone — a
  span of ticks can leap, replay, or memoize without consuming a chain.
  Shaped draws supply the remaining counter words by element position, so
  a ``(N, N)`` uniform is effectively keyed ``(seed, tick, stream, row,
  col)`` — per-row keying without materializing per-row key arrays.

Distinct ``STREAM_*`` ids keep per-phase draws independent (no key reuse
across phases — the discipline KB601/KB204 enforce).  Every literal
stream id folded onto a counter chain must appear in keyscope's
``KEYSCOPE_STREAMS`` double-entry register (analysis/rng/rules.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# One id per randomized phase, in tick order.  New phases append —
# renumbering changes every draw of every banked run.  Ids 0-5 are the
# sparse tick's streams (folded onto the (seed, cursor) counter pair);
# ids 6-9 are the dense tick's streams (folded onto the (key, tick)
# counter pair).  The two families never share a base key, so the id
# spaces could overlap — they stay disjoint anyway so a leap report line
# names its phase unambiguously.
STREAM_PROXY = 0  # proxy slot picks for ping-req fan-out
STREAM_CHAIN = 1  # the four delivery legs of each indirect-ping chain
STREAM_DRAW = 2  # ping target pick among the oldest-k Known slots
STREAM_PING = 3  # direct ping delivery bernoulli
STREAM_ACK = 4  # ack delivery bernoulli
STREAM_GOSSIP = 5  # piggyback share slot picks
STREAM_TICK_PROXY = 6  # dense tick: proxy member choose-k for escalation
STREAM_TICK_PING = 7  # dense tick: ping target pick / candidate choice
STREAM_TICK_BERN = 8  # dense tick: join-reply bernoulli matrix
STREAM_TICK_DROP = 9  # dense tick: datagram drop uniform matrix


def stream_table() -> dict[str, int]:
    """Live ``{name: id}`` view of every ``STREAM_*`` constant, in id order.

    Read off the module's attributes at call time (not a frozen copy), so
    keyscope's double-entry check (analysis/rng/rules.py
    ``KEYSCOPE_STREAMS``) sees exactly what the kernels will fold in —
    including any renumbering a bad edit (or a mutation test) introduces."""
    import sys

    mod = sys.modules[__name__]
    table = {
        name: getattr(mod, name)
        for name in dir(mod)
        if name.startswith("STREAM_") and isinstance(getattr(mod, name), int)
    }
    return dict(sorted(table.items(), key=lambda kv: (kv[1], kv[0])))


def stream_key(seed: jax.Array, cursor: jax.Array, stream: int) -> jax.Array:
    """Threefry key for one phase of one tick — pure function of the counters."""
    base = jax.random.fold_in(jax.random.PRNGKey(seed), cursor)
    return jax.random.fold_in(base, jnp.uint32(stream))


def stream_uniform(
    seed: jax.Array, cursor: jax.Array, stream: int, shape: tuple[int, ...]
) -> jax.Array:
    """Shaped float32 uniform in [0, 1) for one phase (position = row/slot)."""
    # f32 pinned: draw values feed thresholds and floor(u * count) index
    # math where f64 would shift pick boundaries (same pin as ops/sampling).
    return jax.random.uniform(
        stream_key(seed, cursor, stream), shape, dtype=jnp.float32
    )


def tick_stream_key(key: jax.Array, tick: jax.Array, stream: int) -> jax.Array:
    """Dense-tick threefry key for ``(key, tick, stream)`` — no chain.

    ``key`` is the raw ``uint32[2]`` carried in ``MeshState``; collapsing
    it to one seed word re-roots the derivation in a fresh ``PRNGKey`` so
    keyscope's provenance walker sees ``counter_seed`` (leapable), not
    ``carried_key`` (chain-coupled).  The tick fold is the counter; the
    stream fold separates the phases of one tick."""
    seed = key[0] ^ key[1]
    base = jax.random.fold_in(jax.random.PRNGKey(seed), tick)
    return jax.random.fold_in(base, jnp.uint32(stream))


def tick_draw_keys(
    key: jax.Array, tick: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """The dense tick's four draw keys ``(proxy, ping, bern, drop)``.

    Replaces ``split_tick_keys``'s 5-way chain fork: same four draw rows,
    but each a pure function of ``(key, tick)`` — and no ``next`` row,
    because the carried key plane is constant under the counter scheme."""
    return (
        tick_stream_key(key, tick, STREAM_TICK_PROXY),
        tick_stream_key(key, tick, STREAM_TICK_PING),
        tick_stream_key(key, tick, STREAM_TICK_BERN),
        tick_stream_key(key, tick, STREAM_TICK_DROP),
    )
