"""The leap kernel: the phase graph's SPAN program — k quiescent ticks as
one batched pass, bit-exact.

This is the warp engine's derivation from the tick op graph: inside a warp
span (horizon.py's quiescence predicate + no scheduled events) every op
the planner marks ``invariant`` is provably a fixed point, and
``plan(graph, "span")`` prunes them — ``make_leap_fn`` checks at build
time that the planner's surviving op set is exactly what this module
implements (draw = rng_streams + probe_draw, refresh = the degenerate
call1/call2 timer-restamp + latency-decay forms, ledger = the
anti-entropy/finish fixed-point writes), so a new op added to the graph
cannot silently leak past the leap. Concretely, the dense tick collapses:
membership, aliveness, identity views, broadcast bookkeeping and the
anti-entropy ledger are all fixed points, and the only surviving per-tick
work is

- the A3 ping-target draw (uniform among the 5 longest-unheard Known peers,
  kaboodle.rs:655-675),
- the resulting timer refreshes — ``timer[i, tgt_i] = t`` (the A3 stamp,
  immediately re-stamped by the call-2 Ack) and ``timer[tgt_i, i] = t`` (the
  call-1 Q1 mark at the target),
- the latency-EWMA decay on exactly the pinged edges: every sample inside a
  span is zero ticks (ping and ack resolve within the tick), so the update
  is ``lat <- 0.2 * lat`` (``0.8 * 0 + 0.2 * old``; first sample 0 where
  still NaN) at cell ``(i, tgt_i)`` — for mutual pings via the wave-1 mark,
  otherwise via the wave-2 ack mark, never both (kernel.py ``_fast``'s
  two-wave sampling order, degenerate inside the span).

Because the PRNG is counter-keyed (Warp 3.0, phasegraph/rng.py), the k
ticks' draws do not need ANY sequential key work: each in-span tick's
ping key is ``tick_stream_key(st.key, t, STREAM_TICK_PING)`` — a pure
function of the (constant) key plane and the tick index — and the k
uniform vectors are generated as ONE ``[k, N]`` batch up front.

The remaining sequential dependence — tick s's draw ranks timers that tick
s-1 refreshed — is paid with O(N·W) work per tick instead of the dense
kernel's O(N^2): a tick refreshes exactly TWO cells per pinging row, so the
oldest-k structure is maintained incrementally as *segmented reductions*.
Each row is segmented into blocks of ``W`` columns and the scan carries, per
``(row, block)`` segment, its 5 lexicographically-smallest ``(timer, col)``
pairs. Target selection reduces the ``[N, 5·B]`` summary (the global
oldest-5 is always among the per-block oldest-5s); the refresh then
re-reduces only the touched segments — a ``[2N, W]`` gather + masked
reduction — and scatters them back. Per-tick [N, N] traffic: one O(N)
scatter. The dense fast-path tick's ~6 combined HBM sweeps (PERF.md round
4) never happen.

Draw parity: the per-segment and cross-segment reductions compute exactly
the stable k-smallest ordering of ``ops.sampling._stable_k_smallest_iter``
(score-then-column lexicographic, ties toward the lower column), and the
selection tail (``ops.sampling.pick_candidate``) is literally shared with
the dense kernel — same uniform in, bit-identical target out. The ping
keys replicate the dense tick's ``STREAM_TICK_PING`` counter derivation
exactly, and the carried key plane is constant under the counter scheme,
so the span exits with the exact key the dense run would carry.

Fixed-point writes the leap must still perform once (the dense tick rewrites
them every tick): ``kpr_partner = -1``, ``kpr_fp = fingerprint``, ``kpr_n =
membership count`` — the anti-entropy ledger of the span's final tick.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.ops.hashing import membership_fingerprint
from kaboodle_tpu.ops.sampling import pick_candidate
from kaboodle_tpu.phasegraph.graph import build_graph
from kaboodle_tpu.phasegraph import rng as pg_rng
from kaboodle_tpu.phasegraph.plan import plan
from kaboodle_tpu.sim.state import MeshState
from kaboodle_tpu.spec import KNOWN

# The span-program op sets this module implements; make_leap_fn refuses to
# build if the planner derives anything else from the graph (the leap would
# no longer be bit-exact with k dense ticks).
_SPAN_PASSES = {
    "draw": ("rng_streams", "probe_draw"),
    "refresh": ("call1", "call2"),
    "ledger": ("anti_entropy", "finish"),
}

# The hybrid (near-quiescent) program's pass sets — Warp 2.0. The sterile
# anti-entropy pass is the one addition over the strict span: under the
# activity signature's sterility bits (warp/horizon.py) every KPR exchange
# provably inserts nothing, so its whole effect is two timer marks per
# delivered request plus the kpr ledger rewrite — both modeled exactly.
_HYBRID_PASSES = {
    "draw": ("rng_streams", "probe_draw"),
    "refresh": ("call1", "call2"),
    "ae": ("anti_entropy",),
    "ledger": ("finish",),
}

# Segment width: columns per (row, block) summary segment. The per-tick cost
# is ~O(N·W) for the touched-segment re-reduction plus O(N·5·ceil(N/W)) for
# the cross-segment selection, so W ~ sqrt-ish of N balances the two; 64
# keeps both small across the bench range without retuning.
_SEG_W = 64


def _lex_k_smallest(t: jax.Array, c: jax.Array, k: int, tmax) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per row: the k lex-smallest ``(t, c)`` pairs, ties toward lower ``c``.

    The generalized form of ``ops.sampling._stable_k_smallest_iter`` for
    candidate lists whose column identity is carried explicitly (``c``), so
    it applies to gathered segments and stacked per-segment summaries alike.
    ``t == tmax`` is the ineligible sentinel. Returns ``(t_k, c_k, valid)``,
    each ``[..., k]``, in ascending lex order — identical ordering to the
    dense kernel's stable k-smallest (pinned in tests/test_warp.py via
    end-state equality).
    """
    big_c = jnp.int32(jnp.iinfo(jnp.int32).max)
    prev_t = jnp.full(t.shape[:-1], jnp.iinfo(t.dtype).min, t.dtype)
    prev_c = jnp.full(t.shape[:-1], -1, jnp.int32)
    out_t, out_c, out_v = [], [], []
    for _ in range(k):
        after = (t > prev_t[..., None]) | (
            (t == prev_t[..., None]) & (c > prev_c[..., None])
        )
        t_r = jnp.min(jnp.where(after, t, tmax), axis=-1)
        c_r = jnp.min(
            jnp.where(after & (t == t_r[..., None]), c, big_c), axis=-1
        )
        out_t.append(t_r)
        out_c.append(c_r)
        out_v.append(t_r != tmax)
        prev_t, prev_c = t_r, c_r
    return (
        jnp.stack(out_t, axis=-1),
        jnp.stack(out_c, axis=-1),
        jnp.stack(out_v, axis=-1),
    )


def make_leap_fn(
    cfg: SwimConfig,
    k: int,
    constrain: Callable[[jax.Array], jax.Array] | None = None,
    hybrid: bool = False,
    masked: bool = False,
) -> Callable:
    """Build the jittable k-tick leap for a given protocol config.

    ``k`` is static (the span length folds into the compiled program — the
    warp runner's bounded cache holds one program per power-of-two bucket,
    never one per distinct span length). ``constrain`` is the sharding
    hook: applied to every scan carry each step, it keeps the GSPMD layout
    stable under the scan, like ``parallel.make_sharded_tick``'s per-tick
    constraint (the runner passes a row-axis pin built from
    ``parallel.row_matrix_sharding``).

    ``hybrid=True`` builds the Warp 2.0 **near-quiescent** program
    (``plan(graph, "hybrid")``): the strict span's draw/refresh machinery
    plus the sterile anti-entropy pass — per tick, the fused dense tick's
    phase-0/1 KnownPeersRequest partner selection (O(N) scatter-min, exact
    arrival-order priority), the request/reply timer marks folded into the
    same segmented scatter the ping marks use, and the kpr ledger carried
    through the scan. Under the activity signature's sterility bits
    (warp/horizon.py: no timer may expire in-span, no join owed, no
    waiting-on-alive cell, no Known-dead cell, no missing-alive member, no
    stale identity view) every AE reply share is a subset of the
    requester's membership, so fingerprints and membership are span
    invariants and the program is bit-equal to k dense fused ticks. A
    strictly-quiescent entry state is the degenerate case (no candidate
    ever matches), so the hybrid program is a strict superset of the span
    program, bit-for-bit.

    ``masked=True`` returns ``leap(st, k_m)`` with a *traced* span length
    ``k_m <= k``: steps beyond ``k_m`` are select-masked no-ops (key chain,
    timer scatters, ledger and tick counter all freeze), which is what
    lets the fleet runner vmap ONE compiled program over members leaping
    to their own per-member horizons. ``k_m == 0`` returns the state
    bit-unchanged.

    Precondition (checked by the caller via horizon signatures, never
    re-checked here): the state is in the program's signature class and
    the next ``k`` (or ``k_m``) ticks carry no scheduled events and end
    strictly before the earliest timer expiry. Under that precondition the
    result is bit-equal to the dense fault-free ticks (tests/test_warp.py
    pins it per state variant; tests/test_fuzz_parity.py fuzzes it through
    whole schedules).
    """
    if k < 1:
        raise ValueError("need k >= 1")
    # Derive the program from the op graph and pin it to what this module
    # implements: every op outside these passes must have been pruned by
    # the planner (as a span fixed point / by a signature bit).
    mode = "hybrid" if hybrid else "span"
    want = _HYBRID_PASSES if hybrid else _SPAN_PASSES
    prog = plan(build_graph(cfg, faulty=False), mode)
    got = {p.name: p.op_names for p in prog.tail}
    if got != want:
        raise NotImplementedError(
            f"{mode} plan {got} != leap implementation {want}"
        )
    det = cfg.deterministic
    kk = cfg.num_candidate_target_peers

    def pin(x: jax.Array) -> jax.Array:
        return constrain(x) if constrain is not None else x

    # Named scope: labels the leap's ops in jax.profiler captures (metadata
    # only — numerics and compiled-program identity are unchanged).
    @jax.named_scope("kaboodle:leap")
    def leap_impl(st: MeshState, k_m) -> MeshState:  # graftlint: traced
        n = st.state.shape[-1]
        n_cand = min(kk, n)
        W = min(_SEG_W, n)
        B = -(-n // W)  # segments per row
        pad = B * W - n
        idx = jnp.arange(n, dtype=jnp.int32)
        eye = idx[:, None] == idx[None, :]
        S, T, alive, lat = st.state, st.timer, st.alive, st.latency
        has_lat = lat is not None
        tmax = jnp.asarray(jnp.iinfo(T.dtype).max, dtype=T.dtype)
        tmin = jnp.asarray(jnp.iinfo(T.dtype).min, dtype=T.dtype)

        # The eligibility mask is a span invariant (membership and aliveness
        # are fixed points — in the hybrid class too: marks land only on
        # Known-of-alive cells, and sterile AE inserts nothing), so the
        # masked scores — exactly what the dense draw ranks — can be the
        # carry; every in-span write lands on an eligible cell (both
        # endpoints alive and mutually Known). Padded to the segment grid
        # with ineligible sentinel columns.
        elig = alive[:, None] & (S == KNOWN) & ~eye
        scores0 = jnp.pad(
            jnp.where(elig, T, tmax), ((0, 0), (0, pad)), constant_values=tmax
        )
        cols = jnp.broadcast_to(jnp.arange(B * W, dtype=jnp.int32)[None, :], (n, B * W))

        # Entry summaries: per (row, segment), the oldest-5 (timer, col)
        # pairs — one blocked reduction over the padded matrix, the only
        # O(N^2) pass besides the final merge-back.
        sum_t0, sum_c0, _ = _lex_k_smallest(
            scores0.reshape(n, B, W), cols.reshape(n, B, W), n_cand, tmax
        )  # [n, B, n_cand]

        if hybrid:
            # Span invariants the sterile AE pass ranks against: per-row
            # fingerprint and map size (S, idv are fixed points in-class).
            member = S > 0
            fp = membership_fingerprint(
                member, st.id_view if st.id_view is not None else st.identity
            )
            n_row = jnp.sum(member, axis=-1, dtype=jnp.int32)
        INF = jnp.int32(jnp.iinfo(jnp.int32).max)

        if not masked:
            # ---- the [k, ...] draw batch (counter-based PRNG) -------------
            # Warp 3.0: each in-span tick's ping key is a pure function of
            # (st.key, tick, STREAM_TICK_PING) — no chain to advance, so
            # the whole batch derives directly from the [k] tick vector and
            # the carried key plane is constant across the span.
            ticks = st.tick + jnp.arange(k, dtype=jnp.int32)  # [k] in-span ticks
            if det:
                xs = (ticks, jnp.zeros((k, 1), dtype=jnp.float32))  # u unused
            else:
                # dtype pinned f32 (KB401): must match the dense kernel's
                # pick_candidate uniforms bit-for-bit under any x64 flag.
                xs = (
                    ticks,
                    jax.vmap(
                        lambda tt: jax.random.uniform(
                            pg_rng.tick_stream_key(
                                st.key, tt, pg_rng.STREAM_TICK_PING
                            ),
                            (n,),
                            dtype=jnp.float32,
                        )
                    )(ticks),
                )
        else:
            # Masked mode: counter keys need no chain advance, so the step
            # index is the only scanned input — each active step derives its
            # ping key from (st.key, st.tick + step) in the body.
            xs = jnp.arange(k, dtype=jnp.int32)

        seg = jnp.arange(W, dtype=jnp.int32)[None, :]  # [1, W] within-segment

        def body(carry, x):
            if hybrid:
                scores, sum_t, sum_c, lat, kprp, kprf, kprn = carry
            else:
                scores, sum_t, sum_c, lat = carry
            if masked:
                step = x
                active = step < k_m
                t = st.tick + step
                u_t = (
                    None if det
                    else jax.random.uniform(
                        pg_rng.tick_stream_key(
                            st.key, t, pg_rng.STREAM_TICK_PING
                        ),
                        (n,),
                        dtype=jnp.float32,
                    )
                )
            else:
                t, u_t = x
                active = None
            tT = t.astype(scores.dtype)

            # Cross-segment selection: the global oldest-5 of a row is among
            # its per-segment oldest-5s, in the same stable (timer, col)
            # order the dense draw ranks.
            _, cand_idx, cand_valid = _lex_k_smallest(
                sum_t.reshape(n, B * n_cand),
                sum_c.reshape(n, B * n_cand),
                n_cand,
                tmax,
            )
            # The shared selection tail derives the candidate count from
            # the valid mask itself (dead/empty rows get 0 -> tgt = -1).
            u_sel = None if det else u_t
            tgt = pick_candidate(
                jnp.minimum(cand_idx, n - 1), cand_valid, u_sel
            )
            has_ping = tgt >= 0  # False exactly on dead/empty rows
            if masked:
                has_ping &= active
            tgtc = jnp.clip(tgt, 0)

            if hybrid:
                # Sterile anti-entropy: the fused dense tick's phase-0/1
                # candidate selection (exec.py _ae_phase01) against the
                # span-invariant (fp, n_row). Phase 0 — last tick's KPR
                # senders, priority = sender index — as an O(N) scatter-min
                # instead of the dense [N, N] compare (same minimum, bit
                # exact); phase 1 — this tick's delivered acks, priority =
                # n + target. Sender aliveness is NOT checked in phase 0
                # (matching the dense kernel: a dead sender's stale request
                # can win the priority and then fail delivery).
                ppc = jnp.clip(kprp, 0)
                cand0 = (kprp >= 0) & (kprf != fp[ppc]) & (n_row[ppc] <= kprn)
                prio0 = (
                    jnp.full((n,), INF, jnp.int32)
                    .at[ppc]
                    .min(jnp.where(cand0, idx, INF))
                )
                prio0 = jnp.where(alive, prio0, INF)
                # Phase 1: in-class every ping is delivered and acked
                # (targets are Known => alive), so del_ack == has_ping.
                tc = jnp.clip(tgt, 0)
                cand1 = has_ping & (fp[tc] != fp) & (n_row <= n_row[tc])
                prio1 = jnp.where(cand1, jnp.int32(n) + tgt, INF)
                best = jnp.minimum(prio0, prio1)
                partner = jnp.where(best == prio0, prio0, tgt).astype(jnp.int32)
                has_req = (best != INF) & alive
                partner = jnp.where(has_req, partner, -1)
                pc2 = jnp.clip(partner, 0)
                del_kpr = has_req & alive[pc2]
                if masked:
                    del_kpr &= active

                # Timer effect: ping A3 stamp + ack re-stamp at (i, tgt_i),
                # Q1 mark at (tgt_i, i), AE request mark at (partner_i, i)
                # and reply mark at (i, partner_i) — all writing this tick's
                # value, so one scatter-max with a dtype-min no-op sentinel
                # covers all four edge families.
                rows_u = jnp.concatenate([idx, tgtc, pc2, idx])
                cols_u = jnp.concatenate([tgtc, idx, idx, pc2])
                val = jnp.where(
                    jnp.concatenate([has_ping, has_ping, del_kpr, del_kpr]),
                    tT,
                    tmin,
                )
            else:
                # Cumulative timer effect of the tick's surviving traffic:
                # the A3 stamp + ack re-stamp at (i, tgt_i) and the Q1 mark
                # at (tgt_i, i), all writing the same tick value — a
                # scatter-max with a dtype-min no-op sentinel masks out
                # pingless rows, and duplicate edges (mutual pings) collide
                # on equal values.
                rows_u = jnp.concatenate([idx, tgtc])
                cols_u = jnp.concatenate([tgtc, idx])
                val = jnp.where(jnp.concatenate([has_ping, has_ping]), tT, tmin)
            scores = pin(scores.at[rows_u, cols_u].max(val))

            # Touched segments are re-reduced from the updated scores and
            # scattered back; every other segment's summary is untouched by
            # construction (an untouched listed segment rewrites its own
            # values — the masked/no-ping entries degenerate to that).
            blocks_u = cols_u // W
            seg_cols = blocks_u[:, None] * W + seg  # [2N or 4N, W] global cols
            seg_t = scores[rows_u[:, None], seg_cols]
            new_t, new_c, _ = _lex_k_smallest(seg_t, seg_cols, n_cand, tmax)
            sum_t = pin(sum_t.at[rows_u, blocks_u].set(new_t))
            sum_c = pin(sum_c.at[rows_u, blocks_u].set(new_c))

            if has_lat:
                # One zero-tick EWMA sample per pinged edge (module
                # docstring): NaN -> 0.0 first sample, else 0.2 * old. AE
                # marks never sample (their cells are not in a waiting
                # state in-class), so the hybrid adds nothing here.
                cur = lat[idx, tgtc]
                upd = jnp.where(
                    jnp.isnan(cur), jnp.float32(0.0), jnp.float32(0.2) * cur
                )
                lat = pin(lat.at[idx, tgtc].set(jnp.where(has_ping, upd, cur)))

            if hybrid:
                # The kpr ledger the dense _finish writes every tick:
                # partner where the request delivered, the (invariant)
                # fingerprint and map size. Frozen on masked-out steps.
                led_p = jnp.where(del_kpr, partner, -1)
                if masked:
                    kprp = jnp.where(active, led_p, kprp)
                    kprf = jnp.where(active, fp, kprf)
                    kprn = jnp.where(active, n_row, kprn)
                else:
                    kprp, kprf, kprn = led_p, fp, n_row
                return (scores, sum_t, sum_c, lat, kprp, kprf, kprn), None
            return (scores, sum_t, sum_c, lat), None

        if hybrid:
            carry0 = (
                pin(scores0), pin(sum_t0), pin(sum_c0), lat,
                st.kpr_partner, st.kpr_fp, st.kpr_n,
            )
            (scores_k, _, _, lat_k, kprp_k, kprf_k, kprn_k), _ = (
                jax.lax.scan(body, carry0, xs)
            )
        else:
            carry0 = (pin(scores0), pin(sum_t0), pin(sum_c0), lat)
            (scores_k, _, _, lat_k), _ = jax.lax.scan(body, carry0, xs)
            # Anti-entropy ledger at the span's final tick (fixed point,
            # written once): no request in flight, fingerprint + map size.
            fp = membership_fingerprint(
                S > 0, st.id_view if st.id_view is not None else st.identity
            )
            n_row = jnp.sum(S > 0, axis=-1, dtype=jnp.int32)
            kprp_k = jnp.full((n,), -1, dtype=jnp.int32)
            kprf_k, kprn_k = fp, n_row
            if masked:
                # k_m == 0 must leave the ledger untouched too.
                ran = k_m > 0
                kprp_k = jnp.where(ran, kprp_k, st.kpr_partner)
                kprf_k = jnp.where(ran, kprf_k, st.kpr_fp)
                kprn_k = jnp.where(ran, kprn_k, st.kpr_n)

        return dataclasses.replace(
            st,
            timer=jnp.where(elig, scores_k[:, :n], T),
            latency=lat_k,
            tick=st.tick + (k_m if masked else k),
            key=st.key,  # the carried key plane is constant under Warp 3.0
            kpr_partner=kprp_k,
            kpr_fp=kprf_k,
            kpr_n=kprn_k,
        )

    if masked:
        def leap(st: MeshState, k_m) -> MeshState:  # graftlint: traced
            return leap_impl(st, jnp.asarray(k_m, jnp.int32))
    else:
        def leap(st: MeshState) -> MeshState:  # graftlint: traced
            return leap_impl(st, None)

    # Program metadata for derived consumers (trace slices, registry, dryrun).
    leap.program = prog
    return leap
