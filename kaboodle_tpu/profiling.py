"""Tracing / profiling hooks for the simulator (SURVEY.md §5).

The reference's only observability is ``log::debug!`` lines per send/recv
(kaboodle.rs:190,213,266,404). The simulator gets three real tools:

- :func:`trace` — a context manager around the JAX profiler; the captured
  trace (TensorBoard / Perfetto format) shows the tick kernel's XLA ops,
  fusion boundaries, and HBM traffic on the device timeline.
- :func:`tick_stats` — per-tick structured metrics from a scan run, as a
  NumPy record table (the tensor-reduction metrics are computed on device by
  the kernel for free; this just fetches and tabulates).
- :func:`log_run` — a compact human-readable per-tick trace of a run, the
  simulator twin of the reference's RUST_LOG=debug output.
"""

from __future__ import annotations

import contextlib

import numpy as np

from kaboodle_tpu.sim.state import TickMetrics


def leaf_equal(a, b) -> bool:
    """Bit-equality of two pytree leaves, NaN==NaN on the latency plane.

    THE bit-exactness predicate of every A/B and dryrun gate (`bench.py`
    --warp / --telemetry-ab / --fastpath-ab, `python -m kaboodle_tpu
    phasegraph`): shape and dtype must match exactly, values must match
    bitwise, and on floating leaves NaN positions must coincide (the
    latency EWMA plane carries NaN for never-measured edges). One
    definition, one home — a lane-local copy that drifts redefines what
    "bit-exact" means for that gate only.
    """
    av, bv = np.asarray(a), np.asarray(b)
    if av.shape != bv.shape or av.dtype != bv.dtype:
        return False
    if np.issubdtype(av.dtype, np.floating):
        return bool(((av == bv) | (np.isnan(av) & np.isnan(bv))).all())
    return bool((av == bv).all())


def state_equal(a, b) -> bool:
    """:func:`leaf_equal` over two whole pytrees (states, metrics)."""
    import jax

    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(leaf_equal(x, y) for x, y in zip(la, lb))


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a JAX profiler trace of everything run inside the block.

    Thin delegation to ``jax.profiler.trace`` (kept as the package's named
    entry point for SURVEY.md §5 discoverability). View with TensorBoard
    (`tensorboard --logdir <log_dir>`) or upload the .trace.json.gz to
    Perfetto. First-compile noise included; for clean kernel timings run one
    warmup call before entering.
    """
    import jax

    with jax.profiler.trace(log_dir):
        yield


def tick_stats(metrics: TickMetrics) -> np.ndarray:
    """Stacked per-tick metrics (from ``simulate``) -> structured NumPy table.

    Fields mirror TickMetrics; one row per tick.
    """
    msgs = np.asarray(metrics.messages_delivered)
    out = np.zeros(
        msgs.shape[0],
        dtype=[
            ("tick", np.int32),
            ("messages_delivered", np.int32),
            ("converged", bool),
            ("agree_fraction", np.float32),
            ("mean_membership", np.float32),
            ("fingerprint_min", np.uint32),
            ("fingerprint_max", np.uint32),
        ],
    )
    out["tick"] = np.arange(msgs.shape[0])
    out["messages_delivered"] = msgs
    out["converged"] = np.asarray(metrics.converged)
    out["agree_fraction"] = np.asarray(metrics.agree_fraction)
    out["mean_membership"] = np.asarray(metrics.mean_membership)
    out["fingerprint_min"] = np.asarray(metrics.fingerprint_min)
    out["fingerprint_max"] = np.asarray(metrics.fingerprint_max)
    return out


def fleet_tick_stats(metrics: TickMetrics, member: int) -> np.ndarray:
    """One ensemble member's per-tick table from fleet-stacked metrics.

    ``metrics`` comes from :func:`kaboodle_tpu.fleet.simulate_fleet` (leaves
    ``[T, E]``); this slices member ``member`` and delegates to
    :func:`tick_stats`. For whole-ensemble statistics use the on-device
    reductions in ``kaboodle_tpu.fleet.stats`` — slicing E members through
    here is exactly the per-member host round-trip the fleet design avoids.
    """
    import jax

    return tick_stats(jax.tree.map(lambda a: a[:, member], metrics))


def fleet_run_stats(metrics: TickMetrics) -> np.ndarray:
    """Per-tick ensemble summary of a fleet scan, as a NumPy record table.

    One row per tick: converged-member count and the agree-fraction
    mean/min over the ensemble — the host-side rendering of
    ``kaboodle_tpu.fleet.stats.agree_fraction_trajectory`` for quick
    inspection (the reductions run on device; only [T]-vectors land here).
    """
    from kaboodle_tpu.fleet.stats import agree_fraction_trajectory

    traj = agree_fraction_trajectory(metrics)
    ticks = np.asarray(traj["mean"]).shape[0]
    ensemble = np.asarray(metrics.converged).shape[-1]
    out = np.zeros(
        ticks,
        dtype=[
            ("tick", np.int32),
            ("converged_members", np.int32),
            ("agree_fraction_mean", np.float32),
            ("agree_fraction_min", np.float32),
        ],
    )
    out["tick"] = np.arange(ticks)
    out["converged_members"] = np.round(
        np.asarray(traj["converged_fraction"]) * ensemble
    ).astype(np.int32)
    out["agree_fraction_mean"] = np.asarray(traj["mean"])
    out["agree_fraction_min"] = np.asarray(traj["min"])
    return out


def warp_stats(dense_ticks, metrics: TickMetrics | None) -> np.ndarray:
    """Per-dense-tick table of a warped run, tick column = real tick index.

    ``(dense_ticks, metrics)`` come from
    :func:`kaboodle_tpu.warp.runner.simulate_warped`: only the densely
    executed ticks carry metrics. Strict-leaped spans are provably
    converged and quiet (their rows would be constant); HYBRID-leaped
    spans (Warp 2.0 near-quiescent drain windows) are NOT converged —
    armed timers and disagreeing fingerprints persist through them — so a
    gap between rows means only "nothing the span programs cannot model
    happened", not "all quiet": consult the run's ``WarpLedger`` per-class
    records for what each gap was. The returned table is
    :func:`tick_stats`' layout with the ``tick`` column rewritten to the
    actual tick indices — gaps between consecutive rows are exactly the
    leaped spans. ``None`` metrics (everything leaped) gives an empty table.
    """
    if metrics is None:
        return np.zeros(0, dtype=tick_stats(
            TickMetrics(*(np.zeros((0,)),) * 6)).dtype)
    out = tick_stats(metrics)
    out["tick"] = np.asarray(dense_ticks)
    return out


def warp_summary(dense_ticks, total_ticks: int,
                 metrics: TickMetrics | None = None) -> dict:
    """Ratio-style summary of a warped run, safe at every degenerate shape.

    An already-converged entry state leaps the WHOLE schedule (zero dense
    ticks, ``metrics is None``) — and a zero-length schedule runs nothing at
    all — so every ratio here guards its denominator instead of trusting
    the caller: ``dense_fraction``/``leaped_fraction`` are 0.0/1.0 on an
    all-leaped run and both 0.0 on an empty one, and
    ``mean_msgs_per_dense_tick`` is 0.0 when no dense tick executed.
    """
    dense = int(np.asarray(dense_ticks).size)
    total = int(total_ticks)
    if dense > total:
        raise ValueError(f"dense_ticks ({dense}) exceeds total_ticks ({total})")
    msgs = (
        int(np.asarray(metrics.messages_delivered).sum())
        if metrics is not None
        else 0
    )
    return {
        "total_ticks": total,
        "dense_ticks": dense,
        "leaped_ticks": total - dense,
        "dense_fraction": dense / total if total else 0.0,
        "leaped_fraction": (total - dense) / total if total else 0.0,
        "messages_delivered": msgs,
        "mean_msgs_per_dense_tick": msgs / dense if dense else 0.0,
    }


def log_run(metrics: TickMetrics, emit=print) -> None:
    """Per-tick one-liners (the RUST_LOG=debug analogue, main.rs:54-58)."""
    for row in tick_stats(metrics):
        emit(
            f"tick {row['tick']:>4}: msgs={row['messages_delivered']:<6} "
            f"agree={row['agree_fraction']:.3f} "
            f"members={row['mean_membership']:.1f} "
            f"fp=[{row['fingerprint_min']:08x},{row['fingerprint_max']:08x}]"
            f"{' CONVERGED' if row['converged'] else ''}"
        )
