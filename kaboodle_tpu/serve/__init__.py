"""serve — gossip-as-a-service: continuous-batching simulation serving.

The ROADMAP's "heavy traffic from millions of users" demands a *runtime*,
not another batch CLI: this package keeps one resident fixed-shape ``[E]``
fleet per N-class and admits concurrent simulation requests into its free
lanes MID-FLIGHT — a converged lane is frozen, harvested and re-seeded with
the next request's seed/knobs without ever leaving the compiled step
program (the whole admission surface is traced), so a warmed server
compiles nothing, whatever the request mix does.

Layers:

- pool.py    — the lane pool: fixed-shape FleetState + per-lane generation
               counters, on-device admit/retire/re-seed, pow2 N-classes.
- shardpool.py — the GSPMD twin: the same pool resident on a fleet device
               mesh (lanes across chips; rows too on a 2-D mesh).
- engine.py  — the resident step loop: the phasegraph serve step (masked
               converge chunks) composed with the per-member fleet warp
               (quiescent horizon-mode lanes fast-forward, hot lanes tick
               dense), plus request bookkeeping and lane spill.
- server.py  — asyncio JSON-over-TCP front end (submit/status/cancel/
               stream) streaming ``kaboodle-telemetry/1`` records live.
- client.py  — the asyncio client + a one-shot synchronous helper.
- loadgen.py — closed+open-loop load driver (BENCH_serve.json).
- dryrun.py  — the CI lane: in-process server, toy requests, schema-checked
               manifest, zero-fresh-compiles assertion.
- federation/ — the multi-engine tier: consistent-hash router, shared
               spill root, WAL failover, fed-load driver.
"""

from kaboodle_tpu.serve.engine import ServeEngine, ServeRequest
from kaboodle_tpu.serve.pool import LanePool, lane_n_class
from kaboodle_tpu.serve.shardpool import ShardedLanePool

__all__ = [
    "LanePool",
    "ServeEngine",
    "ServeRequest",
    "ShardedLanePool",
    "lane_n_class",
]
