"""Admission control: quotas, priorities, backpressure, load shedding.

PR 10's front door was wide open: any client could queue unbounded work
and the engine would dutifully FIFO it. This module is the policy layer
the engine (and through it ``server.py``) consults at submit time:

- **per-tenant token buckets**: each tenant refills at ``rate`` requests/s
  up to ``burst``; an empty bucket rejects with :class:`QuotaError`
  carrying ``retry_after_s`` (when one token will exist). The clock is
  injectable, so tests and the chaos harness drive it deterministically.
- **bounded submit queue**: more than ``max_queue`` queued requests
  rejects with :class:`QueueFullError` + retry-after — unless the
  newcomer strictly outranks the lowest-priority queued request, in which
  case the engine sheds that victim instead (lowest priority first,
  oldest within a class), emitted as a ``shed`` event.
- **priority classes**: higher ``ServeRequest.priority`` admits first
  (FIFO within a class), and under lane pressure a strictly
  lower-priority PARKED lane may be preempted — spill-evicted to make
  room. Running lanes are never preempted: a dispatched round is paid
  for, and eviction mid-run would forfeit it.

Policy decisions live here; mechanism (who owns which lane, spill I/O)
stays in the engine. Everything host-side, nothing traced.
"""

from __future__ import annotations

import time

from kaboodle_tpu.errors import KaboodleError


class AdmissionError(KaboodleError):
    """Base for structured submit rejections (carries ``retry_after_s``)."""

    kind = "rejected"

    def __init__(self, msg: str, retry_after_s: float = 0.0) -> None:
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class QueueFullError(AdmissionError):
    """Submit queue at capacity and nobody shed — back off and retry."""

    kind = "queue_full"


class QuotaError(AdmissionError):
    """The tenant's token bucket is empty — back off and retry."""

    kind = "quota"


class TokenBucket:
    """Monotonic-clock token bucket: ``rate`` tokens/s, cap ``burst``."""

    def __init__(self, rate: float, burst: float, clock=time.monotonic):
        if rate <= 0 or burst <= 0:
            raise ValueError("need rate > 0 and burst > 0")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()

    def _refill(self) -> None:
        now = self._clock()
        self._tokens = min(
            self.burst, self._tokens + (now - self._last) * self.rate
        )
        self._last = now

    def try_take(self, n: float = 1.0) -> bool:
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def retry_after(self, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will exist (0 when they already do)."""
        self._refill()
        if self._tokens >= n:
            return 0.0
        return (n - self._tokens) / self.rate


class AdmissionController:
    """The submit-time policy gate the engine consults.

    ``quotas`` maps tenant -> ``(rate, burst)``; ``default_quota`` covers
    unlisted tenants (None = unmetered). ``max_queue`` bounds QUEUED
    requests engine-wide. Stateless about lanes — the engine owns those.
    """

    def __init__(
        self,
        max_queue: int = 64,
        quotas: dict[str, tuple[float, float]] | None = None,
        default_quota: tuple[float, float] | None = None,
        clock=time.monotonic,
    ) -> None:
        if max_queue < 1:
            raise ValueError("need max_queue >= 1")
        self.max_queue = int(max_queue)
        self._clock = clock
        self._quota_spec = dict(quotas or {})
        self._default_quota = default_quota
        self._buckets: dict[str, TokenBucket] = {}

    def _bucket(self, tenant: str) -> TokenBucket | None:
        b = self._buckets.get(tenant)
        if b is not None:
            return b
        spec = self._quota_spec.get(tenant, self._default_quota)
        if spec is None:
            return None
        b = TokenBucket(spec[0], spec[1], clock=self._clock)
        self._buckets[tenant] = b
        return b

    def check_quota(self, tenant: str) -> None:  # conc: event-loop
        """Take one token for ``tenant`` or raise :class:`QuotaError`."""
        b = self._bucket(tenant)
        if b is None or b.try_take():
            return
        raise QuotaError(
            f"tenant {tenant!r} over quota", retry_after_s=b.retry_after()
        )

    def snapshot(self) -> dict:  # conc: event-loop
        """Point-in-time view for the metrics plane: queue bound plus the
        live token balance per tenant (refilled first, so the gauge reads
        what ``try_take`` would see). Only tenants that have actually
        submitted appear — buckets are lazily materialized."""
        tenants = {}
        for tenant, b in self._buckets.items():
            b._refill()
            tenants[tenant] = {
                "tokens": b._tokens,
                "rate": b.rate,
                "burst": b.burst,
            }
        return {"max_queue": self.max_queue, "tenants": tenants}

    def check_queue(self, queued: int) -> None:  # conc: event-loop
        """Raise :class:`QueueFullError` when the queue is at capacity.

        The engine calls this AFTER trying to shed a lower-priority queued
        victim; retry-after is a queue-drain heuristic (half the queue at
        one admission per idle poll), honest about being an estimate."""
        if queued < self.max_queue:
            return
        raise QueueFullError(
            f"submit queue full ({queued}/{self.max_queue})",
            retry_after_s=0.05 * max(1, queued // 2),
        )
