"""Deterministic fault injection for the serve stack (CI chaos lane).

``make serve-chaos-dryrun`` (= ``python -m kaboodle_tpu serve
--chaos-dryrun``) runs six scripted failure scenarios against real
engines/servers in this process, each asserting the invariant that makes
it worth having:

1. **spill_latency** — the round loop must not stall on spill I/O: with
   large-member spills in flight, per-round latency stays within 10% (+ a
   small absolute timer-noise floor) of a no-spill run; the synchronous
   write path is measured FIRST as the baseline the async path replaces.
2. **engine_kill** — an engine abandoned mid-service (journal + spill
   files left behind) recovers: completed requests keep their results and
   are never re-run (no duplicate completion in the journal), spilled
   requests re-attach and their restore→resume continuations land
   leaf-for-leaf on an uninterrupted twin engine's states, in-flight
   requests re-queue and complete.
3. **spill_write_failure** — an injected write failure degrades the lane
   back to parked with a loud ``spill_failed`` event (never an exception
   mid-round), the next idle countdown retries and succeeds, and the
   request survives restore+resume. Service to other lanes continues
   throughout.
4. **corrupt_restore** — a truncated spill file turns restore into a
   structured ``CheckpointError`` + ``restore_failed`` event; the request
   stays spilled (retryable), the engine keeps serving new work.
5. **slow_consumer** — a stream subscriber that stops reading loses
   events into a counted ``stream_gap`` record instead of wedging the
   server; ops and other requests are unaffected while it stalls.
6. **submit_flood** — an open-loop burst 10× over capacity against
   admission control: rejections are structured ``queue_full`` errors
   carrying retry-after, higher-priority arrivals shed the lowest queued,
   every admitted request completes within a (generous) SLO, quota'd
   tenants get ``quota`` rejections that a retrying client rides out.

Every device-touching phase runs after warmup under the KB405 compile
counter and asserts ZERO fresh compiles — chaos must not cost the
zero-recompile contract. All schedules are fixed-seed deterministic.
"""

from __future__ import annotations

import asyncio
import json
import os
import tempfile
import time

import jax
import numpy as np

CHAOS_SEED = 1207  # fixed: every schedule below derives from constants

_WAIT_S = 60.0


def _leaves_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    if len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        if x.shape != y.shape or x.dtype != y.dtype:
            return False
        eq = np.array_equal(
            x, y, equal_nan=np.issubdtype(x.dtype, np.floating)
        )
        if not eq:
            return False
    return True


def _percentile_ms(lat_s, q) -> float:
    # Host-list percentile over a few hundred floats, not a device fetch.
    return float(np.percentile(np.asarray(lat_s, dtype=np.float64) * 1e3, q))  # noqa: KB501


# -- 1. spill latency: the async round loop never stalls on disk -----------


def scenario_spill_latency() -> dict:
    from kaboodle_tpu.analysis.ir.surface import compile_counter
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.serve.engine import ServeEngine, ServeRequest
    from kaboodle_tpu.serve.pool import LanePool

    cfg = SwimConfig(deterministic=True)
    rounds = 40

    def measure(sync: bool | None) -> list[float]:
        """Per-round latencies for one arm: a long horizon runner plus 3
        kept lanes that all spill mid-measurement (``sync=None`` disables
        spilling — the no-spill baseline)."""
        tmp = tempfile.mkdtemp(prefix="kaboodle-chaos-spill-")
        engine = ServeEngine(
            [LanePool(32, 4, cfg=cfg, chunk=8)], warp=False,
            sync_spill=bool(sync),
        )
        engine.warmup()
        runner = engine.submit(ServeRequest(
            n=32, seed=1, mode="ticks", ticks=8 * (rounds + 8),
            scenario="steady",
        ))
        kept = [
            engine.submit(ServeRequest(n=32, seed=10 + i, mode="ticks",
                                       ticks=8, scenario="steady",
                                       keep=True))
            for i in range(3)
        ]
        while any(engine.status(r)["state"] != "parked" for r in kept):
            engine.step()
        if sync is not None:  # arm the spill mid-flight, then measure
            engine.spill_dir = tmp
            engine.spill_after = 0
        lat = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            engine.step()
            lat.append(time.perf_counter() - t0)
        assert engine.status(runner)["state"] == "running", "runner starved"
        if sync is not None:
            engine.settle_spills()
            for r in kept:
                assert engine.status(r)["state"] == "spilled", (
                    r, engine.status(r))
        engine.close()
        return lat

    # Prime the n=32 program family once, outside the counter: each arm's
    # pool rebuild then re-dispatches the process-cached programs.
    prime = ServeEngine([LanePool(32, 4, cfg=cfg, chunk=8)], warp=False)
    prime.warmup()
    with compile_counter() as box:
        base = measure(None)
        sync_arm = measure(True)  # the pre-hardening baseline, first
        async_arm = measure(False)
    assert box.count == 0, f"{box.count} fresh compiles during spill arms"

    def _mean_ms(lat_s) -> float:
        return float(np.mean(np.asarray(lat_s, dtype=np.float64)) * 1e3)

    out = {
        "rounds": rounds,
        "no_spill_mean_ms": _mean_ms(base),
        "no_spill_p50_ms": _percentile_ms(base, 50),
        "no_spill_p99_ms": _percentile_ms(base, 99),
        "sync_mean_ms": _mean_ms(sync_arm),
        "sync_p50_ms": _percentile_ms(sync_arm, 50),
        "sync_p99_ms": _percentile_ms(sync_arm, 99),
        "async_mean_ms": _mean_ms(async_arm),
        "async_p50_ms": _percentile_ms(async_arm, 50),
        "async_p99_ms": _percentile_ms(async_arm, 99),
    }
    # The acceptance gate: mean round latency with spills in flight within
    # 10% of the no-spill baseline (+0.5ms absolute floor so micro-round
    # timer noise cannot flake CI). Mean, not p99: over 40 rounds p99 is
    # the max, and a background writer briefly competing for cores is not
    # a round-loop stall — the sync baseline, which DOES stall the loop
    # for every write+fsync, is what this gate separates us from.
    limit = out["no_spill_mean_ms"] * 1.10 + 0.5
    assert out["async_mean_ms"] <= limit, (
        f"async spill stalls the round loop: mean "
        f"{out['async_mean_ms']:.3f}ms > {limit:.3f}ms "
        f"(no-spill mean {out['no_spill_mean_ms']:.3f}ms)"
    )
    out["async_within_10pct"] = True
    return out


# -- 2. engine kill: journal recovery, bit-exact continuations -------------


def scenario_engine_kill() -> dict:
    from kaboodle_tpu.analysis.ir.surface import compile_counter
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.serve.engine import ServeEngine, ServeRequest
    from kaboodle_tpu.serve.pool import LanePool

    cfg = SwimConfig(deterministic=True)
    tmp = tempfile.mkdtemp(prefix="kaboodle-chaos-kill-")
    jdir = os.path.join(tmp, "journal")
    sdir = os.path.join(tmp, "spill")
    os.makedirs(sdir)

    reqs = [
        ServeRequest(n=16, seed=13, mode="ticks", ticks=24,
                     scenario="steady", keep=True),   # parks -> spills
        ServeRequest(n=16, seed=2, mode="converge", ticks=40),  # completes
        # Long enough that it CANNOT finish between admission and the kill
        # point however slow the spill write is — the kill must interrupt
        # it mid-flight so recovery has something to re-queue.
        ServeRequest(n=16, seed=5, mode="ticks", ticks=800,
                     scenario="steady"),
    ]

    def drive_to_kill_point(engine, rids):
        for _ in range(400):
            engine.step()
            if (engine.status(rids[0])["state"] == "spilled"
                    and engine.status(rids[1])["state"] == "done"):
                return
        raise AssertionError("kill point never reached")

    # Uninterrupted twin: the ground truth for every request.
    twin = ServeEngine(
        [LanePool(16, 2, cfg=cfg, chunk=8)], warp=False,
        spill_after=0, spill_dir=os.path.join(tmp, "twin-spill"),
    )
    os.makedirs(twin.spill_dir, exist_ok=True)
    twin.warmup()

    victim = ServeEngine(
        [LanePool(16, 2, cfg=cfg, chunk=8)], warp=False,
        spill_after=0, spill_dir=sdir, journal_dir=jdir,
    )
    victim.warmup()

    with compile_counter() as box:
        t_rids = [twin.submit(r) for r in reqs]
        drive_to_kill_point(twin, t_rids)
        assert twin.restore(t_rids[0])
        # Disarm idle-spill: the continuation parks again (keep=True) and
        # must still hold its lane when the member is fetched below.
        twin.spill_after = None
        twin.resume(t_rids[0], mode="ticks", ticks=16)
        twin.drain()
        want_member = twin.pools[16].member(twin.status(t_rids[0])["lane"])
        want = {rid: twin.status(rid)["result"] for rid in t_rids}

        v_rids = [victim.submit(r) for r in reqs]
        drive_to_kill_point(victim, v_rids)
        pre_kill_r1 = victim.status(v_rids[1])["result"]
        # KILL: abandon the engine object mid-service. No close(), no
        # flush — exactly what a crashed process leaves behind: the WAL
        # (flushed per append) and the durable spill files.
        del victim

        recovered = ServeEngine(
            [LanePool(16, 2, cfg=cfg, chunk=8)], warp=False,
            spill_dir=sdir, journal_dir=jdir,
        )
        recovered.warmup()
        counts = recovered.recover()
        assert counts == {"done": 1, "spilled": 1, "requeued": 1,
                          "cancelled": 0, "dropped": 0}, counts

        # Replay nothing twice: the completed request keeps its pre-crash
        # result verbatim and is not re-admitted.
        r_done = recovered.status(v_rids[1])
        assert r_done["state"] == "done"
        assert r_done["result"] == pre_kill_r1 == want[t_rids[1]]

        # The spilled request re-attaches and its continuation lands
        # bit-exactly on the uninterrupted twin's state.
        assert recovered.status(v_rids[0])["state"] == "spilled"
        assert recovered.restore(v_rids[0])
        recovered.resume(v_rids[0], mode="ticks", ticks=16)
        drained = recovered.drain()
        row0 = recovered.status(v_rids[0])
        assert row0["result"] == want[t_rids[0]], (row0["result"],
                                                   want[t_rids[0]])
        got_member = recovered.pools[16].member(row0["lane"])
        assert _leaves_equal(got_member, want_member), (
            "recovered continuation diverged from the uninterrupted twin"
        )

        # The in-flight request re-queued and re-ran to the same answer.
        row2 = recovered.status(v_rids[2])
        assert row2["state"] == "done"
        assert row2["result"] == want[t_rids[2]]
    assert box.count == 0, f"{box.count} fresh compiles across kill+recovery"

    # No duplicate completion: recovery compacted the journal, so the WAL
    # holds only post-recovery transitions — none may belong to the
    # already-completed request.
    dup = [
        rec for rec in map(json.loads, open(os.path.join(jdir, "wal.jsonl")))
        if rec["rid"] == v_rids[1]
    ]
    assert not dup, f"journal replayed the completed request: {dup}"
    terminal = [e for e in drained if e.get("request_id") == v_rids[1]
                and e.get("event") in ("converged", "completed", "exhausted")]
    assert not terminal, "duplicate completion event after recovery"
    recovered.close()
    return {"recovered": counts, "bit_exact": True,
            "duplicate_completions": 0}


# -- 3. spill write failure: degrade loudly, retry, never lose -------------


def scenario_spill_write_failure() -> dict:
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.serve.engine import ServeEngine, ServeRequest
    from kaboodle_tpu.serve.pool import LanePool

    cfg = SwimConfig(deterministic=True)
    tmp = tempfile.mkdtemp(prefix="kaboodle-chaos-wfail-")
    engine = ServeEngine(
        [LanePool(16, 2, cfg=cfg, chunk=8)], warp=False,
        spill_after=1, spill_dir=tmp,
    )
    engine.warmup()
    kept = engine.submit(ServeRequest(n=16, seed=13, mode="ticks", ticks=16,
                                      scenario="steady", keep=True))
    engine.drain()
    assert engine.status(kept)["state"] == "parked"

    engine.spiller.fail_next(1)
    events: list[dict] = []
    for _ in range(200):
        events.extend(engine.step())
        if any(e.get("event") == "spill_failed" for e in events):
            break
        # With nothing running a round is microseconds — give the writer
        # thread a slice, or 200 rounds spin before it ever executes.
        time.sleep(0.002)
    else:
        raise AssertionError("injected spill failure never surfaced")
    row = engine.status(kept)
    assert row["state"] == "parked", row  # degraded, lane held, no raise
    assert row["lane"] is not None

    # Service continues while the retry rides the next idle countdown.
    other = engine.submit(ServeRequest(n=16, seed=3, mode="converge",
                                       ticks=40))
    for _ in range(400):
        events.extend(engine.step())
        if engine.status(kept)["state"] == "spilled":
            break
        time.sleep(0.002)
    else:
        raise AssertionError("spill retry never succeeded")
    assert engine.status(other)["state"] == "done"
    assert os.path.exists(engine.status(kept)["spill_path"])

    # The request is intact end to end: restore + resume completes.
    assert engine.restore(kept)
    engine.resume(kept, mode="ticks", ticks=8)
    engine.drain()
    assert engine.status(kept)["result"]["ticks_run"] == 24
    engine.close()
    kinds = [e.get("event") for e in events]
    return {"spill_failed_events": kinds.count("spill_failed"),
            "recovered_spill": True}


# -- 4. corrupt spill file: structured restore failure, service intact -----


def scenario_corrupt_restore() -> dict:
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.errors import CheckpointError
    from kaboodle_tpu.serve.engine import ServeEngine, ServeRequest
    from kaboodle_tpu.serve.pool import LanePool

    cfg = SwimConfig(deterministic=True)
    tmp = tempfile.mkdtemp(prefix="kaboodle-chaos-corrupt-")
    events: list[dict] = []
    engine = ServeEngine(
        [LanePool(16, 2, cfg=cfg, chunk=8)], warp=False,
        spill_after=0, spill_dir=tmp, on_event=events.append,
    )
    engine.warmup()
    kept = engine.submit(ServeRequest(n=16, seed=13, mode="ticks", ticks=16,
                                      scenario="steady", keep=True))
    engine.drain()
    engine.settle_spills()
    path = engine.status(kept)["spill_path"]
    assert engine.status(kept)["state"] == "spilled"

    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[: len(blob) // 3])  # truncated mid-write, post-rename

    try:
        engine.restore(kept)
        raise AssertionError("restore of a truncated spill did not raise")
    except CheckpointError:
        pass
    assert engine.status(kept)["state"] == "spilled"  # retryable, not lost
    assert any(e.get("event") == "restore_failed" for e in events)

    # The engine is unharmed: new work runs to completion.
    other = engine.submit(ServeRequest(n=16, seed=4, mode="converge",
                                       ticks=40))
    engine.drain()
    assert engine.status(other)["state"] == "done"
    engine.close()
    return {"structured_failure": True, "engine_survived": True}


# -- 5. slow stream consumer: bounded queues, gap records ------------------


async def _slow_consumer_exercise() -> dict:
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.serve.client import ServeClient
    from kaboodle_tpu.serve.engine import ServeEngine, ServeRequest
    from kaboodle_tpu.serve.pool import LanePool
    from kaboodle_tpu.serve.server import ServeServer, _Subscriber
    from kaboodle_tpu.telemetry.manifest import run_record

    # The queue mechanics, driven to overflow deterministically.
    sub = _Subscriber(maxsize=4)
    for i in range(20):
        sub.push(run_record("serve_event", event="x", lane=-1, i=i))
    drained = []
    while not sub.q.empty():
        drained.append(sub.q.get_nowait())
    assert len(drained) == 4 and sub.dropped == 16, (len(drained), sub.dropped)
    sub.push(run_record("serve_event", event="y", lane=-1))
    gap = sub.q.get_nowait()
    assert gap["kind"] == "stream_gap" and gap["dropped"] == 16, gap
    assert sub.q.get_nowait()["event"] == "y"

    # The live shape: a subscriber that never reads must not stall ops or
    # other requests.
    cfg = SwimConfig(deterministic=True)
    engine = ServeEngine([LanePool(16, 2, cfg=cfg, chunk=8)], warp=False)
    server = ServeServer(engine, port=0, stream_queue=8)
    engine.warmup()
    await server.start()
    reader, writer = await asyncio.open_connection("127.0.0.1", server.port)
    writer.write(json.dumps({"op": "stream"}).encode() + b"\n")
    await writer.drain()
    await reader.readline()  # ack — then never read again

    client = await ServeClient.connect(port=server.port)
    rids = [
        await client.submit(16, seed=i, mode="converge", ticks=40,
                            timeout=_WAIT_S)
        for i in range(6)
    ]
    for rid in rids:
        row = await client.wait(rid, timeout=_WAIT_S)
        assert row["state"] == "done", row
    stats = await client.stats(timeout=_WAIT_S)
    await client.shutdown()
    await server.close()
    writer.close()
    return {"gap_records": True, "dropped_counted": 16,
            "requests_served_past_stalled_stream": len(rids),
            "rounds": stats["round"]}


def scenario_slow_consumer() -> dict:
    return asyncio.run(_slow_consumer_exercise())


# -- 6. submit flood: admission control under 10x overload -----------------


async def _submit_flood_exercise() -> dict:
    from kaboodle_tpu.analysis.ir.surface import compile_counter
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.serve.admission import AdmissionController
    from kaboodle_tpu.serve.client import ServeClient, ServeError
    from kaboodle_tpu.serve.engine import ServeEngine, ServeRequest
    from kaboodle_tpu.serve.pool import LanePool
    from kaboodle_tpu.serve.server import ServeServer

    cfg = SwimConfig(deterministic=True)
    admission = AdmissionController(
        max_queue=6, quotas={"metered": (2.0, 2.0)}
    )
    engine = ServeEngine(
        [LanePool(16, 2, cfg=cfg, chunk=8)], warp=False,
        admission=admission, spill_after=None,
        spill_dir=tempfile.mkdtemp(prefix="kaboodle-chaos-flood-"),
    )
    events: list[dict] = []
    server = ServeServer(engine, port=0)
    downstream = engine.on_event

    def tap(rec):
        events.append(rec)
        downstream(rec)

    engine.on_event = tap
    engine.warmup()
    await server.start()
    client = await ServeClient.connect(port=server.port)

    # Warm wave (uncounted): both request shapes through the pool.
    for i in range(2):
        rid = await client.submit(16, seed=i, timeout=_WAIT_S,
                                  **_flood_fields(i))
        await client.wait(rid, timeout=_WAIT_S)

    capacity = 2 + admission.max_queue  # lanes + queue slots
    flood = 10 * capacity
    admitted: list[int] = []
    rejected: list[dict] = []
    with compile_counter() as box:
        t0 = time.perf_counter()
        # Open-loop burst: pipeline every submit line on a raw connection
        # BEFORE reading any response. A closed-loop client can never
        # outrun the engine — every awaited roundtrip lets rounds retire
        # work — so overload has to arrive as buffered back-to-back ops.
        f_reader, f_writer = await asyncio.open_connection(
            "127.0.0.1", server.port
        )
        for i in range(flood):
            op = {"op": "submit", "n": 16, "seed": 100 + i,
                  "tenant": f"t{i % 3}", "priority": i % 3,
                  **_flood_fields(i)}
            f_writer.write(json.dumps(op).encode() + b"\n")
        await f_writer.drain()
        for _ in range(flood):
            resp = json.loads(await asyncio.wait_for(
                f_reader.readline(), _WAIT_S))
            if resp.get("ok"):
                admitted.append(resp["request_id"])
            else:
                assert resp.get("kind") == "queue_full", resp
                assert resp.get("retry_after_s", 0) > 0, (
                    "queue_full without retry-after")
                rejected.append(resp)
        f_writer.close()
        lat: list[float] = []
        completed = 0
        for rid in admitted:
            row = await client.wait(rid, timeout=_WAIT_S)
            if row["state"] == "done":
                completed += 1
                lat.append(time.perf_counter() - t0)  # burst-to-completion
            else:
                assert row["state"] == "cancelled", row  # shed
        elapsed = time.perf_counter() - t0
    assert box.count == 0, f"{box.count} fresh compiles during the flood"

    sheds = [e for e in events if e.get("event") == "shed"]
    rejects = [e for e in events if e.get("event") == "rejected"]
    assert rejected, "a 10x flood produced no queue_full backpressure"
    assert len(rejects) >= len(rejected)
    assert sheds, "no higher-priority arrival ever shed the lowest queued"
    assert completed, "nothing survived admission"
    p99 = _percentile_ms(lat, 99) if lat else 0.0
    assert p99 < 30_000, f"admitted p99 {p99:.0f}ms blew the SLO"

    # Quota arm: a metered tenant runs dry with retry-after, and the
    # retrying client path rides it out.
    quota_rejects = 0
    for i in range(5):
        try:
            rid = await client.submit(16, seed=500 + i, timeout=_WAIT_S,
                                      tenant="metered", **_flood_fields(i))
            await client.wait(rid, timeout=_WAIT_S)
        except ServeError as e:
            assert e.kind == "quota", e.kind
            assert e.retry_after_s > 0
            quota_rejects += 1
    assert quota_rejects, "the metered tenant was never throttled"
    rid = await client.submit(16, seed=600, timeout=_WAIT_S, retries=8,
                              tenant="metered", **_flood_fields(0))
    row = await client.wait(rid, timeout=_WAIT_S)
    assert row["state"] == "done", row

    await client.shutdown()
    await server.close()
    return {
        "offered": flood,
        "capacity": capacity,
        "admitted": len(admitted),
        "completed": completed,
        "queue_full_rejections": len(rejected),
        "sheds": len(sheds),
        "shed_rate": round(len(sheds) / max(1, len(admitted)), 3),
        "goodput_rps": round(completed / elapsed, 2),
        "admitted_p50_ms": _percentile_ms(lat, 50) if lat else None,
        "admitted_p99_ms": p99 if lat else None,
        "quota_rejections": quota_rejects,
        "retry_with_backoff_succeeded": True,
        "compiles_steady": 0,
    }


def _flood_fields(i: int) -> dict:
    if i % 2:
        return {"mode": "ticks", "ticks": 24, "scenario": "steady"}
    return {"mode": "converge", "ticks": 40, "scenario": "boot"}


def scenario_submit_flood() -> dict:
    return asyncio.run(_submit_flood_exercise())


# -- driver ----------------------------------------------------------------

SCENARIOS = (
    ("spill_latency", scenario_spill_latency),
    ("engine_kill", scenario_engine_kill),
    ("spill_write_failure", scenario_spill_write_failure),
    ("corrupt_restore", scenario_corrupt_restore),
    ("slow_consumer", scenario_slow_consumer),
    ("submit_flood", scenario_submit_flood),
)


def run_chaos_dryrun() -> int:
    from kaboodle_tpu.analysis.conc import sanitizer
    from kaboodle_tpu.analysis.ir.surface import assert_counter_live

    assert_counter_live()
    report: dict = {"dryrun": "serve-chaos", "seed": CHAOS_SEED,
                    "scenarios": {}}
    # Every scenario runs under the runtime concurrency sanitizer: all
    # SpillManager locks become order-recorded wrappers (an ABBA raises at
    # the acquisition that closes the cycle, no deadlock interleaving
    # needed) and the asyncio scenarios run loop-watchdogged — a chaos
    # pass is also a race regression test. Threshold 1s: toy-scale rounds
    # are sub-ms, warmup/recovery stalls are budgeted, so any trip is a
    # real steady-state stall.
    with sanitizer.enabled(loop_threshold_s=1.0):
        for name, fn in SCENARIOS:
            t0 = time.perf_counter()
            report["scenarios"][name] = fn()
            report["scenarios"][name]["elapsed_s"] = round(
                time.perf_counter() - t0, 2
            )
        report["sanitizer"] = sanitizer.report()
        sanitizer.assert_clean()
    report["ok"] = True
    print(json.dumps(report))
    return 0
