"""asyncio client for the serve server (plus a one-shot sync helper).

One :class:`ServeClient` is one TCP connection running sequential
request/response ops; :meth:`ServeClient.open_stream` opens a SECOND
connection switched into live-event mode and yields manifest records as
they arrive (the stream ack is awaited before returning, so records for
work submitted after ``open_stream`` can never be missed).

Failure surface: a server-side op error raises :class:`ServeError`
carrying the structured ``kind`` (``queue_full`` / ``quota`` /
``bad_request`` / ``checkpoint`` / ``failover`` / ``internal``) and
``retry_after_s`` when the server supplied one. Every op takes
``timeout=`` seconds (None = unbounded) and raises a clean
:class:`TimeoutError` — after which THIS connection is desynchronized (a
late response may still be in flight) and refuses further ops; open a
fresh client. ``submit`` can retry ``queue_full``/``quota`` rejections
with backoff honoring the server's retry-after.

Reconnect (``reconnect=True``): when the transport breaks mid-op — the
server restarted, a federation router failed an engine over, a proxy
dropped the connection — the client re-dials with exponential backoff
and transparently re-sends the op, but ONLY for ops that are safe to
repeat (``_IDEMPOTENT``: a re-sent ``wait`` just waits again, a re-sent
``cancel`` reports already-cancelled). ``submit`` is never auto-resent:
the break may have landed after the server admitted the request, and a
blind re-send would run it twice. Request ids survive the reconnect
(server-side state), so a ``wait`` parked across a restart resumes by
rid on the fresh connection.
"""

from __future__ import annotations

import asyncio
import json

# Ops safe to re-send on a fresh connection after a transport break:
# re-executing any of these cannot double-run a simulation.
_IDEMPOTENT = frozenset(
    {"status", "wait", "stats", "metrics", "cancel", "restore", "resume"}
)


class ServeError(RuntimeError):
    """A structured op failure from the server."""

    def __init__(self, message: str, kind: str = "internal",
                 retry_after_s: float = 0.0) -> None:
        super().__init__(message)
        self.kind = kind
        self.retry_after_s = float(retry_after_s)


class ServeStream:
    """A live-event connection: async-iterate manifest records."""

    def __init__(self, reader, writer) -> None:
        self._reader = reader
        self._writer = writer

    def __aiter__(self) -> "ServeStream":
        return self

    async def __anext__(self) -> dict:
        line = await self._reader.readline()
        if not line:
            raise StopAsyncIteration
        return json.loads(line)

    async def close(self) -> None:
        self._writer.close()


class ServeClient:
    """Sequential JSON-over-TCP ops against a :class:`ServeServer`."""

    def __init__(self, host: str, port: int, reader, writer,
                 reconnect: bool = False, redial_max: int = 8,
                 redial_backoff: float = 0.05) -> None:
        self.host = host
        self.port = port
        self._reader = reader
        self._writer = writer
        self._desynced = False
        self.reconnect = bool(reconnect)
        self.redial_max = int(redial_max)
        self.redial_backoff = float(redial_backoff)

    @classmethod
    async def connect(cls, host: str = "127.0.0.1", port: int = 7447,
                      reconnect: bool = False, redial_max: int = 8,
                      redial_backoff: float = 0.05):
        reader, writer = await asyncio.open_connection(host, port)
        return cls(host, port, reader, writer, reconnect=reconnect,
                   redial_max=redial_max, redial_backoff=redial_backoff)

    async def _redial(self) -> None:
        """Re-establish the transport with exponential backoff (the
        server may be mid-restart; each retry doubles the sleep). A
        fresh connection has clean request/response pairing, so an
        earlier timeout's desync is cleared."""
        last: Exception = ConnectionError("redial")
        for attempt in range(self.redial_max):
            try:
                self._reader, self._writer = await asyncio.open_connection(
                    self.host, self.port
                )
                self._desynced = False
                return
            except OSError as e:
                last = e
                await asyncio.sleep(self.redial_backoff * (2 ** attempt))
        raise ConnectionError(
            f"could not reconnect to {self.host}:{self.port} after "
            f"{self.redial_max} attempts"
        ) from last

    async def _rpc(self, timeout: float | None = None, **op) -> dict:
        """One request/response op, with transparent reconnect for
        idempotent ops when enabled. A break during a non-idempotent op
        (``submit``) always surfaces — the server may have applied it."""
        retriable = self.reconnect and op.get("op") in _IDEMPOTENT
        while True:
            try:
                return await self._rpc_once(timeout=timeout, **op)
            except (ConnectionError, OSError, asyncio.IncompleteReadError):
                if not retriable:
                    raise
                retriable = False  # one transparent repeat per op
                await self._redial()

    async def _rpc_once(self, timeout: float | None = None, **op) -> dict:
        if self._desynced:
            if self.reconnect:
                await self._redial()
            else:
                raise ConnectionError(
                    "connection desynchronized by an earlier timeout; "
                    "reconnect"
                )
        self._writer.write(json.dumps(op).encode() + b"\n")
        await self._writer.drain()
        try:
            if timeout is None:
                line = await self._reader.readline()
            else:
                line = await asyncio.wait_for(
                    self._reader.readline(), timeout
                )
        except asyncio.TimeoutError:
            # The response (if any) is still in flight; this connection's
            # request/response pairing is broken from here on.
            self._desynced = True
            raise TimeoutError(
                f"op {op.get('op')!r} timed out after {timeout}s"
            ) from None
        if not line:
            raise ConnectionError("server closed the connection")
        resp = json.loads(line)
        if not resp.get("ok"):
            raise ServeError(
                resp.get("error", "server error"),
                kind=resp.get("kind", "internal"),
                retry_after_s=resp.get("retry_after_s", 0.0),
            )
        return resp

    async def submit(self, n: int, timeout: float | None = None,
                     retries: int = 0, backoff: float = 0.05,
                     **fields) -> int:
        """Submit a request; returns its request id. Fields mirror
        :class:`~kaboodle_tpu.serve.engine.ServeRequest` (seed, mode,
        ticks, drop_rate, scenario, keep, tenant, priority).

        ``retries`` re-attempts ``queue_full``/``quota`` rejections,
        sleeping the server's ``retry_after_s`` when it gave one (else
        exponential ``backoff`` doublings) — other error kinds re-raise
        immediately; retrying a ``bad_request`` would never succeed."""
        attempt = 0
        while True:
            try:
                resp = await self._rpc(timeout=timeout, op="submit", n=n,
                                       **fields)
                return resp["request_id"]
            except ServeError as e:
                if e.kind not in ("queue_full", "quota") or attempt >= retries:
                    raise
                delay = e.retry_after_s or backoff * (2 ** attempt)
                attempt += 1
                await asyncio.sleep(delay)

    async def status(self, request_id: int | None = None,
                     timeout: float | None = None):
        resp = await self._rpc(timeout=timeout, op="status",
                               request_id=request_id)
        return resp["status"]

    async def wait(self, request_id: int,
                   timeout: float | None = None) -> dict:
        """Block until the request is terminal; returns its status row
        (the harvest result included). ``timeout`` seconds bounds the
        block with a clean :class:`TimeoutError`."""
        resp = await self._rpc(timeout=timeout, op="wait",
                               request_id=request_id)
        return resp["status"]

    async def cancel(self, request_id: int,
                     timeout: float | None = None) -> bool:
        resp = await self._rpc(timeout=timeout, op="cancel",
                               request_id=request_id)
        return resp["cancelled"]

    async def restore(self, request_id: int,
                      timeout: float | None = None) -> bool:
        resp = await self._rpc(timeout=timeout, op="restore",
                               request_id=request_id)
        return resp["restored"]

    async def resume(self, request_id: int, mode: str = "ticks",
                     ticks: int = 16, timeout: float | None = None) -> None:
        await self._rpc(timeout=timeout, op="resume",
                        request_id=request_id, mode=mode, ticks=ticks)

    async def adopt(self, n: int, spill_path: str,
                    saved_run: dict | None = None,
                    owner: str | None = None,
                    timeout: float | None = None, **fields) -> int:
        """Federation failover handover: hand a dead engine's spilled
        request (file + frozen run counters + owner stamp) to this
        engine. Returns the adopting engine's fresh request id."""
        resp = await self._rpc(timeout=timeout, op="adopt", n=n,
                               spill_path=spill_path, saved_run=saved_run,
                               owner=owner, **fields)
        return resp["request_id"]

    async def stats(self, timeout: float | None = None) -> dict:
        resp = await self._rpc(timeout=timeout, op="stats")
        return resp["stats"]

    async def metrics(self, timeout: float | None = None) -> dict:
        """The metrics-plane snapshot (``MetricsRegistry.collect()``):
        counters, pull-gauges and histograms. Requires a server started
        with ``--obs`` — otherwise a ``bad_request`` ServeClientError."""
        resp = await self._rpc(timeout=timeout, op="metrics")
        return resp["metrics"]

    async def shutdown(self) -> None:
        self._writer.write(json.dumps({"op": "shutdown"}).encode() + b"\n")
        await self._writer.drain()
        await self._reader.readline()  # the bye ack

    async def open_stream(self) -> ServeStream:
        """A NEW connection in live-event mode (awaits the ack, so the
        subscription is active before this returns)."""
        reader, writer = await asyncio.open_connection(self.host, self.port)
        writer.write(json.dumps({"op": "stream"}).encode() + b"\n")
        await writer.drain()
        ack = json.loads(await reader.readline())
        if not ack.get("streaming"):
            raise RuntimeError(f"stream handshake failed: {ack}")
        return ServeStream(reader, writer)

    async def close(self) -> None:
        self._writer.close()


def run_one(n: int, host: str = "127.0.0.1", port: int = 7447,
            **fields) -> dict:
    """Synchronous one-shot: connect, submit, wait, return the status row."""

    async def go() -> dict:
        client = await ServeClient.connect(host, port)
        try:
            rid = await client.submit(n, **fields)
            return await client.wait(rid)
        finally:
            await client.close()

    return asyncio.run(go())
