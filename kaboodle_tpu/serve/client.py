"""asyncio client for the serve server (plus a one-shot sync helper).

One :class:`ServeClient` is one TCP connection running sequential
request/response ops; :meth:`ServeClient.open_stream` opens a SECOND
connection switched into live-event mode and yields manifest records as
they arrive (the stream ack is awaited before returning, so records for
work submitted after ``open_stream`` can never be missed).
"""

from __future__ import annotations

import asyncio
import json


class ServeStream:
    """A live-event connection: async-iterate manifest records."""

    def __init__(self, reader, writer) -> None:
        self._reader = reader
        self._writer = writer

    def __aiter__(self) -> "ServeStream":
        return self

    async def __anext__(self) -> dict:
        line = await self._reader.readline()
        if not line:
            raise StopAsyncIteration
        return json.loads(line)

    async def close(self) -> None:
        self._writer.close()


class ServeClient:
    """Sequential JSON-over-TCP ops against a :class:`ServeServer`."""

    def __init__(self, host: str, port: int, reader, writer) -> None:
        self.host = host
        self.port = port
        self._reader = reader
        self._writer = writer

    @classmethod
    async def connect(cls, host: str = "127.0.0.1", port: int = 7447):
        reader, writer = await asyncio.open_connection(host, port)
        return cls(host, port, reader, writer)

    async def _rpc(self, **op) -> dict:
        self._writer.write(json.dumps(op).encode() + b"\n")
        await self._writer.drain()
        line = await self._reader.readline()
        if not line:
            raise ConnectionError("server closed the connection")
        resp = json.loads(line)
        if not resp.get("ok"):
            raise RuntimeError(resp.get("error", "server error"))
        return resp

    async def submit(self, n: int, **fields) -> int:
        """Submit a request; returns its request id. Fields mirror
        :class:`~kaboodle_tpu.serve.engine.ServeRequest` (seed, mode,
        ticks, drop_rate, scenario, keep)."""
        resp = await self._rpc(op="submit", n=n, **fields)
        return resp["request_id"]

    async def status(self, request_id: int | None = None):
        resp = await self._rpc(op="status", request_id=request_id)
        return resp["status"]

    async def wait(self, request_id: int) -> dict:
        """Block until the request is terminal; returns its status row
        (the harvest result included)."""
        resp = await self._rpc(op="wait", request_id=request_id)
        return resp["status"]

    async def cancel(self, request_id: int) -> bool:
        resp = await self._rpc(op="cancel", request_id=request_id)
        return resp["cancelled"]

    async def restore(self, request_id: int) -> bool:
        resp = await self._rpc(op="restore", request_id=request_id)
        return resp["restored"]

    async def resume(self, request_id: int, mode: str = "ticks",
                     ticks: int = 16) -> None:
        await self._rpc(op="resume", request_id=request_id, mode=mode,
                        ticks=ticks)

    async def stats(self) -> dict:
        resp = await self._rpc(op="stats")
        return resp["stats"]

    async def shutdown(self) -> None:
        self._writer.write(json.dumps({"op": "shutdown"}).encode() + b"\n")
        await self._writer.drain()
        await self._reader.readline()  # the bye ack

    async def open_stream(self) -> ServeStream:
        """A NEW connection in live-event mode (awaits the ack, so the
        subscription is active before this returns)."""
        reader, writer = await asyncio.open_connection(self.host, self.port)
        writer.write(json.dumps({"op": "stream"}).encode() + b"\n")
        await writer.drain()
        ack = json.loads(await reader.readline())
        if not ack.get("streaming"):
            raise RuntimeError(f"stream handshake failed: {ack}")
        return ServeStream(reader, writer)

    async def close(self) -> None:
        self._writer.close()


def run_one(n: int, host: str = "127.0.0.1", port: int = 7447,
            **fields) -> dict:
    """Synchronous one-shot: connect, submit, wait, return the status row."""

    async def go() -> dict:
        client = await ServeClient.connect(host, port)
        try:
            rid = await client.submit(n, **fields)
            return await client.wait(rid)
        finally:
            await client.close()

    return asyncio.run(go())
