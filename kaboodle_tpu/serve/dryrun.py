"""The serve CI lane: in-process server, toy requests, hard assertions.

``make serve-dryrun`` (= ``python -m kaboodle_tpu serve --dryrun``) boots
the full stack — engine over a 4-lane n=16 pool, asyncio TCP server on an
ephemeral port, client + live stream connection — and drives 8 toy
requests through it, asserting the three service contracts:

1. **zero fresh compiles after warmup** over the ENTIRE exercise
   (admissions, leap and chunk rounds, harvests, re-seeds, park, spill,
   restore, resume, cancel) via the KB405 compile counter;
2. **bit-exact service**: a converge-mode request's harvest matches a
   standalone ``run_until_converged`` of the same (seed, scenario) —
   conv_tick AND the final member state leaf-for-leaf;
3. **streamed = written**: every manifest record seen live on the stream
   connection also landed in the manifest file, and the file passes the
   schema gate (``read_manifest(validate=True)``).

Prints a one-line JSON tail for the CI log.
"""

from __future__ import annotations

import asyncio
import json
import os
import tempfile

_WAIT_S = 30.0  # outer bound for any single dryrun phase


async def _exercise(report: dict) -> None:
    from kaboodle_tpu.analysis.ir.surface import (
        assert_counter_live,
        compile_counter,
    )
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.serve.client import ServeClient
    from kaboodle_tpu.serve.engine import ServeEngine
    from kaboodle_tpu.serve.pool import LanePool
    from kaboodle_tpu.serve.server import ServeServer
    from kaboodle_tpu.sim.runner import run_until_converged
    from kaboodle_tpu.sim.state import init_state
    from kaboodle_tpu.telemetry.manifest import read_manifest

    assert_counter_live()
    cfg = SwimConfig(deterministic=True)
    tmp = tempfile.mkdtemp(prefix="kaboodle-serve-dryrun-")
    manifest_path = os.path.join(tmp, "manifest.jsonl")
    pool = LanePool(16, 4, cfg=cfg, chunk=8)
    engine = ServeEngine(
        [pool], warp=True, max_leap=64, spill_after=2, spill_dir=tmp
    )
    server = ServeServer(engine, port=0, manifest_path=manifest_path)
    engine.warmup()
    await server.start()

    client = await ServeClient.connect(port=server.port)
    stream = await client.open_stream()
    streamed: list[dict] = []

    async def pump() -> None:
        async for rec in stream:
            streamed.append(rec)

    pump_task = asyncio.create_task(pump())

    with compile_counter() as box:
        # 8 toy requests through the 4-lane pool: converge and horizon
        # modes interleaved, so the drain mixes chunk rounds, leap rounds
        # and mid-flight re-seeds of retired lanes.
        rids = []
        for i in range(8):
            horizon = bool(i % 2)
            rids.append(await client.submit(
                16, seed=i,
                mode="ticks" if horizon else "converge",
                ticks=40,
                scenario="steady" if horizon else "boot",
                keep=(i == 0),
            ))
        rows = {}
        for rid in rids:
            rows[rid] = await asyncio.wait_for(client.wait(rid), _WAIT_S)

        # keep=True parked its lane; ride the idle countdown into a spill,
        # then restore + resume + cancel — the whole lifecycle, still
        # under the compile counter.
        kept = rids[0]

        async def _await_state(rid: int, state: str) -> dict:
            while True:
                row = await client.status(rid)
                if row["state"] == state:
                    return row
                await asyncio.sleep(0.01)

        spilled = await asyncio.wait_for(_await_state(kept, "spilled"), _WAIT_S)
        assert os.path.exists(spilled["spill_path"]), spilled
        assert await client.restore(kept)
        await client.resume(kept, mode="ticks", ticks=8)
        await asyncio.wait_for(client.wait(kept), _WAIT_S)
        await client.cancel(kept)
    report["compiles_after_warmup"] = box.count

    # -- contract 2: bit-exact service (harvest vs standalone run; the
    # full member-state leaf equality is pinned in tests/test_serve.py) ----
    probe = rows[rids[2]]  # a converge-mode request whose lane was re-seeded
    from kaboodle_tpu.sim.runner import state_agreement

    ref_state, ref_ticks, ref_conv = run_until_converged(
        init_state(16, seed=2), cfg, max_ticks=40
    )
    _, ref_fp_min, ref_fp_max, ref_alive = state_agreement(ref_state)
    assert probe["result"]["conv_tick"] == int(ref_ticks), (
        probe["result"], int(ref_ticks))
    assert probe["result"]["converged"] == bool(ref_conv)
    assert probe["result"]["fp_min"] == int(ref_fp_min)
    assert probe["result"]["fp_max"] == int(ref_fp_max)
    assert probe["result"]["n_alive"] == int(ref_alive)
    report["bitexact_conv_tick"] = True

    stats = await client.stats()
    report["rounds"] = stats["round"]
    report["requests"] = stats["requests"]

    await client.shutdown()
    await server.close()
    await asyncio.wait_for(pump_task, _WAIT_S)

    # -- contract 3: streamed == written, schema-clean ----------------------
    written = list(read_manifest(manifest_path, validate=True))
    assert streamed, "stream connection saw no records"
    assert len(written) == len(streamed) + 1, (  # +1: the pre-stream warm rec
        len(written), len(streamed))
    kinds = {}
    for rec in written:
        kinds[rec["kind"]] = kinds.get(rec["kind"], 0) + 1
    assert kinds.get("serve_event", 0) >= 8 + 8  # admissions + completions
    assert kinds.get("serve_round", 0) >= 1
    events = {r.get("event") for r in written if r["kind"] == "serve_event"}
    for needed in ("warm", "admitted", "converged", "completed", "spilled",
                   "restored", "resumed", "cancelled"):
        assert needed in events, (needed, sorted(events))
    report["manifest_records"] = len(written)
    report["streamed_records"] = len(streamed)
    report["record_kinds"] = kinds

    assert report["compiles_after_warmup"] == 0, report


def run_dryrun() -> int:
    report: dict = {"dryrun": "serve"}
    asyncio.run(_exercise(report))
    report["ok"] = True
    print(json.dumps(report))
    return 0
