"""The resident step loop: requests in, convergence events out.

One :class:`ServeEngine` owns a set of lane pools (one per pow2 N-class)
and advances them all with a continuous-batching round loop:

1. **admit** — queued requests drop into free lanes of their class pool
   (one warmed reseed dispatch each; the retired occupant is overwritten
   in place on device).
2. **advance** — each pool with active lanes runs either ONE masked
   fleet-leap dispatch (per-member horizons: every horizon-mode lane leaps
   exactly its own ``k_m``, converge-mode and hot lanes freeze at
   ``k_m == 0``) or one serve-step chunk (masked dense ticks, per-lane
   convergence tests inside the compiled while_loop). The leap path is the
   Warp 2.0 per-member round from ``run_fleet_warped``, re-used verbatim:
   same signature fetch, same classifier, same bucketed ProgramCache.
3. **harvest** — lanes whose run finished (converged under a converge-mode
   budget, or horizon exhausted) are read out with one vmapped agreement
   fetch, emitted as ``serve_event`` records, then parked or released.
   Released lanes are immediately re-seedable: retire/re-seed never leaves
   the warmed program set.
4. **spill** — parked lanes idle past ``spill_after`` rounds are gathered
   (traced-lane fetch) and written through ``checkpoint.save``; a later
   ``restore`` inserts them back into a free lane of the same class.

Correctness rules the loop enforces:

- converge-mode lanes NEVER leap — a hybrid leap may jump past the first
  fp-agreement tick, and the service contract is bit-exactness with a
  standalone ``run_until_converged`` of the same (seed, knobs, scenario).
  Only horizon-mode lanes (exact tick budgets) take the fast-forward.
- leaps only in fault-free, non-telemetry pools (the span programs'
  precondition; exact counter totals require dense ticks).
- every per-round ``k_m`` is clamped to ``max_leap``, so only the leap
  buckets warmed by :meth:`ServeEngine.warmup` are ever requested — leap
  composition is exact, so the clamp costs dispatches, not correctness.
"""

from __future__ import annotations

import dataclasses
import os
from collections import OrderedDict

import jax.numpy as jnp
import numpy as np

from kaboodle_tpu.serve.pool import LanePool, lane_n_class
from kaboodle_tpu.telemetry.manifest import run_record
from kaboodle_tpu.warp.horizon import decode_signature
from kaboodle_tpu.warp.runner import (
    MIN_LEAP,
    _classify,
    _fleet_signature,
    _get_fleet_leap,
    _leap_budget,
)

# Request lifecycle states (the engine's host-side view of a lane).
QUEUED = "queued"
RUNNING = "running"
PARKED = "parked"
SPILLED = "spilled"
DONE = "done"
CANCELLED = "cancelled"


@dataclasses.dataclass(frozen=True)
class ServeRequest:
    """One simulation request: scenario + knobs + N-class.

    ``mode="converge"`` runs until fp-agreement (or ``ticks`` as the
    budget cap) — the served twin of ``run_until_converged``, bit-exact
    per the admission parity pin. ``mode="ticks"`` runs exactly ``ticks``
    ticks (horizon mode) — the lane the warp fast-forward applies to.
    ``keep=True`` parks the finished lane (spillable, resumable) instead
    of releasing it."""

    n: int
    seed: int = 0
    mode: str = "converge"
    ticks: int = 64
    drop_rate: float = 0.0
    scenario: str = "boot"
    keep: bool = False

    def __post_init__(self):
        if self.mode not in ("converge", "ticks"):
            raise ValueError(f"unknown mode {self.mode!r}")
        if self.ticks < 1:
            raise ValueError("need ticks >= 1")

    @property
    def n_class(self) -> int:
        return lane_n_class(self.n)

    @property
    def until_conv(self) -> bool:
        return self.mode == "converge"


class ServeEngine:
    """Continuous-batching round loop over a dict of lane pools.

    ``pools`` maps pow2 N-class -> :class:`LanePool`; requests are routed
    by :func:`lane_n_class`. ``on_event`` (optional) is called with every
    emitted manifest record as it happens — the server's live stream tap.
    """

    def __init__(
        self,
        pools,
        warp: bool = True,
        max_leap: int = 256,
        spill_after: int | None = None,
        spill_dir: str | None = None,
        on_event=None,
    ) -> None:
        self.pools: dict[int, LanePool] = {}
        for pool in pools:
            if pool.n in self.pools:
                raise ValueError(f"duplicate pool class n={pool.n}")
            self.pools[pool.n] = pool
        if not self.pools:
            raise ValueError("need at least one pool")
        self.warp = bool(warp)
        self.max_leap = int(max_leap)
        if self.max_leap < MIN_LEAP:
            raise ValueError(f"need max_leap >= MIN_LEAP ({MIN_LEAP})")
        self.spill_after = spill_after
        self.spill_dir = spill_dir
        self.on_event = on_event
        self.round = 0
        self._next_rid = 0
        # rid -> bookkeeping row; insertion order is admission FIFO order.
        self._requests: OrderedDict[int, dict] = OrderedDict()
        # (n_class, lane) -> rid for lanes currently occupied by a request.
        self._lane_owner: dict[tuple[int, int], int] = {}

    # -- request surface ---------------------------------------------------

    def submit(self, req: ServeRequest) -> int:
        """Queue a request; returns its request id. Raises on an unserved
        N-class or a faulty knob no pool can honor — rejection is loud,
        not an event."""
        n_class = req.n_class
        pool = self.pools.get(n_class)
        if pool is None:
            raise ValueError(
                f"no pool serves N-class {n_class} (request n={req.n})"
            )
        if req.scenario not in ("boot", "steady"):
            raise ValueError(f"unknown scenario {req.scenario!r}")
        if req.drop_rate and not pool.faulty:
            raise ValueError(
                f"pool n={n_class} is fault-free; drop_rate must be 0"
            )
        rid = self._next_rid
        self._next_rid += 1
        self._requests[rid] = {
            "req": req,
            "state": QUEUED,
            "lane": None,
            "pool": n_class,
            "generation": None,
            "result": None,
            "idle_rounds": 0,
            "spill_path": None,
        }
        return rid

    def cancel(self, rid: int) -> bool:
        """Cancel a request in any non-terminal state; frees its lane."""
        row = self._requests.get(rid)
        if row is None or row["state"] in (DONE, CANCELLED):
            return False
        if row["state"] in (RUNNING, PARKED):
            pool = self.pools[row["pool"]]
            pool.release(row["lane"])
            del self._lane_owner[(row["pool"], row["lane"])]
            row["lane"] = None
        row["state"] = CANCELLED
        self._emit("serve_event", event="cancelled", request_id=rid,
                   pool_n=row["pool"], lane=-1)
        return True

    def status(self, rid: int | None = None):
        """One request's public row, or all of them (rid=None)."""
        if rid is not None:
            row = self._requests.get(rid)
            return None if row is None else self._public_row(rid, row)
        return [self._public_row(r, row) for r, row in self._requests.items()]

    def _public_row(self, rid: int, row: dict) -> dict:
        req = row["req"]
        out = {
            "request_id": rid,
            "state": row["state"],
            "n": req.n,
            "n_class": row["pool"],
            "seed": req.seed,
            "mode": req.mode,
            "lane": row["lane"],
            "generation": row["generation"],
        }
        if row["result"] is not None:
            out["result"] = dict(row["result"])
        if row["spill_path"] is not None:
            out["spill_path"] = row["spill_path"]
        return out

    # -- spill / restore ---------------------------------------------------

    def _spill(self, rid: int, row: dict) -> None:
        from kaboodle_tpu import checkpoint

        pool = self.pools[row["pool"]]
        lane = row["lane"]
        path = os.path.join(
            self.spill_dir, f"lane-n{row['pool']}-req{rid}.npz"
        )
        checkpoint.save(path, pool.member(lane))
        pool.release(lane)
        del self._lane_owner[(row["pool"], lane)]
        row.update(state=SPILLED, lane=None, spill_path=path)
        self._emit("serve_event", event="spilled", request_id=rid,
                   pool_n=row["pool"], lane=lane, path=path)

    def restore(self, rid: int) -> bool:
        """Bring a spilled request back into a free lane (parked). Returns
        False when its class pool has no free lane right now."""
        from kaboodle_tpu import checkpoint

        row = self._requests.get(rid)
        if row is None or row["state"] != SPILLED:
            raise ValueError(f"request {rid} is not spilled")
        pool = self.pools[row["pool"]]
        lane = pool.free_lane()
        if lane is None:
            return False
        member = checkpoint.load(row["spill_path"])
        row["generation"] = pool.insert(lane, member)
        self._lane_owner[(row["pool"], lane)] = rid
        row.update(state=PARKED, lane=lane, idle_rounds=0)
        self._emit("serve_event", event="restored", request_id=rid,
                   pool_n=row["pool"], lane=lane,
                   generation=row["generation"])
        return True

    def resume(self, rid: int, mode: str = "ticks", ticks: int = 16) -> None:
        """Re-activate a parked request with a fresh budget (continuation
        runs across the park/spill boundary keep their tick counters)."""
        row = self._requests.get(rid)
        if row is None or row["state"] != PARKED:
            raise ValueError(f"request {rid} is not parked")
        if mode not in ("converge", "ticks"):
            raise ValueError(f"unknown mode {mode!r}")
        pool = self.pools[row["pool"]]
        pool.resume(row["lane"], until_conv=(mode == "converge"),
                    budget=int(ticks))
        row["state"] = RUNNING
        row["idle_rounds"] = 0
        row["result"] = None  # the continuation's harvest replaces it
        self._emit("serve_event", event="resumed", request_id=rid,
                   pool_n=row["pool"], lane=row["lane"], mode=mode,
                   ticks=int(ticks))

    # -- the round loop ----------------------------------------------------

    @property
    def busy(self) -> bool:
        if any(row["state"] == QUEUED for row in self._requests.values()):
            return True
        return any(pool.active.any() for pool in self.pools.values())

    def step(self) -> list[dict]:
        """One engine round: admit, advance every pool, harvest, spill.

        Returns the manifest records emitted this round (also fanned out
        through ``on_event`` as they happen)."""
        self._events: list[dict] = []
        self._admit_queued()
        for pool in self.pools.values():
            if not pool.active.any():
                continue
            if not self._try_leap_round(pool):
                self._chunk_round(pool)
            self._harvest(pool)
        self._spill_idle()
        self.round += 1
        return self._events

    def drain(self, max_rounds: int = 10_000) -> list[dict]:
        """Round until nothing is queued or active; returns all events."""
        events: list[dict] = []
        for _ in range(max_rounds):
            if not self.busy:
                return events
            events.extend(self.step())
        raise RuntimeError(f"engine still busy after {max_rounds} rounds")

    def _admit_queued(self) -> None:
        for rid, row in self._requests.items():
            if row["state"] != QUEUED:
                continue
            pool = self.pools[row["pool"]]
            lane = pool.free_lane()
            if lane is None:
                continue  # class full this round; stays queued (FIFO)
            req: ServeRequest = row["req"]
            row["generation"] = pool.admit(
                lane, seed=req.seed, drop_rate=req.drop_rate,
                until_conv=req.until_conv, budget=req.ticks,
                scenario=req.scenario,
            )
            self._lane_owner[(row["pool"], lane)] = rid
            row.update(state=RUNNING, lane=lane)
            self._emit("serve_event", event="admitted", request_id=rid,
                       pool_n=row["pool"], lane=lane,
                       generation=row["generation"], seed=req.seed,
                       mode=req.mode, scenario=req.scenario)

    def _try_leap_round(self, pool: LanePool) -> bool:
        """One masked fleet-leap dispatch if any horizon lane can cover
        MIN_LEAP ticks; returns False when this round must run dense."""
        if not self.warp or pool.faulty or pool.telemetry:
            return False
        horizon = pool.active & ~pool.until_conv & (pool.remaining > 0)
        if not horizon.any():
            return False
        rows = np.asarray(_fleet_signature(pool.cfg)(pool.mesh))
        # int32 on the host: jnp.asarray is then a plain device put — an
        # int64 vector would dispatch a fresh convert_element_type program
        # and break the zero-recompile contract.
        k_m = np.zeros((pool.lanes,), dtype=np.int32)
        for e in np.flatnonzero(horizon):
            cls = decode_signature(rows[e])
            mode = _classify(cls, hybrid=True)
            if mode != "dense":
                k_m[e] = min(
                    _leap_budget(cls, mode, int(pool.remaining[e])),
                    self.max_leap,
                )
        if k_m.max() < MIN_LEAP:
            return False
        K = 1 << int(k_m.max() - 1).bit_length()
        K = max(K, MIN_LEAP)
        pool.mesh = _get_fleet_leap(pool.cfg, K)(pool.mesh, jnp.asarray(k_m))
        pool.advance_leaped(k_m)
        self._emit(
            "serve_round", round=self.round, pool_n=pool.n, engine="leap",
            lanes=int((k_m > 0).sum()), ticks=int(k_m.sum()), bucket=K,
        )
        return True

    def _chunk_round(self, pool: LanePool) -> None:
        prev = pool.ticks_run.copy()
        pool.step()
        self._emit(
            "serve_round", round=self.round, pool_n=pool.n, engine="chunk",
            lanes=int(pool.active.sum()),
            ticks=int((pool.ticks_run - prev).sum()),
        )

    def _harvest(self, pool: LanePool) -> None:
        finished = pool.active & (
            (pool.until_conv & (pool.conv_tick >= 0))
            | (pool.remaining <= 0)
        )
        if not finished.any():
            return
        converged, fp_min, fp_max, n_alive = pool.agreement()
        for lane in np.flatnonzero(finished):
            lane = int(lane)
            rid = self._lane_owner[(pool.n, lane)]
            row = self._requests[rid]
            req: ServeRequest = row["req"]
            result = {
                "ticks_run": int(pool.ticks_run[lane]),
                "conv_tick": int(pool.conv_tick[lane]),
                "converged": bool(converged[lane]),
                "fp_min": int(fp_min[lane]),
                "fp_max": int(fp_max[lane]),
                "n_alive": int(n_alive[lane]),
                "messages": int(pool.messages[lane]),
            }
            counters = pool.counters_row(lane)
            if counters is not None:
                result["counters"] = counters
            row["result"] = result
            if not pool.until_conv[lane]:
                event = "completed"  # horizon run: ran its exact ticks
            elif pool.conv_tick[lane] >= 0:
                event = "converged"
            else:
                event = "exhausted"  # converge run: budget up, no agreement
            self._emit(
                "serve_event", event=event, request_id=rid, pool_n=pool.n,
                lane=lane, generation=row["generation"], **result,
            )
            if req.keep:
                pool.park(lane)
                row["state"] = PARKED
                row["idle_rounds"] = 0
            else:
                pool.release(lane)
                del self._lane_owner[(pool.n, lane)]
                row.update(state=DONE, lane=None)

    def _spill_idle(self) -> None:
        if self.spill_after is None or self.spill_dir is None:
            return
        for rid, row in self._requests.items():
            if row["state"] != PARKED:
                continue
            row["idle_rounds"] += 1
            if row["idle_rounds"] > self.spill_after:
                self._spill(rid, row)

    # -- warmup ------------------------------------------------------------

    def warmup(self) -> None:
        """Compile the whole serving surface with state-preserving
        dispatches: each pool's program set (pool.warmup), then — for
        pools the warp applies to — the signature fetch and every leap
        bucket 8..max_leap at ``k_m = 0`` (the masked span program freezes
        everyone bit-exactly at zero). After this the round loop's
        admit/leap/chunk/harvest/spill path compiles nothing."""
        for pool in self.pools.values():
            pool.warmup()
            if not self.warp or pool.faulty or pool.telemetry:
                continue
            np.asarray(_fleet_signature(pool.cfg)(pool.mesh))
            zeros = jnp.zeros((pool.lanes,), jnp.int32)
            K = MIN_LEAP
            while K <= self.max_leap:
                pool.mesh = _get_fleet_leap(pool.cfg, K)(pool.mesh, zeros)
                K <<= 1
        self._emit_standalone(
            "serve_event", event="warm", request_id=-1, lane=-1,
            pool_n=min(self.pools), pools=sorted(self.pools),
        )

    # -- events ------------------------------------------------------------

    def _emit(self, kind: str, **fields) -> dict:
        rec = self._emit_standalone(kind, **fields)
        self._events.append(rec)
        return rec

    def _emit_standalone(self, kind: str, **fields) -> dict:
        rec = run_record(kind, **fields)
        if self.on_event is not None:
            self.on_event(rec)
        return rec

    def stats(self) -> dict:
        states: dict[str, int] = {}
        for row in self._requests.values():
            states[row["state"]] = states.get(row["state"], 0) + 1
        return {
            "round": self.round,
            "requests": len(self._requests),
            "states": states,
            "pools": {n: p.stats() for n, p in self.pools.items()},
        }
