"""The resident step loop: requests in, convergence events out.

One :class:`ServeEngine` owns a set of lane pools (one per pow2 N-class)
and advances them all with a continuous-batching round loop:

1. **admit** — queued requests drop into free lanes of their class pool
   (one warmed reseed dispatch each; the retired occupant is overwritten
   in place on device). With an :class:`~kaboodle_tpu.serve.admission.
   AdmissionController` attached, admission runs in priority order and a
   strictly lower-priority PARKED lane may be spill-evicted to make room
   (running lanes are never preempted).
2. **advance** — each pool with active lanes runs either ONE masked
   fleet-leap dispatch (per-member horizons: every horizon-mode lane leaps
   exactly its own ``k_m``, converge-mode and hot lanes freeze at
   ``k_m == 0``) or one serve-step chunk (masked dense ticks, per-lane
   convergence tests inside the compiled while_loop). The leap path is the
   Warp 2.0 per-member round from ``run_fleet_warped``, re-used verbatim:
   same signature fetch, same classifier, same bucketed ProgramCache.
3. **harvest** — lanes whose run finished (converged under a converge-mode
   budget, or horizon exhausted) are read out with one vmapped agreement
   fetch, emitted as ``serve_event`` records, then parked or released.
   Released lanes are immediately re-seedable: retire/re-seed never leaves
   the warmed program set.
4. **spill** — parked lanes idle past ``spill_after`` rounds dispatch a
   traced-lane gather (fresh immutable device buffers) and hand it to the
   background :class:`~kaboodle_tpu.serve.spill.SpillManager`, whose
   writer thread blocks on the device->host transfer and the disk write;
   the round loop NEVER blocks on either. Initiation is paced
   (``spills_per_round``, default 1) so a burst of idle lanes costs one
   gather dispatch per round, not a stall. The lane stays held (state
   ``spilling``) until the write is durable, then frees; a failed write
   degrades the lane back to parked with a loud ``spill_failed`` event.
   ``sync_spill=True`` keeps the old blocking write — the chaos harness's
   A/B baseline.

Crash safety: with ``journal_dir`` set, every lifecycle transition is
appended to a write-ahead :class:`~kaboodle_tpu.serve.journal.
ServeJournal` before the engine acts on it, and a restarted engine's
:meth:`ServeEngine.recover` folds the journal back into a live request
table — completed requests keep their results (nothing replays twice),
spilled requests re-attach to their durable files, and requests whose
lane state died with the process re-queue from their seeds with
cumulative tick budgets.

Correctness rules the loop enforces:

- converge-mode lanes NEVER leap — a hybrid leap may jump past the first
  fp-agreement tick, and the service contract is bit-exactness with a
  standalone ``run_until_converged`` of the same (seed, knobs, scenario).
  Only horizon-mode lanes (exact tick budgets) take the fast-forward.
- leaps only in fault-free, non-telemetry pools (the span programs'
  precondition; exact counter totals require dense ticks).
- every per-round ``k_m`` is clamped to ``max_leap``, so only the leap
  buckets warmed by :meth:`ServeEngine.warmup` are ever requested — leap
  composition is exact, so the clamp costs dispatches, not correctness.
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import OrderedDict

import numpy as np

from kaboodle_tpu.analysis.conc import sanitizer as _conc_sanitizer
from kaboodle_tpu.errors import CheckpointError
from kaboodle_tpu.serve.admission import AdmissionError
from kaboodle_tpu.serve.obsplane import (
    SEG_ADMIT,
    SEG_DISPATCH,
    SEG_HARVEST,
    SEG_JOURNAL,
    SEG_POLL,
    SEG_ROUND,
    SEG_SPILL,
    ObsPlane,
)
from kaboodle_tpu.serve.pool import LanePool, lane_n_class
from kaboodle_tpu.telemetry.manifest import run_record
from kaboodle_tpu.warp.horizon import decode_signature
from kaboodle_tpu.warp.runner import (
    MIN_LEAP,
    SpanMemo,
    WarpLedger,
    _check_warp_mode,
    _classify,
    _leap_budget,
)

# Request lifecycle states (the engine's host-side view of a lane).
QUEUED = "queued"
RUNNING = "running"
PARKED = "parked"
SPILLING = "spilling"  # lane held; background write not yet durable
SPILLED = "spilled"
DONE = "done"
CANCELLED = "cancelled"


@dataclasses.dataclass(frozen=True)
class ServeRequest:
    """One simulation request: scenario + knobs + N-class.

    ``mode="converge"`` runs until fp-agreement (or ``ticks`` as the
    budget cap) — the served twin of ``run_until_converged``, bit-exact
    per the admission parity pin. ``mode="ticks"`` runs exactly ``ticks``
    ticks (horizon mode) — the lane the warp fast-forward applies to.
    ``keep=True`` parks the finished lane (spillable, resumable) instead
    of releasing it. ``tenant`` names the quota bucket and ``priority``
    the admission class (higher admits first; both inert without an
    :class:`~kaboodle_tpu.serve.admission.AdmissionController`)."""

    n: int
    seed: int = 0
    mode: str = "converge"
    ticks: int = 64
    drop_rate: float = 0.0
    scenario: str = "boot"
    keep: bool = False
    tenant: str = "default"
    priority: int = 1

    def __post_init__(self):
        if self.mode not in ("converge", "ticks"):
            raise ValueError(f"unknown mode {self.mode!r}")
        if self.ticks < 1:
            raise ValueError("need ticks >= 1")
        if self.priority < 0:
            raise ValueError("need priority >= 0")

    @property
    def n_class(self) -> int:
        return lane_n_class(self.n)

    @property
    def until_conv(self) -> bool:
        return self.mode == "converge"


def _fresh_row(req: ServeRequest) -> dict:
    return {
        "req": req,
        "state": QUEUED,
        "lane": None,
        "pool": req.n_class,
        "generation": None,
        "result": None,
        "idle_rounds": 0,
        "spill_path": None,
        "saved_run": None,
        "spill_owner": None,  # engine-id stamped in the spill file
        "retry_spill": False,
    }


class ServeEngine:
    """Continuous-batching round loop over a dict of lane pools.

    ``pools`` maps pow2 N-class -> :class:`LanePool`; requests are routed
    by :func:`lane_n_class`. ``on_event`` (optional) is called with every
    emitted manifest record as it happens — the server's live stream tap.
    ``admission`` (optional) gates submits; ``journal_dir`` (optional)
    write-ahead-logs every transition for :meth:`recover`.
    """

    def __init__(
        self,
        pools,
        warp: bool = True,
        max_leap: int = 256,
        spill_after: int | None = None,
        spill_dir: str | None = None,
        on_event=None,
        admission=None,
        journal_dir: str | None = None,
        sync_spill: bool = False,
        spill_depth: int = 4,
        spills_per_round: int = 1,
        obs=None,
        engine_id: str | None = None,
        warp_memo: SpanMemo | bool | None = None,
        warp_mode: str = "exact",
    ) -> None:
        self.pools: dict[int, LanePool] = {}
        for pool in pools:
            if pool.n in self.pools:
                raise ValueError(f"duplicate pool class n={pool.n}")
            self.pools[pool.n] = pool
        if not self.pools:
            raise ValueError("need at least one pool")
        self.warp = bool(warp)
        self.max_leap = int(max_leap)
        if self.max_leap < MIN_LEAP:
            raise ValueError(f"need max_leap >= MIN_LEAP ({MIN_LEAP})")
        # Warp 3.0: signature-keyed span memo for the leap rounds.
        # ``True`` adopts the process-shared ``span_memo`` (serve lanes,
        # fleet drains, and sim runs then trade deltas); a ``SpanMemo``
        # instance scopes the cache to this engine; ``None`` turns
        # memoization off (every leap round dispatches, as before).
        _check_warp_mode(warp_mode)
        self.warp_mode = warp_mode
        if warp_memo is True:
            from kaboodle_tpu.warp.runner import span_memo

            self.warp_memo: SpanMemo | None = span_memo
        elif warp_memo is False:
            self.warp_memo = None
        else:
            self.warp_memo = warp_memo
        self.spill_after = spill_after
        self.spill_dir = spill_dir
        # Federation identity: namespaces this engine's spill files under
        # ``<spill_dir>/<engine_id>/`` and stamps every spill archive +
        # journal directory, so engines sharing one spill root can never
        # collide on paths or silently cross-restore a lane snapshot.
        self.engine_id = engine_id
        if engine_id is not None and spill_dir is not None:
            self.spill_dir = os.path.join(spill_dir, engine_id)
            os.makedirs(self.spill_dir, exist_ok=True)
        self.on_event = on_event
        self.admission = admission
        self.sync_spill = bool(sync_spill)
        self.spill_depth = int(spill_depth)
        # Idle spills initiated per round: even the HOST side of a spill
        # (the traced-lane gather + device->host copy) costs a round-loop
        # slice, so initiation is paced — one copy per round keeps round
        # latency within the no-spill envelope however many lanes idle out
        # together. Evictions (preemption) bypass the pacing: admission
        # needs the lane this round.
        self.spills_per_round = int(spills_per_round)
        self.journal = None
        if journal_dir is not None:
            from kaboodle_tpu.serve.journal import ServeJournal

            if engine_id is not None:
                journal_dir = os.path.join(journal_dir, engine_id)
            self.journal = ServeJournal(journal_dir, owner=engine_id)
        self.round = 0
        self._next_rid = 0
        self._events: list[dict] = []
        self._spiller = None  # lazy: engines that never spill get no thread
        # rid -> bookkeeping row; insertion order is admission FIFO order.
        self._requests: OrderedDict[int, dict] = OrderedDict()
        # (n_class, lane) -> rid for lanes currently occupied by a request.
        self._lane_owner: dict[tuple[int, int], int] = {}
        # Why-dense ledger (ISSUE 15): when a round falls back from leap to
        # chunk, the blocking signature terms are recorded per horizon lane
        # — host-side bookkeeping only, engine state stays bit-identical.
        # Surfaced by the obs plane's warp_blocked_* gauges.
        self.warp_ledger = WarpLedger()
        # Observability plane (ISSUE 14): obs=True gets the defaults,
        # obs=ObsPlane(...) a configured one. The plane is a pure observer
        # — engine state is bit-identical with it on or off — and its
        # epoch is shared with the journal so WAL ts_us and span t0_us
        # live on one monotonic timeline.
        self.obs: ObsPlane | None = None
        if obs:
            self.obs = obs if isinstance(obs, ObsPlane) else ObsPlane()
            self.obs.bind(self)
            if self.journal is not None:
                self.journal.epoch_ns = self.obs.epoch_ns

    @property
    def spiller(self):
        if self._spiller is None:
            from kaboodle_tpu.serve.spill import SpillManager

            self._spiller = SpillManager(
                depth=self.spill_depth, owner=self.engine_id
            )
        return self._spiller

    def close(self) -> None:  # conc: event-loop
        """Join outstanding spill I/O and release the journal handle."""
        if self._spiller is not None:
            self._spiller.flush()
            self._poll_spills()
            self._spiller.close()
        if self.obs is not None:
            for rec in self.obs.flush_spans():
                if self.on_event is not None:
                    self.on_event(rec)
            self.obs.close()
        if self.journal is not None:
            self.journal.close()

    def _log(self, op: str, rid: int, **fields) -> None:
        if self.journal is None:
            return
        if self.obs is not None:
            t0 = time.perf_counter_ns()
            self.journal.append(op, rid, **fields)
            self.obs.profiler.add_ns(
                SEG_JOURNAL, time.perf_counter_ns() - t0
            )
        else:
            self.journal.append(op, rid, **fields)

    def _span(self, rid: int, span: str | None, pool_n: int = -1,
              lane: int = -1, **extra) -> None:
        """One lifecycle edge on the tracer: closes the request's open
        span (fanning the ``serve_span`` record out to the stream and
        manifest) and opens the next (None = terminal). No-op without an
        observability plane."""
        if self.obs is None:
            return
        rec = self.obs.transition(rid, span, pool_n=pool_n, lane=lane,
                                  **extra)
        if rec is not None and self.on_event is not None:
            self.on_event(rec)

    # -- request surface ---------------------------------------------------

    def submit(self, req: ServeRequest) -> int:  # conc: event-loop
        """Queue a request; returns its request id. Raises on an unserved
        N-class or a faulty knob no pool can honor — rejection is loud,
        not an event. With admission control attached, quota and
        queue-capacity rejections raise structured
        :class:`~kaboodle_tpu.serve.admission.AdmissionError` subclasses
        carrying ``retry_after_s`` (emitted as ``rejected`` events)."""
        n_class = req.n_class
        pool = self.pools.get(n_class)
        if pool is None:
            raise ValueError(
                f"no pool serves N-class {n_class} (request n={req.n})"
            )
        if req.scenario not in ("boot", "steady"):
            raise ValueError(f"unknown scenario {req.scenario!r}")
        if req.drop_rate and not pool.faulty:
            raise ValueError(
                f"pool n={n_class} is fault-free; drop_rate must be 0"
            )
        if self.admission is not None:
            try:
                self._admission_gate(req)
            except AdmissionError as e:
                self._emit_standalone(
                    "serve_event", event="rejected", request_id=-1,
                    pool_n=n_class, lane=-1, tenant=req.tenant,
                    priority=req.priority, reason=e.kind,
                    retry_after_s=e.retry_after_s,
                )
                raise
        rid = self._next_rid
        self._next_rid += 1
        self._requests[rid] = _fresh_row(req)
        self._log("submitted", rid, req=dataclasses.asdict(req))
        self._emit_standalone(
            "serve_event", event="submitted", request_id=rid, pool_n=n_class,
            lane=-1, tenant=req.tenant, priority=req.priority,
        )
        self._span(rid, "queued", pool_n=n_class)
        return rid

    def _admission_gate(self, req: ServeRequest) -> None:
        """Quota first (one token per accepted OR shed-displacing submit),
        then queue capacity — shedding the lowest-priority queued request
        when the newcomer strictly outranks it, else raising queue_full."""
        self.admission.check_quota(req.tenant)
        queued = [
            (rid, row)
            for rid, row in self._requests.items()
            if row["state"] == QUEUED
        ]
        if len(queued) < self.admission.max_queue:
            return
        victim = min(queued, key=lambda kv: (kv[1]["req"].priority, kv[0]))
        if victim[1]["req"].priority < req.priority:
            self._shed(victim[0], victim[1])
            return
        self.admission.check_queue(len(queued))

    def _shed(self, rid: int, row: dict) -> None:
        row["state"] = CANCELLED
        self._log("shed", rid)
        self._emit_standalone(
            "serve_event", event="shed", request_id=rid, pool_n=row["pool"],
            lane=-1, tenant=row["req"].tenant, priority=row["req"].priority,
        )
        self._span(rid, None, fate="shed")

    def cancel(self, rid: int) -> bool:  # conc: event-loop
        """Cancel a request in any non-terminal state; frees its lane."""
        row = self._requests.get(rid)
        if row is None or row["state"] in (DONE, CANCELLED):
            return False
        if row["state"] in (RUNNING, PARKED, SPILLING):
            pool = self.pools[row["pool"]]
            pool.release(row["lane"])
            del self._lane_owner[(row["pool"], row["lane"])]
            row["lane"] = None
        if self._spiller is not None:
            self._spiller.drop_cache(rid)
        row["state"] = CANCELLED
        row["retry_spill"] = False
        self._log("cancelled", rid)
        self._emit("serve_event", event="cancelled", request_id=rid,
                   pool_n=row["pool"], lane=-1)
        self._span(rid, None, fate="cancelled")
        return True

    def status(self, rid: int | None = None):  # conc: event-loop
        """One request's public row, or all of them (rid=None)."""
        if rid is not None:
            row = self._requests.get(rid)
            return None if row is None else self._public_row(rid, row)
        return [self._public_row(r, row) for r, row in self._requests.items()]

    def _public_row(self, rid: int, row: dict) -> dict:
        req = row["req"]
        out = {
            "request_id": rid,
            "state": row["state"],
            "n": req.n,
            "n_class": row["pool"],
            "seed": req.seed,
            "mode": req.mode,
            "tenant": req.tenant,
            "priority": req.priority,
            "lane": row["lane"],
            "generation": row["generation"],
        }
        if row["result"] is not None:
            out["result"] = dict(row["result"])
        if row["spill_path"] is not None:
            out["spill_path"] = row["spill_path"]
        return out

    # -- spill / restore ---------------------------------------------------

    def _spill_path(self, rid: int, row: dict) -> str:
        return os.path.join(
            self.spill_dir, f"lane-n{row['pool']}-req{rid}.npz"
        )

    def _begin_spill(self, rid: int, row: dict, evict: bool = False) -> bool:
        """Start spilling a parked lane. Async path: host-copy now, hand
        to the background writer, hold the lane (``spilling``) until the
        write is durable — unless ``evict``, where the lane frees
        immediately and the host copy is the request until durable.
        Returns False (and emits ``spill_deferred``) when the bounded
        write queue is full — the caller retries next round."""
        pool = self.pools[row["pool"]]
        lane = row["lane"]
        path = self._spill_path(rid, row)
        saved_run = pool.run_counters(lane)
        if self.sync_spill:
            from kaboodle_tpu import checkpoint

            checkpoint.save(path, pool.member(lane), atomic=True,
                            owner=self.engine_id)
            pool.release(lane)
            del self._lane_owner[(row["pool"], lane)]
            row.update(state=SPILLED, lane=None, spill_path=path,
                       saved_run=saved_run, spill_owner=self.engine_id)
            self._log("spilled", rid, path=path, saved_run=saved_run)
            self._emit("serve_event",
                       event="preempted" if evict else "spilled",
                       request_id=rid, pool_n=row["pool"], lane=lane,
                       path=path)
            self._span(rid, "spilled", pool_n=row["pool"])
            return True
        # A thunk binding the warmed gather to the current (immutable)
        # mesh snapshot: the writer thread executes the gather and the
        # device->host transfer, so the round loop pays only a queue put.
        member = pool.member_snapshot(lane)
        if not self.spiller.submit_write(rid, path, member):
            self.spiller.drop_cache(rid)
            self._emit("serve_event", event="spill_deferred", request_id=rid,
                       pool_n=row["pool"], lane=lane)
            return False
        self._log("spill_begin", rid, path=path)
        row.update(spill_path=path, saved_run=saved_run,
                   spill_owner=self.engine_id)
        if evict:
            pool.release(lane)
            del self._lane_owner[(row["pool"], lane)]
            row.update(state=SPILLED, lane=None)
            self._emit("serve_event", event="preempted", request_id=rid,
                       pool_n=row["pool"], lane=lane, path=path)
            self._span(rid, "spilling", pool_n=row["pool"])
        else:
            row["state"] = SPILLING
            self._span(rid, "spilling", pool_n=row["pool"], lane=lane)
        return True

    def _poll_spills(self) -> None:
        """Fold completed background I/O into request state (round start).

        A durable write frees a ``spilling`` lane (or just journals an
        evicted one); a failed write degrades a held lane back to parked
        — loudly — or, for an evicted lane whose host copy is the only
        remaining state, marks the write for retry."""
        if self._spiller is None:
            return
        for res in self._spiller.poll():
            row = self._requests.get(res.rid)
            if row is None or res.op == "read":
                continue  # prefetch results live in the cache
            if res.ok:
                if row["state"] == SPILLING:
                    pool = self.pools[row["pool"]]
                    lane = row["lane"]
                    pool.release(lane)
                    del self._lane_owner[(row["pool"], lane)]
                    row.update(state=SPILLED, lane=None)
                    self._log("spilled", res.rid, path=res.path,
                              saved_run=row["saved_run"])
                    self._emit("serve_event", event="spilled",
                               request_id=res.rid, pool_n=row["pool"],
                               lane=lane, path=res.path)
                    self._span(res.rid, "spilled", pool_n=row["pool"])
                elif row["state"] == SPILLED:
                    self._log("spilled", res.rid, path=res.path,
                              saved_run=row["saved_run"])
                    self._emit("serve_event", event="spilled",
                               request_id=res.rid, pool_n=row["pool"],
                               lane=-1, path=res.path)
                    self._span(res.rid, "spilled", pool_n=row["pool"])
                # restored/cancelled while in flight: the file is a
                # harmless stale snapshot; nothing to transition.
            elif row["state"] == SPILLING:
                self.spiller.drop_cache(res.rid)
                row.update(state=PARKED, idle_rounds=0, spill_path=None,
                           saved_run=None)
                self._log("spill_failed", res.rid, path=res.path,
                          error=res.error)
                self._emit("serve_event", event="spill_failed",
                           request_id=res.rid, pool_n=row["pool"],
                           lane=row["lane"], error=res.error)
                self._span(res.rid, "parked", pool_n=row["pool"],
                           lane=row["lane"], fate="spill_failed")
            elif row["state"] == SPILLED:
                row["retry_spill"] = True
                self._log("spill_failed", res.rid, path=res.path,
                          error=res.error)
                self._emit("serve_event", event="spill_failed",
                           request_id=res.rid, pool_n=row["pool"], lane=-1,
                           error=res.error, retrying=True)

    def _retry_spills(self) -> None:
        for rid, row in self._requests.items():
            if not row["retry_spill"]:
                continue
            member = self.spiller.cached(rid)
            if member is None:  # a racing write actually landed
                row["retry_spill"] = False
                continue
            if self.spiller.submit_write(rid, row["spill_path"], member):
                row["retry_spill"] = False

    def settle_spills(self, max_rounds: int = 100) -> None:
        """Block until no spill write is in flight or pending retry (test
        and shutdown helper — the round loop itself never blocks)."""
        if self._spiller is None:
            return
        for _ in range(max_rounds):
            self._spiller.flush()
            self.step()
            if not any(
                row["state"] == SPILLING or row["retry_spill"]
                for row in self._requests.values()
            ):
                return
        raise RuntimeError("spill writes still failing after retries")

    def restore(self, rid: int) -> bool:  # conc: event-loop
        """Bring a spilled request back into a free lane (parked). Returns
        False when its class pool has no free lane right now. Prefers the
        spill manager's host cache (an evicted lane whose write has not
        landed yet restores from memory); a missing/corrupt spill file
        raises :class:`CheckpointError` after a ``restore_failed`` event —
        the request stays spilled, the engine keeps serving."""
        from kaboodle_tpu import checkpoint

        row = self._requests.get(rid)
        if row is None or row["state"] != SPILLED:
            raise ValueError(f"request {rid} is not spilled")
        pool = self.pools[row["pool"]]
        lane = pool.free_lane()
        if lane is None:
            return False
        member = (
            self._spiller.cached(rid) if self._spiller is not None else None
        )
        if member is None:
            # The stamp check: this engine's own files carry its id; an
            # adopted request's file carries the DEAD engine's id, which
            # rode in through adopt(). Anything else is an alien snapshot.
            expect = row["spill_owner"] if self.engine_id is not None else None
            try:
                member = checkpoint.load(
                    row["spill_path"], expect_owner=expect
                )
            except CheckpointError as e:
                self._emit_standalone(
                    "serve_event", event="restore_failed", request_id=rid,
                    pool_n=row["pool"], lane=-1, error=str(e),
                )
                raise
        row["generation"] = pool.insert(lane, member)
        if row["saved_run"] is not None:
            pool.set_run_counters(lane, row["saved_run"])
        if self._spiller is not None:
            self._spiller.drop_cache(rid)
        row["retry_spill"] = False
        self._lane_owner[(row["pool"], lane)] = rid
        row.update(state=PARKED, lane=lane, idle_rounds=0)
        self._log("restored", rid)
        self._emit("serve_event", event="restored", request_id=rid,
                   pool_n=row["pool"], lane=lane,
                   generation=row["generation"])
        self._span(rid, "parked", pool_n=row["pool"], lane=lane,
                   fate="restored")
        return True

    def resume(self, rid: int, mode: str = "ticks", ticks: int = 16) -> None:  # conc: event-loop
        """Re-activate a parked request with a fresh budget (continuation
        runs across the park/spill boundary keep their tick counters)."""
        row = self._requests.get(rid)
        if row is None or row["state"] != PARKED:
            raise ValueError(f"request {rid} is not parked")
        if mode not in ("converge", "ticks"):
            raise ValueError(f"unknown mode {mode!r}")
        pool = self.pools[row["pool"]]
        pool.resume(row["lane"], until_conv=(mode == "converge"),
                    budget=int(ticks))
        row["state"] = RUNNING
        row["idle_rounds"] = 0
        row["result"] = None  # the continuation's harvest replaces it
        self._log("resumed", rid, mode=mode, ticks=int(ticks))
        self._emit("serve_event", event="resumed", request_id=rid,
                   pool_n=row["pool"], lane=row["lane"], mode=mode,
                   ticks=int(ticks))
        self._span(rid, "running", pool_n=row["pool"], lane=row["lane"])

    def adopt(
        self,
        req: ServeRequest,
        spill_path: str,
        saved_run: dict | None,
        owner: str | None,
    ) -> int:  # conc: event-loop
        """Take over a dead engine's SPILLED request (federation failover).

        The router replays the dead engine's journal, finds a non-terminal
        request whose last durable state is a spill file in the shared
        root, and hands (request, file, frozen run counters, dead engine's
        id) to a survivor here. The request lands ``spilled`` under a
        fresh local rid; a later :meth:`restore` loads the file expecting
        the DEAD engine's owner stamp — the explicit handover is exactly
        the intentional cross-engine restore the stamp guard exists to
        separate from accidental ones. Journaled as one ``adopted`` record
        (submitted + spilled folded), so the survivor's own recovery
        re-attaches it like any native spill."""
        if req.n_class not in self.pools:
            raise ValueError(
                f"no pool serves N-class {req.n_class} (adopt n={req.n})"
            )
        if not os.path.exists(spill_path):
            raise CheckpointError(f"adopt: spill file missing: {spill_path}")
        rid = self._next_rid
        self._next_rid += 1
        row = _fresh_row(req)
        row.update(state=SPILLED, spill_path=spill_path, saved_run=saved_run,
                   spill_owner=owner)
        self._requests[rid] = row
        self._log("adopted", rid, req=dataclasses.asdict(req),
                  path=spill_path, saved_run=saved_run, owner=owner)
        self._emit_standalone(
            "serve_event", event="adopted", request_id=rid,
            pool_n=row["pool"], lane=-1, path=spill_path,
            prior_owner=owner if owner is not None else "",
        )
        self._span(rid, "spilled", pool_n=row["pool"])
        return rid

    # -- crash recovery ----------------------------------------------------

    def recover(self) -> dict:
        """Rebuild the request table from this engine's journal.

        Call on a FRESH engine (warmed pools, empty table) constructed
        with the crashed engine's ``journal_dir``. Folding rules: terminal
        rows keep their results and are never re-run; rows whose last
        durable state is a spill file re-attach as ``spilled`` (their
        host run counters ride along, so a later restore+resume is a true
        continuation); everything else — queued, running, parked-but-not-
        spilled, mid-spill — lost its device state with the process and
        re-queues from its seed with a cumulative tick budget (original
        budget plus every journaled ticks-mode resume). Returns per-
        disposition counts, emits ``recovered`` (+ per-row ``requeued``),
        and compacts the journal."""
        if self.journal is None:
            raise ValueError("recover() needs an engine with journal_dir")
        if self._requests:
            raise ValueError("recover() needs an empty engine")
        # Startup phase: replay + cold restores are budgeted stalls for
        # the conc sanitizer's loop watchdog, like warmup's compiles.
        _conc_sanitizer.budget_current_callback()
        table, next_rid = self.journal.replay()
        counts = {"done": 0, "spilled": 0, "requeued": 0, "cancelled": 0,
                  "dropped": 0}
        requeued: list[int] = []
        for rid in sorted(table):
            jrow = table[rid]
            if jrow.get("req") is None:
                counts["dropped"] += 1  # torn submit: client never acked
                continue
            req = ServeRequest(**jrow["req"])
            if req.n_class not in self.pools:
                counts["dropped"] += 1
                continue
            row = _fresh_row(req)
            op = jrow.get("op")
            spill_ok = bool(jrow.get("spill_path")) and os.path.exists(
                jrow["spill_path"]
            )
            if op in ("cancelled", "shed"):
                row["state"] = CANCELLED
                counts["cancelled"] += 1
            elif spill_ok and op in ("spilled", "restored", "adopted"):
                # Native spills restore under this engine's stamp; adopted
                # ones keep the dead engine's (the journal remembers it).
                owner = (jrow.get("spill_owner") if op == "adopted"
                         else self.engine_id)
                row.update(state=SPILLED, spill_path=jrow["spill_path"],
                           saved_run=jrow.get("saved_run"),
                           result=jrow.get("result"), spill_owner=owner)
                counts["spilled"] += 1
            elif op == "harvested":
                row.update(state=DONE, result=jrow.get("result"))
                counts["done"] += 1
            else:
                extra = int(jrow.get("extra_ticks", 0))
                if extra:
                    req = dataclasses.replace(req, ticks=req.ticks + extra)
                    row["req"] = req
                counts["requeued"] += 1
                requeued.append(rid)
            self._requests[rid] = row
        self._next_rid = max(self._next_rid, next_rid)
        self.journal.compact(table, self._next_rid)
        # Re-queue in journal order (the WAL's sequence numbers; rid as
        # the pre-seq fallback), so recovery admission replays the order
        # the crashed engine actually witnessed.
        requeued.sort(key=lambda rid: (table[rid].get("seq", rid), rid))
        for rid in requeued:
            self._log("requeued", rid)
            self._emit_standalone(
                "serve_event", event="requeued", request_id=rid,
                pool_n=self._requests[rid]["pool"], lane=-1,
            )
            self._span(rid, "queued", pool_n=self._requests[rid]["pool"])
        for rid, row in self._requests.items():
            if row["state"] == SPILLED:
                self._span(rid, "spilled", pool_n=row["pool"])
        self._emit_standalone(
            "serve_event", event="recovered", request_id=-1, lane=-1,
            pool_n=min(self.pools), **counts,
        )
        return counts

    # -- the round loop ----------------------------------------------------

    @property
    def busy(self) -> bool:
        if any(row["state"] == QUEUED for row in self._requests.values()):
            return True
        return any(pool.active.any() for pool in self.pools.values())

    @property
    def spilling(self) -> bool:
        """Spill I/O in flight or pending retry (the server's idle loop
        keeps stepping while this holds, so completions get folded)."""
        return any(
            row["state"] == SPILLING or row["retry_spill"]
            for row in self._requests.values()
        )

    def step(self) -> list[dict]:  # conc: event-loop
        """One engine round: fold spill completions, admit, advance every
        pool, harvest, spill. Never blocks on disk.

        Returns the manifest records emitted this round (also fanned out
        through ``on_event`` as they happen). With an observability plane
        attached the loop sections are bracketed by monotonic-clock laps
        (preallocated accumulators — no allocation per round) and, when
        tracing, the finished round is emitted as one ``round`` span
        carrying the segment split."""
        obs = self.obs
        prof = obs.profiler if obs is not None else None
        self._events = []
        if prof is not None:
            t = prof.round_begin()
            t0_us = obs.now_us()
        self._poll_spills()
        self._retry_spills()
        if prof is not None:
            t = prof.lap(SEG_POLL, t)
        self._admit_queued()
        if prof is not None:
            t = prof.lap(SEG_ADMIT, t)
        for pool in self.pools.values():
            if not pool.active.any():
                continue
            if not self._try_leap_round(pool):
                self._chunk_round(pool)
            if prof is not None:
                t = prof.lap(SEG_DISPATCH, t)
            self._harvest(pool)
            if prof is not None:
                t = prof.lap(SEG_HARVEST, t)
        self._spill_idle()
        if prof is not None:
            t = prof.lap(SEG_SPILL, t)
        rnd = self.round
        self.round += 1
        if self.journal is not None and self.journal.should_compact():
            table, next_rid = self.journal.replay()
            self.journal.compact(table, max(next_rid, self._next_rid))
        if prof is not None:
            prof.lap(SEG_JOURNAL, t)
            prof.round_end()
            if obs.trace:
                self._emit(
                    "serve_span", span="round", request_id=-1, pool_n=-1,
                    lane=-1, round=rnd, t0_us=t0_us,
                    dur_us=int(prof.last_us[SEG_ROUND]),
                    segments=prof.last_segments(),
                )
        return self._events

    def drain(self, max_rounds: int = 10_000) -> list[dict]:
        """Round until nothing is queued or active; returns all events."""
        events: list[dict] = []
        for _ in range(max_rounds):
            if not self.busy:
                return events
            events.extend(self.step())
        raise RuntimeError(f"engine still busy after {max_rounds} rounds")

    def _admit_queued(self) -> None:
        queued = [
            (rid, row)
            for rid, row in self._requests.items()
            if row["state"] == QUEUED
        ]
        if self.admission is not None:
            # Priority classes admit first; FIFO (rid order) within one.
            queued.sort(key=lambda kv: (-kv[1]["req"].priority, kv[0]))
        for rid, row in queued:
            pool = self.pools[row["pool"]]
            lane = pool.free_lane()
            if lane is None and self.admission is not None:
                if self._preempt_for(row):
                    lane = pool.free_lane()
            if lane is None:
                continue  # class full this round; stays queued
            req: ServeRequest = row["req"]
            row["generation"] = pool.admit(
                lane, seed=req.seed, drop_rate=req.drop_rate,
                until_conv=req.until_conv, budget=req.ticks,
                scenario=req.scenario,
            )
            self._lane_owner[(row["pool"], lane)] = rid
            row.update(state=RUNNING, lane=lane)
            self._log("admitted", rid, lane=lane,
                      generation=row["generation"])
            self._emit("serve_event", event="admitted", request_id=rid,
                       pool_n=row["pool"], lane=lane,
                       generation=row["generation"], seed=req.seed,
                       mode=req.mode, scenario=req.scenario)
            self._span(rid, "running", pool_n=row["pool"], lane=lane)

    def _preempt_for(self, row: dict) -> bool:
        """Spill-evict one strictly lower-priority PARKED lane of this
        class (lowest priority, then oldest-parked) to admit ``row``.
        Running lanes are never preempted. Needs a spill_dir."""
        if self.spill_dir is None:
            return False
        pri = row["req"].priority
        victims = [
            (rid, r)
            for rid, r in self._requests.items()
            if r["state"] == PARKED and r["pool"] == row["pool"]
            and r["req"].priority < pri
        ]
        if not victims:
            return False
        vrid, vrow = min(
            victims,
            key=lambda kv: (kv[1]["req"].priority, -kv[1]["idle_rounds"],
                            kv[0]),
        )
        return self._begin_spill(vrid, vrow, evict=True)

    def _try_leap_round(self, pool: LanePool) -> bool:
        """One masked fleet-leap dispatch if any horizon lane can cover
        MIN_LEAP ticks; returns False when this round must run dense."""
        if not self.warp or pool.faulty or pool.telemetry:
            return False
        horizon = pool.active & ~pool.until_conv & (pool.remaining > 0)
        if not horizon.any():
            return False
        rows = np.asarray(  # noqa: KB501 — bounded [E]-row fetch; the round loop dispatches inline by design (server.py docstring)
            pool.signature()
        )
        # int32 on the host: the pool's leap hook then device-puts it as
        # is — an int64 vector would dispatch a fresh convert_element_type
        # program and break the zero-recompile contract.
        k_m = np.zeros((pool.lanes,), dtype=np.int32)
        tracing = self.obs is not None and self.obs.trace
        classes: list[dict] = []
        decoded: list[tuple] = []  # (cls, mode) per horizon lane
        for e in np.flatnonzero(horizon):
            cls = decode_signature(rows[e])
            mode = _classify(cls, hybrid=True, warp_mode=self.warp_mode)
            if mode != "dense":
                k_m[e] = min(
                    _leap_budget(cls, mode, int(pool.remaining[e])),
                    self.max_leap,
                )
            decoded.append((cls, mode))
            if tracing:
                classes.append({
                    "lane": int(e), "k": int(k_m[e]), "mode": mode,
                    "class_key": cls.key, "terms": cls.describe()["terms"],
                })
        if k_m.max() < MIN_LEAP:
            # Round falls back to the chunk engine: attribute the dense
            # ticks each horizon lane is about to pay (pool.chunk) to the
            # signature terms that blocked its leap — host-side only.
            for cls, mode in decoded:
                self.warp_ledger.record_blocked(
                    cls, pool.chunk, "serve", mode=mode
                )
            return False
        K = 1 << int(k_m.max() - 1).bit_length()
        K = max(K, MIN_LEAP)
        if tracing:
            t0_us = self.obs.now_us()
        memo_hits, dispatched = pool.leap(K, k_m, memo=self.warp_memo)
        pool.advance_leaped(k_m)
        self._emit(
            "serve_round", round=self.round, pool_n=pool.n,
            engine="leap" if dispatched else "leap+memo",
            lanes=int((k_m > 0).sum()), ticks=int(k_m.sum()), bucket=K,
            memo_hits=memo_hits,
        )
        if tracing:
            # One advance span per pool round, each leaping lane annotated
            # with its Warp 2.0 signature class — the trace exporter fans
            # these onto the per-lane tracks. dur_us is dispatch wall time
            # (the round loop's view; device compute is asynchronous).
            self._emit(
                "serve_span", span="advance", request_id=-1, pool_n=pool.n,
                lane=-1, t0_us=t0_us, dur_us=self.obs.now_us() - t0_us,
                round=self.round, engine="leap", bucket=K, classes=classes,
            )
        return True

    def _chunk_round(self, pool: LanePool) -> None:
        prev = pool.ticks_run.copy()
        tracing = self.obs is not None and self.obs.trace
        if tracing:
            t0_us = self.obs.now_us()
            active = [int(e) for e in np.flatnonzero(pool.active)]
        pool.step()
        self._emit(
            "serve_round", round=self.round, pool_n=pool.n, engine="chunk",
            lanes=int(pool.active.sum()),
            ticks=int((pool.ticks_run - prev).sum()),
        )
        if tracing:
            self._emit(
                "serve_span", span="advance", request_id=-1, pool_n=pool.n,
                lane=-1, t0_us=t0_us, dur_us=self.obs.now_us() - t0_us,
                round=self.round, engine="chunk",
                classes=[
                    {"lane": e, "k": int(pool.ticks_run[e] - prev[e])}
                    for e in active
                ],
            )

    def _harvest(self, pool: LanePool) -> None:
        finished = pool.active & (
            (pool.until_conv & (pool.conv_tick >= 0))
            | (pool.remaining <= 0)
        )
        if not finished.any():
            return
        converged, fp_min, fp_max, n_alive = pool.agreement()
        for lane in np.flatnonzero(finished):
            lane = int(lane)
            rid = self._lane_owner[(pool.n, lane)]
            row = self._requests[rid]
            req: ServeRequest = row["req"]
            result = {
                "ticks_run": int(pool.ticks_run[lane]),
                "conv_tick": int(pool.conv_tick[lane]),
                "converged": bool(converged[lane]),
                "fp_min": int(fp_min[lane]),
                "fp_max": int(fp_max[lane]),
                "n_alive": int(n_alive[lane]),
                "messages": int(pool.messages[lane]),
            }
            counters = pool.counters_row(lane)
            if counters is not None:
                result["counters"] = counters
            row["result"] = result
            if not pool.until_conv[lane]:
                event = "completed"  # horizon run: ran its exact ticks
            elif pool.conv_tick[lane] >= 0:
                event = "converged"
            else:
                event = "exhausted"  # converge run: budget up, no agreement
            self._log("harvested", rid, event=event, result=result)
            self._emit(
                "serve_event", event=event, request_id=rid, pool_n=pool.n,
                lane=lane, generation=row["generation"], **result,
            )
            if req.keep:
                pool.park(lane)
                row["state"] = PARKED
                row["idle_rounds"] = 0
                self._span(rid, "parked", pool_n=pool.n, lane=lane,
                           fate=event, ticks_run=result["ticks_run"])
            else:
                pool.release(lane)
                del self._lane_owner[(pool.n, lane)]
                row.update(state=DONE, lane=None)
                self._span(rid, None, fate=event,
                           ticks_run=result["ticks_run"])

    def _spill_idle(self) -> None:
        if self.spill_after is None or self.spill_dir is None:
            return
        begun = 0
        for rid, row in self._requests.items():
            if row["state"] != PARKED:
                continue
            row["idle_rounds"] += 1
            if (row["idle_rounds"] > self.spill_after
                    and begun < self.spills_per_round):
                # deferred (queue full) => retried next round
                begun += int(self._begin_spill(rid, row))

    # -- warmup ------------------------------------------------------------

    def warmup(self) -> None:
        """Compile the whole serving surface with state-preserving
        dispatches: each pool's program set (pool.warmup), then — for
        pools the warp applies to — the signature fetch and every leap
        bucket 8..max_leap at ``k_m = 0`` (the masked span program freezes
        everyone bit-exactly at zero). After this the round loop's
        admit/leap/chunk/harvest/spill path compiles nothing — the async
        spill's host copies are device fetches, not programs."""
        # Budgeted for the conc sanitizer's loop watchdog, like the
        # compiles_steady gauge: warmup stalls are the contract, steady-
        # state stalls are the bug.
        with _conc_sanitizer.budgeted():
            for pool in self.pools.values():
                pool.warmup()
                if not self.warp or pool.faulty or pool.telemetry:
                    continue
                np.asarray(pool.signature())
                zeros = np.zeros((pool.lanes,), dtype=np.int32)
                K = MIN_LEAP
                while K <= self.max_leap:
                    pool.leap(K, zeros)
                    K <<= 1
        self._emit_standalone(
            "serve_event", event="warm", request_id=-1, lane=-1,
            pool_n=min(self.pools), pools=sorted(self.pools),
        )
        if self.obs is not None:
            # Warmup compiles are the budgeted ones; from here on the
            # ``compiles_steady`` gauge counts contract violations.
            self.obs.reset_compiles()

    # -- events ------------------------------------------------------------

    def _emit(self, kind: str, **fields) -> dict:
        rec = self._emit_standalone(kind, **fields)
        self._events.append(rec)
        return rec

    def _emit_standalone(self, kind: str, **fields) -> dict:
        if self.obs is not None:
            if self.obs.trace and "t_us" not in fields:
                # Wall-clock stamp (plane-epoch us) so lifecycle events
                # land on the same trace timeline as the spans.
                fields["t_us"] = self.obs.now_us()
            rec = run_record(kind, **fields)
            self.obs.on_record(rec)
        else:
            rec = run_record(kind, **fields)
        if self.on_event is not None:
            self.on_event(rec)
        return rec

    def stats(self) -> dict:  # conc: event-loop
        states: dict[str, int] = {}
        for row in self._requests.values():
            states[row["state"]] = states.get(row["state"], 0) + 1
        return {
            "round": self.round,
            "requests": len(self._requests),
            "states": states,
            "pools": {n: p.stats() for n, p in self.pools.items()},
        }
