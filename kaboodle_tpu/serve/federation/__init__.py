"""Federated serving: many engines behind one consistent-hash front door.

ROADMAP item 1's second rung. One :class:`~kaboodle_tpu.serve.federation.
router.FedRouter` speaks the same NDJSON wire protocol as ``server.py``
and places requests onto member :class:`~kaboodle_tpu.serve.server.
ServeServer` engines:

- **placement** (ring.py): a consistent-hash ring over the live members
  (stable hashes — ``hashlib``, never the per-process-salted builtin —
  so placement is deterministic across router restarts), preference-order
  walk filtered to members that serve the request's N-class, tie-broken
  by router-tracked inflight load.
- **failover** (router.py): engines share one spill root and one journal
  root, each namespaced per engine-id (engine.py). When a member dies
  mid-round the router replays its journal READ-ONLY
  (:func:`~kaboodle_tpu.serve.journal.replay_journal`): journaled results
  are served from the fold and never re-run, spilled requests are
  ``adopt``-ed onto survivors (the spill file still carries the dead
  engine's owner stamp — the checkpoint guard's sanctioned handover),
  and everything else re-queues from its seed with its cumulative tick
  budget. A client parked in ``wait`` rides through transparently.
- **proof** (fedload.py): the ``fed-load`` driver and the
  ``make fedserve-dryrun`` CI lane — two engines + router on loopback,
  mixed N-classes, park/resume churn, a kill-one-engine chaos scenario,
  zero lost terminals, zero duplicate completions, zero steady compiles.
"""

from kaboodle_tpu.serve.federation.ring import HashRing
from kaboodle_tpu.serve.federation.router import EngineMember, FedRouter

__all__ = ["EngineMember", "FedRouter", "HashRing"]
