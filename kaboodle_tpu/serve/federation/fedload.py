"""fed-load: the federation proof-under-fire driver (BENCH_fedserve.json).

Builds a real two-engine federation in one process — two
:class:`~kaboodle_tpu.serve.server.ServeServer` engines on loopback
sharing one spill root and one journal root (namespaced per engine-id),
one :class:`~kaboodle_tpu.serve.federation.router.FedRouter` in front —
and drives it three ways, all inside the KB405 compile counter:

- **SLO levels**: open-loop waves of mixed N-classes (16 and 32) at
  multiples of the single-engine BENCH_serve open-loop baseline rate,
  with park/resume churn riding along (every 8th request is kept, then
  resumed and cancelled after its first harvest). Each level banks
  per-N-class latency percentiles — the federated SLO curves.
- **chaos**: a mixed batch including kept-and-spilled requests, then
  ``ServeServer.kill()`` on one engine mid-round (listener gone,
  connections RST, round loop cancelled — the engine object is NOT
  closed, exactly a crash). Every outstanding ``wait`` must resolve
  through the router's WAL failover with exactly one result per request,
  and a request that had spilled on the dead engine must restore+resume
  on the survivor (checkpoint adoption across the owner stamp).
- **the gate**: ``compiles_steady == 0`` across all of it — placement,
  failover, adoption and churn ride the warmed programs.

Engine e0's N=32 pool is a :class:`~kaboodle_tpu.serve.shardpool.
ShardedLanePool` on a 2x2 device mesh when the process has >= 4 devices
(CI forces 8 virtual CPU devices), so the sharded pool serves real
federated traffic under the same compile gate; the chaos adoption moves
a spill file written by the dead engine into the survivor's pool, so
checkpoints are engine-portable. Only ONE engine gets a sharded pool:
both engines share the event loop thread, and that keeps every
collective dispatch serialized (the CPU rendezvous discipline from
shardpool.py).

``--dryrun`` is the same machinery at toy sizes with hard asserts — the
``make fedserve-dryrun`` CI lane.
"""

from __future__ import annotations

import asyncio
import json
import os
import tempfile
import time

from kaboodle_tpu.serve.loadgen import _latency_stats, _mix_fields

_WAIT_S = 60.0


def _class_of(i: int) -> int:
    """The level mix: every 4th request is the big sharded class."""
    return 32 if i % 4 == 3 else 16


def _build_engines(scratch: str, lanes: int, chunk: int, shard: bool):
    """Two engines on one shared spill/journal root. e0 may carry the
    sharded N=32 pool; e1 is all single-device — adoption across the two
    proves the spill format is placement-agnostic."""
    import jax

    from kaboodle_tpu.serve.engine import ServeEngine
    from kaboodle_tpu.serve.pool import LanePool

    spill_root = os.path.join(scratch, "spill")
    journal_root = os.path.join(scratch, "journal")
    sharded = shard and len(jax.devices()) >= 4
    engines = []
    for eid in ("e0", "e1"):
        pools = [LanePool(16, lanes, chunk=chunk)]
        if eid == "e0" and sharded:
            from kaboodle_tpu.fleet.sharding import make_fleet_mesh
            from kaboodle_tpu.serve.shardpool import ShardedLanePool

            pools.append(ShardedLanePool(
                32, lanes, chunk=chunk, device_mesh=make_fleet_mesh(2, 2)
            ))
        else:
            pools.append(LanePool(32, lanes, chunk=chunk))
        engines.append(ServeEngine(
            pools, warp=True, max_leap=64, spill_after=2,
            spill_dir=spill_root, journal_dir=journal_root,
            engine_id=eid,
        ))
    return engines, spill_root, journal_root, sharded


async def _open_level(client_factory, submit_client, requests: int,
                      rate: float):
    """One open-loop federated level: scheduled submits on the shared
    router connection, each completion waited on its own connection;
    every 8th request exercises the park -> resume -> cancel churn."""
    lat: dict[int, list] = {16: [], 32: []}
    waiters: list[asyncio.Task] = []

    async def complete(i: int, rid: int, t0: float) -> None:
        from kaboodle_tpu.serve.client import ServeError

        c = await client_factory()
        try:
            await c.wait(rid)
            lat[_class_of(i)].append(time.perf_counter() - t0)
            if i % 8 == 0:  # churn: the kept lane parks after harvest
                # The lane may already have idled out and spilled (or be
                # mid-spill) by the time the resume lands — that IS the
                # churn: restore and retry until the resume sticks.
                for _ in range(200):
                    try:
                        await c.resume(rid, mode="ticks", ticks=4)
                        break
                    except ServeError:
                        try:
                            await c.restore(rid)
                        except ServeError:
                            await asyncio.sleep(0.02)
                else:
                    raise RuntimeError(f"resume never stuck for {rid}")
                await c.wait(rid)
                await c.cancel(rid)  # release the lane
        finally:
            await c.close()

    start = time.perf_counter()
    for i in range(requests):
        delay = start + i / rate - time.perf_counter()
        if delay > 0:
            await asyncio.sleep(delay)
        fields = _mix_fields(i)
        fields["keep"] = i % 8 == 0
        t0 = time.perf_counter()
        rid = await submit_client.submit(_class_of(i), **fields)
        waiters.append(asyncio.create_task(complete(i, rid, t0)))
    await asyncio.wait_for(asyncio.gather(*waiters), _WAIT_S)
    elapsed = time.perf_counter() - start
    return lat, elapsed


async def _submit_on(client, router_client_factory, target: str, n: int,
                     max_tries: int, **fields) -> int | None:
    """Submit requests until one PLACES on ``target`` (placement is a
    deterministic hash of tenant:class:seed, so varying the seed walks
    the ring). Returns its router rid, or None after ``max_tries``."""
    base_seed = int(fields.pop("seed", 0))
    for t in range(max_tries):
        rid = await client.submit(n, seed=base_seed + 1000 * t, **fields)
        c = await router_client_factory()
        try:
            row = await c.status(rid)
        finally:
            await c.close()
        if row and row.get("engine") == target:
            return rid
        await client.cancel(rid)
    return None


async def _chaos(client_factory, server_kill, victim: str) -> dict:
    """Kill one engine under load and account for every request.

    Pre-kill state staged on the victim: one request harvested, parked
    and SPILLED (the adoption path), plus long ticks-mode runs still
    RUNNING (the re-queue path). Requests on the survivor ride along as
    the control group. After ``kill()``, every wait must resolve exactly
    once, and the spilled request must restore+resume on the survivor."""
    client = await client_factory()
    outcomes: dict[int, dict] = {}

    # 1. A kept request on the victim, run to harvest, then spilled.
    kept_rid = await _submit_on(
        client, client_factory, victim, 16, 40,
        mode="ticks", ticks=6, scenario="steady", keep=True,
    )
    assert kept_rid is not None, f"no kept request placed on {victim}"
    await client.wait(kept_rid)
    deadline = time.perf_counter() + _WAIT_S
    while True:
        row = await client.status(kept_rid)
        if row["state"] == "spilled":
            break
        assert time.perf_counter() < deadline, \
            f"kept request never spilled: {row}"
        await asyncio.sleep(0.05)

    # 2. Long runners: some pinned to the victim (they will die mid-run
    # and re-queue from seed), the rest landing wherever the ring says.
    run_rids: list[int] = []
    for k in range(2):
        rid = await _submit_on(
            client, client_factory, victim, 16, 40, seed=50 + k,
            mode="ticks", ticks=160, scenario="steady",
        )
        assert rid is not None, f"no runner placed on {victim}"
        run_rids.append(rid)
    for k in range(6):
        fields = _mix_fields(k)
        fields["seed"] = 200 + k
        run_rids.append(await client.submit(16, **fields))

    async def complete(rid: int) -> None:
        c = await client_factory()
        try:
            row = await c.wait(rid)
            outcomes[rid] = row
        finally:
            await c.close()

    waiters = [asyncio.create_task(complete(rid)) for rid in run_rids]
    await asyncio.sleep(0.1)  # let the victim's lanes start ticking
    await server_kill()
    await asyncio.wait_for(asyncio.gather(*waiters), _WAIT_S)

    # Zero lost terminals, exactly one resolution per request.
    assert len(outcomes) == len(run_rids), (len(outcomes), len(run_rids))
    assert len(set(run_rids)) == len(run_rids)
    for rid in run_rids:
        row = outcomes[rid]
        assert row.get("result") is not None or row["state"] == "done", row
    # The spilled request adopts onto the survivor and keeps working.
    await client.restore(kept_rid)
    await client.resume(kept_rid, mode="ticks", ticks=4)
    resumed = await asyncio.wait_for(client.wait(kept_rid), _WAIT_S)
    assert resumed.get("result") is not None, resumed
    assert resumed.get("engine") != victim, resumed
    await client.cancel(kept_rid)

    metrics = await client.metrics()
    failovers = sum(
        metrics["counters"].get("fed_failovers_total", {}).values()
    )
    moves = sum(
        metrics["counters"].get("fed_rebalance_moves_total", {}).values()
    )
    assert failovers == 1, metrics["counters"]
    assert moves >= 1, metrics["counters"]
    await client.close()
    return {
        "killed": victim,
        "requests": len(run_rids) + 1,
        "resolved": len(outcomes) + 1,
        "lost_terminals": 0,
        "failovers": int(failovers),
        "rebalance_moves": int(moves),
        "adopted_resume_engine": resumed.get("engine"),
    }


async def _run(args) -> dict:
    from kaboodle_tpu.analysis.ir.surface import (
        assert_counter_live,
        compile_counter,
    )
    from kaboodle_tpu.serve.client import ServeClient
    from kaboodle_tpu.serve.federation.router import EngineMember, FedRouter
    from kaboodle_tpu.serve.server import ServeServer

    assert_counter_live()
    scratch = tempfile.mkdtemp(prefix="kaboodle-fed-")
    engines, spill_root, journal_root, sharded = _build_engines(
        scratch, args.lanes, args.chunk, shard=not args.no_shard
    )
    t0 = time.perf_counter()
    for eng in engines:
        eng.warmup()
    warmup_s = time.perf_counter() - t0

    servers = [ServeServer(eng, port=0) for eng in engines]
    for srv in servers:
        await srv.start()
    router = FedRouter(
        [EngineMember(eng.engine_id, "127.0.0.1", srv.port)
         for eng, srv in zip(engines, servers)],
        journal_root=journal_root, spill_root=spill_root, port=0,
    )
    await router.start()

    async def client_factory():
        return await ServeClient.connect(port=router.port)

    # Warm wave through the router (uncounted): both classes, spread
    # seeds so both engines see wire traffic before measurement.
    warm = await client_factory()
    for i in range(4 * args.lanes):
        rid = await warm.submit(_class_of(i), **_mix_fields(i))
        await warm.wait(rid)
    await warm.close()

    levels: dict[str, dict] = {}
    with compile_counter() as box:
        submit_client = await client_factory()
        for mult in args.levels:
            rate = args.base_rate * mult
            requests = max(args.requests, int(rate * args.level_seconds))
            lat, elapsed = await _open_level(
                client_factory, submit_client, requests, rate
            )
            done = sum(len(v) for v in lat.values())
            levels[f"{mult:g}x"] = {
                "offered_rps": round(rate, 2),
                "requests": requests,
                "elapsed_s": round(elapsed, 3),
                "achieved_rps": round(done / elapsed, 2),
                "latency_by_class": {
                    str(n): _latency_stats(v) for n, v in lat.items() if v
                },
            }
        await submit_client.close()
        chaos = await _chaos(client_factory, servers[1].kill,
                             engines[1].engine_id)
    compiles = box.count

    probe = await client_factory()
    stats = await probe.stats()
    metrics = await probe.metrics()
    await probe.shutdown()
    await probe.close()
    await router.close()
    await servers[0].close()  # closes engine e0
    engines[1].close()  # e1's server was killed, not closed

    return {
        "bench": "fedserve",
        "members": len(engines),
        "sharded_pool": sharded,
        "lanes": args.lanes,
        "chunk": args.chunk,
        "base_rate_rps": args.base_rate,
        "warmup_s": round(warmup_s, 3),
        "compiles_steady": compiles,
        "levels": levels,
        "chaos": chaos,
        "router": {
            "alive": stats["alive"],
            "routes": stats["routes"],
            "submits": metrics["counters"].get("fed_submits_total", {}),
            "ring_size": metrics["gauges"].get("fed_ring_size", {}),
        },
    }


def main(argv=None) -> int:
    """``python -m kaboodle_tpu fed-load`` — federated load + chaos."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="kaboodle-tpu fed-load",
        description="two-engine federation load driver with kill-one-"
                    "engine chaos (BENCH_fedserve.json)",
    )
    parser.add_argument("--lanes", type=int, default=8, help="lanes per pool")
    parser.add_argument("--chunk", type=int, default=8)
    parser.add_argument("--requests", type=int, default=48,
                        help="minimum measured requests per level")
    parser.add_argument("--base-rate", type=float, default=50.0,
                        help="1x offered req/s (the BENCH_serve open-loop "
                             "baseline rate)")
    parser.add_argument("--levels", default="2,5,10",
                        help="comma-separated offered-load multiples")
    parser.add_argument("--level-seconds", type=float, default=1.0,
                        help="minimum offered-schedule length per level")
    parser.add_argument("--no-shard", action="store_true",
                        help="plain pools everywhere (skip the sharded "
                             "N=32 pool on e0)")
    parser.add_argument("--dryrun", action="store_true",
                        help="CI sizes: tiny levels, full chaos asserts, "
                             "one-line JSON tail, no report file")
    parser.add_argument("--out", default="BENCH_fedserve.json")
    args = parser.parse_args(argv)
    if args.dryrun:
        args.levels = [2.0]
        args.requests = 24
        args.level_seconds = 0.0
    else:
        args.levels = [float(tok) for tok in args.levels.split(",")]

    report = asyncio.run(_run(args))
    if args.dryrun:
        assert report["compiles_steady"] == 0, report["compiles_steady"]
        assert report["chaos"]["lost_terminals"] == 0
        assert report["chaos"]["failovers"] == 1
        print(json.dumps({
            "fedserve_dryrun": "ok",
            "sharded_pool": report["sharded_pool"],
            "levels": list(report["levels"]),
            "chaos": report["chaos"],
            "compiles_steady": report["compiles_steady"],
        }))
        return 0
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(json.dumps(report))
    if report["compiles_steady"] != 0:
        print(f"FAIL: {report['compiles_steady']} fresh compiles in the "
              "steady phase (zero-recompile gate)")
        return 1
    return 0
