"""Consistent-hash placement ring (the federation's request->engine map).

Classic Karger ring with virtual nodes: each member owns ``vnodes``
points on a 64-bit circle, a key is placed on the first point at or past
its own hash. Properties the federation leans on, pinned in
tests/test_fedserve.py:

- **stability**: adding or removing one of M members moves only ~1/M of
  the key space — a failover re-places the dead engine's keys and
  nothing else, so the survivors' warmed lanes keep their traffic.
- **determinism**: hashes come from ``hashlib.blake2b``, never the
  builtin ``hash`` (salted per process) — two router processes, or one
  router across restarts, place every key identically. Placement state
  never needs journaling.
- **spread**: vnodes smooth per-member load to within a few percent at
  the federation's key cardinalities.

Pure host-side stdlib; no jax anywhere near this module.
"""

from __future__ import annotations

import bisect
import hashlib


def stable_hash(key: str) -> int:
    """64-bit process-independent hash (blake2b prefix)."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


class HashRing:
    """Consistent-hash ring over member ids, ``vnodes`` points each."""

    def __init__(self, members=(), vnodes: int = 64) -> None:
        if vnodes < 1:
            raise ValueError("need vnodes >= 1")
        self.vnodes = int(vnodes)
        self._points: list[int] = []  # sorted vnode hashes
        self._owner: dict[int, str] = {}  # vnode hash -> member id
        self._members: set[str] = set()
        for m in members:
            self.add(m)

    # -- membership --------------------------------------------------------

    def add(self, member: str) -> None:
        if member in self._members:
            return
        self._members.add(member)
        for i in range(self.vnodes):
            h = stable_hash(f"{member}#{i}")
            # A 64-bit collision across members is ~impossible at these
            # cardinalities; refuse loudly rather than silently re-own.
            if h in self._owner:
                raise ValueError(
                    f"vnode hash collision: {member!r} vs "
                    f"{self._owner[h]!r}"
                )
            self._owner[h] = member
            bisect.insort(self._points, h)

    def remove(self, member: str) -> None:
        if member not in self._members:
            return
        self._members.discard(member)
        dead = [h for h, m in self._owner.items() if m == member]
        for h in dead:
            del self._owner[h]
            self._points.pop(bisect.bisect_left(self._points, h))

    @property
    def members(self) -> frozenset:
        return frozenset(self._members)

    @property
    def size(self) -> int:
        """Live vnode count (the placement-ring-size gauge)."""
        return len(self._points)

    # -- placement ---------------------------------------------------------

    def place(self, key: str) -> str:
        """The member owning ``key`` (first vnode clockwise of its hash)."""
        if not self._points:
            raise ValueError("ring has no members")
        i = bisect.bisect_left(self._points, stable_hash(key))
        if i == len(self._points):
            i = 0  # wrap past the top of the circle
        return self._owner[self._points[i]]

    def preference(self, key: str, limit: int | None = None) -> list[str]:
        """Distinct members in ring order from ``key``'s placement point —
        the failover/filter walk order (index 0 is :meth:`place`)."""
        if not self._points:
            return []
        out: list[str] = []
        seen: set[str] = set()
        start = bisect.bisect_left(self._points, stable_hash(key))
        n = len(self._points)
        want = len(self._members) if limit is None else min(
            limit, len(self._members)
        )
        for step in range(n):
            m = self._owner[self._points[(start + step) % n]]
            if m not in seen:
                seen.add(m)
                out.append(m)
                if len(out) >= want:
                    break
        return out
